"""Headline benchmark: Llama-class causal-LM training throughput on TPU.

Prints ONE JSON line:
  {"metric": "tokens_per_sec_per_chip", "value": N, "unit": "tok/s/chip",
   "vs_baseline": MFU/0.45, ...}

The reference publishes no numbers (BASELINE.md: published={}), so
vs_baseline is measured MFU against the north-star 45% MFU target for
Llama-8B-class fine-tuning. Runs on whatever chips are present (the CI
driver runs it on the 1-chip emulated v5e).

Model/config choice and the measurement method are profile-driven — see
PROFILE.md: the 0.9B llama_1b() config at batch 12 is the highest-MFU point
that fits one v5e's HBM with Adam state, and steps are timed *pipelined*
(single device fetch at the end) because the axon tunnel adds ~66 ms to
every synchronous host fetch, which is dispatch latency, not step time.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubeflow_tpu.models.llama import Llama, llama_1b
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES
    from kubeflow_tpu.train.metrics import peak_flops_per_chip
    from kubeflow_tpu.train.step import init_train_state, make_train_step

    # 0.9B-param bench model: flagship topology (GQA/RoPE/SwiGLU/scan,
    # head_dim 128) at the largest size that fits one emulated v5e with
    # Adam state. Full-block remat; bf16 Adam first moment buys batch 12
    # (PROFILE.md has the sweep).
    cfg = llama_1b()
    batch, seq = 12, 1024

    n_chips = jax.device_count()
    mesh = build_mesh(MeshConfig(), jax.devices())
    model = Llama(cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    tx = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    state = init_train_state(
        model, tx, jax.random.key(0), (tokens,), mesh, DEFAULT_RULES)
    step = make_train_step(model, mesh, DEFAULT_RULES)

    rng = np.random.default_rng(0)
    def make_batch():
        return {
            "inputs": rng.integers(0, cfg.vocab_size, (batch, seq),
                                   dtype=np.int32),
            "targets": rng.integers(0, cfg.vocab_size, (batch, seq),
                                    dtype=np.int32),
        }

    # Warmup: compile + 2 steady-state steps (each synced, paying the
    # tunnel's fetch latency — excluded from the measurement).
    for i in range(3):
        state, metrics = step(state, make_batch())
        loss = float(metrics["loss"])
        print(f"warmup {i}: loss={loss:.3f}", file=sys.stderr)

    # Timed: chained steps, one fetch at the end. Each step consumes the
    # previous step's state (donated), so the device executes them
    # back-to-back; dividing wall time by N gives true per-step time.
    timed = 10
    batches = [make_batch() for _ in range(timed)]
    t0 = time.perf_counter()
    for b in batches:
        state, metrics = step(state, b)
    final_loss = float(metrics["loss"])  # forces completion of the chain
    dt = (time.perf_counter() - t0) / timed
    print(f"timed {timed} steps: {dt*1e3:.1f} ms/step "
          f"loss={final_loss:.3f}", file=sys.stderr)

    model_flops = 6 * cfg.num_params * batch * seq
    mfu = model_flops / dt / (peak_flops_per_chip() * n_chips)
    result = {
        "metric": "tokens_per_sec_per_chip",
        "value": round(batch * seq / dt / n_chips, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "model_params": cfg.num_params,
        "chips": n_chips,
        "device_kind": jax.devices()[0].device_kind,
        "peak_flops_per_chip": peak_flops_per_chip(),
        "batch": batch,
        "seq_len": seq,
        "avg_step_time_s": round(dt, 4),
    }
    print(json.dumps(result))


def main_serve() -> None:
    """`python bench.py --serve`: serving benchmark → SERVEBENCH.json +
    one JSON line on stdout (kubeflow_tpu/serve/bench.py)."""
    from kubeflow_tpu.serve.bench import run_servebench

    result = run_servebench(size="1b", quick=False)
    with open("SERVEBENCH.json", "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({
        "metric": "serve_decode_tok_s",
        "value": result["decode"][
            f"slots_{max(int(k.split('_')[1]) for k in result['decode'])}"][
                "decode_tok_s"],
        "unit": "tok/s",
        "detail": "SERVEBENCH.json",
    }))


if __name__ == "__main__":
    if "--serve" in sys.argv:
        main_serve()
    else:
        main()
