"""Headline benchmark: Llama-class causal-LM training throughput on TPU.

Prints ONE JSON line:
  {"metric": "tokens_per_sec_per_chip", "value": N, "unit": "tok/s/chip",
   "vs_baseline": MFU/0.45, ...}

The reference publishes no numbers (BASELINE.md: published={}), so
vs_baseline is measured MFU against the north-star 45% MFU target for
Llama-8B-class fine-tuning. Runs on whatever chips are present (the CI
driver runs it on the 1-chip emulated v5e).
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubeflow_tpu.models.llama import Llama, LlamaConfig
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from kubeflow_tpu.parallel.sharding import DEFAULT_RULES
    from kubeflow_tpu.train.metrics import StepTimer, peak_flops_per_chip
    from kubeflow_tpu.train.step import init_train_state, make_train_step

    # ~330M-param bench model: same flagship topology (GQA/RoPE/SwiGLU/scan)
    # sized to fit comfortably in one emulated v5e's HBM with Adam state.
    cfg = LlamaConfig(
        vocab_size=32768, hidden_size=1024, intermediate_size=4096,
        num_layers=16, num_heads=16, num_kv_heads=8, head_dim=64,
        max_seq_len=1024, remat=False, attention_impl="auto",
        flash_block_q=256, flash_block_kv=256)
    batch, seq = 8, 1024

    n_chips = jax.device_count()
    mesh = build_mesh(MeshConfig(), jax.devices())
    model = Llama(cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    state = init_train_state(
        model, optax.adamw(3e-4), jax.random.key(0), (tokens,), mesh,
        DEFAULT_RULES)
    step = make_train_step(model, mesh, DEFAULT_RULES)

    rng = np.random.default_rng(0)
    def make_batch():
        return {
            "inputs": rng.integers(0, cfg.vocab_size, (batch, seq),
                                   dtype=np.int32),
            "targets": rng.integers(0, cfg.vocab_size, (batch, seq),
                                    dtype=np.int32),
        }

    timer = StepTimer(num_params=cfg.num_params, tokens_per_step=batch * seq,
                      num_chips=n_chips, warmup_steps=2)
    warmup, timed = 2, 8
    for i in range(warmup + timed):
        b = make_batch()
        timer.start()
        state, metrics = step(state, b)
        jax.block_until_ready(metrics["loss"])
        snap = timer.stop()
        print(f"step {i}: {snap['step_time_s']*1e3:.1f} ms "
              f"loss={float(metrics['loss']):.3f}", file=sys.stderr)

    final = timer.snapshot()
    result = {
        "metric": "tokens_per_sec_per_chip",
        "value": round(final["tokens_per_sec_per_chip"], 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(final["mfu"] / 0.45, 4),
        "mfu": round(final["mfu"], 4),
        "model_params": cfg.num_params,
        "chips": n_chips,
        "device_kind": jax.devices()[0].device_kind,
        "peak_flops_per_chip": peak_flops_per_chip(),
        "batch": batch,
        "seq_len": seq,
        "avg_step_time_s": round(final["avg_step_time_s"], 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
