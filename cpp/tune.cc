#include "tune.h"

#include "util.h"

#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <ctime>

namespace tpk {

namespace {

bool IsTerminalExp(const std::string& phase) {
  return phase == "Succeeded" || phase == "Failed";
}

bool IsTerminalTrial(const std::string& phase) {
  return phase == "Succeeded" || phase == "Failed" ||
         phase == "EarlyStopped" || phase == "Stopped";
}

std::string FormatParam(const Json& v) {
  if (v.is_string()) return v.as_string();
  if (v.is_bool()) return v.as_bool() ? "true" : "false";
  if (v.is_number()) {
    char buf[64];
    snprintf(buf, sizeof(buf), "%.10g", v.as_number());
    return buf;
  }
  return v.dump();
}

// value at a string position: is this a word boundary?
bool IsWordChar(char c) {
  return isalnum(static_cast<unsigned char>(c)) || c == '_';
}

}  // namespace

// --------------------------------------------------------------------------
// Template substitution
// --------------------------------------------------------------------------

Json ExperimentController::Substitute(const Json& tmpl, const Json& params,
                                      const std::string& trial_name) {
  if (tmpl.is_string()) {
    const std::string& s = tmpl.as_string();
    // Whole-string token keeps the parameter's JSON type: {"lr": "${lr}"}
    // becomes a number in the materialized job spec.
    if (s.size() > 3 && s.compare(0, 2, "${") == 0 && s.back() == '}' &&
        s.find("${", 2) == std::string::npos) {
      std::string key = s.substr(2, s.size() - 3);
      if (key.rfind("trialParameters.", 0) == 0) key = key.substr(16);
      if (key == "trialName") return Json(trial_name);
      if (params.has(key)) return params.get(key);
    }
    std::string out;
    size_t pos = 0;
    while (pos < s.size()) {
      size_t open = s.find("${", pos);
      if (open == std::string::npos) {
        out.append(s, pos, std::string::npos);
        break;
      }
      size_t close = s.find('}', open + 2);
      if (close == std::string::npos) {
        out.append(s, pos, std::string::npos);
        break;
      }
      out.append(s, pos, open - pos);
      std::string key = s.substr(open + 2, close - open - 2);
      if (key.rfind("trialParameters.", 0) == 0) key = key.substr(16);
      if (key == "trialName") {
        out += trial_name;
      } else if (params.has(key)) {
        out += FormatParam(params.get(key));
      } else {
        // Unknown token stays visible — easier to debug than silent "".
        out.append(s, open, close - open + 1);
      }
      pos = close + 1;
    }
    return Json(out);
  }
  if (tmpl.is_array()) {
    Json arr = Json::Array();
    for (const auto& e : tmpl.elements()) {
      arr.push_back(Substitute(e, params, trial_name));
    }
    return arr;
  }
  if (tmpl.is_object()) {
    Json obj = Json::Object();
    for (const auto& [k, v] : tmpl.items()) {
      obj[k] = Substitute(v, params, trial_name);
    }
    return obj;
  }
  return tmpl;
}

// --------------------------------------------------------------------------
// Metric extraction (the metrics-collector stand-in)
// --------------------------------------------------------------------------

std::vector<std::pair<double, double>> ExperimentController::ParseMetrics(
    const std::string& log_text, const std::string& metric) {
  std::vector<std::pair<double, double>> out;
  size_t pos = 0;
  double seq = 0;
  while (pos < log_text.size()) {
    size_t nl = log_text.find('\n', pos);
    if (nl == std::string::npos) nl = log_text.size();
    std::string line = log_text.substr(pos, nl - pos);
    pos = nl + 1;
    if (line.empty()) continue;

    size_t first = line.find_first_not_of(" \t");
    if (first != std::string::npos && line[first] == '{') {
      // JSONL path: the runtime's step-metrics records.
      try {
        Json rec = Json::parse(line.substr(first));
        if (rec.is_object() && rec.has(metric) &&
            rec.get(metric).is_number()) {
          double step = rec.get("step").is_number()
                            ? rec.get("step").as_number()
                            : seq;
          out.emplace_back(step, rec.get(metric).as_number());
          seq += 1;
          continue;
        }
      } catch (const std::exception&) {
        // fall through to the text scan
      }
    }
    // stdout-regex fallback: `metric = value` (Katib StdOut collector).
    size_t at = 0;
    while ((at = line.find(metric, at)) != std::string::npos) {
      size_t end = at + metric.size();
      bool lb = at == 0 || !IsWordChar(line[at - 1]);
      if (!lb || (end < line.size() && IsWordChar(line[end]))) {
        at = end;
        continue;
      }
      size_t q = end;
      while (q < line.size() && (line[q] == ' ' || line[q] == '\t')) ++q;
      if (q < line.size() && line[q] == '=') {
        ++q;
        while (q < line.size() && (line[q] == ' ' || line[q] == '\t')) ++q;
        char* endp = nullptr;
        double v = strtod(line.c_str() + q, &endp);
        if (endp && endp != line.c_str() + q) {
          out.emplace_back(seq, v);
          seq += 1;
          break;  // one observation per line
        }
      }
      at = end;
    }
  }
  return out;
}

std::string ExperimentController::ReadWorkerLog(
    const std::string& job_name) const {
  std::string path = workdir_ + "/" + job_name + "/worker-0.log";
  FILE* f = fopen(path.c_str(), "r");
  if (!f) return "";
  constexpr long kMax = 4 << 20;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  long start = size > kMax ? size - kMax : 0;
  fseek(f, start, SEEK_SET);
  std::string content(size - start, '\0');
  size_t got = fread(content.data(), 1, content.size(), f);
  content.resize(got);
  fclose(f);
  return content;
}

double ExperimentController::ObjectiveValue(
    const std::vector<std::pair<double, double>>& obs, const Json& objective,
    bool* ok) const {
  if (obs.empty()) {
    *ok = false;
    return 0;
  }
  *ok = true;
  const std::string goal = objective.get("goal").as_string().empty()
                               ? "minimize"
                               : objective.get("goal").as_string();
  std::string strategy = objective.get("strategy").as_string();
  if (strategy.empty()) strategy = goal == "maximize" ? "max" : "min";
  if (strategy == "latest") return obs.back().second;
  double best = obs[0].second;
  for (const auto& [step, v] : obs) {
    if (strategy == "max" ? v > best : v < best) best = v;
  }
  return best;
}

// --------------------------------------------------------------------------
// Controller
// --------------------------------------------------------------------------

ExperimentController::ExperimentController(Store* store,
                                           SuggestionInterface* suggestion,
                                           std::string workdir)
    : store_(store),
      suggestion_(suggestion),
      workdir_(std::move(workdir)) {}

void ExperimentController::SetPhase(Json* status, const std::string& phase,
                                    const std::string& reason,
                                    const std::string& message) {
  const std::string prev = status->get("phase").as_string();
  (*status)["phase"] = phase;
  if (!status->has("conditions")) (*status)["conditions"] = Json::Array();
  if (prev != phase) {
    Json cond = Json::Object();
    cond["type"] = phase;
    cond["status"] = "True";
    cond["reason"] = reason;
    cond["message"] = message;
    cond["lastTransitionTime"] = Timestamp(now_s_);
    (*status)["conditions"].push_back(cond);
  }
}

void ExperimentController::ReconcileTrial(const Json& exp_spec,
                                          const std::string& exp_name,
                                          const Resource& trial) {
  (void)exp_name;
  Json status = trial.status;
  const std::string phase = status.get("phase").as_string();
  if (IsTerminalTrial(phase)) return;

  auto job = store_->Get("JAXJob", trial.name);
  if (!job) {
    if (phase.empty()) {
      // Materialize the child job (idempotent: keyed by trial name).
      auto r = store_->Create("JAXJob", trial.name,
                              trial.spec.get("job_spec"));
      if (!r.ok) {
        SetPhase(&status, "Failed", "JobCreateFailed", r.error);
      } else {
        SetPhase(&status, "Running", "JobCreated", "child JAXJob created");
      }
    } else {
      SetPhase(&status, "Failed", "JobMissing",
               "child JAXJob disappeared");
    }
    store_->UpdateStatus("Trial", trial.name, status);
    return;
  }

  const Json& objective = exp_spec.get("objective");
  const std::string metric = objective.get("metric").as_string();
  const std::string jphase = job->status.get("phase").as_string();

  if (jphase == "Succeeded") {
    auto obs = ParseMetrics(ReadWorkerLog(trial.name), metric);
    bool ok = false;
    double value = ObjectiveValue(obs, objective, &ok);
    if (!ok) {
      SetPhase(&status, "Failed", "MetricsUnavailable",
               "objective metric '" + metric + "' not found in worker log");
    } else {
      Json observation = Json::Object();
      observation["metric"] = metric;
      observation["value"] = value;
      status["observation"] = observation;
      SetPhase(&status, "Succeeded", "JobSucceeded", "observation recorded");
    }
  } else if (jphase == "Failed") {
    SetPhase(&status, "Failed", "JobFailed",
             "child JAXJob failed: " + jphase);
  } else {
    // Running: refresh intermediate history for early stopping — but only
    // when the log actually grew (this path runs every event-loop pass).
    struct stat st;
    std::string log_path = workdir_ + "/" + trial.name + "/worker-0.log";
    long size = stat(log_path.c_str(), &st) == 0 ? st.st_size : 0;
    auto seen = log_size_seen_.find(trial.name);
    bool grew = seen == log_size_seen_.end() || seen->second != size;
    if (grew) log_size_seen_[trial.name] = size;
    auto obs = grew ? ParseMetrics(ReadWorkerLog(trial.name), metric)
                    : std::vector<std::pair<double, double>>{};
    size_t prev = status.get("history").is_array()
                      ? status.get("history").size()
                      : 0;
    if (grew && obs.size() != prev) {
      Json hist = Json::Array();
      size_t start = obs.size() > 256 ? obs.size() - 256 : 0;
      for (size_t i = start; i < obs.size(); ++i) {
        Json pt = Json::Array();
        pt.push_back(obs[i].first);
        pt.push_back(obs[i].second);
        hist.push_back(pt);
      }
      status["history"] = hist;
    }
    if (phase.empty()) {
      SetPhase(&status, "Running", "JobCreated", "child JAXJob created");
    }
  }
  if (IsTerminalTrial(status.get("phase").as_string())) {
    log_size_seen_.erase(trial.name);
  }
  if (status.dump() != trial.status.dump()) {
    store_->UpdateStatus("Trial", trial.name, status);
  }
}

void ExperimentController::MaybeEarlyStop(
    const Json& exp_spec, const std::string& exp_name,
    const std::vector<Resource>& trials) {
  (void)exp_name;
  const Json& es = exp_spec.get("early_stopping");
  if (!es.is_object()) return;
  const std::string algo = es.get("algorithm").as_string();
  if (!algo.empty() && algo != "medianstop") return;
  int64_t min_trials = es.get("min_trials").as_int(3);
  int64_t start_step = es.get("start_step").as_int(5);

  const Json& objective = exp_spec.get("objective");
  const std::string goal = objective.get("goal").as_string().empty()
                               ? "minimize"
                               : objective.get("goal").as_string();
  const bool maximize = goal == "maximize";

  std::vector<double> done;
  for (const auto& t : trials) {
    if (t.status.get("phase").as_string() == "Succeeded" &&
        t.status.get("observation").is_object()) {
      done.push_back(t.status.get("observation").get("value").as_number());
    }
  }
  if (done.empty() || static_cast<int64_t>(done.size()) < min_trials) return;
  std::sort(done.begin(), done.end());
  double median = done[done.size() / 2];

  for (const auto& stale : trials) {
    // Re-fetch: ReconcileTrial ran in this same pass and may have just
    // moved the trial to Succeeded — deciding on the captured snapshot
    // would clobber that transition with a blind EarlyStopped overwrite.
    auto cur = store_->Get("Trial", stale.name);
    if (!cur) continue;
    const Resource& t = *cur;
    if (t.status.get("phase").as_string() != "Running") continue;
    const Json& hist = t.status.get("history");
    if (!hist.is_array() || hist.size() == 0 ||
        static_cast<int64_t>(hist.size()) < start_step) {
      continue;
    }
    double best = hist.elements()[0].elements()[1].as_number();
    for (const auto& pt : hist.elements()) {
      double v = pt.elements()[1].as_number();
      if (maximize ? v > best : v < best) best = v;
    }
    const bool worse = maximize ? best < median : best > median;
    if (!worse) continue;

    store_->Delete("JAXJob", t.name);  // watch → gang killed
    log_size_seen_.erase(t.name);
    Json status = t.status;
    Json observation = Json::Object();
    observation["metric"] = objective.get("metric").as_string();
    observation["value"] = best;
    status["observation"] = observation;
    SetPhase(&status, "EarlyStopped", "MedianStop",
             "best-so-far worse than median of completed trials");
    store_->UpdateStatus("Trial", t.name, status);
    metrics_.trials_early_stopped++;
  }
}

void ExperimentController::Reconcile(const std::string& name) {
  auto res = store_->Get("Experiment", name);
  if (!res || res->deleted) return;
  Json spec = res->spec;
  Json status = res->status;
  const std::string phase = status.get("phase").as_string();
  if (IsTerminalExp(phase)) return;

  if (phase.empty()) {
    metrics_.experiments_created++;
    SetPhase(&status, "Created", "ExperimentCreated", "accepted");
  }

  // Gather this experiment's trials, ordered by index.
  std::vector<Resource> trials;
  for (const auto& t : store_->List("Trial")) {
    if (t.spec.get("experiment").as_string() == name) trials.push_back(t);
  }
  std::sort(trials.begin(), trials.end(),
            [](const Resource& a, const Resource& b) {
              return a.spec.get("index").as_int() <
                     b.spec.get("index").as_int();
            });

  for (const auto& t : trials) ReconcileTrial(spec, name, t);
  MaybeEarlyStop(spec, name, trials);

  // Re-read post-reconcile state and count.
  Counts c;
  int64_t max_index = -1;
  Json trial_history = Json::Array();
  std::string best_trial;
  Json best_params;
  double best_value = 0;
  bool have_best = false;
  const Json& objective = spec.get("objective");
  const bool maximize = objective.get("goal").as_string() == "maximize";

  for (auto& t : trials) {
    auto fresh = store_->Get("Trial", t.name);
    if (fresh) t = *fresh;
    c.created++;
    max_index = std::max(max_index, t.spec.get("index").as_int());
    const std::string tp = t.status.get("phase").as_string();
    if (tp == "Succeeded") {
      c.succeeded++;
    } else if (tp == "Failed") {
      c.failed++;
    } else if (tp == "EarlyStopped") {
      c.early_stopped++;
    } else if (tp == "Stopped") {
      // killed at experiment completion; counts only as created
    } else {
      c.active++;
    }

    Json h = Json::Object();
    h["params"] = t.spec.get("params");
    h["status"] = tp;
    if (t.status.get("observation").is_object()) {
      double v = t.status.get("observation").get("value").as_number();
      h["value"] = v;
      if (!have_best || (maximize ? v > best_value : v < best_value)) {
        have_best = true;
        best_value = v;
        best_trial = t.name;
        best_params = t.spec.get("params");
      }
    }
    trial_history.push_back(h);
  }

  Json tc = Json::Object();
  tc["created"] = c.created;
  tc["succeeded"] = c.succeeded;
  tc["failed"] = c.failed;
  tc["earlyStopped"] = c.early_stopped;
  tc["running"] = c.active;
  status["trials"] = tc;
  if (have_best) {
    Json opt = Json::Object();
    opt["trial"] = best_trial;
    opt["params"] = best_params;
    opt["value"] = best_value;
    status["optimal"] = opt;
  }

  auto stop_active = [&]() {
    for (const auto& t : trials) {
      const std::string tp = t.status.get("phase").as_string();
      if (IsTerminalTrial(tp)) continue;
      store_->Delete("JAXJob", t.name);
      log_size_seen_.erase(t.name);
      Json ts = t.status;
      SetPhase(&ts, "Stopped", "ExperimentCompleted",
               "experiment reached a terminal phase");
      store_->UpdateStatus("Trial", t.name, ts);
    }
  };

  int64_t max_trials = spec.get("max_trials").as_int(10);
  int64_t parallel = spec.get("parallel_trials").as_int(1);
  int64_t max_failed = spec.get("max_failed_trials").as_int(3);
  double target = objective.get("target").as_number(NAN);

  // 1) Goal reached?
  if (have_best && !std::isnan(target) &&
      (maximize ? best_value >= target : best_value <= target)) {
    stop_active();
    SetPhase(&status, "Succeeded", "GoalReached",
             "objective target met by " + best_trial);
    metrics_.experiments_succeeded++;
    store_->UpdateStatus("Experiment", name, status);
    return;
  }
  // 2) Failure budget blown?
  if (max_failed >= 0 && c.failed > max_failed) {
    stop_active();
    SetPhase(&status, "Failed", "MaxFailedTrialsReached",
             std::to_string(c.failed) + " trials failed");
    metrics_.experiments_failed++;
    store_->UpdateStatus("Experiment", name, status);
    return;
  }
  // 3) Budget exhausted and everything settled?
  bool exhausted = status.get("searchSpaceExhausted").as_bool(false);
  if ((c.created >= max_trials || exhausted) && c.active == 0) {
    if (have_best) {
      SetPhase(&status, "Succeeded", exhausted ? "SearchSpaceExhausted"
                                               : "MaxTrialsReached",
               "best value " + FormatParam(Json(best_value)));
      metrics_.experiments_succeeded++;
    } else {
      SetPhase(&status, "Failed", "NoObservations",
               "no trial produced an observation");
      metrics_.experiments_failed++;
    }
    store_->UpdateStatus("Experiment", name, status);
    return;
  }

  // 4) Spawn more trials up to the parallelism cap.
  int64_t want = std::min(parallel - c.active,
                          max_trials - c.created);
  // Failed suggestion calls retry with exponential backoff (the event loop
  // reconciles ~20x/s — unbounded retry would fork crash-looping services
  // at that rate) and fail the experiment after a persistent streak.
  int64_t sugg_fails = status.get("suggestionFailures").as_int(0);
  double last_attempt = status.get("lastSuggestionAttempt").as_number(0);
  double backoff_s = sugg_fails > 0
                         ? std::min(1 << std::min<int64_t>(sugg_fails, 5),
                                    30)
                         : 0;
  // A pending algorithm (hyperband waiting on a rung) is re-polled at 1s —
  // not every 50ms tick, and without counting as a failure.
  bool pending_wait =
      status.get("suggestionPending").as_bool(false) &&
      now_s_ < status.get("lastSuggestionAttempt").as_number(0) + 1.0;
  if (want > 0 && !exhausted && !pending_wait &&
      (sugg_fails == 0 || now_s_ >= last_attempt + backoff_s)) {
    Json assignments;
    bool pending = false;
    std::string error;
    if (!suggestion_->GetSuggestions(spec, trial_history,
                                     static_cast<int>(want), &assignments,
                                     &error, &pending)) {
      metrics_.suggestion_errors++;
      status["suggestionError"] = error;
      status["suggestionFailures"] = sugg_fails + 1;
      status["lastSuggestionAttempt"] = now_s_;
      if (sugg_fails + 1 >= 5) {
        stop_active();
        SetPhase(&status, "Failed", "SuggestionUnavailable",
                 "suggestion service failed " +
                     std::to_string(sugg_fails + 1) + "x: " + error);
        metrics_.experiments_failed++;
        store_->UpdateStatus("Experiment", name, status);
        return;
      }
      SetPhase(&status, "Running", "SuggestionFailed", error);
    } else {
      if (status.has("suggestionError")) {
        status["suggestionError"] = Json();
        status["suggestionFailures"] = 0;
      }
      if (assignments.size() == 0 && pending) {
        // Algorithm is waiting on running trials (rung promotion): retry
        // later; NOT exhaustion.
        status["suggestionPending"] = true;
        status["lastSuggestionAttempt"] = now_s_;
      } else if (assignments.size() == 0) {
        // Grid (or any finite space) ran dry: stop proposing; completion
        // is decided above once running trials settle.
        status["searchSpaceExhausted"] = true;
      } else if (status.get("suggestionPending").as_bool(false)) {
        status["suggestionPending"] = false;
      }
      for (const auto& a : assignments.elements()) {
        int64_t index = ++max_index;
        std::string tname = name + "-" + std::to_string(index);
        Json tspec = Json::Object();
        tspec["experiment"] = name;
        tspec["index"] = index;
        tspec["params"] = a;
        tspec["job_spec"] =
            Substitute(spec.get("trial_template"), a, tname);
        auto r = store_->Create("Trial", tname, tspec);
        if (r.ok) metrics_.trials_created++;
      }
      SetPhase(&status, "Running", "TrialsLaunched", "suggestions applied");
    }
  } else if (phase.empty() || phase == "Created") {
    SetPhase(&status, "Running", "Reconciling", "trials in flight");
  }

  if (status.dump() != res->status.dump()) {
    store_->UpdateStatus("Experiment", name, status);
  }
}

void ExperimentController::Tick(double now_s) {
  now_s_ = now_s;
  for (const auto& res : store_->List("Experiment")) {
    if (!IsTerminalExp(res.status.get("phase").as_string())) {
      Reconcile(res.name);
    }
  }
}

void ExperimentController::OnDeleted(const Resource& res) {
  // Cascade GC (upstream: ownerReferences + apiserver garbage collection).
  if (res.kind == "Experiment") {
    for (const auto& t : store_->List("Trial")) {
      if (t.spec.get("experiment").as_string() != res.name) continue;
      store_->Delete("JAXJob", t.name);  // watch → gang killed
      store_->Delete("Trial", t.name);
    }
  } else if (res.kind == "Trial") {
    store_->Delete("JAXJob", res.name);
    log_size_seen_.erase(res.name);
  }
}

// --------------------------------------------------------------------------
// SubprocessSuggestion
// --------------------------------------------------------------------------

SubprocessSuggestion::SubprocessSuggestion(std::string python)
    : python_(std::move(python)) {}

SubprocessSuggestion::~SubprocessSuggestion() { Shutdown(); }

void SubprocessSuggestion::Shutdown() {
  if (in_fd_ >= 0) {
    close(in_fd_);
    in_fd_ = -1;
  }
  if (out_fd_ >= 0) {
    close(out_fd_);
    out_fd_ = -1;
  }
  out_buf_.clear();
  if (pid_ > 0) {
    kill(pid_, SIGKILL);  // may be hung; SIGTERM could leave a zombie wait
    waitpid(pid_, nullptr, 0);
    pid_ = -1;
  }
}

bool SubprocessSuggestion::EnsureRunning(std::string* error) {
  if (pid_ > 0) {
    int wstatus = 0;
    if (waitpid(pid_, &wstatus, WNOHANG) == pid_) {
      pid_ = -1;  // died; clean up pipes and respawn below
      if (in_fd_ >= 0) close(in_fd_);
      in_fd_ = -1;
      if (out_fd_ >= 0) close(out_fd_);
      out_fd_ = -1;
      out_buf_.clear();
    } else {
      return true;
    }
  }
  int to_child[2], from_child[2];
  if (pipe(to_child) != 0) {
    if (error) *error = std::string("pipe: ") + strerror(errno);
    return false;
  }
  if (pipe(from_child) != 0) {
    if (error) *error = std::string("pipe: ") + strerror(errno);
    close(to_child[0]);
    close(to_child[1]);
    return false;
  }
  pid_t pid = fork();
  if (pid < 0) {
    if (error) *error = std::string("fork: ") + strerror(errno);
    close(to_child[0]);
    close(to_child[1]);
    close(from_child[0]);
    close(from_child[1]);
    return false;
  }
  if (pid == 0) {
    dup2(to_child[0], 0);
    dup2(from_child[1], 1);
    close(to_child[0]);
    close(to_child[1]);
    close(from_child[0]);
    close(from_child[1]);
    execlp(python_.c_str(), python_.c_str(), "-m",
           "kubeflow_tpu.tune.service", (char*)nullptr);
    _exit(127);
  }
  close(to_child[0]);
  close(from_child[1]);
  pid_ = pid;
  in_fd_ = to_child[1];
  out_fd_ = from_child[0];
  // Non-blocking writes: a wedged child that stops reading stdin must not
  // block the event loop once the request outgrows the pipe buffer.
  fcntl(in_fd_, F_SETFL, fcntl(in_fd_, F_GETFL, 0) | O_NONBLOCK);
  return true;
}

bool SubprocessSuggestion::GetSuggestions(const Json& experiment_spec,
                                          const Json& trials, int count,
                                          Json* assignments,
                                          std::string* error, bool* pending) {
  if (pending) *pending = false;
  if (!EnsureRunning(error)) return false;
  Json req = Json::Object();
  req["op"] = "get_suggestions";
  Json exp = Json::Object();
  exp["parameters"] = experiment_spec.get("parameters");
  exp["objective"] = experiment_spec.get("objective");
  exp["algorithm"] = experiment_spec.get("algorithm");
  req["experiment"] = exp;
  req["trials"] = trials;
  req["count"] = count;
  req["seed"] = experiment_spec.get("seed").as_int(0);
  std::string line = req.dump() + "\n";
  // Bounded write + read: this runs inside the control plane's only event
  // loop, so a hung service must not freeze the API server / job reaping —
  // kill and respawn on deadline instead.
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms_);
  size_t off = 0;
  while (off < line.size()) {
    ssize_t sent = write(in_fd_, line.data() + off, line.size() - off);
    if (sent > 0) {
      off += sent;
      continue;
    }
    if (sent < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
      Shutdown();
      if (error) *error = "suggestion service write failed";
      return false;
    }
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    pollfd wfd{in_fd_, POLLOUT, 0};
    if (left <= 0 || poll(&wfd, 1, static_cast<int>(left)) <= 0) {
      Shutdown();
      if (error) *error = "suggestion service timed out (write)";
      return false;
    }
  }
  std::string resp_line;
  while (true) {
    size_t nl = out_buf_.find('\n');
    if (nl != std::string::npos) {
      resp_line = out_buf_.substr(0, nl);
      out_buf_.erase(0, nl + 1);
      break;
    }
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    pollfd pfd{out_fd_, POLLIN, 0};
    int pr = left <= 0 ? 0 : poll(&pfd, 1, static_cast<int>(left));
    if (pr <= 0) {
      Shutdown();
      if (error) *error = "suggestion service timed out";
      return false;
    }
    char buf[4096];
    ssize_t got = read(out_fd_, buf, sizeof(buf));
    if (got <= 0) {
      Shutdown();
      if (error) *error = "suggestion service closed (EOF)";
      return false;
    }
    out_buf_.append(buf, got);
  }
  try {
    Json resp = Json::parse(resp_line);
    if (!resp.get("ok").as_bool(false)) {
      if (error) *error = resp.get("error").as_string();
      return false;
    }
    *assignments = resp.get("assignments");
    if (pending) *pending = resp.get("pending").as_bool(false);
    return true;
  } catch (const std::exception& e) {
    if (error) *error = std::string("bad suggestion response: ") + e.what();
    return false;
  }
}

}  // namespace tpk
