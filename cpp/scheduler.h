// Gang/slice scheduler — topology-aware, all-or-nothing placement.
//
// Upstream parity: training-operator's gang scheduling delegates to Volcano/
// scheduler-plugins PodGroups with minMember = Σreplicas (SURVEY.md §2.1
// JobController.SyncPodGroup); a partial gang deadlocks a TPU slice, so
// placement must be atomic. Here slices are declared capacity pools (device
// counts); a job asks for `replicas × devices_per_proc` devices on one slice
// (or spans slices for multi-slice jobs), and allocation either fully
// succeeds or leaves state untouched.

#pragma once

#include <algorithm>
#include <map>
#include <optional>
#include <string>
#include <vector>

namespace tpk {

struct SliceInfo {
  std::string name;
  int capacity = 0;  // devices
  int used = 0;
  int free() const { return capacity - used; }
};

struct Allocation {
  // slice name → devices taken. Multi-slice jobs hold several entries.
  std::map<std::string, int> slices;
};

class Scheduler {
 public:
  void AddSlice(const std::string& name, int capacity) {
    slices_[name] = {name, capacity, 0};
  }

  std::vector<SliceInfo> Slices() const {
    std::vector<SliceInfo> out;
    for (const auto& [_, s] : slices_) out.push_back(s);
    return out;
  }

  // Gang-allocate `devices` across `num_slices` slices (devices must divide
  // evenly). Single-slice jobs prefer the fullest slice that fits
  // (bin-packing keeps large contiguous slices free for big gangs).
  std::optional<Allocation> Allocate(int devices, int num_slices = 1) {
    if (devices <= 0 || num_slices <= 0 || devices % num_slices) {
      return std::nullopt;
    }
    int per_slice = devices / num_slices;
    // Candidate slices with room, fullest-first.
    std::vector<SliceInfo*> fits;
    for (auto& [_, s] : slices_) {
      if (s.free() >= per_slice) fits.push_back(&s);
    }
    if (static_cast<int>(fits.size()) < num_slices) return std::nullopt;
    std::sort(fits.begin(), fits.end(), [](SliceInfo* a, SliceInfo* b) {
      return a->free() < b->free();
    });
    Allocation alloc;
    for (int i = 0; i < num_slices; ++i) {
      fits[i]->used += per_slice;
      alloc.slices[fits[i]->name] = per_slice;
    }
    return alloc;
  }

  void Release(const Allocation& alloc) {
    for (const auto& [name, n] : alloc.slices) {
      auto it = slices_.find(name);
      if (it != slices_.end()) {
        it->second.used -= n;
        if (it->second.used < 0) it->second.used = 0;
      }
    }
  }

 private:
  std::map<std::string, SliceInfo> slices_;
};

}  // namespace tpk
