// SHA-256 (FIPS 180-4), header-only — fingerprints for the pipeline step
// cache and artifact digests (SURVEY.md §5.4: KFP api-server computes
// fingerprint(component spec + inputs) to skip completed steps; ours also
// content-addresses artifact directories for lineage).

#pragma once

#include <cstdint>
#include <cstring>
#include <string>

namespace tpk {

class Sha256 {
 public:
  Sha256() { Reset(); }

  void Reset() {
    static constexpr uint32_t kInit[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    memcpy(h_, kInit, sizeof(h_));
    len_ = 0;
    buf_len_ = 0;
  }

  void Update(const void* data, size_t n) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    len_ += n;
    while (n > 0) {
      size_t take = 64 - buf_len_;
      if (take > n) take = n;
      memcpy(buf_ + buf_len_, p, take);
      buf_len_ += take;
      p += take;
      n -= take;
      if (buf_len_ == 64) {
        Block(buf_);
        buf_len_ = 0;
      }
    }
  }

  void Update(const std::string& s) { Update(s.data(), s.size()); }

  // Returns lowercase hex digest and resets.
  std::string HexDigest() {
    uint64_t bits = len_ * 8;
    uint8_t pad = 0x80;
    Update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len_ != 56) Update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = (bits >> (56 - 8 * i)) & 0xff;
    // Update() would re-count length; feed the final block directly.
    memcpy(buf_ + 56, lenb, 8);
    Block(buf_);
    static const char* hex = "0123456789abcdef";
    std::string out(64, '0');
    for (int i = 0; i < 8; ++i) {
      for (int j = 0; j < 4; ++j) {
        uint8_t b = (h_[i] >> (24 - 8 * j)) & 0xff;
        out[i * 8 + j * 2] = hex[b >> 4];
        out[i * 8 + j * 2 + 1] = hex[b & 0xf];
      }
    }
    Reset();
    return out;
  }

  static std::string Hash(const std::string& s) {
    Sha256 h;
    h.Update(s);
    return h.HexDigest();
  }

 private:
  static uint32_t Rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void Block(const uint8_t* p) {
    static constexpr uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i) {
      w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
             (uint32_t(p[i * 4 + 2]) << 8) | uint32_t(p[i * 4 + 3]);
    }
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^
                    (w[i - 15] >> 3);
      uint32_t s1 = Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^
                    (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h_[0], b = h_[1], c = h_[2], d = h_[3];
    uint32_t e = h_[4], f = h_[5], g = h_[6], hh = h_[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g;
      g = f;
      f = e;
      e = d + t1;
      d = c;
      c = b;
      b = a;
      a = t1 + t2;
    }
    h_[0] += a;
    h_[1] += b;
    h_[2] += c;
    h_[3] += d;
    h_[4] += e;
    h_[5] += f;
    h_[6] += g;
    h_[7] += hh;
  }

  uint32_t h_[8];
  uint64_t len_ = 0;
  uint8_t buf_[64];
  size_t buf_len_ = 0;
};

}  // namespace tpk
