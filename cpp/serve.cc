#include "serve.h"

#include "util.h"

#include <fcntl.h>
#include <netinet/in.h>
#include <poll.h>
#include <signal.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <functional>

namespace tpk {

namespace {

Allocation AllocFromJson(const Json& j) {
  Allocation a;
  for (const auto& [name, n] : j.items()) {
    a.slices[name] = static_cast<int>(n.as_int());
  }
  return a;
}

Json AllocToJson(const Allocation& a) {
  Json j = Json::Object();
  for (const auto& [name, n] : a.slices) j[name] = n;
  return j;
}

}  // namespace

// --------------------------------------------------------------------------
// HttpProbe
// --------------------------------------------------------------------------

bool HttpProbe::Request(int port, const std::string& raw, std::string* body,
                        int* status) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return false;
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms_);
  auto left_ms = [&]() {
    return static_cast<int>(
        std::max<long long>(0, std::chrono::duration_cast<
                                   std::chrono::milliseconds>(
                                   deadline - std::chrono::steady_clock::now())
                                   .count()));
  };
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    if (errno != EINPROGRESS) {
      close(fd);
      return false;
    }
    pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, left_ms()) <= 0) {
      close(fd);
      return false;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 || err != 0) {
      close(fd);
      return false;
    }
  }
  const std::string& req = raw;
  size_t off = 0;
  while (off < req.size()) {
    ssize_t sent = write(fd, req.data() + off, req.size() - off);
    if (sent > 0) {
      off += sent;
      continue;
    }
    if (errno != EAGAIN && errno != EWOULDBLOCK) {
      close(fd);
      return false;
    }
    pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, left_ms()) <= 0) {
      close(fd);
      return false;
    }
  }
  std::string resp;
  while (true) {
    char buf[4096];
    ssize_t got = read(fd, buf, sizeof(buf));
    if (got > 0) {
      resp.append(buf, got);
      if (resp.size() > (1u << 20)) break;  // cap
      continue;
    }
    if (got == 0) break;
    if (errno != EAGAIN && errno != EWOULDBLOCK) break;
    pollfd pfd{fd, POLLIN, 0};
    if (poll(&pfd, 1, left_ms()) <= 0) break;
  }
  close(fd);
  if (resp.compare(0, 5, "HTTP/") != 0) return false;
  size_t sp = resp.find(' ');
  *status = sp == std::string::npos ? 0 : atoi(resp.c_str() + sp + 1);
  size_t hdr_end = resp.find("\r\n\r\n");
  *body = hdr_end == std::string::npos ? "" : resp.substr(hdr_end + 4);
  return true;
}

bool HttpProbe::Get(int port, const std::string& path, std::string* body,
                    int* status) {
  return Request(port,
                 "GET " + path + " HTTP/1.0\r\nHost: 127.0.0.1\r\n\r\n",
                 body, status);
}

bool HttpProbe::Post(int port, const std::string& path,
                     const std::string& payload, int* status) {
  std::string body;
  return Request(
      port,
      "POST " + path + " HTTP/1.0\r\nHost: 127.0.0.1\r\n"
      "Content-Type: application/json\r\nContent-Length: " +
          std::to_string(payload.size()) + "\r\n\r\n" + payload,
      &body, status);
}

bool HttpProbe::Ready(int port) {
  std::string body;
  int status = 0;
  return Get(port, "/v2/health/ready", &body, &status) && status == 200;
}

bool HttpProbe::ModelReady(int port, const std::string& model,
                           const std::string& want_dir) {
  std::string body;
  int status = 0;
  if (!Get(port, "/v2/models/" + model + "/ready", &body, &status) ||
      status != 200) {
    return false;
  }
  if (want_dir.empty()) return true;
  try {
    return Json::parse(body).get("model_dir").as_string() == want_dir;
  } catch (const std::exception&) {
    return false;
  }
}

bool HttpProbe::Metrics(int port, std::string* body) {
  int status = 0;
  return Get(port, "/metrics", body, &status) && status == 200;
}

// --------------------------------------------------------------------------
// ServeController
// --------------------------------------------------------------------------

ServeController::ServeController(Store* store, ExecutorInterface* executor,
                                 Scheduler* scheduler, ProbeInterface* probe,
                                 std::string workdir, std::string python)
    : store_(store),
      executor_(executor),
      scheduler_(scheduler),
      probe_(probe),
      workdir_(std::move(workdir)),
      python_(std::move(python)) {
  mkdir(workdir_.c_str(), 0755);
}

std::string ServeController::ProcId(const std::string& name, int replica) {
  // "srv" segment keeps these ids disjoint from JAXJob's "<job>/<index>".
  return name + "/srv" + std::to_string(replica);
}

double ServeController::ParseRequestsTotal(const std::string& text) {
  double total = 0;
  size_t pos = 0;
  const std::string key = "tpk_serve_requests_total";
  while (pos < text.size()) {
    size_t nl = text.find('\n', pos);
    if (nl == std::string::npos) nl = text.size();
    std::string line = text.substr(pos, nl - pos);
    pos = nl + 1;
    if (line.compare(0, key.size(), key) != 0) continue;
    size_t sp = line.rfind(' ');
    if (sp != std::string::npos) total += atof(line.c_str() + sp + 1);
  }
  return total;
}

void ServeController::EnsureReplica(View& v, int index) {
  Json replicas = v.status.get("replicaState").is_array()
                      ? v.status.get("replicaState")
                      : Json::Array();
  while (static_cast<int>(replicas.size()) <= index) {
    replicas.push_back(Json());
  }
  Json rs = replicas.elements()[index];
  const std::string id = ProcId(v.res.name, index);

  // 0 = launched, 1 = no capacity (cheap, retry level-style), 2 = spawn
  // failure (must back off — retrying forks at tick rate).
  auto launch = [&](Json& rec) -> int {
    int devices =
        static_cast<int>(v.spec.get("devices_per_replica").as_int(1));
    Allocation alloc;
    if (!rec.get("alloc").is_object() || rec.get("alloc").size() == 0) {
      auto got = scheduler_->Allocate(devices, 1);
      if (!got) {
        rec = Json::Object();
        rec["pendingReason"] = "insufficient device capacity";
        return 1;
      }
      alloc = *got;
      rec["alloc"] = AllocToJson(alloc);
    }
    int port = FreePort();
    const Json& model = v.spec.get("model");
    LaunchSpec s;
    s.id = id;
    s.argv = {python_, "-m", "kubeflow_tpu.serve.server",
              "--port", std::to_string(port)};
    int grpc_port = 0;
    if (v.spec.get("grpc").as_bool(false)) {
      grpc_port = FreePort();
      s.argv.push_back("--grpc-port");
      s.argv.push_back(std::to_string(grpc_port));
    }
    if (!model.get("model_dir").as_string().empty()) {
      s.argv.push_back("--model-dir");
      s.argv.push_back(model.get("model_dir").as_string());
    } else if (!model.get("storage_uri").as_string().empty()) {
      s.argv.push_back("--storage-uri");
      s.argv.push_back(model.get("storage_uri").as_string());
    }
    if (!model.get("name").as_string().empty()) {
      s.argv.push_back("--name");
      s.argv.push_back(model.get("name").as_string());
    }
    // Tensor-parallel serving: model.mesh {"tensor": 8} → --mesh tensor=8
    // (admission already validated axes and the device budget).
    if (model.get("mesh").is_object()) {
      std::string mesh_arg;
      for (const auto& [axis, n] : model.get("mesh").items()) {
        if (!mesh_arg.empty()) mesh_arg += ",";
        mesh_arg += axis + "=" + std::to_string(n.as_int(1));
      }
      s.argv.push_back("--mesh");
      s.argv.push_back(mesh_arg);
    }
    if (v.spec.get("max_batch_size").is_number()) {
      s.argv.push_back("--max-batch-size");
      s.argv.push_back(
          std::to_string(v.spec.get("max_batch_size").as_int()));
    }
    if (v.spec.get("max_latency_ms").is_number()) {
      char buf[32];
      snprintf(buf, sizeof(buf), "%g",
               v.spec.get("max_latency_ms").as_number());
      s.argv.push_back("--max-latency-ms");
      s.argv.push_back(buf);
    }
    int cpu = static_cast<int>(v.spec.get("cpu_devices").as_int(0));
    if (cpu > 0) {
      s.argv.push_back("--cpu-devices");
      s.argv.push_back(std::to_string(cpu));
      s.env["PALLAS_AXON_POOL_IPS"] = "";  // keep axon off CPU workers
    }
    s.env["TPK_SERVICE"] = v.res.name;
    std::string dir = workdir_ + "/" + v.res.name;
    mkdir(dir.c_str(), 0755);
    // Request logger (KServe agent logger): spec.logger = true or
    // {"mode": "all"|"metadata"} → per-replica JSONL request log.
    const Json& logger = v.spec.get("logger");
    if (logger.as_bool(false) || logger.is_object()) {
      s.argv.push_back("--request-log");
      s.argv.push_back(dir + "/requests-" + std::to_string(index) +
                       ".jsonl");
      const std::string mode = logger.get("mode").as_string();
      if (!mode.empty()) {
        s.argv.push_back("--request-log-mode");
        s.argv.push_back(mode);
      }
    }
    s.stdout_path = dir + "/server-" + std::to_string(index) + ".log";
    s.stderr_path = dir + "/server-" + std::to_string(index) + ".err";
    std::string error;
    if (!executor_->LaunchGang({s}, &error)) {
      rec["pendingReason"] = "launch failed: " + error;
      return 2;
    }
    rec["id"] = id;
    rec["port"] = port;
    // Unconditional: a relaunch after spec.grpc was disabled must clear
    // the old port or status would advertise a dead gRPC endpoint.
    rec["grpc_port"] = grpc_port > 0 ? Json(grpc_port) : Json();
    rec["pid"] = executor_->Status(id).pid;
    rec["ready"] = false;
    rec["backoffUntil"] = Json();
    rec["pendingReason"] = Json();
    // Record what this replica serves, so a spec change (canary promote /
    // model update) triggers a rolling restart instead of being ignored.
    rec["model_dir"] = !model.get("model_dir").as_string().empty()
                           ? model.get("model_dir")
                           : model.get("storage_uri");
    metrics_.replica_starts++;
    return 0;
  };

  auto schedule_backoff = [&](Json& rec) {
    int64_t restarts = rec.get("restarts").as_int(0);
    rec["restarts"] = restarts + 1;
    double delay =
        std::min(60.0, std::pow(2.0, std::min<int64_t>(restarts, 6)));
    rec["backoffUntil"] = now_s_ + delay;
  };

  if (rs.is_null() || !rs.get("id").is_string()) {
    if (rs.is_null()) rs = Json::Object();
    // Spawn failures back off (forking at tick rate is a fork bomb);
    // capacity waits retry level-style — Allocate is cheap and the device
    // may free any moment.
    if (!rs.get("backoffUntil").is_number() ||
        now_s_ >= rs.get("backoffUntil").as_number(0)) {
      if (launch(rs) == 2) schedule_backoff(rs);
    }
    Json arr = Json::Array();
    for (size_t i = 0; i < replicas.size(); ++i) {
      arr.push_back(static_cast<int>(i) == index ? rs
                                                 : replicas.elements()[i]);
    }
    v.status["replicaState"] = arr;
    return;
  }

  auto st = executor_->Status(id);
  if (st.phase == ProcessStatus::Phase::kRunning) {
    // Model changed under this replica (e.g. canary promoted): bounce it —
    // ROLLING: at most one not-ready replica at a time, so a multi-replica
    // service keeps serving through a model update (a 1-replica service
    // unavoidably blips). backoffUntil=0 routes the relaunch through the
    // "backoff elapsed" branch immediately, without counting a crash.
    const Json& model = v.spec.get("model");
    const std::string want =
        !model.get("model_dir").as_string().empty()
            ? model.get("model_dir").as_string()
            : model.get("storage_uri").as_string();
    if (rs.get("model_dir").is_string() &&
        rs.get("model_dir").as_string() != want) {
      bool others_ready = true;
      for (size_t i = 0; i < replicas.size(); ++i) {
        if (static_cast<int>(i) == index) continue;
        const Json& other = replicas.elements()[i];
        if (other.is_object() && other.get("id").is_string() &&
            !other.get("ready").as_bool(false)) {
          others_ready = false;
          break;
        }
      }
      if (others_ready) {
        executor_->Kill(id);
        rs["ready"] = false;
        rs["backoffUntil"] = 0.0;
        Json arr2 = Json::Array();
        for (size_t i = 0; i < replicas.size(); ++i) {
          arr2.push_back(static_cast<int>(i) == index
                             ? rs
                             : replicas.elements()[i]);
        }
        v.status["replicaState"] = arr2;
        return;
      }
    }
    bool ready = rs.get("ready").as_bool(false);
    // Not-ready replicas probe every 1s; ready ones re-probe every 10s —
    // the kubelet liveness analog, so a wedged-but-alive server drops out
    // of the endpoint list instead of staying Ready forever.
    double interval = ready ? 10.0 : 1.0;
    if (now_s_ - rs.get("lastProbe").as_number(0) >= interval) {
      rs["lastProbe"] = now_s_;
      if (probe_->Ready(static_cast<int>(rs.get("port").as_int()))) {
        rs["probeFails"] = 0;
        if (!ready) {
          rs["ready"] = true;
          rs["readySince"] = now_s_;
        }
      } else if (ready) {
        int64_t fails = rs.get("probeFails").as_int(0) + 1;
        rs["probeFails"] = fails;
        if (fails >= 2) rs["ready"] = false;  // wedged: pull endpoint
      }
    }
  } else {
    // Server exited — crash-loop with exponential backoff. A long stable
    // run resets the streak so one crash a day doesn't accrue forever.
    rs["ready"] = false;
    if (!rs.get("backoffUntil").is_number()) {
      if (rs.get("readySince").is_number() &&
          now_s_ - rs.get("readySince").as_number(0) > 300) {
        rs["restarts"] = 0;
      }
      schedule_backoff(rs);
      rs["readySince"] = Json();
      metrics_.replica_restarts++;
    } else if (now_s_ >= rs.get("backoffUntil").as_number(0)) {
      if (launch(rs) == 2) schedule_backoff(rs);  // keeps alloc, new port
    }
  }
  Json arr = Json::Array();
  for (size_t i = 0; i < replicas.size(); ++i) {
    arr.push_back(static_cast<int>(i) == index ? rs
                                               : replicas.elements()[i]);
  }
  v.status["replicaState"] = arr;
}

void ServeController::StopReplica(View& v, int index) {
  const Json& replicas = v.status.get("replicaState");
  if (!replicas.is_array() ||
      index >= static_cast<int>(replicas.size())) {
    return;
  }
  const Json& rs = replicas.elements()[index];
  if (rs.is_object()) {
    if (rs.get("id").is_string()) {
      executor_->Kill(rs.get("id").as_string());
    }
    if (rs.get("alloc").is_object() && rs.get("alloc").size() > 0) {
      scheduler_->Release(AllocFromJson(rs.get("alloc")));
    }
  }
}

int ServeController::DesiredReplicas(View& v) {
  int64_t min_r = v.spec.get("min_replicas").as_int(
      v.spec.get("replicas").as_int(1));
  int64_t max_r = v.spec.get("max_replicas").as_int(min_r);
  double target = v.spec.get("target_rps").as_number(0);
  // Scale-to-zero (the Knative KPA capability, SURVEY.md §5.3): after
  // `scale_to_zero_after_s` with no served requests the replica count
  // drops to 0 (processes stopped, devices released). Cold start is an
  // explicit control-plane activation — clients that find the service
  // Idle bump `spec.wake` (TrainingClient.wake_service) and wait Ready;
  // a data-plane activator proxy that buffers the first request is the
  // production shape this stands in for.
  double idle_after = v.spec.get("scale_to_zero_after_s").as_number(0);
  bool rps_autoscale = target > 0 && max_r > min_r;
  if (!rps_autoscale && idle_after <= 0) {
    // Disabling scale-to-zero must clear a stale reaped marker, or
    // re-enabling it later would instantly reap the live service.
    if (v.status.get("idle").as_bool(false)) v.status["idle"] = false;
    return static_cast<int>(v.spec.get("replicas").as_int(min_r));
  }
  // Throughput autoscaler: rps over the scrape interval / target per
  // replica (KPA stand-in).
  Json as = v.status.get("autoscale").is_object()
                ? v.status.get("autoscale")
                : Json::Object();
  // Fixed-replica services must keep following spec.replicas updates —
  // only the rps autoscaler owns the persisted `desired`.
  int desired = static_cast<int>(
      rps_autoscale ? as.get("desired").as_int(min_r)
                    : v.spec.get("replicas").as_int(min_r));
  double interval = v.spec.get("scale_interval_s").as_number(10);
  double last_t = as.get("lastTime").as_number(0);
  if (now_s_ - last_t >= interval) {
    // Per-replica (per-port) counter deltas: a restarted replica resets its
    // counter to 0, and a replica whose scrape fails must be skipped — a
    // global total would read either case as negative load and scale the
    // service down under real traffic.
    Json baselines = as.get("perReplica").is_object()
                         ? as.get("perReplica")
                         : Json::Object();
    double delta = 0;
    bool scraped = false, attempted = false;
    const Json& replicas = v.status.get("replicaState");
    if (replicas.is_array()) {
      for (const auto& rs : replicas.elements()) {
        if (!rs.is_object() || !rs.get("ready").as_bool(false)) continue;
        attempted = true;
        std::string body;
        int port = static_cast<int>(rs.get("port").as_int());
        if (!probe_->Metrics(port, &body)) continue;  // baseline persists
        double t = ParseRequestsTotal(body);
        std::string key = std::to_string(port);
        if (baselines.has(key)) {
          double prev = baselines.get(key).as_number(0);
          // Counter went backwards ⇒ server restarted on the same port:
          // everything it now reports happened inside this window.
          delta += t >= prev ? t - prev : t;
        }
        // First successful scrape of a port only sets its baseline.
        baselines[key] = t;
        scraped = true;
      }
    }
    if (attempted) {
      // Record the attempt time even when every scrape failed, so a
      // wedged /metrics endpoint is retried once per interval, not once
      // per 50ms loop tick.
      as["lastTime"] = now_s_;
    }
    if (scraped) {
      as["lastScrapeOk"] = now_s_;
      if (delta > 0) {
        as["lastActive"] = now_s_;  // served traffic this window
      }
      if (rps_autoscale && last_t > 0) {
        double rps = delta / (now_s_ - last_t);
        desired = static_cast<int>(std::ceil(rps / target));
        desired = std::max(desired, static_cast<int>(min_r));
        desired = std::min(desired, static_cast<int>(max_r));
        if (desired != static_cast<int>(as.get("desired").as_int(min_r))) {
          metrics_.scale_events++;
          as["lastScaleTime"] = now_s_;
        }
      }
      as["perReplica"] = baselines;
      as["desired"] = desired;
    }
    v.status["autoscale"] = as;
  }
  // Idle reaping applies only when something would otherwise run — a
  // service scaled to zero BY HAND stays phase Ready, never Idle.
  if (idle_after > 0 && desired > 0) {
    bool reaped = v.status.get("idle").as_bool(false);
    double last_active = as.get("lastActive").as_number(0);
    // Activation: a wake timestamp newer than the last activity counts
    // as activity (and survives restarts — both live in the store).
    double wake = v.spec.get("wake").as_number(0);
    if (wake > last_active) {
      last_active = wake;
      as["lastActive"] = wake;
      v.status["autoscale"] = as;
    }
    // The idle clock only runs while the service can actually serve: a
    // replica still loading its model (cold start can exceed a short
    // idle window) or crash-looping must not be reaped as "idle" —
    // unless it is ALREADY reaped, where zero ready replicas is the
    // steady state and refreshing would immediately resurrect it.
    bool any_ready = false;
    const Json& reps = v.status.get("replicaState");
    if (reps.is_array()) {
      for (const auto& rs : reps.elements()) {
        if (rs.is_object() && rs.get("ready").as_bool(false)) {
          any_ready = true;
          break;
        }
      }
    }
    if (!reaped && !any_ready) {
      // Refresh at bounded granularity, not per tick — a long cold
      // start or crash loop must not append a WAL record per second.
      // The grain must not exceed idle_after: with idle_after <
      // interval, an interval-stale lastActive at the ready transition
      // would let the first post-cold-start scrape reap the service
      // before it served anything.
      double grain = std::min(interval, idle_after) / 2.0;
      if (now_s_ - last_active >= grain) {
        as["lastActive"] = now_s_;
        v.status["autoscale"] = as;
      }
      return desired;
    }
    if (last_active == 0) {
      // Defensive: ready with no recorded activity — start the clock.
      as["lastActive"] = now_s_;
      v.status["autoscale"] = as;
    } else if (as.get("lastScrapeOk").as_number(0) - last_active >=
               idle_after) {
      // Reap only on scrape EVIDENCE: a successful /metrics read at
      // least idle_after past the last activity. Comparing against
      // wall-clock `now` instead would reap a busy service whenever
      // idle_after < scale_interval_s (traffic lands between scrapes)
      // or whenever its metrics endpoint is wedged.
      if (!v.status.get("idle").as_bool(false)) {
        // Transition only: an idle service must not re-fire the metric
        // or rewrite its status (WAL churn) on every 50ms tick.
        metrics_.scale_events++;
        as["lastScaleTime"] = now_s_;
        v.status["autoscale"] = as;
        v.status["idle"] = true;
      }
      return 0;
    }
  }
  if (v.status.get("idle").as_bool(false)) v.status["idle"] = false;
  return desired;
}

void ServeController::Reconcile(const std::string& name) {
  auto res = store_->Get("InferenceService", name);
  if (!res || res->deleted) return;
  View v{*res, res->spec, res->status};

  if (v.status.get("phase").as_string().empty()) {
    metrics_.services_created++;
  }

  int desired = DesiredReplicas(v);
  desired = std::max(desired, 0);

  // Scale down: stop surplus replicas (highest index first).
  Json replicas = v.status.get("replicaState").is_array()
                      ? v.status.get("replicaState")
                      : Json::Array();
  if (static_cast<int>(replicas.size()) > desired) {
    for (int i = static_cast<int>(replicas.size()) - 1; i >= desired; --i) {
      StopReplica(v, i);
    }
    Json trimmed = Json::Array();
    for (int i = 0; i < desired; ++i) {
      trimmed.push_back(replicas.elements()[i]);
    }
    v.status["replicaState"] = trimmed;
  }
  // Scale up / keep alive.
  for (int i = 0; i < desired; ++i) {
    EnsureReplica(v, i);
  }

  // Aggregate status + endpoints.
  int running = 0, ready = 0;
  Json endpoints = Json::Array();
  const Json& rss = v.status.get("replicaState");
  if (rss.is_array()) {
    for (size_t i = 0; i < rss.size(); ++i) {
      const Json& rs = rss.elements()[i];
      if (!rs.is_object() || !rs.get("id").is_string()) continue;
      auto st = executor_->Status(rs.get("id").as_string());
      if (st.phase == ProcessStatus::Phase::kRunning) {
        ++running;
        if (rs.get("ready").as_bool(false)) {
          ++ready;
          Json ep = Json::Object();
          ep["replica"] = static_cast<int>(i);
          ep["url"] = "http://127.0.0.1:" +
                      std::to_string(rs.get("port").as_int());
          if (rs.get("grpc_port").is_number()) {
            ep["grpc"] = "127.0.0.1:" +
                         std::to_string(rs.get("grpc_port").as_int());
          }
          endpoints.push_back(ep);
        }
      }
    }
  }
  Json counts = Json::Object();
  counts["desired"] = desired;
  counts["running"] = running;
  counts["ready"] = ready;
  v.status["replicas"] = counts;

  // Canary rollout (KServe canaryTrafficPercent): spec.canary =
  // {model_dir, traffic_percent, replicas?} materializes a shadow
  // "<name>-canary" service running the candidate model; the primary's
  // endpoint list carries BOTH tracks with traffic weights. Promote =
  // update spec.model.model_dir to the canary dir and drop spec.canary
  // (replicas roll to the new model); rollback = drop spec.canary.
  const Json& canary = v.spec.get("canary");
  const std::string child_name = name + "-canary";
  const bool is_child = !v.spec.get("canary_of").as_string().empty();
  if (!is_child && canary.is_object() &&
      !canary.get("model_dir").as_string().empty()) {
    int64_t pct = canary.get("traffic_percent").as_int(10);
    pct = std::max<int64_t>(0, std::min<int64_t>(100, pct));
    Json cspec = Json::Object();
    for (const auto& [k, val] : v.spec.items()) {
      if (k == "canary" || k == "min_replicas" || k == "max_replicas" ||
          k == "target_rps") {
        continue;  // the canary track doesn't autoscale
      }
      cspec[k] = val;
    }
    Json cmodel = v.spec.get("model");
    cmodel["model_dir"] = canary.get("model_dir");
    cspec["model"] = cmodel;
    cspec["replicas"] = canary.get("replicas").as_int(1);
    cspec["canary_of"] = name;
    auto child = store_->Get("InferenceService", child_name);
    if (child && child->spec.get("canary_of").as_string() != name) {
      // A pre-existing unrelated service holds the shadow's name: refuse
      // to adopt it (updating would hijack — and later delete — a user's
      // service); surface the conflict instead.
      Json cstat = Json::Object();
      cstat["error"] = "canary blocked: service " + child_name +
                       " already exists and is not this service's shadow";
      v.status["canary"] = cstat;
    } else {
      if (!child) {
        store_->Create("InferenceService", child_name, cspec);
        metrics_.canary_rollouts++;
      } else if (child->spec.dump() != cspec.dump()) {
        store_->UpdateSpec("InferenceService", child_name, cspec);
      }
      // Weighted endpoint union: stable gets 100-pct, canary pct.
      Json weighted = Json::Array();
      for (const auto& ep : endpoints.elements()) {
        Json e = ep;
        e["track"] = "stable";
        e["weight"] = 100 - pct;
        weighted.push_back(e);
      }
      int canary_ready = 0;
      if (child) {
        for (const auto& ep : child->status.get("endpoints").elements()) {
          Json e = ep;
          e["track"] = "canary";
          e["weight"] = pct;
          weighted.push_back(e);
          ++canary_ready;
        }
      }
      endpoints = weighted;
      Json cstat = Json::Object();
      cstat["service"] = child_name;
      cstat["traffic_percent"] = pct;
      cstat["ready"] = canary_ready;
      v.status["canary"] = cstat;
    }
  } else if (!is_child) {
    // No canary configured: tear down a stale child of ours.
    auto child = store_->Get("InferenceService", child_name);
    if (child && child->spec.get("canary_of").as_string() == name) {
      store_->Delete("InferenceService", child_name);
    }
    if (v.status.has("canary")) v.status["canary"] = Json();
  }
  v.status["endpoints"] = endpoints;

  std::string phase;
  if (desired == 0) {
    // Idle = reaped by scale-to-zero (wake brings it back); Ready =
    // scaled to zero by hand.
    phase = v.status.get("idle").as_bool(false) ? "Idle" : "Ready";
  } else if (ready == desired) {
    phase = "Ready";
  } else if (running > 0) {
    phase = "Running";
  } else {
    phase = "Pending";
  }
  const std::string prev = v.status.get("phase").as_string();
  v.status["phase"] = phase;
  if (prev != phase) {
    if (!v.status.has("conditions")) v.status["conditions"] = Json::Array();
    Json cond = Json::Object();
    cond["type"] = phase;
    cond["status"] = "True";
    cond["reason"] = phase == "Ready"  ? "AllReplicasReady"
                     : phase == "Idle" ? "ScaledToZero"
                                       : "Reconciling";
    cond["message"] = std::to_string(ready) + "/" +
                      std::to_string(desired) + " replicas ready";
    cond["lastTransitionTime"] = Timestamp(now_s_);
    v.status["conditions"].push_back(cond);
    // Services have no terminal phase, so a crash-looping one flaps
    // forever: keep only the newest conditions or the status (and every
    // WAL rewrite of it) grows without bound.
    const Json& conds = v.status.get("conditions");
    if (conds.size() > 20) {
      Json trimmed = Json::Array();
      for (size_t i = conds.size() - 20; i < conds.size(); ++i) {
        trimmed.push_back(conds.elements()[i]);
      }
      v.status["conditions"] = trimmed;
    }
  }

  if (v.status.dump() != res->status.dump()) {
    store_->UpdateStatus("InferenceService", name, v.status);
  }
}

void ServeController::Tick(double now_s) {
  now_s_ = now_s;
  for (const auto& res : store_->List("InferenceService")) {
    Reconcile(res.name);
  }
}

void ServeController::OnDeleted(const Resource& res) {
  const Json& replicas = res.status.get("replicaState");
  if (replicas.is_array()) {
    for (const auto& rs : replicas.elements()) {
      if (!rs.is_object()) continue;
      if (rs.get("id").is_string()) {
        executor_->Kill(rs.get("id").as_string());
      }
      if (rs.get("alloc").is_object() && rs.get("alloc").size() > 0) {
        scheduler_->Release(AllocFromJson(rs.get("alloc")));
      }
    }
  }
  // Deleting a primary cascades to its canary shadow (whose own kDeleted
  // event then kills the canary replicas through this same path).
  const std::string child_name = res.name + "-canary";
  auto child = store_->Get("InferenceService", child_name);
  if (child && child->spec.get("canary_of").as_string() == res.name) {
    store_->Delete("InferenceService", child_name);
  }
}

void ServeController::Recover() {
  // Orphaned server processes from a previous control-plane incarnation:
  // kill by recorded pid and relaunch fresh (allocations were rebuilt
  // empty with the scheduler).
  for (const auto& res : store_->List("InferenceService")) {
    const Json& replicas = res.status.get("replicaState");
    if (!replicas.is_array() || replicas.size() == 0) continue;
    for (const auto& rs : replicas.elements()) {
      int pid = static_cast<int>(
          rs.is_object() ? rs.get("pid").as_int(-1) : -1);
      // Whole process group, like JaxJobController::Recover — the server
      // may have forked helpers (storage initializer) that must die too.
      if (pid > 1) kill(-pid, SIGKILL);
    }
    Json status = res.status;
    status["replicaState"] = Json::Array();
    status["phase"] = "Pending";
    store_->UpdateStatus("InferenceService", res.name, status);
  }
}

// -- TrainedModel controller -------------------------------------------------

namespace {
// Re-post the async load if no readiness after this long (covers a lost
// POST or a server that failed mid-load and cleared its error on retry).
constexpr double kLoadRepostSeconds = 60.0;
}  // namespace

void TrainedModelController::Tick(double now_s) {
  now_s_ = now_s;
  for (const auto& res : store_->List("TrainedModel")) Reconcile(res.name);
}

void TrainedModelController::Reconcile(const std::string& name) {
  auto r = store_->Get("TrainedModel", name);
  if (!r) return;
  const Json& spec = r->spec;
  Json status = r->status;
  const std::string parent = spec.get("inference_service").as_string();
  const Json& model = spec.get("model");
  const std::string mname = model.get("name").as_string();
  const std::string mdir = model.get("model_dir").as_string();

  auto update = [&](Json& next) {
    if (next.dump() != r->status.dump()) {  // WAL writes only on change
      store_->UpdateStatus("TrainedModel", name, next);
    }
  };

  auto isvc = store_->Get("InferenceService", parent);
  if (!isvc) {
    status["phase"] = "Pending";
    status["message"] = "waiting for InferenceService " + parent;
    status["loaded"] = Json::Object();
    status["posted"] = Json::Object();
    update(status);
    return;
  }

  // Name collisions silently hijack the parent's (or a sibling's) model in
  // the shared repository — reject instead (first created wins; creation
  // order via resource id).
  if (isvc->spec.get("model").get("name").as_string() == mname) {
    status["phase"] = "Failed";
    status["message"] = "model.name " + mname +
                        " collides with the parent's base model";
    update(status);
    return;
  }
  for (const auto& other : store_->List("TrainedModel")) {
    if (other.name == name) continue;
    if (other.spec.get("inference_service").as_string() == parent &&
        other.spec.get("model").get("name").as_string() == mname &&
        other.name < name) {  // deterministic winner (no creation ts kept)
      status["phase"] = "Failed";
      status["message"] = "model.name " + mname +
                          " collides with TrainedModel " + other.name;
      update(status);
      return;
    }
  }

  // Rename: RETIRE the previous name — unload it from every replica,
  // retrying across ticks until each current replica acknowledged (a
  // momentarily-unready replica must not keep the old model forever;
  // 404 counts as done — that server never had it, e.g. post-restart).
  const std::string prev = status.get("modelName").as_string();
  const Json& replicas = isvc->status.get("replicaState");
  Json retired = status.get("retired").is_object() ? status.get("retired")
                                                   : Json::Object();
  if (!prev.empty() && prev != mname) {
    if (!retired.has(prev)) retired[prev] = Json::Object();
    status["loaded"] = Json::Object();
    status["posted"] = Json::Object();
  }
  status["modelName"] = mname;
  if (replicas.is_array()) {
    Json retired_next = Json::Object();
    for (const auto& [rn, done0] : retired.items()) {
      if (rn == mname) continue;  // renamed back: live again, not retired
      Json done = done0.is_object() ? done0 : Json::Object();
      bool complete = true;
      for (const auto& rs : replicas.elements()) {
        if (!rs.is_object()) continue;
        const std::string key =
            std::to_string(rs.get("port").as_int()) + ":" +
            std::to_string(rs.get("pid").as_int(-1));
        if (done.get(key).as_bool(false)) continue;
        if (!rs.get("ready").as_bool(false)) {
          complete = false;  // retry when it comes back (or vanishes)
          continue;
        }
        int http = 0;
        if (probe_->Post(static_cast<int>(rs.get("port").as_int()),
                         "/v2/repository/models/" + rn + "/unload", "{}",
                         &http) &&
            (http / 100 == 2 || http == 404)) {
          done[key] = true;
          if (http / 100 == 2) metrics_.unloads++;
        } else {
          complete = false;
        }
      }
      if (!complete) retired_next[rn] = done;
    }
    retired = retired_next;
  }
  status["retired"] = retired;

  // Per-replica load state, keyed port:pid:spec-digest: a restarted
  // replica (new pid) re-loads, and a model_dir/name change (new digest)
  // re-loads on live replicas. Keys survive readiness blips — they are
  // pruned only when the replica itself is gone.
  // FNV-1a, not std::hash: std::hash is implementation-defined, so a
  // controller binary/stdlib upgrade would change every digest and
  // trigger a spurious re-load of every model on every replica.
  const std::string digest_src = mname + "|" + mdir;
  uint64_t fnv = 1469598103934665603ull;
  for (unsigned char c : digest_src) {
    fnv ^= c;
    fnv *= 1099511628211ull;
  }
  const std::string digest = std::to_string(fnv);
  const Json loaded_old = status.get("loaded").is_object()
                              ? status.get("loaded")
                              : Json::Object();
  const Json posted_old = status.get("posted").is_object()
                              ? status.get("posted")
                              : Json::Object();
  Json loaded = Json::Object();
  Json posted = Json::Object();
  int ready_n = 0, loaded_n = 0;
  if (replicas.is_array()) {
    Json payload = Json::Object();
    payload["model_dir"] = mdir;
    const std::string body = payload.dump();
    for (const auto& rs : replicas.elements()) {
      if (!rs.is_object()) continue;
      const int port = static_cast<int>(rs.get("port").as_int());
      const std::string key = std::to_string(port) + ":" +
                              std::to_string(rs.get("pid").as_int(-1)) +
                              ":" + digest;
      const bool was_loaded = loaded_old.get(key).as_bool(false);
      if (!rs.get("ready").as_bool(false)) {
        // Blip tolerance: a known-loaded replica that is momentarily
        // unready keeps its state — reloading a server that still has
        // the model would recompile for nothing.
        if (was_loaded) loaded[key] = true;
        continue;
      }
      ready_n++;
      if (was_loaded) {
        loaded[key] = true;
        loaded_n++;
        continue;
      }
      const double since = posted_old.get(key).as_number(0);
      // Readiness only counts AFTER we posted for this key: on a
      // model_dir change the server's previous version still answers
      // ready, and trusting it would skip the re-load entirely. (During
      // a version swap the old model serves until the new load lands —
      // readiness is optimistic for that window, by design.)
      if (since > 0 && probe_->ModelReady(port, mname, mdir)) {
        loaded[key] = true;
        loaded_n++;
        metrics_.loads++;
        continue;
      }
      if (since > 0 && now_s_ - since < kLoadRepostSeconds) {
        posted[key] = since;  // in flight; poll again next tick
        continue;
      }
      int http = 0;
      if (probe_->Post(port, "/v2/repository/models/" + mname + "/load",
                       body, &http) &&
          (http == 200 || http == 202)) {
        posted[key] = now_s_;
      } else {
        metrics_.load_failures++;  // retried next Tick
      }
    }
  }
  status["loaded"] = loaded;
  status["posted"] = posted;
  Json counts = Json::Object();
  counts["ready"] = ready_n;
  counts["loaded"] = loaded_n;
  status["replicas"] = counts;
  if (ready_n == 0) {
    status["phase"] = "Pending";
    status["message"] = "no ready replicas on " + parent;
  } else if (loaded_n == ready_n) {
    status["phase"] = "Ready";
    status["message"] = "";
  } else {
    status["phase"] = "Pending";
    status["message"] = "loading (" + std::to_string(loaded_n) + "/" +
                        std::to_string(ready_n) + " replicas)";
  }
  update(status);
}

void TrainedModelController::OnDeleted(const Resource& res) {
  // Best-effort unload from every replica that had it (the server marks
  // the model UNAVAILABLE; a vanished replica is already clean).
  const std::string parent = res.spec.get("inference_service").as_string();
  const std::string mname = res.spec.get("model").get("name").as_string();
  auto isvc = store_->Get("InferenceService", parent);
  if (!isvc || mname.empty()) return;
  const Json& replicas = isvc->status.get("replicaState");
  if (!replicas.is_array()) return;
  for (const auto& rs : replicas.elements()) {
    if (!rs.is_object() || !rs.get("ready").as_bool(false)) continue;
    int http = 0;
    if (probe_->Post(static_cast<int>(rs.get("port").as_int()),
                     "/v2/repository/models/" + mname + "/unload", "{}",
                     &http) &&
        http / 100 == 2) {
      metrics_.unloads++;
    }
  }
}

}  // namespace tpk
