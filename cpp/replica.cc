#include "replica.h"

#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdio>
#include <cstring>

namespace tpk {

namespace {

constexpr int kVoteTimeoutMs = 300;       // per-peer vote RPC
constexpr int kShipTimeoutMs = 1000;      // per-peer append RPC
constexpr int kSnapshotTimeoutMs = 4000;  // catch-up transfer

}  // namespace

Replication::Replication(Store* store, Options opts)
    : store_(store), opts_(std::move(opts)) {
  for (const auto& sock : opts_.peers) {
    if (sock.empty() || sock == opts_.self) continue;
    peers_.push_back(Peer{sock, -1, 0, false});
  }
  // Deterministic-enough jitter seed: distinct per replica identity and
  // process, so simultaneous restarts don't campaign in lockstep.
  rng_state_ = static_cast<unsigned>(getpid());
  for (char c : opts_.self) rng_state_ = rng_state_ * 31 + c;
  LoadState();
  leader_ = opts_.leader_hint;
  last_contact_ms_ = NowMs();
  // Bootstrap (no --replica-of): campaign quickly so a fresh cluster
  // forms without waiting a full lease. With a leader hint, give that
  // leader its whole lease first.
  ResetElectionDeadline(/*short_fuse=*/opts_.leader_hint.empty());
}

double Replication::NowMs() const {
  return std::chrono::duration<double, std::milli>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void Replication::ResetElectionDeadline(bool short_fuse) {
  const int base = short_fuse ? std::max(opts_.lease_ms / 4, 100)
                              : opts_.lease_ms;
  const int jitter_span = std::max(base / 2, 50);
  const int jitter = static_cast<int>(rand_r(&rng_state_) %
                                      static_cast<unsigned>(jitter_span));
  election_deadline_ms_ = NowMs() + base + jitter;
}

void Replication::LoadState() {
  if (opts_.state_path.empty()) return;
  FILE* f = fopen(opts_.state_path.c_str(), "r");
  if (!f) return;
  char buf[512];
  size_t got = fread(buf, 1, sizeof(buf) - 1, f);
  fclose(f);
  buf[got] = '\0';
  try {
    Json st = Json::parse(buf);
    term_ = st.get("term").as_int(0);
    voted_for_ = st.get("votedFor").as_string();
  } catch (const std::exception& e) {
    fprintf(stderr, "tpk-controlplane: replication state %s unreadable "
            "(%s) — starting at term 0\n", opts_.state_path.c_str(),
            e.what());
  }
}

void Replication::PersistState() {
  // Terms and votes must survive a crash (a replica that forgets its
  // vote could grant two candidates the same term — split brain), so
  // this is temp + fsync + atomic rename, all checked.
  if (opts_.state_path.empty()) return;
  Json st = Json::Object();
  st["term"] = term_;
  st["votedFor"] = voted_for_;
  std::string data = st.dump();
  data += '\n';
  std::string tmp = opts_.state_path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) {
    fprintf(stderr, "tpk-controlplane: cannot persist replication state "
            "%s: %s\n", tmp.c_str(), strerror(errno));
    return;
  }
  bool ok = fwrite(data.data(), 1, data.size(), f) == data.size();
  ok = ok && fflush(f) == 0 && fsync(fileno(f)) == 0;
  if (fclose(f) != 0) ok = false;
  if (!ok || rename(tmp.c_str(), opts_.state_path.c_str()) != 0) {
    remove(tmp.c_str());
    fprintf(stderr, "tpk-controlplane: cannot persist replication state "
            "%s: %s\n", opts_.state_path.c_str(), strerror(errno));
  }
}

bool Replication::TookLeadership() {
  bool took = leadership_gained_;
  leadership_gained_ = false;
  return took;
}

void Replication::BecomeLeader() {
  role_ = Role::kLeader;
  leader_ = opts_.self;
  // Whatever the log holds is now committed by fiat of the election
  // restriction (we were at least as long as a majority): apply any
  // suffix the old leader never confirmed, then serve from it.
  store_->ApplyReplicatedUpTo(store_->WalSeq());
  commit_seq_ = store_->WalSeq();
  for (auto& p : peers_) {
    p.acked_seq = 0;  // re-learn follower positions via heartbeats
    p.reachable = false;
  }
  last_quorum_ok_ms_ = NowMs();
  last_heartbeat_ms_ = 0;  // heartbeat on the next Tick
  leadership_gained_ = true;
  fprintf(stderr, "tpk-controlplane: LEADER at term %lld (seq %llu, "
          "%zu peers, quorum %d)\n", static_cast<long long>(term_),
          static_cast<unsigned long long>(store_->WalSeq()),
          peers_.size(), quorum());
}

void Replication::StepDown(const std::string& reason, int64_t new_term) {
  if (new_term > term_) {
    term_ = new_term;
    voted_for_.clear();
    PersistState();
  }
  if (role_ == Role::kLeader) {
    fprintf(stderr, "tpk-controlplane: stepping down at term %lld: %s\n",
            static_cast<long long>(term_), reason.c_str());
  }
  role_ = Role::kFollower;
  leader_.clear();
  last_contact_ms_ = NowMs();
  ResetElectionDeadline(false);
}

bool Replication::PeerRequest(Peer& p, const Json& req, Json* resp,
                              int timeout_ms) {
  std::string line = req.dump();
  line += '\n';
  for (int attempt = 0; attempt < 2; ++attempt) {
    const bool was_cached = p.fd >= 0;
    if (p.fd < 0) {
      p.fd = socket(AF_UNIX, SOCK_STREAM, 0);
      if (p.fd < 0) return false;
      sockaddr_un addr{};
      addr.sun_family = AF_UNIX;
      strncpy(addr.sun_path, p.sock.c_str(), sizeof(addr.sun_path) - 1);
      if (connect(p.fd, reinterpret_cast<sockaddr*>(&addr),
                  sizeof(addr)) != 0) {
        close(p.fd);
        p.fd = -1;
        p.reachable = false;
        return false;
      }
    }
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(p.fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(p.fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    bool ok = true;
    size_t off = 0;
    while (off < line.size()) {
      ssize_t n = send(p.fd, line.data() + off, line.size() - off,
                       MSG_NOSIGNAL);
      if (n <= 0) {
        ok = false;
        break;
      }
      off += static_cast<size_t>(n);
    }
    std::string buf;
    while (ok && buf.find('\n') == std::string::npos) {
      char tmp[65536];
      ssize_t n = recv(p.fd, tmp, sizeof(tmp), 0);
      if (n <= 0) {
        ok = false;
        break;
      }
      buf.append(tmp, static_cast<size_t>(n));
    }
    if (!ok) {
      // A timed-out or half-done exchange leaves request/reply pairing
      // undefined on this connection — drop it so the next request
      // starts clean (the Python client's reset-on-error rule).
      close(p.fd);
      p.fd = -1;
      p.reachable = false;
      if (was_cached) continue;  // stale cached fd: one fresh reconnect
      return false;
    }
    try {
      *resp = Json::parse(buf.substr(0, buf.find('\n')));
    } catch (const std::exception&) {
      close(p.fd);
      p.fd = -1;
      p.reachable = false;
      return false;
    }
    p.reachable = true;
    return true;
  }
  return false;
}

bool Replication::ShipSnapshotTo(Peer& p, int timeout_ms) {
  std::string snap, wal;
  if (!store_->ReadReplicaFiles(&snap, &wal)) return false;
  Json req = Json::Object();
  req["op"] = "repl.snapshot";
  req["term"] = term_;
  req["leader"] = opts_.self;
  req["commitSeq"] = static_cast<int64_t>(commit_seq_);
  req["snapshot"] = snap;
  req["wal"] = wal;
  Json resp;
  if (!PeerRequest(p, req, &resp, timeout_ms)) return false;
  if (resp.get("staleTerm").as_bool()) {
    StepDown("stale term reported by " + p.sock,
             resp.get("term").as_int());
    return false;
  }
  if (!resp.get("ok").as_bool()) return false;
  ++snapshots_shipped_;
  p.acked_seq = static_cast<uint64_t>(resp.get("seq").as_int());
  return true;
}

int Replication::ShipRound(const Store::BatchBytes& batch,
                           int timeout_ms) {
  int acks = 0;
  for (auto& p : peers_) {
    if (role_ != Role::kLeader) break;  // deposed mid-round
    if (p.acked_seq >= batch.last_seq) {
      ++acks;
      continue;
    }
    Json req = Json::Object();
    req["op"] = "repl.append";
    req["term"] = term_;
    req["leader"] = opts_.self;
    req["prevSeq"] = static_cast<int64_t>(batch.prev_seq);
    req["prevCrc"] = static_cast<int64_t>(batch.prev_crc);
    req["commitSeq"] = static_cast<int64_t>(commit_seq_);
    req["data"] = batch.bytes;
    Json resp;
    if (!PeerRequest(p, req, &resp, timeout_ms)) continue;
    if (resp.get("staleTerm").as_bool()) {
      StepDown("stale term reported by " + p.sock,
               resp.get("term").as_int());
      break;
    }
    if (resp.get("ok").as_bool()) {
      p.acked_seq = static_cast<uint64_t>(resp.get("seq").as_int());
    } else if (resp.get("needSnapshot").as_bool()) {
      // The follower's log diverged (behind after a crash, or carrying
      // records a rolled-back batch left stranded): reseed it from our
      // snapshot + tail, then re-ship this batch.
      if (ShipSnapshotTo(p, kSnapshotTimeoutMs) &&
          PeerRequest(p, req, &resp, timeout_ms) &&
          resp.get("ok").as_bool()) {
        p.acked_seq = static_cast<uint64_t>(resp.get("seq").as_int());
      }
    }
    if (p.acked_seq >= batch.last_seq) ++acks;
  }
  return acks;
}

bool Replication::CommitQuorum(std::string* error) {
  Store::BatchBytes batch;
  if (!store_->PendingBatchBytes(&batch) || !enabled()) {
    // Nothing to replicate (or single-node mode): the plain covering
    // fsync, byte-for-byte the ISSUE 8 path.
    return store_->CommitGroup(error);
  }
  if (role_ != Role::kLeader) {
    store_->AbortBatch();
    if (error) *error = "not leader (stepped down with a batch open)";
    return false;
  }
  ++shipped_batches_;
  // Crash window: nothing shipped, nothing locally durable — the whole
  // batch is legitimately lost with the process (all replies were held).
  MaybeCrashAtPoint("repl.pre-ship");
  const double t0 = NowMs();
  const int needed = quorum() - 1;  // our own covering fsync is the +1
  int acks = ShipRound(batch, kShipTimeoutMs);
  while (acks < needed && role_ == Role::kLeader &&
         NowMs() - t0 < opts_.quorum_timeout_ms) {
    // Quorum-degraded stall: clients see their acks held (and time out
    // under their own deadline budget) while we retry the ship — the
    // honest behavior, since releasing early would acknowledge a batch
    // a minority holds.
    usleep(20 * 1000);
    acks = ShipRound(batch, kShipTimeoutMs);
  }
  // Crash window: followers may hold the batch durably, we do not, and
  // no reply was released — applied-never-acked on survivors is legal.
  MaybeCrashAtPoint("repl.post-ship-pre-quorum");
  if (acks < needed || role_ != Role::kLeader) {
    ++quorum_failures_;
    store_->AbortBatch();
    char buf[160];
    snprintf(buf, sizeof(buf),
             "quorum not reached: %d/%d follower acks (+1 self, need %d "
             "of %zu) within %d ms — batch rolled back",
             acks, static_cast<int>(peers_.size()), quorum(),
             peers_.size() + 1, opts_.quorum_timeout_ms);
    if (error) *error = buf;
    if (role_ == Role::kLeader) {
      // A leader that cannot reach a majority must not keep serving:
      // step down and let the majority side elect.
      StepDown(buf, term_);
    }
    return false;
  }
  if (!store_->CommitGroup(error)) {
    // Local disk failed AFTER a majority of followers landed the batch:
    // CommitGroup already rolled our memory back; our log is now behind
    // the followers', and the next leader (or our own next append's
    // needSnapshot reply) reconciles via resync.
    ++quorum_failures_;
    return false;
  }
  commit_seq_ = batch.last_seq;
  last_quorum_ok_ms_ = NowMs();
  ++quorum_commits_;
  // Crash window: quorum-durable everywhere but no reply released — the
  // mutation MUST survive failover (the harness's acked⇒survives proof
  // targets the release that follows this return).
  MaybeCrashAtPoint("repl.post-quorum-pre-release");
  return true;
}

Json Replication::HandleAppend(const Json& req) {
  Json resp = Json::Object();
  const int64_t t = req.get("term").as_int();
  // ack-after-quorum: term-check — a stale leader's append is rejected
  // before a single byte can land or apply (the fencing that makes a
  // deposed leader harmless).
  if (t < term_ || (t == term_ && role_ == Role::kLeader)) {
    ++stale_rejections_;
    resp["ok"] = false;
    resp["staleTerm"] = true;
    resp["term"] = term_;
    return resp;
  }
  if (t > term_) {
    const bool was_leader = role_ == Role::kLeader;
    term_ = t;
    voted_for_.clear();
    PersistState();
    if (was_leader) StepDown("append from newer term", t);
  }
  role_ = Role::kFollower;
  leader_ = req.get("leader").as_string();
  last_contact_ms_ = NowMs();
  ResetElectionDeadline(false);
  const uint64_t prev =
      static_cast<uint64_t>(req.get("prevSeq").as_int());
  const uint32_t prev_crc =
      static_cast<uint32_t>(req.get("prevCrc").as_int());
  if (prev != store_->WalSeq() ||
      (prev > 0 && prev_crc != store_->WalTipCrc())) {
    // Behind (missed batches), ahead (stranded rolled-back records), or
    // DIVERGED — same seq, different record: a batch a crashed leader
    // shipped us that the new leader's history replaced (the Raft
    // (term,index) check, with the tip record's CRC standing in for
    // the per-entry term). Either way the leader's log is
    // authoritative — ask for a reseed.
    resp["ok"] = false;
    resp["needSnapshot"] = true;
    resp["seq"] = static_cast<int64_t>(store_->WalSeq());
    resp["term"] = term_;
    return resp;
  }
  const std::string& data = req.get("data").as_string();
  if (!data.empty()) {
    std::string err;
    if (!store_->AppendReplicatedLog(data, &err)) {
      resp["ok"] = false;
      resp["error"] = err;
      resp["term"] = term_;
      return resp;
    }
  }
  // ack-after-quorum: apply — only the prefix the leader reports
  // committed becomes visible to this follower's reads and watch
  // fan-out; the durable-but-uncommitted suffix stays buffered.
  store_->ApplyReplicatedUpTo(
      static_cast<uint64_t>(req.get("commitSeq").as_int()));
  resp["ok"] = true;
  resp["seq"] = static_cast<int64_t>(store_->WalSeq());
  resp["term"] = term_;
  return resp;
}

Json Replication::HandleSnapshot(const Json& req) {
  Json resp = Json::Object();
  const int64_t t = req.get("term").as_int();
  // Same fencing as the append path: a stale leader cannot reseed us.
  if (t < term_ || (t == term_ && role_ == Role::kLeader)) {
    ++stale_rejections_;
    resp["ok"] = false;
    resp["staleTerm"] = true;
    resp["term"] = term_;
    return resp;
  }
  if (t > term_) {
    const bool was_leader = role_ == Role::kLeader;
    term_ = t;
    voted_for_.clear();
    PersistState();
    if (was_leader) StepDown("snapshot from newer term", t);
  }
  role_ = Role::kFollower;
  leader_ = req.get("leader").as_string();
  last_contact_ms_ = NowMs();
  ResetElectionDeadline(false);
  std::string err;
  if (!store_->InstallReplica(req.get("snapshot").as_string(),
                              req.get("wal").as_string(), &err)) {
    resp["ok"] = false;
    resp["error"] = err;
    resp["term"] = term_;
    return resp;
  }
  resp["ok"] = true;
  resp["seq"] = static_cast<int64_t>(store_->WalSeq());
  resp["term"] = term_;
  return resp;
}

Json Replication::HandleVote(const Json& req) {
  Json resp = Json::Object();
  resp["ok"] = true;
  const int64_t t = req.get("term").as_int();
  const std::string& cand = req.get("candidate").as_string();
  const uint64_t cand_seq =
      static_cast<uint64_t>(req.get("lastSeq").as_int());
  bool granted = false;
  if (t >= term_) {
    // Lease protection: a replica that still hears from its leader (or
    // IS a leader that recently reached quorum) refuses to depose it —
    // a partitioned-then-healed replica with a bumped term cannot
    // disrupt a live majority.
    const double now = NowMs();
    const bool lease_fresh =
        role_ == Role::kLeader
            ? now - last_quorum_ok_ms_ < opts_.lease_ms
            : !leader_.empty() &&
                  now - last_contact_ms_ < opts_.lease_ms;
    if (!(lease_fresh && cand != leader_)) {
      if (t > term_) {
        const bool was_leader = role_ == Role::kLeader;
        term_ = t;
        voted_for_.clear();
        PersistState();
        if (was_leader) StepDown("vote request from newer term", t);
      }
      // The election restriction: never elect a shorter log than our
      // own — this is what makes acked (quorum-durable) batches survive
      // failover, since any majority intersects the batch's quorum. An
      // EQUAL-length log whose tip record differs from ours (divergence
      // a dead leader left behind) is refused too: without per-entry
      // terms we cannot tell whose tip is the committed one, and
      // refusing is the safe direction (a live leader reseeds the
      // diverged replica on first contact; a wrong grant could elect
      // the stranded record over the acked one).
      const uint64_t cand_crc =
          static_cast<uint64_t>(req.get("lastCrc").as_int());
      const bool up_to_date =
          cand_seq > store_->WalSeq() ||
          (cand_seq == store_->WalSeq() &&
           (store_->WalSeq() == 0 || cand_crc == store_->WalTipCrc()));
      if (up_to_date && (voted_for_.empty() || voted_for_ == cand)) {
        voted_for_ = cand;
        PersistState();
        granted = true;
        // Granting resets our own fuse: give the candidate a chance to
        // win before we campaign against it.
        ResetElectionDeadline(false);
      }
    }
  }
  resp["granted"] = granted;
  resp["term"] = term_;
  return resp;
}

void Replication::RunElection() {
  ++elections_;
  term_ += 1;
  voted_for_ = opts_.self;
  PersistState();
  Json req = Json::Object();
  req["op"] = "repl.vote";
  req["term"] = term_;
  req["candidate"] = opts_.self;
  req["lastSeq"] = static_cast<int64_t>(store_->WalSeq());
  req["lastCrc"] = static_cast<int64_t>(store_->WalTipCrc());
  int votes = 1;  // our own
  for (auto& p : peers_) {
    Json resp;
    if (!PeerRequest(p, req, &resp, kVoteTimeoutMs)) continue;
    const int64_t peer_term = resp.get("term").as_int();
    if (peer_term > term_) {
      StepDown("outvoted by newer term", peer_term);
      return;
    }
    if (resp.get("granted").as_bool()) ++votes;
  }
  if (votes >= quorum()) {
    BecomeLeader();
    SendHeartbeats();  // announce immediately; fences older leaders
  } else {
    // Lost. During bootstrap (no leader ever heard — peers likely just
    // not up yet) retry on the short fuse so the fresh cluster forms as
    // soon as a quorum answers; once a leader has existed, back off a
    // full jittered lease so a live majority isn't churned.
    ResetElectionDeadline(/*short_fuse=*/leader_.empty());
  }
}

void Replication::SendHeartbeats() {
  last_heartbeat_ms_ = NowMs();
  ++heartbeats_sent_;
  const int hb_timeout = std::max(50, std::min(opts_.lease_ms / 3, 250));
  int responses = 0;
  for (auto& p : peers_) {
    if (role_ != Role::kLeader) break;
    Json req = Json::Object();
    req["op"] = "repl.append";
    req["term"] = term_;
    req["leader"] = opts_.self;
    req["prevSeq"] = static_cast<int64_t>(store_->WalSeq());
    req["prevCrc"] = static_cast<int64_t>(store_->WalTipCrc());
    req["commitSeq"] = static_cast<int64_t>(commit_seq_);
    req["data"] = "";
    Json resp;
    if (!PeerRequest(p, req, &resp, hb_timeout)) continue;
    if (resp.get("staleTerm").as_bool()) {
      StepDown("stale term reported by " + p.sock,
               resp.get("term").as_int());
      break;
    }
    ++responses;
    if (resp.get("ok").as_bool()) {
      p.acked_seq = static_cast<uint64_t>(resp.get("seq").as_int());
    } else if (resp.get("needSnapshot").as_bool()) {
      // Heartbeats double as the catch-up probe: a follower that
      // rejoined behind (or diverged) reseeds without waiting for the
      // next mutation.
      ShipSnapshotTo(p, kSnapshotTimeoutMs);
    }
  }
  if (role_ == Role::kLeader && responses + 1 >= quorum()) {
    last_quorum_ok_ms_ = NowMs();
  }
}

void Replication::Tick() {
  if (!enabled()) return;
  const double now = NowMs();
  if (role_ == Role::kLeader) {
    if (now - last_heartbeat_ms_ >= opts_.lease_ms / 3.0) {
      SendHeartbeats();
    }
    // The leader's own lease: a partitioned leader that has not heard a
    // majority for a whole lease steps down rather than keep serving
    // reads/watches from arbitrarily stale state while the majority
    // side elects — "cannot reach a majority must not serve" applies to
    // the read path too, not just mutations.
    if (role_ == Role::kLeader &&
        NowMs() - last_quorum_ok_ms_ >= opts_.lease_ms) {
      StepDown("leader lease expired: no majority contact for a full "
               "lease", term_);
    }
  } else if (now >= election_deadline_ms_) {
    // The leader lease expired with no append/heartbeat: campaign.
    RunElection();
  }
}

Json Replication::StateJson() const {
  Json out = Json::Object();
  out["role"] = role_ == Role::kLeader ? "leader" : "follower";
  out["term"] = term_;
  out["leader"] = leader_;
  out["self"] = opts_.self;
  out["quorum"] = quorum();
  out["replicas"] = static_cast<int64_t>(peers_.size() + 1);
  out["leaseMs"] = opts_.lease_ms;
  const uint64_t seq = store_->WalSeq();
  out["seq"] = static_cast<int64_t>(seq);
  out["appliedSeq"] = static_cast<int64_t>(store_->AppliedSeq());
  out["commitSeq"] = static_cast<int64_t>(commit_seq_);
  // Follower-side lag: records durable here but not yet committed by
  // the leader's word (bounded by one heartbeat interval).
  out["lagRecords"] =
      role_ == Role::kLeader
          ? static_cast<int64_t>(0)
          : static_cast<int64_t>(store_->UnappliedRecords());
  Json followers = Json::Array();
  int64_t max_lag = 0;
  for (const auto& p : peers_) {
    Json f = Json::Object();
    f["sock"] = p.sock;
    f["ackedSeq"] = static_cast<int64_t>(p.acked_seq);
    const int64_t lag = role_ == Role::kLeader && seq >= p.acked_seq
                            ? static_cast<int64_t>(seq - p.acked_seq)
                            : 0;
    f["lagRecords"] = lag;
    f["reachable"] = p.reachable;
    followers.push_back(f);
    if (lag > max_lag) max_lag = lag;
  }
  out["followers"] = followers;
  if (role_ == Role::kLeader) out["lagRecords"] = max_lag;
  out["shippedBatches"] = shipped_batches_;
  out["quorumCommits"] = quorum_commits_;
  out["quorumFailures"] = quorum_failures_;
  out["snapshotsShipped"] = snapshots_shipped_;
  out["elections"] = elections_;
  out["staleRejections"] = stale_rejections_;
  out["heartbeatsSent"] = heartbeats_sent_;
  return out;
}

}  // namespace tpk
