#include "server.h"

#include "admission.h"

#include "events.h"

#include <fcntl.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstring>

#include "util.h"

namespace tpk {

namespace {

// µs on the steady clock — the trace ring's timeline (Chrome trace
// wants monotonic µs, not wall time).
double SteadyMicros() {
  return std::chrono::duration<double, std::micro>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

Server::Server(Store* store, Scheduler* scheduler, JaxJobController* jaxjob,
               std::string socket_path, std::string workdir,
               ExperimentController* tune, PipelineRunController* pipelines,
               ServeController* serve, Replication* repl)
    : store_(store),
      scheduler_(scheduler),
      jaxjob_(jaxjob),
      tune_(tune),
      pipelines_(pipelines),
      serve_(serve),
      repl_(repl),
      socket_path_(std::move(socket_path)),
      workdir_(std::move(workdir)) {}

Server::~Server() { Stop(); }

bool Server::Start(std::string* error) {
  listen_fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
  if (listen_fd_ < 0) {
    if (error) *error = strerror(errno);
    return false;
  }
  unlink(socket_path_.c_str());
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, socket_path_.c_str(), sizeof(addr.sun_path) - 1);
  // Backlog sized for a connection burst (the ctrlbench K-client ramp):
  // the accept loop drains it in one pass, but the kernel queue must
  // hold the burst until that pass runs.
  if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(listen_fd_, 128) < 0) {
    if (error) *error = strerror(errno);
    close(listen_fd_);
    listen_fd_ = -1;
    return false;
  }
  // Non-blocking listener: the accept loop below drains to EAGAIN, and
  // a connection that vanishes between poll and accept must not wedge
  // the event loop.
  fcntl(listen_fd_, F_SETFL, fcntl(listen_fd_, F_GETFL, 0) | O_NONBLOCK);
  return true;
}

void Server::Stop() {
  for (auto& c : clients_) close(c.fd);
  clients_.clear();
  if (listen_fd_ >= 0) {
    close(listen_fd_);
    listen_fd_ = -1;
    unlink(socket_path_.c_str());
  }
}

Json Server::Dispatch(const Json& req) {
  Json resp = Json::Object();
  const std::string op = req.get("op").as_string();
  const std::string kind = req.get("kind").as_string();
  const std::string name = req.get("name").as_string();

  // Replication verbs (ISSUE 11): served on any replica, any role —
  // the handlers do their own term fencing. A single-node server
  // (--peers unset) does not expose them at all.
  if (repl_ && repl_->enabled() && op.compare(0, 5, "repl.") == 0) {
    if (op == "repl.append") return repl_->HandleAppend(req);
    if (op == "repl.snapshot") return repl_->HandleSnapshot(req);
    if (op == "repl.vote") return repl_->HandleVote(req);
    resp["ok"] = false;
    resp["error"] = "unknown op: " + op;
    return resp;
  }
  // Followers serve reads and watches at their applied seq; mutations
  // redirect to the leader (the Python client follows `redirect` and
  // retries under its deadline budget).
  if (repl_ && repl_->enabled() && !repl_->IsLeader() &&
      (op == "create" || op == "update_spec" || op == "update_status" ||
       op == "delete" || op == "event")) {
    resp["ok"] = false;
    resp["notLeader"] = true;
    resp["redirect"] = repl_->leader();
    resp["error"] = "not leader (role=follower, term " +
                    std::to_string(repl_->term()) + "); leader: " +
                    (repl_->leader().empty() ? "<unknown — election "
                                               "pending>"
                                             : repl_->leader());
    return resp;
  }

  auto fill = [&](const Store::Result& r) {
    resp["ok"] = r.ok;
    if (!r.ok) {
      resp["error"] = r.error;
    } else {
      resp["resource"] = Store::ToJson(r.resource);
    }
  };

  if (op == "ping") {
    resp["ok"] = true;
    resp["pong"] = true;
  } else if (op == "create") {
    Json spec = req.get("spec");
    if (kind != "Profile") {
      // PodDefaults-equivalent (admission.h): the namespace's Profile
      // may carry per-kind partial specs that fill missing fields
      // before validation — so a bad default fails loudly here.
      auto prof = store_->Get("Profile", SpecNamespace(spec));
      if (prof && prof->spec.get("defaults").is_object()) {
        spec = MergeNamespaceDefaults(
            spec, prof->spec.get("defaults").get(kind));
      }
    }
    std::string veto = ValidateSpec(kind, spec);
    if (!veto.empty()) {
      resp["ok"] = false;
      resp["error"] = "invalid " + kind + " spec: " + veto;
    } else {
      fill(store_->Create(kind, name, spec));
    }
  } else if (op == "get") {
    auto r = store_->Get(kind, name);
    resp["ok"] = r.has_value();
    if (r) {
      resp["resource"] = Store::ToJson(*r);
    } else {
      resp["error"] = "not found: " + kind + "/" + name;
    }
  } else if (op == "list") {
    resp["ok"] = true;
    Json items = Json::Array();
    for (const auto& r : store_->List(kind)) {
      items.push_back(Store::ToJson(r));
    }
    resp["items"] = items;
  } else if (op == "update_spec") {
    std::string veto = ValidateSpec(kind, req.get("spec"));
    if (!veto.empty()) {
      resp["ok"] = false;
      resp["error"] = "invalid " + kind + " spec: " + veto;
    } else {
      fill(store_->UpdateSpec(kind, name, req.get("spec"),
                              req.get("expected_version").is_number()
                                  ? req.get("expected_version").as_int()
                                  : -1));
    }
  } else if (op == "update_status") {
    fill(store_->UpdateStatus(kind, name, req.get("status"),
                              req.get("expected_version").is_number()
                                  ? req.get("expected_version").as_int()
                                  : -1));
  } else if (op == "delete") {
    fill(store_->Delete(kind, name));
  } else if (op == "metrics") {
    resp["ok"] = true;
    Json m = jaxjob_ ? jaxjob_->metrics().ToJson() : Json::Object();
    if (tune_) m["tune"] = tune_->metrics().ToJson();
    if (pipelines_) m["pipelines"] = pipelines_->metrics().ToJson();
    if (serve_) m["serve"] = serve_->metrics().ToJson();
    resp["metrics"] = m;
  } else if (op == "stateinfo") {
    // Durability health: WAL replay stats, compaction counters, fsync
    // mode — the operator's view of whether state survives a crash.
    // Under replication the payload grows replication{role, term, seq,
    // quorum, followers[], lagRecords, ...}.
    resp["ok"] = true;
    Json info = store_->StateInfo();
    if (repl_ && repl_->enabled()) info["replication"] = repl_->StateJson();
    resp["stateinfo"] = info;
  } else if (op == "watch.poll") {
    // Poll-based informer (ISSUE 11): committed, coalesced events with
    // resourceVersion > `since`, served from the store's delivery ring
    // — on followers too, at their applied seq, which is how watcher
    // fan-out scales horizontally. resync=true means the cursor
    // predates the ring: re-list, then resume from the returned
    // resourceVersion.
    resp["ok"] = true;
    Json w = store_->WatchSince(req.get("since").as_int(0), kind);
    resp["events"] = w.get("events");
    resp["resourceVersion"] = w.get("resourceVersion");
    resp["resync"] = w.get("resync");
  } else if (op == "events") {
    // Per-job structured event history (events.h): ordered events +
    // conditions from the resource status — `tpukit events <job>`.
    // Status rides the WAL, so the history survives restarts.
    const std::string k = kind.empty() ? "JAXJob" : kind;
    auto r = store_->Get(k, name);
    if (!r) {
      resp["ok"] = false;
      resp["error"] = "not found: " + k + "/" + name;
    } else {
      resp["ok"] = true;
      resp["events"] = r->status.get("events").is_array()
                           ? r->status.get("events")
                           : Json::Array();
      resp["conditions"] = r->status.get("conditions").is_array()
                               ? r->status.get("conditions")
                               : Json::Array();
      resp["phase"] = r->status.get("phase").as_string();
    }
  } else if (op == "event") {
    // Worker-posted event (the trainer's CheckpointSaved path): append
    // one event to the job's history through the normal status write —
    // WAL-persisted like every controller-recorded event.
    const std::string k = kind.empty() ? "JAXJob" : kind;
    auto r = store_->Get(k, name);
    if (!r) {
      resp["ok"] = false;
      resp["error"] = "not found: " + k + "/" + name;
    } else {
      std::string type = req.get("type").as_string();
      if (type != "Warning") type = "Normal";
      Json status = AppendStatusEvent(
          r->status, type, req.get("reason").as_string(),
          req.get("message").as_string(), NowWall());
      if (status.dump() == r->status.dump()) {
        // Exact-duplicate event (AppendStatusEvent's dedup no-op): a
        // worker retry loop must not bump resourceVersion / append WAL
        // records / fire watches for history that didn't change.
        resp["ok"] = true;
        resp["resource"] = Store::ToJson(*r);
      } else {
        fill(store_->UpdateStatus(k, name, status));
      }
    }
  } else if (op == "trace") {
    // The control plane's span ring as Chrome trace-event JSON —
    // `tpukit trace` (the /debug/trace analog for this process).
    resp["ok"] = true;
    resp["trace"] = TraceJson();
  } else if (op == "slices") {
    resp["ok"] = true;
    Json arr = Json::Array();
    for (const auto& s : scheduler_->Slices()) {
      Json j = Json::Object();
      j["name"] = s.name;
      j["capacity"] = s.capacity;
      j["used"] = s.used;
      arr.push_back(j);
    }
    resp["slices"] = arr;
  } else if (op == "logs") {
    // Tail a worker's log file. The name becomes a path component, so it
    // must pass the same validation Create enforces (no '/', no '..').
    if (!Store::ValidName(name)) {
      resp["ok"] = false;
      resp["error"] = "invalid name: " + name;
      return resp;
    }
    int replica = static_cast<int>(req.get("replica").as_int(0));
    int64_t max_bytes = req.get("max_bytes").as_int(65536);
    std::string path = workdir_ + "/" + name + "/worker-" +
                       std::to_string(replica) +
                       (req.get("stderr").as_bool(false) ? ".err" : ".log");
    FILE* f = fopen(path.c_str(), "r");
    if (!f) {
      resp["ok"] = false;
      resp["error"] = "no log at " + path;
    } else {
      fseek(f, 0, SEEK_END);
      long size = ftell(f);
      long start = size > max_bytes ? size - max_bytes : 0;
      fseek(f, start, SEEK_SET);
      std::string content(size - start, '\0');
      size_t got = fread(content.data(), 1, content.size(), f);
      content.resize(got);
      fclose(f);
      resp["ok"] = true;
      resp["path"] = path;
      resp["content"] = content;
      // Followers track absolute file offsets: `size` is the total file
      // length, `offset` where `content` starts within it.
      resp["size"] = static_cast<int64_t>(size);
      resp["offset"] = static_cast<int64_t>(start);
    }
  } else {
    resp["ok"] = false;
    resp["error"] = "unknown op: " + op;
  }
  return resp;
}

void Server::RecordSpan(const std::string& name, const std::string& trace,
                        double ts_us, double dur_us) {
  // Both strings are wire-controlled; the ring RETAINS them past the
  // request (unlike the line buffer), so bound them or a hostile client
  // could park gigabytes here (the Python side bounds ids to 128 too).
  constexpr size_t kMaxStr = 128;
  trace_ring_.push_back({name.substr(0, kMaxStr), trace.substr(0, kMaxStr),
                         ts_us, dur_us});
  while (trace_ring_.size() > kTraceRingCap) trace_ring_.pop_front();
}

Json Server::TraceJson() const {
  Json events = Json::Array();
  for (const auto& sp : trace_ring_) {
    Json ev = Json::Object();
    ev["name"] = sp.name;
    ev["cat"] = "tpk";
    ev["ph"] = "X";
    ev["ts"] = sp.ts_us;
    ev["dur"] = sp.dur_us;
    ev["pid"] = static_cast<int64_t>(getpid());
    ev["tid"] = "controlplane";
    Json args = Json::Object();
    args["trace_id"] = sp.trace;
    ev["args"] = args;
    events.push_back(ev);
  }
  Json doc = Json::Object();
  doc["traceEvents"] = events;
  doc["displayTimeUnit"] = "ms";
  return doc;
}

void Server::HandleLine(Client& c, const std::string& line) {
  Json resp;
  std::string span_name = "controlplane.bad_request";
  std::string trace;
  const double t0 = SteadyMicros();
  const bool group = store_->group_commit() > 0;
  try {
    Json req = Json::parse(line);
    span_name = "controlplane." + req.get("op").as_string();
    trace = req.get("trace").as_string();
    resp = Dispatch(req);
  } catch (const std::exception& e) {
    resp = Json::Object();
    resp["ok"] = false;
    resp["error"] = std::string("bad request: ") + e.what();
  }
  // Every dispatched request leaves one span in the ring (the `trace`
  // verb included — its own handling is part of the timeline too).
  RecordSpan(span_name, trace, t0, SteadyMicros() - t0);
  std::string out = resp.dump();
  out += '\n';
  // Ack-after-durable: a reply that acknowledges buffered WAL records
  // is staged until the pass's covering fsync; anything queued behind
  // one on the same connection stages too (reply order is the
  // protocol). And not just the mutations' own replies: ANY reply
  // computed while batch records are buffered observed applied-but-
  // uncommitted state — released early, a failed commit would leak a
  // dirty read (e.g. a get on another connection claiming a rolled-back
  // create exists) that the per-record path can never produce. Such
  // replies ride the commit and become the batch error on failure.
  // Read-only traffic while no batch is open skips the wait.
  const bool sees_batch = group && store_->PendingGroupRecords() > 0;
  if (sees_batch || !c.staged.empty()) {
    c.staged.emplace_back(std::move(out), sees_batch);
  } else {
    c.out_buf += out;
  }
}

void Server::CommitAndRelease() {
  std::string err;
  bool ok;
  if (repl_ && repl_->enabled()) {
    // ack-after-quorum: quorum-wait — ship the batch's framed bytes to
    // the followers and hold every staged reply until a majority of
    // the replica set (our own covering fsync included) has it durable.
    // Quorum failure rolls the whole batch back (nothing was promised)
    // and the release below turns the staged replies into errors.
    // Routed through CommitQuorum for EVERY role: a leader deposed
    // mid-pass (a newer-term vote/append dispatched after this pass's
    // mutations) must have its open batch ABORTED with error replies —
    // the plain CommitGroup would land it on this minority replica
    // alone and ack writes the new leader's history will erase.
    ok = repl_->CommitQuorum(&err);
  } else {
    // ack-after-durable: commit — the single covering fsync for every
    // mutation this pass applied (single-node mode).
    ok = store_->CommitGroup(&err);
  }
  std::string failure;
  if (!ok) {
    Json e = Json::Object();
    e["ok"] = false;
    e["error"] = "group commit failed, mutation rolled back: " + err;
    failure = e.dump();
    failure += '\n';
  }
  // ack-after-durable: release — staged replies reach the socket only
  // after the commit; batch-dependent replies (acks and reads over the
  // now-rolled-back state) answer with the error (nothing durable was
  // promised, and a success reply here would be a dirty read).
  for (auto& c : clients_) {
    for (auto& [reply, sees_batch] : c.staged) {
      c.out_buf += (ok || !sees_batch) ? reply : failure;
    }
    c.staged.clear();
  }
}

int Server::PollOnce(int timeout_ms) {
  if (listen_fd_ < 0) return 0;
  std::vector<pollfd> fds;
  fds.push_back({listen_fd_, POLLIN, 0});
  for (auto& c : clients_) {
    short events = POLLIN;
    if (!c.out_buf.empty()) events |= POLLOUT;
    fds.push_back({c.fd, events, 0});
  }
  int n = poll(fds.data(), fds.size(), timeout_ms);
  if (n <= 0) return 0;

  const int group_max = store_->group_commit();
  int served = 0;
  if (fds[0].revents & POLLIN) {
    // Drain the accept queue: a burst of K new clients joins in ONE
    // pass instead of paying one poll cycle each.
    int fd;
    while ((fd = accept(listen_fd_, nullptr, nullptr)) >= 0) {
      // Non-blocking: a stalled client must never block the event loop
      // (this thread also runs reconciles and exit reaping).
      fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
      clients_.push_back(Client{fd, "", "", {}, false});
    }
  }
  // Phase 1 — read + dispatch: drain every readable connection so all
  // requests already in flight join this pass's batch. Replies stage
  // (group mode) or append to out_buf (per-record mode); nothing is
  // written back yet.
  for (size_t i = 1; i < fds.size(); ++i) {
    Client& c = clients_[i - 1];
    if (!(fds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
    while (true) {
      char buf[4096];
      ssize_t got = read(c.fd, buf, sizeof(buf));
      if (got > 0) {
        c.in_buf.append(buf, got);
        continue;
      }
      if (got < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
      c.dead = true;  // EOF or hard error; handle what already arrived
      break;
    }
    size_t nl;
    while ((nl = c.in_buf.find('\n')) != std::string::npos) {
      std::string line = c.in_buf.substr(0, nl);
      c.in_buf.erase(0, nl + 1);
      if (!line.empty()) {
        HandleLine(c, line);
        ++served;
      }
      if (group_max > 0 && store_->PendingGroupRecords() >= group_max) {
        // Batch cap: land what we have mid-pass so one huge burst can't
        // grow the commit (and every waiter's ack latency) unboundedly.
        CommitAndRelease();
      }
    }
  }
  // Phase 2 — the pass's covering commit, then the held acks.
  if (group_max > 0) CommitAndRelease();
  // Phase 3 — opportunistic non-blocking writes (fds are O_NONBLOCK):
  // this pass's responses go out now instead of waiting a poll cycle.
  for (auto& c : clients_) {
    if (c.dead || c.out_buf.empty()) continue;
    ssize_t sent = write(c.fd, c.out_buf.data(), c.out_buf.size());
    if (sent > 0) {
      c.out_buf.erase(0, sent);
    } else if (sent < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
      c.dead = true;
      continue;
    }
    // Cap pending output: a client that never reads gets disconnected
    // rather than growing the buffer unboundedly.
    if (c.out_buf.size() > (8u << 20)) c.dead = true;
  }
  // Sweep dead clients.
  for (auto it = clients_.begin(); it != clients_.end();) {
    if (it->dead) {
      close(it->fd);
      it = clients_.erase(it);
    } else {
      ++it;
    }
  }
  return served;
}

}  // namespace tpk
