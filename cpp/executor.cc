#include "executor.h"

#include <fcntl.h>
#include <signal.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>

extern char** environ;

namespace tpk {

int LocalExecutor::Spawn(const LaunchSpec& spec, std::string* error) {
  std::vector<char*> argv;
  for (const auto& a : spec.argv) argv.push_back(const_cast<char*>(a.c_str()));
  argv.push_back(nullptr);

  // Build env: inherited + overrides.
  std::vector<std::string> env_storage;
  for (char** e = environ; *e; ++e) {
    const char* eq = strchr(*e, '=');
    if (!eq) continue;
    std::string key(*e, eq - *e);
    if (spec.env.count(key)) continue;  // overridden below
    env_storage.emplace_back(*e);
  }
  for (const auto& [k, v] : spec.env) env_storage.push_back(k + "=" + v);
  std::vector<char*> envp;
  for (auto& s : env_storage) envp.push_back(const_cast<char*>(s.c_str()));
  envp.push_back(nullptr);

  pid_t pid = fork();
  if (pid < 0) {
    if (error) *error = std::string("fork: ") + strerror(errno);
    return -1;
  }
  if (pid == 0) {
    // Child. Redirect stdout/stderr to log files if requested.
    if (!spec.stdout_path.empty()) {
      int fd = open(spec.stdout_path.c_str(), O_WRONLY | O_CREAT | O_APPEND,
                    0644);
      if (fd >= 0) { dup2(fd, 1); close(fd); }
    }
    if (!spec.stderr_path.empty()) {
      int fd = open(spec.stderr_path.c_str(), O_WRONLY | O_CREAT | O_APPEND,
                    0644);
      if (fd >= 0) { dup2(fd, 2); close(fd); }
    }
    // Own process group so Kill can signal the whole subtree.
    setpgid(0, 0);
    execvpe(argv[0], argv.data(), envp.data());  // PATH lookup (bare "python3")
    fprintf(stderr, "execvpe %s failed: %s\n", argv[0], strerror(errno));
    _exit(127);
  }
  setpgid(pid, pid);  // also from parent: avoids a race with exec
  return pid;
}

bool LocalExecutor::LaunchGang(const std::vector<LaunchSpec>& specs,
                               std::string* error) {
  std::vector<std::pair<std::string, int>> started;
  for (const auto& spec : specs) {
    int pid = Spawn(spec, error);
    if (pid < 0) {
      // Gang atomicity: kill everything already started.
      for (auto& [id, p] : started) kill(-p, SIGKILL);
      return false;
    }
    started.emplace_back(spec.id, pid);
  }
  std::lock_guard<std::mutex> lock(mu_);
  for (auto& [id, pid] : started) {
    // Purge stale pid mappings from a previous gang of the same job — a
    // not-yet-reaped old worker must not clobber the new one's status when
    // its exit finally arrives.
    for (auto it = by_pid_.begin(); it != by_pid_.end();) {
      it = (it->second == id) ? by_pid_.erase(it) : std::next(it);
    }
    procs_[id] = {ProcessStatus::Phase::kRunning, -1, pid};
    by_pid_[pid] = id;
  }
  return true;
}

void LocalExecutor::Kill(const std::string& id) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = procs_.find(id);
  if (it == procs_.end() ||
      it->second.phase != ProcessStatus::Phase::kRunning) {
    return;
  }
  kill(-it->second.pid, SIGKILL);  // whole process group
}

ProcessStatus LocalExecutor::Status(const std::string& id) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = procs_.find(id);
  return it == procs_.end() ? ProcessStatus{} : it->second;
}

std::vector<std::string> LocalExecutor::Poll() {
  std::vector<std::string> changed;
  while (true) {
    int status = 0;
    pid_t pid = waitpid(-1, &status, WNOHANG);
    if (pid <= 0) break;
    std::lock_guard<std::mutex> lock(mu_);
    auto it = by_pid_.find(pid);
    if (it == by_pid_.end()) continue;
    const std::string& id = it->second;
    // Belt-and-braces vs stale exits: only record if this pid is still the
    // one attributed to the id (LaunchGang purges, but be defensive).
    if (procs_.count(id) && procs_[id].pid == pid) {
      int code = WIFEXITED(status)    ? WEXITSTATUS(status)
                 : WIFSIGNALED(status) ? 128 + WTERMSIG(status)
                                       : 1;
      procs_[id] = {code == 0 ? ProcessStatus::Phase::kSucceeded
                              : ProcessStatus::Phase::kFailed,
                    code, pid};
      changed.push_back(id);
    }
    by_pid_.erase(it);
  }
  return changed;
}

}  // namespace tpk
