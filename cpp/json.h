// Minimal JSON value + parser + serializer (header-only, no deps).
//
// The control plane speaks newline-delimited JSON over a unix socket and
// persists a JSONL WAL; resources carry arbitrary user spec documents, so we
// need a dynamic value type. ~300 lines covers the subset we use: null/bool/
// number/string/array/object, UTF-8 passthrough, \uXXXX escapes (BMP).
//
// Reference parity note: upstream Kubeflow's controllers lean on Kubernetes'
// apimachinery for (un)structured objects; this plus store.h is our
// equivalent kernel surface (SURVEY.md §1 L0/L1).

#pragma once

#include <cmath>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace tpk {

class Json;
using JsonArray = std::vector<Json>;
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { kNull, kBool, kNumber, kString, kArray, kObject };

  Json() : type_(Type::kNull) {}
  Json(std::nullptr_t) : type_(Type::kNull) {}
  Json(bool b) : type_(Type::kBool), bool_(b) {}
  Json(int v) : type_(Type::kNumber), num_(v) {}
  Json(int64_t v) : type_(Type::kNumber), num_(static_cast<double>(v)) {}
  Json(double v) : type_(Type::kNumber), num_(v) {}
  Json(const char* s) : type_(Type::kString), str_(s) {}
  Json(std::string s) : type_(Type::kString), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::kArray), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::kObject), obj_(std::move(o)) {}

  static Json Object() { return Json(JsonObject{}); }
  static Json Array() { return Json(JsonArray{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::kNull; }
  bool is_bool() const { return type_ == Type::kBool; }
  bool is_number() const { return type_ == Type::kNumber; }
  bool is_string() const { return type_ == Type::kString; }
  bool is_array() const { return type_ == Type::kArray; }
  bool is_object() const { return type_ == Type::kObject; }

  bool as_bool(bool dflt = false) const {
    return is_bool() ? bool_ : dflt;
  }
  double as_number(double dflt = 0) const {
    return is_number() ? num_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    return is_number() ? static_cast<int64_t>(num_) : dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return is_string() ? str_ : empty;
  }

  // Object access. get() returns null Json for missing keys.
  const Json& get(const std::string& key) const {
    static const Json null_json;
    if (!is_object()) return null_json;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_json : it->second;
  }
  Json& operator[](const std::string& key) {
    if (type_ == Type::kNull) { type_ = Type::kObject; }
    if (!is_object()) throw std::runtime_error("json: not an object");
    return obj_[key];
  }
  bool has(const std::string& key) const {
    return is_object() && obj_.count(key) > 0;
  }
  void erase(const std::string& key) { if (is_object()) obj_.erase(key); }
  const JsonObject& items() const {
    static const JsonObject empty;
    return is_object() ? obj_ : empty;
  }

  // Array access.
  const JsonArray& elements() const {
    static const JsonArray empty;
    return is_array() ? arr_ : empty;
  }
  void push_back(Json v) {
    if (type_ == Type::kNull) { type_ = Type::kArray; }
    if (!is_array()) throw std::runtime_error("json: not an array");
    arr_.push_back(std::move(v));
  }
  size_t size() const {
    return is_array() ? arr_.size() : (is_object() ? obj_.size() : 0);
  }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size()) throw std::runtime_error("json: trailing data");
    return v;
  }

 private:
  void write(std::ostringstream& os) const {
    switch (type_) {
      case Type::kNull: os << "null"; break;
      case Type::kBool: os << (bool_ ? "true" : "false"); break;
      case Type::kNumber: {
        if (std::isfinite(num_) && num_ == std::floor(num_) &&
            std::fabs(num_) < 1e15) {
          os << static_cast<int64_t>(num_);
        } else if (std::isfinite(num_)) {
          char buf[32];
          snprintf(buf, sizeof(buf), "%.17g", num_);
          os << buf;
        } else {
          os << "null";  // JSON has no Inf/NaN
        }
        break;
      }
      case Type::kString: write_string(os, str_); break;
      case Type::kArray: {
        os << '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) os << ',';
          arr_[i].write(os);
        }
        os << ']';
        break;
      }
      case Type::kObject: {
        os << '{';
        bool first = true;
        for (const auto& [k, v] : obj_) {
          if (!first) os << ',';
          first = false;
          write_string(os, k);
          os << ':';
          v.write(os);
        }
        os << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  static void skip_ws(const std::string& t, size_t& p) {
    while (p < t.size() && (t[p] == ' ' || t[p] == '\t' || t[p] == '\n' ||
                            t[p] == '\r')) {
      ++p;
    }
  }

  static Json parse_value(const std::string& t, size_t& p) {
    skip_ws(t, p);
    if (p >= t.size()) throw std::runtime_error("json: unexpected end");
    char c = t[p];
    if (c == '{') return parse_object(t, p);
    if (c == '[') return parse_array(t, p);
    if (c == '"') return Json(parse_string(t, p));
    if (c == 't') { expect(t, p, "true"); return Json(true); }
    if (c == 'f') { expect(t, p, "false"); return Json(false); }
    if (c == 'n') { expect(t, p, "null"); return Json(); }
    return parse_number(t, p);
  }

  static void expect(const std::string& t, size_t& p, const char* lit) {
    size_t n = strlen(lit);
    if (t.compare(p, n, lit) != 0) throw std::runtime_error("json: bad literal");
    p += n;
  }

  static Json parse_object(const std::string& t, size_t& p) {
    Json obj = Json::Object();
    ++p;  // '{'
    skip_ws(t, p);
    if (p < t.size() && t[p] == '}') { ++p; return obj; }
    while (true) {
      skip_ws(t, p);
      if (p >= t.size() || t[p] != '"')
        throw std::runtime_error("json: expected key");
      std::string key = parse_string(t, p);
      skip_ws(t, p);
      if (p >= t.size() || t[p] != ':')
        throw std::runtime_error("json: expected ':'");
      ++p;
      obj[key] = parse_value(t, p);
      skip_ws(t, p);
      if (p < t.size() && t[p] == ',') { ++p; continue; }
      if (p < t.size() && t[p] == '}') { ++p; break; }
      throw std::runtime_error("json: expected ',' or '}'");
    }
    return obj;
  }

  static Json parse_array(const std::string& t, size_t& p) {
    Json arr = Json::Array();
    ++p;  // '['
    skip_ws(t, p);
    if (p < t.size() && t[p] == ']') { ++p; return arr; }
    while (true) {
      arr.push_back(parse_value(t, p));
      skip_ws(t, p);
      if (p < t.size() && t[p] == ',') { ++p; continue; }
      if (p < t.size() && t[p] == ']') { ++p; break; }
      throw std::runtime_error("json: expected ',' or ']'");
    }
    return arr;
  }

  static std::string parse_string(const std::string& t, size_t& p) {
    ++p;  // '"'
    std::string out;
    while (p < t.size() && t[p] != '"') {
      char c = t[p];
      if (c == '\\') {
        ++p;
        if (p >= t.size()) break;
        char e = t[p];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (p + 4 >= t.size())
              throw std::runtime_error("json: bad \\u escape");
            unsigned code = std::stoul(t.substr(p + 1, 4), nullptr, 16);
            p += 4;
            // Encode BMP codepoint as UTF-8 (surrogate pairs unsupported;
            // they round-trip as two 3-byte sequences, acceptable here).
            if (code < 0x80) {
              out += static_cast<char>(code);
            } else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: throw std::runtime_error("json: bad escape");
        }
        ++p;
      } else {
        out += c;
        ++p;
      }
    }
    if (p >= t.size()) throw std::runtime_error("json: unterminated string");
    ++p;  // closing '"'
    return out;
  }

  static Json parse_number(const std::string& t, size_t& p) {
    size_t start = p;
    if (p < t.size() && (t[p] == '-' || t[p] == '+')) ++p;
    while (p < t.size() &&
           (isdigit(static_cast<unsigned char>(t[p])) || t[p] == '.' ||
            t[p] == 'e' || t[p] == 'E' || t[p] == '-' || t[p] == '+')) {
      ++p;
    }
    if (p == start) throw std::runtime_error("json: bad number");
    return Json(std::stod(t.substr(start, p - start)));
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

}  // namespace tpk
