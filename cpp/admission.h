// Admission validation — the validating-webhook layer (SURVEY.md §2.1
// "Webhooks": upstream each job kind has a validating admission webhook
// rejecting malformed specs before they reach the controllers; here the
// API server validates on create/update_spec so users get a clean error at
// submit time instead of a controller-side Failed phase later).

#pragma once

#include <cmath>
#include <set>
#include <string>

#include "json.h"
#include "spec_schema.gen.h"

namespace tpk {

// --- Namespace defaults (the PodDefaults-webhook analog) -------------------
//
// Upstream, the PodDefaults mutating webhook injects env/volumes/
// tolerations into pods by label selector (SURVEY.md §2.5). Here the
// namespace object itself (Profile — its name IS the namespace) may carry
// `defaults: {<Kind>: {<partial spec>}}`; at CREATE admission the API
// server deep-merges the kind's defaults into the submitted spec, filling
// ONLY missing fields (the user's spec always wins, recursively for
// objects). The merged spec is what gets stored — validation then runs on
// the final object, so a bad default fails loudly at submit.
//
// Null semantics (ADVICE r5): an EXPLICITLY-present JSON `null` in the
// user's spec is a user-wins OPT-OUT of that key's namespace default —
// the key is STRIPPED before validation (the stored spec simply omits
// it), never silently refilled with the default. `{"lora": null}` under
// a namespace that defaults `lora` therefore means "no LoRA", exactly as
// it would in a namespace without defaults. Nulls on keys the namespace
// does not default are left untouched: top-level validators already
// treat null as absent, and schema-typed runtime fields keep rejecting
// null unless their type admits it — so opting out is scoped to the
// defaulting machinery, not a general null-erasure pass.

inline std::string SpecNamespace(const Json& spec) {
  // Mirror of jaxjob.cc NamespaceOf / controlplane.client namespace_of.
  const std::string ns = spec.get("namespace").as_string();
  return ns.empty() ? "default" : ns;
}

inline Json MergeNamespaceDefaults(const Json& spec, const Json& defaults,
                                   bool top = true) {
  if (!defaults.is_object()) return spec;
  if (spec.is_null()) return defaults;
  if (!spec.is_object()) return spec;  // scalar user value always wins
  Json out = spec;
  for (const auto& [k, dv] : defaults.items()) {
    if (top && k == "namespace") {
      // A default must never MOVE the resource into another tenancy —
      // the Profile consulted was chosen by the pre-merge namespace.
      continue;
    }
    if (out.has(k) && out.get(k).is_null()) {
      // Explicit null opts OUT of this key's default (see the design
      // note above): strip it so validation sees the key as absent.
      out.erase(k);
    } else if (!out.has(k)) {
      out[k] = dv;
    } else if (out.get(k).is_object() && dv.is_object()) {
      out[k] = MergeNamespaceDefaults(out.get(k), dv, /*top=*/false);
    }
  }
  return out;
}

// The generated runtime-field table (kubeflow_tpu/utils/spec_schema.py —
// ONE schema, consumed here and by TrainJobSpec; SURVEY.md §5.6 drift
// guard). Parsed once.
inline const Json& SpecSchemaRuntime() {
  static const Json schema = Json::parse(kSpecSchemaJson);
  return schema.get("JAXJob.runtime");
}

// The serving twin: InferenceService `model.generative` knob table.
inline const Json& SpecSchemaGenerative() {
  static const Json schema = Json::parse(kSpecSchemaJson);
  return schema.get("InferenceService.model.generative");
}

// A JSON number that is a representable integer: bounds first (casting
// a double beyond int64 range is UB), then the truncation guard (2.5
// must not pass as 2 while the worker receives 2.5 and fails later).
inline bool IsIntegralNumber(const Json& v) {
  if (!v.is_number()) return false;
  const double num = v.as_number();
  return num >= -9.2e18 && num <= 9.2e18 && num == std::floor(num);
}

// Validates one schema-typed field value against its table entry;
// "" = ok. `scope` prefixes the field in error messages ("runtime." /
// "model.generative.").
inline std::string ValidateRuntimeField(
    const std::string& field, const Json& v, const Json& entry,
    const std::string& scope = "runtime.") {
  const std::string type = entry.get("type").as_string();
  const std::string where = scope + field;
  if (type == "int") {
    if (!v.is_number()) return where + " must be a number";
    if (!IsIntegralNumber(v)) {
      return where + " must be an integer";
    }
    if (entry.has("min") && v.as_int() < entry.get("min").as_int()) {
      return where + " must be >= " +
             std::to_string(entry.get("min").as_int());
    }
    return "";
  }
  if (type == "number") {
    if (!v.is_number()) return where + " must be a number";
    if (entry.has("min") && v.as_number() < entry.get("min").as_number()) {
      return where + " must be >= " + entry.get("min").dump();
    }
    return "";
  }
  if (type == "string" || type == "string_or_null") {
    if (type == "string_or_null" && v.is_null()) return "";
    if (!v.is_string()) return where + " must be a string";
    if (entry.has("enum")) {
      std::string allowed;
      for (const auto& e : entry.get("enum").elements()) {
        if (e.as_string() == v.as_string()) return "";
        if (!allowed.empty()) allowed += " | ";
        allowed += e.as_string();
      }
      return where + " must be " + allowed;
    }
    return "";
  }
  if (type == "bool_or_string") {
    if (!v.is_bool() && !v.is_string()) {
      return where + " must be a bool or a string";
    }
    return "";
  }
  if (type == "object") {
    if (!v.is_object()) return where + " must be an object";
    return "";
  }
  if (type == "int_or_null") {
    if (v.is_null()) return "";
    if (!IsIntegralNumber(v)) {
      return where + " must be an integer or null";
    }
    return "";
  }
  if (type == "int_array") {
    // Non-empty by rule: an empty bucket list passes the type check but
    // crashes the engine at model load (buckets[-1]) — the crash-loop
    // this table exists to catch at submit.
    if (!v.is_array() || v.size() == 0) {
      return where + " must be a non-empty array of integers";
    }
    for (const auto& e : v.elements()) {
      if (!IsIntegralNumber(e)) {
        return where + " must contain only integers";
      }
      if (entry.has("min") && e.as_int() < entry.get("min").as_int()) {
        return where + " elements must be >= " +
               std::to_string(entry.get("min").as_int());
      }
    }
    return "";
  }
  return where + ": unknown schema type " + type;  // schema bug — loud
}

// Returns "" when valid, else a human-readable rejection reason.
inline std::string ValidateSpec(const std::string& kind, const Json& spec) {
  if (!spec.is_object()) return "spec must be an object";

  auto positive_int = [&](const char* field, int64_t dflt,
                          int64_t min) -> std::string {
    const Json& v = spec.get(field);
    if (v.is_null()) {
      return dflt >= min ? ""
                         : std::string(field) + " is required";
    }
    if (!v.is_number()) return std::string(field) + " must be a number";
    if (v.as_int() < min) {
      return std::string(field) + " must be >= " + std::to_string(min);
    }
    return "";
  };

  if (kind == "JAXJob") {
    std::string err;
    if (!(err = positive_int("replicas", 1, 1)).empty()) return err;
    if (!(err = positive_int("devices_per_proc", 1, 1)).empty()) return err;
    if (!(err = positive_int("backoff_limit", 3, 0)).empty()) return err;
    if (!(err = positive_int("num_slices", 1, 1)).empty()) return err;
    const std::string policy = spec.get("restart_policy").as_string();
    if (!policy.empty() && policy != "Never" && policy != "OnFailure" &&
        policy != "ExitCode") {
      return "restart_policy must be Never | OnFailure | ExitCode";
    }
    if (spec.get("command").is_array() &&
        spec.get("command").size() == 0) {
      return "command must be a non-empty argv array";
    }
    const Json& rt = spec.get("runtime");
    if (!rt.is_null()) {
      if (!rt.is_object()) return "runtime must be an object";
      // Schema-driven validation (generated table, spec_schema.gen.h):
      // every present field must exist in the schema and satisfy its
      // type/min/enum — unknown fields (typo'd knobs) and mismatched
      // JSON types are rejected at submit, not discovered as a worker
      // crash. Type-strict by construction: as_string()/as_int() default
      // fallbacks never decide admission.
      const Json& table = SpecSchemaRuntime();
      for (const auto& [field, value] : rt.items()) {
        if (!table.has(field)) {
          return "runtime." + field + " is not a JAXJob runtime field "
                 "(see spec_schema.json)";
        }
        std::string ferr = ValidateRuntimeField(field, value,
                                                table.get(field));
        if (!ferr.empty()) return ferr;
      }
      // Cross-field semantics stay hand-coded (the schema is per-field).
      int64_t accum = rt.get("accum_steps").as_int(1);
      int64_t batch = rt.get("batch_size").as_int(-1);
      if (batch >= 0 && accum >= 1 && batch % accum) {
        return "runtime.batch_size must be divisible by accum_steps";
      }
      // grad_accum (canonical) vs accum_steps (legacy alias): both set
      // and disagreeing would train a different global-batch split than
      // one of the two knobs promises — refuse at submit, mirroring the
      // Python Trainer.
      int64_t gaccum = rt.get("grad_accum").as_int(0);
      if (gaccum >= 1) {
        if (batch >= 0 && batch % gaccum) {
          return "runtime.batch_size must be divisible by grad_accum";
        }
        if (rt.has("accum_steps") && accum > 1 && accum != gaccum) {
          return "runtime.grad_accum and accum_steps disagree — set one";
        }
      }
      // FSDP master-state sharding: the shorthand fills mesh.fsdp, so a
      // mesh that names a DIFFERENT fsdp degree is a contradiction the
      // worker would refuse anyway — fail it at submit. param_dtype only
      // configures the fsdp runtime's gathered compute copies.
      int64_t fsdp = rt.get("fsdp").as_int(0);
      if (fsdp >= 1) {
        const Json& mesh_fsdp = rt.get("mesh").get("fsdp");
        if (mesh_fsdp.is_number() && IsIntegralNumber(mesh_fsdp) &&
            mesh_fsdp.as_int() != fsdp) {
          return "runtime.fsdp conflicts with runtime.mesh.fsdp — set one";
        }
        const Json& pipe = rt.get("mesh").get("pipe");
        if (pipe.is_number() && pipe.as_number() > 1) {
          return "runtime.fsdp doesn't compose with pipeline "
                 "parallelism (mesh.pipe > 1)";
        }
        if (rt.get("lora").is_object() && rt.get("lora").size() > 0) {
          return "runtime.fsdp doesn't compose with lora (the "
                 "adapter-only optimizer state is the memory win there)";
        }
      }
      // (param_dtype without fsdp is refused by the worker's Trainer —
      // admission's job here is typos/types, and the schema enum
      // already pins the dtype spelling.)
      // runtime.lora contents (the schema types it as an object; the
      // knob semantics live here so a typo'd rank fails at submit,
      // mirroring the Python Trainer's validation).
      const Json& lora = rt.get("lora");
      if (lora.is_object() && lora.size() > 0) {
        // ({} = LoRA disabled, matching the Python Trainer's falsy
        // check; contents are validated only when the knob is in use.)
        for (const auto& [k, v] : lora.items()) {
          (void)v;
          if (k != "rank" && k != "alpha" && k != "targets") {
            return "runtime.lora." + k +
                   " is not a lora field (rank, alpha, targets)";
          }
        }
        const Json& rank = lora.get("rank");
        if (!rank.is_number() ||
            rank.as_number() != std::floor(rank.as_number()) ||
            rank.as_number() < 1) {
          return "runtime.lora.rank must be an integer >= 1";
        }
        if (lora.has("alpha") && (!lora.get("alpha").is_number() ||
                                  lora.get("alpha").as_number() <= 0)) {
          return "runtime.lora.alpha must be a number > 0";
        }
        if (lora.has("targets")) {
          const std::string t = lora.get("targets").as_string();
          if (t != "attn" && t != "attn_mlp") {
            return "runtime.lora.targets must be attn | attn_mlp";
          }
        }
        // Pipeline parallelism is switched by mesh.pipe > 1 (the
        // `pipeline` object only tunes it) — check both surfaces.
        // Bounds before any as_int (cast beyond int64 is UB), and no
        // default fallback decides admission: a non-number pipe simply
        // isn't "> 1" here (the mesh itself fails later validation).
        const Json& pipe = rt.get("mesh").get("pipe");
        const bool pipe_gt1 = pipe.is_number() && pipe.as_number() > 1;
        if ((rt.get("pipeline").is_object() &&
             rt.get("pipeline").size() > 0) || pipe_gt1) {
          return "runtime.lora doesn't compose with pipeline "
                 "parallelism (pipeline stages have no adapter path)";
        }
      }
      // (non-object lora is rejected by the schema-driven loop above)
    }
    const Json& elastic = spec.get("elastic");
    if (!elastic.is_null()) {
      if (!elastic.is_object()) return "elastic must be an object";
      // Integral + bounded before any as_int: the cast-beyond-int64 UB
      // guard, same as ValidateRuntimeField.
      auto small_int = [](const Json& v, int64_t lo, int64_t hi) {
        if (!v.is_number()) return false;
        const double num = v.as_number();
        if (num != std::floor(num) || num < static_cast<double>(lo) ||
            num > static_cast<double>(hi)) {
          return false;
        }
        return true;
      };
      int64_t replicas = spec.get("replicas").as_int(1);
      if (elastic.has("min_fsdp")) {
        // fsdp elasticity: the resize unit is the fsdp mesh axis, not
        // the replica count. Field-by-field like the fsdp cross-field
        // checks above, plus the divisibility contract the controller's
        // candidate picker relies on (targets are divisors of max_fsdp,
        // so the master-state sharding plan survives every resize).
        if (elastic.has("min") || elastic.has("max")) {
          return "elastic.min/max and elastic.min_fsdp are mutually "
                 "exclusive (replica vs fsdp elasticity)";
        }
        const Json& rtf = spec.get("runtime").get("fsdp");
        if (!small_int(rtf, 1, 1 << 20)) {
          return "elastic.min_fsdp needs runtime.fsdp >= 1";
        }
        const int64_t fsdp = rtf.as_int();
        int64_t dpp = spec.get("devices_per_proc").as_int(1);
        if (replicas * dpp != fsdp) {
          return "elastic fsdp resize needs runtime.fsdp == replicas * "
                 "devices_per_proc (the fsdp axis spans the gang)";
        }
        if (!small_int(elastic.get("min_fsdp"), 1, fsdp)) {
          return "elastic.min_fsdp must be an integer in "
                 "[1, runtime.fsdp]";
        }
        const int64_t fmin = elastic.get("min_fsdp").as_int();
        int64_t fmax = fsdp;
        if (elastic.has("max_fsdp")) {
          if (!small_int(elastic.get("max_fsdp"), fsdp, 1 << 20)) {
            return "elastic.max_fsdp must be an integer >= runtime.fsdp";
          }
          fmax = elastic.get("max_fsdp").as_int();
          if (fmax % fsdp != 0) {
            return "elastic.max_fsdp must be a multiple of runtime.fsdp "
                   "(resize targets are divisors of max_fsdp and the "
                   "launch shape must be one of them)";
          }
        }
        if (elastic.has("resize_policy")) {
          const std::string& pol =
              elastic.get("resize_policy").as_string();
          if (pol != "auto" && pol != "manual") {
            return "elastic.resize_policy must be auto | manual";
          }
        }
        if (elastic.has("target_fsdp")) {
          if (!small_int(elastic.get("target_fsdp"), fmin, fmax) ||
              fmax % elastic.get("target_fsdp").as_int() != 0) {
            return "elastic.target_fsdp must be a divisor of max_fsdp "
                   "in [min_fsdp, max_fsdp]";
          }
        }
      } else {
        if (elastic.has("max_fsdp") || elastic.has("resize_policy") ||
            elastic.has("target_fsdp")) {
          return "elastic.max_fsdp/resize_policy/target_fsdp need "
                 "elastic.min_fsdp";
        }
        if (!small_int(elastic.get("min"), 1, replicas)) {
          return "elastic.min must be an integer in [1, replicas]";
        }
        int64_t emin = elastic.get("min").as_int();
        if (elastic.has("max") &&
            !small_int(elastic.get("max"), emin, replicas)) {
          return "elastic.max must be an integer in [min, replicas]";
        }
      }
      if (elastic.has("heartbeat_timeout_s") &&
          (!elastic.get("heartbeat_timeout_s").is_number() ||
           elastic.get("heartbeat_timeout_s").as_number() <= 0)) {
        return "elastic.heartbeat_timeout_s must be a number > 0";
      }
      if (elastic.has("upsize_cooldown_s") &&
          (!elastic.get("upsize_cooldown_s").is_number() ||
           elastic.get("upsize_cooldown_s").as_number() < 0)) {
        return "elastic.upsize_cooldown_s must be a number >= 0";
      }
    }
    const Json& fault = spec.get("fault");
    if (!fault.is_null()) {
      if (!fault.is_object()) return "fault must be an object";
      int64_t replicas = spec.get("replicas").as_int(1);
      int64_t proc = fault.get("proc").as_int(0);
      if (proc < 0 || proc >= replicas) {
        return "fault.proc out of range [0, replicas)";
      }
      int64_t fstep = fault.get("step").as_int(-1);
      if (fstep < 0) {
        return "fault.step must be a step index >= 0";
      }
      // The fault must be reachable, or the chaos test silently tests
      // nothing.
      int64_t steps = spec.get("runtime").get("steps").as_int(-1);
      if (steps >= 0 && fstep >= steps) {
        return "fault.step beyond runtime.steps — it would never fire";
      }
      // Only signals that actually terminate the worker: SIGSTOP would
      // hang the gang forever, SIGCHLD/SIGWINCH are ignored no-ops.
      int64_t sig = fault.get("signal").as_int(9);
      if (sig != 1 && sig != 2 && sig != 3 && sig != 6 && sig != 9 &&
          sig != 15) {
        return "fault.signal must be a terminating signal "
               "(1|2|3|6|9|15)";
      }
    }
    return "";
  }

  if (kind == "Profile") {
    // Multi-tenancy stub (SURVEY.md §2.5/§7.4): a Profile is a namespace
    // with a device quota; its name IS the namespace.
    if (!spec.get("max_devices").is_null() &&
        spec.get("max_devices").as_int(-1) < 0) {
      return "max_devices must be >= 0";
    }
    const Json& defs = spec.get("defaults");
    if (!defs.is_null()) {
      if (!defs.is_object()) {
        return "defaults must be an object of {Kind: partial spec}";
      }
      for (const auto& [k, v] : defs.items()) {
        if (!v.is_object()) {
          return "defaults." + k + " must be an object (a partial " + k +
                 " spec)";
        }
        if (k == "Profile") {
          return "defaults.Profile is not allowed (namespaces don't "
                 "default namespaces)";
        }
        if (v.has("namespace")) {
          return "defaults." + k + ".namespace is not allowed (a "
                 "default cannot move resources between namespaces)";
        }
      }
    }
    return "";
  }

  if (kind == "Experiment") {
    if (!spec.get("parameters").is_array() ||
        spec.get("parameters").size() == 0) {
      return "parameters must be a non-empty array";
    }
    for (const auto& p : spec.get("parameters").elements()) {
      if (p.get("name").as_string().empty()) {
        return "every parameter needs a name";
      }
      const std::string t = p.get("type").as_string();
      if (t == "categorical") {
        if (!p.get("values").is_array() || p.get("values").size() == 0) {
          return "categorical parameter " + p.get("name").as_string() +
                 " needs values";
        }
      } else if (t.empty() || t == "double" || t == "int") {
        if (!p.get("min").is_number() || !p.get("max").is_number()) {
          return "parameter " + p.get("name").as_string() +
                 " needs numeric min/max";
        }
      } else {
        return "parameter " + p.get("name").as_string() +
               ": unknown type " + t;
      }
    }
    if (spec.get("objective").get("metric").as_string().empty()) {
      return "objective.metric is required";
    }
    if (!spec.get("trial_template").is_object()) {
      return "trial_template (a JAXJob spec) is required";
    }
    std::string err;
    if (!(err = positive_int("max_trials", 10, 1)).empty()) return err;
    if (!(err = positive_int("parallel_trials", 1, 1)).empty()) return err;
    return ValidateSpec("JAXJob", spec.get("trial_template")).empty()
               ? ""
               : "trial_template: " +
                     ValidateSpec("JAXJob", spec.get("trial_template"));
  }

  if (kind == "PipelineRun" || kind == "ScheduledPipelineRun") {
    if (spec.get("pipeline").as_string().empty() &&
        !spec.get("pipeline_spec").is_object()) {
      return "spec needs `pipeline` (name) or inline `pipeline_spec`";
    }
    if (kind == "ScheduledPipelineRun") {
      const Json& sched = spec.get("schedule");
      if (!sched.is_object()) return "schedule is required";
      bool has_interval = sched.get("interval_seconds").is_number();
      bool has_cron = !sched.get("cron").as_string().empty();
      if (has_interval == has_cron) {
        return "schedule needs exactly one of interval_seconds | cron";
      }
      if (has_interval && sched.get("interval_seconds").as_number() <= 0) {
        return "schedule.interval_seconds must be > 0";
      }
    }
    return "";
  }

  if (kind == "InferenceService") {
    const Json& model = spec.get("model");
    if (!model.is_object()) return "model is required";
    if (model.get("model_dir").as_string().empty() &&
        model.get("storage_uri").as_string().empty()) {
      return "model needs model_dir or storage_uri";
    }
    std::string err;
    if (!(err = positive_int("devices_per_replica", 1, 1)).empty()) {
      return err;
    }
    int64_t min_r = spec.get("min_replicas").as_int(0);
    int64_t max_r = spec.get("max_replicas").as_int(min_r);
    if (min_r < 0) return "min_replicas must be >= 0";
    if (max_r < min_r) return "max_replicas must be >= min_replicas";
    if (spec.get("replicas").is_number() &&
        spec.get("replicas").as_int() < 0) {
      return "replicas must be >= 0";
    }
    if (spec.get("scale_to_zero_after_s").is_number() &&
        spec.get("scale_to_zero_after_s").as_number() < 0) {
      return "scale_to_zero_after_s must be >= 0";
    }
    const Json& logger = spec.get("logger");
    if (logger.is_object()) {
      const std::string mode = logger.get("mode").as_string();
      if (!mode.empty() && mode != "metadata" && mode != "all") {
        return "logger.mode must be metadata | all";
      }
    }
    const Json& canary = spec.get("canary");
    if (!canary.is_null()) {
      if (!canary.is_object()) return "canary must be an object";
      if (canary.get("model_dir").as_string().empty()) {
        return "canary needs model_dir";
      }
      int64_t pct = canary.get("traffic_percent").as_int(10);
      if (pct < 0 || pct > 100) {
        return "canary.traffic_percent must be in [0, 100]";
      }
    }
    // Generative serving knobs (model.generative — GenerationEngine /
    // text2text config): schema-driven like runtime, from the SAME
    // generated table (spec_schema.gen.h "InferenceService.model.
    // generative"), so a typo'd serving knob — or kv_block_size/
    // kv_blocks against a binary that predates the paged KV cache —
    // fails at submit, not as a replica crash-loop. Known limit: the
    // table is the UNION of the causal-LM and text2text runtimes
    // (which runtime applies is decided by the checkpoint's
    // architectures at load time — admission cannot see it), so a
    // cross-runtime knob (e.g. in_buckets on a Llama service) passes
    // here and still fails at model load. Typos and type errors are
    // what this catches.
    const Json& gen = model.get("generative");
    if (!gen.is_null()) {
      if (!gen.is_object()) return "model.generative must be an object";
      const Json& gtable = SpecSchemaGenerative();
      for (const auto& [field, value] : gen.items()) {
        if (!gtable.has(field)) {
          return "model.generative." + field + " is not a generative "
                 "serving knob (see spec_schema.json)";
        }
        std::string gerr = ValidateRuntimeField(
            field, value, gtable.get(field), "model.generative.");
        if (!gerr.empty()) return gerr;
      }
      // Cross-field composition rules (ISSUE 18): the engine refusals
      // that are expressible from the spec alone move here, so an
      // invalid composition rejects at submit instead of crash-looping
      // the replica at load. Checkpoint-derived refusals (sliding-
      // window draft past its window, draft/target vocab mismatch,
      // rolling-window × paged) stay load-time — admission cannot see
      // the checkpoint.
      const int64_t kv_bs = gen.get("kv_block_size").as_int(0);
      const Json& role = gen.get("role");
      if (role.is_string() && role.as_string() != "unified" &&
          kv_bs == 0) {
        return "model.generative.role=" + role.as_string() +
               " needs kv_block_size > 0 (KV blocks are the "
               "prefill->decode wire unit)";
      }
      if (gen.get("kv_blocks").as_int(0) > 0 && kv_bs == 0) {
        return "model.generative.kv_blocks needs kv_block_size > 0 "
               "(a block count without a block size is meaningless)";
      }
      if (gen.get("kv_host_tier_blocks").as_int(0) > 0 && kv_bs == 0) {
        return "model.generative.kv_host_tier_blocks needs "
               "kv_block_size > 0 (the host tier spills whole blocks)";
      }
      // Quantized KV blocks (ISSUE 19). Enum validity ("none" | "int8"
      // | "fp8") is schema-table-driven above; the composition rules
      // live here: the scale pool is a paged structure (no flat-cache
      // quantization), and a speculative rejection rewind would
      // re-quantize committed rows, so kv_quant x draft is refused —
      // the engine raises the same refusals at load, this just moves
      // them to submit.
      const Json& kvq = gen.get("kv_quant");
      const bool quantized = kvq.is_string() && kvq.as_string() != "none";
      if (quantized && kv_bs == 0) {
        return "model.generative.kv_quant=" + kvq.as_string() +
               " needs kv_block_size > 0 (the quantized scale pool "
               "is paged; the flat cache has no quantized form)";
      }
      if (quantized && gen.get("draft").is_object()) {
        return "model.generative.kv_quant=" + kvq.as_string() +
               " does not compose with draft (speculative decoding): "
               "a rejection rewind would re-quantize committed KV "
               "rows — drop one of the two";
      }
      const Json& draft = gen.get("draft");
      if (draft.is_object()) {
        static const std::set<std::string> kDraftKeys = {
            "checkpoint", "gamma", "model_overrides"};
        for (const auto& [dk, dv] : draft.items()) {
          (void)dv;
          if (!kDraftKeys.count(dk)) {
            return "model.generative.draft." + dk +
                   " is not a draft knob (checkpoint | gamma | "
                   "model_overrides)";
          }
        }
        if (draft.get("checkpoint").as_string().empty()) {
          return "model.generative.draft needs a checkpoint (HF dir "
                 "of the draft model)";
        }
        const Json& gamma = draft.get("gamma");
        if (!gamma.is_null() &&
            (!IsIntegralNumber(gamma) || gamma.as_int() < 1)) {
          return "model.generative.draft.gamma must be an integer >= 1";
        }
        const Json& ovr = draft.get("model_overrides");
        if (!ovr.is_null() && !ovr.is_object()) {
          return "model.generative.draft.model_overrides must be an "
                 "object";
        }
      }
    }
    // Tensor-parallel serving mesh: {"tensor": 8} etc. The axis product
    // is the device count one replica's SPMD program spans — it must be
    // covered by devices_per_replica or the scheduler would launch a
    // mesh bigger than its allocation.
    const Json& mesh = model.get("mesh");
    if (!mesh.is_null()) {
      if (!mesh.is_object()) return "model.mesh must be an object";
      static const std::set<std::string> kAxes = {"data", "fsdp", "pipe",
                                                  "tensor", "seq", "expert"};
      int64_t prod = 1;
      for (const auto& [axis, n] : mesh.items()) {
        if (!kAxes.count(axis)) {
          return "model.mesh: unknown axis " + axis;
        }
        if (!n.is_number() ||
            n.as_number() != static_cast<double>(n.as_int(0)) ||
            n.as_int(0) < 1) {
          return "model.mesh." + axis + " must be an integer >= 1";
        }
        // Overflow-safe product: divide-first so prod can never exceed
        // 2^40 (far past any real device count) — a wrapped-negative
        // product would sail under the budget check below.
        if (n.as_int() > (int64_t{1} << 40) / prod) {
          return "model.mesh device product is implausibly large";
        }
        prod *= n.as_int();
      }
      if (prod > spec.get("devices_per_replica").as_int(1)) {
        return "model.mesh needs " + std::to_string(prod) +
               " devices but devices_per_replica is " +
               std::to_string(spec.get("devices_per_replica").as_int(1));
      }
    }
    return "";
  }

  if (kind == "TrainedModel") {
    if (spec.get("inference_service").as_string().empty()) {
      return "inference_service (the parent InferenceService) is required";
    }
    const Json& model = spec.get("model");
    if (!model.is_object()) return "model is required";
    const std::string mname = model.get("name").as_string();
    if (mname.empty()) return "model.name is required";
    for (char c : mname) {  // the name becomes a URL path segment
      if (!isalnum(static_cast<unsigned char>(c)) && c != '-' &&
          c != '_' && c != '.') {
        return "model.name must be [A-Za-z0-9._-] (it names a repository "
               "URL path)";
      }
    }
    if (model.get("model_dir").as_string().empty()) {
      return "model.model_dir is required";
    }
    return "";
  }

  // Unknown kinds (Pipeline IR, Trial internals, user resources) pass —
  // the store is schema-free by design, like CRDs without a webhook.
  return "";
}

}  // namespace tpk
