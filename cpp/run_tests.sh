#!/usr/bin/env bash
# C++ test matrix — the `go test && go test -race` analog (SURVEY.md §5.2):
# plain, ASan+UBSan, and TSan builds must all be green. Run from repo root:
#   bash cpp/run_tests.sh
set -euo pipefail
cd "$(dirname "$0")/.."

for variant in "" address thread; do
  case "$variant" in
    address) dir=build-asan ;;
    thread)  dir=build-tsan ;;
    *)       dir=build ;;
  esac
  echo "=== variant: ${variant:-plain} ($dir) ==="
  cmake -S cpp -B "$dir" ${variant:+-DTPK_SANITIZE=$variant} >/dev/null
  cmake --build "$dir" -j"$(nproc)" >/dev/null
  ctest --test-dir "$dir" --output-on-failure
done
echo "all sanitizer variants green"
