// API server: newline-delimited JSON over a unix domain socket.
//
// The kube-apiserver surface of the rebuild (SURVEY.md §1 L0, §7.1 item 4):
// clients (Python SDK, tpukit CLI) connect to <socket>, send one JSON
// request per line, receive one JSON response per line. Ops mirror the
// resource verbs (create/get/list/update_spec/delete) plus control-plane
// introspection (metrics/slices/logs/ping). Auth is a stub (filesystem
// permissions on the socket), matching the descope note in SURVEY.md §7.4.

#pragma once

#include <functional>
#include <map>
#include <string>
#include <vector>

#include "jaxjob.h"
#include "json.h"
#include "pipelines.h"
#include "scheduler.h"
#include "serve.h"
#include "store.h"
#include "tune.h"

namespace tpk {

class Server {
 public:
  Server(Store* store, Scheduler* scheduler, JaxJobController* jaxjob,
         std::string socket_path, std::string workdir,
         ExperimentController* tune = nullptr,
         PipelineRunController* pipelines = nullptr,
         ServeController* serve = nullptr);
  ~Server();

  bool Start(std::string* error);

  // One event-loop pass: accept clients, read/dispatch requests, write
  // responses. timeout_ms bounds the poll wait. Returns requests served.
  int PollOnce(int timeout_ms);

  void Stop();

  Json Dispatch(const Json& req);  // public for unit tests

 private:
  struct Client {
    int fd;
    std::string in_buf;
    std::string out_buf;
  };

  void HandleLine(Client& c, const std::string& line);

  Store* store_;
  Scheduler* scheduler_;
  JaxJobController* jaxjob_;
  ExperimentController* tune_;
  PipelineRunController* pipelines_;
  ServeController* serve_;
  std::string socket_path_;
  std::string workdir_;
  int listen_fd_ = -1;
  std::vector<Client> clients_;
};

}  // namespace tpk
