// API server: newline-delimited JSON over a unix domain socket.
//
// The kube-apiserver surface of the rebuild (SURVEY.md §1 L0, §7.1 item 4):
// clients (Python SDK, tpukit CLI) connect to <socket>, send one JSON
// request per line, receive one JSON response per line. Ops mirror the
// resource verbs (create/get/list/update_spec/delete) plus control-plane
// introspection (metrics/slices/logs/ping). Auth is a stub (filesystem
// permissions on the socket), matching the descope note in SURVEY.md §7.4.

#pragma once

#include <deque>
#include <functional>
#include <map>
#include <string>
#include <vector>

#include "jaxjob.h"
#include "json.h"
#include "pipelines.h"
#include "replica.h"
#include "scheduler.h"
#include "serve.h"
#include "store.h"
#include "tune.h"

namespace tpk {

class Server {
 public:
  // `repl` (ISSUE 11) turns the group-commit release gate into the
  // quorum gate: non-null + enabled means mutations redirect to the
  // leader on followers, repl.* verbs are served, and a leader's pass
  // commit ships the batch and waits for majority durability before any
  // staged reply releases (ack-after-quorum). Null/disabled is the
  // single-node ISSUE 8 path, byte-for-byte.
  Server(Store* store, Scheduler* scheduler, JaxJobController* jaxjob,
         std::string socket_path, std::string workdir,
         ExperimentController* tune = nullptr,
         PipelineRunController* pipelines = nullptr,
         ServeController* serve = nullptr,
         Replication* repl = nullptr);
  ~Server();

  bool Start(std::string* error);

  // One event-loop pass: accept clients (drained to EAGAIN), read and
  // dispatch every complete request already in each socket, land the
  // pass's mutations through ONE store group commit (covering fsync),
  // and only then flush the queued replies. timeout_ms bounds the poll
  // wait. Returns requests served.
  //
  // Ack-after-durable: with group commit enabled (store->group_commit()
  // > 0), a reply whose request buffered WAL records is staged and
  // released only after CommitGroup() returns true — so under
  // `--fsync always` an acknowledged mutation is never lost, while all
  // mutations of one pass share one fsync. Every reply computed while
  // batch records are buffered rides the commit (reads included — they
  // observed applied-but-uncommitted state); on commit failure all of
  // them become error replies, so a rolled-back batch leaks neither
  // acks nor dirty reads. Read-only replies while no batch is open
  // skip the wait.
  // With group commit off the per-record path runs exactly as before.
  int PollOnce(int timeout_ms);

  void Stop();

  Json Dispatch(const Json& req);  // public for unit tests

  // One span per dispatched request in a bounded process-local ring
  // (the control plane's half of the end-to-end trace: clients attach
  // their trace id to each request; the `trace` verb exports the ring
  // as Chrome trace-event JSON for chrome://tracing / Perfetto —
  // `tpukit trace`).
  struct TraceSpan {
    std::string name;   // "controlplane.<op>"
    std::string trace;  // caller-attached trace id ("" when absent)
    double ts_us;       // µs since process start (steady clock)
    double dur_us;
  };
  void RecordSpan(const std::string& name, const std::string& trace,
                  double ts_us, double dur_us);
  Json TraceJson() const;  // {"traceEvents": [...]} — the `trace` verb

 private:
  struct Client {
    int fd;
    std::string in_buf;
    std::string out_buf;
    // Replies staged during a group-commit pass: (reply line, whether
    // it depends on the open batch — acks AND reads computed over
    // uncommitted state). Released into out_buf by CommitAndRelease,
    // in dispatch order.
    std::vector<std::pair<std::string, bool>> staged;
    bool dead = false;
  };

  void HandleLine(Client& c, const std::string& line);
  // Lands the pending store batch and releases every staged reply:
  // verbatim on success; batch-dependent replies (acks and reads over
  // uncommitted state) become error replies on failure — the mutations
  // were rolled back, nothing was promised, and nothing dirty leaks.
  void CommitAndRelease();

  Store* store_;
  Scheduler* scheduler_;
  JaxJobController* jaxjob_;
  ExperimentController* tune_;
  PipelineRunController* pipelines_;
  ServeController* serve_;
  Replication* repl_;
  std::string socket_path_;
  std::string workdir_;
  int listen_fd_ = -1;
  std::vector<Client> clients_;
  std::deque<TraceSpan> trace_ring_;
  static constexpr size_t kTraceRingCap = 2048;
};

}  // namespace tpk
