// Pipeline/PipelineRun controllers — the KFP-equivalent orchestration layer
// (SURVEY.md §2.4, §3.5, §7.1 item 8).
//
// Collapses the reference's pipeline stack into control-plane-native form:
//   - api-server IR→Argo compilation (⟨pipelines: backend/src/apiserver⟩):
//     here the compiled IR (tpk-pipeline/v1 JSON from the Python DSL) is
//     stored as a Pipeline resource and executed directly — no Workflow CR
//     intermediary, the controller IS the DAG engine.
//   - per-node driver (⟨pipelines: backend/src/v2/driver⟩): input/DAG
//     resolution happens in Reconcile; each ready task becomes a child
//     JAXJob running the Python launcher.
//   - step cache (⟨pipelines: backend/src/apiserver⟩ cache +
//     ⟨backend/src/v2/driver⟩ cache key): fingerprint = sha256(component
//     spec, resolved params, input artifact digests) looked up in the
//     lineage store before launching.
//   - MLMD lineage (google/ml-metadata, the stack's one C++ component):
//     LineageStore below — append-only JSONL of executions with
//     content-addressed artifact digests (own schema per SURVEY.md §7.4).

#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "json.h"
#include "store.h"

namespace tpk {

// Append-only execution/artifact log with fingerprint lookup (MLMD +
// KFP-cache stand-in). One JSONL record per completed task execution.
class LineageStore {
 public:
  // path empty = in-memory only (unit tests).
  explicit LineageStore(std::string path = "");
  ~LineageStore();

  int Load();  // replays the log; returns records applied

  // Record a completed execution. `outputs` maps name -> {path, digest}.
  void Record(const std::string& fingerprint, const std::string& run,
              const std::string& task, const Json& outputs);

  // Most recent execution with this fingerprint, or null Json.
  Json Lookup(const std::string& fingerprint) const;

  int64_t size() const { return static_cast<int64_t>(by_fp_.size()); }

 private:
  std::string path_;
  FILE* file_ = nullptr;
  std::map<std::string, Json> by_fp_;
};

struct PipelineMetrics {
  int64_t runs_created = 0;
  int64_t runs_succeeded = 0;
  int64_t runs_failed = 0;
  int64_t tasks_launched = 0;
  int64_t cache_hits = 0;

  Json ToJson() const {
    Json j = Json::Object();
    j["runs_created"] = runs_created;
    j["runs_succeeded"] = runs_succeeded;
    j["runs_failed"] = runs_failed;
    j["tasks_launched"] = tasks_launched;
    j["cache_hits"] = cache_hits;
    return j;
  }
};

// ScheduledPipelineRun — the ScheduledWorkflow/recurring-run controller
// (⟨pipelines: backend/src/crd/controller/scheduledworkflow⟩, SURVEY.md
// §2.4): spec {pipeline|pipeline_spec, params, schedule:
// {interval_seconds: N} | {cron: "m h dom mon dow"}, suspend, max_runs}.
// Each firing materializes a PipelineRun named <name>-<n>.
class ScheduleController {
 public:
  explicit ScheduleController(Store* store) : store_(store) {}

  void Tick(double now_s);

  int64_t runs_created() const { return runs_created_; }

  // Does `cron` ("m h dom mon dow"; fields: *, */n, or comma list) match
  // the given UTC time? Exposed for tests.
  static bool CronMatches(const std::string& cron, time_t t,
                          std::string* error = nullptr);

 private:
  Store* store_;
  int64_t runs_created_ = 0;
};

class PipelineRunController {
 public:
  PipelineRunController(Store* store, LineageStore* lineage,
                        std::string workdir,
                        std::string python = "python3");

  void Reconcile(const std::string& name);
  void Tick(double now_s);

  // Watch hook for kDeleted: kills child task jobs of a deleted run.
  void OnDeleted(const Resource& res);

  PipelineMetrics& metrics() { return metrics_; }

  // sha256 over dir contents (sorted relative paths + bytes); exposed for
  // tests. Returns "" if the directory is missing.
  static std::string DirDigest(const std::string& dir);

  // Dependency closure of a task: depends_on + argument producers.
  static std::vector<std::string> TaskDeps(const Json& task);

 private:
  struct RunView {
    Resource res;
    Json ir;       // resolved pipeline IR
    Json params;   // resolved pipeline params
    Json status;
  };

  bool ResolveIR(const Resource& res, RunView* run, std::string* error);
  bool ValidateDag(const Json& tasks, std::string* error) const;
  void LaunchTask(RunView& run, const std::string& tname, const Json& task);
  void CheckRunningTask(RunView& run, const std::string& tname,
                        const Json& task);
  void SetPhase(Json* status, const std::string& phase,
                const std::string& reason, const std::string& message);

  Store* store_;
  LineageStore* lineage_;
  std::string workdir_;
  std::string python_;
  PipelineMetrics metrics_;
  double now_s_ = 0;
};

}  // namespace tpk
