// JSON round-trip and edge-case tests.
#include <cassert>
#include <cstdio>

#include "json.h"

using tpk::Json;

#define CHECK(cond)                                              \
  do {                                                           \
    if (!(cond)) {                                               \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                  \
    }                                                            \
  } while (0)

int main() {
  // Parse basics.
  Json v = Json::parse(R"({"a": 1, "b": [true, null, "x\n"], "c": -2.5})");
  CHECK(v.get("a").as_int() == 1);
  CHECK(v.get("b").elements().size() == 3);
  CHECK(v.get("b").elements()[0].as_bool());
  CHECK(v.get("b").elements()[1].is_null());
  CHECK(v.get("b").elements()[2].as_string() == "x\n");
  CHECK(v.get("c").as_number() == -2.5);
  CHECK(v.get("missing").is_null());

  // Round trip preserves structure.
  Json again = Json::parse(v.dump());
  CHECK(again.dump() == v.dump());

  // Integers stay integral in output.
  Json n(42);
  CHECK(n.dump() == "42");
  Json big(static_cast<int64_t>(1234567890123LL));
  CHECK(big.dump() == "1234567890123");

  // String escapes round-trip.
  Json s(std::string("quote\" slash\\ tab\t nl\n"));
  CHECK(Json::parse(s.dump()).as_string() == s.as_string());

  // \u escape decodes to UTF-8.
  Json u = Json::parse(R"("é")");
  CHECK(u.as_string() == "\xc3\xa9");

  // Nested object building.
  Json obj = Json::Object();
  obj["x"]["y"] = 5;  // auto-vivify
  CHECK(obj.get("x").get("y").as_int() == 5);

  // Errors.
  bool threw = false;
  try { Json::parse("{bad}"); } catch (...) { threw = true; }
  CHECK(threw);
  threw = false;
  try { Json::parse("[1,2") ; } catch (...) { threw = true; }
  CHECK(threw);
  threw = false;
  try { Json::parse("1 2"); } catch (...) { threw = true; }
  CHECK(threw);

  printf("test_json OK\n");
  return 0;
}
