// Replication tests (ISSUE 11): shipped-batch byte parity with the local
// WAL, commit-gated follower apply, abort rollback, term fencing of a
// stale leader, snapshot catch-up after compaction, torn shipped-batch
// tails truncating exactly like local replay, and the vote rules
// (term + log-length + lease). Handler-level — the socket transport is
// exercised by the Python e2e suite against real binaries. Runs under
// the ASan/TSan matrix like every store test.
#include <unistd.h>

#include <cstdio>
#include <fstream>
#include <sstream>
#include <string>

#include "replica.h"
#include "store.h"

using tpk::Json;
using tpk::Replication;
using tpk::Store;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

namespace {

std::string ReadFile(const std::string& path) {
  std::ifstream in(path, std::ios::binary);
  std::ostringstream os;
  os << in.rdbuf();
  return os.str();
}

void Cleanup(const std::string& wal) {
  std::remove(wal.c_str());
  std::remove((wal + ".snap").c_str());
  std::remove((wal + ".replstate").c_str());
}

Replication::Options FollowerOpts(const std::string& wal, int lease_ms) {
  Replication::Options o;
  o.self = "/tmp/tpk_repl_self.sock";
  o.peers = {"/tmp/tpk_repl_peer.sock"};
  o.state_path = wal + ".replstate";
  o.leader_hint = "/tmp/tpk_repl_leader.sock";
  o.lease_ms = lease_ms;
  o.quorum_timeout_ms = 100;
  return o;
}

Json AppendReq(int64_t term, uint64_t prev_seq, uint64_t commit_seq,
               const std::string& data, uint32_t prev_crc = 0,
               const std::string& leader = "/tmp/tpk_repl_leader.sock") {
  Json req = Json::Object();
  req["op"] = "repl.append";
  req["term"] = term;
  req["leader"] = leader;
  req["prevSeq"] = static_cast<int64_t>(prev_seq);
  req["prevCrc"] = static_cast<int64_t>(prev_crc);
  req["commitSeq"] = static_cast<int64_t>(commit_seq);
  req["data"] = data;
  return req;
}

}  // namespace

int main() {
  // Shipped bytes are the local WAL bytes, byte for byte: the leader's
  // open batch (PendingBatchBytes) equals exactly what CommitGroup then
  // appends to the leader's file, and a follower landing those bytes
  // produces a byte-identical WAL file.
  {
    std::string lwal = "/tmp/tpk_repl_leader.jsonl";
    std::string fwal = "/tmp/tpk_repl_follower.jsonl";
    Cleanup(lwal);
    Cleanup(fwal);
    Store leader(lwal);
    leader.SetGroupCommit(64);
    CHECK(leader.Create("Widget", "a", Json::Object()).ok);
    CHECK(leader.UpdateSpec("Widget", "a", Json::Object()).ok);
    CHECK(leader.Create("Widget", "b", Json::Object()).ok);
    Store::BatchBytes batch;
    CHECK(leader.PendingBatchBytes(&batch));
    CHECK(batch.records == 3);
    CHECK(batch.prev_seq == 0 && batch.last_seq == 3);
    const std::string pre = ReadFile(lwal);
    CHECK(leader.CommitGroup());
    CHECK(ReadFile(lwal) == pre + batch.bytes);  // shipped == written

    Store follower(fwal);
    std::string err;
    CHECK(follower.AppendReplicatedLog(batch.bytes, &err));
    CHECK(ReadFile(fwal) == ReadFile(lwal));  // replica WAL byte parity
    CHECK(follower.WalSeq() == 3);
    // Commit-gated apply: durable but invisible until the leader's
    // commitSeq covers it (no dirty follower reads of an abortable
    // batch)...
    CHECK(follower.AppliedSeq() == 0);
    CHECK(follower.UnappliedRecords() == 3);
    CHECK(!follower.Get("Widget", "a").has_value());
    // ...and a partial commitSeq applies exactly the prefix.
    CHECK(follower.ApplyReplicatedUpTo(2) == 2);
    CHECK(follower.Get("Widget", "a").has_value());
    CHECK(!follower.Get("Widget", "b").has_value());
    CHECK(follower.ApplyReplicatedUpTo(3) == 1);
    CHECK(follower.Get("Widget", "b").has_value());
    CHECK(follower.AppliedSeq() == 3);
    // The applied events reach the follower's watch fan-out (coalesced:
    // a's create+update collapse to one ADDED).
    int events = 0;
    follower.Watch("", [&events](const tpk::WatchEvent&) { ++events; });
    CHECK(follower.DrainWatches() == 2);
    CHECK(events == 2);
    Cleanup(lwal);
    Cleanup(fwal);
  }

  // AbortBatch is the quorum-failure rollback: memory restored from
  // pre-images, clocks rewound, queued watch events dropped, and the
  // WAL file never touched — then the store keeps working.
  {
    std::string wal = "/tmp/tpk_repl_abort.jsonl";
    Cleanup(wal);
    Store s(wal);
    s.SetGroupCommit(64);
    CHECK(s.Create("Widget", "keep", Json::Object()).ok);
    CHECK(s.CommitGroup());
    const std::string durable = ReadFile(wal);
    int events = 0;
    s.Watch("", [&events](const tpk::WatchEvent&) { ++events; });
    CHECK(s.DrainWatches() == 1);  // the committed create
    CHECK(s.Create("Widget", "doomed", Json::Object()).ok);
    CHECK(s.UpdateSpec("Widget", "keep", Json::Object()).ok);
    Store::BatchBytes batch;
    CHECK(s.PendingBatchBytes(&batch));
    s.AbortBatch();
    CHECK(ReadFile(wal) == durable);            // disk untouched
    CHECK(!s.Get("Widget", "doomed").has_value());
    CHECK(s.Get("Widget", "keep")->generation == 1);  // spec bump undone
    CHECK(s.DrainWatches() == 0);               // batch events dropped
    CHECK(s.PendingGroupRecords() == 0);
    auto r = s.Create("Widget", "after", Json::Object());
    CHECK(r.ok);
    CHECK(s.CommitGroup());
    Store s2(wal);
    s2.Load();
    CHECK(s2.WalSeq() == 2);  // keep + after; doomed never durable
    CHECK(s2.Get("Widget", "after").has_value());
    CHECK(events == 1);
    Cleanup(wal);
  }

  // Term fencing: a stale leader's append (and snapshot) is rejected
  // before anything lands or applies — the deposed-leader harmlessness
  // the failover harness relies on.
  {
    std::string wal = "/tmp/tpk_repl_fence.jsonl";
    Cleanup(wal);
    Store s(wal);
    Replication repl(&s, FollowerOpts(wal, 50));
    // A term-5 leader establishes itself.
    Json ok = repl.HandleAppend(AppendReq(5, 0, 0, ""));
    CHECK(ok.get("ok").as_bool());
    CHECK(repl.term() == 5);
    // Build one framed record by committing through a scratch leader.
    std::string lwal = "/tmp/tpk_repl_fence_l.jsonl";
    Cleanup(lwal);
    Store leader(lwal);
    leader.SetGroupCommit(64);
    CHECK(leader.Create("Widget", "w", Json::Object()).ok);
    Store::BatchBytes batch;
    CHECK(leader.PendingBatchBytes(&batch));
    CHECK(leader.CommitGroup());
    // The stale (term 3 < 5) leader ships that batch: rejected by term,
    // nothing written, nothing applied.
    Json stale = repl.HandleAppend(AppendReq(3, 0, 1, batch.bytes));
    CHECK(!stale.get("ok").as_bool());
    CHECK(stale.get("staleTerm").as_bool());
    CHECK(stale.get("term").as_int() == 5);
    CHECK(s.WalSeq() == 0);
    CHECK(!s.Get("Widget", "w").has_value());
    Json stale_snap = Json::Object();
    stale_snap["op"] = "repl.snapshot";
    stale_snap["term"] = 3;
    stale_snap["leader"] = "/tmp/tpk_repl_leader.sock";
    stale_snap["commitSeq"] = 1;
    stale_snap["snapshot"] = "";
    stale_snap["wal"] = ReadFile(lwal);
    CHECK(!repl.HandleSnapshot(stale_snap).get("ok").as_bool());
    CHECK(s.WalSeq() == 0);
    // The CURRENT term's leader ships the same batch: accepted.
    Json good = repl.HandleAppend(AppendReq(5, 0, 1, batch.bytes));
    CHECK(good.get("ok").as_bool());
    CHECK(s.Get("Widget", "w").has_value());
    // A mismatched prevSeq (leader ahead — we missed a batch) asks for
    // the snapshot reseed instead of guessing.
    Json gap = repl.HandleAppend(AppendReq(5, 7, 7, batch.bytes));
    CHECK(!gap.get("ok").as_bool());
    CHECK(gap.get("needSnapshot").as_bool());
    CHECK(gap.get("seq").as_int() == 1);
    Cleanup(wal);
    Cleanup(lwal);
  }

  // Divergence detection (the Raft (term,index) check via the tip
  // record's CRC): a follower holding a DIFFERENT record at the same
  // sequence — a batch a crashed leader shipped that the new leader's
  // history replaced — is told to reseed instead of silently extending
  // the stranded record.
  {
    std::string wal = "/tmp/tpk_repl_diverge.jsonl";
    std::string l1 = "/tmp/tpk_repl_diverge_l1.jsonl";
    std::string l2 = "/tmp/tpk_repl_diverge_l2.jsonl";
    Cleanup(wal);
    Cleanup(l1);
    Cleanup(l2);
    // Two histories for seq 1: the stranded one (shipped by the dead
    // leader) and the committed one (the new leader's).
    Store stranded_leader(l1);
    stranded_leader.SetGroupCommit(64);
    Json sspec = Json::Object();
    sspec["stranded"] = true;
    CHECK(stranded_leader.Create("Widget", "w", sspec).ok);
    Store::BatchBytes stranded;
    CHECK(stranded_leader.PendingBatchBytes(&stranded));
    CHECK(stranded_leader.CommitGroup());
    Store committed_leader(l2);
    committed_leader.SetGroupCommit(64);
    Json cspec = Json::Object();
    cspec["committed"] = true;
    CHECK(committed_leader.Create("Widget", "w", cspec).ok);
    Store::BatchBytes committed;
    CHECK(committed_leader.PendingBatchBytes(&committed));
    CHECK(committed_leader.CommitGroup());
    CHECK(stranded_leader.WalTipCrc() != committed_leader.WalTipCrc());

    Store s(wal);
    Replication repl(&s, FollowerOpts(wal, 50));
    // The dead leader's batch lands (term 1).
    CHECK(repl.HandleAppend(AppendReq(1, 0, 1, stranded.bytes))
              .get("ok").as_bool());
    CHECK(s.WalSeq() == 1);
    // The new leader (term 2) heartbeats with ITS tip identity: same
    // seq, different record — the follower must ask for a reseed, not
    // ack a log it does not actually share.
    Json hb = repl.HandleAppend(
        AppendReq(2, 1, 1, "", committed_leader.WalTipCrc()));
    CHECK(!hb.get("ok").as_bool());
    CHECK(hb.get("needSnapshot").as_bool());
    CHECK(s.Get("Widget", "w")->spec.get("stranded").as_bool());
    // The reseed replaces the stranded history with the committed one.
    std::string snap, lwal, err;
    CHECK(committed_leader.ReadReplicaFiles(&snap, &lwal));
    Json snap_req = Json::Object();
    snap_req["op"] = "repl.snapshot";
    snap_req["term"] = 2;
    snap_req["leader"] = "/tmp/tpk_repl_leader.sock";
    snap_req["commitSeq"] = 1;
    snap_req["snapshot"] = snap;
    snap_req["wal"] = lwal;
    CHECK(repl.HandleSnapshot(snap_req).get("ok").as_bool());
    CHECK(s.WalTipCrc() == committed_leader.WalTipCrc());
    CHECK(s.Get("Widget", "w")->spec.get("committed").as_bool());
    // And a MATCHING tip identity heartbeats clean.
    CHECK(repl.HandleAppend(
              AppendReq(2, 1, 1, "", committed_leader.WalTipCrc()))
              .get("ok").as_bool());
    Cleanup(wal);
    Cleanup(l1);
    Cleanup(l2);
  }

  // Catch-up from snapshot after compaction: the leader's snapshot +
  // tail files install over a stale follower and replay to the exact
  // same state and sequence — the rejoin path when the tail the
  // follower missed was compacted away.
  {
    std::string lwal = "/tmp/tpk_repl_catchup_l.jsonl";
    std::string fwal = "/tmp/tpk_repl_catchup_f.jsonl";
    Cleanup(lwal);
    Cleanup(fwal);
    Store leader(lwal);
    leader.SetGroupCommit(64);
    for (int i = 0; i < 8; ++i) {
      CHECK(leader.Create("Widget", "w" + std::to_string(i),
                          Json::Object()).ok);
      CHECK(leader.CommitGroup());
    }
    CHECK(leader.Compact());
    CHECK(leader.Create("Widget", "post-compact", Json::Object()).ok);
    CHECK(leader.CommitGroup());

    Store follower(fwal);
    // The follower has its own (diverged) history: install overwrites.
    CHECK(follower.Create("Widget", "stale-local", Json::Object()).ok);
    std::string snap, wal;
    CHECK(leader.ReadReplicaFiles(&snap, &wal));
    CHECK(!snap.empty());
    std::string err;
    CHECK(follower.InstallReplica(snap, wal, &err));
    CHECK(follower.WalSeq() == leader.WalSeq());
    CHECK(follower.load_stats().snapshot_loaded);
    CHECK(follower.load_stats().clean);
    CHECK(!follower.Get("Widget", "stale-local").has_value());
    CHECK(follower.Get("Widget", "w7").has_value());
    CHECK(follower.Get("Widget", "post-compact").has_value());
    CHECK(ReadFile(fwal) == ReadFile(lwal));
    CHECK(ReadFile(fwal + ".snap") == ReadFile(lwal + ".snap"));
    Cleanup(lwal);
    Cleanup(fwal);
  }

  // A torn shipped-batch tail on the follower truncates on replay
  // exactly like a torn local append: replay stops at the last good
  // record, the torn bytes leave the file, and the load is clean.
  {
    std::string lwal = "/tmp/tpk_repl_torn_l.jsonl";
    std::string fwal = "/tmp/tpk_repl_torn_f.jsonl";
    Cleanup(lwal);
    Cleanup(fwal);
    Store leader(lwal);
    leader.SetGroupCommit(64);
    for (int i = 0; i < 3; ++i) {
      CHECK(leader.Create("Widget", "w" + std::to_string(i),
                          Json::Object()).ok);
    }
    Store::BatchBytes batch;
    CHECK(leader.PendingBatchBytes(&batch));
    CHECK(leader.CommitGroup());
    {
      Store follower(fwal);
      std::string err;
      CHECK(follower.AppendReplicatedLog(batch.bytes, &err));
      CHECK(follower.ApplyReplicatedUpTo(batch.last_seq) == 3);
    }
    // Tear the follower's file mid-final-record (the crash-mid-append
    // shape, here crash-mid-replicated-append).
    std::string bytes = ReadFile(fwal);
    CHECK(truncate(fwal.c_str(), bytes.size() - 7) == 0);
    Store reloaded(fwal);
    CHECK(reloaded.Load() == 2);
    CHECK(reloaded.load_stats().clean);
    CHECK(reloaded.load_stats().truncated_bytes > 0);
    CHECK(reloaded.WalSeq() == 2);
    CHECK(!reloaded.Get("Widget", "w2").has_value());
    // And the torn record can be re-shipped: the leader's next append
    // sees the seq gap (needSnapshot in the handler); at store level a
    // reseed lands the full log again.
    std::string snap, wal;
    CHECK(leader.ReadReplicaFiles(&snap, &wal));
    std::string err;
    CHECK(reloaded.InstallReplica(snap, wal, &err));
    CHECK(reloaded.Get("Widget", "w2").has_value());
    CHECK(reloaded.WalSeq() == leader.WalSeq());
    Cleanup(lwal);
    Cleanup(fwal);
  }

  // Shipped-batch verification: corrupt shipped bytes (bit flip) or a
  // sequence gap reject the WHOLE batch with nothing written.
  {
    std::string lwal = "/tmp/tpk_repl_verify_l.jsonl";
    std::string fwal = "/tmp/tpk_repl_verify_f.jsonl";
    Cleanup(lwal);
    Cleanup(fwal);
    Store leader(lwal);
    leader.SetGroupCommit(64);
    CHECK(leader.Create("Widget", "a", Json::Object()).ok);
    CHECK(leader.Create("Widget", "b", Json::Object()).ok);
    Store::BatchBytes batch;
    CHECK(leader.PendingBatchBytes(&batch));
    CHECK(leader.CommitGroup());
    Store follower(fwal);
    std::string corrupted = batch.bytes;
    corrupted[corrupted.size() / 2] ^= 0x20;  // flip inside record 1 or 2
    std::string err;
    CHECK(!follower.AppendReplicatedLog(corrupted, &err));
    CHECK(follower.WalSeq() == 0);
    CHECK(ReadFile(fwal).empty());
    // Contiguity: shipping the batch twice is a seq regression, not a
    // silent double apply.
    CHECK(follower.AppendReplicatedLog(batch.bytes, &err));
    CHECK(!follower.AppendReplicatedLog(batch.bytes, &err));
    CHECK(follower.WalSeq() == 2);
    Cleanup(lwal);
    Cleanup(fwal);
  }

  // Vote rules: term, log length, one vote per term, and the lease gate
  // (a follower that still hears its leader refuses to depose it).
  {
    std::string wal = "/tmp/tpk_repl_vote.jsonl";
    Cleanup(wal);
    Store s(wal);
    Replication repl(&s, FollowerOpts(wal, 40));
    // Establish a leader at term 2 (fresh lease from this append).
    CHECK(repl.HandleAppend(AppendReq(2, 0, 0, "")).get("ok").as_bool());
    Json vote = Json::Object();
    vote["op"] = "repl.vote";
    vote["term"] = 3;
    vote["candidate"] = "/tmp/tpk_repl_other.sock";
    vote["lastSeq"] = 0;
    // Lease fresh → denied even at a higher term, and OUR term must not
    // adopt the candidate's (else the live leader gets fenced anyway).
    Json denied = repl.HandleVote(vote);
    CHECK(!denied.get("granted").as_bool());
    CHECK(repl.term() == 2);
    usleep(90 * 1000);  // lease (40 ms) expires
    // Stale term → denied regardless of lease.
    Json stale_vote = vote;
    stale_vote["term"] = 1;
    CHECK(!repl.HandleVote(stale_vote).get("granted").as_bool());
    // Expired lease + newer term + log at least as long → granted.
    Json granted = repl.HandleVote(vote);
    CHECK(granted.get("granted").as_bool());
    CHECK(repl.term() == 3);
    // One vote per term: a second candidate at the same term is denied.
    Json rival = vote;
    rival["candidate"] = "/tmp/tpk_repl_rival.sock";
    CHECK(!repl.HandleVote(rival).get("granted").as_bool());
    // A shorter log is never electable: bump our log, candidate at 0.
    CHECK(repl.HandleAppend(AppendReq(3, 0, 0, "")).get("ok").as_bool());
    {
      std::string lwal = "/tmp/tpk_repl_vote_l.jsonl";
      Cleanup(lwal);
      Store leader(lwal);
      leader.SetGroupCommit(64);
      CHECK(leader.Create("Widget", "w", Json::Object()).ok);
      Store::BatchBytes b;
      CHECK(leader.PendingBatchBytes(&b));
      CHECK(leader.CommitGroup());
      CHECK(repl.HandleAppend(AppendReq(3, 0, 1, b.bytes))
                .get("ok").as_bool());
      Cleanup(lwal);
    }
    usleep(90 * 1000);
    Json short_cand = vote;
    short_cand["term"] = 4;
    short_cand["lastSeq"] = 0;  // our log is at seq 1
    CHECK(!repl.HandleVote(short_cand).get("granted").as_bool());
    // Equal length but a DIFFERENT tip record (divergence a dead leader
    // left behind): refused — electing it could replace the committed
    // record with the stranded one.
    Json diverged_cand = vote;
    diverged_cand["term"] = 4;
    diverged_cand["lastSeq"] = 1;
    diverged_cand["lastCrc"] = static_cast<int64_t>(s.WalTipCrc() ^ 0x1);
    CHECK(!repl.HandleVote(diverged_cand).get("granted").as_bool());
    Json long_cand = vote;
    long_cand["term"] = 4;
    long_cand["lastSeq"] = 1;
    long_cand["lastCrc"] = static_cast<int64_t>(s.WalTipCrc());
    CHECK(repl.HandleVote(long_cand).get("granted").as_bool());
    // Terms and votes persisted: a restart remembers term 4.
    Replication repl2(&s, FollowerOpts(wal, 40));
    CHECK(repl2.term() == 4);
    Cleanup(wal);
  }

  // Single-node WAL parity: a store whose batches commit WITHOUT any
  // replication produces byte-for-byte the same WAL as one driven
  // through PendingBatchBytes+CommitGroup (the export is read-only).
  {
    std::string a = "/tmp/tpk_repl_parity_a.jsonl";
    std::string b = "/tmp/tpk_repl_parity_b.jsonl";
    Cleanup(a);
    Cleanup(b);
    Store sa(a);
    sa.SetGroupCommit(64);
    Store sb(b);
    sb.SetGroupCommit(64);
    for (int i = 0; i < 4; ++i) {
      Json spec = Json::Object();
      spec["i"] = i;
      CHECK(sa.Create("Widget", "w" + std::to_string(i), spec).ok);
      CHECK(sb.Create("Widget", "w" + std::to_string(i), spec).ok);
    }
    Store::BatchBytes peek;
    CHECK(sb.PendingBatchBytes(&peek));  // the leader-path read
    CHECK(sa.CommitGroup());
    CHECK(sb.CommitGroup());
    CHECK(ReadFile(a) == ReadFile(b));
    Cleanup(a);
    Cleanup(b);
  }

  printf("test_replication: OK\n");
  return 0;
}
