// Store tests: CRUD, optimistic concurrency, watches, WAL replay.
#include <sys/stat.h>

#include <cassert>
#include <cstdio>
#include <unistd.h>

#include "store.h"

using tpk::Json;
using tpk::Store;
using tpk::WatchEvent;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

int main() {
  {
    Store store;
    Json spec = Json::Object();
    spec["replicas"] = 2;
    auto r = store.Create("JAXJob", "j1", spec);
    CHECK(r.ok);
    CHECK(r.resource.generation == 1);

    // Duplicate create fails.
    CHECK(!store.Create("JAXJob", "j1", spec).ok);

    // Spec update bumps generation; status update does not.
    auto r2 = store.UpdateSpec("JAXJob", "j1", spec);
    CHECK(r2.ok && r2.resource.generation == 2);
    Json st = Json::Object();
    st["phase"] = "Running";
    auto r3 = store.UpdateStatus("JAXJob", "j1", st);
    CHECK(r3.ok && r3.resource.generation == 2);
    CHECK(r3.resource.status.get("phase").as_string() == "Running");

    // CAS conflict.
    auto r4 = store.UpdateStatus("JAXJob", "j1", st, /*expected=*/1);
    CHECK(!r4.ok && r4.error.find("conflict") != std::string::npos);

    // Watches observe ordered events after drain. Drain first: events queued
    // before a watcher registers are still pending and would be delivered.
    store.DrainWatches();
    std::vector<std::string> seen;
    store.Watch("JAXJob", [&seen](const WatchEvent& ev) {
      seen.push_back(ev.resource.name + ":" +
                     std::to_string(static_cast<int>(ev.type)));
    });
    store.Create("JAXJob", "j2", spec);
    store.Delete("JAXJob", "j2");
    CHECK(seen.empty());  // nothing until drained
    store.DrainWatches();
    CHECK(seen.size() == 2);
    CHECK(seen[0] == "j2:0");  // ADDED
    CHECK(seen[1] == "j2:2");  // DELETED

    // List filters by kind.
    store.Create("Other", "x", spec);
    CHECK(store.List("JAXJob").size() == 1);
    CHECK(store.List("").size() == 2);
  }

  // WAL persistence across restarts.
  {
    char tmpl[] = "/tmp/tpk_store_walXXXXXX";
    int fd = mkstemp(tmpl);
    close(fd);
    std::string wal = tmpl;
    {
      Store store(wal);
      Json spec = Json::Object();
      spec["v"] = 1;
      store.Create("JAXJob", "a", spec);
      Json st = Json::Object();
      st["phase"] = "Succeeded";
      store.UpdateStatus("JAXJob", "a", st);
      store.Create("JAXJob", "b", spec);
      store.Delete("JAXJob", "b");
    }
    {
      Store store(wal);
      int n = store.Load();
      CHECK(n == 4);
      auto a = store.Get("JAXJob", "a");
      CHECK(a.has_value());
      CHECK(a->status.get("phase").as_string() == "Succeeded");
      CHECK(!store.Get("JAXJob", "b").has_value());
      // Versions continue monotonically after replay.
      auto r = store.Create("JAXJob", "c", Json::Object());
      CHECK(r.resource.resource_version > a->resource_version);
    }
    unlink(wal.c_str());
  }

  // Name validation: names become path components and proc-id prefixes.
  {
    Store s;
    CHECK(!s.Create("JAXJob", "a/b", Json::Object()).ok);
    CHECK(!s.Create("JAXJob", "..", Json::Object()).ok);
    CHECK(!s.Create("JAXJob", "", Json::Object()).ok);
    CHECK(!s.Create("JAXJob", ".hidden", Json::Object()).ok);
    CHECK(s.Create("JAXJob", "ok-name_1.2", Json::Object()).ok);
    CHECK(!Store::ValidName(std::string(300, 'a')));
  }

  // Crash mid-append (torn tail): Load() must truncate the torn line IN
  // THE FILE before the writer reopens — without that, the next append
  // glues onto the torn line and every later record is silently lost on
  // the NEXT replay (regression: the seed's append-mode reopen bug).
  {
    std::string wal = "/tmp/tpk_test_store_tornwal.jsonl";
    std::remove(wal.c_str());
    {
      Store w(wal);
      Json spec = Json::Object();
      spec["v"] = 1;
      CHECK(w.Create("JAXJob", "a", spec).ok);
      CHECK(w.Create("JAXJob", "b", spec).ok);
    }
    struct stat st;
    CHECK(stat(wal.c_str(), &st) == 0);
    CHECK(truncate(wal.c_str(), st.st_size - 7) == 0);  // tear record "b"
    {
      Store r(wal);
      CHECK(r.Load() == 1);  // stopped at the torn record
      CHECK(r.load_stats().clean);  // torn tail = expected crash shape
      CHECK(r.load_stats().truncated_bytes > 0);
      CHECK(r.Get("JAXJob", "a").has_value());
      CHECK(!r.Get("JAXJob", "b").has_value());
      // Appending after the repair must start on a fresh line.
      CHECK(r.Create("JAXJob", "c", Json::Object()).ok);
    }
    {
      Store r2(wal);
      CHECK(r2.Load() == 2);  // a AND c survive a SECOND replay
      CHECK(r2.Get("JAXJob", "a").has_value());
      CHECK(r2.Get("JAXJob", "c").has_value());
      CHECK(r2.load_stats().clean);
      CHECK(r2.load_stats().truncated_bytes == 0);
    }
    std::remove(wal.c_str());
  }

  // WAL records larger than 64KB must replay intact (regression: fixed-size
  // fgets buffer truncated them and dropped all later records).
  {
    std::string wal = "/tmp/tpk_test_store_bigwal.jsonl";
    std::remove(wal.c_str());
    {
      Store w(wal);
      Json spec = Json::Object();
      spec["blob"] = std::string(200 * 1024, 'x');
      CHECK(w.Create("JAXJob", "big", spec).ok);
      CHECK(w.Create("JAXJob", "after", Json::Object()).ok);
    }
    Store r(wal);
    CHECK(r.Load() == 2);
    CHECK(r.Get("JAXJob", "big").has_value());
    CHECK(r.Get("JAXJob", "after").has_value());
    CHECK(r.Get("JAXJob", "big")->spec.get("blob").as_string().size() ==
          200 * 1024);
    std::remove(wal.c_str());
  }

  printf("test_store OK\n");
  return 0;
}
