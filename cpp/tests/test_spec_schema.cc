// Spec drift guard, C++ side (SURVEY.md §5.6): admission must enforce the
// GENERATED runtime field table (spec_schema.gen.h) mechanically — every
// entry's type/min/enum, and rejection of fields not in the table. If a
// field is deleted from the schema, the presence assertions below fail;
// if one is added without regenerating, the Python suite's cross-check
// fails (tests/test_spec_schema.py). No e2e required to notice drift.
#include <cstdio>
#include <string>

#include "admission.h"
#include "json.h"

using tpk::Json;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

namespace {

std::string ValidateRuntime(Json runtime) {
  Json spec = Json::Object();
  spec["replicas"] = 1;
  spec["runtime"] = runtime;
  return tpk::ValidateSpec("JAXJob", spec);
}

Json One(const std::string& field, Json value) {
  Json rt = Json::Object();
  rt[field] = std::move(value);
  return rt;
}

std::string ValidateGenerative(Json gen) {
  Json spec = Json::parse(R"({"model": {"model_dir": "/m"}})");
  spec["model"]["generative"] = std::move(gen);
  return tpk::ValidateSpec("InferenceService", spec);
}

}  // namespace

int main() {
  const Json& table = tpk::SpecSchemaRuntime();
  CHECK(table.is_object());
  // A hollowed-out schema must not pass silently: the core contract
  // fields are pinned by name.
  for (const char* core : {"steps", "batch_size", "accum_steps",
                           "learning_rate", "lr_schedule", "model",
                           "dataset", "mesh"}) {
    CHECK(table.has(core));
  }

  int checked = 0;
  for (const auto& [field, entry] : table.items()) {
    const std::string type = entry.get("type").as_string();
    if (type == "int") {
      int64_t min = entry.get("min").as_int(0);
      CHECK(ValidateRuntime(One(field, min)).empty());
      CHECK(!ValidateRuntime(One(field, min - 1)).empty());
      CHECK(!ValidateRuntime(One(field, min + 0.5)).empty());  // integral
      CHECK(!ValidateRuntime(One(field, "2")).empty());        // type
    } else if (type == "number") {
      double min = entry.get("min").as_number();
      CHECK(ValidateRuntime(One(field, min)).empty());
      CHECK(!ValidateRuntime(One(field, min - 1)).empty());
      CHECK(!ValidateRuntime(One(field, "fast")).empty());
    } else if (type == "string") {
      if (entry.has("enum")) {
        for (const auto& e : entry.get("enum").elements()) {
          CHECK(ValidateRuntime(One(field, e.as_string())).empty());
        }
        CHECK(!ValidateRuntime(One(field, "no-such-enum-value")).empty());
      } else {
        CHECK(ValidateRuntime(One(field, "x")).empty());
      }
      CHECK(!ValidateRuntime(One(field, 5)).empty());
    } else if (type == "string_or_null") {
      if (entry.has("enum")) {
        for (const auto& e : entry.get("enum").elements()) {
          CHECK(ValidateRuntime(One(field, e.as_string())).empty());
        }
        CHECK(!ValidateRuntime(One(field, "no-such-enum-value")).empty());
      } else {
        CHECK(ValidateRuntime(One(field, "x")).empty());
      }
      CHECK(ValidateRuntime(One(field, nullptr)).empty());
      CHECK(!ValidateRuntime(One(field, 5)).empty());
    } else if (type == "bool_or_string") {
      CHECK(ValidateRuntime(One(field, true)).empty());
      CHECK(ValidateRuntime(One(field, "ring")).empty());
      CHECK(!ValidateRuntime(One(field, 5)).empty());
    } else if (type == "object") {
      CHECK(ValidateRuntime(One(field, Json::Object())).empty());
      CHECK(!ValidateRuntime(One(field, 5)).empty());
    } else {
      fprintf(stderr, "FAIL: schema type %s unhandled by this test\n",
              type.c_str());
      return 1;
    }
    ++checked;
  }
  CHECK(checked >= 25);  // the real table, not a stub

  // Unknown runtime fields (typo'd knobs) are rejected at submit.
  CHECK(!ValidateRuntime(One("stesp", 100)).empty());
  std::string err = ValidateRuntime(One("no_such_knob", 1));
  CHECK(err.find("not a JAXJob runtime field") != std::string::npos);

  // Cross-field semantics still enforced on top of the schema.
  Json rt = Json::Object();
  rt["batch_size"] = 8;
  rt["accum_steps"] = 3;
  CHECK(!ValidateRuntime(rt).empty());
  rt["accum_steps"] = 2;
  CHECK(ValidateRuntime(rt).empty());

  // grad_accum (canonical) mirrors the divisibility rule and must not
  // silently disagree with its legacy alias.
  rt = Json::Object();
  rt["batch_size"] = 8;
  rt["grad_accum"] = 3;
  CHECK(ValidateRuntime(rt).find("divisible by grad_accum") !=
        std::string::npos);
  rt["grad_accum"] = 4;
  CHECK(ValidateRuntime(rt).empty());
  rt["accum_steps"] = 2;
  CHECK(ValidateRuntime(rt).find("disagree") != std::string::npos);
  rt["accum_steps"] = 4;
  CHECK(ValidateRuntime(rt).empty());

  // FSDP knob contradictions fail at submit, not as a worker crash.
  rt = Json::Object();
  rt["fsdp"] = 4;
  Json mesh = Json::Object();
  mesh["fsdp"] = 2;
  rt["mesh"] = mesh;
  CHECK(ValidateRuntime(rt).find("conflicts with runtime.mesh.fsdp") !=
        std::string::npos);
  rt["mesh"]["fsdp"] = 4;
  CHECK(ValidateRuntime(rt).empty());
  rt["mesh"] = Json::Object();
  rt["mesh"]["pipe"] = 2;
  CHECK(ValidateRuntime(rt).find("pipeline") != std::string::npos);
  rt["mesh"] = Json::Object();
  Json lora = Json::Object();
  lora["rank"] = 4;
  rt["lora"] = lora;
  CHECK(ValidateRuntime(rt).find("lora") != std::string::npos);

  printf("spec schema drift guard: %d fields enforced\n", checked);

  // --- Generative serving knobs (InferenceService.model.generative) ----
  {
    const Json& gtable = tpk::SpecSchemaGenerative();
    CHECK(gtable.is_object());
    // The paged-KV knobs this table exists to carry, plus the engine
    // core, pinned by name.
    for (const char* core : {"kv_block_size", "kv_blocks", "slots",
                             "max_len", "chunk", "prefill_buckets",
                             "pipeline_depth", "prefix_cache"}) {
      CHECK(gtable.has(core));
    }
    int gchecked = 0;
    for (const auto& [field, entry] : gtable.items()) {
      const std::string type = entry.get("type").as_string();
      if (type == "int") {
        int64_t min = entry.get("min").as_int(0);
        CHECK(ValidateGenerative(One(field, min)).empty());
        CHECK(!ValidateGenerative(One(field, min - 1)).empty());
        CHECK(!ValidateGenerative(One(field, min + 0.5)).empty());
        CHECK(!ValidateGenerative(One(field, "2")).empty());
      } else if (type == "int_or_null") {
        CHECK(ValidateGenerative(One(field, 7)).empty());
        CHECK(ValidateGenerative(One(field, nullptr)).empty());
        CHECK(!ValidateGenerative(One(field, "7")).empty());
        CHECK(!ValidateGenerative(One(field, 1.5)).empty());
      } else if (type == "int_array") {
        Json arr = Json::Array();
        arr.push_back(Json(int64_t{32}));
        arr.push_back(Json(int64_t{128}));
        CHECK(ValidateGenerative(One(field, arr)).empty());
        CHECK(!ValidateGenerative(One(field, 32)).empty());
        // Empty bucket lists crash the engine at load — rejected here.
        CHECK(!ValidateGenerative(One(field, Json::Array())).empty());
        Json bad = Json::Array();
        bad.push_back(Json("x"));
        CHECK(!ValidateGenerative(One(field, bad)).empty());
        Json frac = Json::Array();
        frac.push_back(Json(1.5));
        CHECK(!ValidateGenerative(One(field, frac)).empty());
        if (entry.has("min")) {
          Json low = Json::Array();
          low.push_back(Json(entry.get("min").as_int() - 1));
          CHECK(!ValidateGenerative(One(field, low)).empty());
        }
      } else if (type == "object") {
        // draft has cross-field content rules (below) — an empty
        // object is rightly rejected, so probe with a minimal valid
        // instance instead.
        Json obj = Json::Object();
        if (field == "draft") obj["checkpoint"] = "/d";
        CHECK(ValidateGenerative(One(field, obj)).empty());
        CHECK(!ValidateGenerative(One(field, 5)).empty());
      } else if (type == "string_or_null") {
        // role and kv_quant additionally have cross-field rules (both
        // need kv_block_size > 0) — satisfy them so the enum probe
        // isolates the schema check.
        auto probe = [&](Json v) {
          Json g = One(field, std::move(v));
          if (field == "role" || field == "kv_quant") {
            g["kv_block_size"] = 16;
          }
          return ValidateGenerative(std::move(g));
        };
        if (entry.has("enum")) {
          for (const auto& e : entry.get("enum").elements()) {
            CHECK(probe(Json(e.as_string())).empty());
          }
          CHECK(!probe(Json("no-such-enum-value")).empty());
        } else {
          CHECK(probe(Json("x")).empty());
        }
        CHECK(probe(Json(nullptr)).empty());
        CHECK(!probe(Json(int64_t{5})).empty());
      } else {
        fprintf(stderr, "FAIL: generative schema type %s unhandled\n",
                type.c_str());
        return 1;
      }
      ++gchecked;
    }
    CHECK(gchecked >= 15);
    // Unknown knobs (typos, or knobs newer than this binary) rejected.
    std::string gerr = ValidateGenerative(One("kv_blocksize", 16));
    CHECK(gerr.find("not a generative serving knob") != std::string::npos);
    // Non-object generative rejected; absent generative still fine.
    Json spec = Json::parse(R"({"model": {"model_dir": "/m",
                                          "generative": 5}})");
    CHECK(!tpk::ValidateSpec("InferenceService", spec).empty());
    CHECK(tpk::ValidateSpec("InferenceService",
                            Json::parse(R"({"model": {"model_dir": "/m"}})"))
              .empty());
    printf("generative knob table: %d fields enforced\n", gchecked);

    // Cross-field composition rules (ISSUE 18): what used to crash-
    // loop the replica at load now rejects at submit.
    // Split roles need the paged pool.
    Json gen = Json::Object();
    gen["role"] = "prefill";
    CHECK(ValidateGenerative(gen).find("needs kv_block_size") !=
          std::string::npos);
    gen["kv_block_size"] = 16;
    CHECK(ValidateGenerative(gen).empty());
    gen["role"] = "unified";
    gen["kv_block_size"] = 0;
    CHECK(ValidateGenerative(gen).empty());  // unified never needs it
    // Block counts / host tier without a block size are meaningless.
    CHECK(!ValidateGenerative(One("kv_blocks", 64)).empty());
    CHECK(!ValidateGenerative(One("kv_host_tier_blocks", 64)).empty());
    gen = One("kv_blocks", 64);
    gen["kv_block_size"] = 16;
    CHECK(ValidateGenerative(gen).empty());
    // Draft spec contents: checkpoint required, gamma integral >= 1,
    // typo'd keys loud. The draft COMPOSES with role + paging now, so
    // the old draft-x-role / draft-x-paged refusals must NOT resurface.
    Json draft = Json::Object();
    CHECK(ValidateGenerative(One("draft", draft))
              .find("needs a checkpoint") != std::string::npos);
    draft["checkpoint"] = "/drafts/tiny";
    CHECK(ValidateGenerative(One("draft", draft)).empty());
    draft["gamma"] = 0;
    CHECK(ValidateGenerative(One("draft", draft))
              .find("gamma") != std::string::npos);
    draft["gamma"] = 2.5;
    CHECK(!ValidateGenerative(One("draft", draft)).empty());
    draft["gamma"] = 4;
    CHECK(ValidateGenerative(One("draft", draft)).empty());
    draft["gamm"] = 4;
    CHECK(ValidateGenerative(One("draft", draft))
              .find("not a draft knob") != std::string::npos);
    gen = Json::Object();
    draft = Json::Object();
    draft["checkpoint"] = "/drafts/tiny";
    draft["model_overrides"] = 5;
    CHECK(ValidateGenerative(One("draft", draft))
              .find("model_overrides") != std::string::npos);
    draft["model_overrides"] = Json::Object();
    gen["draft"] = draft;
    gen["role"] = "decode";
    gen["kv_block_size"] = 16;
    gen["kv_blocks"] = 64;
    gen["pipeline_depth"] = 2;
    CHECK(ValidateGenerative(gen).empty());  // spec x paged x disagg
    // Quantized KV blocks (ISSUE 19): table row pinned by name; the
    // scale pool is paged, so kv_quant needs kv_block_size > 0; and
    // kv_quant x draft is refused (a rejection rewind would
    // re-quantize committed rows). "none" is the escape hatch and
    // composes with everything, including draft.
    CHECK(gtable.has("kv_quant"));
    CHECK(ValidateGenerative(One("kv_quant", "int8"))
              .find("needs kv_block_size") != std::string::npos);
    gen = One("kv_quant", "fp8");
    gen["kv_block_size"] = 16;
    CHECK(ValidateGenerative(gen).empty());
    gen["role"] = "prefill";  // quant x disagg composes
    CHECK(ValidateGenerative(gen).empty());
    gen["role"] = nullptr;
    draft = Json::Object();
    draft["checkpoint"] = "/drafts/tiny";
    gen["draft"] = draft;
    CHECK(ValidateGenerative(gen).find("does not compose with draft") !=
          std::string::npos);
    gen["kv_quant"] = "none";  // escape hatch composes with draft
    CHECK(ValidateGenerative(gen).empty());
    CHECK(ValidateGenerative(One("kv_quant", "none")).empty());
    printf("generative cross-field composition rules OK\n");
  }

  // --- Namespace defaults (PodDefaults analog) -------------------------
  {
    using tpk::MergeNamespaceDefaults;
    using tpk::SpecNamespace;
    Json spec = Json::parse(R"({
      "namespace": "team-a",
      "runtime": {"steps": 50, "checkpoint": {"interval": 5}}
    })");
    Json defs = Json::parse(R"({
      "backoff_limit": 2,
      "runtime": {"steps": 999, "log_every": 10,
                  "checkpoint": {"interval": 99, "keep": 3}}
    })");
    CHECK(SpecNamespace(spec) == "team-a");
    CHECK(SpecNamespace(Json::Object()) == "default");
    Json merged = MergeNamespaceDefaults(spec, defs);
    // Missing fields filled at every depth...
    CHECK(merged.get("backoff_limit").as_int() == 2);
    CHECK(merged.get("runtime").get("log_every").as_int() == 10);
    CHECK(merged.get("runtime").get("checkpoint").get("keep").as_int() == 3);
    // ...but the user's values always win.
    CHECK(merged.get("runtime").get("steps").as_int() == 50);
    CHECK(merged.get("runtime").get("checkpoint").get("interval")
              .as_int() == 5);
    // No defaults -> spec unchanged.
    CHECK(MergeNamespaceDefaults(spec, Json()).dump() == spec.dump());

    // Explicit null = user-wins OPT-OUT of that key's default (ADVICE
    // r5): the key is STRIPPED before validation, not silently
    // refilled — at the top level and recursively inside objects.
    Json optout = Json::parse(R"({
      "namespace": "team-a",
      "backoff_limit": null,
      "runtime": {"steps": 50, "log_every": null}
    })");
    Json m2 = MergeNamespaceDefaults(optout, defs);
    CHECK(!m2.has("backoff_limit"));
    CHECK(!m2.get("runtime").has("log_every"));
    CHECK(m2.get("runtime").get("steps").as_int() == 50);
    // Untouched defaults still fill around the opt-out.
    CHECK(m2.get("runtime").get("checkpoint").get("keep").as_int() == 3);
    // The stripped spec validates as if the key were never sent — this
    // is why stripping must happen BEFORE validation: a surviving
    // runtime.log_every=null would be rejected by its schema type.
    CHECK(tpk::ValidateSpec("JAXJob", m2).empty());
    // Null on a key the namespace does NOT default is left untouched
    // (opt-out is scoped to the defaulting machinery).
    Json nodef = Json::parse(R"({"runtime": {"steps": 5},
                                 "elastic": null})");
    Json m3 = MergeNamespaceDefaults(nodef, defs);
    CHECK(m3.has("elastic") && m3.get("elastic").is_null());
    printf("null opt-out of namespace defaults OK\n");

    // Profile.defaults validation: object-of-objects, no Profile key.
    Json prof = Json::Object();
    prof["defaults"] = Json::parse(R"({"JAXJob": {"backoff_limit": 1}})");
    CHECK(tpk::ValidateSpec("Profile", prof).empty());
    prof["defaults"] = Json::parse(R"({"JAXJob": 5})");
    CHECK(!tpk::ValidateSpec("Profile", prof).empty());
    prof["defaults"] = Json::parse(R"({"Profile": {}})");
    CHECK(!tpk::ValidateSpec("Profile", prof).empty());
    printf("namespace defaults: merge + validation OK\n");
  }
  return 0;
}
