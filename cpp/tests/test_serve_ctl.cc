// InferenceService controller semantics against FakeExecutor + FakeProbe —
// envtest-style (SURVEY.md §4.2): replica launch + readiness, crash-loop
// backoff with streak reset, manual scaling, throughput autoscaling,
// delete cleanup, and Prometheus parsing. No processes or HTTP.
#include <cstdio>
#include <string>

#include "executor.h"
#include "scheduler.h"
#include "serve.h"
#include "store.h"

using tpk::FakeExecutor;
using tpk::FakeProbe;
using tpk::Json;
using tpk::Scheduler;
using tpk::ServeController;
using tpk::Store;
using tpk::TrainedModelController;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

namespace {

std::string Phase(Store& store, const std::string& name) {
  auto r = store.Get("InferenceService", name);
  return r ? r->status.get("phase").as_string() : "<gone>";
}

int Port(Store& store, const std::string& name, int replica) {
  auto r = store.Get("InferenceService", name);
  return static_cast<int>(r->status.get("replicaState")
                              .elements()[replica]
                              .get("port")
                              .as_int());
}

Json BaseSpec(int replicas) {
  Json spec = Json::Object();
  Json model = Json::Object();
  model["name"] = "m";
  model["model_dir"] = "/tmp/bundle";
  spec["model"] = model;
  spec["replicas"] = replicas;
  spec["devices_per_replica"] = 1;
  return spec;
}

struct Harness {
  Store store;
  Scheduler sched;
  FakeExecutor exec;
  FakeProbe probe;
  ServeController ctl{&store, &exec, &sched, &probe, "/tmp/tpk_test_serve"};
  double now = 1000.0;

  Harness(int capacity = 8) { sched.AddSlice("local", capacity); }

  void Tick() {
    ctl.Tick(now);
    store.DrainWatches();
  }
};

}  // namespace

int main() {
  // --- Prometheus parsing ----------------------------------------------
  {
    std::string text =
        "# TYPE tpk_serve_requests_total counter\n"
        "tpk_serve_requests_total{model=\"a\"} 120\n"
        "tpk_serve_requests_total{model=\"b\"} 30.5\n"
        "tpk_serve_examples_total{model=\"a\"} 999\n";
    CHECK(ServeController::ParseRequestsTotal(text) == 150.5);
    CHECK(ServeController::ParseRequestsTotal("") == 0);
  }

  // --- Launch + readiness gating ---------------------------------------
  {
    Harness h;
    h.store.Create("InferenceService", "svc", BaseSpec(2));
    h.Tick();
    CHECK(h.exec.launched.size() == 2);
    CHECK(h.exec.launched[0].argv[2] == "kubeflow_tpu.serve.server");
    CHECK(h.exec.launched[0].env.at("TPK_SERVICE") == "svc");
    CHECK(h.sched.Slices()[0].used == 2);
    CHECK(Phase(h.store, "svc") == "Running");  // up but not ready

    // Distinct ports; mark both ready via the probe.
    int p0 = Port(h.store, "svc", 0), p1 = Port(h.store, "svc", 1);
    CHECK(p0 != p1 && p0 > 0);
    h.probe.ready = {p0, p1};
    h.now += 2;  // probe rate limit
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Ready");
    auto r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("endpoints").size() == 2);
    CHECK(r->status.get("endpoints").elements()[0].get("url").as_string() ==
          "http://127.0.0.1:" + std::to_string(p0));
    CHECK(h.ctl.metrics().replica_starts == 2);
  }

  // --- Tensor-parallel mesh flag reaches the server ---------------------
  {
    Harness h;
    Json spec = BaseSpec(1);
    spec["devices_per_replica"] = 4;
    Json mesh = Json::Object();
    mesh["tensor"] = 4;
    Json model = spec.get("model");
    model["mesh"] = mesh;
    spec["model"] = model;
    h.store.Create("InferenceService", "svc-tp", spec);
    h.Tick();
    CHECK(h.exec.launched.size() == 1);
    const auto& argv = h.exec.launched[0].argv;
    bool found = false;
    for (size_t i = 0; i + 1 < argv.size(); ++i) {
      if (argv[i] == "--mesh" && argv[i + 1] == "tensor=4") found = true;
    }
    CHECK(found);
    CHECK(h.sched.Slices()[0].used == 4);  // the mesh's devices are held
  }

  // --- Crash loop: backoff, relaunch on new port, streak reset ----------
  {
    Harness h;
    h.store.Create("InferenceService", "svc", BaseSpec(1));
    h.Tick();
    int p0 = Port(h.store, "svc", 0);
    h.probe.ready = {p0};
    h.now += 2;
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Ready");

    h.exec.Finish("svc/srv0", 1);  // server dies
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Pending");
    CHECK(h.ctl.metrics().replica_restarts == 1);
    CHECK(h.exec.launched.size() == 1);  // backoff: not yet relaunched
    h.now += 3;                          // past 2^0=1s... and 2s backoff
    h.Tick();
    CHECK(h.exec.launched.size() == 2);  // relaunched
    int p1 = Port(h.store, "svc", 0);
    CHECK(p1 != 0);
    // Device allocation was retained across the restart (1 used, not 2).
    CHECK(h.sched.Slices()[0].used == 1);

    // Ready for >300s resets the crash streak.
    h.probe.ready.insert(p1);
    h.now += 2;
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Ready");
    h.now += 400;
    h.Tick();
    h.exec.Finish("svc/srv0", 137);
    h.Tick();
    auto r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicaState").elements()[0].get("restarts")
              .as_int() == 1);  // streak reset, back to 1 (not 2)
  }

  // --- Manual scale down releases devices; delete cleans up -------------
  {
    Harness h;
    h.store.Create("InferenceService", "svc", BaseSpec(3));
    h.Tick();
    CHECK(h.sched.Slices()[0].used == 3);

    Json spec = BaseSpec(1);
    h.store.UpdateSpec("InferenceService", "svc", spec);
    h.Tick();
    CHECK(h.exec.killed.size() == 2);
    CHECK(h.sched.Slices()[0].used == 1);
    auto r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicaState").size() == 1);

    auto del = h.store.Delete("InferenceService", "svc");
    h.ctl.OnDeleted(del.resource);
    CHECK(h.exec.killed.size() == 3);
    CHECK(h.sched.Slices()[0].used == 0);
  }

  // --- Throughput autoscaler: scale up on load, down when idle ----------
  {
    Harness h;
    Json spec = BaseSpec(1);
    spec["min_replicas"] = 1;
    spec["max_replicas"] = 4;
    spec["target_rps"] = 10;
    spec["scale_interval_s"] = 10;
    h.store.Create("InferenceService", "svc", spec);
    h.Tick();
    int p0 = Port(h.store, "svc", 0);
    h.probe.ready = {p0};
    h.probe.metrics[p0] = "tpk_serve_requests_total{model=\"m\"} 0\n";
    h.now += 2;
    h.Tick();  // first scrape: baseline
    h.now += 11;
    h.Tick();
    CHECK(h.store.Get("InferenceService", "svc")
              ->status.get("replicas").get("desired").as_int() == 1);

    // 350 requests in ~10s → 35 rps → ceil(35/10)=4 replicas.
    h.probe.metrics[p0] = "tpk_serve_requests_total{model=\"m\"} 350\n";
    h.now += 11;
    h.Tick();
    auto r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicas").get("desired").as_int() == 4);
    CHECK(h.ctl.metrics().scale_events == 1);
    CHECK(h.exec.launched.size() == 4);

    // All replicas ready, traffic stops → back to min.
    for (int i = 0; i < 4; ++i) {
      int p = Port(h.store, "svc", i);
      h.probe.ready.insert(p);
      h.probe.metrics[p] =
          "tpk_serve_requests_total{model=\"m\"} " +
          std::to_string(i == 0 ? 350 : 0) + "\n";
    }
    h.now += 2;
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Ready");
    h.now += 11;
    h.Tick();  // scrape: totals unchanged → 0 rps → min
    h.now += 1;
    h.Tick();
    r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicas").get("desired").as_int() == 1);
    CHECK(h.sched.Slices()[0].used == 1);
  }

  // --- Scale-to-zero: idle reap, wake cold-start, hand-zero stays Ready --
  {
    Harness h;
    Json spec = BaseSpec(1);
    spec["scale_to_zero_after_s"] = 30;
    spec["scale_interval_s"] = 5;
    h.store.Create("InferenceService", "svc", spec);
    h.Tick();
    int p0 = Port(h.store, "svc", 0);
    h.probe.ready = {p0};
    h.probe.metrics[p0] = "tpk_serve_requests_total{model=\"m\"} 0\n";
    h.now += 6;
    h.Tick();  // readiness recorded (scrape sees last tick's not-ready)
    CHECK(Phase(h.store, "svc") == "Ready");
    h.now += 6;
    h.Tick();  // first scrape: counter baseline; birth = activity

    // Traffic within the window keeps it alive past idle_after.
    h.probe.metrics[p0] = "tpk_serve_requests_total{model=\"m\"} 10\n";
    h.now += 6;
    h.Tick();  // delta>0 -> lastActive refreshed
    h.now += 25;
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Ready");

    // No traffic for idle_after -> reaped to 0, phase Idle.
    h.now += 31;
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Idle");
    auto r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicas").get("desired").as_int() == 0);
    CHECK(r->status.get("replicaState").size() == size_t{0});
    CHECK(h.sched.Slices()[0].used == 0);  // devices released
    auto events = h.ctl.metrics().scale_events;
    std::string dump = r->status.dump();
    h.now += 1;  // further idle ticks must not re-fire metric or status
    h.Tick();
    h.now += 1;
    h.Tick();
    CHECK(h.ctl.metrics().scale_events == events);
    CHECK(h.store.Get("InferenceService", "svc")->status.dump() == dump);

    // Wake: spec.wake bump brings it back (cold start).
    auto cur = h.store.Get("InferenceService", "svc");
    Json wspec = cur->spec;
    wspec["wake"] = h.now;
    h.store.UpdateSpec("InferenceService", "svc", wspec);
    h.Tick();
    r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicas").get("desired").as_int() == 1);
    CHECK(r->status.get("replicaState").size() == 1);
    int p1 = Port(h.store, "svc", 0);
    h.probe.ready = {p1};
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Ready");

    // spec.replicas updates still honored with scale_to_zero set.
    Json up = r->spec;
    up["replicas"] = 2;
    up["wake"] = h.now;  // fresh activity alongside the resize
    h.store.UpdateSpec("InferenceService", "svc", up);
    h.Tick();
    r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicas").get("desired").as_int() == 2);
  }
  {
    // Hand-zeroed service with scale_to_zero configured: stays Ready
    // (nothing was reaped), never flips Idle.
    Harness h;
    Json spec = BaseSpec(0);
    spec["scale_to_zero_after_s"] = 5;
    h.store.Create("InferenceService", "svc0", spec);
    h.Tick();
    h.now += 60;
    h.Tick();
    CHECK(Phase(h.store, "svc0") == "Ready");
  }

  // --- Liveness: wedged-but-alive server drops out of endpoints ---------
  {
    Harness h;
    h.store.Create("InferenceService", "svc", BaseSpec(1));
    h.Tick();
    int p0 = Port(h.store, "svc", 0);
    h.probe.ready = {p0};
    h.now += 2;
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Ready");

    h.probe.ready.clear();  // server wedges: alive but unresponsive
    h.now += 11;
    h.Tick();  // probe fail #1 — still Ready (transient tolerance)
    CHECK(Phase(h.store, "svc") == "Ready");
    h.now += 11;
    h.Tick();  // probe fail #2 — endpoint pulled
    CHECK(Phase(h.store, "svc") == "Running");
    CHECK(h.store.Get("InferenceService", "svc")
              ->status.get("endpoints").size() == 0);
    // Server answers again → back to Ready.
    h.probe.ready = {p0};
    h.now += 2;
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Ready");
  }

  // --- Autoscaler: failed scrape keeps baseline (no spurious max) -------
  {
    Harness h;
    Json spec = BaseSpec(1);
    spec["min_replicas"] = 1;
    spec["max_replicas"] = 4;
    spec["target_rps"] = 10;
    spec["scale_interval_s"] = 10;
    h.store.Create("InferenceService", "svc", spec);
    h.Tick();
    int p0 = Port(h.store, "svc", 0);
    h.probe.ready = {p0};
    h.probe.metrics[p0] = "tpk_serve_requests_total{model=\"m\"} 200\n";
    h.now += 2;
    h.Tick();  // baseline total=200
    h.probe.metrics.erase(p0);  // scrape outage
    h.now += 11;
    h.Tick();
    // Outage over; totals unchanged → rps 0 over the long window, not
    // (200-0)/10 → desired stays at min, no burst to max.
    h.probe.metrics[p0] = "tpk_serve_requests_total{model=\"m\"} 200\n";
    h.now += 11;
    h.Tick();
    CHECK(h.store.Get("InferenceService", "svc")
              ->status.get("replicas").get("desired").as_int() == 1);
    CHECK(h.ctl.metrics().scale_events == 0);
  }

  // --- Autoscaler: replica counter reset (restart) is not negative load -
  {
    Harness h;
    Json spec = BaseSpec(2);
    spec["min_replicas"] = 2;  // autoscaler floor (spec.replicas unused
    spec["max_replicas"] = 4;  // once target_rps engages the autoscaler)
    spec["target_rps"] = 2;
    spec["scale_interval_s"] = 10;
    h.store.Create("InferenceService", "svc", spec);
    h.Tick();
    int p0 = Port(h.store, "svc", 0), p1 = Port(h.store, "svc", 1);
    h.probe.ready = {p0, p1};
    h.probe.metrics[p0] = "tpk_serve_requests_total{model=\"m\"} 100\n";
    h.probe.metrics[p1] = "tpk_serve_requests_total{model=\"m\"} 100\n";
    h.now += 2;
    h.Tick();  // replicas become ready
    h.now += 11;
    h.Tick();  // baseline per replica recorded
    // Replica 1 "restarted": counter reset to 10; replica 0 advanced 50.
    h.probe.metrics[p0] = "tpk_serve_requests_total{model=\"m\"} 150\n";
    h.probe.metrics[p1] = "tpk_serve_requests_total{model=\"m\"} 10\n";
    h.now += 11;
    h.Tick();
    // delta = 50 + 10 = 60 over ~11s → ~5.5 rps → ceil(5.5/2) = 3, NOT a
    // collapse to min from a "negative" global delta.
    auto r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicas").get("desired").as_int() == 3);
  }

  // --- Unschedulable: capacity 0 → Pending with reason ------------------
  {
    Harness h(0);
    h.store.Create("InferenceService", "svc", BaseSpec(1));
    h.Tick();
    CHECK(Phase(h.store, "svc") == "Pending");
    auto r = h.store.Get("InferenceService", "svc");
    CHECK(r->status.get("replicaState").elements()[0].get("pendingReason")
              .as_string().find("capacity") != std::string::npos);
    CHECK(h.exec.launched.empty());
  }

  // --- TrainedModel: load pushed to ready replicas, re-load on restart --
  {
    Harness h;
    TrainedModelController tm(&h.store, &h.probe);

    Json spec = Json::Object();
    Json model = Json::Object();
    model["name"] = "extra";
    model["model_dir"] = "/bundles/extra";
    spec["inference_service"] = "parent";
    spec["model"] = model;
    h.store.Create("TrainedModel", "tm1", spec);

    // No parent yet: Pending, no posts.
    tm.Tick(h.now);
    auto r = h.store.Get("TrainedModel", "tm1");
    CHECK(r->status.get("phase").as_string() == "Pending");
    CHECK(h.probe.posts.empty());

    // Parent with two replicas, one ready.
    Json pspec = Json::Object();
    Json pmodel = Json::Object();
    pmodel["name"] = "base";
    pmodel["model_dir"] = "/bundles/base";
    pspec["model"] = pmodel;
    h.store.Create("InferenceService", "parent", pspec);
    Json pstatus = Json::Object();
    Json reps = Json::Array();
    Json r0 = Json::Object();
    r0["port"] = 9001;
    r0["pid"] = 111;
    r0["ready"] = true;
    Json r1 = Json::Object();
    r1["port"] = 9002;
    r1["pid"] = 112;
    r1["ready"] = false;
    reps.push_back(r0);
    reps.push_back(r1);
    pstatus["replicaState"] = reps;
    h.store.UpdateStatus("InferenceService", "parent", pstatus);

    h.probe.posts.clear();
    tm.Tick(h.now);
    r = h.store.Get("TrainedModel", "tm1");
    // Async protocol: load POSTed (202), not yet ready → Pending.
    CHECK(r->status.get("phase").as_string() == "Pending");
    CHECK(h.probe.posts.size() == 1);
    CHECK(h.probe.posts[0].port == 9001);
    CHECK(h.probe.posts[0].path == "/v2/repository/models/extra/load");
    CHECK(h.probe.posts[0].payload.find("/bundles/extra") !=
          std::string::npos);

    // In flight: a second tick does NOT re-post (60s repost window).
    tm.Tick(h.now);
    CHECK(h.probe.posts.size() == 1);

    // The async load lands (model readiness turns 200) → Ready.
    h.probe.model_ready[{9001, "extra"}] = "/bundles/extra";
    tm.Tick(h.now);
    r = h.store.Get("TrainedModel", "tm1");
    CHECK(r->status.get("phase").as_string() == "Ready");
    CHECK(h.probe.posts.size() == 1);

    // Readiness blip: replica goes unready and back — NO reload.
    r0["ready"] = false;
    reps = Json::Array();
    reps.push_back(r0);
    reps.push_back(r1);
    pstatus["replicaState"] = reps;
    h.store.UpdateStatus("InferenceService", "parent", pstatus);
    tm.Tick(h.now);
    r0["ready"] = true;
    reps = Json::Array();
    reps.push_back(r0);
    reps.push_back(r1);
    pstatus["replicaState"] = reps;
    h.store.UpdateStatus("InferenceService", "parent", pstatus);
    tm.Tick(h.now);
    r = h.store.Get("TrainedModel", "tm1");
    CHECK(r->status.get("phase").as_string() == "Ready");
    CHECK(h.probe.posts.size() == 1);  // state kept across the blip

    // Replica restart (same port, new pid) → re-load (fresh server lost
    // the model; its readiness probe is cleared too).
    h.probe.model_ready.clear();
    r0["pid"] = 222;
    reps = Json::Array();
    reps.push_back(r0);
    reps.push_back(r1);
    pstatus["replicaState"] = reps;
    h.store.UpdateStatus("InferenceService", "parent", pstatus);
    tm.Tick(h.now);
    CHECK(h.probe.posts.size() == 2);
    h.probe.model_ready[{9001, "extra"}] = "/bundles/extra";
    tm.Tick(h.now);
    r = h.store.Get("TrainedModel", "tm1");
    CHECK(r->status.get("phase").as_string() == "Ready");

    // Second replica becomes ready → loads there; unreachable retries.
    r1["ready"] = true;
    reps = Json::Array();
    reps.push_back(r0);
    reps.push_back(r1);
    pstatus["replicaState"] = reps;
    h.store.UpdateStatus("InferenceService", "parent", pstatus);
    h.probe.post_unreachable.insert(9002);
    tm.Tick(h.now);
    r = h.store.Get("TrainedModel", "tm1");
    CHECK(r->status.get("phase").as_string() == "Pending");  // 1/2 loaded
    CHECK(tm.metrics().load_failures >= 1);
    h.probe.post_unreachable.clear();
    h.probe.model_ready[{9002, "extra"}] = "/bundles/extra";
    tm.Tick(h.now);  // posts the load
    tm.Tick(h.now);  // observes readiness
    r = h.store.Get("TrainedModel", "tm1");
    CHECK(r->status.get("phase").as_string() == "Ready");
    CHECK(r->status.get("replicas").get("loaded").as_int(0) == 2);

    // model_dir change (spec update) → digest changes → re-load on live
    // replicas, not silent staleness.
    h.probe.posts.clear();
    h.probe.model_ready.clear();
    Json spec2 = h.store.Get("TrainedModel", "tm1")->spec;
    spec2["model"]["model_dir"] = "/bundles/extra-v2";
    h.store.UpdateSpec("TrainedModel", "tm1", spec2);
    tm.Tick(h.now);
    r = h.store.Get("TrainedModel", "tm1");
    CHECK(r->status.get("phase").as_string() == "Pending");
    CHECK(h.probe.posts.size() == 2);  // both replicas reload
    CHECK(h.probe.posts[0].payload.find("extra-v2") != std::string::npos);

    // Collision with the parent's base model name → Failed, no posts.
    Json cspec = Json::Object();
    Json cmodel = Json::Object();
    cmodel["name"] = "base";
    cmodel["model_dir"] = "/bundles/x";
    cspec["inference_service"] = "parent";
    cspec["model"] = cmodel;
    h.store.Create("TrainedModel", "clash", cspec);
    h.probe.posts.clear();
    tm.Tick(h.now);
    CHECK(h.store.Get("TrainedModel", "clash")->status.get("phase")
              .as_string() == "Failed");
    CHECK(h.probe.posts.empty() ||
          h.probe.posts[0].path.find("/base/") == std::string::npos);
    h.store.Delete("TrainedModel", "clash");

    // Delete → unload posted to every ready replica.
    h.probe.posts.clear();
    auto res = *h.store.Get("TrainedModel", "tm1");
    h.store.Delete("TrainedModel", "tm1");
    tm.OnDeleted(res);
    CHECK(h.probe.posts.size() == 2);
    CHECK(h.probe.posts[0].path == "/v2/repository/models/extra/unload");
    CHECK(tm.metrics().unloads == 2);
  }

  printf("test_serve_ctl OK\n");
  return 0;
}
