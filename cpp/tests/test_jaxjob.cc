// JAXJob controller semantics against the FakeExecutor — the envtest analog
// (SURVEY.md §4.2): no processes start; tests flip process status by hand
// and assert on the conditions state machine, gang atomicity, restart
// policies, backoff, deadlines, and TTL GC.
#include <cstdio>

#include "admission.h"
#include "events.h"
#include "executor.h"
#include "jaxjob.h"
#include "scheduler.h"
#include "store.h"

using tpk::FakeExecutor;
using tpk::JaxJobController;
using tpk::Json;
using tpk::Scheduler;
using tpk::Store;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

namespace {

std::string Phase(Store& store, const std::string& name) {
  auto r = store.Get("JAXJob", name);
  return r ? r->status.get("phase").as_string() : "<gone>";
}

Json BaseSpec(int replicas) {
  Json spec = Json::Object();
  spec["replicas"] = replicas;
  spec["devices_per_proc"] = 1;
  return spec;
}

struct Harness {
  Store store;
  Scheduler sched;
  FakeExecutor exec;
  JaxJobController ctl{&store, &exec, &sched, "/tmp/tpk_test_ctl"};
  double now = 1000.0;

  Harness(int capacity = 8) { sched.AddSlice("local", capacity); }

  void Settle() {
    // Drive watch → reconcile → watch until quiescent (bounded).
    for (int i = 0; i < 10; ++i) {
      ctl.Tick(now);
      std::vector<std::string> dirty;
      int w = store.Watch("JAXJob", [&dirty](const tpk::WatchEvent& ev) {
        dirty.push_back(ev.resource.name);
      });
      int n = store.DrainWatches();
      store.Unwatch(w);
      for (const auto& d : dirty) ctl.Reconcile(d);
      if (n == 0) break;
    }
  }
};

}  // namespace

int main() {
  // --- Happy path: create → Running → all succeed → Succeeded ----------
  {
    Harness h;
    h.store.Create("JAXJob", "j1", BaseSpec(2));
    h.Settle();
    CHECK(Phase(h.store, "j1") == "Running");
    CHECK(h.exec.launched.size() == 2);
    // env contract injected
    CHECK(h.exec.launched[0].env.at("TPK_NUM_PROCS") == "2");
    CHECK(h.exec.launched[0].env.at("TPK_PROC_ID") == "0");
    CHECK(h.exec.launched[1].env.at("TPK_PROC_ID") == "1");
    CHECK(h.exec.launched[0].env.count("TPK_COORDINATOR") == 1);

    h.exec.Finish("j1/0", 0);
    h.Settle();
    CHECK(Phase(h.store, "j1") == "Running");  // one worker still up
    h.exec.Finish("j1/1", 0);
    h.Settle();
    CHECK(Phase(h.store, "j1") == "Succeeded");
    CHECK(h.ctl.metrics().jobs_succeeded == 1);
    // Allocation released.
    CHECK(h.sched.Slices()[0].used == 0);
  }

  // --- Gang pending when capacity insufficient, runs after release -----
  {
    Harness h(4);
    h.store.Create("JAXJob", "big", BaseSpec(3));
    Json small = BaseSpec(2);
    h.store.Create("JAXJob", "small", small);
    h.Settle();
    // big took 3 of 4; small can't fit its gang of 2 → Pending, NOT partial.
    CHECK(Phase(h.store, "big") == "Running");
    CHECK(Phase(h.store, "small") == "Pending");
    CHECK(h.exec.launched.size() == 3);  // no partial gang

    h.exec.Finish("big/0", 0);
    h.exec.Finish("big/1", 0);
    h.exec.Finish("big/2", 0);
    h.Settle();
    CHECK(Phase(h.store, "big") == "Succeeded");
    CHECK(Phase(h.store, "small") == "Running");
  }

  // --- OnFailure: worker dies → gang killed → restart → backoff limit --
  {
    Harness h;
    Json spec = BaseSpec(2);
    spec["restart_policy"] = "OnFailure";
    spec["backoff_limit"] = 1;
    h.store.Create("JAXJob", "flaky", spec);
    h.Settle();
    CHECK(Phase(h.store, "flaky") == "Running");

    h.exec.Finish("flaky/0", 1);
    h.Settle();
    // Restarted once: peer killed, new gang launched (4 launches total).
    CHECK(Phase(h.store, "flaky") == "Running");
    CHECK(h.exec.killed.size() >= 1);
    CHECK(h.exec.launched.size() == 4);
    auto r = h.store.Get("JAXJob", "flaky");
    CHECK(r->status.get("restarts").as_int() == 1);

    h.exec.Finish("flaky/1", 1);
    h.Settle();
    CHECK(Phase(h.store, "flaky") == "Failed");  // backoff exhausted
    CHECK(h.ctl.metrics().jobs_failed == 1);
    CHECK(h.sched.Slices()[0].used == 0);
  }

  // --- Never policy: first failure is terminal -------------------------
  {
    Harness h;
    Json spec = BaseSpec(2);
    spec["restart_policy"] = "Never";
    h.store.Create("JAXJob", "oneshot", spec);
    h.Settle();
    h.exec.Finish("oneshot/0", 2);
    h.Settle();
    CHECK(Phase(h.store, "oneshot") == "Failed");
    CHECK(h.exec.launched.size() == 2);  // no relaunch
  }

  // --- ExitCode policy: 1–127 permanent, 128+ retryable ----------------
  {
    Harness h;
    Json spec = BaseSpec(1);
    spec["restart_policy"] = "ExitCode";
    spec["backoff_limit"] = 5;
    h.store.Create("JAXJob", "sigkilled", spec);
    h.Settle();
    h.exec.Finish("sigkilled/0", 137);  // SIGKILL → retryable
    h.Settle();
    CHECK(Phase(h.store, "sigkilled") == "Running");
    auto r = h.store.Get("JAXJob", "sigkilled");
    CHECK(r->status.get("restarts").as_int() == 1);

    h.exec.Finish("sigkilled/0", 3);  // app error → permanent
    h.Settle();
    CHECK(Phase(h.store, "sigkilled") == "Failed");
  }

  // --- Launch failure: allocation released, job Pending ----------------
  {
    Harness h;
    h.exec.fail_next_launch = true;
    h.store.Create("JAXJob", "nolaunch", BaseSpec(2));
    h.ctl.Reconcile("nolaunch");
    CHECK(Phase(h.store, "nolaunch") == "Pending");
    CHECK(h.sched.Slices()[0].used == 0);
    // Next reconcile pass succeeds.
    h.Settle();
    CHECK(Phase(h.store, "nolaunch") == "Running");
  }

  // --- activeDeadlineSeconds → Failed; TTL → deleted --------------------
  {
    Harness h;
    Json spec = BaseSpec(1);
    spec["active_deadline_seconds"] = 10;
    spec["ttl_seconds_after_finished"] = 5;
    h.store.Create("JAXJob", "slow", spec);
    h.Settle();
    CHECK(Phase(h.store, "slow") == "Running");
    h.now += 11;
    h.Settle();
    CHECK(Phase(h.store, "slow") == "Failed");
    CHECK(h.exec.killed.size() >= 1);
    h.now += 6;
    h.Settle();
    CHECK(!h.store.Get("JAXJob", "slow").has_value());  // GC'd
  }

  // --- Delete of a Running job kills the gang + releases devices --------
  {
    Harness h;
    h.store.Create("JAXJob", "doomed", BaseSpec(2));
    h.Settle();
    CHECK(Phase(h.store, "doomed") == "Running");
    CHECK(h.sched.Slices()[0].used == 2);

    auto r = h.store.Delete("JAXJob", "doomed");
    CHECK(r.ok);
    h.ctl.OnDeleted(r.resource);  // what main.cc's watch does on kDeleted
    CHECK(h.exec.killed.size() == 2);
    CHECK(h.sched.Slices()[0].used == 0);
  }

  // --- Namespace device quota (Profile stub, SURVEY.md §2.5/§7.4) -------
  {
    Harness h;  // 8 local devices
    Json prof = Json::Object();
    prof["max_devices"] = 4;
    h.store.Create("Profile", "team-a", prof);

    Json a = BaseSpec(4);  // 4 devices in team-a: fills the quota
    a["namespace"] = "team-a";
    h.store.Create("JAXJob", "qa", a);
    h.Settle();
    CHECK(Phase(h.store, "qa") == "Running");

    Json b = BaseSpec(2);  // 2 more in team-a: over quota despite capacity
    b["namespace"] = "team-a";
    h.store.Create("JAXJob", "qb", b);
    h.Settle();
    CHECK(Phase(h.store, "qb") == "Pending");
    {
      auto r = h.store.Get("JAXJob", "qb");
      const Json& conds = r->status.get("conditions");
      CHECK(conds.size() > 0);
      CHECK(conds.elements()[conds.size() - 1].get("reason").as_string() ==
            "QuotaExceeded");
    }

    Json c = BaseSpec(2);  // other namespaces are unconstrained
    c["namespace"] = "team-b";
    h.store.Create("JAXJob", "qc", c);
    h.Settle();
    CHECK(Phase(h.store, "qc") == "Running");

    // Freeing team-a capacity lets the queued job launch.
    h.store.Delete("JAXJob", "qa");
    h.Settle();
    h.ctl.Tick(h.now + 10);
    h.Settle();
    CHECK(Phase(h.store, "qb") == "Running");
  }

  printf("test_jaxjob OK\n");
  // --- Elastic: downsize past backoff, upsize on freed capacity --------
  {
    Harness h;
    Json spec = BaseSpec(2);
    spec["backoff_limit"] = 0;
    Json el = Json::Object();
    el["min"] = 1;
    spec["elastic"] = el;
    h.store.Create("JAXJob", "je", spec);
    h.Settle();
    CHECK(Phase(h.store, "je") == "Running");
    CHECK(h.exec.launched.size() == 2);

    // Worker death past the (zero) backoff budget: the job must NOT
    // fail — it downsizes to 1 and resumes (VERDICT r3 item 7 e2e shape).
    h.exec.Finish("je/1", 137);
    h.Settle();
    CHECK(Phase(h.store, "je") == "Running");
    auto r = h.store.Get("JAXJob", "je");
    CHECK(r->status.get("effectiveReplicas").as_int() == 1);
    CHECK(h.exec.launched.size() == 3);  // 2 original + 1 downsized
    CHECK(h.exec.launched[2].env.at("TPK_NUM_PROCS") == "1");
    CHECK(h.sched.Slices()[0].used == 1);
    CHECK(h.ctl.metrics().elastic_resizes == 1);
    CHECK(h.ctl.metrics().jobs_failed == 0);

    // Capacity is free again: after the upsize cooldown the gang grows
    // back to the desired size and resumes from checkpoint.
    h.now += 31;
    h.Settle();
    r = h.store.Get("JAXJob", "je");
    CHECK(r->status.get("effectiveReplicas").as_int() == 2);
    CHECK(Phase(h.store, "je") == "Running");
    CHECK(h.exec.launched.size() == 5);  // + 2 upsized workers
    CHECK(h.exec.launched.back().env.at("TPK_NUM_PROCS") == "2");
    CHECK(h.ctl.metrics().elastic_resizes == 2);

    h.exec.Finish("je/0", 0);
    h.exec.Finish("je/1", 0);
    h.Settle();
    CHECK(Phase(h.store, "je") == "Succeeded");
  }

  // --- Elastic: downsize when the full gang never fits -----------------
  {
    Harness h(1);  // capacity 1 device
    Json spec = BaseSpec(2);
    Json el = Json::Object();
    el["min"] = 1;
    spec["elastic"] = el;
    h.store.Create("JAXJob", "js", spec);
    h.Settle();
    CHECK(Phase(h.store, "js") == "Running");
    auto r = h.store.Get("JAXJob", "js");
    CHECK(r->status.get("effectiveReplicas").as_int() == 1);
    CHECK(h.exec.launched.size() == 1);
  }

  // --- Elastic: downsize counts as an attempt (fault gating) ------------
  {
    Harness h;
    Json spec = BaseSpec(2);
    spec["backoff_limit"] = 0;
    Json el = Json::Object();
    el["min"] = 1;
    spec["elastic"] = el;
    Json fault = Json::Object();
    fault["proc"] = 0;
    fault["step"] = 5;
    spec["fault"] = fault;
    h.store.Create("JAXJob", "jfault", spec);
    h.Settle();
    CHECK(h.exec.launched[0].env.count("TPK_FAULT") == 1);  // first attempt
    h.exec.Finish("jfault/0", 137);
    h.Settle();
    CHECK(Phase(h.store, "jfault") == "Running");
    auto r = h.store.Get("JAXJob", "jfault");
    CHECK(r->status.get("effectiveReplicas").as_int() == 1);
    CHECK(r->status.get("restarts").as_int() == 1);  // attempt consumed
    // The relaunched worker 0 must NOT get the fault re-armed — the
    // default is first-attempt-only, and the downsize WAS an attempt.
    CHECK(h.exec.launched.size() == 3);
    CHECK(h.exec.launched[2].env.count("TPK_FAULT") == 0);
  }

  // --- Elastic: upsize probes a REAL allocation (fragmentation-safe) ---
  {
    Harness h(1);           // slice "local" capacity 1
    h.sched.AddSlice("b", 1);  // + slice "b" capacity 1: 2 free total,
                               // but no single slice can host 2
    Json spec = BaseSpec(2);
    Json el = Json::Object();
    el["min"] = 1;
    spec["elastic"] = el;
    h.store.Create("JAXJob", "jfrag", spec);
    h.Settle();
    auto r = h.store.Get("JAXJob", "jfrag");
    CHECK(r->status.get("effectiveReplicas").as_int() == 1);  // downsized
    CHECK(Phase(h.store, "jfrag") == "Running");
    size_t launches = h.exec.launched.size();
    // Past the cooldown, the free-device SUM (1 free + 1 held = 2) would
    // suggest an upsize — but no allocation of 2-on-one-slice exists, so
    // the healthy gang must NOT be killed.
    h.now += 31;
    h.Settle();
    CHECK(Phase(h.store, "jfrag") == "Running");
    CHECK(h.exec.launched.size() == launches);  // no kill/relaunch churn
    CHECK(h.store.Get("JAXJob", "jfrag")
              ->status.get("effectiveReplicas").as_int() == 1);
    // Books restored: exactly one device still held.
    int used = 0;
    for (const auto& s : h.sched.Slices()) used += s.used;
    CHECK(used == 1);
  }

  // --- Elastic: without the policy, past-backoff death still fails -----
  {
    Harness h;
    Json spec = BaseSpec(2);
    spec["backoff_limit"] = 0;
    h.store.Create("JAXJob", "jf", spec);
    h.Settle();
    h.exec.Finish("jf/1", 137);
    h.Settle();
    CHECK(Phase(h.store, "jf") == "Failed");
  }

  // --- Elastic admission ------------------------------------------------
  {
    Json spec = BaseSpec(2);
    Json el = Json::Object();
    el["min"] = 0;
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["min"] = 3;  // > replicas
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["min"] = 1;
    el["max"] = 5;  // > replicas
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["max"] = 1.5;  // non-integral
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    Json huge = Json::Object();
    huge["min"] = 1e300;  // beyond int64: UB-guarded rejection
    spec["elastic"] = huge;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["max"] = 2;
    el["heartbeat_timeout_s"] = -1;
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["heartbeat_timeout_s"] = 5;
    spec["elastic"] = el;
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());
  }

  // --- LoRA admission ---------------------------------------------------
  {
    Json spec = BaseSpec(1);
    Json rt = Json::Object();
    rt["model"] = std::string("llama_tiny");
    Json lora = Json::Object();
    spec["runtime"] = rt;
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());
    // {} = disabled (Python falsy semantics): valid.
    rt["lora"] = lora;
    spec["runtime"] = rt;
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());
    // rank required once any knob is set; integral, >= 1
    lora["rank"] = 0;
    rt["lora"] = lora;
    spec["runtime"] = rt;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    lora["rank"] = 2.5;
    rt["lora"] = lora;
    spec["runtime"] = rt;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    lora["rank"] = 8;
    lora["targets"] = std::string("everything");
    rt["lora"] = lora;
    spec["runtime"] = rt;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    lora["targets"] = std::string("attn");
    lora["rnk"] = 4;  // typo'd knob
    rt["lora"] = lora;
    spec["runtime"] = rt;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    Json ok = Json::Object();
    ok["rank"] = 8;
    ok["alpha"] = 16.0;
    ok["targets"] = std::string("attn_mlp");
    rt["lora"] = ok;
    spec["runtime"] = rt;
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());
    // lora x pipeline: refused at submit (no adapter path in stages) —
    // via the pipeline object AND via the real switch, mesh.pipe > 1.
    Json pl = Json::Object();
    pl["microbatches"] = 2;
    rt["pipeline"] = pl;
    spec["runtime"] = rt;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    rt.erase("pipeline");
    Json mesh = Json::Object();
    mesh["pipe"] = 2;
    rt["mesh"] = mesh;
    spec["runtime"] = rt;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
  }

  // --- Structured event log (events.h): ordered lifecycle history -------
  {
    Harness h;
    h.store.Create("JAXJob", "ev", BaseSpec(1));
    h.Settle();
    h.exec.Finish("ev/0", 0);
    h.Settle();
    CHECK(Phase(h.store, "ev") == "Succeeded");
    auto r = h.store.Get("JAXJob", "ev");
    const Json& evs = r->status.get("events");
    CHECK(evs.is_array() && evs.size() >= 4);
    std::vector<std::string> reasons;
    double last_unix = 0;
    for (const auto& e : evs.elements()) {
      reasons.push_back(e.get("reason").as_string());
      CHECK(e.get("unix").as_number() >= last_unix);  // ordered
      last_unix = e.get("unix").as_number();
      CHECK(!e.get("timestamp").as_string().empty());
    }
    auto idx = [&](const std::string& what) {
      for (size_t i = 0; i < reasons.size(); ++i) {
        if (reasons[i] == what) return static_cast<int>(i);
      }
      return -1;
    };
    CHECK(idx("Submitted") == 0);
    CHECK(idx("Scheduled") > idx("Submitted"));
    CHECK(idx("Launched") > idx("Scheduled"));
    CHECK(idx("Succeeded") > idx("Launched"));
  }

  // --- Event dedup: exact repeat = no-op; new message merges ------------
  {
    Json st = Json::Object();
    st = tpk::AppendStatusEvent(st, "Warning", "Unschedulable", "no cap",
                                100.0);
    std::string before = st.dump();
    st = tpk::AppendStatusEvent(st, "Warning", "Unschedulable", "no cap",
                                101.0);
    CHECK(st.dump() == before);  // exact repeat: byte-identical status
    st = tpk::AppendStatusEvent(st, "Warning", "Unschedulable",
                                "still no cap", 102.0);
    CHECK(st.get("events").size() == 1);  // merged, not appended
    const Json& merged = st.get("events").elements()[0];
    CHECK(merged.get("count").as_int() == 2);
    CHECK(merged.get("message").as_string() == "still no cap");
    st = tpk::AppendStatusEvent(st, "Normal", "Scheduled", "ok", 103.0);
    CHECK(st.get("events").size() == 2);  // different reason appends
    // Bounded: the log trims oldest-first past the cap.
    for (int i = 0; i < 2 * static_cast<int>(tpk::kMaxStatusEvents); ++i) {
      st = tpk::AppendStatusEvent(st, "Normal", "R" + std::to_string(i),
                                  "m", 104.0 + i);
    }
    CHECK(st.get("events").size() == tpk::kMaxStatusEvents);
  }

  // --- Unschedulable pend: repeated reconciles must not churn status ----
  {
    Harness h(/*capacity=*/1);
    h.store.Create("JAXJob", "toobig", BaseSpec(4));
    h.Settle();
    CHECK(Phase(h.store, "toobig") == "Pending");
    auto v1 = h.store.Get("JAXJob", "toobig")->resource_version;
    for (int i = 0; i < 5; ++i) h.Settle();  // level-triggered retries
    auto v2 = h.store.Get("JAXJob", "toobig")->resource_version;
    CHECK(v1 == v2);  // event dedup kept the status write-free
  }

  // --- fsdp elasticity: the resize unit is the mesh axis ----------------
  // Spec shape: 1 proc x 4 devices, runtime.fsdp=4, min_fsdp=1 — the
  // CPU-provable topology (a single proc virtualizes its devices).
  auto FsdpSpec = [] {
    Json spec = BaseSpec(1);
    spec["devices_per_proc"] = 4;
    spec["cpu_devices_per_proc"] = 4;
    spec["backoff_limit"] = 0;
    Json rt = Json::Object();
    rt["fsdp"] = 4;
    rt["steps"] = 8;
    spec["runtime"] = rt;
    Json el = Json::Object();
    el["min_fsdp"] = 1;
    spec["elastic"] = el;
    return spec;
  };

  // --- fsdp downsize past backoff: 4 -> 2 -> 1, then Failed -------------
  {
    Harness h;
    Json spec = FsdpSpec();
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());
    h.store.Create("JAXJob", "jfsdp", spec);
    h.Settle();
    CHECK(Phase(h.store, "jfsdp") == "Running");
    CHECK(h.exec.launched.size() == 1);
    CHECK(h.sched.Slices()[0].used == 4);

    // SIGKILL (137 = retryable) past the zero backoff: the job must NOT
    // fail — it reshards to the next divisor down and relaunches.
    h.exec.Finish("jfsdp/0", 137);
    h.Settle();
    CHECK(Phase(h.store, "jfsdp") == "Running");
    auto r = h.store.Get("JAXJob", "jfsdp");
    CHECK(r->status.get("effectiveFsdp").as_int() == 2);
    CHECK(r->status.get("restarts").as_int() == 1);  // attempt consumed
    CHECK(h.exec.launched.size() == 2);
    CHECK(h.sched.Slices()[0].used == 2);  // downsized gang holds less
    // The worker learns the new topology through its launch shape: the
    // virtual-device count scales with the per-proc device share.
    {
      const auto& argv = h.exec.launched[1].argv;
      bool saw = false;
      for (size_t i = 0; i + 1 < argv.size(); ++i) {
        if (argv[i] == "--cpu-devices") {
          saw = true;
          CHECK(argv[i + 1] == "2");
        }
      }
      CHECK(saw);
    }
    // ...and through runtime.json, rewritten with the resized fsdp.
    {
      FILE* f = fopen("/tmp/tpk_test_ctl/jfsdp/runtime.json", "r");
      CHECK(f != nullptr);
      char buf[4096];
      size_t n = fread(buf, 1, sizeof(buf) - 1, f);
      fclose(f);
      buf[n] = '\0';
      Json rt = Json::parse(buf);
      CHECK(rt.get("fsdp").as_int() == 2);
      CHECK(rt.get("steps").as_int() == 8);  // rest of runtime intact
    }
    CHECK(h.ctl.metrics().elastic_resizes == 1);

    // Second death: 2 -> 1 (min_fsdp floor).
    h.exec.Finish("jfsdp/0", 137);
    h.Settle();
    CHECK(Phase(h.store, "jfsdp") == "Running");
    r = h.store.Get("JAXJob", "jfsdp");
    CHECK(r->status.get("effectiveFsdp").as_int() == 1);
    CHECK(h.ctl.metrics().elastic_resizes == 2);

    // Event hygiene (satellite of ISSUE 17): the two transitions are
    // TWO entries carrying old -> new topology, count 1 each — the
    // same-reason merge must not collapse distinct resizes.
    {
      const Json& evs = r->status.get("events");
      int down = 0;
      bool saw42 = false, saw21 = false;
      for (const auto& e : evs.elements()) {
        if (e.get("reason").as_string() != "ElasticDownsize") continue;
        down++;
        CHECK(e.get("count").as_int() == 1);
        const std::string& m = e.get("message").as_string();
        if (m.find("fsdp 4 -> 2") != std::string::npos) saw42 = true;
        if (m.find("fsdp 2 -> 1") != std::string::npos) saw21 = true;
      }
      CHECK(down == 2);
      CHECK(saw42 && saw21);
    }

    // At the floor there is nowhere left to shrink: next death fails.
    h.exec.Finish("jfsdp/0", 137);
    h.Settle();
    CHECK(Phase(h.store, "jfsdp") == "Failed");
    CHECK(h.sched.Slices()[0].used == 0);
  }

  // --- fsdp downsize when the full mesh never fits: 4 -> 2 -> 1 ---------
  // Back-to-back capacity step-downs produce NO interleaving events, so
  // this is the path where same-reason merge would have collapsed two
  // distinct transitions into one lying count — pin that they stay two.
  {
    Harness h(1);  // capacity 1 device
    h.store.Create("JAXJob", "jtight", FsdpSpec());
    h.Settle();
    CHECK(Phase(h.store, "jtight") == "Running");
    auto r = h.store.Get("JAXJob", "jtight");
    CHECK(r->status.get("effectiveFsdp").as_int() == 1);
    CHECK(h.exec.launched.size() == 1);
    int down = 0;
    bool saw42 = false, saw21 = false;
    for (const auto& e : r->status.get("events").elements()) {
      if (e.get("reason").as_string() != "ElasticDownsize") continue;
      down++;
      CHECK(e.get("count").as_int() == 1);
      const std::string& m = e.get("message").as_string();
      if (m.find("fsdp 4 -> 2") != std::string::npos) saw42 = true;
      if (m.find("fsdp 2 -> 1") != std::string::npos) saw21 = true;
    }
    CHECK(down == 2);
    CHECK(saw42 && saw21);
  }

  // --- fsdp upsize: regrow to a bigger divisor past the cooldown --------
  {
    Harness h;
    h.store.Create("JAXJob", "jgrow", FsdpSpec());
    h.Settle();
    h.exec.Finish("jgrow/0", 137);
    h.Settle();
    auto r = h.store.Get("JAXJob", "jgrow");
    CHECK(r->status.get("effectiveFsdp").as_int() == 2);
    CHECK(Phase(h.store, "jgrow") == "Running");

    h.now += 31;  // past the 30s default upsize cooldown
    h.Settle();
    r = h.store.Get("JAXJob", "jgrow");
    CHECK(r->status.get("effectiveFsdp").as_int() == 4);
    CHECK(Phase(h.store, "jgrow") == "Running");
    CHECK(h.sched.Slices()[0].used == 4);
    bool saw_up = false;
    for (const auto& e : r->status.get("events").elements()) {
      if (e.get("reason").as_string() == "ElasticUpsize" &&
          e.get("message").as_string().find("fsdp 2 -> 4") !=
              std::string::npos) {
        saw_up = true;
      }
    }
    CHECK(saw_up);
  }

  // --- fsdp explicit resize request: target_fsdp fires exactly once -----
  {
    Harness h;
    Json spec = FsdpSpec();
    h.store.Create("JAXJob", "jreq", spec);
    h.Settle();
    CHECK(Phase(h.store, "jreq") == "Running");
    size_t launches = h.exec.launched.size();

    Json el = Json::Object();
    el["min_fsdp"] = 1;
    el["target_fsdp"] = 2;
    el["resize_policy"] = std::string("manual");
    spec["elastic"] = el;
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());
    CHECK(h.store.UpdateSpec("JAXJob", "jreq", spec).ok);
    h.Settle();
    auto r = h.store.Get("JAXJob", "jreq");
    CHECK(r->status.get("effectiveFsdp").as_int() == 2);
    CHECK(Phase(h.store, "jreq") == "Running");
    CHECK(h.exec.launched.size() == launches + 1);
    bool saw_req = false;
    for (const auto& e : r->status.get("events").elements()) {
      if (e.get("reason").as_string() == "ElasticResizeRequested" &&
          e.get("message").as_string().find("fsdp 4 -> 2") !=
              std::string::npos) {
        saw_req = true;
      }
    }
    CHECK(saw_req);

    // The latch: the same target must not re-fire (no kill churn), and
    // manual policy means no automatic regrow past the cooldown either.
    launches = h.exec.launched.size();
    h.now += 61;
    h.Settle();
    r = h.store.Get("JAXJob", "jreq");
    CHECK(r->status.get("effectiveFsdp").as_int() == 2);
    CHECK(h.exec.launched.size() == launches);
  }

  // --- fsdp elastic admission -------------------------------------------
  {
    Json spec = FsdpSpec();
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());
    Json el = Json::Object();

    el["min_fsdp"] = 1;
    el["min"] = 1;  // replica + fsdp elasticity: mutually exclusive
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el.erase("min");

    Json norust = FsdpSpec();  // min_fsdp without runtime.fsdp
    Json rt0 = Json::Object();
    rt0["steps"] = 8;
    norust["runtime"] = rt0;
    CHECK(!tpk::ValidateSpec("JAXJob", norust).empty());

    Json badshape = FsdpSpec();  // fsdp != replicas * devices_per_proc
    badshape["devices_per_proc"] = 2;
    CHECK(!tpk::ValidateSpec("JAXJob", badshape).empty());

    el["min_fsdp"] = 5;  // > runtime.fsdp
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["min_fsdp"] = 1;

    el["max_fsdp"] = 6;  // not a multiple of runtime.fsdp
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["max_fsdp"] = 8;
    spec["elastic"] = el;
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());

    el["target_fsdp"] = 3;  // not a divisor of max_fsdp
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["target_fsdp"] = 2;
    spec["elastic"] = el;
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());

    el["resize_policy"] = std::string("sometimes");
    spec["elastic"] = el;
    CHECK(!tpk::ValidateSpec("JAXJob", spec).empty());
    el["resize_policy"] = std::string("manual");
    spec["elastic"] = el;
    CHECK(tpk::ValidateSpec("JAXJob", spec).empty());

    Json orphan = BaseSpec(2);  // fsdp-only knobs without min_fsdp
    Json el2 = Json::Object();
    el2["min"] = 1;
    el2["max_fsdp"] = 8;
    orphan["elastic"] = el2;
    CHECK(!tpk::ValidateSpec("JAXJob", orphan).empty());
  }

  // --- AppendStatusEvent merge_same_reason=false: transitions stay ------
  {
    Json st = Json::Object();
    st = tpk::AppendStatusEvent(st, "Normal", "ElasticDownsize",
                                "fsdp 4 -> 2", 100.0,
                                /*merge_same_reason=*/false);
    std::string before = st.dump();
    // Exact repeat is still a no-op (level-triggered reconciles).
    st = tpk::AppendStatusEvent(st, "Normal", "ElasticDownsize",
                                "fsdp 4 -> 2", 101.0,
                                /*merge_same_reason=*/false);
    CHECK(st.dump() == before);
    // A DISTINCT transition with the same reason appends, never merges.
    st = tpk::AppendStatusEvent(st, "Normal", "ElasticDownsize",
                                "fsdp 2 -> 1", 102.0,
                                /*merge_same_reason=*/false);
    CHECK(st.get("events").size() == 2);
    CHECK(st.get("events").elements()[0].get("count").as_int() == 1);
    CHECK(st.get("events").elements()[1].get("count").as_int() == 1);
  }

  return 0;
}
