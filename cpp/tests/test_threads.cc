// Threaded stress over the components that claim thread safety: the Store
// (mutex-guarded CRUD + CAS + WAL append) and the LocalExecutor (spawn /
// status / reap from different threads). Built to run under
// -DTPK_SANITIZE=thread — the `go test -race` analog the reference runs in
// CI (SURVEY.md §5.2). Watch *delivery* (DrainWatches) stays on the owning
// event loop by design; enqueueing from writer threads is exercised here.

#include <atomic>
#include <cassert>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

#include "executor.h"
#include "store.h"

using tpk::Json;
using tpk::LaunchSpec;
using tpk::LocalExecutor;
using tpk::ProcessStatus;
using tpk::Store;

static void TestStoreConcurrentCrud() {
  Store store;
  constexpr int kThreads = 4;
  constexpr int kOps = 300;
  std::atomic<int> created{0}, cas_conflicts{0};

  // A shared resource every thread CASes against.
  assert(store.Create("Job", "shared", Json::Object()).ok);

  std::vector<std::thread> workers;
  for (int t = 0; t < kThreads; ++t) {
    workers.emplace_back([&, t]() {
      for (int i = 0; i < kOps; ++i) {
        const std::string name =
            "job-" + std::to_string(t) + "-" + std::to_string(i);
        Json spec = Json::Object();
        spec["idx"] = i;
        if (store.Create("Job", name, spec).ok) created++;
        auto got = store.Get("Job", name);
        assert(got && got->spec.get("idx").as_int() == i);
        Json status = Json::Object();
        status["phase"] = "Running";
        assert(store.UpdateStatus("Job", name, status).ok);
        // CAS on the shared resource: conflicts are expected, corruption
        // is not.
        auto cur = store.Get("Job", "shared");
        assert(cur);
        Json s2 = Json::Object();
        s2["winner"] = t;
        auto r = store.UpdateSpec("Job", "shared", s2, cur->resource_version);
        if (!r.ok) cas_conflicts++;
        if (i % 3 == 0) assert(store.Delete("Job", name).ok);
        (void)store.List("Job");
      }
    });
  }
  // Concurrent readers while writers run.
  std::atomic<bool> stop{false};
  std::thread reader([&]() {
    while (!stop.load()) {
      (void)store.List("Job");
      (void)store.Get("Job", "shared");
    }
  });
  for (auto& w : workers) w.join();
  stop.store(true);
  reader.join();

  assert(created.load() == kThreads * kOps);
  // 2/3 of created jobs survive per thread.
  size_t expect = 1 + kThreads * (kOps - (kOps + 2) / 3);
  assert(store.List("Job").size() == expect);
  printf("store: %d creates, %d CAS conflicts, %zu live\n", created.load(),
         cas_conflicts.load(), store.List("Job").size());
}

static void TestExecutorConcurrentStatusPoll() {
  LocalExecutor exec;
  constexpr int kGangs = 8;
  for (int g = 0; g < kGangs; ++g) {
    std::vector<LaunchSpec> specs;
    LaunchSpec s;
    s.id = "gang" + std::to_string(g) + "/0";
    s.argv = {"/bin/sh", "-c", "exit 0"};
    specs.push_back(s);
    std::string error;
    assert(exec.LaunchGang(specs, &error));
  }
  std::atomic<bool> stop{false};
  std::thread statuser([&]() {
    while (!stop.load()) {
      for (int g = 0; g < kGangs; ++g) {
        (void)exec.Status("gang" + std::to_string(g) + "/0");
      }
    }
  });
  int done = 0;
  for (int spins = 0; done < kGangs && spins < 20000; ++spins) {
    done += static_cast<int>(exec.Poll().size());
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  stop.store(true);
  statuser.join();
  assert(done == kGangs);
  for (int g = 0; g < kGangs; ++g) {
    auto st = exec.Status("gang" + std::to_string(g) + "/0");
    assert(st.phase == ProcessStatus::Phase::kSucceeded);
  }
  printf("executor: %d gangs reaped under concurrent Status()\n", done);
}

int main() {
  TestStoreConcurrentCrud();
  TestExecutorConcurrentStatusPoll();
  printf("test_threads: OK\n");
  return 0;
}
