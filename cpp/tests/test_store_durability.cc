// Durability tests for the hardened WAL: framed records (CRC32 + seq),
// legacy plain-JSONL replay, mid-file corruption detection, checked write
// errors (disk full must not diverge memory from disk), snapshot +
// compaction, fsync policy, and the crash window between snapshot rename
// and WAL truncate. Runs under the ASan/TSan matrix like every store test.
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <fstream>
#include <sstream>
#include <string>

#include "store.h"

using tpk::Json;
using tpk::Store;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

namespace {

std::string ReadFile(const std::string& path) {
  std::ifstream in(path, std::ios::binary);
  std::ostringstream os;
  os << in.rdbuf();
  return os.str();
}

void WriteFile(const std::string& path, const std::string& content) {
  std::ofstream out(path, std::ios::binary | std::ios::trunc);
  out << content;
}

}  // namespace

int main() {
  // Legacy plain-JSONL WALs (pre-framing) still replay, and new appends
  // onto them are framed — a mixed file replays end to end.
  {
    std::string wal = "/tmp/tpk_dur_legacy.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    WriteFile(wal,
              "{\"kind\":\"JAXJob\",\"name\":\"old1\",\"spec\":{\"v\":1},"
              "\"status\":{},\"resourceVersion\":1,\"generation\":1}\n"
              "{\"kind\":\"JAXJob\",\"name\":\"old2\",\"spec\":{\"v\":2},"
              "\"status\":{},\"resourceVersion\":2,\"generation\":1}\n");
    {
      Store s(wal);
      CHECK(s.Load() == 2);
      CHECK(s.load_stats().clean);
      CHECK(s.Get("JAXJob", "old1").has_value());
      // New append is framed and versions continue past the legacy ones.
      auto r = s.Create("JAXJob", "new1", Json::Object());
      CHECK(r.ok && r.resource.resource_version == 3);
    }
    std::string content = ReadFile(wal);
    CHECK(content.find("v1 ") != std::string::npos);  // framed append landed
    Store s2(wal);
    CHECK(s2.Load() == 3);
    CHECK(s2.Get("JAXJob", "new1").has_value());
    std::remove(wal.c_str());
  }

  // Mid-file corruption (bit flip on a COMPLETE line) is loud — replay
  // stops early, clean=false with an error, and the file is truncated to
  // the last good record so the next replay is consistent.
  {
    std::string wal = "/tmp/tpk_dur_corrupt.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    {
      Store w(wal);
      Json spec = Json::Object();
      spec["payload"] = "aaaaaaaaaaaaaaaa";
      CHECK(w.Create("JAXJob", "r1", spec).ok);
      CHECK(w.Create("JAXJob", "r2", spec).ok);
      CHECK(w.Create("JAXJob", "r3", spec).ok);
    }
    std::string content = ReadFile(wal);
    size_t second = content.find("\n") + 1;
    size_t flip = content.find("aaaa", second);
    CHECK(flip != std::string::npos);
    content[flip] = 'b';  // CRC now mismatches on record 2
    WriteFile(wal, content);
    {
      Store r(wal);
      CHECK(r.Load() == 1);
      CHECK(!r.load_stats().clean);  // stopped EARLY, not a clean EOF
      CHECK(r.load_stats().error.find("crc mismatch") != std::string::npos);
      CHECK(r.load_stats().truncated_bytes > 0);
      CHECK(r.Get("JAXJob", "r1").has_value());
      CHECK(!r.Get("JAXJob", "r2").has_value());
      CHECK(r.Create("JAXJob", "r4", Json::Object()).ok);
    }
    Store r2(wal);
    CHECK(r2.Load() == 2);
    CHECK(r2.load_stats().clean);
    std::remove(wal.c_str());
  }

  // Write errors FAIL the mutation: on a full device the create returns
  // an error and memory stays in sync with disk (nothing applied).
  {
    Store s("/dev/full");
    auto r = s.Create("JAXJob", "doomed", Json::Object());
    CHECK(!r.ok);
    CHECK(r.error.find("wal append failed") != std::string::npos);
    CHECK(!s.Get("JAXJob", "doomed").has_value());
    // Subsequent mutations stay loud too (either retried-and-failed or
    // WAL-broken, depending on whether rollback worked on the device).
    CHECK(!s.Create("JAXJob", "doomed2", Json::Object()).ok);
    CHECK(s.List("").empty());
  }

  // Snapshot + compaction: past the threshold the WAL is folded into
  // <wal>.snap and truncated; replay is snapshot-then-tail with a bounded
  // record count, versions continue monotonically, and stateinfo reports
  // the compaction.
  {
    std::string wal = "/tmp/tpk_dur_compact.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    int64_t last_version = 0;
    {
      Store w(wal);
      w.SetCompactionThreshold(8);
      CHECK(w.Create("JAXJob", "job", Json::Object()).ok);
      for (int i = 0; i < 40; ++i) {  // heartbeat-style status churn
        Json st = Json::Object();
        st["beat"] = i;
        auto r = w.UpdateStatus("JAXJob", "job", st);
        CHECK(r.ok);
        last_version = r.resource.resource_version;
      }
      Json info = w.StateInfo();
      CHECK(info.get("compactions").as_int() >= 1);
      CHECK(info.get("walRecords").as_int() <= 8);
      CHECK(info.get("compactError").is_null());
    }
    struct stat st;
    CHECK(stat((wal + ".snap").c_str(), &st) == 0);  // snapshot exists
    {
      Store r(wal);
      r.SetCompactionThreshold(8);
      int applied = r.Load();
      // Bounded replay: snapshot(1 resource) + short tail, NOT all 41.
      CHECK(applied <= 9);
      CHECK(r.load_stats().snapshot_loaded);
      CHECK(r.load_stats().snapshot_records == 1);
      auto job = r.Get("JAXJob", "job");
      CHECK(job.has_value());
      CHECK(job->resource_version == last_version);
      CHECK(job->status.get("beat").as_int() == 39);
      // resourceVersions keep increasing after a snapshot-based replay.
      auto cr = r.Create("JAXJob", "after", Json::Object());
      CHECK(cr.ok && cr.resource.resource_version > last_version);
      // Watches still see post-replay events (no watch regressions).
      r.DrainWatches();  // flush events queued before the watcher existed
      int events = 0;
      r.Watch("JAXJob", [&events](const tpk::WatchEvent&) { ++events; });
      Json st2 = Json::Object();
      st2["beat"] = 99;
      CHECK(r.UpdateStatus("JAXJob", "job", st2).ok);
      r.DrainWatches();
      CHECK(events == 1);
    }
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
  }

  // Crash window between snapshot rename and WAL truncate: replay stops
  // at the stale tail's sequence regression with EXACTLY the snapshot
  // state — loud, but never doubled or diverged.
  {
    std::string wal = "/tmp/tpk_dur_crashwindow.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    std::string pre_compact_wal;
    {
      Store w(wal);
      CHECK(w.Create("JAXJob", "x", Json::Object()).ok);
      Json st = Json::Object();
      st["phase"] = "Running";
      CHECK(w.UpdateStatus("JAXJob", "x", st).ok);
      pre_compact_wal = ReadFile(wal);
      CHECK(w.Compact(nullptr));
    }
    WriteFile(wal, pre_compact_wal);  // simulate the un-truncated WAL
    Store r(wal);
    CHECK(r.Load() == 1);  // the snapshot's single resource
    CHECK(!r.load_stats().clean);  // stale tail reported, not silent
    auto x = r.Get("JAXJob", "x");
    CHECK(x.has_value());
    CHECK(x->status.get("phase").as_string() == "Running");
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
  }

  // fsync=always exercises the fsync-per-record path on a real fd.
  {
    std::string wal = "/tmp/tpk_dur_fsync.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    {
      Store w(wal);
      w.SetFsync(Store::FsyncPolicy::kAlways);
      CHECK(w.Create("JAXJob", "durable", Json::Object()).ok);
      Json info = w.StateInfo();
      CHECK(info.get("fsync").as_string() == "always");
    }
    Store r(wal);
    CHECK(r.Load() == 1);
    CHECK(r.Get("JAXJob", "durable").has_value());
    std::remove(wal.c_str());
  }

  // Explicit Compact() with an empty tail afterwards still replays the
  // full state (snapshot-only load), and deletes survive compaction.
  {
    std::string wal = "/tmp/tpk_dur_snaponly.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    {
      Store w(wal);
      CHECK(w.Create("JAXJob", "keep", Json::Object()).ok);
      CHECK(w.Create("JAXJob", "gone", Json::Object()).ok);
      CHECK(w.Delete("JAXJob", "gone").ok);
      std::string err;
      CHECK(w.Compact(&err));
    }
    Store r(wal);
    CHECK(r.Load() == 1);
    CHECK(r.load_stats().snapshot_records == 1);
    CHECK(r.load_stats().tail_records == 0);
    CHECK(r.Get("JAXJob", "keep").has_value());
    CHECK(!r.Get("JAXJob", "gone").has_value());
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
  }

  // ---- Group commit (ISSUE 8) ---------------------------------------------

  // Mutations buffer until CommitGroup lands them with one covering
  // fsync; replay sees exactly the committed records, and the WAL bytes
  // are identical to the per-record path's (byte-for-byte parity).
  {
    std::string wal_on = "/tmp/tpk_dur_group_on.jsonl";
    std::string wal_off = "/tmp/tpk_dur_group_off.jsonl";
    for (const auto& w : {wal_on, wal_off}) {
      std::remove(w.c_str());
      std::remove((w + ".snap").c_str());
    }
    auto workload = [](Store& s) {
      Json spec = Json::Object();
      spec["x"] = 1;
      CHECK(s.Create("JAXJob", "a", spec).ok);
      CHECK(s.Create("JAXJob", "b", spec).ok);
      Json st = Json::Object();
      st["phase"] = "Running";
      CHECK(s.UpdateStatus("JAXJob", "a", st).ok);
      CHECK(s.UpdateSpec("JAXJob", "b", spec).ok);
      CHECK(s.Delete("JAXJob", "b").ok);
      return 0;
    };
    {
      Store on(wal_on);
      on.SetFsync(Store::FsyncPolicy::kAlways);
      on.SetGroupCommit(64);
      workload(on);
      CHECK(on.PendingGroupRecords() == 5);
      CHECK(ReadFile(wal_on).empty());  // nothing durable before commit
      CHECK(on.CommitGroup(nullptr));
      CHECK(on.PendingGroupRecords() == 0);
      Json info = on.StateInfo();
      CHECK(info.get("groupCommit").get("commits").as_int() == 1);
      CHECK(info.get("groupCommit").get("records").as_int() == 5);
      CHECK(info.get("groupCommit").get("fsyncs").as_int() == 1);
      CHECK(info.get("groupCommit").get("maxBatchObserved").as_int() == 5);
    }
    {
      Store off(wal_off);
      off.SetFsync(Store::FsyncPolicy::kAlways);
      workload(off);  // per-record path, five fsyncs
    }
    CHECK(ReadFile(wal_on) == ReadFile(wal_off));  // byte-for-byte parity
    Store r(wal_on);
    CHECK(r.Load() == 5);
    CHECK(r.load_stats().clean);
    CHECK(r.Get("JAXJob", "a").has_value());
    CHECK(!r.Get("JAXJob", "b").has_value());
    for (const auto& w : {wal_on, wal_off}) std::remove(w.c_str());
  }

  // A batch torn mid-record (crash during the covering write) truncates
  // to the last durable record — the standard torn-tail discipline at
  // batch granularity.
  {
    std::string wal = "/tmp/tpk_dur_group_torn.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    {
      Store w(wal);
      w.SetGroupCommit(64);
      for (int i = 0; i < 4; ++i) {
        CHECK(w.Create("JAXJob", "j" + std::to_string(i),
                       Json::Object()).ok);
      }
      CHECK(w.CommitGroup(nullptr));
    }
    std::string content = ReadFile(wal);
    WriteFile(wal, content.substr(0, content.size() - 7));  // tear record 4
    Store r(wal);
    CHECK(r.Load() == 3);
    CHECK(r.load_stats().clean);  // torn FINAL record = expected shape
    CHECK(r.load_stats().truncated_bytes > 0);
    CHECK(!r.Get("JAXJob", "j3").has_value());
    std::remove(wal.c_str());
  }

  // Commit failure rolls the WHOLE batch back — memory, versions, and
  // queued watch events — so nothing unacknowledged survives anywhere
  // (the per-record reject-on-failure contract at batch granularity).
  {
    Store s("/dev/full");
    s.SetGroupCommit(64);
    int events = 0;
    s.Watch("JAXJob", [&events](const tpk::WatchEvent&) { ++events; });
    auto r = s.Create("JAXJob", "doomed", Json::Object());
    CHECK(r.ok);  // buffered: durability is promised at commit, not here
    CHECK(s.Get("JAXJob", "doomed").has_value());
    std::string err;
    CHECK(!s.CommitGroup(&err));
    CHECK(err.find("group commit failed") != std::string::npos ||
          err.find("WAL broken") != std::string::npos);
    CHECK(!s.Get("JAXJob", "doomed").has_value());  // rolled back
    s.DrainWatches();
    CHECK(events == 0);  // the batch's watch events died with it
    // Later mutations stay loud (broken WAL or repeated commit failure).
    auto r2 = s.Create("JAXJob", "doomed2", Json::Object());
    if (r2.ok) CHECK(!s.CommitGroup(nullptr));
    CHECK(s.List("").empty() || !s.Get("JAXJob", "doomed2").has_value());
  }

  // The loss window: buffered records that never reach CommitGroup die
  // with the process — and they were never acknowledged, so replay
  // correctly shows an empty store.
  {
    std::string wal = "/tmp/tpk_dur_group_loss.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    {
      Store w(wal);
      w.SetGroupCommit(64);
      CHECK(w.Create("JAXJob", "lost", Json::Object()).ok);
      // No CommitGroup: destructor drops the user-space batch buffer.
    }
    Store r(wal);
    CHECK(r.Load() == 0);
    CHECK(!r.Get("JAXJob", "lost").has_value());
    std::remove(wal.c_str());
  }

  // Mixed legacy + group-committed appends replay end to end.
  {
    std::string wal = "/tmp/tpk_dur_group_legacy.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    WriteFile(wal,
              "{\"kind\":\"JAXJob\",\"name\":\"old1\",\"spec\":{\"v\":1},"
              "\"status\":{},\"resourceVersion\":1,\"generation\":1}\n");
    {
      Store w(wal);
      w.SetGroupCommit(64);
      CHECK(w.Load() == 1);
      CHECK(w.Create("JAXJob", "new1", Json::Object()).ok);
      CHECK(w.CommitGroup(nullptr));
    }
    Store r(wal);
    CHECK(r.Load() == 2);
    CHECK(r.load_stats().clean);
    CHECK(r.Get("JAXJob", "old1").has_value());
    CHECK(r.Get("JAXJob", "new1").has_value());
    std::remove(wal.c_str());
  }

  // fsync=interval composes: covering fsyncs fire once the ACCUMULATED
  // record count crosses the interval, not per commit.
  {
    std::string wal = "/tmp/tpk_dur_group_interval.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    Store w(wal);
    w.SetFsync(Store::FsyncPolicy::kInterval, 8);
    w.SetGroupCommit(64);
    for (int commit = 0; commit < 3; ++commit) {
      for (int i = 0; i < 3; ++i) {
        CHECK(w.Create("JAXJob",
                       "j" + std::to_string(commit * 3 + i),
                       Json::Object()).ok);
      }
      CHECK(w.CommitGroup(nullptr));
    }
    Json info = w.StateInfo();
    CHECK(info.get("groupCommit").get("commits").as_int() == 3);
    CHECK(info.get("groupCommit").get("records").as_int() == 9);
    CHECK(info.get("groupCommit").get("fsyncs").as_int() == 1);  // at 9 >= 8
    std::remove(wal.c_str());
  }

  // Explicit Compact() with a batch open lands the batch first: the
  // snapshot may never make unacknowledged mutations durable ahead of
  // their commit, nor strand committed ones behind a stale tail.
  {
    std::string wal = "/tmp/tpk_dur_group_compact.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    {
      Store w(wal);
      w.SetGroupCommit(64);
      for (int i = 0; i < 3; ++i) {
        CHECK(w.Create("JAXJob", "j" + std::to_string(i),
                       Json::Object()).ok);
      }
      CHECK(w.PendingGroupRecords() == 3);
      std::string err;
      CHECK(w.Compact(&err));
      CHECK(w.PendingGroupRecords() == 0);
    }
    Store r(wal);
    CHECK(r.Load() == 3);
    CHECK(r.load_stats().snapshot_loaded);
    CHECK(r.load_stats().tail_records == 0);
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
  }

  // ---- Watch coalescing (ISSUE 8) -----------------------------------------

  // A run of ADDED/MODIFIED per (kind, name) collapses to ONE event with
  // the latest resource; DELETED is a barrier; counters land in
  // stateinfo.
  {
    Store s("");  // coalescing is store-level, WAL not needed
    std::vector<std::string> seen;
    s.Watch("JAXJob", [&seen](const tpk::WatchEvent& ev) {
      seen.push_back(ev.resource.name + ":" +
                     std::to_string(static_cast<int>(ev.type)) + ":" +
                     std::to_string(ev.resource.status.get("beat").as_int(-1)));
    });
    CHECK(s.Create("JAXJob", "hot", Json::Object()).ok);
    for (int i = 0; i < 5; ++i) {
      Json st = Json::Object();
      st["beat"] = i;
      CHECK(s.UpdateStatus("JAXJob", "hot", st).ok);
    }
    CHECK(s.DrainWatches() == 1);
    // One ADDED (the create opened the run) carrying the LAST status.
    CHECK(seen.size() == 1);
    CHECK(seen[0] == "hot:0:4");
    Json info = s.StateInfo();
    CHECK(info.get("watch").get("coalescedEvents").as_int() == 5);
    CHECK(info.get("watch").get("deliveredEvents").as_int() == 1);

    // DELETED is never coalesced away, and a re-create after it starts
    // a fresh run: modify → delete → create delivers all three.
    seen.clear();
    Json st = Json::Object();
    st["beat"] = 9;
    CHECK(s.UpdateStatus("JAXJob", "hot", st).ok);
    CHECK(s.Delete("JAXJob", "hot").ok);
    CHECK(s.Create("JAXJob", "hot", Json::Object()).ok);
    CHECK(s.DrainWatches() == 3);
    CHECK(seen.size() == 3);
    CHECK(seen[0] == "hot:1:9");   // MODIFIED, latest pre-delete state
    CHECK(seen[1] == "hot:2:9");   // DELETED
    CHECK(seen[2] == "hot:0:-1");  // fresh ADDED
  }

  // Events queued by an OPEN batch are invisible to DrainWatches until
  // the covering commit lands: a delivered event cannot be recalled, so
  // only committed mutations may fan out (and a failed commit can still
  // drop its batch's events). Committed events ahead of the batch still
  // drain.
  {
    std::string wal = "/tmp/tpk_dur_group_watchgate.jsonl";
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
    Store s(wal);
    s.SetGroupCommit(64);
    int events = 0;
    s.Watch("JAXJob", [&events](const tpk::WatchEvent&) { ++events; });
    CHECK(s.Create("JAXJob", "a", Json::Object()).ok);
    CHECK(s.CommitGroup(nullptr));
    CHECK(s.Create("JAXJob", "b", Json::Object()).ok);  // opens a batch
    CHECK(s.DrainWatches() == 1);  // only the committed "a" delivers
    CHECK(events == 1);
    CHECK(s.DrainWatches() == 0);  // "b" stays gated behind its commit
    CHECK(events == 1);
    CHECK(s.CommitGroup(nullptr));
    CHECK(s.DrainWatches() == 1);  // now it delivers
    CHECK(events == 2);
    std::remove(wal.c_str());
    std::remove((wal + ".snap").c_str());
  }

  printf("test_store_durability OK\n");
  return 0;
}
