// PipelineRun controller semantics against the FakeExecutor — envtest-style
// (SURVEY.md §4.2): DAG ordering, dependency gating, fail-fast, cycle/ref
// validation, the content-hash step cache, lineage persistence, and SHA-256
// vectors. No real processes; tests flip job status and write artifact
// files by hand.
#include <sys/stat.h>

#include <cstdio>
#include <cstdlib>
#include <string>

#include "executor.h"
#include "jaxjob.h"
#include "pipelines.h"
#include "scheduler.h"
#include "sha256.h"
#include "store.h"

using tpk::FakeExecutor;
using tpk::JaxJobController;
using tpk::Json;
using tpk::LineageStore;
using tpk::PipelineRunController;
using tpk::Scheduler;
using tpk::Sha256;
using tpk::Store;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

namespace {

const char* kWorkdir = "/tmp/tpk_test_pipe";

std::string RunPhase(Store& store, const std::string& name) {
  auto r = store.Get("PipelineRun", name);
  return r ? r->status.get("phase").as_string() : "<gone>";
}

std::string TaskPhase(Store& store, const std::string& run,
                      const std::string& task) {
  auto r = store.Get("PipelineRun", run);
  return r ? r->status.get("tasks").get(task).get("phase").as_string()
           : "<gone>";
}

void WriteArtifact(const std::string& run, const std::string& task,
                   const std::string& output, const std::string& content) {
  std::string dir = std::string(kWorkdir) + "/" + run + "/artifacts/" +
                    task + "/" + output;
  std::string cur;
  for (char c : dir + "/") {
    if (c == '/') {
      if (!cur.empty()) mkdir(cur.c_str(), 0755);
    }
    cur += c;
  }
  FILE* f = fopen((dir + "/data.txt").c_str(), "w");
  // Fixture writes must not fail silently: a short artifact would turn
  // downstream cache/lineage assertions into confusing false failures.
  if (!f || fwrite(content.data(), 1, content.size(), f)
                != content.size()) {
    fprintf(stderr, "FAIL %s:%d: fixture write %s\n", __FILE__, __LINE__,
            dir.c_str());
    abort();
  }
  fclose(f);
}

// Three-task linear pipeline: a -> b -> c, param n feeds a.
Json LinearIR() {
  auto comp = [](const std::string& name, std::vector<std::string> ins,
                 std::vector<std::string> outs) {
    Json c = Json::Object();
    c["name"] = name;
    c["kind"] = "python";
    c["source"] = "def " + name + "(**kw): pass\n";
    c["params"] = Json::Object();
    c["defaults"] = Json::Object();
    Json in = Json::Array(), out = Json::Array();
    for (const auto& i : ins) in.push_back(i);
    for (const auto& o : outs) out.push_back(o);
    c["inputs"] = in;
    c["outputs"] = out;
    c["replicas"] = 1;
    c["cache"] = true;
    return c;
  };
  Json ir = Json::Object();
  ir["schema"] = "tpk-pipeline/v1";
  ir["name"] = "linear";
  Json params = Json::Object();
  params["n"] = 5;
  ir["params"] = params;
  Json tasks = Json::Object();
  {
    Json t = Json::Object();
    t["component"] = comp("a", {}, {"out"});
    t["component"]["params"]["n"] = "int";
    Json args = Json::Object();
    Json ref = Json::Object();
    ref["param"] = "n";
    args["n"] = ref;
    t["arguments"] = args;
    t["depends_on"] = Json::Array();
    tasks["a"] = t;
  }
  {
    Json t = Json::Object();
    t["component"] = comp("b", {"data"}, {"out"});
    Json args = Json::Object();
    Json ref = Json::Object();
    ref["task"] = "a";
    ref["output"] = "out";
    args["data"] = ref;
    t["arguments"] = args;
    t["depends_on"] = Json::Array();
    tasks["b"] = t;
  }
  {
    Json t = Json::Object();
    t["component"] = comp("c", {"data"}, {"report"});
    Json args = Json::Object();
    Json ref = Json::Object();
    ref["task"] = "b";
    ref["output"] = "out";
    args["data"] = ref;
    t["arguments"] = args;
    t["depends_on"] = Json::Array();
    tasks["c"] = t;
  }
  ir["tasks"] = tasks;
  return ir;
}

struct Harness {
  Store store;
  Scheduler sched;
  FakeExecutor exec;
  LineageStore lineage;  // in-memory
  JaxJobController jobs{&store, &exec, &sched, kWorkdir};
  PipelineRunController ctl{&store, &lineage, kWorkdir};
  double now = 1000.0;

  Harness(int capacity = 8) { sched.AddSlice("local", capacity); }

  void Settle(int rounds = 8) {
    for (int i = 0; i < rounds; ++i) {
      std::vector<std::string> dirty;
      int w = store.Watch("", [&](const tpk::WatchEvent& ev) {
        if (ev.type == tpk::WatchEvent::Type::kDeleted) {
          if (ev.resource.kind == "JAXJob") jobs.OnDeleted(ev.resource);
          if (ev.resource.kind == "PipelineRun") ctl.OnDeleted(ev.resource);
        } else if (ev.resource.kind == "JAXJob") {
          dirty.push_back(ev.resource.name);
        }
      });
      jobs.Tick(now);
      ctl.Tick(now);
      store.DrainWatches();
      for (const auto& d : dirty) jobs.Reconcile(d);
      store.DrainWatches();
      store.Unwatch(w);
    }
  }

  Json RunSpec(const Json& ir) {
    Json spec = Json::Object();
    spec["pipeline_spec"] = ir;
    return spec;
  }
};

}  // namespace

int main() {
  // --- SHA-256 vectors --------------------------------------------------
  {
    CHECK(Sha256::Hash("") ==
          "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855");
    CHECK(Sha256::Hash("abc") ==
          "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad");
    // Multi-block (>64 bytes).
    CHECK(Sha256::Hash(std::string(1000, 'a')) ==
          "41edece42d63e8d9bf515a9ba6932e1c20cbc9f5a5d134645adb5db1b9737ea3");
  }

  // --- DAG execution order + artifact flow ------------------------------
  {
    Harness h;
    h.store.Create("PipelineRun", "r1", h.RunSpec(LinearIR()));
    h.Settle();
    CHECK(RunPhase(h.store, "r1") == "Running");
    // Only `a` is launched; b/c gated on deps.
    CHECK(TaskPhase(h.store, "r1", "a") == "Running");
    CHECK(TaskPhase(h.store, "r1", "b") == "Pending");
    CHECK(h.exec.launched.size() == 1);
    // Launcher command hit the executor.
    CHECK(h.exec.launched[0].argv[2] == "kubeflow_tpu.pipelines.launcher");

    WriteArtifact("r1", "a", "out", "AAA");
    h.exec.Finish("r1.a/0", 0);
    h.Settle();
    CHECK(TaskPhase(h.store, "r1", "a") == "Succeeded");
    CHECK(TaskPhase(h.store, "r1", "b") == "Running");
    // b's task spec received a's artifact path.
    auto run = h.store.Get("PipelineRun", "r1");
    std::string a_out = run->status.get("tasks").get("a").get("outputs")
                            .get("out").as_string();
    CHECK(a_out.find("/r1/artifacts/a/out") != std::string::npos);

    WriteArtifact("r1", "b", "out", "BBB");
    h.exec.Finish("r1.b/0", 0);
    WriteArtifact("r1", "c", "report", "CCC");
    h.Settle();
    h.exec.Finish("r1.c/0", 0);
    h.Settle();
    CHECK(RunPhase(h.store, "r1") == "Succeeded");
    CHECK(h.ctl.metrics().tasks_launched == 3);
    CHECK(h.lineage.size() == 3);
    // Child jobs are GC'd once harvested (no unbounded store/WAL growth).
    CHECK(!h.store.Get("JAXJob", "r1.a").has_value());
    CHECK(!h.store.Get("JAXJob", "r1.c").has_value());
    // Digests recorded and non-empty.
    run = h.store.Get("PipelineRun", "r1");
    CHECK(!run->status.get("tasks").get("a").get("digests").get("out")
               .as_string().empty());
  }

  // --- Step cache: identical second run reuses everything ---------------
  {
    Harness h;
    h.store.Create("PipelineRun", "r1", h.RunSpec(LinearIR()));
    h.Settle();
    WriteArtifact("r1", "a", "out", "AAA");
    h.exec.Finish("r1.a/0", 0);
    h.Settle();
    WriteArtifact("r1", "b", "out", "BBB");
    h.exec.Finish("r1.b/0", 0);
    h.Settle();
    WriteArtifact("r1", "c", "report", "CCC");
    h.exec.Finish("r1.c/0", 0);
    h.Settle();
    CHECK(RunPhase(h.store, "r1") == "Succeeded");

    h.store.Create("PipelineRun", "r2", h.RunSpec(LinearIR()));
    h.Settle();
    CHECK(RunPhase(h.store, "r2") == "Succeeded");  // all cache hits
    CHECK(h.ctl.metrics().cache_hits == 3);
    CHECK(h.exec.launched.size() == 3);  // no new launches
    CHECK(TaskPhase(h.store, "r2", "b") == "Cached");
    auto run = h.store.Get("PipelineRun", "r2");
    CHECK(run->status.get("tasks").get("b").get("cachedFrom").as_string() ==
          "r1");

    // Changed param → a's fingerprint differs → a re-runs; b/c then see new
    // upstream digests only if a's output changes. Write identical output:
    // b and c still cache-hit (content-addressed, not run-addressed).
    Json spec = h.RunSpec(LinearIR());
    Json overrides = Json::Object();
    overrides["n"] = 6;
    spec["params"] = overrides;
    h.store.Create("PipelineRun", "r3", spec);
    h.Settle();
    CHECK(TaskPhase(h.store, "r3", "a") == "Running");  // cache miss
    WriteArtifact("r3", "a", "out", "AAA");             // same content
    h.exec.Finish("r3.a/0", 0);
    h.Settle();
    CHECK(RunPhase(h.store, "r3") == "Succeeded");
    CHECK(TaskPhase(h.store, "r3", "b") == "Cached");
    CHECK(h.exec.launched.size() == 4);  // only a re-ran
  }

  // --- Fail fast: running tasks stopped, pending skipped ----------------
  {
    Harness h;
    // Diamond: a -> {b, c} -> d; b fails while c runs.
    Json ir = LinearIR();
    Json tasks = ir.get("tasks");
    Json d = Json::Object();
    d["component"] = tasks.get("c").get("component");
    d["component"]["name"] = "d";
    Json args = Json::Object();
    Json ref = Json::Object();
    ref["task"] = "c";
    ref["output"] = "report";
    args["data"] = ref;
    d["arguments"] = args;
    d["depends_on"] = Json::Array();
    tasks["d"] = d;
    // Rewire c to depend on a (parallel with b).
    Json cref = Json::Object();
    cref["task"] = "a";
    cref["output"] = "out";
    tasks["c"]["arguments"]["data"] = cref;
    ir["tasks"] = tasks;

    h.store.Create("PipelineRun", "r1", h.RunSpec(ir));
    h.Settle();
    WriteArtifact("r1", "a", "out", "AAA2");
    h.exec.Finish("r1.a/0", 0);
    h.Settle();
    CHECK(TaskPhase(h.store, "r1", "b") == "Running");
    CHECK(TaskPhase(h.store, "r1", "c") == "Running");

    h.exec.Finish("r1.b/0", 1);  // b fails (restart Never)
    h.Settle();
    CHECK(RunPhase(h.store, "r1") == "Failed");
    CHECK(TaskPhase(h.store, "r1", "b") == "Failed");
    CHECK(TaskPhase(h.store, "r1", "c") == "Stopped");
    CHECK(TaskPhase(h.store, "r1", "d") == "Skipped");
    // c's job was deleted → gang killed, devices back.
    CHECK(!h.store.Get("JAXJob", "r1.c").has_value());
    CHECK(h.sched.Slices()[0].used == 0);
    CHECK(h.ctl.metrics().runs_failed == 1);
  }

  // --- Validation: unknown dep + cycle → Failed InvalidPipeline ---------
  {
    Harness h;
    Json ir = LinearIR();
    ir["tasks"]["b"]["arguments"]["data"]["task"] = "ghost";
    h.store.Create("PipelineRun", "bad", h.RunSpec(ir));
    h.Settle(1);
    CHECK(RunPhase(h.store, "bad") == "Failed");

    Json ir2 = LinearIR();
    // a depends on c → cycle a→b→c→a (via depends_on).
    Json dep = Json::Array();
    dep.push_back("c");
    ir2["tasks"]["a"]["depends_on"] = dep;
    h.store.Create("PipelineRun", "cyc", h.RunSpec(ir2));
    h.Settle(1);
    CHECK(RunPhase(h.store, "cyc") == "Failed");
    auto r = h.store.Get("PipelineRun", "cyc");
    CHECK(r->status.get("conditions").elements().back().get("message")
              .as_string().find("cycle") != std::string::npos);
  }

  // --- Named pipeline resource + param overrides ------------------------
  {
    Harness h;
    h.store.Create("Pipeline", "lin", LinearIR());
    Json spec = Json::Object();
    spec["pipeline"] = "lin";
    Json overrides = Json::Object();
    overrides["n"] = 9;
    spec["params"] = overrides;
    h.store.Create("PipelineRun", "byname", spec);
    h.Settle();
    CHECK(RunPhase(h.store, "byname") == "Running");
    // Resolved param value rode into the task spec file.
    FILE* f = fopen((std::string(kWorkdir) + "/byname/tasks/a.json").c_str(),
                    "r");
    CHECK(f != nullptr);
    char buf[4096];
    size_t got = fread(buf, 1, sizeof(buf), f);
    fclose(f);
    CHECK(std::string(buf, got).find("\"n\":9") != std::string::npos);

    Json bad = Json::Object();
    bad["pipeline"] = "nope";
    h.store.Create("PipelineRun", "orphan", bad);
    h.Settle(1);
    CHECK(RunPhase(h.store, "orphan") == "Failed");
  }

  // --- Lineage persistence: reload serves cache across restarts ---------
  {
    std::string lpath = std::string(kWorkdir) + "/lineage_test.jsonl";
    remove(lpath.c_str());
    {
      LineageStore l1(lpath);
      l1.Load();
      Json outputs = Json::Object();
      Json rec = Json::Object();
      rec["path"] = "/tmp/x";
      rec["digest"] = "d1";
      outputs["out"] = rec;
      l1.Record("fp1", "r1", "a", outputs);
    }
    LineageStore l2(lpath);
    CHECK(l2.Load() == 1);
    Json hit = l2.Lookup("fp1");
    CHECK(hit.is_object());
    CHECK(hit.get("outputs").get("out").get("digest").as_string() == "d1");
    CHECK(l2.Lookup("nope").is_null());
  }

  // --- TPU placement fields flow component -> JAXJob spec ----------------
  {
    Harness h;
    h.sched.AddSlice("s2", 8);  // multi-slice placement needs two pools
    Json ir = Json::Object();
    ir["schema"] = "tpk-pipeline/v1";
    ir["name"] = "place";
    ir["params"] = Json::Object();
    Json tasks = Json::Object();
    Json t = Json::Object();
    Json c = Json::Object();
    c["name"] = "p";
    c["kind"] = "python";
    c["source"] = "def p(**kw): pass\n";
    c["params"] = Json::Object();
    c["defaults"] = Json::Object();
    c["inputs"] = Json::Array();
    c["outputs"] = Json::Array();
    c["replicas"] = 2;
    c["cache"] = false;
    c["devices_per_proc"] = 4;
    c["num_slices"] = 2;
    t["component"] = c;
    t["arguments"] = Json::Object();
    t["depends_on"] = Json::Array();
    tasks["p"] = t;
    ir["tasks"] = tasks;
    h.store.Create("PipelineRun", "pr", h.RunSpec(ir));
    h.Settle();
    auto j = h.store.Get("JAXJob", "pr.p");
    CHECK(j.has_value());
    CHECK(j->spec.get("replicas").as_int(0) == 2);
    CHECK(j->spec.get("devices_per_proc").as_int(0) == 4);
    CHECK(j->spec.get("num_slices").as_int(0) == 2);
  }

  printf("test_pipelines OK\n");
  return 0;
}
