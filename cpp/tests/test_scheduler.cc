// Gang/slice scheduler tests: atomicity, bin-packing, multi-slice.
#include <cstdio>

#include "scheduler.h"

using tpk::Scheduler;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

int main() {
  Scheduler s;
  s.AddSlice("a", 8);
  s.AddSlice("b", 4);

  // Bin-packing: prefers the fullest slice that fits.
  auto a1 = s.Allocate(4);
  CHECK(a1.has_value());
  CHECK(a1->slices.count("b") == 1);  // b (free 4) is tighter than a (free 8)

  // Too big → nullopt, state untouched (atomicity).
  CHECK(!s.Allocate(9).has_value());
  auto a2 = s.Allocate(8);
  CHECK(a2.has_value() && a2->slices.count("a") == 1);

  // Everything full now.
  CHECK(!s.Allocate(1).has_value());
  s.Release(*a1);
  CHECK(s.Allocate(4).has_value());

  // Multi-slice gang: needs per-slice room in N distinct slices.
  Scheduler m;
  m.AddSlice("s0", 4);
  m.AddSlice("s1", 4);
  auto span = m.Allocate(8, /*num_slices=*/2);
  CHECK(span.has_value());
  CHECK(span->slices.size() == 2);
  CHECK(span->slices.at("s0") == 4 && span->slices.at("s1") == 4);
  CHECK(!m.Allocate(2, 2).has_value());  // both slices now full
  m.Release(*span);
  CHECK(m.Allocate(2, 2).has_value());

  // Indivisible request rejected.
  CHECK(!m.Allocate(3, 2).has_value());

  printf("test_scheduler OK\n");
  return 0;
}
