// Experiment/Trial controller semantics against FakeExecutor +
// FakeSuggestion — the envtest analog for the Katib-equivalent layer
// (SURVEY.md §4.2): no processes or suggestion services start; tests flip
// job status by hand, write fake worker logs, and assert on the
// experiment state machine, parallelism cap, optimal tracking, goal/
// failure-budget completion, substitution, metric parsing, and medianstop.
#include <sys/stat.h>

#include <cstdio>
#include <cstdlib>
#include <string>

#include "executor.h"
#include "jaxjob.h"
#include "scheduler.h"
#include "store.h"
#include "tune.h"

using tpk::ExperimentController;
using tpk::FakeExecutor;
using tpk::FakeSuggestion;
using tpk::JaxJobController;
using tpk::Json;
using tpk::Scheduler;
using tpk::Store;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                       \
    }                                                                 \
  } while (0)

namespace {

const char* kWorkdir = "/tmp/tpk_test_tune";

std::string ExpPhase(Store& store, const std::string& name) {
  auto r = store.Get("Experiment", name);
  return r ? r->status.get("phase").as_string() : "<gone>";
}

std::string TrialPhase(Store& store, const std::string& name) {
  auto r = store.Get("Trial", name);
  return r ? r->status.get("phase").as_string() : "<gone>";
}

void WriteLog(const std::string& job, const std::string& content) {
  mkdir(kWorkdir, 0755);
  std::string dir = std::string(kWorkdir) + "/" + job;
  mkdir(dir.c_str(), 0755);
  FILE* f = fopen((dir + "/worker-0.log").c_str(), "w");
  // Fixture writes must not fail silently: a short log would turn the
  // metric-parsing assertions into confusing false failures.
  if (!f || fwrite(content.data(), 1, content.size(), f)
                != content.size()) {
    fprintf(stderr, "FAIL %s:%d: fixture write %s\n", __FILE__, __LINE__,
            dir.c_str());
    abort();
  }
  fclose(f);
}

Json Assignment(double lr) {
  Json a = Json::Object();
  a["lr"] = lr;
  return a;
}

Json BaseExpSpec(int max_trials, int parallel) {
  Json spec = Json::Object();
  Json params = Json::Array();
  Json lr = Json::Object();
  lr["name"] = "lr";
  lr["type"] = "double";
  lr["min"] = 0.001;
  lr["max"] = 0.1;
  params.push_back(lr);
  spec["parameters"] = params;
  Json obj = Json::Object();
  obj["metric"] = "loss";
  obj["goal"] = "minimize";
  spec["objective"] = obj;
  Json algo = Json::Object();
  algo["name"] = "random";
  spec["algorithm"] = algo;
  spec["max_trials"] = max_trials;
  spec["parallel_trials"] = parallel;
  Json tmpl = Json::Object();
  tmpl["replicas"] = 1;
  tmpl["devices_per_proc"] = 1;
  Json cmd = Json::Array();
  cmd.push_back("trainer");
  cmd.push_back("--lr=${lr}");
  tmpl["command"] = cmd;
  spec["trial_template"] = tmpl;
  return spec;
}

struct Harness {
  Store store;
  Scheduler sched;
  FakeExecutor exec;
  FakeSuggestion sugg;
  JaxJobController jobs{&store, &exec, &sched, kWorkdir};
  ExperimentController ctl{&store, &sugg, kWorkdir};
  double now = 1000.0;

  Harness(int capacity = 8) { sched.AddSlice("local", capacity); }

  // Emulates main.cc's loop: tune tick + jaxjob reconciles + delete routing.
  void Settle(int rounds = 8) {
    for (int i = 0; i < rounds; ++i) {
      std::vector<std::string> dirty;
      int w = store.Watch("", [&](const tpk::WatchEvent& ev) {
        if (ev.type == tpk::WatchEvent::Type::kDeleted) {
          if (ev.resource.kind == "JAXJob") {
            jobs.OnDeleted(ev.resource);
          } else {
            ctl.OnDeleted(ev.resource);
          }
        } else if (ev.resource.kind == "JAXJob") {
          dirty.push_back(ev.resource.name);
        }
      });
      jobs.Tick(now);
      ctl.Tick(now);
      store.DrainWatches();
      for (const auto& d : dirty) jobs.Reconcile(d);
      store.DrainWatches();
      store.Unwatch(w);
    }
  }
};

}  // namespace

int main() {
  // --- Substitution: ${p}, ${trialParameters.p}, ${trialName}, typing ---
  {
    Json params = Json::Object();
    params["lr"] = 0.003;
    params["opt"] = "adam";
    params["layers"] = 4;
    Json tmpl = Json::Object();
    tmpl["a"] = "--lr=${lr} --opt=${trialParameters.opt}";
    tmpl["b"] = "${lr}";             // whole-token: stays a number
    tmpl["c"] = "${trialName}";
    tmpl["d"] = "${unknown} stays";
    Json arr = Json::Array();
    arr.push_back("n=${layers}");
    tmpl["e"] = arr;
    Json out = ExperimentController::Substitute(tmpl, params, "exp-0");
    CHECK(out.get("a").as_string() == "--lr=0.003 --opt=adam");
    CHECK(out.get("b").is_number() && out.get("b").as_number() == 0.003);
    CHECK(out.get("c").as_string() == "exp-0");
    CHECK(out.get("d").as_string() == "${unknown} stays");
    CHECK(out.get("e").elements()[0].as_string() == "n=4");
  }

  // --- Metric parsing: JSONL + stdout-regex fallback, word boundaries ---
  {
    std::string log =
        "{\"step\": 1, \"loss\": 0.9, \"tokens_per_sec\": 100}\n"
        "garbage line\n"
        "{\"step\": 2, \"loss\": 0.5}\n"
        "epoch done: val_loss=0.44 loss=0.40\n"
        "not_my_loss=9.9\n";
    auto obs = ExperimentController::ParseMetrics(log, "loss");
    CHECK(obs.size() == 3);
    CHECK(obs[0].first == 1 && obs[0].second == 0.9);
    CHECK(obs[1].first == 2 && obs[1].second == 0.5);
    CHECK(obs[2].second == 0.40);  // `loss=0.40`, not val_loss / not_my_loss
    auto val = ExperimentController::ParseMetrics(log, "val_loss");
    CHECK(val.size() == 1 && val[0].second == 0.44);
  }

  // --- Happy path: parallelism cap, trials run, optimal tracked --------
  {
    Harness h;
    h.sugg.queue = {Assignment(0.01), Assignment(0.02), Assignment(0.03)};
    h.store.Create("Experiment", "opt", BaseExpSpec(3, 2));
    h.Settle();
    CHECK(ExpPhase(h.store, "opt") == "Running");
    // Parallelism 2: only two trials (and their jobs) exist so far.
    CHECK(h.store.List("Trial").size() == 2);
    CHECK(TrialPhase(h.store, "opt-0") == "Running");
    // Substituted command reached the executor.
    CHECK(h.exec.launched.size() == 2);
    CHECK(h.exec.launched[0].argv[1] == "--lr=0.01");

    // Trial 0 finishes well, trial 1 poorly.
    WriteLog("opt-0", "{\"step\": 1, \"loss\": 0.30}\n");
    h.exec.Finish("opt-0/0", 0);
    WriteLog("opt-1", "{\"step\": 1, \"loss\": 0.80}\n");
    h.exec.Finish("opt-1/0", 0);
    h.Settle();
    CHECK(TrialPhase(h.store, "opt-0") == "Succeeded");
    // Third trial was launched after capacity freed.
    CHECK(h.store.List("Trial").size() == 3);
    WriteLog("opt-2", "{\"step\": 1, \"loss\": 0.50}\n");
    h.exec.Finish("opt-2/0", 0);
    h.Settle();

    CHECK(ExpPhase(h.store, "opt") == "Succeeded");
    auto exp = h.store.Get("Experiment", "opt");
    CHECK(exp->status.get("optimal").get("trial").as_string() == "opt-0");
    CHECK(exp->status.get("optimal").get("value").as_number() == 0.30);
    CHECK(exp->status.get("trials").get("succeeded").as_int() == 3);
    CHECK(h.ctl.metrics().experiments_succeeded == 1);
    CHECK(h.ctl.metrics().trials_created == 3);
  }

  // --- Goal reached: stops early, kills the in-flight trial -------------
  {
    Harness h;
    h.sugg.queue = {Assignment(0.01), Assignment(0.02), Assignment(0.03),
                    Assignment(0.04)};
    Json spec = BaseExpSpec(4, 2);
    spec["objective"]["target"] = 0.2;
    h.store.Create("Experiment", "goal", spec);
    h.Settle();
    WriteLog("goal-0", "loss=0.15\n");  // beats target via regex path
    h.exec.Finish("goal-0/0", 0);
    h.Settle();
    CHECK(ExpPhase(h.store, "goal") == "Succeeded");
    auto exp = h.store.Get("Experiment", "goal");
    CHECK(exp->status.get("conditions")
              .elements()
              .back()
              .get("reason")
              .as_string() == "GoalReached");
    // In-flight trial 1 was stopped and its job deleted.
    CHECK(TrialPhase(h.store, "goal-1") == "Stopped");
    CHECK(!h.store.Get("JAXJob", "goal-1").has_value());
    // Only 2 trials ever created (no new ones after goal).
    CHECK(h.store.List("Trial").size() == 2);
  }

  // --- Failure budget: trials fail → experiment Failed ------------------
  {
    Harness h;
    h.sugg.queue = {Assignment(0.01), Assignment(0.02), Assignment(0.03),
                    Assignment(0.04)};
    Json spec = BaseExpSpec(4, 1);
    spec["max_failed_trials"] = 1;
    h.store.Create("Experiment", "bad", spec);
    h.Settle();
    h.exec.Finish("bad-0/0", 1);  // job fails (Never not set → OnFailure
    h.Settle();                   // default backoff 3... use spec override)
    // Default restart policy retries; exhaust backoff.
    for (int i = 0; i < 4; ++i) {
      h.exec.Finish("bad-0/0", 1);
      h.Settle();
    }
    CHECK(TrialPhase(h.store, "bad-0") == "Failed");
    h.Settle();
    h.exec.Finish("bad-1/0", 1);
    h.Settle();
    for (int i = 0; i < 4; ++i) {
      h.exec.Finish("bad-1/0", 1);
      h.Settle();
    }
    CHECK(ExpPhase(h.store, "bad") == "Failed");
    CHECK(h.ctl.metrics().experiments_failed == 1);
  }

  // --- Missing metric in log → trial Failed (MetricsUnavailable) --------
  {
    Harness h;
    h.sugg.queue = {Assignment(0.01)};
    h.store.Create("Experiment", "nometric", BaseExpSpec(1, 1));
    h.Settle();
    WriteLog("nometric-0", "training finished, no metrics emitted\n");
    h.exec.Finish("nometric-0/0", 0);
    h.Settle();
    CHECK(TrialPhase(h.store, "nometric-0") == "Failed");
  }

  // --- Suggestion failure: backoff + retry, error surfaced --------------
  {
    Harness h;
    h.sugg.fail_next = true;
    h.sugg.queue = {Assignment(0.01)};
    h.store.Create("Experiment", "flaky", BaseExpSpec(1, 1));
    h.Settle(1);
    CHECK(ExpPhase(h.store, "flaky") == "Running");
    auto exp = h.store.Get("Experiment", "flaky");
    CHECK(!exp->status.get("suggestionError").as_string().empty());
    CHECK(h.ctl.metrics().suggestion_errors == 1);
    int calls = h.sugg.calls;
    h.Settle();  // same timestamp: retry suppressed by backoff
    CHECK(h.sugg.calls == calls);
    CHECK(h.store.List("Trial").empty());
    h.now += 5;  // past the backoff window → retry succeeds
    h.Settle();
    CHECK(h.store.List("Trial").size() == 1);
  }

  // --- Persistent suggestion failure → experiment Failed ----------------
  {
    Harness h;
    h.store.Create("Experiment", "dead", BaseExpSpec(2, 1));
    for (int i = 0; i < 6; ++i) {
      h.sugg.fail_next = true;
      h.Settle(1);
      h.now += 60;  // clear any backoff window
    }
    CHECK(ExpPhase(h.store, "dead") == "Failed");
    auto exp = h.store.Get("Experiment", "dead");
    CHECK(exp->status.get("conditions")
              .elements()
              .back()
              .get("reason")
              .as_string() == "SuggestionUnavailable");
    CHECK(h.ctl.metrics().experiments_failed == 1);
  }

  // --- Grid exhaustion: fewer suggestions than budget → Succeeded -------
  {
    Harness h;
    h.sugg.queue = {Assignment(0.01)};  // only one point "in the grid"
    h.store.Create("Experiment", "grid", BaseExpSpec(10, 2));
    h.Settle();
    CHECK(h.store.List("Trial").size() == 1);
    WriteLog("grid-0", "loss=0.5\n");
    h.exec.Finish("grid-0/0", 0);
    h.Settle();
    CHECK(ExpPhase(h.store, "grid") == "Succeeded");
    auto exp = h.store.Get("Experiment", "grid");
    CHECK(exp->status.get("conditions")
              .elements()
              .back()
              .get("reason")
              .as_string() == "SearchSpaceExhausted");
  }

  // --- Medianstop: running trial worse than median gets stopped ---------
  {
    Harness h;
    h.sugg.queue = {Assignment(0.01), Assignment(0.02), Assignment(0.03),
                    Assignment(0.04)};
    Json spec = BaseExpSpec(4, 4);
    Json es = Json::Object();
    es["algorithm"] = "medianstop";
    es["min_trials"] = 3;
    es["start_step"] = 2;
    spec["early_stopping"] = es;
    h.store.Create("Experiment", "estop", spec);
    h.Settle();
    CHECK(h.store.List("Trial").size() == 4);
    for (int i = 0; i < 3; ++i) {
      std::string t = "estop-" + std::to_string(i);
      WriteLog(t, "loss=0.3\n");
      h.exec.Finish(t + "/0", 0);
    }
    // Trial 3 reports much worse intermediate values over >= start_step.
    WriteLog("estop-3",
             "{\"step\": 1, \"loss\": 2.0}\n{\"step\": 2, \"loss\": 1.9}\n");
    h.Settle();
    CHECK(TrialPhase(h.store, "estop-3") == "EarlyStopped");
    CHECK(!h.store.Get("JAXJob", "estop-3").has_value());  // job deleted
    CHECK(h.ctl.metrics().trials_early_stopped == 1);
    // EarlyStopped still carries its observation; experiment completes.
    auto t3 = h.store.Get("Trial", "estop-3");
    CHECK(t3->status.get("observation").get("value").as_number() == 1.9);
    h.Settle();
    CHECK(ExpPhase(h.store, "estop") == "Succeeded");
  }

  // --- Experiment delete cascades: trials + jobs GC'd, gang killed ------
  {
    Harness h;
    h.sugg.queue = {Assignment(0.01), Assignment(0.02)};
    h.store.Create("Experiment", "gc", BaseExpSpec(2, 2));
    h.Settle();
    CHECK(h.store.List("Trial").size() == 2);
    CHECK(h.sched.Slices()[0].used == 2);

    h.store.Delete("Experiment", "gc");
    h.Settle();
    CHECK(h.store.List("Trial").empty());
    CHECK(!h.store.Get("JAXJob", "gc-0").has_value());
    CHECK(h.exec.killed.size() == 2);       // gangs killed
    CHECK(h.sched.Slices()[0].used == 0);   // devices released
  }

  // --- pending protocol: empty+pending is NOT exhaustion ------------------
  {
    Harness h;
    CHECK(h.store.Create("Experiment", "pend", BaseExpSpec(4, 2)).ok);
    h.sugg.pending_next = true;  // hyperband waiting on a rung
    h.Settle(1);
    auto exp = h.store.Get("Experiment", "pend");
    CHECK(!exp->status.get("searchSpaceExhausted").as_bool(false));
    CHECK(exp->status.get("suggestionPending").as_bool(false));
    CHECK(exp->status.get("phase").as_string() == "Running");
    // Next poll (after the 1s hold) yields assignments: trials launch.
    Json a = Json::Object();
    a["lr"] = 0.01;
    h.sugg.queue.push_back(a);
    h.now += 2.0;
    h.Settle();
    CHECK(h.store.List("Trial").size() == 1);
    // Finish the trial; the next suggestion is empty WITHOUT pending —
    // that is real exhaustion, and the experiment completes.
    WriteLog("pend-0", "{\"step\": 1, \"loss\": 0.5}\n");
    h.exec.Finish("pend-0/0", 0);
    h.now += 2.0;
    h.Settle();
    auto exp2 = h.store.Get("Experiment", "pend");
    CHECK(exp2->status.get("searchSpaceExhausted").as_bool(false));
    CHECK(exp2->status.get("phase").as_string() == "Succeeded");
  }

  printf("test_tune OK\n");
  return 0;
}
