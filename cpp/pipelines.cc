#include "pipelines.h"

#include "util.h"

#include <dirent.h>
#include <sys/stat.h>
#include <sys/types.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <ctime>

#include "sha256.h"

namespace tpk {

namespace {

bool IsTerminalRun(const std::string& phase) {
  return phase == "Succeeded" || phase == "Failed";
}

bool TaskDone(const std::string& phase) {
  return phase == "Succeeded" || phase == "Cached";
}

// Terminal task phases: nothing more will happen to this task.
bool TaskTerminal(const std::string& phase) {
  return TaskDone(phase) || phase == "Failed" || phase == "Skipped" ||
         phase == "Stopped";
}

std::string ReadSmallFile(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return "";
  std::string out;
  char buf[4096];
  size_t got;
  while ((got = fread(buf, 1, sizeof(buf), f)) > 0) out.append(buf, got);
  fclose(f);
  return out;
}

// The launcher records a component's return value under this implicit
// output artifact (pipelines/launcher.py RESULT_OUTPUT).
constexpr const char* kResultOutput = "__result__";

// Read back a task's recorded return value from its result artifact.
// Returns a null Json when absent/unparsable.
Json ReadResultValue(const Json& outputs) {
  const std::string dir = outputs.get(kResultOutput).as_string();
  if (dir.empty()) return Json();
  const std::string text = ReadSmallFile(dir + "/value.json");
  if (text.empty()) return Json();
  try {
    return Json::parse(text);
  } catch (const std::exception&) {
    return Json();
  }
}

void MkdirP(const std::string& path) {
  std::string cur;
  for (size_t i = 0; i <= path.size(); ++i) {
    if (i == path.size() || path[i] == '/') {
      if (!cur.empty()) mkdir(cur.c_str(), 0755);
      if (i < path.size()) cur += '/';
    } else {
      cur += path[i];
    }
  }
}

bool WriteFile(const std::string& path, const std::string& content) {
  FILE* f = fopen(path.c_str(), "w");
  if (!f) return false;
  bool ok = fwrite(content.data(), 1, content.size(), f) == content.size();
  ok = fclose(f) == 0 && ok;
  return ok;
}

void ListDirSorted(const std::string& dir, const std::string& rel,
                   std::vector<std::string>* out) {
  DIR* d = opendir(dir.c_str());
  if (!d) return;
  std::vector<std::string> names;
  while (struct dirent* e = readdir(d)) {
    std::string n = e->d_name;
    if (n == "." || n == "..") continue;
    names.push_back(n);
  }
  closedir(d);
  std::sort(names.begin(), names.end());
  for (const auto& n : names) {
    std::string full = dir + "/" + n;
    std::string r = rel.empty() ? n : rel + "/" + n;
    struct stat st;
    if (stat(full.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) {
      ListDirSorted(full, r, out);
    } else {
      out->push_back(r);
    }
  }
}

}  // namespace

// --------------------------------------------------------------------------
// LineageStore
// --------------------------------------------------------------------------

LineageStore::LineageStore(std::string path) : path_(std::move(path)) {}

LineageStore::~LineageStore() {
  if (file_) fclose(file_);
}

int LineageStore::Load() {
  if (path_.empty()) return 0;
  int applied = 0;
  FILE* f = fopen(path_.c_str(), "r");
  if (f) {
    char* line = nullptr;
    size_t cap = 0;
    ssize_t got;
    while ((got = getline(&line, &cap, f)) > 0) {
      try {
        Json rec = Json::parse(std::string(line, got));
        const std::string fp = rec.get("fingerprint").as_string();
        if (!fp.empty()) {
          by_fp_[fp] = rec;
          ++applied;
        }
      } catch (const std::exception&) {
        // torn tail write (crash mid-append): ignore, like the WAL replay
      }
    }
    free(line);
    fclose(f);
  }
  file_ = fopen(path_.c_str(), "a");
  return applied;
}

void LineageStore::Record(const std::string& fingerprint,
                          const std::string& run, const std::string& task,
                          const Json& outputs) {
  Json rec = Json::Object();
  rec["fingerprint"] = fingerprint;
  rec["run"] = run;
  rec["task"] = task;
  rec["outputs"] = outputs;
  rec["ts"] = NowWall();
  by_fp_[fingerprint] = rec;
  if (!path_.empty() && !file_) file_ = fopen(path_.c_str(), "a");
  if (file_) {
    std::string line = rec.dump() + "\n";
    if (fwrite(line.data(), 1, line.size(), file_) != line.size() ||
        fflush(file_) != 0) {
      // Short write: the file may now end in a torn line. Stop
      // appending FOR GOOD (clearing path_ disables the lazy reopen
      // above — a later append would glue onto the torn line and make
      // the next Load() drop that whole glued record, the ISSUE 2 WAL
      // bug class). Memory stays authoritative for this run; Load()
      // already drops an unparseable tail, so the next start simply
      // re-executes the uncached tasks instead of reading garbage.
      fclose(file_);
      file_ = nullptr;
      path_.clear();
    }
  }
}

Json LineageStore::Lookup(const std::string& fingerprint) const {
  auto it = by_fp_.find(fingerprint);
  return it == by_fp_.end() ? Json() : it->second;
}

// --------------------------------------------------------------------------
// ScheduleController
// --------------------------------------------------------------------------

namespace {

// One cron field: "*", "*/n", or comma-separated values.
bool CronFieldMatches(const std::string& field, int value, int base,
                      std::string* error) {
  if (field == "*") return true;
  if (field.rfind("*/", 0) == 0) {
    int n = atoi(field.c_str() + 2);
    if (n <= 0) {
      if (error) *error = "bad cron step: " + field;
      return false;
    }
    return (value - base) % n == 0;
  }
  size_t pos = 0;
  while (pos <= field.size()) {
    size_t comma = field.find(',', pos);
    if (comma == std::string::npos) comma = field.size();
    std::string part = field.substr(pos, comma - pos);
    char* end = nullptr;
    long v = strtol(part.c_str(), &end, 10);
    if (end == part.c_str() || *end != '\0') {
      if (error) *error = "bad cron value: " + part;
      return false;
    }
    if (static_cast<int>(v) == value) return true;
    pos = comma + 1;
  }
  return false;
}

}  // namespace

bool ScheduleController::CronMatches(const std::string& cron, time_t t,
                                     std::string* error) {
  std::vector<std::string> fields;
  size_t pos = 0;
  while (pos < cron.size()) {
    size_t sp = cron.find(' ', pos);
    if (sp == std::string::npos) sp = cron.size();
    if (sp > pos) fields.push_back(cron.substr(pos, sp - pos));
    pos = sp + 1;
  }
  if (fields.size() != 5) {
    if (error) *error = "cron needs 5 fields (m h dom mon dow)";
    return false;
  }
  struct tm tmv;
  gmtime_r(&t, &tmv);
  const int values[5] = {tmv.tm_min, tmv.tm_hour, tmv.tm_mday,
                         tmv.tm_mon + 1, tmv.tm_wday};
  const int bases[5] = {0, 0, 1, 1, 0};
  for (int i = 0; i < 5; ++i) {
    if (!CronFieldMatches(fields[i], values[i], bases[i], error)) {
      return false;
    }
  }
  return true;
}

void ScheduleController::Tick(double now_s) {
  for (const auto& res : store_->List("ScheduledPipelineRun")) {
    if (res.spec.get("suspend").as_bool(false)) continue;
    Json status = res.status;
    int64_t created = status.get("runsCreated").as_int(0);
    int64_t max_runs = res.spec.get("max_runs").as_int(-1);
    if (max_runs >= 0 && created >= max_runs) continue;

    const Json& sched = res.spec.get("schedule");
    double last = status.get("lastRunUnix").as_number(0);
    bool fire = false;
    if (sched.get("interval_seconds").is_number()) {
      fire = now_s - last >= sched.get("interval_seconds").as_number();
    } else {
      const std::string cron = sched.get("cron").as_string();
      std::string err;
      time_t t = static_cast<time_t>(now_s);
      // Fire at most once per matching minute.
      bool same_minute =
          last > 0 && static_cast<int64_t>(last) / 60 ==
                          static_cast<int64_t>(now_s) / 60;
      fire = !same_minute && CronMatches(cron, t, &err);
      if (!err.empty() && status.get("scheduleError").as_string() != err) {
        status["scheduleError"] = err;
        store_->UpdateStatus("ScheduledPipelineRun", res.name, status);
        continue;
      }
    }
    if (!fire) continue;

    Json run_spec = Json::Object();
    if (res.spec.get("pipeline_spec").is_object()) {
      run_spec["pipeline_spec"] = res.spec.get("pipeline_spec");
    } else {
      run_spec["pipeline"] = res.spec.get("pipeline");
    }
    if (res.spec.get("params").is_object()) {
      run_spec["params"] = res.spec.get("params");
    }
    std::string run_name = res.name + "-" + std::to_string(created + 1);
    auto r = store_->Create("PipelineRun", run_name, run_spec);
    if (r.ok) {
      ++runs_created_;
      status["runsCreated"] = created + 1;
      status["lastRunUnix"] = now_s;
      status["lastRunTime"] = Timestamp(now_s);
      status["lastRun"] = run_name;
      store_->UpdateStatus("ScheduledPipelineRun", res.name, status);
    }
  }
}

// --------------------------------------------------------------------------
// PipelineRunController
// --------------------------------------------------------------------------

PipelineRunController::PipelineRunController(Store* store,
                                             LineageStore* lineage,
                                             std::string workdir,
                                             std::string python)
    : store_(store),
      lineage_(lineage),
      workdir_(std::move(workdir)),
      python_(std::move(python)) {
  MkdirP(workdir_);
}

std::string PipelineRunController::DirDigest(const std::string& dir) {
  struct stat st;
  if (stat(dir.c_str(), &st) != 0 || !S_ISDIR(st.st_mode)) return "";
  std::vector<std::string> files;
  ListDirSorted(dir, "", &files);
  Sha256 h;
  for (const auto& rel : files) {
    // Unambiguous framing: name (NUL-free by construction) + NUL + 8-byte
    // content length + content. Plain separators would let crafted file
    // bytes alias a different tree and poison the step cache.
    h.Update(rel);
    h.Update("\0", 1);
    std::string path = dir + "/" + rel;
    struct stat fs;
    uint64_t size = stat(path.c_str(), &fs) == 0
                        ? static_cast<uint64_t>(fs.st_size)
                        : 0;
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = (size >> (56 - 8 * i)) & 0xff;
    h.Update(lenb, 8);
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) continue;
    char buf[65536];
    size_t got;
    while ((got = fread(buf, 1, sizeof(buf), f)) > 0) h.Update(buf, got);
    fclose(f);
  }
  return h.HexDigest();
}

std::vector<std::string> PipelineRunController::TaskDeps(const Json& task) {
  std::vector<std::string> deps;
  for (const auto& d : task.get("depends_on").elements()) {
    deps.push_back(d.as_string());
  }
  for (const auto& [k, arg] : task.get("arguments").items()) {
    (void)k;
    if (arg.is_object() && arg.has("task")) {
      deps.push_back(arg.get("task").as_string());
    }
    if (arg.is_object() && arg.get("collect").is_array()) {
      for (const auto& e : arg.get("collect").elements()) {
        if (e.has("task")) deps.push_back(e.get("task").as_string());
      }
    }
  }
  // Condition operands referencing task results are data dependencies too.
  for (const auto& clause : task.get("when").elements()) {
    for (const char* side : {"lhs", "rhs"}) {
      const Json& op = clause.get(side);
      if (op.is_object() && op.has("task")) {
        deps.push_back(op.get("task").as_string());
      }
    }
  }
  // An exit handler waits on its whole scope.
  for (const auto& s : task.get("scope").elements()) {
    deps.push_back(s.as_string());
  }
  std::sort(deps.begin(), deps.end());
  deps.erase(std::unique(deps.begin(), deps.end()), deps.end());
  return deps;
}

bool PipelineRunController::ResolveIR(const Resource& res, RunView* run,
                                      std::string* error) {
  Json ir;
  if (run->status.get("pipelineSnapshot").is_object()) {
    // Frozen at first reconcile: editing the named Pipeline mid-run must
    // not desync the task list from status.tasks.
    ir = run->status.get("pipelineSnapshot");
  } else if (res.spec.get("pipeline_spec").is_object()) {
    ir = res.spec.get("pipeline_spec");
  } else {
    const std::string pname = res.spec.get("pipeline").as_string();
    if (pname.empty()) {
      *error = "spec needs `pipeline` (name) or inline `pipeline_spec`";
      return false;
    }
    auto p = store_->Get("Pipeline", pname);
    if (!p) {
      *error = "pipeline not found: " + pname;
      return false;
    }
    ir = p->spec;
  }
  if (!ir.get("tasks").is_object() || ir.get("tasks").size() == 0) {
    *error = "pipeline IR has no tasks";
    return false;
  }
  Json params = Json::Object();
  for (const auto& [k, v] : ir.get("params").items()) params[k] = v;
  for (const auto& [k, v] : res.spec.get("params").items()) {
    if (!params.has(k)) {
      *error = "unknown pipeline param override: " + k;
      return false;
    }
    params[k] = v;
  }
  run->ir = ir;
  run->params = params;
  return true;
}

bool PipelineRunController::ValidateDag(const Json& tasks,
                                        std::string* error) const {
  // Existence + cycle check (iterative DFS, colors: 0 white 1 gray 2 black).
  std::map<std::string, int> color;
  for (const auto& [name, task] : tasks.items()) {
    (void)task;
    color[name] = 0;
  }
  for (const auto& [name, task] : tasks.items()) {
    for (const auto& d : TaskDeps(task)) {
      if (!tasks.has(d)) {
        *error = "task `" + name + "` depends on unknown task `" + d + "`";
        return false;
      }
    }
  }
  std::vector<std::pair<std::string, size_t>> stack;
  for (const auto& [root, task] : tasks.items()) {
    (void)task;
    if (color[root] != 0) continue;
    stack.push_back({root, 0});
    color[root] = 1;
    while (!stack.empty()) {
      auto& [name, idx] = stack.back();
      auto deps = TaskDeps(tasks.get(name));
      if (idx < deps.size()) {
        std::string next = deps[idx++];
        if (color[next] == 1) {
          *error = "dependency cycle through `" + next + "`";
          return false;
        }
        if (color[next] == 0) {
          color[next] = 1;
          stack.push_back({next, 0});
        }
      } else {
        color[name] = 2;
        stack.pop_back();
      }
    }
  }
  return true;
}

namespace {

bool NumericValue(const Json& v, double* out) {
  if (v.is_number()) {
    *out = v.as_number();
    return true;
  }
  if (v.is_bool()) {
    *out = v.as_bool(false) ? 1.0 : 0.0;
    return true;
  }
  return false;
}

Json ResolveOperand(const Json& op, const Json& params,
                    const Json& task_statuses) {
  if (op.has("value")) return op.get("value");
  if (op.has("param")) return params.get(op.get("param").as_string());
  if (op.has("task")) {
    return task_statuses.get(op.get("task").as_string()).get("result");
  }
  return Json();
}

// Evaluate one `when` clause. Returns false (with *error set) when the
// operands are not comparable — a authoring bug surfaced as task failure.
bool EvalClause(const Json& clause, const Json& params,
                const Json& task_statuses, bool* result,
                std::string* error) {
  const Json a = ResolveOperand(clause.get("lhs"), params, task_statuses);
  const Json b = ResolveOperand(clause.get("rhs"), params, task_statuses);
  const std::string op = clause.get("op").as_string();
  int cmp;
  double x, y;
  if (NumericValue(a, &x) && NumericValue(b, &y)) {
    cmp = x < y ? -1 : (x > y ? 1 : 0);
  } else if (a.is_string() && b.is_string()) {
    const int c = a.as_string().compare(b.as_string());
    cmp = c < 0 ? -1 : (c > 0 ? 1 : 0);
  } else {
    *error = "condition operands not comparable: " + a.dump() + " " + op +
             " " + b.dump();
    return false;
  }
  if (op == "==") *result = cmp == 0;
  else if (op == "!=") *result = cmp != 0;
  else if (op == ">") *result = cmp > 0;
  else if (op == ">=") *result = cmp >= 0;
  else if (op == "<") *result = cmp < 0;
  else if (op == "<=") *result = cmp <= 0;
  else {
    *error = "unknown condition op: " + op;
    return false;
  }
  return true;
}

}  // namespace

void PipelineRunController::SetPhase(Json* status, const std::string& phase,
                                     const std::string& reason,
                                     const std::string& message) {
  const std::string prev = status->get("phase").as_string();
  (*status)["phase"] = phase;
  if (!status->has("conditions")) (*status)["conditions"] = Json::Array();
  if (prev != phase) {
    Json cond = Json::Object();
    cond["type"] = phase;
    cond["status"] = "True";
    cond["reason"] = reason;
    cond["message"] = message;
    cond["lastTransitionTime"] = Timestamp(now_s_);
    (*status)["conditions"].push_back(cond);
  }
}

void PipelineRunController::LaunchTask(RunView& run, const std::string& tname,
                                       const Json& task) {
  const std::string& rname = run.res.name;
  const Json& comp = task.get("component");
  Json tstatus = run.status.get("tasks").get(tname);

  // Resolve arguments → params + input artifact paths/digests.
  Json params = Json::Object();
  Json inputs = Json::Object();
  Json input_digests = Json::Object();
  for (const auto& [arg_name, arg] : task.get("arguments").items()) {
    if (arg.has("value")) {
      params[arg_name] = arg.get("value");
    } else if (arg.has("param")) {
      params[arg_name] = run.params.get(arg.get("param").as_string());
    } else if (arg.get("collect").is_array()) {
      // ParallelFor fan-in: arrays of upstream artifact paths (launcher
      // stages a symlink dir) or of recorded return values (a json param).
      Json paths = Json::Array();
      Json digests = Json::Array();
      Json values = Json::Array();
      bool artifacts = false;
      for (const auto& e : arg.get("collect").elements()) {
        const std::string src = e.get("task").as_string();
        const Json& src_status = run.status.get("tasks").get(src);
        if (e.has("output")) {
          artifacts = true;
          const std::string out = e.get("output").as_string();
          paths.push_back(src_status.get("outputs").get(out));
          digests.push_back(src_status.get("digests").get(out));
        } else {
          values.push_back(src_status.get("result"));
        }
      }
      if (artifacts) {
        inputs[arg_name] = paths;
        input_digests[arg_name] = digests;
      } else {
        params[arg_name] = values;
      }
    } else if (arg.has("result")) {
      const std::string src = arg.get("task").as_string();
      params[arg_name] = run.status.get("tasks").get(src).get("result");
    } else if (arg.has("task")) {
      const std::string src = arg.get("task").as_string();
      const std::string out = arg.get("output").as_string();
      const Json& src_status = run.status.get("tasks").get(src);
      inputs[arg_name] = src_status.get("outputs").get(out);
      input_digests[arg_name] = src_status.get("digests").get(out);
    }
  }

  // Step-cache fingerprint: component spec + resolved params + input
  // content digests (the KFP v2 cache-key recipe).
  Json fp_doc = Json::Object();
  fp_doc["component"] = comp;
  fp_doc["params"] = params;
  fp_doc["inputs"] = input_digests;
  const std::string fp = Sha256::Hash(fp_doc.dump());
  tstatus["fingerprint"] = fp;

  if (comp.get("cache").as_bool(true)) {
    Json hit = lineage_->Lookup(fp);
    if (hit.is_object()) {
      // Reuse only if every cached artifact still exists on disk.
      bool all_present = true;
      Json outputs = Json::Object();
      Json digests = Json::Object();
      for (const auto& [oname, rec] : hit.get("outputs").items()) {
        const std::string path = rec.get("path").as_string();
        struct stat st;
        if (stat(path.c_str(), &st) != 0) {
          all_present = false;
          break;
        }
        outputs[oname] = path;
        digests[oname] = rec.get("digest");
      }
      if (all_present) {
        tstatus["phase"] = "Cached";
        tstatus["outputs"] = outputs;
        tstatus["digests"] = digests;
        tstatus["cachedFrom"] = hit.get("run").as_string();
        if (!comp.get("returns").as_string().empty()) {
          tstatus["result"] = ReadResultValue(outputs);
        }
        run.status["tasks"][tname] = tstatus;
        metrics_.cache_hits++;
        return;
      }
    }
  }

  // Materialize output dirs + task spec, launch the launcher as a JAXJob.
  Json outputs = Json::Object();
  for (const auto& o : comp.get("outputs").elements()) {
    outputs[o.as_string()] = workdir_ + "/" + rname + "/artifacts/" + tname +
                             "/" + o.as_string();
  }
  if (!comp.get("returns").as_string().empty()) {
    // Implicit artifact for the component's return value.
    outputs[kResultOutput] =
        workdir_ + "/" + rname + "/artifacts/" + tname + "/" + kResultOutput;
  }
  Json task_spec = Json::Object();
  task_spec["component"] = comp;
  task_spec["params"] = params;
  task_spec["inputs"] = inputs;
  task_spec["outputs"] = outputs;
  MkdirP(workdir_ + "/" + rname + "/tasks");
  const std::string spec_path =
      workdir_ + "/" + rname + "/tasks/" + tname + ".json";
  if (!WriteFile(spec_path, task_spec.dump())) {
    tstatus["phase"] = "Failed";
    tstatus["message"] = "cannot write task spec: " + spec_path;
    run.status["tasks"][tname] = tstatus;
    return;
  }

  // Task names contain no '.', so <run>.<task> cannot collide across
  // (run, task) pairs the way '-' joining can (run "a-b"+task "t" vs run
  // "a"+task "b-t").
  const std::string job = rname + "." + tname;
  // A leftover job under this name (crash between job-create and status
  // write, or a deleted earlier run) is stale by construction — this task
  // is Pending, so nothing of ours is running. Replace it.
  if (store_->Get("JAXJob", job)) store_->Delete("JAXJob", job);
  Json job_spec = Json::Object();
  job_spec["replicas"] = comp.get("replicas").as_int(1);
  // TPU placement from the component (kfp-kubernetes analog): chips per
  // process and slice count flow straight into the gang request.
  job_spec["devices_per_proc"] = comp.get("devices_per_proc").as_int(1);
  if (comp.get("num_slices").as_int(1) > 1) {
    job_spec["num_slices"] = comp.get("num_slices");
  }
  if (comp.get("cpu_devices_per_proc").as_int(0) > 0) {
    job_spec["cpu_devices_per_proc"] = comp.get("cpu_devices_per_proc");
  }
  int64_t retries = comp.get("retries").as_int(0);
  job_spec["restart_policy"] = retries > 0 ? "OnFailure" : "Never";
  if (retries > 0) job_spec["backoff_limit"] = retries;
  Json cmd = Json::Array();
  cmd.push_back(python_);
  cmd.push_back("-m");
  cmd.push_back("kubeflow_tpu.pipelines.launcher");
  cmd.push_back("--spec");
  cmd.push_back(spec_path);
  job_spec["command"] = cmd;
  auto r = store_->Create("JAXJob", job, job_spec);
  if (!r.ok) {
    tstatus["phase"] = "Failed";
    tstatus["message"] = "job create failed: " + r.error;
  } else {
    tstatus["phase"] = "Running";
    tstatus["job"] = job;
    tstatus["outputs"] = outputs;
    metrics_.tasks_launched++;
  }
  run.status["tasks"][tname] = tstatus;
}

void PipelineRunController::CheckRunningTask(RunView& run,
                                             const std::string& tname,
                                             const Json& task) {
  Json tstatus = run.status.get("tasks").get(tname);
  const std::string job = tstatus.get("job").as_string();
  auto j = store_->Get("JAXJob", job);
  if (!j) {
    tstatus["phase"] = "Failed";
    tstatus["message"] = "child job disappeared: " + job;
    run.status["tasks"][tname] = tstatus;
    return;
  }
  const std::string jphase = j->status.get("phase").as_string();
  if (jphase == "Succeeded") {
    Json digests = Json::Object();
    Json lineage_outputs = Json::Object();
    for (const auto& [oname, opath] : tstatus.get("outputs").items()) {
      const std::string digest = DirDigest(opath.as_string());
      digests[oname] = digest;
      Json rec = Json::Object();
      rec["path"] = opath;
      rec["digest"] = digest;
      lineage_outputs[oname] = rec;
    }
    tstatus["digests"] = digests;
    tstatus["phase"] = "Succeeded";
    if (!task.get("component").get("returns").as_string().empty()) {
      tstatus["result"] = ReadResultValue(tstatus.get("outputs"));
    }
    lineage_->Record(tstatus.get("fingerprint").as_string(), run.res.name,
                     tname, lineage_outputs);
    store_->Delete("JAXJob", job);  // harvested; GC the child resource
  } else if (jphase == "Failed") {
    tstatus["phase"] = "Failed";
    tstatus["message"] = "task job failed";
    store_->Delete("JAXJob", job);
  }
  run.status["tasks"][tname] = tstatus;
}

void PipelineRunController::Reconcile(const std::string& name) {
  auto res = store_->Get("PipelineRun", name);
  if (!res || res->deleted) return;
  const std::string phase = res->status.get("phase").as_string();
  if (IsTerminalRun(phase)) return;

  RunView run{*res, Json(), Json(), res->status};
  if (phase.empty()) {
    metrics_.runs_created++;
    SetPhase(&run.status, "Created", "RunCreated", "accepted");
  }

  std::string error;
  if (!ResolveIR(*res, &run, &error)) {
    SetPhase(&run.status, "Failed", "InvalidPipeline", error);
    metrics_.runs_failed++;
    store_->UpdateStatus("PipelineRun", name, run.status);
    return;
  }
  const Json& tasks = run.ir.get("tasks");

  if (!run.status.get("tasks").is_object()) {
    if (!ValidateDag(tasks, &error)) {
      SetPhase(&run.status, "Failed", "InvalidPipeline", error);
      metrics_.runs_failed++;
      store_->UpdateStatus("PipelineRun", name, run.status);
      return;
    }
    Json tmap = Json::Object();
    for (const auto& [tname, task] : tasks.items()) {
      (void)task;
      Json ts = Json::Object();
      ts["phase"] = "Pending";
      tmap[tname] = ts;
    }
    run.status["tasks"] = tmap;
    run.status["pipelineSnapshot"] = run.ir;  // freeze for later passes
  }

  // 1. Harvest running tasks.
  for (const auto& [tname, task] : tasks.items()) {
    if (run.status.get("tasks").get(tname).get("phase").as_string() ==
        "Running") {
      CheckRunningTask(run, tname, task);
    }
  }

  // 2. Fail fast on any failure (Argo failFast): stop in-flight tasks and
  // skip pending ones — EXCEPT exit handlers, which must still run.
  bool any_failed = false;
  for (const auto& [tname, ts] : run.status.get("tasks").items()) {
    (void)tname;
    if (ts.get("phase").as_string() == "Failed") any_failed = true;
  }
  if (any_failed) {
    for (const auto& [tname, ts] : run.status.get("tasks").items()) {
      if (tasks.get(tname).get("exit_handler").as_bool(false)) continue;
      const std::string tp = ts.get("phase").as_string();
      if (tp == "Running") {
        store_->Delete("JAXJob", ts.get("job").as_string());
        Json stopped = ts;
        stopped["phase"] = "Stopped";
        run.status["tasks"][tname] = stopped;
      } else if (tp == "Pending") {
        Json skipped = ts;
        skipped["phase"] = "Skipped";
        skipped["reason"] = "RunFailed";
        run.status["tasks"][tname] = skipped;
      }
    }
  }

  // 3. Schedule pending tasks: exit handlers fire when their scope is
  // terminal; ordinary tasks skip-cascade, evaluate their `when` clauses,
  // then launch.
  for (const auto& [tname, task] : tasks.items()) {
    Json ts = run.status.get("tasks").get(tname);
    if (ts.get("phase").as_string() != "Pending") continue;

    if (task.get("exit_handler").as_bool(false)) {
      bool scope_terminal = true;
      for (const auto& s : task.get("scope").elements()) {
        if (!TaskTerminal(run.status.get("tasks")
                              .get(s.as_string())
                              .get("phase")
                              .as_string())) {
          scope_terminal = false;
          break;
        }
      }
      if (scope_terminal) LaunchTask(run, tname, task);
      continue;
    }

    bool ready = true, skip = false;
    for (const auto& d : TaskDeps(task)) {
      const std::string dp =
          run.status.get("tasks").get(d).get("phase").as_string();
      if (dp == "Skipped" || dp == "Stopped") {
        skip = true;  // dependents of skipped tasks are skipped (KFP)
      } else if (!TaskDone(dp)) {
        ready = false;
      }
    }
    if (skip) {
      ts["phase"] = "Skipped";
      ts["reason"] = "UpstreamSkipped";
      run.status["tasks"][tname] = ts;
      continue;
    }
    if (!ready) continue;
    bool when_ok = true;
    std::string eval_error;
    for (const auto& clause : task.get("when").elements()) {
      bool holds = false;
      if (!EvalClause(clause, run.params, run.status.get("tasks"), &holds,
                      &eval_error)) {
        ts["phase"] = "Failed";
        ts["message"] = eval_error;
        run.status["tasks"][tname] = ts;
        when_ok = false;
        break;
      }
      if (!holds) {
        ts["phase"] = "Skipped";
        ts["reason"] = "ConditionFalse";
        ts["condition"] = clause;
        run.status["tasks"][tname] = ts;
        when_ok = false;
        break;
      }
    }
    if (when_ok) LaunchTask(run, tname, task);
  }

  // 4. Aggregate: the run ends only when every task (exit handlers
  // included) is terminal; skipped tasks count as complete.
  int done = 0, failed = 0, running = 0, skipped = 0, total = 0;
  bool all_terminal = true;
  for (const auto& [tname, ts] : run.status.get("tasks").items()) {
    (void)tname;
    ++total;
    const std::string tp = ts.get("phase").as_string();
    if (!TaskTerminal(tp)) all_terminal = false;
    if (TaskDone(tp)) ++done;
    else if (tp == "Failed") ++failed;
    else if (tp == "Running") ++running;
    else if (tp == "Skipped" || tp == "Stopped") ++skipped;
  }

  if (all_terminal && failed > 0) {
    SetPhase(&run.status, "Failed", "TaskFailed",
             std::to_string(failed) + " task(s) failed");
    metrics_.runs_failed++;
  } else if (all_terminal) {
    SetPhase(&run.status, "Succeeded", "AllTasksSucceeded",
             std::to_string(done) + " done, " + std::to_string(skipped) +
                 " skipped");
    metrics_.runs_succeeded++;
  } else {
    SetPhase(&run.status, "Running", "Executing",
             std::to_string(done) + "/" + std::to_string(total) + " done, " +
                 std::to_string(running) + " running");
  }

  if (run.status.dump() != res->status.dump()) {
    store_->UpdateStatus("PipelineRun", name, run.status);
  }
}

void PipelineRunController::Tick(double now_s) {
  now_s_ = now_s;
  for (const auto& res : store_->List("PipelineRun")) {
    if (!IsTerminalRun(res.status.get("phase").as_string())) {
      Reconcile(res.name);
    }
  }
}

void PipelineRunController::OnDeleted(const Resource& res) {
  if (res.kind != "PipelineRun") return;
  for (const auto& [tname, ts] : res.status.get("tasks").items()) {
    (void)tname;
    if (ts.get("phase").as_string() == "Running") {
      store_->Delete("JAXJob", ts.get("job").as_string());
    }
  }
}

}  // namespace tpk
