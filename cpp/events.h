// Per-job structured event log — the rebuild's EventRecorder.
//
// Upstream, controllers emit Kubernetes Events (kubectl describe shows
// them) and Katib scrapes worker stdout with a regex sidecar for
// metrics; both are replaced here by ONE structured history: an ordered
// `events` array on the resource STATUS (SURVEY.md §5.5 "structured
// JSONL event log per job — events + conditions in our store"). Because
// events live in status, every append rides the normal UpdateStatus →
// WAL path: the history is crash-durable for free and replays with the
// rest of the state (`tpukit events <job>` after a restart shows the
// same Submitted → … → Succeeded story).
//
// Shape of one event:
//   {type: "Normal"|"Warning", reason: "Scheduled", message, timestamp,
//    unix, count, [lastTimestamp, lastUnix]}
// Dedup (the EventRecorder aggregation, tuned for a WAL-backed store):
//   * same (type, reason, message) as the last event → PURE NO-OP. The
//     level-triggered reconcile re-derives "Unschedulable" every 50 ms
//     tick; recording each repeat would write one WAL record per tick
//     for as long as the job pends. The returned status is unchanged,
//     so the caller's only-write-when-changed guard skips the write.
//   * same (type, reason) as the last event, new message → merged into
//     it (count+1, message/lastTimestamp updated): "CheckpointSaved
//     step 100" aggregates onto "step 50", a QuotaExceeded whose
//     used-count moved updates in place. Only the LAST entry is
//     compared — reasons separated by other events (a restart cycle's
//     Restarted → Scheduled → Launched) append normally; that history
//     is real and bounded by backoff_limit.
//   * different reason → appended.
// `merge_same_reason=false` opts a caller out of the second rule:
// distinct STATE TRANSITIONS that share a reason (two ElasticDownsize
// steps, "fsdp 4 -> 2" then "fsdp 2 -> 1") must stay two entries with
// count 1 each — merging would collapse the resize history into one
// event whose count lies about how many transitions happened. The
// exact-repeat no-op still applies (level-triggered reconciles must
// not churn the WAL).
// Bounded at kMaxStatusEvents, trimmed oldest-first (like upstream
// Events, old entries expire; the conditions array keeps the phase
// transitions).

#pragma once

#include <string>

#include "json.h"
#include "util.h"

namespace tpk {

inline constexpr size_t kMaxStatusEvents = 48;

inline Json AppendStatusEvent(Json status, const std::string& type,
                              const std::string& reason,
                              const std::string& message, double now_s,
                              bool merge_same_reason = true) {
  if (!(now_s > 0)) now_s = NowWall();
  Json events = Json::Array();
  if (status.get("events").is_array()) events = status.get("events");
  if (events.size() > 0) {
    const Json& last = events.elements()[events.size() - 1];
    if (last.get("type").as_string() == type &&
        last.get("reason").as_string() == reason) {
      if (last.get("message").as_string() == message) {
        return status;  // exact repeat: no-op, no status churn
      }
      if (merge_same_reason) {
        Json rebuilt = Json::Array();
        for (size_t i = 0; i + 1 < events.size(); ++i) {
          rebuilt.push_back(events.elements()[i]);
        }
        Json merged = last;
        merged["count"] = last.get("count").as_int(1) + 1;
        merged["message"] = message;
        merged["lastTimestamp"] = Timestamp(now_s);
        merged["lastUnix"] = now_s;
        rebuilt.push_back(merged);
        status["events"] = rebuilt;
        return status;
      }
      // merge_same_reason=false: fall through to append a new entry.
    }
  }
  Json ev = Json::Object();
  ev["type"] = type;
  ev["reason"] = reason;
  ev["message"] = message;
  ev["timestamp"] = Timestamp(now_s);
  ev["unix"] = now_s;
  ev["count"] = 1;
  events.push_back(ev);
  if (events.size() > kMaxStatusEvents) {
    Json trimmed = Json::Array();
    for (size_t i = events.size() - kMaxStatusEvents; i < events.size();
         ++i) {
      trimmed.push_back(events.elements()[i]);
    }
    events = trimmed;
  }
  status["events"] = events;
  return status;
}

}  // namespace tpk
