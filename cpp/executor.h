// Process executor — the kubelet stand-in (SURVEY.md §7.1 layer 4).
//
// Upstream, the training-operator creates Pods and kubelet runs containers;
// process exit codes flow back through pod phases. Here the executor
// fork/execs local worker processes with injected env (the TPK_* bootstrap
// contract) and reports exits. The interface is narrow so a real TPU-VM/GKE
// executor can slot in behind it later.
//
// The `FakeExecutor` records would-launch specs and lets tests flip process
// status by hand — the envtest trick from the reference's controller tests
// (SURVEY.md §4.2), minus Kubernetes.

#pragma once

#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace tpk {

struct LaunchSpec {
  std::string id;           // unique process id: "<job>/<replica>"
  std::vector<std::string> argv;
  std::map<std::string, std::string> env;  // added to inherited environ
  std::string stdout_path;  // log files ("" = inherit)
  std::string stderr_path;
};

struct ProcessStatus {
  enum class Phase { kPending, kRunning, kSucceeded, kFailed };
  Phase phase = Phase::kPending;
  int exit_code = -1;
  int pid = -1;
};

class ExecutorInterface {
 public:
  virtual ~ExecutorInterface() = default;
  // Launch all specs (gang). Returns false (launching nothing) if any spawn
  // fails — gang atomicity at the process level.
  virtual bool LaunchGang(const std::vector<LaunchSpec>& specs,
                          std::string* error) = 0;
  virtual void Kill(const std::string& id) = 0;
  virtual ProcessStatus Status(const std::string& id) const = 0;
  // Reap exited children; returns ids whose status changed.
  virtual std::vector<std::string> Poll() = 0;
};

class LocalExecutor : public ExecutorInterface {
 public:
  bool LaunchGang(const std::vector<LaunchSpec>& specs,
                  std::string* error) override;
  void Kill(const std::string& id) override;
  ProcessStatus Status(const std::string& id) const override;
  std::vector<std::string> Poll() override;

 private:
  int Spawn(const LaunchSpec& spec, std::string* error);

  mutable std::mutex mu_;
  std::map<std::string, ProcessStatus> procs_;
  std::map<int, std::string> by_pid_;
};

class FakeExecutor : public ExecutorInterface {
 public:
  bool LaunchGang(const std::vector<LaunchSpec>& specs,
                  std::string* error) override {
    if (fail_next_launch) {
      if (error) *error = "fake: launch failure injected";
      fail_next_launch = false;
      return false;
    }
    for (const auto& s : specs) {
      launched.push_back(s);
      procs_[s.id] = {ProcessStatus::Phase::kRunning, -1, 9999};
    }
    return true;
  }
  void Kill(const std::string& id) override {
    killed.push_back(id);
    auto it = procs_.find(id);
    if (it != procs_.end() &&
        it->second.phase == ProcessStatus::Phase::kRunning) {
      it->second = {ProcessStatus::Phase::kFailed, 137, -1};
      changed_.push_back(id);
    }
  }
  ProcessStatus Status(const std::string& id) const override {
    auto it = procs_.find(id);
    return it == procs_.end() ? ProcessStatus{} : it->second;
  }
  std::vector<std::string> Poll() override {
    auto out = changed_;
    changed_.clear();
    return out;
  }

  // Test hooks: flip a process's terminal state (the "envtest" lever).
  void Finish(const std::string& id, int exit_code) {
    procs_[id] = {exit_code == 0 ? ProcessStatus::Phase::kSucceeded
                                 : ProcessStatus::Phase::kFailed,
                  exit_code, -1};
    changed_.push_back(id);
  }

  std::vector<LaunchSpec> launched;
  std::vector<std::string> killed;
  bool fail_next_launch = false;

 private:
  std::map<std::string, ProcessStatus> procs_;
  std::vector<std::string> changed_;
};

}  // namespace tpk
