// Replicated control plane (ISSUE 11) — WAL shipping, quorum acks,
// lease-based failover, follower-served reads/watches.
//
// The design is the Raft/etcd lineage scaled to this repo's shapes: the
// framed, group-committed WAL (store.h) IS the replication log, so the
// leader ships each open batch's exact framed bytes to its followers
// over the existing newline-JSON socket protocol (`repl.append` /
// `repl.snapshot` verbs served by cpp/server.cc) and the group-commit
// reply staging becomes the quorum gate: staged replies release only
// once a majority of the replica set — leader included — has the batch
// durable.
//
//   * Ship-then-commit: CommitQuorum ships the open batch to followers
//     FIRST (each lands it durably per its own --fsync policy and acks),
//     then runs the local covering fsync. A batch the quorum rejects is
//     aborted before any local byte lands (Store::AbortBatch — the
//     whole-batch rollback contract of ISSUE 8, so nothing was promised
//     and nothing dirty leaks), and the leader steps down: a leader that
//     cannot reach a majority must not serve.
//   * Commit index: followers append-and-fsync immediately but APPLY
//     only up to the leader's shipped commitSeq (piggybacked on every
//     append/heartbeat), so a follower never serves state the quorum
//     may abort. Follower lag is therefore bounded by one heartbeat.
//   * Leases + elections: followers track leader contact; when the
//     lease (--lease-ms) expires they campaign with term+1, voting
//     gated by term AND log seq (a candidate must be at least as long
//     as the voter's log) AND lease freshness (a replica that still
//     hears its leader refuses to depose it). Majority wins; terms and
//     votes persist across restarts (<wal>.replstate). A deposed or
//     stale leader's appends are rejected by term — the fencing the
//     kill-9 failover harness proves.
//   * Catch-up: a follower whose log diverges (behind after a crash,
//     or ahead with records a quorum-failed leader rolled back)
//     answers needSnapshot; the leader ships its snapshot + WAL tail
//     verbatim (the compaction machinery's files) and the follower
//     reloads from them (Store::InstallReplica) — leader-authoritative,
//     exactly a restart replay.
//
// Threading: every member runs on the owning event-loop thread (the
// same single thread that runs Server::PollOnce and the controllers);
// the Store keeps its own lock. Peer RPCs are synchronous with bounded
// timeouts — while the leader waits for quorum the event loop stalls,
// which is the honest behavior: no progress is safe without a majority.

#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "json.h"
#include "store.h"

namespace tpk {

class Replication {
 public:
  enum class Role { kLeader, kFollower };

  struct Options {
    std::string self;                // our server socket path (identity)
    std::vector<std::string> peers;  // the other replicas' socket paths
    std::string state_path;          // term/vote persistence ("" = none)
    std::string leader_hint;         // --replica-of: where the leader is
    int lease_ms = 1500;             // leader lease / election timeout
    int quorum_timeout_ms = 5000;    // max stall waiting for quorum
  };

  Replication(Store* store, Options opts);

  bool enabled() const { return !opts_.peers.empty(); }
  bool IsLeader() const { return role_ == Role::kLeader; }
  Role role() const { return role_; }
  int64_t term() const { return term_; }
  const std::string& leader() const { return leader_; }
  // Majority of the replica set (peers + self): ⌈(N+1)/2⌉ of N+1... for
  // N total replicas, floor(N/2)+1.
  int quorum() const {
    return static_cast<int>(opts_.peers.size() + 1) / 2 + 1;
  }

  // The leader's commit path: ship the store's open batch to followers,
  // wait (bounded by quorum_timeout_ms) for majority durability, then
  // land the local covering fsync. True = the batch is quorum-durable
  // and staged replies may release. False = the batch was rolled back
  // whole (quorum unreachable → AbortBatch + step-down, or local commit
  // failure → CommitGroup's own rollback) and staged batch-dependent
  // replies must become errors. With no batch open this is a plain
  // (no-op) CommitGroup.
  bool CommitQuorum(std::string* error);

  // Follower-side verb handlers (dispatched by cpp/server.cc).
  Json HandleAppend(const Json& req);
  Json HandleSnapshot(const Json& req);
  Json HandleVote(const Json& req);

  // Heartbeats (leader), lease expiry + elections (follower). Call once
  // per event-loop pass.
  void Tick();

  // True exactly once after each transition INTO leadership — the main
  // loop's cue to run controller Recover() against the applied state.
  bool TookLeadership();

  // stateinfo's replication{} object.
  Json StateJson() const;

 private:
  struct Peer {
    std::string sock;
    int fd = -1;
    uint64_t acked_seq = 0;
    bool reachable = false;
  };

  double NowMs() const;
  void PersistState();
  void LoadState();
  void BecomeLeader();
  void StepDown(const std::string& reason, int64_t new_term);
  void ResetElectionDeadline(bool short_fuse);
  void RunElection();
  void SendHeartbeats();
  // One synchronous request/reply line to a peer (connect cached on the
  // Peer, reconnected on failure). False on transport failure/timeout.
  bool PeerRequest(Peer& p, const Json& req, Json* resp, int timeout_ms);
  // Ship `batch` to every follower not yet known to hold it, handling
  // needSnapshot catch-up inline. Returns follower acks at/above
  // batch.last_seq observed THIS call.
  int ShipRound(const Store::BatchBytes& batch, int timeout_ms);
  bool ShipSnapshotTo(Peer& p, int timeout_ms);

  Store* store_;
  Options opts_;
  Role role_ = Role::kFollower;
  int64_t term_ = 0;
  std::string voted_for_;
  std::string leader_;           // last known leader ("" = unknown)
  std::vector<Peer> peers_;
  uint64_t commit_seq_ = 0;      // highest quorum-durable seq
  double last_contact_ms_ = 0;   // follower: last valid leader append
  double last_quorum_ok_ms_ = 0; // leader: last round that saw majority
  double last_heartbeat_ms_ = 0;
  double election_deadline_ms_ = 0;
  bool leadership_gained_ = false;
  unsigned rng_state_;           // jitter for election deadlines
  // Counters for stateinfo.replication.
  int64_t shipped_batches_ = 0;
  int64_t quorum_commits_ = 0;
  int64_t quorum_failures_ = 0;
  int64_t snapshots_shipped_ = 0;
  int64_t elections_ = 0;
  int64_t stale_rejections_ = 0;  // appends we rejected for stale term
  int64_t heartbeats_sent_ = 0;
};

}  // namespace tpk
