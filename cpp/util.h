// Shared control-plane helpers (single home for what grew copies in each
// controller: wall clock, RFC3339 timestamps, ephemeral port probing).

#pragma once

#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <ctime>
#include <string>

namespace tpk {

inline double NowWall() { return static_cast<double>(time(nullptr)); }

inline std::string Timestamp(double now_s) {
  char buf[32];
  time_t t = static_cast<time_t>(now_s ? now_s : NowWall());
  struct tm tmv;
  gmtime_r(&t, &tmv);
  strftime(buf, sizeof(buf), "%Y-%m-%dT%H:%M:%SZ", &tmv);
  return buf;
}

// Finds a free TCP port on loopback (coordinator/server endpoints). The
// usual bind(0)/close race applies; callers treat collisions as a normal
// launch failure and retry.
inline int FreePort() {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 0;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  int port = 0;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
    socklen_t len = sizeof(addr);
    if (getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) == 0) {
      port = ntohs(addr.sin_port);
    }
  }
  close(fd);
  return port;
}

}  // namespace tpk
