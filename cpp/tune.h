// Experiment/Trial controllers — the Katib-equivalent HPO layer
// (SURVEY.md §2.3, §3.4, §7.1 item 7).
//
// Semantics carried over from the reference's three Go reconcilers:
//   - ExperimentReconciler (⟨katib: pkg/controller.v1beta1/experiment/⟩):
//     goal / maxTrials / maxFailedTrials accounting, parallelism cap,
//     optimal-trial tracking in status.
//   - SuggestionReconciler (⟨katib: pkg/controller.v1beta1/suggestion/⟩):
//     here a single shared suggestion service process spoken to over
//     JSON-lines pipes (the gRPC GetSuggestions contract, different wire).
//   - TrialReconciler (⟨katib: pkg/controller.v1beta1/trial/⟩): materializes
//     the trialTemplate with ${param} substitution into a child JAXJob and
//     harvests the objective metric when it finishes.
// The metrics-collector sidecar (⟨katib: cmd/metricscollector⟩) collapses
// into direct log parsing: the runtime emits JSONL step metrics to the
// worker log, with a `metric=value` stdout-regex fallback for arbitrary
// user commands — feature parity with the reference's collector kinds.
// Early stopping implements the medianstop rule
// (⟨katib: pkg/earlystopping/v1beta1⟩).

#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "json.h"
#include "store.h"

namespace tpk {

// GetSuggestions(experiment, trials, count) — the api.proto Suggestion
// service contract.
class SuggestionInterface {
 public:
  virtual ~SuggestionInterface() = default;
  // `pending` (may be null): empty assignments + pending=true means "the
  // algorithm is waiting on running trials" (hyperband rung promotion) —
  // NOT search-space exhaustion; the controller retries later.
  virtual bool GetSuggestions(const Json& experiment_spec, const Json& trials,
                              int count, Json* assignments,
                              std::string* error, bool* pending = nullptr) = 0;
};

// Spawns `python -m kubeflow_tpu.tune.service` lazily and speaks
// newline-delimited JSON over its stdin/stdout. Respawns on EOF/death.
class SubprocessSuggestion : public SuggestionInterface {
 public:
  explicit SubprocessSuggestion(std::string python = "python3");
  ~SubprocessSuggestion() override;
  bool GetSuggestions(const Json& experiment_spec, const Json& trials,
                      int count, Json* assignments, std::string* error,
                      bool* pending = nullptr) override;

 private:
  bool EnsureRunning(std::string* error);
  void Shutdown();

  std::string python_;
  int pid_ = -1;
  int in_fd_ = -1;   // write end of child's stdin
  int out_fd_ = -1;  // read end of child's stdout
  std::string out_buf_;
  int timeout_ms_ = 15000;
};

// Test double: serves assignments from a queue (the envtest lever).
class FakeSuggestion : public SuggestionInterface {
 public:
  bool GetSuggestions(const Json&, const Json& trials, int count,
                      Json* assignments, std::string* error,
                      bool* pending = nullptr) override {
    ++calls;
    last_trials = trials;
    if (fail_next) {
      fail_next = false;
      if (error) *error = "fake: suggestion failure injected";
      return false;
    }
    if (pending) *pending = pending_next;
    *assignments = Json::Array();
    if (pending_next) {
      pending_next = false;
      return true;
    }
    for (int i = 0; i < count && !queue.empty(); ++i) {
      assignments->push_back(queue.front());
      queue.erase(queue.begin());
    }
    return true;
  }
  std::vector<Json> queue;
  Json last_trials;
  int calls = 0;
  bool fail_next = false;
  bool pending_next = false;
};

struct TuneMetrics {
  int64_t experiments_created = 0;
  int64_t experiments_succeeded = 0;
  int64_t experiments_failed = 0;
  int64_t trials_created = 0;
  int64_t trials_early_stopped = 0;
  int64_t suggestion_errors = 0;

  Json ToJson() const {
    Json j = Json::Object();
    j["experiments_created"] = experiments_created;
    j["experiments_succeeded"] = experiments_succeeded;
    j["experiments_failed"] = experiments_failed;
    j["trials_created"] = trials_created;
    j["trials_early_stopped"] = trials_early_stopped;
    j["suggestion_errors"] = suggestion_errors;
    return j;
  }
};

class ExperimentController {
 public:
  ExperimentController(Store* store, SuggestionInterface* suggestion,
                       std::string workdir);

  // Level-triggered reconcile of one experiment. Safe to call repeatedly.
  void Reconcile(const std::string& name);

  // Reconciles every non-terminal experiment (driven from the event loop;
  // trial/job state changes are picked up level-style each pass).
  void Tick(double now_s);

  // Watch hook for kDeleted events on Experiment/Trial: cascades deletion
  // to child Trials and JAXJobs (upstream: ownerReferences + apiserver GC).
  void OnDeleted(const Resource& res);

  TuneMetrics& metrics() { return metrics_; }

  // ${param} / ${trialParameters.param} / ${trialName} substitution over
  // every string in a JSON template. Exposed for tests.
  static Json Substitute(const Json& tmpl, const Json& params,
                         const std::string& trial_name);

  // Parses (step, value) observations for `metric` out of a worker log:
  // JSONL objects with the metric as a key, else `metric=value` text.
  // Exposed for tests.
  static std::vector<std::pair<double, double>> ParseMetrics(
      const std::string& log_text, const std::string& metric);

 private:
  struct Counts {
    int created = 0, succeeded = 0, failed = 0, early_stopped = 0,
        active = 0;
  };

  void ReconcileTrial(const Json& exp_spec, const std::string& exp_name,
                      const Resource& trial);
  void MaybeEarlyStop(const Json& exp_spec, const std::string& exp_name,
                      const std::vector<Resource>& trials);
  std::string ReadWorkerLog(const std::string& job_name) const;
  double ObjectiveValue(const std::vector<std::pair<double, double>>& obs,
                        const Json& objective, bool* ok) const;
  void SetPhase(Json* status, const std::string& phase,
                const std::string& reason, const std::string& message);

  Store* store_;
  SuggestionInterface* suggestion_;
  std::string workdir_;
  TuneMetrics metrics_;
  double now_s_ = 0;
  // Per-job log size at last parse: the event loop reconciles ~20x/s and
  // worker logs reach MBs — only re-parse when the file has grown.
  std::map<std::string, long> log_size_seen_;
};

}  // namespace tpk
