#include "jaxjob.h"

#include "admission.h"

#include "events.h"

#include "util.h"

#include <netinet/in.h>
#include <sys/socket.h>
#include <signal.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <ctime>

namespace tpk {

namespace {

bool IsTerminal(const std::string& phase) {
  return phase == "Succeeded" || phase == "Failed";
}

}  // namespace

JaxJobController::JaxJobController(Store* store, ExecutorInterface* executor,
                                   Scheduler* scheduler, std::string workdir,
                                   std::string python)
    : store_(store),
      executor_(executor),
      scheduler_(scheduler),
      workdir_(std::move(workdir)),
      python_(std::move(python)) {
  mkdir(workdir_.c_str(), 0755);
}

std::string JaxJobController::ProcId(const std::string& job, int replica) {
  return job + "/" + std::to_string(replica);
}

Allocation JaxJobController::AllocFromStatus(const Json& status) const {
  Allocation alloc;
  for (const auto& [name, n] : status.get("allocation").items()) {
    alloc.slices[name] = static_cast<int>(n.as_int());
  }
  return alloc;
}

namespace {

// The one normalization rule for tenancy lives in admission.h
// (SpecNamespace; Python mirror: controlplane/client.py namespace_of).
std::string NamespaceOf(const Json& spec) { return SpecNamespace(spec); }

// fsdp elasticity policy parsed from spec.elastic. Enabled iff
// elastic.min_fsdp >= 1 AND runtime.fsdp >= 1 (admission enforces both
// plus the divisibility contract; the re-checks here keep the controller
// safe against specs that predate admission).
struct FsdpPolicy {
  bool enabled = false;
  bool auto_resize = true;  // resize_policy "auto" (default) | "manual"
  int base = 0;             // runtime.fsdp as submitted
  int min = 0;              // elastic.min_fsdp
  int max = 0;              // elastic.max_fsdp (default: base)
};

FsdpPolicy FsdpPolicyOf(const Json& spec) {
  FsdpPolicy p;
  const Json& el = spec.get("elastic");
  if (!el.is_object()) return p;
  const int min_fsdp = static_cast<int>(el.get("min_fsdp").as_int(0));
  if (min_fsdp < 1) return p;
  const int base =
      static_cast<int>(spec.get("runtime").get("fsdp").as_int(0));
  if (base < 1) return p;
  p.enabled = true;
  p.base = base;
  p.min = min_fsdp;
  p.max = static_cast<int>(el.get("max_fsdp").as_int(base));
  if (p.max < base) p.max = base;
  p.auto_resize = el.get("resize_policy").as_string() != "manual";
  return p;
}

// Gang shape for an fsdp size. The fsdp axis spans the whole gang
// (admission pins runtime.fsdp == replicas * devices_per_proc), so a
// resize either drops workers at the spec'd per-proc device share
// (multi-worker downsize) or rescales the per-proc share across
// spec.replicas workers (single-proc CPU meshes, and upsizes past the
// base shape). Returns false when `fsdp` fits neither way — callers
// skip such candidates.
bool FsdpGangShape(const Json& spec, int fsdp, int* replicas, int* devices) {
  const int spec_r =
      std::max(1, static_cast<int>(spec.get("replicas").as_int(1)));
  const int dpp =
      std::max(1, static_cast<int>(spec.get("devices_per_proc").as_int(1)));
  if (fsdp >= dpp && fsdp % dpp == 0 && fsdp / dpp <= spec_r) {
    *replicas = fsdp / dpp;
    *devices = dpp;
    return true;
  }
  if (fsdp >= spec_r && fsdp % spec_r == 0) {
    *replicas = spec_r;
    *devices = fsdp / spec_r;
    return true;
  }
  return false;
}

// Largest resize target below `cur`: a divisor of max_fsdp (the
// master-state sharding plan is anchored there — every leaf dim the
// plan shards is divisible by max_fsdp, hence by any divisor, so the
// plan survives the resize), >= min_fsdp, expressible as a gang shape.
// 0 = no smaller topology exists.
int NextFsdpDown(const Json& spec, const FsdpPolicy& p, int cur) {
  int r = 0, d = 0;
  for (int t = std::min(cur - 1, p.max); t >= p.min; --t) {
    if (p.max % t != 0) continue;
    if (!FsdpGangShape(spec, t, &r, &d)) continue;
    return t;
  }
  return 0;
}

}  // namespace

void JaxJobController::SetPhase(JobView& job, const std::string& phase,
                                const std::string& reason,
                                const std::string& message, double now_s) {
  const std::string prev = job.status.get("phase").as_string();
  job.status["phase"] = phase;
  Json cond = Json::Object();
  cond["type"] = phase;
  cond["status"] = "True";
  cond["reason"] = reason;
  cond["message"] = message;
  cond["lastTransitionTime"] = Timestamp(now_s ? now_s : NowWall());
  if (!job.status.has("conditions")) job.status["conditions"] = Json::Array();
  const Json& conds = job.status.get("conditions");
  const std::string last_reason =
      conds.size() > 0
          ? conds.elements()[conds.size() - 1].get("reason").as_string()
          : "";
  // Record phase transitions AND reason changes within a phase (a Pending
  // job moving Unschedulable -> QuotaExceeded must not keep showing the
  // stale reason). Bounded: non-terminal reasons can flap.
  if (prev != phase || last_reason != reason) {
    job.status["conditions"].push_back(cond);
    if (job.status.get("conditions").size() > 20) {
      Json trimmed = Json::Array();
      const Json& all = job.status.get("conditions");
      for (size_t i = all.size() - 20; i < all.size(); ++i) {
        trimmed.push_back(all.elements()[i]);
      }
      job.status["conditions"] = trimmed;
    }
  }
}

void JaxJobController::AppendEvent(JobView& job, const std::string& type,
                                   const std::string& reason,
                                   const std::string& message,
                                   bool merge_same_reason) {
  job.status = AppendStatusEvent(job.status, type, reason, message,
                                 now_s_ ? now_s_ : NowWall(),
                                 merge_same_reason);
}

void JaxJobController::KillAll(const JobView& job) {
  int replicas = static_cast<int>(job.spec.get("replicas").as_int(1));
  for (int i = 0; i < replicas; ++i) {
    executor_->Kill(ProcId(job.res.name, i));
  }
}

void JaxJobController::ReleaseAlloc(JobView& job) {
  if (job.status.get("allocation").is_object() &&
      job.status.get("allocation").size() > 0) {
    scheduler_->Release(AllocFromStatus(job.status));
    job.status["allocation"] = Json::Object();
  }
}

void JaxJobController::ElasticResize(JobView& job, int target,
                                     const std::string& phase,
                                     const std::string& reason,
                                     const std::string& message,
                                     bool count_restart) {
  AppendEvent(job, "Normal", reason, message);
  job.status["effectiveReplicas"] = target;
  job.status["lastResizeUnix"] = now_s_ ? now_s_ : NowWall();
  if (count_restart) {
    job.status["restarts"] = job.status.get("restarts").as_int(0) + 1;
  }
  metrics_.elastic_resizes++;
  SetPhase(job, phase, reason, message, now_s_);
}

int64_t JaxJobController::UsedInNamespace(const std::string& ns,
                                          const std::string& exclude) const {
  int64_t used = 0;
  for (const auto& other : store_->List("JAXJob")) {
    if (other.name == exclude) continue;
    if (NamespaceOf(other.spec) != ns) continue;
    const Json& oalloc = other.status.get("allocation");
    if (oalloc.is_object() && oalloc.size() > 0) {
      for (const auto& [slice, n] : oalloc.items()) {
        (void)slice;
        used += n.as_int();
      }
    }
  }
  return used;
}

int JaxJobController::EffectiveReplicas(const JobView& job) const {
  int spec_r = static_cast<int>(job.spec.get("replicas").as_int(1));
  int eff = static_cast<int>(
      job.status.get("effectiveReplicas").as_int(spec_r));
  if (eff < 1) eff = 1;
  if (eff > spec_r) eff = spec_r;
  return eff;
}

int JaxJobController::EffectiveFsdp(const JobView& job) const {
  const FsdpPolicy p = FsdpPolicyOf(job.spec);
  if (!p.enabled) return 0;
  int eff = static_cast<int>(job.status.get("effectiveFsdp").as_int(p.base));
  if (eff < p.min) eff = p.min;
  if (eff > p.max) eff = p.max;
  return eff;
}

void JaxJobController::ElasticResizeFsdp(JobView& job, int from, int target,
                                         const std::string& phase,
                                         const std::string& reason,
                                         const std::string& detail,
                                         bool count_restart) {
  int from_r = 0, from_d = 0, to_r = 0, to_d = 0;
  FsdpGangShape(job.spec, from, &from_r, &from_d);
  FsdpGangShape(job.spec, target, &to_r, &to_d);
  // The event carries the old -> new topology in full (fsdp axis AND
  // the derived gang shape); merge is disabled so two distinct
  // transitions sharing this reason stay two entries (events.h).
  const std::string message =
      "fsdp " + std::to_string(from) + " -> " + std::to_string(target) +
      " (gang " + std::to_string(from_r) + "x" + std::to_string(from_d) +
      " -> " + std::to_string(to_r) + "x" + std::to_string(to_d) +
      " procs x devices): " + detail;
  AppendEvent(job, "Normal", reason, message, /*merge_same_reason=*/false);
  job.status["effectiveFsdp"] = target;
  if (to_r >= 1) job.status["effectiveReplicas"] = to_r;
  job.status["lastResizeUnix"] = now_s_ ? now_s_ : NowWall();
  if (count_restart) {
    job.status["restarts"] = job.status.get("restarts").as_int(0) + 1;
  }
  metrics_.elastic_resizes++;
  SetPhase(job, phase, reason, message, now_s_);
}

void JaxJobController::LaunchGang(JobView& job) {
  const std::string& name = job.res.name;
  int replicas = EffectiveReplicas(job);
  int devices = static_cast<int>(job.spec.get("devices_per_proc").as_int(1));
  int num_slices = static_cast<int>(job.spec.get("num_slices").as_int(1));
  const int spec_devices = devices;
  const FsdpPolicy fsdp_policy = FsdpPolicyOf(job.spec);
  const int eff_fsdp = fsdp_policy.enabled ? EffectiveFsdp(job) : 0;
  if (eff_fsdp >= 1) {
    // fsdp-elastic gangs derive their shape from the effective fsdp
    // size — the axis spans the gang's devices, so a resize is a new
    // (replicas, devices_per_proc) pair, re-derived here every launch
    // (status survives controller restarts; the shape must too).
    int r = 0, d = 0;
    if (FsdpGangShape(job.spec, eff_fsdp, &r, &d)) {
      replicas = r;
      devices = d;
    }
  }

  // Namespace device quota — the Profile-controller stub (SURVEY.md §2.5
  // row "Profile", §7.4 descope: namespace field + quota, no RBAC/Istio).
  // A Profile resource named like the namespace caps the devices its
  // running JAXJobs may hold; jobs without a namespace live in "default".
  const std::string ns = NamespaceOf(job.spec);
  auto profile = store_->Get("Profile", ns);
  if (profile) {
    int64_t quota = profile->spec.get("max_devices").as_int(-1);
    if (quota >= 0) {
      int64_t used = UsedInNamespace(ns, name);
      if (used + static_cast<int64_t>(replicas) * devices > quota) {
        AppendEvent(job, "Warning", "QuotaExceeded",
                    "namespace " + ns + " quota " + std::to_string(quota) +
                        " devices; " + std::to_string(used) + " in use");
        SetPhase(job, "Pending", "QuotaExceeded",
                 "namespace " + ns + " quota " + std::to_string(quota) +
                     " devices; " + std::to_string(used) + " in use",
                 now_s_);
        return;
      }
    }
  }

  auto alloc = scheduler_->Allocate(replicas * devices, num_slices);
  if (!alloc) {
    // Elastic downsize on scarce capacity: rather than pending forever at
    // the full size, walk the gang down toward elastic.min one step per
    // reconcile — the checkpoint-resume path reshards to whatever size
    // finally fits (SURVEY.md §2.6 Elastic DP).
    if (fsdp_policy.enabled && fsdp_policy.auto_resize &&
        eff_fsdp > fsdp_policy.min) {
      const int t = NextFsdpDown(job.spec, fsdp_policy, eff_fsdp);
      if (t >= 1) {
        // No gang attempt was consumed — the workers never launched.
        ElasticResizeFsdp(job, eff_fsdp, t, "Pending", "ElasticDownsize",
                          "insufficient capacity; retrying smaller",
                          /*count_restart=*/false);
        return;
      }
    }
    const Json& el = job.spec.get("elastic");
    int min_r = static_cast<int>(el.get("min").as_int(0));
    if (el.is_object() && min_r >= 1 && replicas > min_r) {
      // No gang attempt was consumed — the workers never launched.
      ElasticResize(job, replicas - 1, "Pending", "ElasticDownsize",
                    "insufficient capacity for " + std::to_string(replicas) +
                        " workers; retrying at " +
                        std::to_string(replicas - 1),
                    /*count_restart=*/false);
      return;
    }
    AppendEvent(job, "Warning", "Unschedulable",
                "insufficient slice capacity for gang");
    SetPhase(job, "Pending", "Unschedulable",
             "insufficient slice capacity for gang", now_s_);
    return;
  }
  // Allocation granted — the "Scheduled" moment (kube-scheduler's Bind
  // event analog): record which slices host the gang.
  {
    std::string placed;
    for (const auto& [slice, n] : alloc->slices) {
      if (!placed.empty()) placed += ",";
      placed += slice + "=" + std::to_string(n);
    }
    AppendEvent(job, "Normal", "Scheduled",
                std::to_string(replicas) + " worker(s) on " + placed);
  }

  // Job workdir: spec file + per-replica logs.
  std::string dir = workdir_ + "/" + name;
  mkdir(dir.c_str(), 0755);
  std::string spec_path = dir + "/runtime.json";
  {
    Json runtime = job.spec.get("runtime");
    // An fsdp resize lands in the worker through runtime.json: the
    // relaunched gang reads the resized topology at startup and
    // reshards its checkpoint to it — the spec itself is never edited
    // (the submitted runtime.fsdp stays the declared intent).
    if (eff_fsdp >= 1 && runtime.is_object() &&
        static_cast<int>(runtime.get("fsdp").as_int(0)) != eff_fsdp) {
      runtime["fsdp"] = eff_fsdp;
      if (runtime.get("mesh").is_object() &&
          runtime.get("mesh").has("fsdp")) {
        Json mesh = runtime.get("mesh");
        mesh["fsdp"] = eff_fsdp;
        runtime["mesh"] = mesh;
      }
    }
    FILE* f = fopen(spec_path.c_str(), "w");
    if (f) {
      std::string text = runtime.is_null() ? "{}" : runtime.dump();
      bool ok = fwrite(text.data(), 1, text.size(), f) == text.size();
      ok = fclose(f) == 0 && ok;
      // A torn spec must not reach the worker: a missing file fails the
      // replica loudly at startup instead of silently training a
      // truncated runtime config.
      if (!ok) remove(spec_path.c_str());
    }
  }

  int port = FreePort();
  std::string coordinator = "127.0.0.1:" + std::to_string(port);
  int cpu_devices =
      static_cast<int>(job.spec.get("cpu_devices_per_proc").as_int(0));
  // CPU meshes virtualize devices per proc — an fsdp resize must scale
  // the virtual-device count with the per-proc device share or the
  // relaunched worker would build the old mesh.
  if (cpu_devices > 0 && eff_fsdp >= 1 && devices != spec_devices &&
      (cpu_devices * devices) % spec_devices == 0) {
    cpu_devices = cpu_devices * devices / spec_devices;
  }

  std::vector<LaunchSpec> specs;
  for (int i = 0; i < replicas; ++i) {
    LaunchSpec s;
    s.id = ProcId(name, i);
    s.argv = {python_, "-m", "kubeflow_tpu.train.trainer", "--spec",
              spec_path};
    if (cpu_devices > 0) {
      s.argv.push_back("--cpu-devices");
      s.argv.push_back(std::to_string(cpu_devices));
      // Keep the axon sitecustomize from force-selecting the TPU platform
      // in CPU-mode workers (it overrides JAX_PLATFORMS via jax.config).
      s.env["PALLAS_AXON_POOL_IPS"] = "";
      // Custom-command workers (e.g. the pipeline launcher) don't get
      // the --cpu-devices flag (the default argv is replaced below); the
      // launcher honors the env form instead (pipelines/launcher.py).
      s.env["TPK_CPU_DEVICES"] = std::to_string(cpu_devices);
    }
    if (job.spec.get("command").is_array()) {
      s.argv.clear();
      for (const auto& a : job.spec.get("command").elements()) {
        s.argv.push_back(a.as_string());
      }
    }
    if (replicas > 1) {
      s.env["TPK_COORDINATOR"] = coordinator;
    }
    s.env["TPK_NUM_PROCS"] = std::to_string(replicas);
    s.env["TPK_PROC_ID"] = std::to_string(i);
    s.env["TPK_NUM_SLICES"] = std::to_string(num_slices);
    s.env["TPK_SLICE_ID"] = std::to_string(i * num_slices / replicas);
    s.env["TPK_JOB_NAME"] = name;
    // The job's workdir (profiler traces land here: the runtime's
    // profile_start_step/profile_stop_step knobs default their trace
    // dir to $TPK_WORKDIR/profile) and the API socket (the runtime
    // posts CheckpointSaved events back into the job's event log).
    s.env["TPK_WORKDIR"] = dir;
    if (!socket_path_.empty()) {
      s.env["TPK_SOCKET"] = socket_path_;
    }
    // First-class fault injection (SURVEY.md §5.3): spec.fault =
    // {proc, step, signal?, every_attempt?} makes worker `proc` kill
    // itself at training step `step` — deterministic, step-precise chaos
    // replacing test-side pgrep/kill timing. By default the fault fires
    // only on the first attempt so the restarted gang can make progress.
    const Json& fault = job.spec.get("fault");
    if (fault.is_object() &&
        static_cast<int>(fault.get("proc").as_int(0)) == i &&
        (fault.get("every_attempt").as_bool(false) ||
         job.status.get("restarts").as_int(0) == 0)) {
      s.env["TPK_FAULT"] =
          "step=" + std::to_string(fault.get("step").as_int(0)) +
          ";signal=" + std::to_string(fault.get("signal").as_int(9));
    }
    s.stdout_path = dir + "/worker-" + std::to_string(i) + ".log";
    s.stderr_path = dir + "/worker-" + std::to_string(i) + ".err";
    specs.push_back(std::move(s));
  }

  std::string error;
  if (!executor_->LaunchGang(specs, &error)) {
    scheduler_->Release(*alloc);
    AppendEvent(job, "Warning", "LaunchFailed", error);
    SetPhase(job, "Pending", "LaunchFailed", error, now_s_);
    return;
  }

  Json alloc_json = Json::Object();
  for (const auto& [slice, n] : alloc->slices) alloc_json[slice] = n;
  job.status["allocation"] = alloc_json;
  job.status["coordinator"] = coordinator;
  job.status["active"] = true;
  // Record worker pids so a restarted control plane can reap the orphans
  // it can no longer waitpid (Recover()).
  Json pids = Json::Array();
  for (int i = 0; i < replicas; ++i) {
    pids.push_back(executor_->Status(ProcId(name, i)).pid);
  }
  job.status["pids"] = pids;
  if (!job.status.has("startTime")) {
    job.status["startTime"] = Timestamp(now_s_ ? now_s_ : NowWall());
    job.status["startUnix"] = now_s_ ? now_s_ : NowWall();
  }
  AppendEvent(job, "Normal", "Launched",
              "all " + std::to_string(replicas) + " workers launched");
  SetPhase(job, "Running", "GangLaunched",
           "all " + std::to_string(replicas) + " workers launched", now_s_);
}

void JaxJobController::HandleExits(JobView& job) {
  const std::string& name = job.res.name;
  int replicas = EffectiveReplicas(job);
  int succeeded = 0, failed = 0, running = 0;
  int first_fail_code = 0;
  for (int i = 0; i < replicas; ++i) {
    auto st = executor_->Status(ProcId(name, i));
    switch (st.phase) {
      case ProcessStatus::Phase::kSucceeded: ++succeeded; break;
      case ProcessStatus::Phase::kFailed:
        ++failed;
        if (!first_fail_code) first_fail_code = st.exit_code;
        break;
      case ProcessStatus::Phase::kRunning: ++running; break;
      case ProcessStatus::Phase::kPending: break;
    }
  }
  Json pstat = Json::Object();
  pstat["succeeded"] = succeeded;
  pstat["failed"] = failed;
  pstat["running"] = running;
  job.status["processes"] = pstat;

  if (succeeded == replicas) {
    job.status["active"] = false;
    ReleaseAlloc(job);
    job.status["completionUnix"] = now_s_ ? now_s_ : NowWall();
    AppendEvent(job, "Normal", "Succeeded", "all workers exited 0");
    SetPhase(job, "Succeeded", "AllWorkersSucceeded",
             "all workers exited 0", now_s_);
    metrics_.jobs_succeeded++;
    return;
  }
  if (failed == 0) return;  // still running

  // A worker failed: gang semantics = kill the rest, then decide restart.
  KillAll(job);
  job.status["active"] = false;
  ReleaseAlloc(job);

  const std::string policy =
      job.spec.get("restart_policy").as_string().empty()
          ? "OnFailure"
          : job.spec.get("restart_policy").as_string();
  int64_t backoff = job.spec.get("backoff_limit").as_int(3);
  int64_t restarts = job.status.get("restarts").as_int(0);

  bool retryable = policy == "OnFailure";
  if (policy == "ExitCode") {
    // Upstream training-operator semantics: 1–127 permanent, 128+ retryable.
    retryable = first_fail_code >= 128;
  }
  if (retryable && restarts < backoff) {
    job.status["restarts"] = restarts + 1;
    metrics_.gang_restarts++;
    // ONE event per restart cycle (failure + restart together). Each
    // relaunch still appends Scheduled/Launched between cycles, so
    // cycles don't merge — but total restart history is bounded by
    // backoff_limit (3 events per cycle), and past the 48-entry cap the
    // oldest entries expire like upstream Events; conditions keep the
    // phase transitions.
    AppendEvent(job, "Warning", "Restarted",
                "worker exited " + std::to_string(first_fail_code) +
                    "; gang restart " + std::to_string(restarts + 1) +
                    "/" + std::to_string(backoff));
    SetPhase(job, "Restarting", "WorkerFailed",
             "worker exited " + std::to_string(first_fail_code) +
                 "; gang restart " + std::to_string(restarts + 1) + "/" +
                 std::to_string(backoff),
             now_s_);
    // Relaunch happens on the next Reconcile pass (status write below
    // triggers a watch event → reconcile).
    return;
  }
  // Worker death past the backoff budget: instead of failing the job, an
  // elastic policy resumes at a smaller topology from the latest
  // checkpoint — params reshard to the new mesh (the e2e-proven
  // checkpoint-restart elasticity, now with an automatic trigger;
  // SURVEY.md §2.6 Elastic DP / §5.3 ElasticPolicy analog).
  if (retryable) {
    // fsdp elasticity first: the resize unit is the fsdp axis — pick the
    // next divisor of max_fsdp down (the master-state plan survives any
    // divisor), derive the gang shape, and let the relaunch reshard the
    // checkpoint. Mutually exclusive with replica elasticity (admission).
    const FsdpPolicy fp = FsdpPolicyOf(job.spec);
    const int cur_fsdp = fp.enabled ? EffectiveFsdp(job) : 0;
    if (fp.enabled && fp.auto_resize && cur_fsdp > fp.min) {
      const int target = NextFsdpDown(job.spec, fp, cur_fsdp);
      if (target >= 1) {
        // count_restart: this consumed a gang attempt — per-attempt
        // gates (spec.fault's first-attempt default) must see a nonzero
        // count or the fault would re-arm on every elastic relaunch.
        ElasticResizeFsdp(
            job, cur_fsdp, target, "Restarting", "ElasticDownsize",
            std::to_string(failed) + " worker exit(s) past backoff "
                "(first exit " + std::to_string(first_fail_code) +
                "); resuming from latest checkpoint",
            /*count_restart=*/true);
        return;
      }
    }
    const Json& el = job.spec.get("elastic");
    int min_r = static_cast<int>(el.get("min").as_int(0));
    if (el.is_object() && min_r >= 1 && replicas > min_r) {
      int target = replicas - failed;
      if (target < min_r) target = min_r;
      if (target < 1) target = 1;
      // count_restart: this consumed a gang attempt — per-attempt gates
      // (spec.fault's first-attempt default) must see a nonzero count or
      // the fault would re-arm on every elastic relaunch.
      ElasticResize(job, target, "Restarting", "ElasticDownsize",
                    std::to_string(failed) + " worker(s) lost past "
                        "backoff; resuming at " + std::to_string(target) +
                        "/" +
                        std::to_string(job.spec.get("replicas").as_int(1)) +
                        " from latest checkpoint",
                    /*count_restart=*/true);
      return;
    }
  }
  job.status["completionUnix"] = now_s_ ? now_s_ : NowWall();
  AppendEvent(job, "Warning", "Failed",
              std::string(retryable ? "BackoffLimitExceeded"
                                    : "PermanentFailure") +
                  ": worker exited " + std::to_string(first_fail_code));
  SetPhase(job, "Failed",
           retryable ? "BackoffLimitExceeded" : "PermanentFailure",
           "worker exited " + std::to_string(first_fail_code), now_s_);
  metrics_.jobs_failed++;
}

void JaxJobController::CheckHeartbeats(JobView& job) {
  // Hang detection: a worker that stops writing its log for longer than
  // elastic.heartbeat_timeout_s is treated as dead (the failure detector
  // for workers that wedge without exiting — e.g. a hung collective).
  // Killing it routes through the normal gang-failure path: restart
  // within backoff, elastic downsize past it. Wall-clock on purpose —
  // log mtimes are wall time. The timeout must exceed the job's slowest
  // logging interval (log_every steps).
  const Json& el = job.spec.get("elastic");
  double timeout = el.get("heartbeat_timeout_s").as_number(0);
  if (!(timeout > 0)) return;
  int replicas = EffectiveReplicas(job);
  double now_wall = NowWall();
  for (int i = 0; i < replicas; ++i) {
    std::string log_path = workdir_ + "/" + job.res.name + "/worker-" +
                           std::to_string(i) + ".log";
    struct stat st;
    if (stat(log_path.c_str(), &st) != 0) continue;  // not spawned by us
    double age = now_wall - static_cast<double>(st.st_mtime);
    if (age > timeout) {
      AppendEvent(job, "Warning", "HeartbeatTimeout",
                  "worker " + std::to_string(i) + " silent for " +
                      std::to_string(static_cast<int>(age)) +
                      "s; killing for gang restart");
      SetPhase(job, "Running", "HeartbeatTimeout",
               "worker " + std::to_string(i) + " silent for " +
                   std::to_string(static_cast<int>(age)) + "s (timeout " +
                   std::to_string(static_cast<int>(timeout)) +
                   "s); killing for gang restart",
               now_s_);
      executor_->Kill(ProcId(job.res.name, i));
    }
  }
}

void JaxJobController::MaybeUpsize(JobView& job) {
  // Capacity-driven upsize: a gang running below its desired size (after
  // an elastic downsize) grows back when freed devices can host it —
  // kill, release, relaunch larger; the runtime resumes from the latest
  // checkpoint and reshards up. Cooldown prevents thrash with the
  // downsize path.
  const Json& el = job.spec.get("elastic");
  if (!el.is_object()) return;
  if (FsdpPolicyOf(job.spec).enabled) {
    // fsdp-elastic gangs regrow along the fsdp axis, never the replica
    // path — effectiveReplicas is derived state here and the replica
    // upsize would fight the fsdp shape.
    MaybeUpsizeFsdp(job);
    return;
  }
  int spec_r = static_cast<int>(job.spec.get("replicas").as_int(1));
  int cap = static_cast<int>(el.get("max").as_int(spec_r));
  if (cap > spec_r) cap = spec_r;
  int eff = EffectiveReplicas(job);
  if (eff >= cap) return;
  double cooldown = el.get("upsize_cooldown_s").as_number(30.0);
  double last = job.status.get("lastResizeUnix").as_number(0);
  double now = now_s_ ? now_s_ : NowWall();
  if (last > 0 && now - last < cooldown) return;
  int devices = static_cast<int>(job.spec.get("devices_per_proc").as_int(1));
  int num_slices = static_cast<int>(job.spec.get("num_slices").as_int(1));

  // Find the largest target the scheduler would ACTUALLY grant by
  // probing real allocations (release current, try bigger, put a
  // same-size allocation back on failure). A free-device sum would
  // ignore per-slice fragmentation and num_slices divisibility and kill
  // a healthy gang for an upsize that can never launch. Single-threaded
  // controller: nothing races the probe.
  Allocation current = AllocFromStatus(job.status);
  scheduler_->Release(current);
  int target = 0;
  std::optional<Allocation> probe;
  for (int t = cap; t > eff; --t) {
    probe = scheduler_->Allocate(t * devices, num_slices);
    if (probe) {
      target = t;
      break;
    }
  }
  if (target == 0) {
    // Nothing bigger fits — restore the books for the running gang.
    auto back = scheduler_->Allocate(eff * devices, num_slices);
    if (back) {
      Json alloc_json = Json::Object();
      for (const auto& [slice, n] : back->slices) alloc_json[slice] = n;
      job.status["allocation"] = alloc_json;
    }
    return;
  }
  scheduler_->Release(*probe);  // LaunchGang re-allocates for real

  // Namespace quota headroom must admit the bigger gang too, or the
  // killed job would land in Pending/QuotaExceeded with zero workers.
  const std::string ns = NamespaceOf(job.spec);
  auto profile = store_->Get("Profile", ns);
  int64_t quota =
      profile ? profile->spec.get("max_devices").as_int(-1) : -1;
  if (quota >= 0 && UsedInNamespace(ns, job.res.name) +
                            static_cast<int64_t>(target) * devices >
                        quota) {
    auto back = scheduler_->Allocate(eff * devices, num_slices);
    if (back) {
      Json alloc_json = Json::Object();
      for (const auto& [slice, n] : back->slices) alloc_json[slice] = n;
      job.status["allocation"] = alloc_json;
    }
    return;
  }

  KillAll(job);
  job.status["active"] = false;
  job.status["allocation"] = Json::Object();  // already released above
  ElasticResize(job, target, "Restarting", "ElasticUpsize",
                "capacity freed; growing " + std::to_string(eff) + " -> " +
                    std::to_string(target) +
                    " workers, resuming from latest checkpoint",
                /*count_restart=*/false);
}

void JaxJobController::MaybeUpsizeFsdp(JobView& job) {
  // The fsdp twin of MaybeUpsize: a gang resized below max_fsdp grows
  // back when freed devices can host a bigger divisor — kill, release,
  // relaunch; the runtime reshards its checkpoint up. Same probe
  // discipline (real allocations, restore the books on failure) and the
  // same cooldown keyed on lastResizeUnix to prevent thrash.
  const FsdpPolicy fp = FsdpPolicyOf(job.spec);
  if (!fp.enabled || !fp.auto_resize) return;
  const int cur = EffectiveFsdp(job);
  if (cur >= fp.max) return;
  const Json& el = job.spec.get("elastic");
  double cooldown = el.get("upsize_cooldown_s").as_number(30.0);
  double last = job.status.get("lastResizeUnix").as_number(0);
  double now = now_s_ ? now_s_ : NowWall();
  if (last > 0 && now - last < cooldown) return;
  int num_slices = static_cast<int>(job.spec.get("num_slices").as_int(1));
  int cur_r = 0, cur_d = 0;
  if (!FsdpGangShape(job.spec, cur, &cur_r, &cur_d)) return;

  Allocation current = AllocFromStatus(job.status);
  scheduler_->Release(current);
  int target = 0, tgt_r = 0, tgt_d = 0;
  std::optional<Allocation> probe;
  for (int t = fp.max; t > cur; --t) {
    if (fp.max % t != 0) continue;
    int r = 0, d = 0;
    if (!FsdpGangShape(job.spec, t, &r, &d)) continue;
    probe = scheduler_->Allocate(r * d, num_slices);
    if (probe) {
      target = t;
      tgt_r = r;
      tgt_d = d;
      break;
    }
  }
  if (target == 0) {
    auto back = scheduler_->Allocate(cur_r * cur_d, num_slices);
    if (back) {
      Json alloc_json = Json::Object();
      for (const auto& [slice, n] : back->slices) alloc_json[slice] = n;
      job.status["allocation"] = alloc_json;
    }
    return;
  }
  scheduler_->Release(*probe);  // LaunchGang re-allocates for real

  const std::string ns = NamespaceOf(job.spec);
  auto profile = store_->Get("Profile", ns);
  int64_t quota =
      profile ? profile->spec.get("max_devices").as_int(-1) : -1;
  if (quota >= 0 && UsedInNamespace(ns, job.res.name) +
                            static_cast<int64_t>(tgt_r) * tgt_d >
                        quota) {
    auto back = scheduler_->Allocate(cur_r * cur_d, num_slices);
    if (back) {
      Json alloc_json = Json::Object();
      for (const auto& [slice, n] : back->slices) alloc_json[slice] = n;
      job.status["allocation"] = alloc_json;
    }
    return;
  }

  KillAll(job);
  job.status["active"] = false;
  job.status["allocation"] = Json::Object();  // already released above
  ElasticResizeFsdp(job, cur, target, "Restarting", "ElasticUpsize",
                    "capacity freed; resuming from latest checkpoint",
                    /*count_restart=*/false);
}

bool JaxJobController::MaybeApplyFsdpTarget(JobView& job) {
  // Explicit resize request: elastic.target_fsdp on a Running gang.
  // status.fsdpTargetApplied latches the last honored value — the
  // request fires once per distinct target, so automatic resizes that
  // later move effectiveFsdp away don't re-trigger a stale request.
  const FsdpPolicy fp = FsdpPolicyOf(job.spec);
  if (!fp.enabled) return false;
  const int target = static_cast<int>(
      job.spec.get("elastic").get("target_fsdp").as_int(0));
  if (target < fp.min || target > fp.max || fp.max % target != 0) {
    return false;  // admission refuses these; stale specs just no-op
  }
  const int applied = static_cast<int>(
      job.status.get("fsdpTargetApplied").as_int(0));
  if (target == applied) return false;
  const int cur = EffectiveFsdp(job);
  if (target == cur) {
    job.status["fsdpTargetApplied"] = target;  // already there: latch only
    return false;
  }
  int r = 0, d = 0;
  if (!FsdpGangShape(job.spec, target, &r, &d)) return false;
  KillAll(job);
  job.status["active"] = false;
  ReleaseAlloc(job);
  job.status["fsdpTargetApplied"] = target;
  ElasticResizeFsdp(job, cur, target, "Restarting", "ElasticResizeRequested",
                    "explicit resize request", /*count_restart=*/false);
  return true;
}

void JaxJobController::Recover() {
  // Control-plane restart with a WAL: jobs marked active reference worker
  // processes this process never spawned (reparented orphans) and slice
  // allocations in a scheduler that was rebuilt empty. Kill the orphans
  // (best effort, by recorded pgid), drop the stale allocation, and mark
  // the gang Restarting — the relaunch resumes from the latest checkpoint.
  for (const auto& res : store_->List("JAXJob")) {
    JobView job{res, res.spec, res.status};
    if (!job.status.get("active").as_bool(false)) continue;
    for (const auto& p : job.status.get("pids").elements()) {
      int pid = static_cast<int>(p.as_int(-1));
      if (pid > 1) kill(-pid, SIGKILL);
    }
    job.status["active"] = false;
    job.status["allocation"] = Json::Object();
    int64_t restarts = job.status.get("restarts").as_int(0);
    job.status["restarts"] = restarts + 1;  // counts toward backoff: a
    // crash-looping control plane must not restart gangs forever
    metrics_.gang_restarts++;
    AppendEvent(job, "Warning", "ControlPlaneRestarted",
                "orphaned gang reaped after control-plane restart");
    SetPhase(job, "Restarting", "ControlPlaneRestarted",
             "orphaned gang reaped after control-plane restart", NowWall());
    store_->UpdateStatus("JAXJob", res.name, job.status);
  }
}

void JaxJobController::OnDeleted(const Resource& res) {
  if (!res.status.get("active").as_bool(false)) return;
  JobView job{res, res.spec, res.status};
  KillAll(job);
  ReleaseAlloc(job);
}

void JaxJobController::Reconcile(const std::string& name) {
  metrics_.reconciles++;
  auto res = store_->Get("JAXJob", name);
  if (!res) return;
  JobView job{*res, res->spec, res->status};
  const std::string phase = job.status.get("phase").as_string();

  if (res->deleted) return;

  if (IsTerminal(phase)) {
    return;  // GC handled by Tick (TTL)
  }

  if (phase.empty()) {
    metrics_.jobs_created++;
    AppendEvent(job, "Normal", "Submitted", "job accepted");
    SetPhase(job, "Created", "JobCreated", "accepted", now_s_);
  }

  bool active = job.status.get("active").as_bool(false);
  if (!active) {
    // Created, Pending, or Restarting → try to launch the gang.
    LaunchGang(job);
  } else {
    HandleExits(job);
  }

  // Only write when something changed — UpdateStatus emits a watch event
  // which re-enqueues this reconcile; an unconditional write would loop.
  if (job.status.dump() != res->status.dump()) {
    store_->UpdateStatus("JAXJob", name, job.status);
  }
}

void JaxJobController::Tick(double now_s) {
  now_s_ = now_s;
  // 1) Reap process exits → reconcile owners.
  for (const auto& id : executor_->Poll()) {
    auto slash = id.find('/');
    if (slash != std::string::npos) {
      Reconcile(id.substr(0, slash));
    }
  }
  // 2) Deadlines, TTL GC, and level-triggered retries for non-terminal jobs.
  std::vector<std::string> pending;  // queued jobs; launched under a budget
  for (const auto& res : store_->List("JAXJob")) {
    JobView job{res, res.spec, res.status};
    const std::string phase = job.status.get("phase").as_string();
    if (IsTerminal(phase)) {
      int64_t ttl = job.spec.get("ttl_seconds_after_finished").as_int(-1);
      double done = job.status.get("completionUnix").as_number(0);
      if (ttl >= 0 && done > 0 && now_s - done > ttl) {
        store_->Delete("JAXJob", res.name);
      }
      continue;
    }
    int64_t deadline = job.spec.get("active_deadline_seconds").as_int(0);
    double started = job.status.get("startUnix").as_number(0);
    if (deadline > 0 && started > 0 && now_s - started > deadline &&
        job.status.get("active").as_bool(false)) {
      KillAll(job);
      job.status["active"] = false;
      ReleaseAlloc(job);
      job.status["completionUnix"] = now_s;
      AppendEvent(job, "Warning", "Failed",
                  "DeadlineExceeded: activeDeadlineSeconds exceeded");
      SetPhase(job, "Failed", "DeadlineExceeded",
               "activeDeadlineSeconds exceeded", now_s);
      metrics_.jobs_failed++;
      store_->UpdateStatus("JAXJob", res.name, job.status);
      continue;
    }
    if (phase == "Pending" || phase == "Restarting" || phase.empty()) {
      pending.push_back(res.name);
    }
    if (phase == "Running" && job.status.get("active").as_bool(false)) {
      // An explicit resize request supersedes this tick's health/upsize
      // checks — the gang it would inspect is already being replaced.
      if (!MaybeApplyFsdpTarget(job)) {
        CheckHeartbeats(job);  // hung-worker kills reaped on a later Poll
        MaybeUpsize(job);
      }
      if (job.status.dump() != res.status.dump()) {
        store_->UpdateStatus("JAXJob", res.name, job.status);
      }
    }
  }
  // Bounded round-robin launch sweep over the queue (see jaxjob.h note):
  // the rotating cursor keeps it fair, the budget keeps a 1000-job
  // backlog from monopolizing the event loop every tick.
  const size_t n = pending.size();
  const size_t budget = std::min(n, kMaxPendingLaunchPerTick);
  for (size_t k = 0; k < budget; ++k) {
    Reconcile(pending[(pending_cursor_ + k) % n]);
  }
  pending_cursor_ = n > 0 ? (pending_cursor_ + budget) % n : 0;
}

}  // namespace tpk
