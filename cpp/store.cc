#include "store.h"

#include <fcntl.h>
#include <signal.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <cctype>
#include <cerrno>
#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace tpk {

namespace {

// Test-only seeded crash points (tests/test_crash_recovery.py's
// kill-9-inside-the-commit-window harness): TPK_CRASH_AT="<point>:<n>"
// SIGKILLs the process on the n-th hit of the named point. One getenv at
// first use; zero cost when unset.
void MaybeCrashAt(const char* point) {
  static const char* spec = getenv("TPK_CRASH_AT");
  if (!spec) return;
  const char* colon = strchr(spec, ':');
  if (!colon) return;
  size_t plen = strlen(point);
  if (plen != static_cast<size_t>(colon - spec) ||
      strncmp(spec, point, plen) != 0) {
    return;
  }
  static int hits = 0;  // only the one named point ever increments
  if (++hits == atoi(colon + 1)) {
    fprintf(stderr, "tpk-controlplane: TPK_CRASH_AT %s firing\n", spec);
    kill(getpid(), SIGKILL);
  }
}

// CRC32 (IEEE/zlib polynomial) over the exact payload bytes as written —
// the integrity check that lets Load() tell a torn/bit-flipped record from
// a good one instead of trusting whatever the JSON parser accepts.
uint32_t Crc32(const char* p, size_t n) {
  static const std::array<uint32_t, 256> table = [] {
    std::array<uint32_t, 256> t{};
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      }
      t[i] = c;
    }
    return t;
  }();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) {
    c = table[(c ^ static_cast<unsigned char>(p[i])) & 0xFF] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

// One framed WAL line: `v1 <seq> <crc32hex> <payload>\n`. `crc_out`
// (optional) receives the record's CRC — the replication layer uses the
// log TIP's crc as its entry-identity check (the per-entry-term stand-in:
// two logs that agree on (seq, crc) agree on the record).
std::string FrameRecord(uint64_t seq, const std::string& payload,
                        uint32_t* crc_out = nullptr) {
  uint32_t crc = Crc32(payload.data(), payload.size());
  if (crc_out) *crc_out = crc;
  char head[64];
  snprintf(head, sizeof(head), "v1 %" PRIu64 " %08x ", seq, crc);
  std::string line = head;
  line += payload;
  line += '\n';
  return line;
}

// Splits a framed line (newline already stripped) into seq + payload,
// verifying the CRC. Returns false with *error on any mismatch.
bool ParseFrame(const std::string& line, uint64_t* seq, std::string* payload,
                std::string* error, uint32_t* crc_out = nullptr) {
  size_t sp1 = line.find(' ', 3);
  size_t sp2 = sp1 == std::string::npos ? std::string::npos
                                        : line.find(' ', sp1 + 1);
  if (sp2 == std::string::npos) {
    *error = "malformed frame header";
    return false;
  }
  char* end = nullptr;
  errno = 0;
  unsigned long long s = strtoull(line.c_str() + 3, &end, 10);
  if (errno != 0 || end != line.c_str() + sp1) {
    *error = "bad sequence number in frame header";
    return false;
  }
  unsigned long crc = strtoul(line.c_str() + sp1 + 1, &end, 16);
  if (end != line.c_str() + sp2) {
    *error = "bad crc in frame header";
    return false;
  }
  *payload = line.substr(sp2 + 1);
  uint32_t got = Crc32(payload->data(), payload->size());
  if (got != static_cast<uint32_t>(crc)) {
    char buf[96];
    snprintf(buf, sizeof(buf),
             "crc mismatch at seq %llu (stored %08lx, computed %08x)", s,
             crc, got);
    *error = buf;
    return false;
  }
  *seq = s;
  if (crc_out) *crc_out = got;
  return true;
}

void FsyncDirOf(const std::string& path) {
  // Durability of the rename itself (best effort — not all filesystems
  // support directory fsync, and failure here never loses applied state).
  size_t slash = path.find_last_of('/');
  std::string dir = slash == std::string::npos ? "." : path.substr(0, slash);
  int fd = open(dir.c_str(), O_RDONLY);
  if (fd >= 0) {
    // tpk-lint: allow(cpp-checked-io) reason=deliberate best-effort per the comment above: not every filesystem supports directory fsync, and failure here never loses applied state
    fsync(fd);
    close(fd);
  }
}

}  // namespace

void MaybeCrashAtPoint(const char* point) { MaybeCrashAt(point); }

Store::Store(std::string wal_path) : wal_path_(std::move(wal_path)) {}

Store::~Store() {
  if (wal_) fclose(wal_);
}

void Store::SetFsync(FsyncPolicy policy, int interval_records) {
  std::lock_guard<std::mutex> lock(mu_);
  fsync_policy_ = policy;
  fsync_interval_ = interval_records > 0 ? interval_records : 1;
}

void Store::SetCompactionThreshold(int records) {
  std::lock_guard<std::mutex> lock(mu_);
  compact_threshold_ = records > 0 ? records : 0;
}

void Store::SetGroupCommit(int max_batch) {
  std::lock_guard<std::mutex> lock(mu_);
  group_commit_max_ = max_batch > 0 ? max_batch : 0;
}

int Store::group_commit() const {
  std::lock_guard<std::mutex> lock(mu_);
  return group_commit_max_;
}

int Store::PendingGroupRecords() const {
  std::lock_guard<std::mutex> lock(mu_);
  return batch_records_;
}

bool Store::EnsureWalLocked(std::string* error) {
  if (wal_broken_) {
    if (error) *error = "WAL broken: " + wal_error_;
    return false;
  }
  if (wal_) return true;
  wal_ = fopen(wal_path_.c_str(), "a");
  if (!wal_) {
    wal_broken_ = true;
    wal_error_ = std::string("cannot open ") + wal_path_ + ": " +
                 strerror(errno);
    if (error) *error = "WAL broken: " + wal_error_;
    return false;
  }
  // Unbuffered: fwrite maps 1:1 onto write(2), so a failed append reports
  // a short count immediately and rollback is a plain ftruncate — no
  // stdio buffer left holding half a record to leak into the next append.
  setvbuf(wal_, nullptr, _IONBF, 0);
  return true;
}

bool Store::WalAppendLocked(const Resource& r, std::string* error) {
  if (wal_path_.empty()) return true;  // in-memory store
  if (!EnsureWalLocked(error)) return false;

  if (group_commit_max_ > 0) {
    // Group-commit mode: the record joins the in-memory batch with its
    // final framing (the bytes CommitGroup writes are exactly the bytes
    // the per-record path would have written, in the same order — WAL
    // parity is byte-for-byte). Durability and failure handling move to
    // CommitGroup; a mutation is only acknowledged after it.
    if (batch_records_ == 0) {
      batch_seq_start_ = wal_seq_;
      batch_crc_start_ = last_crc_;
      batch_version_start_ = next_version_;
      batch_watch_start_ = pending_.size();
    }
    uint64_t seq = wal_seq_ + 1;
    batch_buf_ += FrameRecord(seq, ToJson(r).dump(), &last_crc_);
    wal_seq_ = seq;
    applied_seq_ = seq;  // local writes apply immediately
    ++batch_records_;
    return true;
  }

  uint64_t seq = wal_seq_ + 1;
  uint32_t crc = 0;
  std::string line = FrameRecord(seq, ToJson(r).dump(), &crc);
  long off = ftell(wal_);
  size_t wrote = fwrite(line.data(), 1, line.size(), wal_);
  bool ok = wrote == line.size() && fflush(wal_) == 0;
  // After the chain: a short fwrite short-circuits fflush (errno holds
  // the write error); otherwise errno holds the flush error.
  int saved_errno = errno;
  if (ok && fsync_policy_ != FsyncPolicy::kNever) {
    ++unsynced_records_;
    if (fsync_policy_ == FsyncPolicy::kAlways ||
        unsynced_records_ >= fsync_interval_) {
      if (fsync(fileno(wal_)) != 0) {
        // A failed fsync may drop the very pages it was asked to persist
        // (the fsync-gate problem) — the record cannot be trusted.
        saved_errno = errno;
        ok = false;
      } else {
        unsynced_records_ = 0;
      }
    }
  }
  if (!ok) {
    // Roll the file back to the pre-record offset so a partial append
    // can't become a torn line that replay would stop at.
    std::string reason = std::string("wal append failed: ") +
                         strerror(saved_errno);
    clearerr(wal_);
    if (off < 0 || ftruncate(fileno(wal_), off) != 0) {
      // Can't even restore the file — disk state is unknown. Refuse all
      // further mutations instead of silently diverging memory from disk.
      wal_broken_ = true;
      wal_error_ = reason + "; rollback truncate failed: " +
                   strerror(errno);
      fclose(wal_);
      wal_ = nullptr;
      if (error) *error = "WAL broken: " + wal_error_;
      return false;
    }
    if (error) *error = reason;
    return false;
  }
  wal_seq_ = seq;
  last_crc_ = crc;
  applied_seq_ = seq;
  ++wal_records_;
  return true;
}

void Store::RecordUndoLocked(const std::pair<std::string, std::string>& key) {
  if (group_commit_max_ <= 0 || wal_path_.empty()) return;
  auto it = data_.find(key);
  if (it == data_.end()) {
    batch_undo_.emplace_back(key, std::nullopt);
  } else {
    batch_undo_.emplace_back(key, it->second);
  }
}

void Store::ClearBatchLocked() {
  batch_buf_.clear();
  batch_records_ = 0;
  batch_undo_.clear();
}

void Store::RollbackBatchLocked() {
  // Roll the whole batch out of memory, newest first: pre-images
  // restore data_, the version/seq clocks rewind, and the batch's
  // queued watch events are dropped — the per-record path's
  // reject-on-failure contract at batch granularity. Replies for
  // these mutations were held pending this commit, so nothing was
  // ever acknowledged.
  for (auto it = batch_undo_.rbegin(); it != batch_undo_.rend(); ++it) {
    if (it->second) {
      data_[it->first] = *it->second;
    } else {
      data_.erase(it->first);
    }
  }
  next_version_ = batch_version_start_;
  wal_seq_ = batch_seq_start_;
  last_crc_ = batch_crc_start_;
  applied_seq_ = batch_seq_start_;
  if (pending_.size() > batch_watch_start_) {
    pending_.resize(batch_watch_start_);
  }
  ClearBatchLocked();
}

void Store::AbortBatch() {
  // The quorum said no before the local covering fsync ran: the batch
  // bytes were never written here, so the rollback is memory-only —
  // exactly CommitGroup's failure path minus the file truncate.
  std::lock_guard<std::mutex> lock(mu_);
  if (batch_records_ == 0) return;
  RollbackBatchLocked();
}

bool Store::CommitGroup(std::string* error) {
  std::lock_guard<std::mutex> lock(mu_);
  return CommitGroupLocked(error);
}

bool Store::CommitGroupLocked(std::string* error) {
  if (batch_records_ == 0) return true;  // nothing pending, no fsync
  std::string werr;
  bool ok = EnsureWalLocked(&werr);
  long off = -1;
  int saved_errno = 0;
  if (ok) {
    off = ftell(wal_);
    // The loss window the kill-9 test aims at: mutations are applied in
    // memory and replies staged, but the batch bytes are still only in
    // this process — a SIGKILL here loses exactly the unacknowledged
    // tail, never an acknowledged record.
    MaybeCrashAt("group-commit.pre-write");
    size_t wrote = fwrite(batch_buf_.data(), 1, batch_buf_.size(), wal_);
    ok = wrote == batch_buf_.size() && fflush(wal_) == 0;
    // After the whole chain: a short fwrite short-circuits fflush, so
    // errno still holds the write error; otherwise it holds the flush
    // error (fwrite often buffers fine and ENOSPC only surfaces here).
    saved_errno = errno;
  }
  if (ok && fsync_policy_ != FsyncPolicy::kNever) {
    // Accumulate into unsynced_records_ only on success: a failed commit
    // truncates this batch off disk, and counting its records would make
    // later commits fire their covering fsync early (and drift the
    // stateinfo fsync count from the real unsynced backlog).
    const int pending_unsynced = unsynced_records_ + batch_records_;
    if (fsync_policy_ == FsyncPolicy::kAlways ||
        pending_unsynced >= fsync_interval_) {
      MaybeCrashAt("group-commit.pre-fsync");
      if (fsync(fileno(wal_)) != 0) {
        // Same fsync-gate rule as the per-record path: a failed fsync
        // may drop the very pages it was asked to persist — nothing in
        // this batch can be trusted.
        saved_errno = errno;
        ok = false;
      } else {
        unsynced_records_ = 0;
        ++group_fsyncs_;
      }
    } else {
      unsynced_records_ = pending_unsynced;
    }
  }
  if (!ok) {
    std::string reason = std::string("group commit failed: ") +
                         (werr.empty() ? strerror(saved_errno) : werr.c_str());
    if (wal_) {
      clearerr(wal_);
      if (off < 0 || ftruncate(fileno(wal_), off) != 0) {
        // Disk state unknown — refuse all further mutations rather than
        // silently diverging (mirrors the per-record rollback failure).
        wal_broken_ = true;
        wal_error_ = reason + "; rollback truncate failed: " +
                     strerror(errno);
        fclose(wal_);
        wal_ = nullptr;
      }
    }
    RollbackBatchLocked();
    if (error) {
      *error = wal_broken_ ? "WAL broken: " + wal_error_ : reason;
    }
    return false;
  }
  wal_records_ += batch_records_;
  ++group_commits_;
  group_records_ += batch_records_;
  group_max_batch_ = std::max(group_max_batch_, batch_records_);
  ClearBatchLocked();
  // Compaction is deferred while a batch is open (a snapshot must never
  // make unacknowledged mutations durable ahead of their commit); run it
  // here, where the tail is fully durable.
  MaybeCompactLocked();
  return true;
}

bool Store::PendingBatchBytes(BatchBytes* out) const {
  std::lock_guard<std::mutex> lock(mu_);
  if (batch_records_ == 0) return false;
  out->bytes = batch_buf_;
  out->prev_seq = batch_seq_start_;
  out->last_seq = wal_seq_;
  out->prev_crc = batch_crc_start_;
  out->records = batch_records_;
  return true;
}

uint32_t Store::WalTipCrc() const {
  std::lock_guard<std::mutex> lock(mu_);
  return last_crc_;
}

uint64_t Store::WalSeq() const {
  std::lock_guard<std::mutex> lock(mu_);
  return wal_seq_;
}

uint64_t Store::AppliedSeq() const {
  std::lock_guard<std::mutex> lock(mu_);
  return applied_seq_;
}

int Store::UnappliedRecords() const {
  std::lock_guard<std::mutex> lock(mu_);
  return static_cast<int>(repl_unapplied_.size());
}

bool Store::AppendReplicatedLog(const std::string& bytes,
                                std::string* error) {
  std::lock_guard<std::mutex> lock(mu_);
  if (batch_records_ > 0) {
    // A follower never opens local batches (mutations are redirected to
    // the leader); refusing here keeps the two write paths from ever
    // interleaving in one WAL.
    if (error) *error = "local group-commit batch open";
    return false;
  }
  // Phase 1 — verify every shipped line BEFORE anything touches the
  // disk: framed, CRC-good, sequence contiguous from our WAL tip. Any
  // failure rejects the whole batch with nothing written (the shipped
  // bytes are the leader's exact framed bytes, so a mismatch means
  // corruption in flight or a diverged log — resync, don't guess).
  std::vector<std::pair<uint64_t, Resource>> parsed;
  uint64_t seq = wal_seq_;
  uint32_t tip_crc = last_crc_;
  size_t pos = 0;
  while (pos < bytes.size()) {
    size_t nl = bytes.find('\n', pos);
    if (nl == std::string::npos) {
      if (error) *error = "shipped batch ends mid-record";
      return false;
    }
    std::string line = bytes.substr(pos, nl - pos);
    pos = nl + 1;
    if (line.empty()) continue;
    if (line.compare(0, 3, "v1 ") != 0) {
      if (error) *error = "unframed record in shipped batch";
      return false;
    }
    uint64_t got_seq = 0;
    uint32_t got_crc = 0;
    std::string payload, perr;
    if (!ParseFrame(line, &got_seq, &payload, &perr, &got_crc)) {
      if (error) *error = "shipped batch: " + perr;
      return false;
    }
    if (got_seq != seq + 1) {
      char buf[96];
      snprintf(buf, sizeof(buf),
               "shipped batch seq %" PRIu64 " does not follow %" PRIu64,
               got_seq, seq);
      if (error) *error = buf;
      return false;
    }
    seq = got_seq;
    tip_crc = got_crc;
    Resource r;
    try {
      r = FromJson(Json::parse(payload));
    } catch (const std::exception& e) {
      if (error) *error = std::string("shipped batch record JSON: ") +
                          e.what();
      return false;
    }
    parsed.emplace_back(got_seq, std::move(r));
  }
  if (parsed.empty()) return true;  // pure heartbeat payload
  // Phase 2 — land the bytes durably, the per-record append's checked-IO
  // discipline at batch granularity: a short write or failed covering
  // fsync rolls the file back to the pre-batch offset and rejects.
  if (!wal_path_.empty()) {
    if (!EnsureWalLocked(error)) return false;
    long off = ftell(wal_);
    size_t wrote = fwrite(bytes.data(), 1, bytes.size(), wal_);
    bool ok = wrote == bytes.size() && fflush(wal_) == 0;
    int saved_errno = errno;
    if (ok && fsync_policy_ != FsyncPolicy::kNever) {
      const int pending_unsynced =
          unsynced_records_ + static_cast<int>(parsed.size());
      if (fsync_policy_ == FsyncPolicy::kAlways ||
          pending_unsynced >= fsync_interval_) {
        if (fsync(fileno(wal_)) != 0) {
          saved_errno = errno;
          ok = false;
        } else {
          unsynced_records_ = 0;
        }
      } else {
        unsynced_records_ = pending_unsynced;
      }
    }
    if (!ok) {
      std::string reason = std::string("replicated append failed: ") +
                           strerror(saved_errno);
      clearerr(wal_);
      if (off < 0 || ftruncate(fileno(wal_), off) != 0) {
        wal_broken_ = true;
        wal_error_ = reason + "; rollback truncate failed: " +
                     strerror(errno);
        fclose(wal_);
        wal_ = nullptr;
        if (error) *error = "WAL broken: " + wal_error_;
        return false;
      }
      if (error) *error = reason;
      return false;
    }
    wal_records_ += static_cast<int>(parsed.size());
  }
  wal_seq_ = seq;
  last_crc_ = tip_crc;
  for (auto& p : parsed) repl_unapplied_.push_back(std::move(p));
  return true;
}

int Store::ApplyReplicatedUpTo(uint64_t commit_seq) {
  std::lock_guard<std::mutex> lock(mu_);
  int applied = 0;
  size_t i = 0;
  for (; i < repl_unapplied_.size() && repl_unapplied_[i].first <= commit_seq;
       ++i) {
    const Resource& r = repl_unapplied_[i].second;
    auto key = std::make_pair(r.kind, r.name);
    WatchEvent::Type type;
    if (r.deleted) {
      type = WatchEvent::Type::kDeleted;
      data_.erase(key);
    } else {
      type = data_.count(key) ? WatchEvent::Type::kModified
                              : WatchEvent::Type::kAdded;
      data_[key] = r;
    }
    if (r.resource_version >= next_version_) {
      next_version_ = r.resource_version + 1;
    }
    applied_seq_ = repl_unapplied_[i].first;
    // Events queue only for COMMITTED records — the follower's watch
    // fan-out can never leak a batch the quorum later aborts.
    Append({type, r});
    ++applied;
  }
  if (i > 0) {
    repl_unapplied_.erase(repl_unapplied_.begin(),
                          repl_unapplied_.begin() + i);
  }
  return applied;
}

bool Store::ReadReplicaFiles(std::string* snapshot_bytes,
                             std::string* wal_bytes) const {
  std::lock_guard<std::mutex> lock(mu_);
  if (wal_path_.empty()) return false;
  auto slurp = [](const std::string& path, std::string* out) {
    out->clear();
    FILE* f = fopen(path.c_str(), "r");
    if (!f) return;  // absent file ships as empty (e.g. no snapshot yet)
    char buf[65536];
    size_t got;
    while ((got = fread(buf, 1, sizeof(buf), f)) > 0) out->append(buf, got);
    fclose(f);
  };
  slurp(snapshot_path(), snapshot_bytes);
  slurp(wal_path_, wal_bytes);
  return true;
}

bool Store::InstallReplica(const std::string& snapshot_bytes,
                           const std::string& wal_bytes,
                           std::string* error) {
  std::lock_guard<std::mutex> lock(mu_);
  if (wal_path_.empty()) {
    if (error) *error = "in-memory store cannot install a replica image";
    return false;
  }
  if (wal_) {
    fclose(wal_);
    wal_ = nullptr;
  }
  // Leader-authoritative resync: our own WAL (which may have diverged —
  // e.g. records a rolled-back leader shipped us that never reached
  // quorum) is REPLACED by the leader's files, then replayed exactly
  // like a restart. Snapshot first via temp+rename so a crash between
  // the two writes still loads something coherent.
  auto write_file = [&](const std::string& path, const std::string& data,
                        std::string* werr) {
    std::string tmp = path + ".install";
    FILE* f = fopen(tmp.c_str(), "w");
    if (!f) {
      *werr = "cannot open " + tmp + ": " + strerror(errno);
      return false;
    }
    bool ok = data.empty() ||
              fwrite(data.data(), 1, data.size(), f) == data.size();
    ok = ok && fflush(f) == 0 && fsync(fileno(f)) == 0;
    int saved_errno = errno;
    if (fclose(f) != 0) ok = false;
    if (!ok) {
      remove(tmp.c_str());
      *werr = "short write installing " + path + ": " +
              strerror(saved_errno);
      return false;
    }
    if (rename(tmp.c_str(), path.c_str()) != 0) {
      *werr = "rename installing " + path + ": " + strerror(errno);
      remove(tmp.c_str());
      return false;
    }
    return true;
  };
  std::string werr;
  if (snapshot_bytes.empty()) {
    remove(snapshot_path().c_str());
  } else if (!write_file(snapshot_path(), snapshot_bytes, &werr)) {
    if (error) *error = werr;
    return false;
  }
  if (!write_file(wal_path_, wal_bytes, &werr)) {
    if (error) *error = werr;
    return false;
  }
  FsyncDirOf(wal_path_);
  data_.clear();
  repl_unapplied_.clear();
  pending_.clear();
  recent_events_.clear();
  ring_floor_rv_ = 0;
  next_version_ = 1;
  wal_broken_ = false;
  wal_error_.clear();
  unsynced_records_ = 0;
  LoadLocked();
  // Watchers resync from current state, not an event replay: poll
  // watchers see resync=true (ring cleared) and re-list.
  ring_floor_rv_ = next_version_ - 1;
  if (!load_stats_.clean) {
    if (error) *error = "installed replica image replayed dirty: " +
                        load_stats_.error;
    return false;
  }
  return true;
}

bool Store::ApplyLineLocked(const std::string& raw, bool require_framed,
                            bool* is_meta, std::string* error) {
  std::string line = raw;
  while (!line.empty() && (line.back() == '\n' || line.back() == '\r')) {
    line.pop_back();
  }
  *is_meta = false;
  std::string payload;
  bool framed = line.compare(0, 3, "v1 ") == 0;
  if (framed) {
    uint64_t seq = 0;
    uint32_t crc = 0;
    if (!ParseFrame(line, &seq, &payload, error, &crc)) return false;
    if (seq <= wal_seq_) {
      char buf[96];
      snprintf(buf, sizeof(buf),
               "sequence regression: %" PRIu64 " after %" PRIu64, seq,
               wal_seq_);
      *error = buf;
      return false;
    }
    wal_seq_ = seq;
    last_crc_ = crc;
  } else if (require_framed) {
    *error = "unframed record in snapshot";
    return false;
  } else {
    payload = line;  // legacy plain-JSONL record (pre-framing WAL)
    last_crc_ = Crc32(payload.data(), payload.size());
  }
  Json rec;
  try {
    rec = Json::parse(payload);
  } catch (const std::exception& e) {
    *error = std::string("bad record JSON: ") + e.what();
    return false;
  }
  if (rec.has("snapshotMeta")) {
    const Json& meta = rec.get("snapshotMeta");
    int64_t nv = meta.get("nextVersion").as_int(0);
    if (nv > next_version_) next_version_ = nv;
    *is_meta = true;
    return true;
  }
  Resource r = FromJson(rec);
  auto key = std::make_pair(r.kind, r.name);
  if (r.deleted) {
    data_.erase(key);
  } else {
    data_[key] = r;
  }
  if (r.resource_version >= next_version_) {
    next_version_ = r.resource_version + 1;
  }
  return true;
}

int Store::Load() {
  if (wal_path_.empty()) return 0;
  std::lock_guard<std::mutex> lock(mu_);
  return LoadLocked();
}

int Store::LoadLocked() {
  load_stats_ = LoadStats{};
  wal_seq_ = 0;
  last_crc_ = 0;
  wal_records_ = 0;

  // A leftover temp snapshot means a crash mid-compaction before the
  // atomic rename — the WAL still has everything; just discard it.
  remove((snapshot_path() + ".tmp").c_str());

  char* lbuf = nullptr;
  size_t lcap = 0;
  ssize_t llen;

  // Phase 1: snapshot (full state at the last compaction), if present.
  if (FILE* snap = fopen(snapshot_path().c_str(), "r")) {
    load_stats_.snapshot_loaded = true;
    while ((llen = getline(&lbuf, &lcap, snap)) != -1) {
      std::string line(lbuf, static_cast<size_t>(llen));
      if (line == "\n") continue;
      bool is_meta = false;
      std::string err;
      if (!ApplyLineLocked(line, /*require_framed=*/true, &is_meta, &err)) {
        // Should be impossible (snapshots land via atomic rename): real
        // disk corruption. Keep what replayed, stay loud, continue to
        // the tail — partial state beats no state for an operator
        // deciding what to salvage.
        load_stats_.clean = false;
        load_stats_.error = "snapshot: " + err;
        break;
      }
      if (!is_meta) {
        ++load_stats_.snapshot_records;
        ++load_stats_.applied;
      }
    }
    fclose(snap);
  }

  // Phase 2: the WAL tail, tracking the byte offset after the last good
  // record so a torn/corrupt tail is truncated IN THE FILE before the
  // writer reopens — otherwise the next append glues onto the torn line
  // and every later record is lost on all future replays.
  FILE* f = fopen(wal_path_.c_str(), "r");
  if (!f) {
    free(lbuf);
    return load_stats_.applied;
  }
  long good_end = 0;
  while ((llen = getline(&lbuf, &lcap, f)) != -1) {
    std::string line(lbuf, static_cast<size_t>(llen));
    if (line.back() != '\n') {
      // Partial final record: the expected crash-mid-append shape (power
      // loss / partial writeback). Truncated below; still a clean load.
      break;
    }
    if (line == "\n") {
      good_end = ftell(f);
      continue;
    }
    bool is_meta = false;
    std::string err;
    if (!ApplyLineLocked(line, /*require_framed=*/false, &is_meta, &err)) {
      // Corruption on a COMPLETE line — not a torn tail. Stop early and
      // report loudly; everything after it is cut (a lost earlier
      // mutation makes later state unreliable, the etcd rule).
      load_stats_.clean = false;
      if (load_stats_.error.empty()) load_stats_.error = err;
      break;
    }
    if (!is_meta) {
      ++load_stats_.tail_records;
      ++load_stats_.applied;
    }
    good_end = ftell(f);
  }
  fseek(f, 0, SEEK_END);
  long file_size = ftell(f);
  fclose(f);
  free(lbuf);
  if (file_size > good_end) {
    load_stats_.truncated_bytes = file_size - good_end;
    if (truncate(wal_path_.c_str(), good_end) != 0) {
      // Can't repair the file: appending would glue onto the torn tail.
      wal_broken_ = true;
      wal_error_ = std::string("cannot truncate torn tail of ") +
                   wal_path_ + ": " + strerror(errno);
      load_stats_.clean = false;
      if (load_stats_.error.empty()) load_stats_.error = wal_error_;
    }
  }
  wal_records_ = load_stats_.tail_records;
  // A restart replays (and applies) the full local log: commit-index
  // recovery is the new leader's job — any record here that never
  // reached quorum is either re-committed or truncated by the resync
  // the next leader's first append triggers.
  applied_seq_ = wal_seq_;
  repl_unapplied_.clear();

  // A tail already past the threshold (e.g. compaction was disabled last
  // run) compacts at startup so the NEXT replay is bounded.
  std::string cerr_;
  if (compact_threshold_ > 0 && wal_records_ > compact_threshold_ &&
      !wal_broken_) {
    CompactLocked(&cerr_);
  }
  return load_stats_.applied;
}

bool Store::CompactLocked(std::string* error) {
  if (wal_path_.empty()) return true;
  std::string tmp = snapshot_path() + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) {
    compact_error_ = std::string("cannot open ") + tmp + ": " +
                     strerror(errno);
    if (error) *error = compact_error_;
    return false;
  }
  bool ok = true;
  {
    Json meta = Json::Object();
    Json m = Json::Object();
    m["nextVersion"] = next_version_;
    m["resources"] = static_cast<int64_t>(data_.size());
    meta["snapshotMeta"] = m;
    std::string line = FrameRecord(++wal_seq_, meta.dump(), &last_crc_);
    ok = fwrite(line.data(), 1, line.size(), f) == line.size();
  }
  for (auto it = data_.begin(); ok && it != data_.end(); ++it) {
    std::string line = FrameRecord(++wal_seq_, ToJson(it->second).dump(),
                                   &last_crc_);
    ok = fwrite(line.data(), 1, line.size(), f) == line.size();
  }
  ok = ok && fflush(f) == 0 && fsync(fileno(f)) == 0;
  int saved_errno = errno;
  if (fclose(f) != 0) ok = false;
  if (!ok) {
    remove(tmp.c_str());
    compact_error_ = std::string("snapshot write failed: ") +
                     strerror(saved_errno);
    if (error) *error = compact_error_;
    return false;
  }
  if (rename(tmp.c_str(), snapshot_path().c_str()) != 0) {
    compact_error_ = std::string("snapshot rename failed: ") +
                     strerror(errno);
    remove(tmp.c_str());
    if (error) *error = compact_error_;
    return false;
  }
  FsyncDirOf(wal_path_);
  // Snapshot is durable; the WAL tail it covers can go. If a crash lands
  // between the rename and this truncate, replay stops at the stale
  // tail's sequence regression with exactly the snapshot state.
  if (wal_) {
    fclose(wal_);
    wal_ = nullptr;
  }
  FILE* w = fopen(wal_path_.c_str(), "w");
  if (!w) {
    wal_broken_ = true;
    wal_error_ = std::string("cannot reopen WAL after compaction: ") +
                 strerror(errno);
    compact_error_ = wal_error_;
    if (error) *error = compact_error_;
    return false;
  }
  setvbuf(w, nullptr, _IONBF, 0);
  wal_ = w;
  wal_records_ = 0;
  unsynced_records_ = 0;
  // Snapshot records consumed sequence numbers the followers never saw:
  // the next shipped append's prevSeq mismatch sends them through the
  // snapshot catch-up path (ReadReplicaFiles/InstallReplica) — the
  // documented cost of leader-side compaction under replication.
  applied_seq_ = wal_seq_;
  ++compactions_;
  compact_error_.clear();
  return true;
}

void Store::MaybeCompactLocked() {
  // Runs inline in the mutating request once the tail passes the
  // threshold. Synchronous-by-design: the control plane is effectively a
  // single-writer event loop, the cost is amortized O(1)/record, and a
  // background compactor would need a second WAL handle + copy of data_.
  // If snapshots ever get big enough to matter, this is the seam to move
  // off-thread. Failure is recorded in compact_error_ (stateinfo), never
  // fails the mutation — the WAL append already landed. In group-commit
  // mode this only runs from CommitGroupLocked (batch_records_ == 0
  // there), never with a batch open.
  if (batch_records_ == 0 && compact_threshold_ > 0 &&
      wal_records_ > compact_threshold_) {
    std::string ignored;
    CompactLocked(&ignored);
  }
}

bool Store::Compact(std::string* error) {
  std::lock_guard<std::mutex> lock(mu_);
  // A pending batch must land first: CompactLocked snapshots memory and
  // truncates the WAL, and a batch appended AFTER that truncate would
  // carry sequence numbers at or below the snapshot's (replay would stop
  // at the regression).
  if (!CommitGroupLocked(error)) return false;
  return CompactLocked(error);
}

Json Store::StateInfo() const {
  std::lock_guard<std::mutex> lock(mu_);
  Json out = Json::Object();
  out["walPath"] = wal_path_;
  out["resources"] = static_cast<int64_t>(data_.size());
  out["nextVersion"] = next_version_;
  out["walRecords"] = wal_records_;
  out["walSeq"] = static_cast<int64_t>(wal_seq_);
  out["appliedSeq"] = static_cast<int64_t>(applied_seq_);
  out["unappliedRecords"] = static_cast<int64_t>(repl_unapplied_.size());
  out["walBroken"] = wal_broken_;
  if (!wal_error_.empty()) out["walError"] = wal_error_;
  out["fsync"] = fsync_policy_ == FsyncPolicy::kAlways
                     ? "always"
                     : fsync_policy_ == FsyncPolicy::kInterval ? "interval"
                                                               : "never";
  out["fsyncInterval"] = fsync_interval_;
  out["compactThreshold"] = compact_threshold_;
  out["compactions"] = compactions_;
  if (!compact_error_.empty()) out["compactError"] = compact_error_;
  // Group-commit health (ISSUE 8): how many mutations shared a covering
  // fsync, and how much watch fan-out the coalescer absorbed.
  Json gc = Json::Object();
  gc["maxBatch"] = group_commit_max_;   // config: 0 = off
  gc["commits"] = group_commits_;
  gc["records"] = group_records_;
  gc["fsyncs"] = group_fsyncs_;
  gc["maxBatchObserved"] = group_max_batch_;
  gc["meanBatch"] = group_commits_ > 0
                        ? static_cast<double>(group_records_) /
                              static_cast<double>(group_commits_)
                        : 0.0;
  gc["pendingRecords"] = batch_records_;
  out["groupCommit"] = gc;
  Json watch = Json::Object();
  watch["coalescedEvents"] = watch_coalesced_;
  watch["deliveredEvents"] = watch_delivered_;
  watch["queuedEvents"] = static_cast<int64_t>(pending_.size());
  watch["watchers"] = static_cast<int64_t>(watchers_.size());
  out["watch"] = watch;
  Json replay = Json::Object();
  replay["applied"] = load_stats_.applied;
  replay["snapshotRecords"] = load_stats_.snapshot_records;
  replay["tailRecords"] = load_stats_.tail_records;
  replay["truncatedBytes"] = load_stats_.truncated_bytes;
  replay["snapshotLoaded"] = load_stats_.snapshot_loaded;
  replay["clean"] = load_stats_.clean;
  if (!load_stats_.error.empty()) replay["error"] = load_stats_.error;
  out["replay"] = replay;
  return out;
}

bool Store::ValidName(const std::string& name) {
  // DNS-label-ish, like the reference's metadata.name validation: resource
  // names become filesystem paths (workdir/<name>/worker-N.log) and proc-id
  // prefixes (<name>/<replica>), so '/', '..', and control chars are unsafe.
  if (name.empty() || name.size() > 253 || name[0] == '.') return false;
  for (char c : name) {
    if (!(std::isalnum(static_cast<unsigned char>(c)) || c == '-' ||
          c == '_' || c == '.')) {
      return false;
    }
  }
  return true;
}

Json Store::ToJson(const Resource& r) {
  Json out = Json::Object();
  out["kind"] = r.kind;
  out["name"] = r.name;
  out["spec"] = r.spec;
  out["status"] = r.status;
  out["resourceVersion"] = r.resource_version;
  out["generation"] = r.generation;
  if (r.deleted) out["deleted"] = true;
  return out;
}

Resource Store::FromJson(const Json& rec) {
  Resource r;
  r.kind = rec.get("kind").as_string();
  r.name = rec.get("name").as_string();
  r.spec = rec.get("spec");
  r.status = rec.get("status");
  r.resource_version = rec.get("resourceVersion").as_int();
  r.generation = rec.get("generation").as_int();
  r.deleted = rec.get("deleted").as_bool();
  return r;
}

void Store::Append(const WatchEvent& ev) { pending_.push_back(ev); }

Store::Result Store::Create(const std::string& kind, const std::string& name,
                            Json spec) {
  if (!ValidName(name) || !ValidName(kind)) {
    return {false, "invalid name: must match [A-Za-z0-9._-]{1,253}, not "
                   "leading '.': " + kind + "/" + name, {}};
  }
  std::lock_guard<std::mutex> lock(mu_);
  auto key = std::make_pair(kind, name);
  if (data_.count(key)) {
    return {false, "already exists: " + kind + "/" + name, {}};
  }
  Resource r;
  r.kind = kind;
  r.name = name;
  r.spec = std::move(spec);
  r.status = Json::Object();
  r.resource_version = next_version_;
  r.generation = 1;
  // WAL first, memory second: a failed append (disk full, broken WAL)
  // rejects the mutation instead of letting memory diverge from disk.
  // (Group-commit mode: the append only buffers; RecordUndoLocked keeps
  // the pre-image so a failed covering fsync can reject it just as
  // completely at commit time.)
  std::string werr;
  if (!WalAppendLocked(r, &werr)) return {false, werr, {}};
  RecordUndoLocked(key);
  ++next_version_;
  data_[key] = r;
  Append({WatchEvent::Type::kAdded, r});
  MaybeCompactLocked();
  return {true, "", r};
}

Store::Result Store::UpdateSpec(const std::string& kind,
                                const std::string& name, Json spec,
                                int64_t expected_version) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = data_.find({kind, name});
  if (it == data_.end()) return {false, "not found: " + kind + "/" + name, {}};
  if (expected_version >= 0 &&
      it->second.resource_version != expected_version) {
    return {false, "conflict: version mismatch", {}};
  }
  Resource updated = it->second;
  updated.spec = std::move(spec);
  updated.resource_version = next_version_;
  updated.generation++;
  std::string werr;
  if (!WalAppendLocked(updated, &werr)) return {false, werr, {}};
  RecordUndoLocked(it->first);
  ++next_version_;
  it->second = std::move(updated);
  Append({WatchEvent::Type::kModified, it->second});
  MaybeCompactLocked();
  return {true, "", it->second};
}

Store::Result Store::UpdateStatus(const std::string& kind,
                                  const std::string& name, Json status,
                                  int64_t expected_version) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = data_.find({kind, name});
  if (it == data_.end()) return {false, "not found: " + kind + "/" + name, {}};
  if (expected_version >= 0 &&
      it->second.resource_version != expected_version) {
    return {false, "conflict: version mismatch", {}};
  }
  Resource updated = it->second;
  updated.status = std::move(status);
  updated.resource_version = next_version_;
  std::string werr;
  if (!WalAppendLocked(updated, &werr)) return {false, werr, {}};
  RecordUndoLocked(it->first);
  ++next_version_;
  it->second = std::move(updated);
  Append({WatchEvent::Type::kModified, it->second});
  MaybeCompactLocked();
  return {true, "", it->second};
}

Store::Result Store::Delete(const std::string& kind, const std::string& name) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = data_.find({kind, name});
  if (it == data_.end()) return {false, "not found: " + kind + "/" + name, {}};
  Resource r = it->second;
  r.deleted = true;
  r.resource_version = next_version_;
  std::string werr;
  if (!WalAppendLocked(r, &werr)) return {false, werr, {}};
  RecordUndoLocked(it->first);
  ++next_version_;
  data_.erase(it);
  Append({WatchEvent::Type::kDeleted, r});
  MaybeCompactLocked();
  return {true, "", r};
}

std::optional<Resource> Store::Get(const std::string& kind,
                                   const std::string& name) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = data_.find({kind, name});
  if (it == data_.end()) return std::nullopt;
  return it->second;
}

std::vector<Resource> Store::List(const std::string& kind) const {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<Resource> out;
  for (const auto& [key, r] : data_) {
    if (kind.empty() || key.first == kind) out.push_back(r);
  }
  return out;
}

Json Store::WatchSince(int64_t since_version,
                       const std::string& kind) const {
  std::lock_guard<std::mutex> lock(mu_);
  Json out = Json::Object();
  Json events = Json::Array();
  // A cursor at or below the ring floor may have missed evicted events:
  // the caller must re-list (resync), the etcd compacted-revision rule.
  const bool resync = since_version < ring_floor_rv_;
  if (!resync) {
    for (const auto& ev : recent_events_) {
      if (ev.rv <= since_version) continue;
      if (!kind.empty() &&
          ev.resource.get("kind").as_string() != kind) {
        continue;
      }
      Json e = Json::Object();
      e["type"] = ev.type == WatchEvent::Type::kAdded
                      ? "ADDED"
                      : ev.type == WatchEvent::Type::kDeleted ? "DELETED"
                                                              : "MODIFIED";
      e["resource"] = ev.resource;
      events.push_back(e);
    }
  }
  out["events"] = events;
  out["resourceVersion"] = next_version_ - 1;
  out["resync"] = resync;
  return out;
}

int Store::Watch(const std::string& kind, WatchFn fn) {
  std::lock_guard<std::mutex> lock(mu_);
  int id = next_watch_id_++;
  watchers_.push_back({id, kind, std::move(fn)});
  return id;
}

void Store::Unwatch(int id) {
  std::lock_guard<std::mutex> lock(mu_);
  for (auto it = watchers_.begin(); it != watchers_.end(); ++it) {
    if (it->id == id) {
      watchers_.erase(it);
      return;
    }
  }
}

int Store::DrainWatches() {
  std::vector<WatchEvent> events;
  std::vector<Watcher> watchers;
  {
    std::lock_guard<std::mutex> lock(mu_);
    // Events queued by a still-open batch stay queued: a failed commit
    // must be able to drop them (a delivered event cannot be recalled —
    // watchers would act on mutations that were rolled back), and the
    // rollback's pending_.resize(batch_watch_start_) relies on the
    // batch's events being the intact suffix. Only the committed prefix
    // drains; the suffix delivers after its covering commit.
    const size_t drainable =
        batch_records_ > 0 ? std::min(batch_watch_start_, pending_.size())
                           : pending_.size();
    if (drainable == 0) return 0;
    std::vector<WatchEvent> raw(
        std::make_move_iterator(pending_.begin()),
        std::make_move_iterator(pending_.begin() + drainable));
    pending_.erase(pending_.begin(), pending_.begin() + drainable);
    if (batch_records_ > 0) batch_watch_start_ -= drainable;
    watchers = watchers_;
    // Coalesce per (kind, name): a run of ADDED/MODIFIED with no DELETED
    // between collapses to one event carrying the latest resource (an
    // ADDED that was immediately MODIFIED stays an ADDED). DELETED is a
    // barrier — delivered as-is, and a re-create after it starts fresh.
    // Level-triggered consumers (the reconcilers) only act on current
    // state, so intermediate writes are pure fan-out cost.
    std::map<std::pair<std::string, std::string>, size_t> open_run;
    for (auto& ev : raw) {
      auto key = std::make_pair(ev.resource.kind, ev.resource.name);
      if (ev.type == WatchEvent::Type::kDeleted) {
        open_run.erase(key);
        events.push_back(std::move(ev));
        continue;
      }
      auto it = open_run.find(key);
      if (it != open_run.end()) {
        events[it->second].resource = std::move(ev.resource);
        ++watch_coalesced_;
      } else {
        open_run.emplace(key, events.size());
        events.push_back(std::move(ev));
      }
    }
    // Per-pass delivery budget: leftovers go back to the queue's FRONT
    // (they predate anything a delivery callback appends) and keep
    // their order for the next pass.
    if (events.size() > kMaxWatchDeliverPerPass) {
      const size_t leftover = events.size() - kMaxWatchDeliverPerPass;
      pending_.insert(pending_.begin(),
                      std::make_move_iterator(
                          events.begin() + kMaxWatchDeliverPerPass),
                      std::make_move_iterator(events.end()));
      // Reinserted leftovers are committed events sitting ahead of any
      // open batch's suffix — keep the suffix boundary pointing at it.
      if (batch_records_ > 0) batch_watch_start_ += leftover;
      events.resize(kMaxWatchDeliverPerPass);
    }
    watch_delivered_ += static_cast<int64_t>(events.size());
    // Every delivered (committed, coalesced) event also enters the
    // watch.poll ring — the client-facing fan-out surface followers
    // serve at their applied seq. Evictions raise the resync floor.
    for (const auto& ev : events) {
      recent_events_.push_back({ev.resource.resource_version, ev.type,
                                ToJson(ev.resource)});
      while (recent_events_.size() > kWatchRingCap) {
        ring_floor_rv_ = std::max(ring_floor_rv_,
                                  recent_events_.front().rv);
        recent_events_.pop_front();
      }
    }
  }
  for (const auto& ev : events) {
    for (const auto& w : watchers) {
      if (w.kind.empty() || w.kind == ev.resource.kind) {
        w.fn(ev);
      }
    }
  }
  return static_cast<int>(events.size());
}

}  // namespace tpk
