#include "store.h"

#include <cctype>
#include <cstdio>
#include <cstdlib>

namespace tpk {

Store::Store(std::string wal_path) : wal_path_(std::move(wal_path)) {
  if (!wal_path_.empty()) {
    wal_ = fopen(wal_path_.c_str(), "a");
  }
}

Store::~Store() {
  if (wal_) fclose(wal_);
}

int Store::Load() {
  if (wal_path_.empty()) return 0;
  FILE* f = fopen(wal_path_.c_str(), "r");
  if (!f) return 0;
  int applied = 0;
  std::string line;
  // getline(3): records (full JAXJob specs) can exceed any fixed buffer; a
  // truncated read would mis-parse and silently drop every later record.
  char* lbuf = nullptr;
  size_t lcap = 0;
  ssize_t llen;
  std::lock_guard<std::mutex> lock(mu_);
  while ((llen = getline(&lbuf, &lcap, f)) != -1) {
    line.assign(lbuf, static_cast<size_t>(llen));
    if (line.empty() || line == "\n") continue;
    try {
      Json rec = Json::parse(line);
      Resource r;
      r.kind = rec.get("kind").as_string();
      r.name = rec.get("name").as_string();
      r.spec = rec.get("spec");
      r.status = rec.get("status");
      r.resource_version = rec.get("resourceVersion").as_int();
      r.generation = rec.get("generation").as_int();
      r.deleted = rec.get("deleted").as_bool();
      auto key = std::make_pair(r.kind, r.name);
      if (r.deleted) {
        data_.erase(key);
      } else {
        data_[key] = r;
      }
      if (r.resource_version >= next_version_) {
        next_version_ = r.resource_version + 1;
      }
      ++applied;
    } catch (const std::exception&) {
      // Torn tail write (crash mid-append): stop replay at the corruption.
      break;
    }
  }
  free(lbuf);
  fclose(f);
  return applied;
}

bool Store::ValidName(const std::string& name) {
  // DNS-label-ish, like the reference's metadata.name validation: resource
  // names become filesystem paths (workdir/<name>/worker-N.log) and proc-id
  // prefixes (<name>/<replica>), so '/', '..', and control chars are unsafe.
  if (name.empty() || name.size() > 253 || name[0] == '.') return false;
  for (char c : name) {
    if (!(std::isalnum(static_cast<unsigned char>(c)) || c == '-' ||
          c == '_' || c == '.')) {
      return false;
    }
  }
  return true;
}

Json Store::ToJson(const Resource& r) {
  Json out = Json::Object();
  out["kind"] = r.kind;
  out["name"] = r.name;
  out["spec"] = r.spec;
  out["status"] = r.status;
  out["resourceVersion"] = r.resource_version;
  out["generation"] = r.generation;
  if (r.deleted) out["deleted"] = true;
  return out;
}

void Store::WalWrite(const Resource& r) {
  if (!wal_) return;
  std::string line = ToJson(r).dump();
  fwrite(line.data(), 1, line.size(), wal_);
  fputc('\n', wal_);
  fflush(wal_);
}

void Store::Append(const WatchEvent& ev) { pending_.push_back(ev); }

Store::Result Store::Create(const std::string& kind, const std::string& name,
                            Json spec) {
  if (!ValidName(name) || !ValidName(kind)) {
    return {false, "invalid name: must match [A-Za-z0-9._-]{1,253}, not "
                   "leading '.': " + kind + "/" + name, {}};
  }
  std::lock_guard<std::mutex> lock(mu_);
  auto key = std::make_pair(kind, name);
  if (data_.count(key)) {
    return {false, "already exists: " + kind + "/" + name, {}};
  }
  Resource r;
  r.kind = kind;
  r.name = name;
  r.spec = std::move(spec);
  r.status = Json::Object();
  r.resource_version = next_version_++;
  r.generation = 1;
  data_[key] = r;
  WalWrite(r);
  Append({WatchEvent::Type::kAdded, r});
  return {true, "", r};
}

Store::Result Store::UpdateSpec(const std::string& kind,
                                const std::string& name, Json spec,
                                int64_t expected_version) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = data_.find({kind, name});
  if (it == data_.end()) return {false, "not found: " + kind + "/" + name, {}};
  if (expected_version >= 0 &&
      it->second.resource_version != expected_version) {
    return {false, "conflict: version mismatch", {}};
  }
  it->second.spec = std::move(spec);
  it->second.resource_version = next_version_++;
  it->second.generation++;
  WalWrite(it->second);
  Append({WatchEvent::Type::kModified, it->second});
  return {true, "", it->second};
}

Store::Result Store::UpdateStatus(const std::string& kind,
                                  const std::string& name, Json status,
                                  int64_t expected_version) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = data_.find({kind, name});
  if (it == data_.end()) return {false, "not found: " + kind + "/" + name, {}};
  if (expected_version >= 0 &&
      it->second.resource_version != expected_version) {
    return {false, "conflict: version mismatch", {}};
  }
  it->second.status = std::move(status);
  it->second.resource_version = next_version_++;
  WalWrite(it->second);
  Append({WatchEvent::Type::kModified, it->second});
  return {true, "", it->second};
}

Store::Result Store::Delete(const std::string& kind, const std::string& name) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = data_.find({kind, name});
  if (it == data_.end()) return {false, "not found: " + kind + "/" + name, {}};
  Resource r = it->second;
  r.deleted = true;
  r.resource_version = next_version_++;
  data_.erase(it);
  WalWrite(r);
  Append({WatchEvent::Type::kDeleted, r});
  return {true, "", r};
}

std::optional<Resource> Store::Get(const std::string& kind,
                                   const std::string& name) const {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = data_.find({kind, name});
  if (it == data_.end()) return std::nullopt;
  return it->second;
}

std::vector<Resource> Store::List(const std::string& kind) const {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<Resource> out;
  for (const auto& [key, r] : data_) {
    if (kind.empty() || key.first == kind) out.push_back(r);
  }
  return out;
}

int Store::Watch(const std::string& kind, WatchFn fn) {
  std::lock_guard<std::mutex> lock(mu_);
  int id = next_watch_id_++;
  watchers_.push_back({id, kind, std::move(fn)});
  return id;
}

void Store::Unwatch(int id) {
  std::lock_guard<std::mutex> lock(mu_);
  for (auto it = watchers_.begin(); it != watchers_.end(); ++it) {
    if (it->id == id) {
      watchers_.erase(it);
      return;
    }
  }
}

int Store::DrainWatches() {
  std::vector<WatchEvent> events;
  std::vector<Watcher> watchers;
  {
    std::lock_guard<std::mutex> lock(mu_);
    events.swap(pending_);
    watchers = watchers_;
  }
  for (const auto& ev : events) {
    for (const auto& w : watchers) {
      if (w.kind.empty() || w.kind == ev.resource.kind) {
        w.fn(ev);
      }
    }
  }
  return static_cast<int>(events.size());
}

}  // namespace tpk
