// InferenceService controller — the KServe control plane
// (SURVEY.md §2.2, §3.3, §7.1 item 6).
//
// The reference reconciles `InferenceService` into Knative Services or raw
// Deployments (⟨kserve: pkg/controller/v1beta1/inferenceservice/ —
// InferenceServiceReconciler⟩) and delegates keep-alive/readiness/scaling
// to kubelet probes + Knative KPA. Without Kubernetes, those collapse into
// this controller: it keeps N long-running model-server replicas alive on
// allocated devices, restarts crashed replicas with exponential backoff
// (crash-loop semantics), probes `/v2/health/ready` for readiness, and
// scales replica count between min/max from request throughput scraped off
// each replica's `/metrics` (the simple concurrency autoscaler that stands
// in for Knative KPA; scale-to-zero descoped per SURVEY.md §7.4).
//
// Spec:
//   {"model": {"name": "m", "model_dir": "/bundle"} | {"storage_uri": ...},
//    "replicas": 1,                     // manual scale (no autoscaler)
//    "min_replicas": 1, "max_replicas": 4, "target_rps": 50,  // autoscaler
//    "devices_per_replica": 1, "cpu_devices": 0,
//    "max_batch_size": 32, "max_latency_ms": 5.0}

#pragma once

#include <cstdint>
#include <map>
#include <set>
#include <string>

#include "executor.h"
#include "json.h"
#include "scheduler.h"
#include "store.h"

namespace tpk {

// Readiness + metrics probing, injectable for tests.
class ProbeInterface {
 public:
  virtual ~ProbeInterface() = default;
  virtual bool Ready(int port) = 0;
  // Fetches /metrics; returns false if unreachable.
  virtual bool Metrics(int port, std::string* body) = 0;
};

// Blocking-with-deadline HTTP/1.0 GET against 127.0.0.1 (the model servers
// bind loopback; remote executors would bring their own prober).
class HttpProbe : public ProbeInterface {
 public:
  explicit HttpProbe(int timeout_ms = 1500) : timeout_ms_(timeout_ms) {}
  bool Ready(int port) override;
  bool Metrics(int port, std::string* body) override;

 private:
  bool Get(int port, const std::string& path, std::string* body,
           int* status);
  int timeout_ms_;
};

class FakeProbe : public ProbeInterface {
 public:
  bool Ready(int port) override { return ready.count(port) > 0; }
  bool Metrics(int port, std::string* body) override {
    auto it = metrics.find(port);
    if (it == metrics.end()) return false;
    *body = it->second;
    return true;
  }
  std::set<int> ready;
  std::map<int, std::string> metrics;
};

struct ServeMetrics {
  int64_t services_created = 0;
  int64_t replica_starts = 0;
  int64_t replica_restarts = 0;
  int64_t scale_events = 0;
  int64_t canary_rollouts = 0;

  Json ToJson() const {
    Json j = Json::Object();
    j["services_created"] = services_created;
    j["replica_starts"] = replica_starts;
    j["replica_restarts"] = replica_restarts;
    j["scale_events"] = scale_events;
    j["canary_rollouts"] = canary_rollouts;
    return j;
  }
};

class ServeController {
 public:
  ServeController(Store* store, ExecutorInterface* executor,
                  Scheduler* scheduler, ProbeInterface* probe,
                  std::string workdir, std::string python = "python3");

  void Reconcile(const std::string& name);
  void Tick(double now_s);
  void OnDeleted(const Resource& res);

  // Crash recovery: reap orphaned server processes after a control-plane
  // restart (their pids are recorded in status).
  void Recover();

  ServeMetrics& metrics() { return metrics_; }

  static std::string ProcId(const std::string& name, int replica);

  // Sum of tpk_serve_requests_total across a Prometheus text body.
  static double ParseRequestsTotal(const std::string& metrics_text);

 private:
  struct View {
    Resource res;
    Json spec;
    Json status;
  };

  void EnsureReplica(View& v, int index);
  void StopReplica(View& v, int index);
  int DesiredReplicas(View& v);

  Store* store_;
  ExecutorInterface* executor_;
  Scheduler* scheduler_;
  ProbeInterface* probe_;
  std::string workdir_;
  std::string python_;
  ServeMetrics metrics_;
  double now_s_ = 0;
};

}  // namespace tpk
