// InferenceService controller — the KServe control plane
// (SURVEY.md §2.2, §3.3, §7.1 item 6).
//
// The reference reconciles `InferenceService` into Knative Services or raw
// Deployments (⟨kserve: pkg/controller/v1beta1/inferenceservice/ —
// InferenceServiceReconciler⟩) and delegates keep-alive/readiness/scaling
// to kubelet probes + Knative KPA. Without Kubernetes, those collapse into
// this controller: it keeps N long-running model-server replicas alive on
// allocated devices, restarts crashed replicas with exponential backoff
// (crash-loop semantics), probes `/v2/health/ready` for readiness, and
// scales replica count between min/max from request throughput scraped off
// each replica's `/metrics` (the simple concurrency autoscaler that stands
// in for Knative KPA; scale-to-zero descoped per SURVEY.md §7.4).
//
// Spec:
//   {"model": {"name": "m", "model_dir": "/bundle"} | {"storage_uri": ...},
//    "replicas": 1,                     // manual scale (no autoscaler)
//    "min_replicas": 1, "max_replicas": 4, "target_rps": 50,  // autoscaler
//    "devices_per_replica": 1, "cpu_devices": 0,
//    "max_batch_size": 32, "max_latency_ms": 5.0}

#pragma once

#include <cstdint>
#include <map>
#include <set>
#include <string>
#include <utility>
#include <vector>

#include "executor.h"
#include "json.h"
#include "scheduler.h"
#include "store.h"

namespace tpk {

// Readiness + metrics probing, injectable for tests.
class ProbeInterface {
 public:
  virtual ~ProbeInterface() = default;
  virtual bool Ready(int port) = 0;
  // Fetches /metrics; returns false if unreachable.
  virtual bool Metrics(int port, std::string* body) = 0;
  // JSON POST to a replica's control surface (repository load/unload —
  // the TrainedModel data path). Returns false if unreachable; *status
  // carries the HTTP code when reachable.
  virtual bool Post(int port, const std::string& path,
                    const std::string& payload, int* status) = 0;
  // Per-model readiness (GET /v2/models/{model}/ready == 200) — how the
  // TrainedModel controller observes an async repository load landing.
  // Non-empty `want_dir` additionally requires the served model_dir to
  // match, so an old version still serving does not mask a pending
  // re-load (version-aware readiness).
  virtual bool ModelReady(int port, const std::string& model,
                          const std::string& want_dir = "") = 0;
};

// Blocking-with-deadline HTTP/1.0 GET against 127.0.0.1 (the model servers
// bind loopback; remote executors would bring their own prober).
class HttpProbe : public ProbeInterface {
 public:
  explicit HttpProbe(int timeout_ms = 1500) : timeout_ms_(timeout_ms) {}
  bool Ready(int port) override;
  bool Metrics(int port, std::string* body) override;
  bool Post(int port, const std::string& path, const std::string& payload,
            int* status) override;
  bool ModelReady(int port, const std::string& model,
                  const std::string& want_dir = "") override;

 private:
  bool Get(int port, const std::string& path, std::string* body,
           int* status);
  bool Request(int port, const std::string& raw, std::string* body,
               int* status);
  int timeout_ms_;
};

class FakeProbe : public ProbeInterface {
 public:
  bool Ready(int port) override { return ready.count(port) > 0; }
  bool Metrics(int port, std::string* body) override {
    auto it = metrics.find(port);
    if (it == metrics.end()) return false;
    *body = it->second;
    return true;
  }
  bool Post(int port, const std::string& path, const std::string& payload,
            int* status) override {
    posts.push_back({port, path, payload});
    if (post_unreachable.count(port)) return false;
    *status = post_status;
    return true;
  }
  bool ModelReady(int port, const std::string& model,
                  const std::string& want_dir = "") override {
    auto it = model_ready.find({port, model});
    if (it == model_ready.end()) return false;
    return want_dir.empty() || it->second == want_dir;
  }
  std::set<int> ready;
  std::map<int, std::string> metrics;
  struct PostRecord {
    int port;
    std::string path;
    std::string payload;
  };
  std::vector<PostRecord> posts;
  std::set<int> post_unreachable;
  int post_status = 202;  // async repository load answers 202 LOADING
  // (port, model) -> served model_dir.
  std::map<std::pair<int, std::string>, std::string> model_ready;
};

struct ServeMetrics {
  int64_t services_created = 0;
  int64_t replica_starts = 0;
  int64_t replica_restarts = 0;
  int64_t scale_events = 0;
  int64_t canary_rollouts = 0;

  Json ToJson() const {
    Json j = Json::Object();
    j["services_created"] = services_created;
    j["replica_starts"] = replica_starts;
    j["replica_restarts"] = replica_restarts;
    j["scale_events"] = scale_events;
    j["canary_rollouts"] = canary_rollouts;
    return j;
  }
};

class ServeController {
 public:
  ServeController(Store* store, ExecutorInterface* executor,
                  Scheduler* scheduler, ProbeInterface* probe,
                  std::string workdir, std::string python = "python3");

  void Reconcile(const std::string& name);
  void Tick(double now_s);
  void OnDeleted(const Resource& res);

  // Crash recovery: reap orphaned server processes after a control-plane
  // restart (their pids are recorded in status).
  void Recover();

  ServeMetrics& metrics() { return metrics_; }

  static std::string ProcId(const std::string& name, int replica);

  // Sum of tpk_serve_requests_total across a Prometheus text body.
  static double ParseRequestsTotal(const std::string& metrics_text);

 private:
  struct View {
    Resource res;
    Json spec;
    Json status;
  };

  void EnsureReplica(View& v, int index);
  void StopReplica(View& v, int index);
  int DesiredReplicas(View& v);

  Store* store_;
  ExecutorInterface* executor_;
  Scheduler* scheduler_;
  ProbeInterface* probe_;
  std::string workdir_;
  std::string python_;
  ServeMetrics metrics_;
  double now_s_ = 0;
};

// TrainedModel controller — multi-model serving (⟨kserve: pkg/apis/serving/
// v1alpha1 — TrainedModel⟩ + the agent model puller, SURVEY.md §2.2): a
// lightweight model CR attaches to a RUNNING InferenceService instead of
// deploying its own replicas. The controller pushes repository load calls
// (POST /v2/repository/models/{name}/load with the model dir) to every
// ready replica of the parent, tracks per-replica load state keyed by
// port:pid (a restarted replica re-loads automatically), and unloads on
// delete.
//
// Spec: {"inference_service": "parent", "model": {"name": "m",
//        "model_dir": "/bundle"}}
struct TrainedModelMetrics {
  int64_t loads = 0;
  int64_t unloads = 0;
  int64_t load_failures = 0;

  Json ToJson() const {
    Json j = Json::Object();
    j["loads"] = loads;
    j["unloads"] = unloads;
    j["load_failures"] = load_failures;
    return j;
  }
};

class TrainedModelController {
 public:
  TrainedModelController(Store* store, ProbeInterface* probe)
      : store_(store), probe_(probe) {}

  void Tick(double now_s);
  void Reconcile(const std::string& name);
  void OnDeleted(const Resource& res);

  TrainedModelMetrics& metrics() { return metrics_; }

 private:
  Store* store_;
  ProbeInterface* probe_;
  TrainedModelMetrics metrics_;
  double now_s_ = 0;
};

}  // namespace tpk
