// In-process state store with watch streams — the etcd+apiserver stand-in.
//
// Upstream, every Kubeflow controller is a reconcile loop over watches served
// by kube-apiserver/etcd (SURVEY.md §1 L0: the platform's true kernel, which
// the reference does NOT implement). The rebuild must supply it: resources
// are (kind, name) → {spec, status, resourceVersion, generation}; writers get
// optimistic concurrency via resourceVersion compare-and-swap; watchers get
// ordered ADDED/MODIFIED/DELETED events; a JSONL WAL makes state survive
// restarts (controller restart ≈ apiserver restart + informer resync).

#pragma once

#include <functional>
#include <mutex>
#include <optional>
#include <string>
#include <vector>

#include "json.h"

namespace tpk {

struct Resource {
  std::string kind;
  std::string name;
  Json spec;
  Json status;         // controllers own this; conditions live here
  int64_t resource_version = 0;  // bumped on every write
  int64_t generation = 0;        // bumped on spec writes only
  bool deleted = false;
};

struct WatchEvent {
  enum class Type { kAdded, kModified, kDeleted };
  Type type;
  Resource resource;
};

// A watch is a callback; it fires under no lock (events are queued and
// drained by Store::DrainWatches from the owner's loop thread), preserving
// per-resource ordering. This mirrors informer semantics closely enough for
// controller logic while staying single-threaded-friendly.
using WatchFn = std::function<void(const WatchEvent&)>;

class Store {
 public:
  // wal_path empty = in-memory only (unit tests).
  explicit Store(std::string wal_path = "");
  ~Store();

  // Replays the WAL if present. Returns number of records applied.
  int Load();

  // CRUD. All return the stored resource (with bumped versions) or an error
  // string. expected_version: -1 = unconditional, else CAS.
  struct Result {
    bool ok;
    std::string error;
    Resource resource;
  };
  Result Create(const std::string& kind, const std::string& name, Json spec);
  Result UpdateSpec(const std::string& kind, const std::string& name,
                    Json spec, int64_t expected_version = -1);
  Result UpdateStatus(const std::string& kind, const std::string& name,
                      Json status, int64_t expected_version = -1);
  Result Delete(const std::string& kind, const std::string& name);
  std::optional<Resource> Get(const std::string& kind,
                              const std::string& name) const;
  std::vector<Resource> List(const std::string& kind) const;

  // Watches: all events for `kind` ("" = all kinds). Returns watch id.
  int Watch(const std::string& kind, WatchFn fn);
  void Unwatch(int id);

  // Deliver queued events to watchers. Called from the owning event loop.
  // Returns number of events delivered.
  int DrainWatches();

  static Json ToJson(const Resource& r);

  // True when `name` is safe as a resource name / path component
  // ([A-Za-z0-9._-], <=253 chars, no leading '.').
  static bool ValidName(const std::string& name);

 private:
  void Append(const WatchEvent& ev);
  void WalWrite(const Resource& r);

  mutable std::mutex mu_;
  std::string wal_path_;
  FILE* wal_ = nullptr;
  std::map<std::pair<std::string, std::string>, Resource> data_;
  int64_t next_version_ = 1;
  struct Watcher {
    int id;
    std::string kind;
    WatchFn fn;
  };
  std::vector<Watcher> watchers_;
  std::vector<WatchEvent> pending_;
  int next_watch_id_ = 1;
};

}  // namespace tpk
