// In-process state store with watch streams — the etcd+apiserver stand-in.
//
// Upstream, every Kubeflow controller is a reconcile loop over watches served
// by kube-apiserver/etcd (SURVEY.md §1 L0: the platform's true kernel, which
// the reference does NOT implement). The rebuild must supply it: resources
// are (kind, name) → {spec, status, resourceVersion, generation}; writers get
// optimistic concurrency via resourceVersion compare-and-swap; watchers get
// ordered ADDED/MODIFIED/DELETED events; a WAL makes state survive restarts
// (controller restart ≈ apiserver restart + informer resync).
//
// Durability model (the etcd analog, scaled down):
//   * Every mutation appends one framed record: `v1 <seq> <crc32> <json>\n`.
//     The CRC covers the exact payload bytes; seq is strictly increasing.
//     Legacy plain-JSONL lines (pre-framing WALs) still replay.
//   * Append errors (fwrite/fflush/fsync) FAIL the mutation — memory never
//     diverges from disk. A torn partial append is rolled back by
//     truncating the file to the pre-record offset; if even that fails the
//     WAL is marked broken and every later mutation errors loudly.
//   * Load() stops at the first torn/corrupt record and truncates the file
//     there BEFORE the writer reopens in append mode — without this, the
//     next append glues onto the torn line and every later record is
//     silently lost on all future replays.
//   * When the WAL tail exceeds a record threshold, the store writes a
//     full-state snapshot (temp file + fsync + atomic rename, like etcd's
//     snap/) and truncates the WAL; Load() replays snapshot-then-tail.
//   * `--fsync never|interval|always` bounds the post-SIGKILL loss window
//     (never: page cache only — safe against process death, not power
//     loss; interval: fsync every N records; always: fsync per record).

#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <vector>

#include "json.h"

namespace tpk {

struct Resource {
  std::string kind;
  std::string name;
  Json spec;
  Json status;         // controllers own this; conditions live here
  int64_t resource_version = 0;  // bumped on every write
  int64_t generation = 0;        // bumped on spec writes only
  bool deleted = false;
};

struct WatchEvent {
  enum class Type { kAdded, kModified, kDeleted };
  Type type;
  Resource resource;
};

// A watch is a callback; it fires under no lock (events are queued and
// drained by Store::DrainWatches from the owner's loop thread), preserving
// per-resource ordering. This mirrors informer semantics closely enough for
// controller logic while staying single-threaded-friendly.
using WatchFn = std::function<void(const WatchEvent&)>;

class Store {
 public:
  // wal_path empty = in-memory only (unit tests).
  explicit Store(std::string wal_path = "");
  ~Store();

  // When (and whether) appends reach the platter, not just the page cache.
  enum class FsyncPolicy { kNever, kInterval, kAlways };

  // What Load() found — the replay-health record surfaced by the startup
  // log and the `stateinfo` server verb.
  struct LoadStats {
    int applied = 0;            // records applied (snapshot + tail)
    int snapshot_records = 0;   // replayed from <wal>.snap
    int tail_records = 0;       // replayed from the WAL file itself
    int64_t truncated_bytes = 0;  // torn/corrupt bytes cut off the WAL
    bool snapshot_loaded = false;
    // true = replay ended at a clean EOF (a torn FINAL record — the
    // expected crash-mid-append shape — still counts as clean; it is
    // truncated and reported in truncated_bytes). false = replay stopped
    // EARLY at mid-file corruption (CRC mismatch, seq regression, bad
    // JSON on a complete line): loud, not silent.
    bool clean = true;
    std::string error;          // first corruption, human-readable
  };

  // Durability knobs — set BEFORE Load()/first mutation.
  void SetFsync(FsyncPolicy policy, int interval_records = 64);
  // Snapshot+truncate once the WAL tail exceeds `records` (0 = never).
  void SetCompactionThreshold(int records);

  // Replays snapshot + WAL if present, truncating any torn/corrupt tail
  // in the file before the writer reopens. Returns records applied.
  int Load();
  const LoadStats& load_stats() const { return load_stats_; }

  // Force a snapshot+WAL-truncate now (also runs automatically past the
  // compaction threshold). Returns false (with *error) on I/O failure —
  // the WAL keeps working; compaction failure never loses state.
  bool Compact(std::string* error = nullptr);

  // Durability health for operators: replay stats, compaction counters,
  // fsync mode, live WAL length — the `stateinfo` verb's payload.
  Json StateInfo() const;

  // CRUD. All return the stored resource (with bumped versions) or an error
  // string. expected_version: -1 = unconditional, else CAS.
  struct Result {
    bool ok;
    std::string error;
    Resource resource;
  };
  Result Create(const std::string& kind, const std::string& name, Json spec);
  Result UpdateSpec(const std::string& kind, const std::string& name,
                    Json spec, int64_t expected_version = -1);
  Result UpdateStatus(const std::string& kind, const std::string& name,
                      Json status, int64_t expected_version = -1);
  Result Delete(const std::string& kind, const std::string& name);
  std::optional<Resource> Get(const std::string& kind,
                              const std::string& name) const;
  std::vector<Resource> List(const std::string& kind) const;

  // Watches: all events for `kind` ("" = all kinds). Returns watch id.
  int Watch(const std::string& kind, WatchFn fn);
  void Unwatch(int id);

  // Deliver queued events to watchers. Called from the owning event loop.
  // Returns number of events delivered.
  int DrainWatches();

  static Json ToJson(const Resource& r);

  // True when `name` is safe as a resource name / path component
  // ([A-Za-z0-9._-], <=253 chars, no leading '.').
  static bool ValidName(const std::string& name);

 private:
  void Append(const WatchEvent& ev);
  // Appends one framed record; on I/O failure rolls the file back to the
  // pre-record offset and returns false with *error (the mutation must
  // not commit). Caller holds mu_.
  bool WalAppendLocked(const Resource& r, std::string* error);
  bool EnsureWalLocked(std::string* error);
  bool CompactLocked(std::string* error);
  void MaybeCompactLocked();
  // Parses one WAL/snapshot line (framed or legacy). Returns false with
  // *error on corruption; *is_meta set for snapshot header records.
  bool ApplyLineLocked(const std::string& line, bool require_framed,
                       bool* is_meta, std::string* error);
  std::string snapshot_path() const { return wal_path_ + ".snap"; }

  mutable std::mutex mu_;
  std::string wal_path_;
  FILE* wal_ = nullptr;
  bool wal_broken_ = false;
  std::string wal_error_;
  FsyncPolicy fsync_policy_ = FsyncPolicy::kNever;
  int fsync_interval_ = 64;
  int unsynced_records_ = 0;
  int compact_threshold_ = 0;
  int wal_records_ = 0;     // records in the current WAL tail (post-snapshot)
  uint64_t wal_seq_ = 0;    // last framed sequence number written/replayed
  int64_t compactions_ = 0;
  std::string compact_error_;  // last compaction failure (loud via stateinfo)
  LoadStats load_stats_;
  std::map<std::pair<std::string, std::string>, Resource> data_;
  int64_t next_version_ = 1;
  struct Watcher {
    int id;
    std::string kind;
    WatchFn fn;
  };
  std::vector<Watcher> watchers_;
  std::vector<WatchEvent> pending_;
  int next_watch_id_ = 1;
};

}  // namespace tpk
