// In-process state store with watch streams — the etcd+apiserver stand-in.
//
// Upstream, every Kubeflow controller is a reconcile loop over watches served
// by kube-apiserver/etcd (SURVEY.md §1 L0: the platform's true kernel, which
// the reference does NOT implement). The rebuild must supply it: resources
// are (kind, name) → {spec, status, resourceVersion, generation}; writers get
// optimistic concurrency via resourceVersion compare-and-swap; watchers get
// ordered ADDED/MODIFIED/DELETED events; a WAL makes state survive restarts
// (controller restart ≈ apiserver restart + informer resync).
//
// Durability model (the etcd analog, scaled down):
//   * Every mutation appends one framed record: `v1 <seq> <crc32> <json>\n`.
//     The CRC covers the exact payload bytes; seq is strictly increasing.
//     Legacy plain-JSONL lines (pre-framing WALs) still replay.
//   * Append errors (fwrite/fflush/fsync) FAIL the mutation — memory never
//     diverges from disk. A torn partial append is rolled back by
//     truncating the file to the pre-record offset; if even that fails the
//     WAL is marked broken and every later mutation errors loudly.
//   * Load() stops at the first torn/corrupt record and truncates the file
//     there BEFORE the writer reopens in append mode — without this, the
//     next append glues onto the torn line and every later record is
//     silently lost on all future replays.
//   * When the WAL tail exceeds a record threshold, the store writes a
//     full-state snapshot (temp file + fsync + atomic rename, like etcd's
//     snap/) and truncates the WAL; Load() replays snapshot-then-tail.
//   * `--fsync never|interval|always` bounds the post-SIGKILL loss window
//     (never: page cache only — safe against process death, not power
//     loss; interval: fsync every N records; always: fsync per record).
//   * Group commit (`--group-commit N`, the etcd/raft batched-commit
//     analog): mutations buffer framed records in memory and apply to
//     the in-memory map immediately; CommitGroup() lands the whole
//     batch with ONE write + ONE covering fsync. The owning event loop
//     holds client replies until the commit returns, so the
//     acknowledged-mutation-is-never-lost contract of `--fsync always`
//     is preserved exactly while N mutations share one fsync
//     (unacknowledged mutations may be lost, same as today). A failed
//     commit rolls the batch back — file truncated to the pre-batch
//     offset, memory restored from per-mutation pre-images, queued
//     watch events dropped — the per-record path's reject-on-failure
//     contract at batch granularity. 0 disables: the per-record append
//     path runs byte-for-byte as before.
//   * Replication (ISSUE 11): the framed WAL doubles as the replication
//     log. A leader exports the open batch's exact framed bytes
//     (PendingBatchBytes) and ships them to followers BEFORE its own
//     covering fsync; a follower lands them with AppendReplicatedLog
//     (verify frames + contiguous seq, one durable write — byte-for-byte
//     what the leader writes) but applies them to memory only up to the
//     leader's commit sequence (ApplyReplicatedUpTo), so a follower
//     never serves a batch that the quorum may still abort. A batch the
//     quorum rejects is dropped with AbortBatch (the CommitGroup failure
//     path without the disk rollback — the bytes were never written
//     locally). A lagging or diverged follower is reseeded from the
//     leader's snapshot + WAL tail (ReadReplicaFiles / InstallReplica —
//     the compaction machinery's files, shipped verbatim).

#pragma once

#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <vector>

#include "json.h"

namespace tpk {

struct Resource {
  std::string kind;
  std::string name;
  Json spec;
  Json status;         // controllers own this; conditions live here
  int64_t resource_version = 0;  // bumped on every write
  int64_t generation = 0;        // bumped on spec writes only
  bool deleted = false;
};

struct WatchEvent {
  enum class Type { kAdded, kModified, kDeleted };
  Type type;
  Resource resource;
};

// A watch is a callback; it fires under no lock (events are queued and
// drained by Store::DrainWatches from the owner's loop thread), preserving
// per-resource ordering. This mirrors informer semantics closely enough for
// controller logic while staying single-threaded-friendly.
using WatchFn = std::function<void(const WatchEvent&)>;

class Store {
 public:
  // wal_path empty = in-memory only (unit tests).
  explicit Store(std::string wal_path = "");
  ~Store();

  // When (and whether) appends reach the platter, not just the page cache.
  enum class FsyncPolicy { kNever, kInterval, kAlways };

  // What Load() found — the replay-health record surfaced by the startup
  // log and the `stateinfo` server verb.
  struct LoadStats {
    int applied = 0;            // records applied (snapshot + tail)
    int snapshot_records = 0;   // replayed from <wal>.snap
    int tail_records = 0;       // replayed from the WAL file itself
    int64_t truncated_bytes = 0;  // torn/corrupt bytes cut off the WAL
    bool snapshot_loaded = false;
    // true = replay ended at a clean EOF (a torn FINAL record — the
    // expected crash-mid-append shape — still counts as clean; it is
    // truncated and reported in truncated_bytes). false = replay stopped
    // EARLY at mid-file corruption (CRC mismatch, seq regression, bad
    // JSON on a complete line): loud, not silent.
    bool clean = true;
    std::string error;          // first corruption, human-readable
  };

  // Durability knobs — set BEFORE Load()/first mutation.
  void SetFsync(FsyncPolicy policy, int interval_records = 64);
  // Snapshot+truncate once the WAL tail exceeds `records` (0 = never).
  void SetCompactionThreshold(int records);
  // Group commit: mutations buffer framed records until CommitGroup();
  // `max_batch` is the advisory per-commit record cap the owning event
  // loop enforces (exposed via group_commit()). 0 = off (per-record
  // append path, unchanged).
  void SetGroupCommit(int max_batch);
  int group_commit() const;
  // Records buffered and awaiting a covering fsync (0 when off/idle).
  int PendingGroupRecords() const;
  // Land the pending batch: one fwrite + fflush + covering fsync (per
  // the fsync policy). True when the batch — possibly empty — is
  // durable. On failure every batched mutation is rolled back from
  // memory AND disk; callers must only acknowledge mutations after
  // this returns true (ack-after-durable).
  bool CommitGroup(std::string* error = nullptr);

  // -- replication hooks (ISSUE 11) --------------------------------------
  // The open batch's exact framed bytes plus the sequence range they
  // cover: (prev_seq, last_seq]. False when no batch is open. The bytes
  // are the ones CommitGroup will write — shipped-batch byte parity with
  // the local WAL is by construction, and test-pinned.
  struct BatchBytes {
    std::string bytes;
    uint64_t prev_seq = 0;   // wal_seq_ before the batch opened
    uint64_t last_seq = 0;   // wal_seq_ of the batch's final record
    uint32_t prev_crc = 0;   // tip crc before the batch (see WalTipCrc)
    int records = 0;
  };
  bool PendingBatchBytes(BatchBytes* out) const;
  // CRC of the record at the log tip (0 on an empty log). The
  // replication layer's entry-identity check — the per-entry-term
  // stand-in: two logs agreeing on (WalSeq, WalTipCrc) hold the same
  // record there, so a follower whose tip crc diverges from the
  // leader's prevCrc is reseeded instead of silently extending a
  // stranded (rolled-back) record at the same sequence number.
  uint32_t WalTipCrc() const;
  // Drop the open batch without touching disk: the CommitGroup failure
  // path (pre-images restored, clocks rewound, queued watch events
  // dropped) for a batch the replication quorum rejected before the
  // local covering fsync ever ran.
  void AbortBatch();
  // Follower ingest: verify `bytes` as framed records contiguous from
  // WalSeq()+1 (CRC + seq checked per line; any failure rejects the
  // whole batch with nothing written), land them with one durable
  // write (fsync per the policy — the follower's ack means durable
  // exactly as a local ack does), and BUFFER the parsed records
  // unapplied. ApplyReplicatedUpTo moves the committed prefix into the
  // in-memory map and queues its watch events.
  bool AppendReplicatedLog(const std::string& bytes, std::string* error);
  int ApplyReplicatedUpTo(uint64_t commit_seq);
  uint64_t WalSeq() const;
  uint64_t AppliedSeq() const;
  int UnappliedRecords() const;
  // Catch-up transfer: the on-disk snapshot + WAL tail verbatim (leader
  // side), and their installation over the local state + full reload
  // (follower side). The shipped files contain only committed records —
  // an open batch lives in memory until its covering commit.
  bool ReadReplicaFiles(std::string* snapshot_bytes,
                        std::string* wal_bytes) const;
  bool InstallReplica(const std::string& snapshot_bytes,
                      const std::string& wal_bytes, std::string* error);

  // Replays snapshot + WAL if present, truncating any torn/corrupt tail
  // in the file before the writer reopens. Returns records applied.
  int Load();
  const LoadStats& load_stats() const { return load_stats_; }

  // Force a snapshot+WAL-truncate now (also runs automatically past the
  // compaction threshold). Returns false (with *error) on I/O failure —
  // the WAL keeps working; compaction failure never loses state.
  bool Compact(std::string* error = nullptr);

  // Durability health for operators: replay stats, compaction counters,
  // fsync mode, live WAL length — the `stateinfo` verb's payload.
  Json StateInfo() const;

  // CRUD. All return the stored resource (with bumped versions) or an error
  // string. expected_version: -1 = unconditional, else CAS.
  struct Result {
    bool ok;
    std::string error;
    Resource resource;
  };
  Result Create(const std::string& kind, const std::string& name, Json spec);
  Result UpdateSpec(const std::string& kind, const std::string& name,
                    Json spec, int64_t expected_version = -1);
  Result UpdateStatus(const std::string& kind, const std::string& name,
                      Json status, int64_t expected_version = -1);
  Result Delete(const std::string& kind, const std::string& name);
  std::optional<Resource> Get(const std::string& kind,
                              const std::string& name) const;
  std::vector<Resource> List(const std::string& kind) const;

  // Watches: all events for `kind` ("" = all kinds). Returns watch id.
  int Watch(const std::string& kind, WatchFn fn);
  void Unwatch(int id);

  // Deliver queued events to watchers. Called from the owning event loop.
  // Returns number of events delivered.
  //
  // Fan-out is bounded two ways (ISSUE 8): consecutive ADDED/MODIFIED
  // events for the same (kind, name) with no DELETED between them
  // coalesce to one event carrying the LATEST resource (level-triggered
  // watchers — reconcilers — only need current state, not every
  // intermediate write; an ADDED immediately MODIFIED is still an
  // ADDED, informer-style). DELETED is a barrier: it is never coalesced
  // away and a later re-create starts a fresh run. Per pass at most
  // kMaxWatchDeliverPerPass coalesced events deliver; leftovers keep
  // their order at the queue's front for the next pass.
  int DrainWatches();

  // Client-facing poll watch (`watch.poll` verb, ISSUE 11): committed,
  // post-coalescing events with resourceVersion > `since`, served from a
  // bounded ring DrainWatches fills as it delivers — so followers serve
  // the same coalesced fan-out leaders do, at their applied seq. When
  // `since` predates the ring (events were evicted), the reply carries
  // resync=true and the caller must re-list (etcd's compacted-revision
  // contract). Reply: {events:[{type,resource}...], resourceVersion,
  // resync}.
  Json WatchSince(int64_t since_version, const std::string& kind) const;

  static Json ToJson(const Resource& r);
  // Inverse of ToJson — the ONE place a persisted record becomes a
  // Resource, shared by WAL replay and replicated-batch ingest so the
  // two paths cannot drift field-by-field.
  static Resource FromJson(const Json& rec);

  // True when `name` is safe as a resource name / path component
  // ([A-Za-z0-9._-], <=253 chars, no leading '.').
  static bool ValidName(const std::string& name);

 private:
  void Append(const WatchEvent& ev);
  // Appends one framed record; on I/O failure rolls the file back to the
  // pre-record offset and returns false with *error (the mutation must
  // not commit). Caller holds mu_. In group-commit mode the record only
  // joins the in-memory batch (durability deferred to CommitGroup).
  bool WalAppendLocked(const Resource& r, std::string* error);
  // Captures the pre-mutation state of `key` for batch rollback (no-op
  // outside group-commit mode). Caller holds mu_; call BEFORE mutating
  // data_.
  void RecordUndoLocked(const std::pair<std::string, std::string>& key);
  bool CommitGroupLocked(std::string* error);
  // Memory half of the failed-commit path: restore pre-images, rewind
  // the version/seq clocks, drop the batch's queued watch events.
  void RollbackBatchLocked();
  void ClearBatchLocked();
  int LoadLocked();
  bool EnsureWalLocked(std::string* error);
  bool CompactLocked(std::string* error);
  void MaybeCompactLocked();
  // Parses one WAL/snapshot line (framed or legacy). Returns false with
  // *error on corruption; *is_meta set for snapshot header records.
  bool ApplyLineLocked(const std::string& line, bool require_framed,
                       bool* is_meta, std::string* error);
  std::string snapshot_path() const { return wal_path_ + ".snap"; }

  mutable std::mutex mu_;
  std::string wal_path_;
  FILE* wal_ = nullptr;
  bool wal_broken_ = false;
  std::string wal_error_;
  FsyncPolicy fsync_policy_ = FsyncPolicy::kNever;
  int fsync_interval_ = 64;
  int unsynced_records_ = 0;
  int compact_threshold_ = 0;
  int wal_records_ = 0;     // records in the current WAL tail (post-snapshot)
  uint64_t wal_seq_ = 0;    // last framed sequence number written/replayed
  uint32_t last_crc_ = 0;   // crc of the record at wal_seq_ (log tip)
  // Group commit: the pending batch (framed bytes + rollback state) and
  // its health counters (stateinfo's groupCommit object).
  int group_commit_max_ = 0;   // 0 = off
  std::string batch_buf_;      // framed records awaiting the covering fsync
  int batch_records_ = 0;
  uint64_t batch_seq_start_ = 0;      // wal_seq_ before the batch opened
  uint32_t batch_crc_start_ = 0;      // last_crc_ before the batch opened
  int64_t batch_version_start_ = 0;   // next_version_ before the batch
  size_t batch_watch_start_ = 0;      // pending_.size() before the batch
  std::vector<std::pair<std::pair<std::string, std::string>,
                        std::optional<Resource>>> batch_undo_;
  int64_t group_commits_ = 0;      // CommitGroup calls that landed records
  int64_t group_records_ = 0;      // records landed through group commits
  int64_t group_fsyncs_ = 0;       // covering fsyncs issued
  int group_max_batch_ = 0;        // largest batch landed by one commit
  int64_t watch_coalesced_ = 0;    // events collapsed by DrainWatches
  int64_t watch_delivered_ = 0;    // events actually delivered
  int64_t compactions_ = 0;
  std::string compact_error_;  // last compaction failure (loud via stateinfo)
  LoadStats load_stats_;
  std::map<std::pair<std::string, std::string>, Resource> data_;
  int64_t next_version_ = 1;
  // Replication: records landed in the WAL by AppendReplicatedLog but
  // not yet applied (their seq exceeds the last ApplyReplicatedUpTo).
  // applied_seq_ trails wal_seq_ only on followers; every local-write
  // path keeps them equal.
  std::vector<std::pair<uint64_t, Resource>> repl_unapplied_;
  uint64_t applied_seq_ = 0;
  struct Watcher {
    int id;
    std::string kind;
    WatchFn fn;
  };
  std::vector<Watcher> watchers_;
  std::vector<WatchEvent> pending_;
  int next_watch_id_ = 1;
  // watch.poll ring: delivered (committed, coalesced) events, bounded.
  // ring_floor_rv_ is the highest resourceVersion ever evicted — a
  // `since` at or below it may have missed events and must resync.
  struct RingEvent {
    int64_t rv;
    WatchEvent::Type type;
    Json resource;
  };
  std::deque<RingEvent> recent_events_;
  int64_t ring_floor_rv_ = 0;
  static constexpr size_t kWatchRingCap = 4096;
  // Per-pass delivery budget (post-coalescing): bounds how long one
  // DrainWatches can hold the event loop at high job counts.
  static constexpr size_t kMaxWatchDeliverPerPass = 4096;
};

// Test-only seeded crash hook (TPK_CRASH_AT="<point>:<n>" SIGKILLs on the
// n-th hit), exported for the replication ship path (repl.pre-ship /
// repl.post-ship-pre-quorum / repl.post-quorum-pre-release windows live
// in server.cc/replica.cc but share store.cc's one env-spec counter).
void MaybeCrashAtPoint(const char* point);

}  // namespace tpk
