// tpk-controlplane: the control-plane binary.
//
//   tpk-controlplane --socket /tmp/tpk.sock --workdir /tmp/tpk
//       --slices local=8 [--python python3] [--wal /tmp/tpk/wal.jsonl]
//       [--fsync never|interval|always] [--fsync-interval N] [--compact N]
//
// One process = store + scheduler + JAXJob controller + API server, the
// single-binary equivalent of {kube-apiserver, etcd, scheduler, kubelet,
// training-operator} for local process execution (SURVEY.md §7.1-7.2).

#include <signal.h>

#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>

#include "executor.h"
#include "jaxjob.h"
#include "pipelines.h"
#include "scheduler.h"
#include "serve.h"
#include "server.h"
#include "store.h"
#include "tune.h"

namespace {
volatile sig_atomic_t g_stop = 0;
void OnSignal(int) { g_stop = 1; }
}  // namespace

int main(int argc, char** argv) {
  std::string socket_path = "/tmp/tpk.sock";
  std::string workdir = "/tmp/tpk";
  std::string wal;
  std::string python = "python3";
  std::string fsync_mode = "never";
  int fsync_interval = 64;
  int compact_threshold = 4096;
  std::vector<std::pair<std::string, int>> slices = {{"local", 8}};

  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto next = [&]() -> std::string {
      return i + 1 < argc ? argv[++i] : "";
    };
    if (arg == "--socket") socket_path = next();
    else if (arg == "--workdir") workdir = next();
    else if (arg == "--wal") wal = next();
    else if (arg == "--python") python = next();
    else if (arg == "--fsync") fsync_mode = next();
    else if (arg == "--fsync-interval") fsync_interval = atoi(next().c_str());
    else if (arg == "--compact") compact_threshold = atoi(next().c_str());
    else if (arg == "--slices") {
      slices.clear();
      std::string val = next();  // "name=cap,name=cap"
      size_t pos = 0;
      while (pos < val.size()) {
        size_t comma = val.find(',', pos);
        if (comma == std::string::npos) comma = val.size();
        std::string part = val.substr(pos, comma - pos);
        size_t eq = part.find('=');
        if (eq != std::string::npos) {
          slices.emplace_back(part.substr(0, eq),
                              atoi(part.c_str() + eq + 1));
        }
        pos = comma + 1;
      }
    } else if (arg == "--help") {
      printf("usage: tpk-controlplane --socket PATH --workdir DIR "
             "[--wal FILE] [--python BIN] [--slices name=cap,...] "
             "[--fsync never|interval|always] [--fsync-interval N] "
             "[--compact N]\n");
      return 0;
    }
  }

  tpk::Store::FsyncPolicy fsync_policy;
  if (fsync_mode == "never") {
    fsync_policy = tpk::Store::FsyncPolicy::kNever;
  } else if (fsync_mode == "interval") {
    fsync_policy = tpk::Store::FsyncPolicy::kInterval;
  } else if (fsync_mode == "always") {
    fsync_policy = tpk::Store::FsyncPolicy::kAlways;
  } else {
    fprintf(stderr, "tpk-controlplane: --fsync must be never | interval | "
            "always, got '%s'\n", fsync_mode.c_str());
    return 1;
  }

  signal(SIGINT, OnSignal);
  signal(SIGTERM, OnSignal);
  signal(SIGPIPE, SIG_IGN);

  tpk::Store store(wal);
  store.SetFsync(fsync_policy, fsync_interval);
  store.SetCompactionThreshold(compact_threshold);
  store.Load();
  const tpk::Store::LoadStats& replay = store.load_stats();
  if (!replay.clean) {
    fprintf(stderr,
            "tpk-controlplane: WAL REPLAY STOPPED EARLY AT CORRUPTION: %s "
            "(%lld bytes truncated; state is the last good record)\n",
            replay.error.c_str(),
            static_cast<long long>(replay.truncated_bytes));
  } else if (replay.truncated_bytes > 0) {
    fprintf(stderr,
            "tpk-controlplane: torn WAL tail truncated (%lld bytes) — "
            "expected after a crash mid-append\n",
            static_cast<long long>(replay.truncated_bytes));
  }
  tpk::Scheduler scheduler;
  for (const auto& [name, cap] : slices) scheduler.AddSlice(name, cap);
  tpk::LocalExecutor executor;
  tpk::JaxJobController jaxjob(&store, &executor, &scheduler, workdir, python);
  jaxjob.SetSocketPath(socket_path);
  jaxjob.Recover();
  tpk::SubprocessSuggestion suggestion(python);
  tpk::ExperimentController tune(&store, &suggestion, workdir);
  tpk::LineageStore lineage(workdir + "/lineage.jsonl");
  int lineage_records = lineage.Load();
  tpk::PipelineRunController pipelines(&store, &lineage, workdir, python);
  tpk::ScheduleController schedule(&store);
  // 250ms probe cap: probes run synchronously in this single-threaded loop,
  // so a slow replica must not stall scheduling/API for long (servers are
  // loopback-local; healthy ones answer in ms).
  tpk::HttpProbe probe(250);
  tpk::ServeController serve(&store, &executor, &scheduler, &probe, workdir,
                             python);
  serve.Recover();
  tpk::TrainedModelController trained(&store, &probe);
  tpk::Server server(&store, &scheduler, &jaxjob, socket_path, workdir,
                     &tune, &pipelines, &serve);

  std::string error;
  if (!server.Start(&error)) {
    fprintf(stderr, "tpk-controlplane: cannot listen on %s: %s\n",
            socket_path.c_str(), error.c_str());
    return 1;
  }
  // Replay health, not just a count: operators must see snapshot vs tail
  // split and whether anything was truncated (the `stateinfo` verb serves
  // the same record over the API).
  fprintf(stderr,
          "tpk-controlplane: listening on %s (workdir=%s, WAL replay: "
          "%d applied = %d snapshot + %d tail, %lld bytes truncated, %s, "
          "fsync=%s; %d lineage records, %zu slices)\n",
          socket_path.c_str(), workdir.c_str(), replay.applied,
          replay.snapshot_records, replay.tail_records,
          static_cast<long long>(replay.truncated_bytes),
          replay.clean ? "clean" : "STOPPED AT CORRUPTION",
          fsync_mode.c_str(), lineage_records, slices.size());

  // Watch: any JAXJob change → reconcile (informer-style edge trigger).
  // Deletes are handled inline: the resource is already gone from the
  // store, so the controller must kill the gang from the event's snapshot.
  std::vector<std::string> dirty;
  store.Watch("JAXJob", [&dirty, &jaxjob](const tpk::WatchEvent& ev) {
    if (ev.type == tpk::WatchEvent::Type::kDeleted) {
      jaxjob.OnDeleted(ev.resource);
    } else {
      dirty.push_back(ev.resource.name);
    }
  });
  // Experiment/Trial deletes cascade to their children (apiserver GC).
  store.Watch("Experiment", [&tune](const tpk::WatchEvent& ev) {
    if (ev.type == tpk::WatchEvent::Type::kDeleted) tune.OnDeleted(ev.resource);
  });
  store.Watch("Trial", [&tune](const tpk::WatchEvent& ev) {
    if (ev.type == tpk::WatchEvent::Type::kDeleted) tune.OnDeleted(ev.resource);
  });
  store.Watch("PipelineRun", [&pipelines](const tpk::WatchEvent& ev) {
    if (ev.type == tpk::WatchEvent::Type::kDeleted) {
      pipelines.OnDeleted(ev.resource);
    }
  });
  store.Watch("InferenceService", [&serve](const tpk::WatchEvent& ev) {
    if (ev.type == tpk::WatchEvent::Type::kDeleted) {
      serve.OnDeleted(ev.resource);
    }
  });
  store.Watch("TrainedModel", [&trained](const tpk::WatchEvent& ev) {
    if (ev.type == tpk::WatchEvent::Type::kDeleted) {
      trained.OnDeleted(ev.resource);
    }
  });

  while (!g_stop) {
    server.PollOnce(50);
    store.DrainWatches();
    for (const auto& name : dirty) jaxjob.Reconcile(name);
    dirty.clear();
    double now = static_cast<double>(time(nullptr));
    jaxjob.Tick(now);
    tune.Tick(now);
    schedule.Tick(now);
    pipelines.Tick(now);
    serve.Tick(now);
    trained.Tick(now);
    // Tune/pipeline writes (child JAXJob create/delete) need a jaxjob pass
    // before the next poll so child gangs launch/die promptly.
    store.DrainWatches();
    for (const auto& name : dirty) jaxjob.Reconcile(name);
    dirty.clear();
  }
  fprintf(stderr, "tpk-controlplane: shutting down\n");
  return 0;
}
