// tpk-controlplane: the control-plane binary.
//
//   tpk-controlplane --socket /tmp/tpk.sock --workdir /tmp/tpk
//       --slices local=8 [--python python3] [--wal /tmp/tpk/wal.jsonl]
//       [--fsync never|interval|always] [--fsync-interval N] [--compact N]
//       [--group-commit N]
//
// One process = store + scheduler + JAXJob controller + API server, the
// single-binary equivalent of {kube-apiserver, etcd, scheduler, kubelet,
// training-operator} for local process execution (SURVEY.md §7.1-7.2).

#include <signal.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>

#include "executor.h"
#include "jaxjob.h"
#include "pipelines.h"
#include "replica.h"
#include "scheduler.h"
#include "serve.h"
#include "server.h"
#include "store.h"
#include "tune.h"

namespace {
volatile sig_atomic_t g_stop = 0;
void OnSignal(int) { g_stop = 1; }
}  // namespace

int main(int argc, char** argv) {
  std::string socket_path = "/tmp/tpk.sock";
  std::string workdir = "/tmp/tpk";
  std::string wal;
  std::string python = "python3";
  std::string fsync_mode = "never";
  int fsync_interval = 64;
  int compact_threshold = 4096;
  // Group commit (ISSUE 8): max WAL records per covering fsync. Default
  // on — it only batches what one event-loop pass applies anyway; 0
  // restores the per-record append path byte-for-byte.
  int group_commit = 64;
  // Replication (ISSUE 11): --peers lists the OTHER replicas' sockets
  // (empty = single-node, the ISSUE 8 path byte-for-byte); --replica-of
  // names the leader to follow at startup (absent = bootstrap: campaign
  // for leadership once a quorum of peers answers).
  std::string peers_csv;
  std::string replica_of;
  int lease_ms = 1500;
  int quorum_timeout_ms = 5000;
  std::vector<std::pair<std::string, int>> slices = {{"local", 8}};

  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto next = [&]() -> std::string {
      return i + 1 < argc ? argv[++i] : "";
    };
    if (arg == "--socket") socket_path = next();
    else if (arg == "--workdir") workdir = next();
    else if (arg == "--wal") wal = next();
    else if (arg == "--python") python = next();
    else if (arg == "--fsync") fsync_mode = next();
    else if (arg == "--fsync-interval") fsync_interval = atoi(next().c_str());
    else if (arg == "--compact") compact_threshold = atoi(next().c_str());
    else if (arg == "--group-commit") group_commit = atoi(next().c_str());
    else if (arg == "--peers") peers_csv = next();
    else if (arg == "--replica-of") replica_of = next();
    else if (arg == "--lease-ms") lease_ms = atoi(next().c_str());
    else if (arg == "--quorum-timeout-ms")
      quorum_timeout_ms = atoi(next().c_str());
    else if (arg == "--slices") {
      slices.clear();
      std::string val = next();  // "name=cap,name=cap"
      size_t pos = 0;
      while (pos < val.size()) {
        size_t comma = val.find(',', pos);
        if (comma == std::string::npos) comma = val.size();
        std::string part = val.substr(pos, comma - pos);
        size_t eq = part.find('=');
        if (eq != std::string::npos) {
          slices.emplace_back(part.substr(0, eq),
                              atoi(part.c_str() + eq + 1));
        }
        pos = comma + 1;
      }
    } else if (arg == "--help") {
      printf("usage: tpk-controlplane --socket PATH --workdir DIR "
             "[--wal FILE] [--python BIN] [--slices name=cap,...] "
             "[--fsync never|interval|always] [--fsync-interval N] "
             "[--compact N] [--group-commit N] "
             "[--peers SOCK,SOCK,...] [--replica-of SOCK] "
             "[--lease-ms N] [--quorum-timeout-ms N]\n");
      return 0;
    }
  }

  tpk::Store::FsyncPolicy fsync_policy;
  if (fsync_mode == "never") {
    fsync_policy = tpk::Store::FsyncPolicy::kNever;
  } else if (fsync_mode == "interval") {
    fsync_policy = tpk::Store::FsyncPolicy::kInterval;
  } else if (fsync_mode == "always") {
    fsync_policy = tpk::Store::FsyncPolicy::kAlways;
  } else {
    fprintf(stderr, "tpk-controlplane: --fsync must be never | interval | "
            "always, got '%s'\n", fsync_mode.c_str());
    return 1;
  }

  std::vector<std::string> peers;
  {
    size_t pos = 0;
    while (pos < peers_csv.size()) {
      size_t comma = peers_csv.find(',', pos);
      if (comma == std::string::npos) comma = peers_csv.size();
      std::string part = peers_csv.substr(pos, comma - pos);
      if (!part.empty() && part != socket_path) peers.push_back(part);
      pos = comma + 1;
    }
  }
  if (!peers.empty() && group_commit <= 0) {
    // The replication log IS the group-commit batch: without batching
    // there is nothing to ship before the ack, and the quorum gate
    // would silently not exist.
    fprintf(stderr, "tpk-controlplane: --peers requires --group-commit "
            "> 0 (the batch is the replication unit)\n");
    return 1;
  }
  if (!peers.empty() && wal.empty()) {
    fprintf(stderr, "tpk-controlplane: --peers requires --wal (the WAL "
            "is the replication log)\n");
    return 1;
  }

  signal(SIGINT, OnSignal);
  signal(SIGTERM, OnSignal);
  signal(SIGPIPE, SIG_IGN);

  tpk::Store store(wal);
  store.SetFsync(fsync_policy, fsync_interval);
  store.SetCompactionThreshold(compact_threshold);
  store.SetGroupCommit(group_commit);
  store.Load();
  const tpk::Store::LoadStats& replay = store.load_stats();
  if (!replay.clean) {
    fprintf(stderr,
            "tpk-controlplane: WAL REPLAY STOPPED EARLY AT CORRUPTION: %s "
            "(%lld bytes truncated; state is the last good record)\n",
            replay.error.c_str(),
            static_cast<long long>(replay.truncated_bytes));
  } else if (replay.truncated_bytes > 0) {
    fprintf(stderr,
            "tpk-controlplane: torn WAL tail truncated (%lld bytes) — "
            "expected after a crash mid-append\n",
            static_cast<long long>(replay.truncated_bytes));
  }
  tpk::Replication::Options ropts;
  ropts.self = socket_path;
  ropts.peers = peers;
  ropts.state_path = wal.empty() ? "" : wal + ".replstate";
  ropts.leader_hint = replica_of;
  ropts.lease_ms = lease_ms > 0 ? lease_ms : 1500;
  ropts.quorum_timeout_ms = quorum_timeout_ms > 0 ? quorum_timeout_ms
                                                  : 5000;
  tpk::Replication repl(&store, ropts);
  // Single-node (no peers): every repl path below is inert and the
  // loop is the ISSUE 8 loop byte-for-byte.
  const bool replicated = repl.enabled();

  tpk::Scheduler scheduler;
  for (const auto& [name, cap] : slices) scheduler.AddSlice(name, cap);
  tpk::LocalExecutor executor;
  tpk::JaxJobController jaxjob(&store, &executor, &scheduler, workdir, python);
  jaxjob.SetSocketPath(socket_path);
  // A replicated follower must not adopt/restart gangs it never owns;
  // Recover() runs on promotion instead (TookLeadership below).
  if (!replicated) jaxjob.Recover();
  tpk::SubprocessSuggestion suggestion(python);
  tpk::ExperimentController tune(&store, &suggestion, workdir);
  tpk::LineageStore lineage(workdir + "/lineage.jsonl");
  int lineage_records = lineage.Load();
  tpk::PipelineRunController pipelines(&store, &lineage, workdir, python);
  tpk::ScheduleController schedule(&store);
  // 250ms probe cap: probes run synchronously in this single-threaded loop,
  // so a slow replica must not stall scheduling/API for long (servers are
  // loopback-local; healthy ones answer in ms).
  tpk::HttpProbe probe(250);
  tpk::ServeController serve(&store, &executor, &scheduler, &probe, workdir,
                             python);
  if (!replicated) serve.Recover();
  tpk::TrainedModelController trained(&store, &probe);
  tpk::Server server(&store, &scheduler, &jaxjob, socket_path, workdir,
                     &tune, &pipelines, &serve, &repl);

  std::string error;
  if (!server.Start(&error)) {
    fprintf(stderr, "tpk-controlplane: cannot listen on %s: %s\n",
            socket_path.c_str(), error.c_str());
    return 1;
  }
  // Replay health, not just a count: operators must see snapshot vs tail
  // split and whether anything was truncated (the `stateinfo` verb serves
  // the same record over the API).
  fprintf(stderr,
          "tpk-controlplane: listening on %s (workdir=%s, WAL replay: "
          "%d applied = %d snapshot + %d tail, %lld bytes truncated, %s, "
          "fsync=%s, group-commit=%d; %d lineage records, %zu slices)\n",
          socket_path.c_str(), workdir.c_str(), replay.applied,
          replay.snapshot_records, replay.tail_records,
          static_cast<long long>(replay.truncated_bytes),
          replay.clean ? "clean" : "STOPPED AT CORRUPTION",
          fsync_mode.c_str(), group_commit, lineage_records, slices.size());
  if (replicated) {
    const std::string role_note =
        replica_of.empty() ? "bootstrap — campaigning"
                           : "following " + replica_of;
    fprintf(stderr,
            "tpk-controlplane: replicated (%zu peers, quorum %d, "
            "lease %d ms, term %lld, %s)\n",
            peers.size(), repl.quorum(), ropts.lease_ms,
            static_cast<long long>(repl.term()), role_note.c_str());
  }

  // Watch: any JAXJob change → reconcile (informer-style edge trigger).
  // Deletes are handled inline: the resource is already gone from the
  // store, so the controller must kill the gang from the event's snapshot.
  // Followers drop controller-facing events — they own no gangs and run
  // no reconciles; promotion runs Recover() against the applied state
  // instead (the watch.poll ring still serves them to clients).
  auto lead = [&repl, replicated]() {
    return !replicated || repl.IsLeader();
  };
  std::vector<std::string> dirty;
  store.Watch("JAXJob", [&dirty, &jaxjob, lead](const tpk::WatchEvent& ev) {
    if (!lead()) return;
    if (ev.type == tpk::WatchEvent::Type::kDeleted) {
      jaxjob.OnDeleted(ev.resource);
    } else {
      dirty.push_back(ev.resource.name);
    }
  });
  // Experiment/Trial deletes cascade to their children (apiserver GC).
  store.Watch("Experiment", [&tune, lead](const tpk::WatchEvent& ev) {
    if (!lead()) return;
    if (ev.type == tpk::WatchEvent::Type::kDeleted) tune.OnDeleted(ev.resource);
  });
  store.Watch("Trial", [&tune, lead](const tpk::WatchEvent& ev) {
    if (!lead()) return;
    if (ev.type == tpk::WatchEvent::Type::kDeleted) tune.OnDeleted(ev.resource);
  });
  store.Watch("PipelineRun", [&pipelines, lead](const tpk::WatchEvent& ev) {
    if (!lead()) return;
    if (ev.type == tpk::WatchEvent::Type::kDeleted) {
      pipelines.OnDeleted(ev.resource);
    }
  });
  store.Watch("InferenceService", [&serve, lead](const tpk::WatchEvent& ev) {
    if (!lead()) return;
    if (ev.type == tpk::WatchEvent::Type::kDeleted) {
      serve.OnDeleted(ev.resource);
    }
  });
  store.Watch("TrainedModel", [&trained, lead](const tpk::WatchEvent& ev) {
    if (!lead()) return;
    if (ev.type == tpk::WatchEvent::Type::kDeleted) {
      trained.OnDeleted(ev.resource);
    }
  });

  // Watch coalescing collapses most same-name churn already; the sort+
  // unique below catches the rest so one job never reconciles twice in
  // one pass.
  auto reconcile_dirty = [&dirty, &jaxjob]() {
    std::sort(dirty.begin(), dirty.end());
    dirty.erase(std::unique(dirty.begin(), dirty.end()), dirty.end());
    for (const auto& name : dirty) jaxjob.Reconcile(name);
    dirty.clear();
  };
  // A failed CONTROLLER commit is fatal (etcd's WAL-sync-failure rule):
  // unlike a client batch — whose rollback is complete because replies
  // are held and watch events gated — the Ticks/reconciles act on their
  // mutations in the same call (worker gangs spawned, processes
  // signalled). The store rollback cannot undo those side effects, so
  // continuing would run a controller whose in-process state diverges
  // from durable state (e.g. a Launched gang whose job replays as
  // Pending → duplicate launch). Exit loudly; restart replays the
  // durable state and re-reconciles — the exact path the kill-9 crash
  // tests prove correct.
  auto controller_commit_ok = [&store, &repl, replicated]() {
    std::string gc_err;
    // Controller mutations replicate exactly like client ops (they are
    // the same WAL records); a leader that cannot quorum them — or was
    // deposed while its batch was open — exits rather than run
    // controllers whose side effects outlive a rolled-back batch.
    const bool ok = replicated ? repl.CommitQuorum(&gc_err)
                               : store.CommitGroup(&gc_err);
    if (ok) return true;
    fprintf(stderr,
            "tpk-controlplane: FATAL: controller group commit failed "
            "(%s); controller side effects cannot be rolled back — "
            "exiting, restart replays durable state\n",
            gc_err.c_str());
    return false;
  };
  while (!g_stop) {
    server.PollOnce(50);
    repl.Tick();
    if (replicated && repl.TookLeadership()) {
      // Promotion: the applied store state is now ours to act on.
      // Recover() rebuilds gang/process bookkeeping exactly as a
      // restart would (the old leader's orphaned workers count as one
      // restart, the kill-9 semantics the crash harness pins).
      jaxjob.Recover();
      serve.Recover();
    }
    store.DrainWatches();
    if (lead()) {
      reconcile_dirty();
    } else {
      dirty.clear();  // stale names from a follower window
    }
    if (lead()) {
      double now = static_cast<double>(time(nullptr));
      jaxjob.Tick(now);
      tune.Tick(now);
      schedule.Tick(now);
      pipelines.Tick(now);
      serve.Tick(now);
      trained.Tick(now);
      // Controller-driven mutations (the Ticks above) batch like client
      // ops; land them BEFORE draining their watch events — DrainWatches
      // only delivers committed events (a failed commit must be able to
      // drop its batch's events), so the commit has to come first for the
      // Ticks' child JAXJob create/delete to reach the jaxjob pass below
      // instead of waiting a poll cycle. Failure is fatal — see
      // controller_commit_ok above.
      if (!controller_commit_ok()) return 1;
      // Tune/pipeline writes (child JAXJob create/delete) need a jaxjob
      // pass before the next poll so child gangs launch/die promptly.
      store.DrainWatches();
      reconcile_dirty();
      // ...and the reconcile pass buffers its own mutations: land them
      // before sleeping in poll so the durability window stays one loop
      // pass, not open-ended. Same fatality rule — reconciles spawn too.
      if (!controller_commit_ok()) return 1;
    }
  }
  fprintf(stderr, "tpk-controlplane: shutting down\n");
  return 0;
}
