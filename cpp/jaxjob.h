// JAXJob controller — the north-star CRD controller (SURVEY.md §7.1 item 5).
//
// Reconciles JAXJob resources into gangs of worker processes with the TPK_*
// bootstrap env injected (replacing PyTorchJob's MASTER_ADDR/RANK + c10d
// rendezvous; SURVEY.md §3.1). Semantics carried over from the reference's
// common JobController (⟨training-operator: pkg/controller.v1/common/⟩):
//   - conditions state machine Created → Running → Succeeded/Failed
//     (+ Pending while un-schedulable, Restarting between gang relaunches)
//   - RestartPolicy Never | OnFailure | ExitCode. ExitCode semantics match
//     upstream training-operator (NOT SURVEY.md §5.3, which inverted them):
//     exit 1–127 = permanent failure, 128+ (signal: preemption/OOM-kill)
//     = retryable.
//   - backoffLimit counts gang restarts; activeDeadlineSeconds bounds
//     wall-clock; ttlSecondsAfterFinished garbage-collects the resource.
//   - gang scheduling: whole-slice atomic allocation + all-or-nothing
//     process launch (Volcano PodGroup minMember equivalent).
//   - restart = relaunch from latest orbax checkpoint (the runtime
//     auto-resumes; §5.3/§5.4 checkpoint-restart elasticity).

#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "executor.h"
#include "json.h"
#include "scheduler.h"
#include "store.h"

namespace tpk {

struct ControllerMetrics {
  int64_t jobs_created = 0;
  int64_t jobs_succeeded = 0;
  int64_t jobs_failed = 0;
  int64_t gang_restarts = 0;
  int64_t reconciles = 0;
  int64_t elastic_resizes = 0;

  Json ToJson() const {
    Json j = Json::Object();
    j["jobs_created"] = jobs_created;
    j["jobs_succeeded"] = jobs_succeeded;
    j["jobs_failed"] = jobs_failed;
    j["gang_restarts"] = gang_restarts;
    j["reconciles"] = reconciles;
    j["elastic_resizes"] = elastic_resizes;
    return j;
  }
};

class JaxJobController {
 public:
  JaxJobController(Store* store, ExecutorInterface* executor,
                   Scheduler* scheduler, std::string workdir,
                   std::string python = "python3");

  // Crash recovery: reap orphaned gangs from a previous control-plane
  // incarnation and mark them Restarting. Call once after Store::Load.
  void Recover();

  // Level-triggered reconcile of one job by name. Safe to call repeatedly.
  void Reconcile(const std::string& name);

  // Watch hook for kDeleted events: a deleted job can no longer be fetched
  // by name, so the gang must be killed and its allocation released here
  // (upstream: kubelet kills containers when the pod object goes away).
  void OnDeleted(const Resource& res);

  // Called by the event loop: reap process exits, drive reconciles, enforce
  // deadlines/TTLs. `now_s` injectable for tests.
  void Tick(double now_s);

  ControllerMetrics& metrics() { return metrics_; }

  // Where the API server listens — injected into workers as TPK_SOCKET
  // so the runtime can post events (CheckpointSaved) back into the
  // job's event log. Empty = workers get no event channel.
  void SetSocketPath(const std::string& path) { socket_path_ = path; }

  // Process id helper: "<job>/<replica-index>".
  static std::string ProcId(const std::string& job, int replica);

 private:
  struct JobView {
    Resource res;
    Json spec;
    Json status;
  };

  void LaunchGang(JobView& job);
  void HandleExits(JobView& job);
  // Elastic policy (spec.elastic {min, max?, heartbeat_timeout_s?,
  // upsize_cooldown_s?}): current gang size (status.effectiveReplicas,
  // defaulting to spec.replicas), hang detection via worker-log
  // heartbeats, and capacity-driven upsizing. SURVEY.md §2.6 "Elastic
  // DP" / §5.3 ElasticPolicy+HPA analog.
  int EffectiveReplicas(const JobView& job) const;
  void CheckHeartbeats(JobView& job);
  void MaybeUpsize(JobView& job);
  // The one resize transition: record the new gang size + resize time,
  // bump metrics, set the phase/condition. `count_restart` marks resizes
  // that consumed a gang attempt (worker-death downsizes) so per-attempt
  // gating (spec.fault first-attempt semantics) sees them.
  void ElasticResize(JobView& job, int target, const std::string& phase,
                     const std::string& reason, const std::string& message,
                     bool count_restart);
  // fsdp elasticity (spec.elastic {min_fsdp, max_fsdp?, resize_policy?,
  // target_fsdp?}): the resize unit is the fsdp mesh axis, not the
  // replica count — the controller picks a new fsdp size (a divisor of
  // max_fsdp, so the master-state sharding plan survives), derives the
  // gang shape from it, rewrites runtime.json, and relaunches; the
  // runtime reshards from its own latest checkpoint (ROADMAP item 5).
  // Current size lives in status.effectiveFsdp (default runtime.fsdp).
  int EffectiveFsdp(const JobView& job) const;
  // The fsdp resize transition: stamps an ElasticResize-family event
  // carrying the old -> new topology (merge disabled: two distinct
  // transitions must stay two entries), records effectiveFsdp + the
  // derived effectiveReplicas, bumps metrics, sets phase/condition.
  void ElasticResizeFsdp(JobView& job, int from, int target,
                         const std::string& phase, const std::string& reason,
                         const std::string& detail, bool count_restart);
  // Capacity-driven fsdp regrow (the fsdp twin of MaybeUpsize): probe
  // the scheduler for a bigger divisor under the upsize cooldown.
  void MaybeUpsizeFsdp(JobView& job);
  // Explicit resize request: spec.elastic.target_fsdp applied to a
  // Running gang exactly once per distinct value (status.fsdpTargetApplied
  // latches it so automatic resizes can supersede without re-firing).
  // Returns true when a resize was initiated.
  bool MaybeApplyFsdpTarget(JobView& job);
  // Devices running jobs in `ns` (excluding `exclude`) actually hold —
  // recorded allocations, so elastically resized gangs charge what they
  // use, not their spec maximum.
  int64_t UsedInNamespace(const std::string& ns,
                          const std::string& exclude) const;
  void SetPhase(JobView& job, const std::string& phase,
                const std::string& reason, const std::string& message,
                double now_s);
  // Append one entry to the job's structured event log (events.h):
  // ordered, deduped, bounded, WAL-persisted with the status write the
  // caller's reconcile already makes. type: "Normal" | "Warning".
  // `merge_same_reason=false` keeps distinct same-reason transitions as
  // separate entries (events.h).
  void AppendEvent(JobView& job, const std::string& type,
                   const std::string& reason, const std::string& message,
                   bool merge_same_reason = true);
  void KillAll(const JobView& job);
  void ReleaseAlloc(JobView& job);
  Allocation AllocFromStatus(const Json& status) const;

  Store* store_;
  ExecutorInterface* executor_;
  Scheduler* scheduler_;
  std::string workdir_;
  std::string python_;
  std::string socket_path_;
  ControllerMetrics metrics_;
  double now_s_ = 0;
  // Bounded pending sweep (ISSUE 8): at most this many queued
  // (Pending/Restarting) jobs attempt a launch per Tick, served
  // round-robin from a rotating cursor — thousands of unschedulable
  // jobs must not turn every 50 ms tick into thousands of allocation
  // attempts + status serializations. Watch-driven reconciles
  // (submit, spec change) are NOT capped; freed capacity reaches every
  // queued job within ceil(pending / budget) ticks.
  static constexpr size_t kMaxPendingLaunchPerTick = 128;
  size_t pending_cursor_ = 0;
};

}  // namespace tpk
