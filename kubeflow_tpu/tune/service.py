"""Suggestion service — JSON-lines over stdin/stdout.

The reference deploys one gRPC suggestion service per experiment and the
experiment controller calls `GetSuggestions(experiment, trials)` on it
(⟨katib: pkg/controller.v1beta1/suggestion/⟩ + ⟨pkg/apis/manager/v1beta1 —
api.proto Suggestion service⟩, SURVEY.md §3.4). Here the C++ control plane
spawns ONE shared service process and speaks the same request shape over
pipes — newline-delimited JSON instead of gRPC (grpc C++ is not in the
toolchain; the transport is an implementation detail of the same contract).

Request:
    {"op": "get_suggestions",
     "experiment": {"parameters": [...], "objective": {...},
                    "algorithm": {"name": "tpe", "settings": {...}}},
     "trials": [{"params": {...}, "value": 0.91, "status": "Succeeded"}],
     "count": 2, "seed": 7}
Response:
    {"ok": true, "assignments": [{"lr": 0.003, "opt": "adam"}, ...]}
"""

from __future__ import annotations

import json
import sys

from kubeflow_tpu.tune.algorithms import AlgorithmError, suggest_full


def handle(req: dict) -> dict:
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "pong": True}
    if op != "get_suggestions":
        return {"ok": False, "error": f"unknown op: {op!r}"}
    exp = req.get("experiment") or {}
    algo = exp.get("algorithm") or {}
    objective = exp.get("objective") or {}
    settings = dict(algo.get("settings") or {})
    # TPE needs the optimization direction; carry it from the objective.
    settings.setdefault("goal", objective.get("goal", "minimize"))
    try:
        out = suggest_full(
            algo.get("name", "random"),
            exp.get("parameters") or [],
            req.get("trials") or [],
            int(req.get("count", 1)),
            seed=int(req.get("seed", 0)),
            settings=settings,
        )
    except AlgorithmError as e:
        return {"ok": False, "error": str(e)}
    # `pending` distinguishes "waiting on running trials" (hyperband rung
    # promotion) from exhaustion when assignments is empty.
    return {"ok": True, "assignments": out["assignments"],
            "pending": out["pending"]}


def main(argv: list[str] | None = None) -> int:
    # Line-buffered loop; EOF on stdin = controller went away, exit cleanly.
    # With --remote HOST:PORT the subprocess becomes a thin proxy to an
    # external gRPC Suggestion service (tune/grpc_service.py) — remote /
    # polyglot algorithm services with zero control-plane changes.
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--remote", default="",
                        help="forward to a gRPC Suggestion service")
    args = parser.parse_args(argv)
    remote = None
    if args.remote:
        from kubeflow_tpu.tune.grpc_service import RemoteSuggestion

        remote = RemoteSuggestion(args.remote)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            resp = remote.get(req) if remote is not None else handle(req)
        except Exception as e:  # never kill the service on one bad request
            resp = {"ok": False, "error": f"bad request: {e}"}
        sys.stdout.write(json.dumps(resp) + "\n")
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
