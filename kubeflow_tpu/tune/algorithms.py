"""Hyperparameter search algorithms — the Katib suggestion-service algorithms.

Reimplements the reference's suggestion algorithms natively (⟨katib:
pkg/suggestion/v1beta1/⟩, SURVEY.md §2.3): `random`, `grid`, and `tpe`
(Tree-structured Parzen Estimator — the reference wraps hyperopt's TPE for
its "Bayesian" configs; hyperopt is not installed here, so TPE is
implemented directly from the Bergstra et al. 2011 recipe).

Parameter space schema (Experiment.spec.parameters):
    {"name": "lr",     "type": "double", "min": 1e-5, "max": 1e-1, "log": true}
    {"name": "layers", "type": "int",    "min": 1,    "max": 8,   "step": 2}
    {"name": "opt",    "type": "categorical", "values": ["adam", "sgd"]}

History entries (one per observed trial):
    {"params": {"lr": 3e-4, ...}, "value": 0.92, "status": "Succeeded"}

All algorithms are pure functions of (parameters, history, count, seed):
stateless between calls, like the reference's GetSuggestions(experiment,
trials) contract — the full trial history rides in each request.
"""

from __future__ import annotations

import itertools
import math
import random as _random
from typing import Any, Sequence


class AlgorithmError(ValueError):
    pass


def _check_space(parameters: Sequence[dict]) -> None:
    if not parameters:
        raise AlgorithmError("experiment has no parameters")
    for p in parameters:
        name, typ = p.get("name"), p.get("type", "double")
        if not name:
            raise AlgorithmError(f"parameter missing name: {p}")
        if typ in ("double", "int"):
            if "min" not in p or "max" not in p:
                raise AlgorithmError(f"{name}: {typ} needs min/max")
            if p["max"] < p["min"]:
                raise AlgorithmError(f"{name}: max < min")
            if p.get("log") and p["min"] <= 0:
                raise AlgorithmError(f"{name}: log scale needs min > 0")
        elif typ == "categorical":
            if not p.get("values"):
                raise AlgorithmError(f"{name}: categorical needs values")
        else:
            raise AlgorithmError(f"{name}: unknown type {typ!r}")


def _sample_param(p: dict, rng: _random.Random) -> Any:
    typ = p.get("type", "double")
    if typ == "categorical":
        return rng.choice(p["values"])
    lo, hi = p["min"], p["max"]
    if p.get("log"):
        v = math.exp(rng.uniform(math.log(lo), math.log(hi)))
    else:
        v = rng.uniform(lo, hi)
    if typ == "int":
        step = int(p.get("step", 1) or 1)
        k = round((v - int(lo)) / step)
        return min(max(int(lo) + step * k, int(lo)), int(hi))
    return v


def _key(assignment: dict) -> tuple:
    return tuple(sorted((k, repr(v)) for k, v in assignment.items()))


def suggest_random(parameters: Sequence[dict], history: Sequence[dict],
                   count: int, seed: int = 0, settings: dict | None = None,
                   ) -> list[dict]:
    """Uniform (log-uniform where marked) independent sampling; avoids
    re-proposing assignments already in the history when it can."""
    _check_space(parameters)
    rng = _random.Random(f"{seed}:{len(history)}")
    seen = {_key(h.get("params", {})) for h in history}
    out: list[dict] = []
    for _ in range(count):
        for _attempt in range(20):
            a = {p["name"]: _sample_param(p, rng) for p in parameters}
            if _key(a) not in seen:
                break
        seen.add(_key(a))
        out.append(a)
    return out


def _grid_axis(p: dict) -> list:
    typ = p.get("type", "double")
    if typ == "categorical":
        return list(p["values"])
    lo, hi = p["min"], p["max"]
    if typ == "int":
        step = int(p.get("step", 1) or 1)
        return list(range(int(lo), int(hi) + 1, step))
    # double axis: explicit step, else `num` points (default 5), log-aware.
    if p.get("step"):
        n = int(math.floor((hi - lo) / p["step"] + 1e-9)) + 1
        return [lo + i * p["step"] for i in range(n)]
    num = int(p.get("num", 5))
    if num == 1:
        return [lo]
    if p.get("log"):
        llo, lhi = math.log(lo), math.log(hi)
        return [math.exp(llo + i * (lhi - llo) / (num - 1)) for i in range(num)]
    return [lo + i * (hi - lo) / (num - 1) for i in range(num)]


def suggest_grid(parameters: Sequence[dict], history: Sequence[dict],
                 count: int, seed: int = 0, settings: dict | None = None,
                 ) -> list[dict]:
    """Cartesian-product sweep in deterministic order, resuming past the
    points already tried. Returns fewer than `count` when the grid is
    exhausted (the experiment controller treats that as 'space done')."""
    _check_space(parameters)
    names = [p["name"] for p in parameters]
    axes = [_grid_axis(p) for p in parameters]
    seen = {_key(h.get("params", {})) for h in history}
    out: list[dict] = []
    for combo in itertools.product(*axes):
        if len(out) >= count:
            break
        a = dict(zip(names, combo))
        if _key(a) in seen:
            continue
        seen.add(_key(a))
        out.append(a)
    return out


# ---------------------------------------------------------------------------
# TPE (Bergstra et al., "Algorithms for Hyper-Parameter Optimization", 2011).
# Split observations at the γ-quantile into good/bad sets, model each with a
# 1-d Parzen (kernel-density) mixture per parameter, sample candidates from
# the good model l(x), and keep those maximizing l(x)/g(x) — equivalent to
# maximizing Expected Improvement under the two-density model.
# ---------------------------------------------------------------------------

def _to_unit(p: dict, v: Any) -> float:
    """Map a double/int value into [0,1] (log-aware) for density modeling."""
    lo, hi = p["min"], p["max"]
    if p.get("log"):
        lo, hi, v = math.log(lo), math.log(hi), math.log(max(v, 1e-300))
    return 0.5 if hi == lo else (v - lo) / (hi - lo)


def _from_unit(p: dict, u: float) -> Any:
    lo, hi = p["min"], p["max"]
    u = min(max(u, 0.0), 1.0)
    if p.get("log"):
        v = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
    else:
        v = lo + u * (hi - lo)
    if p.get("type") == "int":
        step = int(p.get("step", 1) or 1)
        v = int(lo) + step * round((v - int(lo)) / step)
        v = min(max(v, int(lo)), int(hi))
    return v


def _parzen_sample(xs: list[float], rng: _random.Random) -> float:
    """Draw from a mixture of gaussians centered at `xs` (unit scale) with a
    Scott-style bandwidth, plus one wide prior component for exploration."""
    bw = max(1.0 / max(len(xs), 1) ** 0.5 * 0.5, 0.05)
    i = rng.randrange(len(xs) + 1)
    if i == len(xs):  # prior component: uniform-ish wide gaussian
        return rng.gauss(0.5, 0.5)
    return rng.gauss(xs[i], bw)


def _parzen_logpdf(xs: list[float], x: float) -> float:
    bw = max(1.0 / max(len(xs), 1) ** 0.5 * 0.5, 0.05)
    comps = [math.exp(-0.5 * ((x - c) / bw) ** 2) / bw for c in xs]
    comps.append(math.exp(-0.5 * ((x - 0.5) / 0.5) ** 2) / 0.5)  # prior
    dens = sum(comps) / (len(xs) + 1) / math.sqrt(2 * math.pi)
    return math.log(max(dens, 1e-300))


def _cat_probs(values: list, obs: list, smooth: float = 1.0) -> list[float]:
    counts = [smooth + sum(1 for o in obs if o == v) for v in values]
    total = sum(counts)
    return [c / total for c in counts]


def suggest_tpe(parameters: Sequence[dict], history: Sequence[dict],
                count: int, seed: int = 0, settings: dict | None = None,
                ) -> list[dict]:
    _check_space(parameters)
    s = settings or {}
    gamma = float(s.get("gamma", 0.25))
    n_candidates = int(s.get("n_candidates", 24))
    n_startup = int(s.get("n_startup", 8))
    goal = s.get("goal", "minimize")

    obs = [h for h in history
           if h.get("value") is not None and h.get("params")]
    if len(obs) < n_startup:
        return suggest_random(parameters, history, count, seed, settings)

    sign = -1.0 if goal == "maximize" else 1.0
    ranked = sorted(obs, key=lambda h: sign * float(h["value"]))
    n_good = max(1, int(math.ceil(gamma * len(ranked))))
    good, bad = ranked[:n_good], ranked[n_good:] or ranked[-1:]

    rng = _random.Random(f"{seed}:{len(history)}:tpe")
    seen = {_key(h.get("params", {})) for h in history}
    out: list[dict] = []
    for _ in range(count):
        best_a, best_score = None, -math.inf
        for _c in range(n_candidates):
            a, score = {}, 0.0
            for p in parameters:
                name = p["name"]
                if p.get("type") == "categorical":
                    values = p["values"]
                    pg = _cat_probs(values, [h["params"].get(name)
                                             for h in good])
                    pb = _cat_probs(values, [h["params"].get(name)
                                             for h in bad])
                    idx = rng.choices(range(len(values)), weights=pg)[0]
                    a[name] = values[idx]
                    score += math.log(pg[idx]) - math.log(pb[idx])
                else:
                    gx = [_to_unit(p, h["params"][name]) for h in good
                          if name in h["params"]]
                    bx = [_to_unit(p, h["params"][name]) for h in bad
                          if name in h["params"]]
                    u = _parzen_sample(gx or [0.5], rng)
                    a[name] = _from_unit(p, u)
                    u_eff = _to_unit(p, a[name])  # score what we'll run
                    score += (_parzen_logpdf(gx or [0.5], u_eff)
                              - _parzen_logpdf(bx or [0.5], u_eff))
            if score > best_score and _key(a) not in seen:
                best_a, best_score = a, score
        if best_a is None:  # every candidate was a duplicate
            best_a = suggest_random(parameters, history, 1,
                                    seed + len(out) + 1, settings)[0]
        seen.add(_key(best_a))
        out.append(best_a)
    return out


# ---------------------------------------------------------------------------
# Hyperband (Li et al., "Hyperband: A Novel Bandit-Based Approach to
# Hyperparameter Optimization", JMLR 2018) — the reference ships it as a
# Katib suggestion service ⟨katib: pkg/suggestion/v1beta1/hyperband⟩.
#
# One parameter is the RESOURCE (settings["resource"], e.g. "steps"): the
# algorithm owns its value. Brackets of successive halving run rung by
# rung; each rung re-proposes the top 1/eta configs at eta× the budget.
# The function is stateless: the bracket/rung position is reconstructed
# from the (ordered) trial history on every call. When a rung is waiting
# on results it returns ([], pending=True) — "ask again later", distinct
# from exhaustion.
#
# Known trade-off of the positional replay: brackets run serially — while
# a rung settles, later (independent) brackets don't propose, idling spare
# parallel_trials capacity. Keying rung membership by params instead of
# position would unlock cross-bracket parallelism at the cost of ambiguity
# under duplicate configs; revisit if hyperband wall-clock matters.
# ---------------------------------------------------------------------------

TERMINAL_TRIAL = ("Succeeded", "Failed", "EarlyStopped", "Stopped")


def hyperband_plan(min_r: float, max_r: float, eta: float) -> list[list[dict]]:
    """Bracket/rung table: brackets[s] is a list of rungs {n, r} — n configs
    at budget r; later rungs keep the top n/eta at eta×r."""
    if not (max_r > 0 and min_r > 0 and max_r >= min_r):
        raise AlgorithmError("hyperband needs 0 < min_resource <= max_resource")
    if eta <= 1:
        raise AlgorithmError("hyperband eta must be > 1")
    s_max = int(math.floor(math.log(max_r / min_r) / math.log(eta)))
    brackets = []
    for s in range(s_max, -1, -1):
        n = int(math.ceil((s_max + 1) * eta ** s / (s + 1)))
        rungs = []
        for i in range(s + 1):
            n_i = max(int(math.floor(n * eta ** (-i))), 1)
            r_i = max_r * eta ** (i - s)
            rungs.append({"n": n_i, "r": r_i})
        brackets.append(rungs)
    return brackets


def _resource_value(p: dict, r: float):
    if p.get("type") == "int":
        return int(min(max(round(r), p["min"]), p["max"]))
    return float(min(max(r, p["min"]), p["max"]))


def suggest_hyperband(parameters: Sequence[dict], history: Sequence[dict],
                      count: int, seed: int = 0,
                      settings: dict | None = None) -> dict:
    """Returns {"assignments": [...], "pending": bool}. pending=True means
    the current rung is waiting on running trials — nothing to propose yet
    but the space is NOT exhausted."""
    _check_space(parameters)
    s = settings or {}
    resource = s.get("resource")
    by_name = {p["name"]: p for p in parameters}
    if not resource or resource not in by_name:
        raise AlgorithmError(
            "hyperband needs settings.resource naming a search parameter "
            f"(have {sorted(by_name)})")
    rp = by_name[resource]
    if rp.get("type") not in ("int", "double"):
        raise AlgorithmError("hyperband resource must be int or double")
    min_r = float(s.get("min_resource", rp["min"]))
    max_r = float(s.get("max_resource", rp["max"]))
    eta = float(s.get("eta", 3.0))
    goal = s.get("goal", "minimize")
    sign = -1.0 if goal == "maximize" else 1.0
    search = [p for p in parameters if p["name"] != resource]
    if not search:
        raise AlgorithmError("hyperband needs at least one non-resource "
                             "parameter")

    brackets = hyperband_plan(min_r, max_r, eta)

    # Replay history through the plan. Each rung's EFFECTIVE size adapts to
    # how many configs actually succeeded in the previous rung, so failed
    # trials shrink later rungs instead of desyncing the slot mapping.
    hist = list(history)
    pos = 0  # next unconsumed history index
    for b, rungs in enumerate(brackets):
        prev_entries: list[dict] = []
        for i, rung in enumerate(rungs):
            if i == 0:
                size = rung["n"]
            else:
                # Promotion needs the WHOLE previous rung settled — a
                # running trial is not a failed one, so the rung size
                # cannot be decided (let alone clamped) until then.
                if any(e.get("status") not in TERMINAL_TRIAL
                       for e in prev_entries):
                    return {"assignments": [], "pending": True}
                promotable = [e for e in prev_entries
                              if e.get("value") is not None]
                size = min(rung["n"], len(promotable))
                if size == 0:
                    break  # bracket dead: every config failed
            assigned = hist[pos:pos + size]
            if len(assigned) < size:
                # This rung is (partially) unproposed — we are here.
                k = len(assigned)
                if i == 0:
                    rng = _random.Random(f"{seed}:hb:{b}:{len(history)}")
                    out = []
                    for j in range(k, min(size, k + count)):
                        a = {p["name"]: _sample_param(p, rng)
                             for p in search}
                        a[resource] = _resource_value(rp, rung["r"])
                        out.append(a)
                    return {"assignments": out, "pending": not out}
                ranked = sorted(
                    (e for e in prev_entries if e.get("value") is not None),
                    key=lambda e: sign * float(e["value"]))
                out = []
                for j in range(k, min(size, k + count)):
                    a = dict(ranked[j]["params"])
                    a[resource] = _resource_value(rp, rung["r"])
                    out.append(a)
                return {"assignments": out, "pending": not out}
            pos += size
            prev_entries = assigned
        # bracket fully proposed; continue to next bracket
    return {"assignments": [], "pending": False}  # plan exhausted


# ---------------------------------------------------------------------------
# CMA-ES (Hansen, "The CMA Evolution Strategy: A Tutorial", 2016) — the
# reference ships it via optuna's sampler ⟨katib: pkg/suggestion/v1beta1⟩.
# Generation-based: λ candidates are drawn from N(m, σ²C) in the unit cube;
# once the whole generation is evaluated, (m, σ, C) update from the ranked
# results (rank-μ + rank-one with step-size/covariance path cumulation).
# Stateless like the others: the evolution state is recomputed by replaying
# completed generations out of the trial history; an incomplete generation
# reports pending.
# ---------------------------------------------------------------------------


def suggest_cmaes(parameters: Sequence[dict], history: Sequence[dict],
                  count: int, seed: int = 0,
                  settings: dict | None = None) -> dict:
    import numpy as np

    _check_space(parameters)
    if any(p.get("type") == "categorical" for p in parameters):
        raise AlgorithmError(
            "cmaes supports numeric parameters only (categorical: use tpe)")
    s = settings or {}
    dim = len(parameters)
    lam = int(s.get("population", 4 + int(3 * math.log(dim + 1))))
    sigma0 = float(s.get("sigma", 0.3))
    goal = s.get("goal", "minimize")
    sign = -1.0 if goal == "maximize" else 1.0

    mu = lam // 2
    w = np.log(mu + 0.5) - np.log(np.arange(1, mu + 1))
    w /= w.sum()
    mu_eff = 1.0 / np.sum(w ** 2)
    cc = (4 + mu_eff / dim) / (dim + 4 + 2 * mu_eff / dim)
    cs = (mu_eff + 2) / (dim + mu_eff + 5)
    c1 = 2 / ((dim + 1.3) ** 2 + mu_eff)
    cmu = min(1 - c1, 2 * (mu_eff - 2 + 1 / mu_eff) /
              ((dim + 2) ** 2 + mu_eff))
    damps = 1 + 2 * max(0.0, math.sqrt((mu_eff - 1) / (dim + 1)) - 1) + cs
    chi_n = math.sqrt(dim) * (1 - 1 / (4 * dim) + 1 / (21 * dim ** 2))

    m = np.full(dim, 0.5)
    sigma = sigma0
    C = np.eye(dim)
    ps = np.zeros(dim)
    pc = np.zeros(dim)

    def sample_generation(g: int) -> list[np.ndarray]:
        # SeedSequence over plain ints: stable across processes (str hash
        # is salted per process — it must never enter the seed path, or a
        # restarted suggestion service would desync from the history).
        rng = np.random.default_rng(
            np.random.SeedSequence([abs(int(seed)), 0xC3A, g]))
        try:
            A = np.linalg.cholesky(C)
        except np.linalg.LinAlgError:
            A = np.linalg.cholesky(C + 1e-10 * np.eye(dim))
        return [np.clip(m + sigma * A @ rng.standard_normal(dim), 0, 1)
                for _ in range(lam)]

    hist = list(history)
    pos = 0
    g = 0
    while True:
        gen = hist[pos:pos + lam]
        if len(gen) < lam:
            # Current generation (partially) unproposed.
            k = len(gen)
            xs = sample_generation(g)
            out = []
            for x in xs[k:k + count]:
                out.append({p["name"]: _from_unit(p, float(x[i]))
                            for i, p in enumerate(parameters)})
            return {"assignments": out, "pending": not out}
        if any(e.get("status") not in TERMINAL_TRIAL for e in gen):
            return {"assignments": [], "pending": True}
        # Generation complete: update the strategy state and continue. The
        # evaluated points are read back from the RECORDED params (mapped
        # into the unit cube), not re-drawn from the RNG — objective values
        # must be credited at the point actually run (int snapping!), and
        # the replay must survive history perturbations and restarts.
        scored = []
        for e in gen:
            if e.get("value") is not None and e.get("params"):
                x = np.array([_to_unit(p, e["params"][p["name"]])
                              for p in parameters])
                scored.append((sign * float(e["value"]), x))
        if len(scored) >= 2:
            scored.sort(key=lambda t: t[0])
            sel = [x for _, x in scored[:mu]]
            while len(sel) < mu:  # failed trials shrink the parent pool
                sel.append(sel[-1])
            X = np.stack(sel)
            m_old = m
            m = w @ X
            try:
                A_inv = np.linalg.inv(np.linalg.cholesky(C))
            except np.linalg.LinAlgError:
                A_inv = np.eye(dim)
            y = (m - m_old) / max(sigma, 1e-12)
            ps = (1 - cs) * ps + math.sqrt(cs * (2 - cs) * mu_eff) * (
                A_inv @ y)
            h_sig = (np.linalg.norm(ps) /
                     math.sqrt(1 - (1 - cs) ** (2 * (g + 1))) <
                     (1.4 + 2 / (dim + 1)) * chi_n)
            pc = (1 - cc) * pc + (
                math.sqrt(cc * (2 - cc) * mu_eff) * y if h_sig else 0)
            ys = (X - m_old) / max(sigma, 1e-12)
            C = ((1 - c1 - cmu) * C + c1 * np.outer(pc, pc) +
                 cmu * (ys.T * w) @ ys)
            C = (C + C.T) / 2  # keep symmetric under fp drift
            sigma *= math.exp(min(
                1.0, (cs / damps) * (np.linalg.norm(ps) / chi_n - 1)))
            sigma = float(np.clip(sigma, 1e-8, 1.0))
        pos += lam
        g += 1


# ---------------------------------------------------------------------------
# PBT (Jaderberg et al., "Population Based Training of Neural Networks",
# 2017) — the reference ships it as a Katib suggestion service ⟨katib:
# pkg/suggestion/v1beta1/pbt⟩. A fixed population trains in segments; after
# each generation the bottom `truncation` fraction EXPLOITS (copies the
# hyperparameters of a random top member) and EXPLORES (perturbs them).
#
# Stateless replay like hyperband/cmaes: member j of generation g is history
# entry [g*N + j], so the whole evolution reconstructs from the ordered
# trial history. Two resource modes:
#   * restart mode (default): each generation's trials train from scratch
#     for a cumulatively larger budget (resource = step·(g+1)) — weight
#     inheritance is approximated by re-training longer, which is the
#     honest trial-restart semantic;
#   * warm-start mode (settings["parent_param"] names a trial parameter):
#     resource stays `step` per segment and each assignment carries the
#     parent's history index in that parameter ("" for generation 0 /
#     self-continuation uses the member's own previous index). The trial
#     template substitutes it into a checkpoint-restore path, giving true
#     PBT weight inheritance over the controller's existing substitution
#     machinery (⟨katib: pbt's checkpoint annotations⟩ equivalent).
# ---------------------------------------------------------------------------


def suggest_pbt(parameters: Sequence[dict], history: Sequence[dict],
                count: int, seed: int = 0,
                settings: dict | None = None) -> dict:
    _check_space(parameters)
    s = settings or {}
    by_name = {p["name"]: p for p in parameters}
    resource = s.get("resource")
    if not resource or resource not in by_name:
        raise AlgorithmError(
            "pbt needs settings.resource naming a search parameter "
            f"(have {sorted(by_name)})")
    rp = by_name[resource]
    if rp.get("type") not in ("int", "double"):
        raise AlgorithmError("pbt resource must be int or double")
    n_pop = int(s.get("population", 8))
    if n_pop < 2:
        raise AlgorithmError("pbt population must be >= 2")
    step = float(s.get("resource_step", rp["min"] if rp["min"] > 0 else 1))
    max_r = float(s.get("max_resource", rp["max"]))
    trunc = float(s.get("truncation", 0.25))
    if not 0.0 < trunc <= 0.5:
        raise AlgorithmError("pbt truncation must be in (0, 0.5]")
    factors = list(s.get("perturb_factors", (0.8, 1.25)))
    resample_prob = float(s.get("resample_prob", 0.25))
    goal = s.get("goal", "minimize")
    sign = -1.0 if goal == "maximize" else 1.0
    parent_param = s.get("parent_param")
    if parent_param and parent_param in by_name:
        raise AlgorithmError(
            f"pbt parent_param {parent_param!r} collides with a search "
            "parameter — it must be a fresh trial-parameter name")
    search = [p for p in parameters if p["name"] != resource]
    if not search:
        raise AlgorithmError("pbt needs at least one non-resource parameter")

    def perturb(a: dict, rng: _random.Random) -> dict:
        out = dict(a)
        for p in search:
            name = p["name"]
            if p.get("type") == "categorical":
                if rng.random() < resample_prob:
                    out[name] = rng.choice(p["values"])
                continue
            if rng.random() < resample_prob:
                out[name] = _sample_param(p, rng)
                continue
            # Multiplicative perturbation in the modeling scale: log-space
            # params multiply the raw value; linear params scale the unit
            # coordinate (keeps the factor meaningful near 0).
            f = rng.choice(factors)
            if p.get("log"):
                out[name] = _from_unit(p, _to_unit(p, out[name] * f))
            else:
                out[name] = _from_unit(p, _to_unit(p, out[name]) * f)
        return out

    def resource_for(g: int) -> Any:
        r = step if parent_param else step * (g + 1)
        return _resource_value(rp, min(r, max_r))

    hist = list(history)
    pos, g = 0, 0
    while True:
        gen = hist[pos:pos + n_pop]
        if len(gen) < n_pop:
            k = len(gen)
            rng = _random.Random(f"{seed}:pbt:{g}:{len(history)}")
            out = []
            if g == 0:
                for j in range(k, min(n_pop, k + count)):
                    a = {p["name"]: _sample_param(p, rng) for p in search}
                    a[resource] = resource_for(0)
                    if parent_param:
                        a[parent_param] = ""
                    out.append(a)
                return {"assignments": out, "pending": not out}
            prev = hist[pos - n_pop:pos]
            ranked = sorted(
                range(n_pop),
                key=lambda j: (sign * float(prev[j]["value"])
                               if prev[j].get("value") is not None
                               else math.inf))
            n_cut = max(1, int(round(trunc * n_pop)))
            top, bottom = ranked[:n_cut], set(ranked[-n_cut:])
            # Members with no metric at all count as bottom too.
            for j in range(n_pop):
                if prev[j].get("value") is None:
                    bottom.add(j)
            for j in range(k, min(n_pop, k + count)):
                src = prev[j].get("params", {})
                base = {p["name"]: src.get(p["name"]) for p in search}
                if j in bottom or any(v is None for v in base.values()):
                    donor = top[rng.randrange(len(top))]
                    dsrc = prev[donor].get("params", {})
                    base = {p["name"]: dsrc.get(p["name"],
                                                _sample_param(p, rng))
                            for p in search}
                    a = perturb(base, rng)
                    parent = pos - n_pop + donor
                else:
                    a = dict(base)
                    parent = pos - n_pop + j
                a[resource] = resource_for(g)
                if parent_param:
                    a[parent_param] = str(parent)
                out.append(a)
            return {"assignments": out, "pending": not out}
        if any(e.get("status") not in TERMINAL_TRIAL for e in gen):
            return {"assignments": [], "pending": True}
        pos += n_pop
        g += 1


# ---------------------------------------------------------------------------
# Regularized evolution (Real et al., "Regularized Evolution for Image
# Classifier Architecture Search", AAAI 2019) — the NAS workhorse. The
# reference ships NAS as ENAS/DARTS suggestion services ⟨katib:
# pkg/suggestion/v1beta1/nas⟩, both of which embed a trained controller /
# supernet in the service; aging evolution reaches comparable architectures
# with a plain ask/tell loop (the AmoebaNet result), which is the honest
# fit for this stateless suggestion protocol. Architectures are encoded in
# the ordinary parameter-space schema (categorical ops / int dims), so any
# trial template can consume them.
#
# Replay: population = the last `population` terminal trials (aging: older
# trials fall out of the window); each proposal picks the best of a random
# `sample` subset and mutates ONE parameter.
# ---------------------------------------------------------------------------


def suggest_evolution(parameters: Sequence[dict], history: Sequence[dict],
                      count: int, seed: int = 0,
                      settings: dict | None = None) -> list[dict]:
    _check_space(parameters)
    s = settings or {}
    pop_size = int(s.get("population", 20))
    sample = int(s.get("sample", 5))
    if pop_size < 2 or sample < 1:
        raise AlgorithmError("evolution needs population >= 2, sample >= 1")
    goal = s.get("goal", "minimize")
    sign = -1.0 if goal == "maximize" else 1.0

    def mutate(a: dict, rng: _random.Random) -> dict:
        out = dict(a)
        p = parameters[rng.randrange(len(parameters))]
        name = p["name"]
        if p.get("type") == "categorical":
            choices = [v for v in p["values"] if v != out.get(name)]
            out[name] = rng.choice(choices or p["values"])
        else:
            # Local move in the unit/model scale; fall back to resample
            # when stuck on a bound.
            u = _to_unit(p, out[name])
            nu = min(max(u + rng.gauss(0.0, 0.15), 0.0), 1.0)
            moved = _from_unit(p, nu)
            out[name] = (moved if moved != out[name]
                         else _sample_param(p, rng))
        return out

    rng = _random.Random(f"{seed}:rea:{len(history)}")
    terminal = [h for h in history
                if h.get("status") in TERMINAL_TRIAL and h.get("params")]
    scored = [h for h in terminal[-pop_size:] if h.get("value") is not None]
    out: list[dict] = []
    seen = {_key(h.get("params", {})) for h in history}
    for _ in range(count):
        if len(scored) < 2:  # seed the population randomly
            a = {p["name"]: _sample_param(p, rng) for p in parameters}
        else:
            tournament = [scored[rng.randrange(len(scored))]
                          for _ in range(sample)]
            parent = min(tournament, key=lambda h: sign * float(h["value"]))
            a = mutate(dict(parent["params"]), rng)
        for _retry in range(20):
            if _key(a) not in seen:
                break
            a = mutate(a, rng)
        seen.add(_key(a))
        out.append(a)
    return out


ALGORITHMS = {
    "random": suggest_random,
    "grid": suggest_grid,
    "tpe": suggest_tpe,
    "bayesian": suggest_tpe,  # reference's "Bayesian" configs use TPE
    "hyperband": suggest_hyperband,
    "cmaes": suggest_cmaes,
    "pbt": suggest_pbt,
    "evolution": suggest_evolution,
    "nas-evolution": suggest_evolution,  # NAS entry point (arch-encoded spaces)
}


def suggest_full(algorithm: str, parameters: Sequence[dict],
                 history: Sequence[dict], count: int, seed: int = 0,
                 settings: dict | None = None) -> dict:
    """Normalized service entry point: always returns
    {"assignments": [...], "pending": bool} (plain-list algorithms never
    report pending)."""
    fn = ALGORITHMS.get(algorithm)
    if fn is None:
        raise AlgorithmError(
            f"unknown algorithm {algorithm!r}; have {sorted(ALGORITHMS)}")
    out = fn(parameters, history, count, seed=seed, settings=settings)
    if isinstance(out, dict):
        return {"assignments": list(out.get("assignments", [])),
                "pending": bool(out.get("pending", False))}
    return {"assignments": list(out), "pending": False}


def suggest(algorithm: str, parameters: Sequence[dict],
            history: Sequence[dict], count: int, seed: int = 0,
            settings: dict | None = None) -> list[dict]:
    """Assignments only (drops the pending signal; see suggest_full)."""
    return suggest_full(algorithm, parameters, history, count, seed=seed,
                        settings=settings)["assignments"]
