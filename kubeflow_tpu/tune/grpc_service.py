"""gRPC transport for the suggestion service — remote/polyglot algorithms.

The reference's Experiment controller calls a per-experiment gRPC
Suggestion service (⟨katib: pkg/apis/manager/v1beta1 — api.proto
Suggestion.GetSuggestions⟩), which lets algorithm services live in any
language and on any machine. The in-tree transport here is the JSON-lines
subprocess (tune/service.py) because the C++ control plane has no gRPC
toolchain — this module restores the REMOTE contract on top of it:

  * `serve_suggestions()` exposes GetSuggestions over gRPC (generic
    handlers, JSON payloads — the same request/response shape as
    service.py, so one contract, two transports);
  * `RemoteSuggestion` is the typed client;
  * `service.py --remote host:port` turns the controller-spawned
    subprocess into a thin proxy, so external algorithm services plug in
    with ZERO control-plane changes.

JSON payloads rather than a new proto: the shape is already the
documented contract (service.py docstring), and a polyglot implementer
needs only a gRPC generic endpoint echoing that JSON — no codegen.
"""

from __future__ import annotations

import json
from concurrent import futures

import grpc

SERVICE = "tpukit.tune.Suggestion"
_METHOD = "GetSuggestions"


def _ser(d: dict) -> bytes:
    return json.dumps(d).encode()


def _deser(b: bytes) -> dict:
    return json.loads(b or b"{}")


def serve_suggestions(port: int = 0, *, host: str = "127.0.0.1",
                      handler=None, max_workers: int = 4):
    """Start a gRPC server answering GetSuggestions with `handler`
    (default: the in-tree algorithm suite via service.handle). Returns
    (server, bound_port). `host` defaults to loopback for safety — pass
    "0.0.0.0" (or a NIC address) to serve REMOTE controllers; the
    channel is insecure, so front it with your mesh/mTLS like any katib
    suggestion deployment."""
    from kubeflow_tpu.tune.service import handle as default_handle

    handle = handler or default_handle

    def get_suggestions(request: dict, context) -> dict:
        try:
            return handle(request)
        except Exception as e:  # contract: errors ride the envelope
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    rpc = grpc.method_handlers_generic_handler(SERVICE, {
        _METHOD: grpc.unary_unary_rpc_method_handler(
            get_suggestions, request_deserializer=_deser,
            response_serializer=_ser),
    })
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((rpc,))
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


class RemoteSuggestion:
    """Client for a remote Suggestion service (any language, same JSON
    contract)."""

    def __init__(self, address: str, timeout: float = 60.0):
        self._channel = grpc.insecure_channel(address)
        self._call = self._channel.unary_unary(
            f"/{SERVICE}/{_METHOD}", request_serializer=_ser,
            response_deserializer=_deser)
        self._timeout = timeout

    def get(self, request: dict) -> dict:
        try:
            return self._call(request, timeout=self._timeout)
        except grpc.RpcError as e:
            return {"ok": False,
                    "error": f"remote suggestion service: {e.code().name}"}

    def close(self):
        self._channel.close()
