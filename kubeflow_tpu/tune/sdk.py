"""Tuning SDK — KatibClient parity (⟨katib: sdk/python — KatibClient,
tune()⟩, SURVEY.md §2.3/§3.4).

`TuneClient` wraps the control-plane client with Experiment conveniences;
`TuneClient.tune()` reproduces the reference's `tune()` UX: hand it a plain
Python function and a search space, and it fabricates the Experiment —
the function's source is packaged into the trial command with parameters
substituted by the C++ trial controller (the reference packages the
function into a container image; here the "image" is a `python -c` stanza).
"""

from __future__ import annotations

import inspect
import textwrap
import time
from typing import Any, Callable, Sequence

from kubeflow_tpu.controlplane.client import Client


class TuneClient:
    def __init__(self, client: Client):
        self.client = client

    # -- CRUD conveniences ---------------------------------------------------

    def create_experiment(self, name: str, *, parameters: Sequence[dict],
                          objective: dict, algorithm: dict | str = "random",
                          trial_template: dict, max_trials: int = 10,
                          parallel_trials: int = 1,
                          max_failed_trials: int = 3,
                          early_stopping: dict | None = None,
                          seed: int = 0) -> dict:
        if isinstance(algorithm, str):
            algorithm = {"name": algorithm}
        spec = {
            "parameters": list(parameters),
            "objective": objective,
            "algorithm": algorithm,
            "trial_template": trial_template,
            "max_trials": max_trials,
            "parallel_trials": parallel_trials,
            "max_failed_trials": max_failed_trials,
            "seed": seed,
        }
        if early_stopping:
            spec["early_stopping"] = early_stopping
        return self.client.create("Experiment", name, spec)

    def get(self, name: str) -> dict:
        return self.client.get("Experiment", name)

    def trials(self, name: str) -> list[dict]:
        return [t for t in self.client.list("Trial")
                if t["spec"].get("experiment") == name]

    def wait(self, name: str, timeout: float = 600.0,
             poll: float = 0.5) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            phase = self.get(name).get("status", {}).get("phase", "")
            if phase in ("Succeeded", "Failed"):
                return phase
            time.sleep(poll)
        raise TimeoutError(f"experiment {name} still "
                           f"{self.get(name).get('status', {}).get('phase')} "
                           f"after {timeout}s")

    def optimal_trial(self, name: str) -> dict:
        """{'trial': ..., 'params': {...}, 'value': ...} of the best trial."""
        opt = self.get(name).get("status", {}).get("optimal")
        if not opt:
            raise RuntimeError(f"experiment {name} has no observations yet")
        return opt

    # -- tune(): python function → Experiment --------------------------------

    def tune(self, name: str, objective_fn: Callable[[dict], Any], *,
             parameters: Sequence[dict], metric: str = "objective",
             goal: str = "minimize", target: float | None = None,
             algorithm: dict | str = "tpe", max_trials: int = 10,
             parallel_trials: int = 1, seed: int = 0,
             python: str = "python3") -> dict:
        """Wraps `objective_fn(params) -> float | dict` into an Experiment.

        The function must be self-contained (its own imports inside the
        body), mirroring the reference tune()'s packaging constraint. It
        receives the parameter dict and returns the objective value (or a
        dict of metrics including `metric`); trial workers print
        `metric=value` lines the trial controller's collector parses.
        """
        source = textwrap.dedent(inspect.getsource(objective_fn))
        if objective_fn.__name__ == "<lambda>":
            raise ValueError("objective_fn must be a named function")
        # Typed parameter literal: numbers stay bare so the dict is valid
        # python after ${...} substitution; categoricals are quoted.
        items = []
        for p in parameters:
            key = p["name"]
            token = "${%s}" % key
            if p.get("type") == "categorical":
                items.append(f'"{key}": "{token}"')
            else:
                items.append(f'"{key}": {token}')
        params_literal = "{" + ", ".join(items) + "}"
        runner = "\n".join([
            source,
            f"params = {params_literal}",
            f"result = {objective_fn.__name__}(params)",
            "metrics = result if isinstance(result, dict) else "
            f"{{{metric!r}: result}}",
            "for k, v in metrics.items():",
            "    print(f\"{k}={v}\", flush=True)",
        ])
        objective = {"metric": metric, "goal": goal}
        if target is not None:
            objective["target"] = target
        trial_template = {
            "replicas": 1,
            "devices_per_proc": 1,
            "command": [python, "-c", runner],
        }
        return self.create_experiment(
            name, parameters=parameters, objective=objective,
            algorithm=algorithm, trial_template=trial_template,
            max_trials=max_trials, parallel_trials=parallel_trials,
            seed=seed)
