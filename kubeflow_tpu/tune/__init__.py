"""Hyperparameter tuning — the Katib-equivalent subsystem (SURVEY.md §2.3).

Layout:
  algorithms.py — random / grid / TPE search (suggestion algorithms)
  service.py    — suggestion service the C++ control plane spawns
  sdk.py        — ExperimentClient + tune() convenience (KatibClient parity)

The Experiment/Trial reconcilers live in the C++ control plane
(cpp/tune.cc), mirroring the reference's Go controllers.
"""

from kubeflow_tpu.tune.algorithms import (  # noqa: F401
    ALGORITHMS,
    AlgorithmError,
    suggest,
    suggest_grid,
    suggest_random,
    suggest_tpe,
)
