"""Synthetic + toy datasets for tests/benchmarks.

The reference's input path is per-framework (tf.data / torch DataLoader in
user images); our first-class loader story is grain (data/loader.py). These
deterministic generators back the test suite and bench.py, mirroring the
reference's CPU-sized MNIST e2e fixtures (SURVEY.md §4.5).
"""

from __future__ import annotations

import numpy as np


def token_batches(batch_size: int, seq_len: int, vocab_size: int,
                  seed: int = 0, sharded_by: int = 1):
    """Infinite causal-LM batches: inputs/targets shifted by one.
    `sharded_by` ensures the global batch divides the dp axis."""
    assert batch_size % sharded_by == 0
    rng = np.random.default_rng(seed)
    while True:
        toks = rng.integers(0, vocab_size, (batch_size, seq_len + 1),
                            dtype=np.int32)
        yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


def learnable_token_batches(batch_size: int, seq_len: int, vocab_size: int,
                            seed: int = 0):
    """A *learnable* sequence task (next token = (token + 1) mod V with a
    fixed random permutation) so convergence tests can assert loss ↓."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(vocab_size).astype(np.int32)
    while True:
        start = rng.integers(0, vocab_size, (batch_size, 1), dtype=np.int32)
        seq = [start]
        for _ in range(seq_len):
            seq.append(perm[seq[-1]])
        toks = np.concatenate(seq, axis=1)
        yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


def mnist_like(batch_size: int, seed: int = 0, num_classes: int = 10):
    """MNIST-shaped separable classification data: class = argmax of a fixed
    linear projection of the image; an MLP must drive loss near zero."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(784, num_classes)).astype(np.float32)
    while True:
        x = rng.normal(size=(batch_size, 784)).astype(np.float32)
        y = np.argmax(x @ w, axis=-1).astype(np.int32)
        yield {"inputs": x, "targets": y}
