"""Background input prefetcher: host data prep + H2D off the hot loop.

The trainer's step loop used to pay `next(data)` (the grain pipeline plus
packed-row assembly), the zigzag permute, and the implicit host->device
transfer synchronously between dispatches — and on a tunnel-latency
backend every host-driven stall in the dispatch path costs ~66 ms
(PROFILE.md §1). `Prefetcher` moves all of that onto one worker thread
that stages up to `depth` device-resident batches ahead of compute — the
`prefetch_to_device` discipline MaxText-class JAX trainers use, and the
tf.data argument (Murray et al. 2021) that input pipelines belong off the
accelerator's critical path.

Resume correctness is the subtle part. The worker snapshots the
iterator's checkpoint state *alongside each batch as it pulls it*, and
`consumed_state()` returns the snapshot paired with the batch most
recently handed to the caller — NOT the iterator's read-ahead position.
A checkpoint taken after training batch N therefore resumes at batch
N+1 even though the worker had already pulled batches N+1..N+depth; a
kill-9 under prefetch replays exactly the right rows.

`depth=0` is the synchronous escape hatch: no thread, every call does
pull -> transform -> place inline, bit-for-bit the pre-prefetch loop
(the `data.next` fault point fires on the calling thread instead).

Failure semantics: any exception raised while pulling or preparing a
batch on the worker (including faults injected at `data.next`) is
queued in order and re-raised from `next()` on the *training* thread —
the step that would have consumed the batch is the step that fails, so
restart policies see data faults exactly like step faults. The worker
exits after queuing an error; `close()` is idempotent, drains the
queue, and joins the thread on every trainer exit path.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable, Iterator, Mapping

from kubeflow_tpu.utils import faults, resilience

_LOG = logging.getLogger(__name__)

#: Fires before every raw-batch pull (ctx: n = 0-based pull index). With
#: depth >= 1 it fires on the worker thread; the injected error is still
#: delivered to the training thread at the matching `next()`.
_FP_NEXT = faults.register_point(
    "data.next", "before each raw-batch pull from the input iterator; "
    "ctx: n (0-based pull index)")

#: Thread-name prefix for every prefetch worker — the test suite's
#: thread-leak guard (tests/conftest.py) keys on it.
THREAD_NAME = "tpk-prefetch"

_STOP = object()  # sentinel: the wrapped iterator is exhausted


class _Failure:
    """An exception captured on the worker, queued in stream order."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class Prefetcher:
    """Depth-K queue of prepared (transformed + device-placed) batches.

    Args:
      it: the raw batch iterator (checkpointable grain iterator or plain
        generator). The prefetcher takes ownership: nothing else may
        pull from it while the prefetcher lives.
      depth: queue capacity. 0 = synchronous passthrough (no thread);
        K >= 1 lets the worker run up to K+1 batches ahead (K queued
        plus one in hand waiting for a slot).
      transform: optional host-side per-batch transform (e.g. the zigzag
        permute) applied before placement.
      place: optional device placement (jax.device_put with the dp
        sharding / make_array_from_process_local_data). Its wall time is
        accounted as `h2d_s`.
      state_fn: returns the iterator's resume state (defaults to
        `loader.iterator_state(it)`; None for plain generators).
      component: label for the shared tpk_* metrics.
    """

    def __init__(self, it: Iterator[Any], *, depth: int,
                 transform: Callable[[Any], Any] | None = None,
                 place: Callable[[Any], Any] | None = None,
                 state_fn: Callable[[], Mapping[str, Any] | None] | None
                 = None,
                 component: str = "train"):
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        from kubeflow_tpu.data.loader import iterator_state

        self._it = iter(it)
        self._depth = int(depth)
        self._transform = transform
        self._place = place
        self._state_fn = state_fn or (lambda: iterator_state(self._it))
        self._component = component
        # Worker-thread writes race the training thread's stats/window
        # reads (and depth-0 counters live on the consumer thread): one
        # lock keeps the counter quartet tear-free.
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._pulled = 0     # raw batches pulled from the iterator
        # guarded-by: _lock
        self._consumed = 0   # batches handed to the caller
        self._exc: BaseException | None = None
        self._exhausted = False
        self._closed = False
        # guarded-by: _lock
        self.data_wait_s = 0.0  # training-thread time spent inside next()
        # guarded-by: _lock
        self.h2d_s = 0.0        # wall time spent in place() (H2D staging)
        resilience.metrics.set_gauge("tpk_data_prefetch_depth",
                                     self._depth, component=component)
        self._thread: threading.Thread | None = None
        if self._depth:
            # Captured BEFORE the worker starts reading ahead: the
            # floor consumed_state() returns until a batch is consumed.
            self._consumed_state = self._state_fn()
            self._q: queue.Queue = queue.Queue(maxsize=self._depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._worker, name=THREAD_NAME, daemon=True)
            self._thread.start()

    # -- worker --------------------------------------------------------------

    # tpk-hot: prefetch-worker
    def _prep(self, raw: Any) -> Any:
        if self._transform is not None:
            raw = self._transform(raw)
        if self._place is not None:
            t0 = time.perf_counter()
            raw = self._place(raw)
            dt = time.perf_counter() - t0
            with self._lock:
                self.h2d_s += dt
            resilience.metrics.inc("tpk_data_h2d_seconds_total", dt,
                                   component=self._component)
        return raw

    def _offer(self, item: Any) -> bool:
        """Blocking put that stays responsive to close(): a worker stuck
        on a full queue must observe the stop flag, not deadlock."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # tpk-hot: prefetch-worker
    def _worker(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                n = self._pulled
            try:
                faults.fire(_FP_NEXT, n=n)
                raw = next(self._it)
            except StopIteration:
                self._offer(_STOP)
                return
            except BaseException as e:
                self._offer(_Failure(e))
                return
            with self._lock:
                self._pulled += 1
            try:
                # Snapshot BEFORE reading ahead any further: this state
                # resumes at the batch after `raw` — what a checkpoint
                # taken after training `raw` must record.
                state = self._state_fn()
                item = (self._prep(raw), state)
            except BaseException as e:
                self._offer(_Failure(e))
                return
            if not self._offer(item):
                return

    # -- consumer ------------------------------------------------------------

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self) -> Any:
        t0 = time.perf_counter()
        try:
            if self._depth == 0:
                if self._closed:
                    raise RuntimeError("Prefetcher is closed")
                with self._lock:
                    n = self._pulled
                faults.fire(_FP_NEXT, n=n)
                raw = next(self._it)  # StopIteration propagates as-is
                with self._lock:
                    self._pulled += 1
                batch = self._prep(raw)
                with self._lock:
                    self._consumed += 1
                return batch
            if self._exc is not None:
                raise self._exc
            if self._exhausted:
                raise StopIteration
            if self._closed:
                # The queue was drained and the worker stopped — a
                # bare q.get() here would block forever.
                raise RuntimeError("Prefetcher is closed")
            item = self._q.get()
            if item is _STOP:
                self._exhausted = True
                raise StopIteration
            if isinstance(item, _Failure):
                self._exc = item.exc
                raise item.exc
            batch, state = item
            self._consumed_state = state
            with self._lock:
                self._consumed += 1
            return batch
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.data_wait_s += dt
            resilience.metrics.inc("tpk_data_wait_seconds_total", dt,
                                   component=self._component)

    next = __next__

    def consumed_state(self) -> Mapping[str, Any] | None:
        """Iterator resume state matching the batches handed out so far
        (None for plain generators). Safe to call after close()."""
        if self._depth == 0:
            return self._state_fn()
        return self._consumed_state

    @property
    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self._depth,
                "pulled": self._pulled,
                "consumed": self._consumed,
                "data_wait_s": self.data_wait_s,
                "h2d_s": self.h2d_s,
            }

    def close(self, timeout: float = 5.0) -> None:
        """Stop and join the worker (idempotent; every trainer exit path
        must land here so restarts never leak threads)."""
        self._closed = True
        if self._thread is None:
            return
        self._stop.set()
        try:  # unblock a worker waiting on a full queue
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout)
        if self._thread.is_alive():
            # Worker is wedged inside next(self._it) (e.g. a stalled
            # storage pull). Keep the handle so a later close() can
            # retry the join, and make the leak visible — the daemon
            # thread still holds the old iterator's resources.
            resilience.metrics.inc("tpk_data_prefetch_close_timeout_total",
                                   component=self._component)
            _LOG.warning(
                "prefetch worker did not exit within %.1fs (stuck in the "
                "input iterator?); thread left running, close() may be "
                "retried", timeout)
            return
        self._thread = None

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
