"""Grain-backed input pipeline with checkpointable iterator state.

The reference delegates input entirely to per-framework user code
(tf.data / torch DataLoader inside the operator-launched images — SURVEY.md
§2.6 note, §7.1 item 1); resume-determinism is the user's problem. Here the
loader is first-class and *checkpointable*: the grain iterator exposes
`get_state()/set_state()` (a small JSON dict), the trainer saves it through
orbax alongside the TrainState, and resume restores the exact stream
position instead of replaying `next(data)` O(steps) times.

Sharding story matches the platform: each process builds the same pipeline
with its `(process_index, process_count)` shard, so the global batch is
assembled from disjoint per-process streams — the grain analog of the
reference's per-worker DataLoader sharding, done for the user.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import numpy as np


class _Windows:
    """Random-access view of a flat token array as non-overlapping
    (seq_len+1)-token windows: window i -> tokens[i*S : i*S + S + 1].
    The +1 overlap gives the shifted-by-one LM targets."""

    def __init__(self, tokens: np.ndarray, seq_len: int):
        if tokens.ndim != 1:
            raise ValueError(f"tokens must be flat, got shape {tokens.shape}")
        self._tokens = tokens
        self._seq = int(seq_len)
        self._n = max((len(tokens) - 1) // self._seq, 0)
        if self._n == 0:
            raise ValueError(
                f"{len(tokens)} tokens can't fill one window of "
                f"{seq_len + 1}")

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> np.ndarray:
        s = int(i) * self._seq
        return np.asarray(self._tokens[s:s + self._seq + 1], np.int32)


def load_tokens(source: Any) -> np.ndarray:
    """Resolve a token source to a flat int32 array.

    Accepts an in-memory array/list, an `.npy` file (memory-mapped so epoch
    shuffles never load the corpus into RAM), a raw `.bin`/`.tokens` file of
    little-endian int32, or a `.txt`/other text file tokenized as UTF-8
    bytes (vocab 256 — the bring-up tokenizer, same trick the serving
    path's `tokenizer="bytes"` mode uses)."""
    if isinstance(source, (list, tuple, np.ndarray)):
        return np.asarray(source, np.int32).reshape(-1)
    path = os.fspath(source)
    if not os.path.exists(path):
        raise FileNotFoundError(f"token source {path!r} does not exist")
    if path.endswith(".npy"):
        return np.load(path, mmap_mode="r")
    if path.endswith((".bin", ".tokens")):
        return np.memmap(path, dtype=np.int32, mode="r")
    with open(path, "rb") as fh:
        return np.frombuffer(fh.read(), dtype=np.uint8).astype(np.int32)


def lm_dataset(
    source: Any,
    *,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
    shuffle: bool = True,
    num_epochs: int | None = None,
    process_index: int | None = None,
    process_count: int | None = None,
    vocab_size: int | None = None,
):
    """Build the grain pipeline: windows -> per-process shard -> (shuffle)
    -> repeat -> batch -> {"inputs", "targets"}.

    Returns a `grain.MapDataset`; `iter()` on it yields a checkpointable
    iterator (get_state/set_state). `batch_size` here is the PER-PROCESS
    batch (the trainer passes its `local_batch_size`)."""
    import grain.python as gp

    if process_index is None or process_count is None:
        import jax

        process_index = jax.process_index()
        process_count = jax.process_count()

    tokens = load_tokens(source)
    if vocab_size is not None:
        # One O(corpus) scan at startup beats training silently on clamped
        # out-of-vocab ids (embedding take clamps, loss stays finite).
        lo, hi = int(np.min(tokens)), int(np.max(tokens))
        if lo < 0 or hi >= vocab_size:
            raise ValueError(
                f"corpus token ids span [{lo}, {hi}] but the model vocab "
                f"is {vocab_size} — wrong tokenizer for this model?")
    ds = gp.MapDataset.source(_Windows(tokens, seq_len))
    if process_count > 1:
        ds = ds[process_index::process_count]
    if len(ds) < batch_size:
        raise ValueError(
            f"shard has {len(ds)} windows < batch_size {batch_size}; "
            f"corpus too small for ({process_count} procs, seq_len "
            f"{seq_len})")
    if shuffle:
        ds = ds.shuffle(seed=seed)
    ds = ds.repeat(num_epochs)
    ds = ds.batch(batch_size, drop_remainder=True)
    return ds.map(lambda b: {"inputs": b[:, :-1], "targets": b[:, 1:]})


def iterator_state(it: Any) -> Mapping[str, Any] | None:
    """The iterator's resume state, or None for plain generators."""
    get = getattr(it, "get_state", None)
    return get() if callable(get) else None


def restore_iterator(it: Any, state: Mapping[str, Any] | None) -> bool:
    """Seek a checkpointable iterator to a saved state. Returns True when
    the seek happened (caller then skips replay)."""
    set_state = getattr(it, "set_state", None)
    if state is None or not callable(set_state):
        return False
    set_state(dict(state))
    return True
