"""Grain-backed input pipeline with checkpointable iterator state.

The reference delegates input entirely to per-framework user code
(tf.data / torch DataLoader inside the operator-launched images — SURVEY.md
§2.6 note, §7.1 item 1); resume-determinism is the user's problem. Here the
loader is first-class and *checkpointable*: the grain iterator exposes
`get_state()/set_state()` (a small JSON dict), the trainer saves it through
orbax alongside the TrainState, and resume restores the exact stream
position instead of replaying `next(data)` O(steps) times.

Sharding story matches the platform: each BATCH REPLICA GROUP builds the
same pipeline with its `(process_index, process_count)` shard — the
trainer passes its group index/count (processes sharing a batch shard
must feed identical rows; exclusive-shard processes get disjoint
streams). The grain analog of the reference's per-worker DataLoader
sharding, done for the user.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import numpy as np


class _Windows:
    """Random-access view of a flat token array as non-overlapping
    (seq_len+1)-token windows: window i -> tokens[i*S : i*S + S + 1].
    The +1 overlap gives the shifted-by-one LM targets."""

    def __init__(self, tokens: np.ndarray, seq_len: int):
        if tokens.ndim != 1:
            raise ValueError(f"tokens must be flat, got shape {tokens.shape}")
        self._tokens = tokens
        self._seq = int(seq_len)
        self._n = max((len(tokens) - 1) // self._seq, 0)
        if self._n == 0:
            raise ValueError(
                f"{len(tokens)} tokens can't fill one window of "
                f"{seq_len + 1}")

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> np.ndarray:
        s = int(i) * self._seq
        return np.asarray(self._tokens[s:s + self._seq + 1], np.int32)


def load_tokens(source: Any) -> np.ndarray:
    """Resolve a token source to a flat int32 array.

    Accepts an in-memory array/list, an `.npy` file (memory-mapped so epoch
    shuffles never load the corpus into RAM), a raw `.bin`/`.tokens` file of
    little-endian int32, or a `.txt`/other text file tokenized as UTF-8
    bytes (vocab 256 — the bring-up tokenizer, same trick the serving
    path's `tokenizer="bytes"` mode uses)."""
    if isinstance(source, (list, tuple, np.ndarray)):
        return np.asarray(source, np.int32).reshape(-1)
    path = os.fspath(source)
    if not os.path.exists(path):
        raise FileNotFoundError(f"token source {path!r} does not exist")
    if path.endswith(".npy"):
        arr = np.load(path, mmap_mode="r")
        if arr.dtype == np.int32:
            return arr
        if arr.dtype.kind in "iu":
            # Wrong-width integer export (int64/uint16/...): converting
            # here materializes the corpus in RAM, so check the ids
            # actually fit rather than silently wrapping.
            if arr.size:
                lo, hi = int(arr.min()), int(arr.max())
                info = np.iinfo(np.int32)
                if lo < info.min or hi > info.max:
                    raise ValueError(
                        f"token file {path!r} holds {arr.dtype} ids "
                        f"spanning [{lo}, {hi}], which overflow int32 — "
                        "re-export the corpus as int32")
            return np.ascontiguousarray(arr, dtype=np.int32)
        # A float (or other non-integer) corpus would flow through to an
        # opaque downstream error (embedding take on float indices);
        # fail at load with the actual problem.
        raise ValueError(
            f"token file {path!r} holds dtype {arr.dtype}; token ids "
            "must be integers (re-export the corpus as int32)")
    if path.endswith((".bin", ".tokens")):
        return np.memmap(path, dtype=np.int32, mode="r")
    with open(path, "rb") as fh:
        return np.frombuffer(fh.read(), dtype=np.uint8).astype(np.int32)


def _checked_tokens(source: Any, vocab_size: int | None) -> np.ndarray:
    tokens = load_tokens(source)
    if vocab_size is not None:
        # One O(corpus) scan at startup beats training silently on clamped
        # out-of-vocab ids (embedding take clamps, loss stays finite).
        lo, hi = int(np.min(tokens)), int(np.max(tokens))
        if lo < 0 or hi >= vocab_size:
            raise ValueError(
                f"corpus token ids span [{lo}, {hi}] but the model vocab "
                f"is {vocab_size} — wrong tokenizer for this model?")
    return tokens


def _pipeline_tail(rows, *, what: str, batch_size: int, seed: int,
                   shuffle: bool, num_epochs: int | None,
                   process_index: int | None, process_count: int | None):
    """Shared scaffold: source -> per-process shard -> (shuffle) -> repeat
    -> batch. `iter()` on the result is checkpointable."""
    import grain.python as gp

    if process_index is None or process_count is None:
        import jax

        process_index = jax.process_index()
        process_count = jax.process_count()

    ds = gp.MapDataset.source(rows)
    if process_count > 1:
        ds = ds[process_index::process_count]
    if len(ds) < batch_size:
        raise ValueError(
            f"shard has {len(ds)} {what} < batch_size {batch_size}; "
            f"corpus too small for {process_count} procs")
    if shuffle:
        ds = ds.shuffle(seed=seed)
    ds = ds.repeat(num_epochs)
    return ds.batch(batch_size, drop_remainder=True)


def lm_dataset(
    source: Any,
    *,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
    shuffle: bool = True,
    num_epochs: int | None = None,
    process_index: int | None = None,
    process_count: int | None = None,
    vocab_size: int | None = None,
):
    """Build the grain pipeline: windows -> per-process shard -> (shuffle)
    -> repeat -> batch -> {"inputs", "targets"}.

    Returns a `grain.MapDataset`; `iter()` on it yields a checkpointable
    iterator (get_state/set_state). `batch_size` here is the PER-PROCESS
    batch (the trainer passes its `local_batch_size`)."""
    tokens = _checked_tokens(source, vocab_size)
    ds = _pipeline_tail(
        _Windows(tokens, seq_len), what="windows", batch_size=batch_size,
        seed=seed, shuffle=shuffle, num_epochs=num_epochs,
        process_index=process_index, process_count=process_count)
    return ds.map(lambda b: {"inputs": b[:, :-1], "targets": b[:, 1:]})


class _PackedRows:
    """Random-access packed rows: each row is seq_len+1 tokens of WHOLE
    documents (first-fit in corpus order — a document that does not fit
    the current row's remaining space closes the row with loss-masked
    padding rather than being split mid-document with restarted
    positions). Only documents longer than a whole row are chunked, each
    chunk its own segment. Stored as a CSR span table into the
    (memmapped) corpus — O(docs) memory, not O(corpus). Padding spans are
    (start=-1, len); their tokens are eos, their segment id is -1, and
    their targets are masked."""

    def __init__(self, tokens: np.ndarray, seq_len: int, eos_id: int):
        self._tokens = tokens
        self._seq = int(seq_len)
        self._eos = int(eos_id)
        row_cap = self._seq + 1
        # Document spans (start, length), eos kept as the doc's last token.
        ends = np.flatnonzero(np.asarray(tokens) == eos_id)
        starts = np.concatenate([[0], ends + 1]).astype(np.int64)
        stops = np.concatenate([ends + 1, [len(tokens)]]).astype(np.int64)
        lens = stops - starts
        keep = lens > 0
        starts, lens = starts[keep], lens[keep]
        n = len(starts)
        # First-fit packing driven by searchsorted over the cumulative
        # lengths: one python iteration per ROW (plus one per over-long
        # doc), not per document — startup stays sub-second at tens of
        # millions of docs where the per-doc loop took minutes.
        csum = np.concatenate([[0], np.cumsum(lens)])
        rows: list[list[tuple[int, int]]] = []
        cur: list[tuple[int, int]] = []
        used = 0

        def close_row():
            nonlocal cur, used
            if used and row_cap - used:
                cur.append((-1, row_cap - used))  # pad span
            if used:
                rows.append(cur)
            cur, used = [], 0

        i = 0
        while i < n:
            ln = int(lens[i])
            if ln > row_cap:  # over-long doc: chunk across dedicated rows
                close_row()
                off = 0
                while ln > 0:
                    piece = min(ln, row_cap)
                    cur.append((int(starts[i] + off), piece))
                    used += piece
                    off += piece
                    ln -= piece
                    if used == row_cap:
                        close_row()
                i += 1
                continue
            # Longest run of whole documents fitting the open row: the
            # last j with csum[j] - csum[i] <= remaining budget.
            j = int(np.searchsorted(
                csum, csum[i] + (row_cap - used), side="right")) - 1
            if j <= i:  # next doc alone doesn't fit the remaining space
                close_row()
                continue
            cur.extend(zip(starts[i:j].tolist(), lens[i:j].tolist()))
            used += int(csum[j] - csum[i])
            i = j
            if used == row_cap:
                close_row()
        close_row()
        if not rows:
            raise ValueError(
                f"corpus has no packed row of {row_cap} tokens")
        # CSR span table (still O(docs) memory): __getitem__ assembles a
        # row with precomputed gather indices + numpy fancy-indexing into
        # the (memmapped) corpus instead of a per-span python loop —
        # packed-row assembly must not be a per-step host cost the
        # prefetcher has to hide.
        self._row_ptr = np.concatenate(
            [[0], np.cumsum([len(r) for r in rows])]).astype(np.int64)
        flat = [sp for r in rows for sp in r]
        self._span_start = np.asarray([s for s, _ in flat], np.int64)
        self._span_len = np.asarray([ln for _, ln in flat], np.int64)

    def __len__(self) -> int:
        return len(self._row_ptr) - 1

    def __getitem__(self, i: int) -> dict:
        row_cap = self._seq + 1
        n = len(self._row_ptr) - 1
        i = int(i)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(f"row {i} out of range for {n} packed rows")
        a, b = self._row_ptr[i], self._row_ptr[i + 1]
        starts = self._span_start[a:b]
        lens = self._span_len[a:b]
        # Per-token span id and within-span position, then one gather.
        offs = np.repeat(np.concatenate([[0], np.cumsum(lens[:-1])]), lens)
        sid = np.repeat(np.arange(b - a), lens)
        pos = (np.arange(row_cap, dtype=np.int64) - offs)
        src = starts[sid] + pos
        pad = starts[sid] < 0
        toks = np.where(
            pad, self._eos,
            np.asarray(self._tokens[np.where(pad, 0, src)])).astype(
                np.int32)
        segs = np.where(pad, -1, sid).astype(np.int32)
        pos = pos.astype(np.int32)
        return {
            "inputs": toks[:-1],
            "targets": toks[1:],
            "segment_ids": segs[:-1],
            "positions": pos[:-1],
            # A target in the NEXT document — or inside padding — is not
            # this segment's to predict.
            "mask": ((segs[:-1] == segs[1:]) & (segs[:-1] >= 0)).astype(
                np.float32),
        }


def packed_lm_dataset(
    source: Any,
    *,
    batch_size: int,
    seq_len: int,
    eos_id: int,
    seed: int = 0,
    shuffle: bool = True,
    num_epochs: int | None = None,
    process_index: int | None = None,
    process_count: int | None = None,
    vocab_size: int | None = None,
):
    """Document-packed LM pipeline: eos-delimited documents greedy-packed
    into fixed rows with per-token segment ids, restarting positions, and
    a cross-document loss mask — the batches the packed-attention path
    (models + fused kernels honoring `segment_ids`) trains on. Same
    checkpointable-iterator contract as `lm_dataset`."""
    tokens = _checked_tokens(source, vocab_size)
    return _pipeline_tail(
        _PackedRows(tokens, seq_len, eos_id), what="packed rows",
        batch_size=batch_size, seed=seed, shuffle=shuffle,
        num_epochs=num_epochs, process_index=process_index,
        process_count=process_count)


def iterator_state(it: Any) -> Mapping[str, Any] | None:
    """The iterator's resume state, or None for plain generators."""
    get = getattr(it, "get_state", None)
    return get() if callable(get) else None


def restore_iterator(it: Any, state: Mapping[str, Any] | None) -> bool:
    """Seek a checkpointable iterator to a saved state. Returns True when
    the seek happened (caller then skips replay)."""
    set_state = getattr(it, "set_state", None)
    if state is None or not callable(set_state):
        return False
    set_state(dict(state))
    return True
