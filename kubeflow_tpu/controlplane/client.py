"""Python client for the C++ control plane (cpp/server.cc protocol).

The SDK surface of the rebuild — fills the role of the reference's
kubernetes python client + `TrainingClient` (⟨training-operator: sdk/python —
TrainingClient⟩, SURVEY.md §3.2): newline-delimited JSON over the control
plane's unix socket, with job-level conveniences (submit, wait, logs).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import time
from typing import Any, Iterator

from kubeflow_tpu.utils import faults, obs
from kubeflow_tpu.utils.resilience import (BackoffPolicy, Deadline,
                                           DeadlineExceeded,
                                           metrics as res_metrics,
                                           retry_call)

_FP_REQUEST = faults.register_point(
    "controlplane.request",
    "per transport attempt, before connect/send; ctx: op, attempt")


class ControlPlaneError(RuntimeError):
    pass


class ControlPlaneDisconnected(ControlPlaneError, ConnectionError):
    """The socket died mid-exchange (truncated read / closed connection)
    — the transient, retryable subset of ControlPlaneError."""


class ControlPlaneSendFailed(ControlPlaneDisconnected):
    """The connection died during the SEND phase (e.g. a cached socket
    to a since-killed leader: broken pipe / reset on sendall). For this
    newline-delimited protocol that is provably pre-dispatch: sendall
    only raises when a suffix of the line — which ends with the
    terminating newline — never reached the kernel, and the server
    dispatches complete lines only. Safe to retry for ANY op, unlike a
    recv-phase death (request fully delivered, outcome unknown)."""


class ControlPlaneUnavailable(ControlPlaneError):
    """Typed terminal error: the retry/deadline budget for one call is
    exhausted and the control plane never answered. Callers distinguish
    'the server rejected this' (ControlPlaneError) from 'the server is
    gone' (this) — the same split client-go makes with IsServerTimeout."""


class NotLeader(ControlPlaneError):
    """A replicated follower rejected a mutation (ISSUE 11). Carries the
    follower's `redirect` hint (the leader's socket path, possibly ""
    mid-election). Always safe to retry — the server applied nothing —
    and the Client handles it internally by re-targeting the leader."""

    def __init__(self, message: str, redirect: str = ""):
        super().__init__(message)
        self.redirect = redirect


#: Transient transport errors worth a reconnect+retry: refused / missing
#: socket (server starting or restarting), reset / broken pipe /
#: truncated read (server died mid-exchange). Plain timeouts are NOT
#: retried — the server may be wedged mid-request, and replaying a
#: non-idempotent op against a wedged server is worse than failing.
TRANSIENT_ERRORS = (ConnectionRefusedError, ConnectionResetError,
                    BrokenPipeError, FileNotFoundError,
                    ControlPlaneDisconnected, NotLeader)

#: Errors that can only occur BEFORE the request took effect server-side
#: (connect-time refusals, send-phase deaths — the newline never left
#: the kernel — and a follower's not-leader rejection, which by contract
#: applied nothing): safe to retry for any op. The rest of
#: TRANSIENT_ERRORS can strike after sendall — the server may have
#: already applied the op — so those only replay for read-only verbs.
_PRE_SEND_ERRORS = (ConnectionRefusedError, FileNotFoundError, NotLeader,
                    ControlPlaneSendFailed)

#: Verbs with no server-side effects: replaying them after a mid-exchange
#: disconnect is always safe (client-go's IsServerTimeout/idempotency
#: split for GET-class requests).
_READ_ONLY_OPS = frozenset(
    {"get", "list", "metrics", "slices", "logs", "ping", "stateinfo",
     "events", "trace", "watch.poll"})


def namespace_of(resource: dict) -> str:
    """The one tenancy normalization rule: resources without a namespace
    live in "default" (mirror of NamespaceOf in cpp/jaxjob.cc)."""
    return resource.get("spec", {}).get("namespace") or "default"


class Client:
    """`retry` / `max_attempts` / `deadline_s` govern the transport's
    resilience (utils/resilience.py): transient socket errors reconnect
    and retry under jittered exponential backoff, bounded by BOTH an
    attempt cap and a per-call wall-clock budget (`deadline_s`, default =
    `timeout`). Connect-time errors retry for any op; mid-exchange
    disconnects only replay read-only verbs (a mutating op may already
    have been applied server-side). Exhaustion raises
    `ControlPlaneUnavailable` with the last transport error chained.
    `max_attempts=1` restores the old single-shot behavior.

    `replicas` (ISSUE 11) teaches the client the replica set of a
    replicated control plane: a follower's not-leader rejection
    re-targets the hinted leader and retries (the rejection applied
    nothing, so this is safe for mutations too); a refused/absent
    socket rotates to the next known replica. Both stay inside the
    call's deadline budget — a failover (lease expiry + election)
    resolves mid-call instead of surfacing as a first-refusal error —
    and the attempt cap scales with the replica count so the budget,
    not the cap, bounds a failover wait."""

    def __init__(self, socket_path: str = "/tmp/tpk.sock",
                 timeout: float = 30.0,
                 retry: BackoffPolicy | None = None,
                 max_attempts: int = 5,
                 deadline_s: float | None = None,
                 trace_id: str | None = None,
                 replicas: list[str] | tuple[str, ...] | None = None):
        self.socket_path = socket_path
        self.timeout = timeout
        self.retry = retry or BackoffPolicy(initial_s=0.05, max_s=2.0)
        self.replicas = [socket_path] + [r for r in (replicas or ())
                                         if r != socket_path]
        self.max_attempts = int(max_attempts) * max(len(self.replicas), 1)
        self.deadline_s = timeout if deadline_s is None else deadline_s
        # One trace identity per client (callers can pass the request id
        # they are working under): attached to every RPC, recorded on the
        # client's spans AND in the server's dispatch trace ring — the
        # cross-process link `tpukit trace` surfaces.
        self.trace_id = obs.sanitize_trace_id(trace_id)
        self._sock: socket.socket | None = None
        self._buf = b""

    # -- transport ----------------------------------------------------------

    def _connect(self, deadline: Deadline) -> socket.socket:
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(max(deadline.bound(self.timeout), 0.001))
            s.connect(self.socket_path)
            self._sock = s
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _request_once(self, req: dict, deadline: Deadline,
                      attempt: int = 0) -> dict:
        faults.fire(_FP_REQUEST, op=req.get("op"), attempt=attempt)
        try:
            s = self._connect(deadline)
            s.settimeout(max(deadline.bound(self.timeout), 0.001))
            try:
                s.sendall(json.dumps(req).encode() + b"\n")
            except (BrokenPipeError, ConnectionResetError) as e:
                # Send-phase death (see ControlPlaneSendFailed): the
                # request line never fully reached the kernel, so the
                # server cannot have dispatched it — retryable even for
                # mutations (the failover path: cached socket to a
                # SIGKILLed leader).
                raise ControlPlaneSendFailed(
                    f"connection to {self.socket_path} died during "
                    f"send: {type(e).__name__}: {e}") from e
            while b"\n" not in self._buf:
                chunk = s.recv(65536)
                if not chunk:
                    raise ControlPlaneDisconnected(
                        "connection closed by control plane")
                self._buf += chunk
        except (OSError, ControlPlaneError):
            # A timeout or half-read leaves request/response pairing
            # undefined on this connection — reset it so the next request
            # starts clean instead of reading a stale reply.
            self.close()
            self._buf = b""
            raise
        line, self._buf = self._buf.split(b"\n", 1)
        resp = json.loads(line)
        if not resp.get("ok"):
            if resp.get("notLeader"):
                raise NotLeader(resp.get("error", "not leader"),
                                redirect=resp.get("redirect", ""))
            raise ControlPlaneError(resp.get("error", "unknown error"))
        return resp

    def _retarget(self, path: str) -> None:
        """Point the transport at another replica (closing the current
        connection so the next attempt connects fresh)."""
        if path == self.socket_path:
            return
        self.close()
        self._buf = b""
        self.socket_path = path
        if path not in self.replicas:
            self.replicas.append(path)

    def _rotate_target(self) -> None:
        """Current replica is unreachable: try the next one in the set
        (no-op for a single-target client — the old behavior exactly)."""
        if len(self.replicas) <= 1:
            return
        i = (self.replicas.index(self.socket_path) + 1
             if self.socket_path in self.replicas else 0)
        self._retarget(self.replicas[i % len(self.replicas)])

    def request(self, **req: Any) -> dict:
        deadline = Deadline(self.deadline_s)
        attempts = [0]
        op = str(req.get("op", ""))
        req.setdefault("trace", self.trace_id)
        t0 = time.perf_counter()

        def once():
            attempt = attempts[0]
            attempts[0] += 1
            try:
                return self._request_once(req, deadline, attempt)
            except NotLeader as e:
                # A follower refused a mutation (nothing applied): chase
                # the redirect when it names the leader, otherwise rotate
                # — mid-election the hint is empty and SOME replica will
                # know the winner within a lease. retry_call then replays
                # under the same deadline budget.
                if e.redirect:
                    self._retarget(e.redirect)
                else:
                    self._rotate_target()
                raise
            except TRANSIENT_ERRORS as e:
                if isinstance(e, (ConnectionRefusedError,
                                  FileNotFoundError,
                                  ControlPlaneSendFailed)):
                    # Dead/absent socket — e.g. a SIGKILLed leader during
                    # failover. Another replica may be (or know) the new
                    # leader; rotating keeps the retries useful instead
                    # of hammering a corpse until the budget dies.
                    self._rotate_target()
                if (not isinstance(e, _PRE_SEND_ERRORS)
                        and req.get("op") not in _READ_ONLY_OPS):
                    # Mid-exchange death on a mutating op: the server may
                    # have applied it before dying — replaying could
                    # double-apply (create -> already-exists, delete ->
                    # not-found). Surface the ambiguity instead (not a
                    # TRANSIENT_ERROR, so retry_call propagates it).
                    raise ControlPlaneUnavailable(
                        f"connection lost mid-exchange during "
                        f"non-idempotent op {req.get('op')!r} (outcome "
                        f"unknown, not retried): "
                        f"{type(e).__name__}: {e}") from e
                raise

        try:
            return retry_call(once, retry_on=TRANSIENT_ERRORS,
                              policy=self.retry,
                              max_attempts=self.max_attempts,
                              deadline=deadline,
                              component="controlplane")
        except TRANSIENT_ERRORS + (DeadlineExceeded, TimeoutError) as e:
            # DeadlineExceeded: the budget expired before an attempt
            # could even start (retry_call's pre-attempt check).
            # TimeoutError/socket.timeout: the budget (or flat timeout)
            # expired MID-attempt on a slow-but-alive server — not
            # retried (it may be wedged mid-request), but still "the
            # control plane never answered", so both wear the typed
            # error the docstring promises.
            raise ControlPlaneUnavailable(
                f"control plane at {self.socket_path} unavailable "
                f"after {attempts[0]} attempt(s) over "
                f"{self.deadline_s:.1f}s budget: "
                f"{type(e).__name__}: {e}") from e
        finally:
            # Per-verb RPC latency distribution + a client-side span,
            # every outcome, retries/backoff included — this is the
            # latency the CALLER experienced, the SRE-relevant number.
            t1 = time.perf_counter()
            res_metrics.observe("tpk_controlplane_rpc_latency_seconds",
                                t1 - t0, verb=op)
            obs.record("controlplane.rpc", t0, t1, self.trace_id,
                       op=op, attempts=max(attempts[0], 1))

    # -- resource verbs -------------------------------------------------------

    def create(self, kind: str, name: str, spec: dict) -> dict:
        return self.request(op="create", kind=kind, name=name,
                            spec=spec)["resource"]

    def get(self, kind: str, name: str) -> dict:
        return self.request(op="get", kind=kind, name=name)["resource"]

    def list(self, kind: str, namespace: str | None = None) -> list[dict]:
        """List resources, optionally filtered to one namespace."""
        items = self.request(op="list", kind=kind)["items"]
        if namespace is None:
            return items
        return [r for r in items if namespace_of(r) == namespace]

    def update_spec(self, kind: str, name: str, spec: dict,
                    expected_version: int | None = None) -> dict:
        req: dict[str, Any] = dict(op="update_spec", kind=kind, name=name,
                                   spec=spec)
        if expected_version is not None:
            req["expected_version"] = expected_version
        return self.request(**req)["resource"]

    def delete(self, kind: str, name: str) -> None:
        self.request(op="delete", kind=kind, name=name)

    def metrics(self) -> dict:
        return self.request(op="metrics")["metrics"]

    def slices(self) -> list[dict]:
        return self.request(op="slices")["slices"]

    def stateinfo(self) -> dict:
        """Durability health of the control plane's store: WAL replay
        stats (records applied, snapshot vs tail, truncated bytes, clean
        vs stopped-at-corruption), compaction counters, the fsync
        policy, group-commit health (`groupCommit`: commits, records,
        covering fsyncs, max/mean batch, pending records) and watch
        fan-out counters (`watch`: coalesced/delivered/queued events) —
        the operator's `etcdctl endpoint status` analog. A replicated
        control plane (ISSUE 11) adds `replication{role, term, leader,
        seq, appliedSeq, commitSeq, quorum, followers[{sock, ackedSeq,
        lagRecords, reachable}], lagRecords, quorumCommits,
        quorumFailures, elections, ...}`."""
        return self.request(op="stateinfo")["stateinfo"]

    def watch_poll(self, kind: str = "", since: int = 0) -> dict:
        """Poll-based informer (ISSUE 11): committed, coalesced events
        with resourceVersion > `since`, served by ANY replica — point a
        watcher at a follower and the event stream scales horizontally.
        Returns {"events": [{type, resource}...], "resourceVersion": rv,
        "resync": bool}; resume with since=rv, and on resync=True
        re-`list()` first (the cursor predates the server's ring)."""
        r = self.request(op="watch.poll", kind=kind, since=int(since))
        return {"events": r.get("events", []),
                "resourceVersion": r.get("resourceVersion", 0),
                "resync": bool(r.get("resync"))}

    def events(self, name: str, kind: str = "JAXJob") -> dict:
        """The per-job structured event log + conditions (the rebuild's
        EventRecorder, SURVEY.md §5.5): {"events": [ordered {type,
        reason, message, timestamp, unix, count}], "conditions": [...]}.
        Events live in the resource status, so they ride the WAL and
        survive a control-plane restart."""
        r = self.request(op="events", kind=kind, name=name)
        return {"events": r.get("events", []),
                "conditions": r.get("conditions", [])}

    def post_event(self, name: str, reason: str, message: str = "",
                   type_: str = "Normal", kind: str = "JAXJob") -> None:
        """Append one event to a job's event log (the worker-side path:
        the trainer posts CheckpointSaved and friends through this)."""
        self.request(op="event", kind=kind, name=name, type=type_,
                     reason=reason, message=message)

    def trace(self) -> dict:
        """The control plane's span ring as a Chrome trace-event
        document (load in chrome://tracing / Perfetto): one `ph: "X"`
        event per dispatched request, with the caller's trace id under
        args — `tpukit trace` prints this."""
        return self.request(op="trace")["trace"]

    def logs(self, name: str, replica: int = 0, stderr: bool = False,
             max_bytes: int = 65536) -> str:
        return self.logs_ex(name, replica, stderr, max_bytes)["content"]

    def logs_ex(self, name: str, replica: int = 0, stderr: bool = False,
                max_bytes: int = 65536) -> dict:
        """Returns {content, size, offset}: `size` is the full log length,
        `offset` is where `content` starts (for follow-mode bookkeeping)."""
        return self.request(op="logs", name=name, replica=replica,
                            stderr=stderr, max_bytes=max_bytes)

    def ping(self) -> bool:
        # Single-shot on purpose: ping IS the health probe the startup
        # poll spins on — retry/backoff here would just slow the poll's
        # own loop (the caller is the retry policy).
        try:
            return bool(self._request_once({"op": "ping"},
                                           Deadline(self.timeout))
                        .get("pong"))
        except (OSError, ControlPlaneError):
            return False

    # -- job conveniences (TrainingClient parity) -----------------------------

    def submit_jaxjob(self, name: str, spec: dict) -> dict:
        return self.create("JAXJob", name, spec)

    def wake_service(self, name: str) -> dict:
        """Cold-start a scale-to-zero'd InferenceService: bump spec.wake
        so the controller scales it back up (the control-plane analog of
        Knative's activator receiving the first request — callers then
        wait_for_phase(name, ("Ready",), kind="InferenceService") and
        send the request)."""
        res = self.get("InferenceService", name)
        spec = dict(res.get("spec", {}))
        spec["wake"] = time.time()
        return self.update_spec("InferenceService", name, spec,
                                expected_version=res.get("resourceVersion"))

    def phase(self, name: str, kind: str = "JAXJob") -> str:
        return self.get(kind, name).get("status", {}).get("phase", "")

    def wait_for_phase(self, name: str, phases=("Succeeded", "Failed"),
                       timeout: float = 300.0, poll: float = 0.5,
                       kind: str = "JAXJob") -> str:
        """Blocks until the resource reaches one of `phases` (like
        TrainingClient.wait_for_job_conditions)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            p = self.phase(name, kind)
            if p in phases:
                return p
            time.sleep(poll)
        raise TimeoutError(
            f"{kind} {name} did not reach {phases} in {timeout}s "
            f"(last phase: {self.phase(name, kind)!r})")

    def train(self, name: str, *, model: str, dataset: str = "synthetic_lm",
              model_kwargs: dict | None = None,
              dataset_kwargs: dict | None = None,
              num_workers: int = 1, devices_per_worker: int = 1,
              cpu_devices_per_worker: int = 0,
              steps: int = 100, batch_size: int = 8,
              learning_rate: float = 1e-3, strategy: str = "dp",
              mesh: dict | None = None, num_slices: int = 1,
              checkpoint: dict | None = None,
              lora: dict | None = None,
              restart_policy: str = "OnFailure", backoff_limit: int = 3,
              log_every: int = 10, **runtime_extra) -> dict:
        """High-level fine-tune entry point — `TrainingClient.train()`
        parity (⟨training-operator: sdk/python — train()⟩, SURVEY.md §3.2):
        fabricates the JAXJob from model/dataset names in the runtime
        registry instead of requiring a hand-written spec.

        `lora={"rank": r, "alpha": a, "targets": "attn"|"attn_mlp"}` is
        the reference SDK's LoraConfig: adapters train, the base stays
        frozen (train/lora.py)."""
        runtime = {
            "model": model, "dataset": dataset,
            "strategy": strategy, "steps": steps,
            "batch_size": batch_size, "learning_rate": learning_rate,
            "log_every": log_every,
        }
        if model_kwargs:
            runtime["model_kwargs"] = model_kwargs
        if dataset_kwargs:
            runtime["dataset_kwargs"] = dataset_kwargs
        if mesh:
            runtime["mesh"] = mesh
        if checkpoint:
            runtime["checkpoint"] = checkpoint
        if lora:
            runtime["lora"] = lora
        runtime.update(runtime_extra)
        spec = {
            "replicas": num_workers,
            "devices_per_proc": devices_per_worker,
            "restart_policy": restart_policy,
            "backoff_limit": backoff_limit,
            "runtime": runtime,
        }
        if num_slices > 1:
            spec["num_slices"] = num_slices
        if cpu_devices_per_worker:
            spec["cpu_devices_per_proc"] = cpu_devices_per_worker
        return self.create("JAXJob", name, spec)

    def stream_metrics(self, name: str, replica: int = 0) -> Iterator[dict]:
        """Parses the worker's JSONL metric lines from its log."""
        for line in self.logs(name, replica, max_bytes=1 << 20).splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "step" in rec:
                yield rec


def find_binary() -> str:
    """Locates tpk-controlplane: $TPK_CONTROLPLANE_BIN, then the build tree."""
    env = os.environ.get("TPK_CONTROLPLANE_BIN")
    if env and os.path.exists(env):
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for rel in ("build/tpk-controlplane", "cpp/build/tpk-controlplane"):
        cand = os.path.join(here, rel)
        if os.path.exists(cand):
            return cand
    raise FileNotFoundError(
        "tpk-controlplane binary not found; build with "
        "`cmake -S cpp -B build && cmake --build build` or set "
        "TPK_CONTROLPLANE_BIN")


def start_controlplane(socket_path: str, workdir: str,
                       slices: str = "local=8", wal: str | None = None,
                       python: str | None = None,
                       wait_s: float = 10.0,
                       extra_args: list[str] | None = None
                       ) -> subprocess.Popen:
    """Starts the control-plane binary and waits for its socket.
    `extra_args` passes durability knobs straight through
    (`--fsync`, `--fsync-interval`, `--compact`)."""
    import sys

    cmd = [find_binary(), "--socket", socket_path, "--workdir", workdir,
           "--slices", slices, "--python", python or sys.executable]
    if wal:
        cmd += ["--wal", wal]
    if extra_args:
        cmd += list(extra_args)
    proc = subprocess.Popen(cmd)
    client = Client(socket_path)
    deadline = time.time() + wait_s
    while time.time() < deadline:
        if proc.poll() is not None:
            raise ControlPlaneError(
                f"control plane exited rc={proc.returncode}")
        try:
            if client.ping():
                client.close()
                return proc
        except (FileNotFoundError, ConnectionRefusedError, OSError):
            pass
        time.sleep(0.1)
    proc.terminate()
    raise TimeoutError(f"control plane socket {socket_path} never came up")


class ClusterHandle:
    """One control plane on a private socket/workdir/WAL that a harness
    can start, SIGKILL, and restart against the same on-disk state — the
    shared lifecycle of the kill-9 crash tests
    (tests/test_crash_recovery.py) and the ctrlbench harness
    (kubeflow_tpu/controlplane/bench.py); one copy so a startup/teardown
    semantics change can't silently leave one of them exercising a
    different lifecycle."""

    def __init__(self, base: str, label: str,
                 extra_args: list[str] | None = None,
                 client_timeout: float = 15.0):
        base = str(base)  # accepts pathlib tmp_path too
        self.sock = os.path.join(base, f"{label}.sock")
        self.work = os.path.join(base, f"{label}-work")
        self.wal = os.path.join(base, f"{label}-wal.jsonl")
        self.extra_args = list(extra_args or [])
        self.client_timeout = client_timeout
        self.proc: subprocess.Popen | None = None

    def start(self) -> Client:
        self.proc = start_controlplane(self.sock, self.work, wal=self.wal,
                                       extra_args=self.extra_args)
        return Client(self.sock, timeout=self.client_timeout)

    def kill9(self) -> None:
        import signal

        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)

    def stop(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            self.proc.wait(timeout=10)
