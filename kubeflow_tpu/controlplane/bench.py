"""Control-plane benchmark harness (ISSUE 8) → CTRLBENCH.json.

Measures the group-commit tentpole against the REAL `tpk-controlplane`
binary (the kill-9 harness's subprocess pattern — no mocks), per
PROFILE.md §1 hygiene: every arm is CLOSED-LOOP, the clock closes only
on acknowledged replies, and paired arms differ by exactly one knob.

Sections (each pinned by tests/test_ctrlbench.py):

  * group_commit — submit (create, durable mutation) and status (get,
    read-only) rps with K concurrent clients under
    `--fsync never|interval|always`, `--group-commit 64` vs `0`. The
    "always" pair is the headline: per-record mode pays one fsync per
    mutation on the event loop; group mode amortizes one covering fsync
    over every mutation of a poll pass, acks released only after it.
  * watch_fanout — ≥1000 queued (unschedulable) JAXJobs: burst-submit
    wall, then hot-spot status churn with a concurrent reader; watch
    coalescing observed via stateinfo deltas, read latency via BOTH
    direct timing and the section delta of the client's
    tpk_controlplane_rpc_latency_seconds histogram.
  * accept_ramp — K clients connect at once; the drained accept loop
    must serve the whole burst without per-connection poll-cycle
    penalties (ISSUE 8 satellite regression row).
  * replicated (ISSUE 11) — 1 leader + 2 followers on localhost vs a
    single node, both at fsync=always with group commit, measurement
    slices ALTERNATING between the arms (PROFILE.md §10: the 9p fsync
    regime drifts minute-to-minute, and the replicated arm pays 3x the
    fsyncs). Records quorum-acked submit rps (the cost of
    ack-after-quorum), follower-served get and watch.poll throughput
    (the horizontal read/watch win), and the replication mechanism
    counters (quorum commits, follower lag) that the shape test pins.

Run `python bench.py --ctrlbench` from the repo root. If the binary is
not built, the result is one skipped-with-reason record (the
SERVEBENCH chip-row convention).
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import statistics
import tempfile
import threading
import time

from kubeflow_tpu.controlplane.client import (Client, ClusterHandle,
                                              find_binary)
from kubeflow_tpu.utils.resilience import metrics as res_metrics

#: One inert JAXJob spec: devices_per_proc far above any slice capacity
#: keeps it queued Unschedulable forever — real store/watch/reconcile
#: load with zero worker processes.
_UNSCHEDULABLE = {"replicas": 1, "devices_per_proc": 4096,
                  "restart_policy": "Never",
                  "command": ["/bin/sh", "-c", "true"]}


def _cluster(base: str, label: str, extra_args: list[str]) -> ClusterHandle:
    """The shared kill-9-harness lifecycle wrapper, with a bench-length
    client timeout (ops can stall ~100ms+ behind a 9p fsync burst)."""
    return ClusterHandle(base, label, extra_args, client_timeout=60)


def _run_threads(n: int, fn) -> list:
    """Run fn(i) on n threads; re-raise the first worker exception (a
    silently-dead worker would fabricate a low rps — the r4 batcher-tail
    lesson)."""
    errors: list[BaseException] = []
    results = [None] * n

    def wrap(i):
        try:
            results[i] = fn(i)
        except BaseException as e:  # noqa: BLE001 — reported below
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


def _closed_loop(sock: str, clients: int, seconds: float, op,
                 warmup_s: float = 0.0) -> dict:
    """Closed-loop rps: `clients` threads each run op(client, i, n)
    continuously; only acks completing inside the [warmup_s, warmup_s +
    seconds) window count, and the wall is that window — no unacked
    pipeline can flatter the number, and the cold-start transient (this
    host's 9p fsync takes ~100 ms on a fresh file and warms to ~2 ms —
    see PROFILE.md §10) stays out of the measurement."""
    t0 = time.perf_counter()
    t_start = t0 + warmup_s
    t_end = t_start + seconds

    def worker(i):
        c = Client(sock, timeout=60)
        try:
            n_total = 0
            counted = 0
            while True:
                now = time.perf_counter()
                if now >= t_end:
                    break
                op(c, i, n_total)
                n_total += 1
                done = time.perf_counter()
                # Only acks COMPLETING inside the window count — an op
                # that straddles t_end (e.g. stalls on an fsync burst)
                # must not credit the window it missed, or the slowest
                # arm gets flattered by up to one op per client.
                if t_start <= done < t_end:
                    counted += 1
            return counted
        finally:
            c.close()

    counts = _run_threads(clients, worker)
    total = sum(counts)
    return {"acked": total, "wall_s": round(seconds, 3),
            "rps": round(total / seconds, 1)}


def _raw_submit_loop(sock_path: str, clients: int, seconds: float,
                     tag, warmup_s: float = 0.0) -> dict:
    """Closed-loop submit rps with a MINIMAL per-op client: raw unix
    socket, hand-built request bytes, one json.loads per reply line.
    The full Client (retry/deadline/histogram/trace plumbing) costs
    enough Python per op that 16 GIL-sharing threads cap near ~1k rps
    aggregate — the measurement client saturates before the group-commit
    server does (whose dispatch is ~60 µs/req) and the on/off ratio
    flattens toward 1 (§1 again: the harness must never be the
    bottleneck). Same window discipline as _closed_loop: only acks
    COMPLETING inside [t_start, t_end) count."""
    t0 = time.perf_counter()
    t_start = t0 + warmup_s
    t_end = t_start + seconds

    def worker(i):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(sock_path)
        buf = b""
        prefix = (f'{{"op": "create", "kind": "Widget", '
                  f'"name": "w-{tag}-{i}-').encode()
        try:
            n = 0
            counted = 0
            while True:
                if time.perf_counter() >= t_end:
                    break
                s.sendall(prefix + str(n).encode()
                          + b'", "spec": {"x": 1}}\n')
                while b"\n" not in buf:
                    chunk = s.recv(65536)
                    if not chunk:
                        raise RuntimeError("control plane disconnected")
                    buf += chunk
                line, buf = buf.split(b"\n", 1)
                n += 1
                done = time.perf_counter()
                if json.loads(line).get("ok") and t_start <= done < t_end:
                    counted += 1
            return counted
        finally:
            s.close()

    counts = _run_threads(clients, worker)
    total = sum(counts)
    return {"acked": total, "wall_s": round(seconds, 3),
            "rps": round(total / seconds, 1)}


def _bench_group_commit_pair(base: str, fsync: str, clients: int,
                             seconds: float, warmup_s: float,
                             slices: int = 4) -> dict:
    """One fsync mode, BOTH arms live at once, submit measurement
    alternating between them in short slices. Sequential arms are not
    comparable on this host: the 9p fsync cost oscillates between ~2 ms
    and ~150 ms regimes on second-to-minute scales (PROFILE.md §10), so
    two windows minutes apart can sample different regimes and the
    on/off ratio becomes noise in either direction — the true ratio is
    large in BOTH regimes (the ON arm amortizes the per-pass fsync over
    every client). Alternating slices bound the regime drift between
    the arms to one slice."""
    clusters: dict = {}
    admins: dict = {}
    arms: dict = {}
    try:
        for key, group in (("on", 64), ("off", 0)):
            clusters[key] = _cluster(base, f"{fsync}-{key}", [
                "--fsync", fsync, "--group-commit", str(group),
                "--compact", "0"])
            admins[key] = clusters[key].start()
            admins[key].create("Widget", "probe", {"x": 0})  # get target
        slice_s = max(seconds / slices, 0.25)
        acked = {"on": 0, "off": 0}
        for s in range(slices):
            for key in ("on", "off"):
                r = _raw_submit_loop(clusters[key].sock, clients, slice_s,
                                     tag=s,
                                     warmup_s=warmup_s if s == 0 else 0.0)
                acked[key] += r["acked"]
        wall = slices * slice_s
        for key, group in (("on", 64), ("off", 0)):
            status = _closed_loop(
                clusters[key].sock, clients, max(seconds / 3, 0.5),
                lambda c, i, n: c.get("Widget", "probe"))
            info = admins[key].stateinfo()
            arms[key] = {
                "fsync": fsync, "group_commit": group,
                "submit_rps": round(acked[key] / wall, 1),
                "submit_acked": acked[key],
                "submit_wall_s": round(wall, 3),
                "status_rps": status["rps"],
                "stateinfo_group": info["groupCommit"],
            }
    finally:
        for a in admins.values():
            a.close()
        for cl in clusters.values():
            cl.stop()
    return {
        "on": arms["on"], "off": arms["off"],
        "speedup_submit": round(arms["on"]["submit_rps"]
                                / max(arms["off"]["submit_rps"], 1e-9), 2),
    }


def _hist_delta(h0: dict, h1: dict) -> dict:
    """h1 - h0 per cumulative bucket: the section-scoped view of one
    series from the process-global registry (get_histogram is cumulative
    over the whole bench process)."""
    return {"buckets": {le: h1["buckets"].get(le, 0)
                        - h0["buckets"].get(le, 0)
                        for le in h1["buckets"]},
            "sum": h1["sum"] - h0["sum"],
            "count": h1["count"] - h0["count"]}


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _bench_watch_fanout(base: str, jobs: int, clients: int,
                        churn_rounds: int) -> dict:
    cluster = _cluster(base, "watch", [
        "--fsync", "always", "--group-commit", "64", "--compact", "0"])
    admin = cluster.start()
    try:
        # Burst-submit `jobs` unschedulable JAXJobs from `clients`
        # parallel submitters — they queue forever, so the store carries
        # a standing backlog for everything below.
        per = (jobs + clients - 1) // clients

        def submit(i):
            c = Client(cluster.sock, timeout=120)
            try:
                for n in range(per):
                    if i * per + n >= jobs:
                        break
                    c.submit_jaxjob(f"q-{i}-{n}", dict(_UNSCHEDULABLE))
            finally:
                c.close()

        t0 = time.perf_counter()
        _run_threads(clients, submit)
        submit_wall = time.perf_counter() - t0
        info0 = admin.stateinfo()

        # Hot-spot status churn: every client hammers ONE job's status
        # (the heartbeat-pileup shape) while a reader times `get` against
        # the full backlog — the reconcile/watch latency a fleet consumer
        # actually sees.
        get_times: list[float] = []
        stop = threading.Event()
        reader_errors: list[BaseException] = []

        def reader():
            c = Client(cluster.sock, timeout=60)
            try:
                while not stop.is_set():
                    t = time.perf_counter()
                    c.get("JAXJob", "q-0-0")
                    get_times.append(time.perf_counter() - t)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                # Same discipline as _run_threads: a silently-dead reader
                # would fabricate a truncated (or empty) latency row.
                reader_errors.append(e)
            finally:
                c.close()

        # Snapshot the get-latency histogram BEFORE the reader starts:
        # the registry is process-global and cumulative, so without a
        # section delta the group-commit arms' thousands of status gets
        # (run earlier, against tiny unloaded stores) would dominate the
        # row that claims to show read latency at `jobs` queued JAXJobs.
        hist0 = res_metrics.get_histogram(
            "tpk_controlplane_rpc_latency_seconds", verb="get")
        rt = threading.Thread(target=reader, daemon=True)
        rt.start()

        def churner(i):
            c = Client(cluster.sock, timeout=60)
            try:
                for n in range(churn_rounds):
                    c.request(op="update_status", kind="JAXJob",
                              name="q-0-0", status={"phase": "Pending",
                                                    "beat": i * 10000 + n})
            finally:
                c.close()

        t1 = time.perf_counter()
        _run_threads(clients, churner)
        churn_wall = time.perf_counter() - t1
        stop.set()
        # Join must outlast the reader's own 60s client timeout: a get
        # stalled behind a 9p fsync burst keeps the thread alive past a
        # shorter join, and then get_times.sort() below would race its
        # append (and a late reader exception would be dropped unseen).
        rt.join(timeout=90)
        if rt.is_alive():
            raise RuntimeError(
                "watch-fanout reader still running after 90s join — "
                "latency row would be read while being written")
        if reader_errors:
            raise reader_errors[0]

        info1 = admin.stateinfo()
        get_times.sort()
        hist = _hist_delta(hist0, res_metrics.get_histogram(
            "tpk_controlplane_rpc_latency_seconds", verb="get"))
        return {
            "jobs": jobs,
            "submit_wall_s": round(submit_wall, 3),
            "submit_rps": round(jobs / submit_wall, 1),
            "churn_updates": clients * churn_rounds,
            "churn_wall_s": round(churn_wall, 3),
            "churn_rps": round(clients * churn_rounds / churn_wall, 1),
            # The fan-out bound: how many intermediate writes the
            # coalescer absorbed before delivery (stateinfo deltas).
            "coalesced_events": (info1["watch"]["coalescedEvents"]
                                 - info0["watch"]["coalescedEvents"]),
            "delivered_events": (info1["watch"]["deliveredEvents"]
                                 - info0["watch"]["deliveredEvents"]),
            "get_p50_ms": round(_percentile(get_times, 0.50) * 1e3, 2),
            "get_p99_ms": round(_percentile(get_times, 0.99) * 1e3, 2),
            "get_samples": len(get_times),
            "rpc_latency_histogram_get": hist,
            "stateinfo_group": info1["groupCommit"],
        }
    finally:
        admin.close()
        cluster.stop()


def _bench_replicated(base: str, clients: int, seconds: float,
                      warmup_s: float, slices: int = 4) -> dict:
    """Single-node vs 3-replica set, both live, alternating submit
    slices; then follower-served read/watch throughput on the live
    replica set."""
    from kubeflow_tpu.controlplane.replication import ReplicaSet

    single = _cluster(base, "repl-single", [
        "--fsync", "always", "--group-commit", "64", "--compact", "0"])
    rset = ReplicaSet(os.path.join(base, "rset"), n=3, lease_ms=1500,
                      fsync="always", client_timeout=60,
                      extra_args=["--compact", "0"])
    os.makedirs(os.path.join(base, "rset"), exist_ok=True)
    single_admin = None
    try:
        single_admin = single.start()
        rset.start()
        lead = rset.wait_leader(timeout=30)
        leader_sock = rset.socks[lead]
        follower = next(i for i in range(3) if i != lead)
        info0 = rset.stateinfo(lead)["replication"]

        slice_s = max(seconds / slices, 0.25)
        acked = {"single": 0, "replicated": 0}
        for s in range(slices):
            for key, sock in (("single", single.sock),
                              ("replicated", leader_sock)):
                r = _raw_submit_loop(sock, clients, slice_s, tag=f"r{s}",
                                     warmup_s=warmup_s if s == 0 else 0.0)
                acked[key] += r["acked"]
        wall = slices * slice_s

        # Follower-served reads: the horizontal scaling surface —
        # closed-loop gets against a FOLLOWER while the leader idles.
        fol_client_sock = rset.socks[follower]
        probe = Client(leader_sock, timeout=60)
        probe.create("Widget", "probe", {"x": 0})
        probe.close()
        time.sleep(1.0)  # one heartbeat: follower applies the probe
        follower_get = _closed_loop(
            fol_client_sock, clients, max(seconds / 3, 0.5),
            lambda c, i, n: c.get("Widget", "probe"))
        # Follower-served watch: take a cursor on the FOLLOWER (since=0
        # would resync — the submit storm evicted the ring's head), make
        # fresh leader writes, and count them arriving in the follower's
        # coalesced stream after the commit heartbeat.
        fc = Client(fol_client_sock, timeout=60)
        cursor = fc.watch_poll()["resourceVersion"]
        wprobe = Client(leader_sock, timeout=60)
        for i in range(8):
            wprobe.create("Widget", f"watchprobe-{i}", {"i": i})
        wprobe.close()
        time.sleep(1.0)
        w1 = fc.watch_poll(since=cursor)
        watch_events = len(w1["events"])
        fol_info = fc.stateinfo()["replication"]
        fc.close()

        lead_admin = Client(leader_sock, timeout=60)
        info1 = lead_admin.stateinfo()["replication"]
        lead_admin.close()
        single_rps = round(acked["single"] / wall, 1)
        repl_rps = round(acked["replicated"] / wall, 1)
        return {
            "replicas": 3,
            "quorum": info1["quorum"],
            "single": {"submit_rps": single_rps,
                       "submit_acked": acked["single"]},
            "replicated": {"submit_rps": repl_rps,
                           "submit_acked": acked["replicated"]},
            "submit_wall_s": round(wall, 3),
            "rps_ratio_replicated_vs_single": round(
                repl_rps / max(single_rps, 1e-9), 3),
            "quorum_commits": (info1["quorumCommits"]
                               - info0["quorumCommits"]),
            "quorum_failures": (info1["quorumFailures"]
                                - info0["quorumFailures"]),
            "snapshots_shipped": info1["snapshotsShipped"],
            "follower_lag_records": max(
                f["lagRecords"] for f in info1["followers"]),
            "follower_acked_seq": [f["ackedSeq"]
                                   for f in info1["followers"]],
            "leader_seq": info1["seq"],
            "follower_get_rps": follower_get["rps"],
            "follower_watch_events": watch_events,
            "follower_applied_seq": fol_info["appliedSeq"],
        }
    finally:
        if single_admin is not None:
            single_admin.close()
        single.stop()
        rset.stop()


def _bench_accept_ramp(base: str, clients: int) -> dict:
    cluster = _cluster(base, "ramp", [
        "--fsync", "always", "--group-commit", "64"])
    admin = cluster.start()
    try:
        barrier = threading.Barrier(clients)

        def connect(i):
            barrier.wait()  # all clients hit accept in one burst
            t0 = time.perf_counter()
            c = Client(cluster.sock, timeout=60)
            try:
                if not c.ping():
                    raise RuntimeError("ping failed during accept ramp")
                return time.perf_counter() - t0
            finally:
                c.close()

        lats = _run_threads(clients, connect)
        return {
            "clients": clients,
            "served": len(lats),
            "first_reply_max_ms": round(max(lats) * 1e3, 2),
            "first_reply_mean_ms": round(statistics.mean(lats) * 1e3, 2),
        }
    finally:
        admin.close()
        cluster.stop()


def run_ctrlbench(quick: bool = False, clients: int = 8) -> dict:
    """The full harness. `quick` shrinks durations/counts for the shape
    test while keeping every section and field."""
    try:
        find_binary()
    except FileNotFoundError as e:
        return {"metric": "ctrlbench", "skipped": "binary_not_built",
                "detail": str(e)}

    seconds = 1.0 if quick else 3.0
    warmup_s = 0.5 if quick else 1.5
    jobs = 150 if quick else 1200
    churn_rounds = 25 if quick else 120
    ramp_clients = 12 if quick else 32
    if not quick:
        clients = max(clients, 16)

    base = tempfile.mkdtemp(prefix="ctrlb-")
    result: dict = {
        "metric": "ctrlbench",
        "quick": quick,
        "clients": clients,
        "measure_s": seconds,
        "warmup_s": warmup_s,
        "method": ("closed-loop against the real tpk-controlplane binary "
                   "over its unix socket; rps counts acknowledged replies "
                   "completing inside the post-warmup window only (per "
                   "PROFILE.md §1/§10 — this host's 9p fsync costs "
                   "~100 ms cold and ~2 ms warm, so cold-start must not "
                   "be charged to either arm); group-commit arms differ "
                   "by the --group-commit flag alone, run as two LIVE "
                   "servers with measurement slices alternating between "
                   "them so both sample the same host fsync regime, and "
                   "submits use a minimal raw-socket client so the "
                   "harness saturates long after the server; compaction "
                   "disabled to keep arms uniform"),
        "group_commit": {},
    }
    try:
        for fsync in ("never", "interval", "always"):
            result["group_commit"][fsync] = _bench_group_commit_pair(
                base, fsync, clients, seconds, warmup_s)
        result["watch_fanout"] = _bench_watch_fanout(base, jobs, clients,
                                                     churn_rounds)
        result["accept_ramp"] = _bench_accept_ramp(base, ramp_clients)
        result["replicated"] = _bench_replicated(base, clients, seconds,
                                                 warmup_s)
    finally:
        # Each arm leaves a cluster workdir + a WAL holding thousands of
        # framed records; repeated runs must not accumulate dead state.
        shutil.rmtree(base, ignore_errors=True)
    return result
