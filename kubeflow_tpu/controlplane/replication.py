"""Python-side replication harness for the replicated control plane
(ISSUE 11).

Two tools, both speaking the C++ server's wire protocol:

  * `FollowerSim` — a scriptable in-process follower: a unix-socket
    server that answers `repl.append` / `repl.vote` / `repl.snapshot`
    exactly as a real follower would (frame + CRC + seq verification,
    so shipped-batch byte parity is checked on every append), with the
    **`controlplane.replicate`** fault point (utils/faults.py) fired on
    every arriving batch. Tests arm FailN/FailProb/Latency against it to
    exercise quorum-degraded mode — one follower down must still ack,
    a lost quorum must stall the leader and surface as
    `ControlPlaneUnavailable` at the caller's deadline — without real
    process kills.
  * `ReplicaSet` — N real `tpk-controlplane` binaries wired into one
    replica set (the kill-9 failover harness's and ctrlbench's shared
    lifecycle): per-replica sockets/workdirs/WALs under one base dir,
    `--peers` cross-wired, followers started with `--replica-of` the
    first replica, leader discovery by polling `stateinfo.replication`.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import zlib

from kubeflow_tpu.controlplane.client import Client, ClusterHandle
from kubeflow_tpu.utils import faults

_FP_REPLICATE = faults.register_point(
    "controlplane.replicate",
    "per shipped batch arriving at a (simulated) follower, before it "
    "acks; ctx: op, prev_seq, records — FailN refuses the ack, Latency "
    "delays it past the leader's ship timeout")


def _tip_crc(data: bytes) -> int:
    """CRC (from the frame header) of the LAST record in `data` — the
    log-tip identity the leader's prevCrc consistency check compares."""
    lines = [ln for ln in data.split(b"\n") if ln]
    if not lines:
        return 0
    head, _, rest = lines[-1][3:].partition(b" ")
    crc_hex = rest.split(b" ", 1)[0]
    return int(crc_hex, 16)


def parse_frames(data: bytes | str) -> list[tuple[int, dict]]:
    """Split framed WAL bytes (`v1 <seq> <crc32hex> <json>\\n`) into
    (seq, record) pairs, verifying each CRC — raises ValueError on any
    mismatch. The Python mirror of cpp/store.cc's ParseFrame, used to
    assert shipped-batch byte parity from the harness side."""
    if isinstance(data, str):
        data = data.encode()
    out: list[tuple[int, dict]] = []
    for line in data.split(b"\n"):
        if not line:
            continue
        if not line.startswith(b"v1 "):
            raise ValueError(f"unframed record: {line[:40]!r}")
        head, _, payload = line[3:].partition(b" ")
        crc_hex, _, payload = payload.partition(b" ")
        if zlib.crc32(payload) & 0xFFFFFFFF != int(crc_hex, 16):
            raise ValueError(f"crc mismatch at seq {int(head)}")
        out.append((int(head), json.loads(payload)))
    return out


class FollowerSim:
    """A fake follower replica: accepts the leader's replication verbs
    on a real unix socket and acknowledges durably-shaped (in-memory)
    appends. `grant_votes=False` makes it a non-voting bystander.

    State exposed for assertions: `log` (the exact shipped bytes,
    concatenated), `records` ((seq, record) pairs), `seq`,
    `applied_seq`, `term`, `counts` ({appends, heartbeats, acks, nacks,
    votes, snapshots})."""

    def __init__(self, sock_path: str, grant_votes: bool = True):
        self.sock_path = sock_path
        self.grant_votes = grant_votes
        self.log = b""
        self.records: list[tuple[int, dict]] = []
        self.seq = 0
        self.tip_crc = 0  # crc of the record at seq (the divergence check)
        self.applied_seq = 0
        self.term = 0
        self.snapshot: bytes = b""
        self.counts = {"appends": 0, "heartbeats": 0, "acks": 0,
                       "nacks": 0, "votes": 0, "snapshots": 0}
        self._lock = threading.Lock()
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FollowerSim":
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.sock_path)
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"tpk-followersim-{self.sock_path}")
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.close()
        for t in self._threads:
            t.join(timeout=5)
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)

    def __enter__(self) -> "FollowerSim":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        buf = b""
        with conn:
            while not self._stop.is_set():
                try:
                    chunk = conn.recv(1 << 20)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line:
                        continue
                    try:
                        resp = self.handle(json.loads(line))
                    except Exception as e:  # noqa: BLE001 — wire boundary
                        resp = {"ok": False, "error": str(e)}
                    try:
                        conn.sendall(json.dumps(resp).encode() + b"\n")
                    except OSError:
                        return

    # -- protocol ----------------------------------------------------------

    def handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "repl.append":
            return self._handle_append(req)
        if op == "repl.vote":
            with self._lock:
                self.counts["votes"] += 1
                granted = self.grant_votes
                if granted:
                    self.term = max(self.term, int(req.get("term", 0)))
                return {"ok": True, "granted": granted, "term": self.term}
        if op == "repl.snapshot":
            return self._handle_snapshot(req)
        if op == "ping":
            return {"ok": True, "pong": True}
        return {"ok": False, "error": f"followersim: unknown op {op!r}"}

    def _handle_append(self, req: dict) -> dict:
        data = req.get("data", "").encode()
        prev_seq = int(req.get("prevSeq", 0))
        with self._lock:
            t = int(req.get("term", 0))
            if t < self.term:
                self.counts["nacks"] += 1
                return {"ok": False, "staleTerm": True, "term": self.term}
            self.term = t
            prev_crc = int(req.get("prevCrc", 0))
            diverged = (prev_seq != self.seq
                        or (prev_seq > 0 and prev_crc != self.tip_crc))
            if not data:
                self.counts["heartbeats"] += 1
                if diverged:
                    return {"ok": False, "needSnapshot": True,
                            "seq": self.seq, "term": self.term}
                self.applied_seq = min(int(req.get("commitSeq", 0)),
                                       self.seq)
                return {"ok": True, "seq": self.seq, "term": self.term}
            self.counts["appends"] += 1
        # Fault point OUTSIDE the lock (a Latency policy must be able to
        # stall several concurrent appends, not serialize them). `sock`
        # lets a test target ONE sim of a set (match={"sock": ...}).
        faults.fire(_FP_REPLICATE, op="append", prev_seq=prev_seq,
                    records=data.count(b"\n"), sock=self.sock_path)
        with self._lock:
            if (prev_seq != self.seq
                    or (prev_seq > 0
                        and int(req.get("prevCrc", 0)) != self.tip_crc)):
                self.counts["nacks"] += 1
                return {"ok": False, "needSnapshot": True,
                        "seq": self.seq, "term": self.term}
            try:
                parsed = parse_frames(data)
            except ValueError as e:
                self.counts["nacks"] += 1
                return {"ok": False, "error": str(e), "term": self.term}
            expect = self.seq
            for seq, _ in parsed:
                expect += 1
                if seq != expect:
                    self.counts["nacks"] += 1
                    return {"ok": False,
                            "error": f"seq gap: {seq} != {expect}",
                            "term": self.term}
            self.log += data
            self.records.extend(parsed)
            self.seq = expect
            self.tip_crc = _tip_crc(data) or self.tip_crc
            self.applied_seq = min(int(req.get("commitSeq", 0)), self.seq)
            self.counts["acks"] += 1
            return {"ok": True, "seq": self.seq, "term": self.term}

    def _handle_snapshot(self, req: dict) -> dict:
        with self._lock:
            t = int(req.get("term", 0))
            if t < self.term:
                return {"ok": False, "staleTerm": True, "term": self.term}
            self.term = t
            self.counts["snapshots"] += 1
            self.snapshot = req.get("snapshot", "").encode()
            wal = req.get("wal", "").encode()
            frames = parse_frames(wal)
            self.log = wal
            self.records = list(frames)
            self.seq = frames[-1][0] if frames else 0
            self.tip_crc = _tip_crc(wal)
            self.applied_seq = min(int(req.get("commitSeq", 0)), self.seq)
            return {"ok": True, "seq": self.seq, "term": self.term}


class ReplicaSet:
    """N real control-plane binaries as one replica set. Replica 0 is
    the bootstrap candidate (no --replica-of); the rest follow it at
    startup. `client()` returns a replica-aware Client that follows
    redirects and rotates across failover."""

    def __init__(self, base: str, n: int = 3, lease_ms: int = 400,
                 fsync: str = "interval", quorum_timeout_ms: int = 4000,
                 extra_args: list[str] | None = None,
                 client_timeout: float = 15.0):
        base = str(base)
        self.base = base
        self.lease_ms = lease_ms
        self.handles: list[ClusterHandle] = []
        self.client_timeout = client_timeout
        socks = [os.path.join(base, f"r{i}.sock") for i in range(n)]
        for i in range(n):
            peers = ",".join(s for j, s in enumerate(socks) if j != i)
            args = ["--fsync", fsync, "--group-commit", "64",
                    "--peers", peers, "--lease-ms", str(lease_ms),
                    "--quorum-timeout-ms", str(quorum_timeout_ms)]
            if i > 0:
                args += ["--replica-of", socks[0]]
            args += list(extra_args or [])
            self.handles.append(ClusterHandle(base, f"r{i}", args,
                                              client_timeout=client_timeout))
            # ClusterHandle derives <base>/<label>.sock — matches socks[i].
            assert self.handles[-1].sock == socks[i]
        self.socks = socks

    def start(self) -> None:
        for h in self.handles:
            h.start().close()

    def stop(self) -> None:
        for h in self.handles:
            h.stop()

    def client(self, **kw) -> Client:
        kw.setdefault("timeout", self.client_timeout)
        return Client(self.socks[0], replicas=self.socks[1:], **kw)

    def stateinfo(self, i: int) -> dict | None:
        """One replica's stateinfo, None when it is down/unreachable."""
        from kubeflow_tpu.controlplane.client import (ControlPlaneError,
                                                      ControlPlaneUnavailable)

        c = Client(self.socks[i], timeout=5, max_attempts=1, deadline_s=5)
        try:
            return c.stateinfo()
        except (ControlPlaneUnavailable, ControlPlaneError, OSError):
            return None
        finally:
            c.close()

    def leader_index(self) -> int | None:
        for i in range(len(self.handles)):
            info = self.stateinfo(i)
            if info and info.get("replication", {}).get("role") == "leader":
                return i
        return None

    def wait_leader(self, timeout: float = 15.0,
                    exclude: int | None = None) -> int:
        """Block until some replica (optionally excluding one index)
        reports role=leader; returns its index."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            for i in range(len(self.handles)):
                if i == exclude:
                    continue
                info = self.stateinfo(i)
                if (info and info.get("replication", {})
                        .get("role") == "leader"):
                    return i
            time.sleep(0.1)
        raise TimeoutError(
            f"no leader emerged within {timeout}s "
            f"(exclude={exclude}, lease={self.lease_ms}ms)")
