"""HuggingFace checkpoint import — safetensors → kubeflow_tpu param trees.

The reference's LLM runtime loads HF-format checkpoints directly
(⟨kserve: python/huggingfaceserver — huggingface_model.py⟩; SURVEY.md §2.2):
a user points an InferenceService at a directory of `config.json` +
`*.safetensors` and serving just works. This module gives the TPU rebuild
the same entry point: it reads HF Llama / BERT checkpoints and produces
this framework's flax param trees (scanned-layer stacked for Llama), so
fine-tuned open-weights models drop into both `serve/` and `train/`.

Only the tensor *layout* is translated (torch Linear stores [out, in];
flax DenseGeneral stores [in, ...out]); no HF code runs at import time and
nothing here depends on torch. RoPE needs no permutation: HF-format Llama
uses the rotate-half convention, which is exactly `models/llama.py
apply_rope`'s split-in-halves form.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.bert import Bert, BertConfig
from kubeflow_tpu.models.llama import Llama, LlamaConfig


def load_safetensors_dir(path: str) -> dict[str, np.ndarray]:
    """All tensors from a HF checkpoint dir (single-file or sharded with a
    model.safetensors.index.json)."""
    from safetensors.numpy import load_file

    index = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        tensors: dict[str, np.ndarray] = {}
        for shard in sorted(set(weight_map.values())):
            tensors.update(load_file(os.path.join(path, shard)))
        return tensors
    single = os.path.join(path, "model.safetensors")
    if os.path.exists(single):
        return load_file(single)
    cands = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not cands:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    tensors = {}
    for f in cands:
        tensors.update(load_file(os.path.join(path, f)))
    return tensors


def read_hf_config(path: str) -> dict:
    with open(os.path.join(path, "config.json")) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Llama
# ---------------------------------------------------------------------------

def llama_config_from_hf(hf: dict, **overrides: Any) -> LlamaConfig:
    """Map HF LlamaConfig fields onto ours. `overrides` win (e.g. dtype,
    attention_impl, max_seq_len truncation for serving memory).

    Unsupported config features fail loudly here — importing a checkpoint
    whose math this model family does not implement must never produce
    silently-wrong logits."""
    heads = hf["num_attention_heads"]
    fields = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=hf.get("num_key_value_heads", heads),
        head_dim=hf.get("head_dim") or hf["hidden_size"] // heads,
        max_seq_len=hf.get("max_position_embeddings", 8192),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    # Qwen2 ships QKV biases (HF LlamaConfig exposes attention_bias
    # explicitly; Qwen2Config implies it).
    fields["attention_bias"] = bool(
        hf.get("attention_bias", hf.get("model_type") == "qwen2"))
    scaling = hf.get("rope_scaling")
    if scaling:
        rtype = scaling.get("rope_type") or scaling.get("type")
        if rtype not in ("llama3", "default"):
            raise ValueError(
                f"unsupported rope_scaling type {rtype!r} (only the "
                "Llama-3.1 'llama3' frequency remap is implemented)")
        if rtype == "llama3":
            fields.update(
                rope_scaling_factor=float(scaling["factor"]),
                rope_scaling_low_freq_factor=float(
                    scaling.get("low_freq_factor", 1.0)),
                rope_scaling_high_freq_factor=float(
                    scaling.get("high_freq_factor", 4.0)),
                rope_scaling_original_max_len=int(
                    scaling.get("original_max_position_embeddings", 8192)))
    if hf.get("sliding_window") and hf.get("use_sliding_window", True):
        # (Qwen2 configs carry a sliding_window value with
        # use_sliding_window=false — windowing disabled — so the gate
        # must read both fields.)
        # Mistral-style windowed attention maps onto the flash kernel's
        # banded MaskSpec (ops/flash_attention.py kind="sliding_window" —
        # blocks beyond the band are skipped, not masked). The serving
        # engine separately enforces max_len <= window, where windowed and
        # causal decode are identical (serve/generation.py).
        fields.update(mask_kind="sliding_window",
                      mask_window=int(hf["sliding_window"]),
                      attention_impl="flash")
    fields.update(overrides)
    return LlamaConfig(**fields)


def _stack(tensors: dict[str, np.ndarray], fmt: str, n: int,
           transform) -> np.ndarray:
    return np.stack([transform(tensors[fmt.format(i=i)]) for i in range(n)])


def _lin(w: np.ndarray) -> np.ndarray:
    """torch Linear [out, in] -> flax kernel [in, out]."""
    return np.ascontiguousarray(w.T)


def _llama_family_params(t: dict, cfg, scan_layers: bool,
                         mlp: dict, extra_layers: dict | None = None) -> dict:
    """Shared Llama-family mapping — attention/norm/embed/lm_head tensors
    are identical across Llama, Mistral, and Mixtral checkpoints; `mlp` is
    the per-family FFN subtree (leaves stacked over layers). One copy so a
    layout fix can never reach one family and miss another.

    Leaves on a path containing 'router' keep fp32 (routing numerics
    decide expert assignment — MoEBlock declares the param fp32);
    everything else casts to cfg.param_dtype."""
    h, nh, nkh, hd = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                      cfg.head_dim)
    L = cfg.num_layers
    pd = np.dtype(jnp.dtype(cfg.param_dtype).name)

    def qk(w, heads):  # torch [heads*hd, H] -> flax [H, heads, hd]
        return np.ascontiguousarray(w.T).reshape(h, heads, hd)

    def ov(w):  # torch [H, nh*hd] -> flax [nh, hd, H]
        return np.ascontiguousarray(w.T).reshape(nh, hd, h)

    p = "model.layers.{i}."
    attn = {
        "q_proj": {"kernel": _stack(
            t, p + "self_attn.q_proj.weight", L, lambda w: qk(w, nh))},
        "k_proj": {"kernel": _stack(
            t, p + "self_attn.k_proj.weight", L, lambda w: qk(w, nkh))},
        "v_proj": {"kernel": _stack(
            t, p + "self_attn.v_proj.weight", L, lambda w: qk(w, nkh))},
        "o_proj": {"kernel": _stack(
            t, p + "self_attn.o_proj.weight", L, ov)},
    }
    if getattr(cfg, "attention_bias", False):
        # Qwen2-family QKV biases: torch [heads*hd] -> flax [heads, hd].
        for name, heads in (("q_proj", nh), ("k_proj", nkh),
                            ("v_proj", nkh)):
            attn[name]["bias"] = _stack(
                t, p + f"self_attn.{name}.bias", L,
                lambda b, heads=heads: b.reshape(heads, hd))
    layers = {
        "input_norm": {"scale": _stack(
            t, p + "input_layernorm.weight", L, lambda w: w)},
        "post_attn_norm": {"scale": _stack(
            t, p + "post_attention_layernorm.weight", L, lambda w: w)},
        "attn": attn,
        "mlp": mlp,
    }
    if extra_layers:
        # Family-specific per-layer subtrees (Gemma-2 sandwich norms,
        # Gemma-3 qk-norms) — leaves already stacked over L like
        # everything above. One-level-nested keys merge INTO the
        # existing subtree (e.g. {"attn": {"q_norm": ...}}), so extras
        # can extend the attention block without a bespoke copy of this
        # function's layout/cast handling.
        for k, v in extra_layers.items():
            if k in layers and isinstance(v, dict) \
                    and isinstance(layers[k], dict):
                layers[k].update(v)
            else:
                layers[k] = v
    params: dict[str, Any] = {
        "embed": t["model.embed_tokens.weight"],
        "final_norm": {"scale": t["model.norm.weight"]},
    }
    if not cfg.tie_embeddings:  # tied: the unembedding reuses `embed`
        if "lm_head.weight" not in t:
            raise KeyError(
                "checkpoint says tie_word_embeddings=false but has no "
                "lm_head.weight — refusing to guess (corrupt export?)")
        params["lm_head"] = {"kernel": _lin(t["lm_head.weight"])}
    if scan_layers:
        params["layers"] = layers
    else:
        for i in range(L):
            params[f"layer_{i}"] = jax.tree.map(lambda x: x[i], layers)

    def cast(path, x):
        fp32 = any(getattr(k, "key", None) == "router" for k in path)
        return jnp.asarray(np.asarray(x, np.float32 if fp32 else pd))

    return jax.tree_util.tree_map_with_path(cast, params)


def import_llama(path: str, *, scan_layers: bool = True,
                 **config_overrides: Any) -> tuple[LlamaConfig, dict]:
    """HF Llama checkpoint dir → (LlamaConfig, flax params).

    The returned tree matches `Llama(cfg).init(...)` exactly (asserted by
    tests/test_hf_import.py), with the scanned trunk's leading layer axis
    when scan_layers=True.
    """
    hf = read_hf_config(path)
    arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
    if ("Qwen2Moe" in arch
            or not any(fam in arch for fam in ("Llama", "Mistral", "Qwen2"))):
        # "Qwen2" must not admit Qwen2MoeForCausalLM — its expert tensors
        # would die below with an opaque missing-key error.
        raise ValueError(f"import_llama cannot load architecture {arch!r}")
    cfg = llama_config_from_hf(hf, scan_layers=scan_layers,
                               **config_overrides)
    t = load_safetensors_dir(path)
    return cfg, _llama_family_params(t, cfg, scan_layers,
                                     _swiglu_mlp(t, cfg.num_layers))


def _swiglu_mlp(t: dict, L: int) -> dict:
    p = "model.layers.{i}."
    return {
        "gate_proj": {"kernel": _stack(
            t, p + "mlp.gate_proj.weight", L, _lin)},
        "up_proj": {"kernel": _stack(
            t, p + "mlp.up_proj.weight", L, _lin)},
        "down_proj": {"kernel": _stack(
            t, p + "mlp.down_proj.weight", L, _lin)},
    }


# ---------------------------------------------------------------------------
# Gemma
# ---------------------------------------------------------------------------

def import_gemma(path: str, *, scan_layers: bool = True,
                 **config_overrides: Any):
    """HF Gemma (v1) checkpoint dir → (LlamaConfig, flax params).

    Gemma is Llama-shaped with three convention changes, all config flags
    on the shared trunk (models/llama.py): zero-centered RMSNorm applied
    as (1 + w), sqrt(hidden) input-embedding scaling, and a
    tanh-approximate-GeLU GLU gate. Tensor names match Llama exactly
    (tied embeddings — no lm_head). Gemma-2/3 add post-norms, logit
    softcapping, and alternating local attention — refused loudly by the
    exact-match dispatch, never imported as v1."""
    hf = read_hf_config(path)
    arch = (hf.get("architectures") or ["GemmaForCausalLM"])[0]
    if "Gemma" in arch and arch != "GemmaForCausalLM":
        # Gemma-2/3 must never import as v1, whatever model_type says.
        raise ValueError(f"import_gemma cannot load architecture {arch!r}")
    if hf.get("model_type") in ("gemma2", "gemma3", "gemma3_text"):
        # A v2/3 config with a missing/defaulted `architectures` key must
        # not slip through the arch check above and import as v1 with
        # silently-wrong math (r4 advisor finding).
        raise ValueError(
            f"import_gemma cannot load model_type "
            f"{hf['model_type']!r} (use import_gemma2 / build_from_hf)")
    if arch != "GemmaForCausalLM" and hf.get("model_type") != "gemma":
        raise ValueError(f"import_gemma cannot load architecture {arch!r}")
    act = (hf.get("hidden_activation") or hf.get("hidden_act")
           or "gelu_pytorch_tanh")
    if act not in ("gelu_pytorch_tanh", "gelu"):
        # HF treats legacy "gelu" configs as the tanh approximation too
        # (the Gemma release-time config bug); anything else is a model
        # this trunk does not implement.
        raise ValueError(f"unsupported Gemma activation {act!r}")
    fields = dict(
        scan_layers=scan_layers, norm_plus_one=True, embed_scale=True,
        mlp_act="gelu_tanh",
        # GemmaConfig's class default is tied embeddings; saved configs
        # omit the field (llama's absent-key default is False).
        tie_embeddings=bool(hf.get("tie_word_embeddings", True)))
    fields.update(config_overrides)  # caller overrides win (then validate)
    cfg = llama_config_from_hf(hf, **fields)
    if not cfg.tie_embeddings:
        raise ValueError(
            "Gemma checkpoints tie embeddings; tie_word_embeddings=false "
            "is not a Gemma-v1 layout")
    t = load_safetensors_dir(path)
    return cfg, _llama_family_params(t, cfg, scan_layers,
                                     _swiglu_mlp(t, cfg.num_layers))


def import_gemma2(path: str, *, scan_layers: bool = True,
                  **config_overrides: Any):
    """HF Gemma-2 checkpoint dir → (LlamaConfig, flax params).

    On top of the Gemma-v1 conventions ((1+w) norms, sqrt(hidden) embed
    scale, GeGLU, tied embeddings), Gemma-2 adds — all config flags on
    the shared trunk (models/llama.py):

      * sandwich norms: attention/MLP OUTPUTS are normed before their
        residual adds (HF post_attention_layernorm →
        `attn_out_norm`, post_feedforward_layernorm → `mlp_out_norm`;
        HF pre_feedforward_layernorm lands in our existing
        `post_attn_norm` slot — same position, normed MLP input);
      * tanh soft-caps on attention scores (`attn_softcap`) and final
        logits (`final_softcap`);
      * score scale query_pre_attn_scalar^-0.5 (folded into q);
      * alternating attention (HF layer_types): even layers sliding
        window, odd layers full causal — `sliding_pattern="even"`, a
        traced per-layer flag through the scanned trunk (einsum
        attention path; the fused kernels don't implement the
        softcapped/alternating score transform).

    Serving: within the window the engine rebuilds causal (exact);
    PAST the window the cache stays full-length (the full-attention
    layers need all history — nothing rolls) and sliding layers band
    their decode reads per the traced flag (round 5)."""
    hf = read_hf_config(path)
    arch = (hf.get("architectures") or [""])[0]
    if hf.get("model_type") in ("gemma3", "gemma3_text") or "Gemma3" in arch:
        raise ValueError(
            f"import_gemma2 cannot load {arch or hf.get('model_type')!r} "
            "(Gemma-3 is not implemented)")
    if not (arch in ("", "Gemma2ForCausalLM")
            or hf.get("model_type") == "gemma2"):
        raise ValueError(f"import_gemma2 cannot load architecture {arch!r}")
    act = (hf.get("hidden_activation") or hf.get("hidden_act")
           or "gelu_pytorch_tanh")
    if act not in ("gelu_pytorch_tanh", "gelu"):
        raise ValueError(f"unsupported Gemma-2 activation {act!r}")
    lt = hf.get("layer_types")
    if lt is not None:
        want = ["sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(hf["num_hidden_layers"])]
        if list(lt) != want:
            raise ValueError(
                "unsupported Gemma-2 layer_types pattern (expected "
                "alternating sliding/full starting sliding at layer 0)")
    fields = dict(
        scan_layers=scan_layers, norm_plus_one=True, embed_scale=True,
        mlp_act="gelu_tanh", sandwich_norms=True,
        attn_softcap=float(hf.get("attn_logit_softcapping") or 0.0),
        final_softcap=float(hf.get("final_logit_softcapping") or 0.0),
        query_pre_attn_scalar=float(hf.get("query_pre_attn_scalar") or 0.0),
        tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
        attention_impl="naive")
    fields.update(config_overrides)
    cfg = llama_config_from_hf(hf, **fields)
    if cfg.mask_kind == "sliding_window":
        # llama_config_from_hf set the window; mark the alternation (it
        # must not override a caller's explicit pattern OR impl choice,
        # so apply after overrides only the fields still defaulted).
        if "sliding_pattern" not in config_overrides:
            import dataclasses
            forced = {"sliding_pattern": "even"}
            if "attention_impl" not in config_overrides:
                forced["attention_impl"] = "naive"
            cfg = dataclasses.replace(cfg, **forced)
    if not cfg.tie_embeddings:
        raise ValueError(
            "Gemma-2 checkpoints tie embeddings; tie_word_embeddings="
            "false is not a Gemma-2 layout")
    t = load_safetensors_dir(path)
    L = cfg.num_layers
    p = "model.layers.{i}."
    extra = {
        "attn_out_norm": {"scale": _stack(
            t, p + "post_attention_layernorm.weight", L, lambda w: w)},
        "post_attn_norm": {"scale": _stack(
            t, p + "pre_feedforward_layernorm.weight", L, lambda w: w)},
        "mlp_out_norm": {"scale": _stack(
            t, p + "post_feedforward_layernorm.weight", L, lambda w: w)},
    }
    return cfg, _llama_family_params(t, cfg, scan_layers,
                                     _swiglu_mlp(t, cfg.num_layers),
                                     extra_layers=extra)


def import_gemma3(path: str, *, scan_layers: bool = True,
                  **config_overrides: Any):
    """HF Gemma-3 TEXT checkpoint dir → (LlamaConfig, flax params).

    On top of Gemma-2's sandwich norms / (1+w) norms / embed scale /
    GeGLU / query_pre_attn scale (soft-caps are GONE in v3), Gemma-3
    adds — all config flags on the shared trunk:

      * QK-norm: per-head (1+w) RMSNorm on projected q/k before RoPE
        (`qk_norm`; HF self_attn.q_norm/k_norm);
      * 5:1 local/global interleave (HF layer_types: every 6th layer
        full attention) — `sliding_pattern="5to1"`;
      * DUAL rope bases: sliding layers use `rope_local_base_freq`,
        full layers `rope_theta` with optional LINEAR scaling
        (`rope_global_scaling_factor`), selected per layer by the same
        traced flag as the mask.

    Multimodal Gemma-3 (`Gemma3ForConditionalGeneration`, a vision tower
    + text model) is refused — this imports the text stack only.
    Serving follows Gemma-2's shape: causal rebuild within the window,
    full-length cache with per-layer banded reads past it (round 5)."""
    hf = read_hf_config(path)
    arch = (hf.get("architectures") or [""])[0]
    if "ConditionalGeneration" in arch or hf.get("vision_config"):
        raise ValueError(
            f"{arch or hf.get('model_type')!r} is multimodal Gemma-3 "
            "(vision tower + text); only text checkpoints "
            "(Gemma3ForCausalLM / gemma3_text) are supported")
    if not (arch in ("", "Gemma3ForCausalLM", "Gemma3TextModel")
            or hf.get("model_type") in ("gemma3", "gemma3_text")):
        raise ValueError(f"import_gemma3 cannot load architecture {arch!r}")
    act = (hf.get("hidden_activation") or hf.get("hidden_act")
           or "gelu_pytorch_tanh")
    if act not in ("gelu_pytorch_tanh", "gelu"):
        raise ValueError(f"unsupported Gemma-3 activation {act!r}")
    lt = hf.get("layer_types")
    if lt is not None:
        want = ["full_attention" if (i + 1) % 6 == 0 else "sliding_attention"
                for i in range(hf["num_hidden_layers"])]
        if list(lt) != want:
            raise ValueError(
                "unsupported Gemma-3 layer_types pattern (expected 5 "
                "sliding : 1 full, full at every 6th layer)")
    elif int(hf.get("sliding_window_pattern", 6)) != 6:
        # Release-era configs carry sliding_window_pattern instead of
        # layer_types; anything but the canonical 6 (= 5 sliding : 1
        # full) would place the full layers at wrong indices — silently
        # wrong logits, so refuse.
        raise ValueError(
            f"unsupported sliding_window_pattern "
            f"{hf['sliding_window_pattern']} (only the 5:1 interleave "
            "is implemented)")
    scaling = hf.get("rope_scaling")
    linear_factor = 1.0
    if scaling:
        rtype = scaling.get("rope_type") or scaling.get("type")
        if rtype != "linear":
            raise ValueError(
                f"unsupported Gemma-3 rope_scaling type {rtype!r} "
                "(global layers use 'linear')")
        linear_factor = float(scaling.get("factor", 1.0))
    fields = dict(
        scan_layers=scan_layers, norm_plus_one=True, embed_scale=True,
        mlp_act="gelu_tanh", sandwich_norms=True, qk_norm=True,
        query_pre_attn_scalar=float(hf.get("query_pre_attn_scalar") or 0.0),
        rope_theta_local=float(hf.get("rope_local_base_freq", 10000.0)),
        rope_global_scaling_factor=linear_factor,
        tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
        attention_impl="naive")
    fields.update(config_overrides)
    # llama_config_from_hf reads hf["rope_scaling"] with llama3-type
    # semantics — Gemma-3's linear scaling is handled above, so shadow it.
    hf = dict(hf, rope_scaling=None)
    cfg = llama_config_from_hf(hf, **fields)
    if cfg.mask_kind == "sliding_window" \
            and "sliding_pattern" not in config_overrides:
        forced = {"sliding_pattern": "5to1"}
        if "attention_impl" not in config_overrides:
            forced["attention_impl"] = "naive"
        cfg = dataclasses.replace(cfg, **forced)
    if not cfg.tie_embeddings:
        raise ValueError(
            "Gemma-3 checkpoints tie embeddings; tie_word_embeddings="
            "false is not a Gemma-3 layout")
    t = load_safetensors_dir(path)
    L = cfg.num_layers
    p = "model.layers.{i}."
    extra = {
        "attn_out_norm": {"scale": _stack(
            t, p + "post_attention_layernorm.weight", L, lambda w: w)},
        "post_attn_norm": {"scale": _stack(
            t, p + "pre_feedforward_layernorm.weight", L, lambda w: w)},
        "mlp_out_norm": {"scale": _stack(
            t, p + "post_feedforward_layernorm.weight", L, lambda w: w)},
        # QK-norm scales live inside the attention subtree ([L, D_head]).
        "attn": {
            "q_norm": {"scale": _stack(
                t, p + "self_attn.q_norm.weight", L, lambda w: w)},
            "k_norm": {"scale": _stack(
                t, p + "self_attn.k_norm.weight", L, lambda w: w)},
        },
    }
    return cfg, _llama_family_params(t, cfg, scan_layers,
                                     _swiglu_mlp(t, cfg.num_layers),
                                     extra_layers=extra)


# ---------------------------------------------------------------------------
# Mixtral (sparse MoE)
# ---------------------------------------------------------------------------

def import_mixtral(path: str, *, scan_layers: bool = True,
                   **config_overrides: Any):
    """HF Mixtral checkpoint dir → (MoEConfig, flax params) for MoELlama.

    The reference serves Mixtral through the same huggingfaceserver entry
    point as Llama (SURVEY.md §2.2); here the block-sparse MoE FFN maps
    onto models/moe.py's capacity-based GShard dispatch. HF Mixtral
    routing is softmax-then-top-k-then-renormalize over all experts —
    exactly gshard_route's recipe — and inference must be DROPLESS, so
    the imported config pins capacity_factor = E/K (capacity == S per
    expert: no token can drop, logits match torch exactly). Serving cost
    of dropless dispatch scales with S^2·E per row at prefill — fine for
    the decode path (S=1) and bucketed prefill at serving lengths.

    Weight mapping per layer: block_sparse_moe.gate [E, H] → router
    [H, E] (fp32); experts.{e}.w1/w3/w2 [M, H]/[M, H]/[H, M] →
    w_gate/w_up [E, H, M], w_down [E, M, H]."""
    from kubeflow_tpu.models.moe import MoEConfig

    hf = read_hf_config(path)
    arch = (hf.get("architectures") or ["MixtralForCausalLM"])[0]
    if "Mixtral" not in arch:
        raise ValueError(f"import_mixtral cannot load architecture {arch!r}")
    E = int(hf["num_local_experts"])
    K = int(hf["num_experts_per_tok"])
    base = llama_config_from_hf(hf, scan_layers=scan_layers)
    cfg = MoEConfig(
        **{f.name: getattr(base, f.name)
           for f in dataclasses.fields(base) if f.init},
        num_experts=E, experts_per_token=K,
        capacity_factor=E / K,
        router_aux_coef=float(hf.get("router_aux_loss_coef", 0.01)))
    if config_overrides:
        cfg = dataclasses.replace(cfg, **config_overrides)
    t = load_safetensors_dir(path)
    L = cfg.num_layers
    p = "model.layers.{i}."
    moe = "block_sparse_moe."

    def experts(i, name):
        return np.stack([
            _lin(t[p.format(i=i) + moe + f"experts.{e}.{name}.weight"])
            for e in range(E)])

    mlp = {
        # fp32 enforced by path name in _llama_family_params.
        "router": np.stack([
            _lin(t[p.format(i=i) + moe + "gate.weight"]) for i in range(L)]),
        "w_gate": np.stack([experts(i, "w1") for i in range(L)]),
        "w_up": np.stack([experts(i, "w3") for i in range(L)]),
        "w_down": np.stack([experts(i, "w2") for i in range(L)]),
    }
    return cfg, _llama_family_params(t, cfg, scan_layers, mlp)


def import_qwen2_moe(path: str, *, scan_layers: bool = True,
                     **config_overrides: Any):
    """HF Qwen2-MoE checkpoint dir → (MoEConfig, flax params) for MoELlama.

    On top of the Mixtral recipe (GShard capacity dispatch pinned
    dropless at E/K), Qwen2-MoE adds — all on the shared MoE trunk
    (models/moe.py):

      * a SHARED expert: an always-on dense SwiGLU
        (`shared_expert_intermediate_size`) scaled by a learned
        per-token sigmoid gate (`shared_expert_gate` [1, H] → [H, 1]);
      * `norm_topk_prob=false` by default — top-k gate values keep their
        raw softmax mass instead of renormalizing to 1;
      * Qwen2's QKV biases (`attention_bias`);
      * expert width `moe_intermediate_size` (the dense
        `intermediate_size` belongs to the shared expert).

    Heterogeneous layouts are refused: `mlp_only_layers` non-empty or
    `decoder_sparse_step != 1` would interleave dense layers into the
    scanned MoE trunk."""
    from kubeflow_tpu.models.moe import MoEConfig

    hf = read_hf_config(path)
    arch = (hf.get("architectures") or [""])[0]
    if not ("Qwen2Moe" in arch or hf.get("model_type") == "qwen2_moe"):
        raise ValueError(
            f"import_qwen2_moe cannot load architecture {arch!r}")
    if hf.get("mlp_only_layers"):
        raise ValueError(
            f"mlp_only_layers={hf['mlp_only_layers']}: dense layers "
            "interleaved into the MoE trunk are not supported (the "
            "scanned trunk is homogeneous)")
    if int(hf.get("decoder_sparse_step", 1)) != 1:
        raise ValueError(
            f"decoder_sparse_step={hf['decoder_sparse_step']}: only "
            "every-layer-sparse checkpoints are supported")
    E = int(hf["num_experts"])
    K = int(hf["num_experts_per_tok"])
    base = llama_config_from_hf(hf, scan_layers=scan_layers,
                                attention_bias=True)
    fields = {f.name: getattr(base, f.name)
              for f in dataclasses.fields(base) if f.init}
    # The dense intermediate_size is the SHARED expert's width; routed
    # experts use moe_intermediate_size.
    fields["intermediate_size"] = int(hf["moe_intermediate_size"])
    cfg = MoEConfig(
        **fields,
        num_experts=E, experts_per_token=K,
        capacity_factor=E / K,  # dropless (see import_mixtral)
        norm_topk_prob=bool(hf.get("norm_topk_prob", False)),
        shared_expert_size=int(hf["shared_expert_intermediate_size"]),
        router_aux_coef=float(hf.get("router_aux_loss_coef", 0.001)))
    if config_overrides:
        cfg = dataclasses.replace(cfg, **config_overrides)
    t = load_safetensors_dir(path)
    if "model.layers.0.mlp.gate.weight" not in t:
        raise ValueError(
            f"config at {path!r} says qwen2_moe but the checkpoint has "
            "no expert router tensors (model.layers.*.mlp.gate.weight) — "
            "a dense-Qwen2 or truncated export mislabeled as MoE")
    L = cfg.num_layers
    p = "model.layers.{i}.mlp."

    def experts(i, name):
        return np.stack([
            _lin(t[p.format(i=i) + f"experts.{e}.{name}.weight"])
            for e in range(E)])

    def shared(name):
        return np.stack([
            _lin(t[p.format(i=i) + f"shared_expert.{name}.weight"])
            for i in range(L)])

    mlp = {
        "router": np.stack([
            _lin(t[p.format(i=i) + "gate.weight"]) for i in range(L)]),
        "w_gate": np.stack([experts(i, "gate_proj") for i in range(L)]),
        "w_up": np.stack([experts(i, "up_proj") for i in range(L)]),
        "w_down": np.stack([experts(i, "down_proj") for i in range(L)]),
        "w_shared_gate": shared("gate_proj"),
        "w_shared_up": shared("up_proj"),
        "w_shared_down": shared("down_proj"),
        "shared_gate": np.stack([
            _lin(t[p.format(i=i) + "shared_expert_gate.weight"])
            for i in range(L)]),
    }
    return cfg, _llama_family_params(t, cfg, scan_layers, mlp)


# ---------------------------------------------------------------------------
# BERT
# ---------------------------------------------------------------------------

def _bert_task_from_arch(hf: dict) -> str:
    """HF `architectures` → serving task (the huggingfaceserver task
    surface): ForSequenceClassification / ForTokenClassification /
    ForMaskedLM / bare BertModel → embedding. Head architectures with no
    implemented head (QuestionAnswering, MultipleChoice, ...) refuse —
    their classifier params would be silently misapplied as a
    sequence-classification head."""
    arch = (hf.get("architectures") or [""])[0]
    if "TokenClassification" in arch:
        return "token_classification"
    if "MaskedLM" in arch or "PreTraining" in arch:
        return "fill_mask"
    if "SequenceClassification" in arch:
        return "sequence_classification"
    if arch in ("BertModel", ""):
        # Bare encoder export — serve sentence embeddings. (HF configs
        # carry a default id2label even here, so arch is the only
        # trustworthy signal.)
        return "embedding"
    raise ValueError(
        f"unsupported BERT head architecture {arch!r}; implemented tasks: "
        "sequence_classification, token_classification, fill_mask, "
        "embedding (pass model_overrides={'task': ...} to force one)")


def bert_config_from_hf(hf: dict, **overrides: Any) -> BertConfig:
    pet = hf.get("position_embedding_type", "absolute")
    if pet != "absolute":
        raise ValueError(
            f"unsupported position_embedding_type {pet!r} (only absolute "
            "position embeddings are implemented)")
    act = hf.get("hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh", "relu"):
        raise ValueError(f"unsupported hidden_act {act!r}")
    fields = dict(
        task=_bert_task_from_arch(hf),
        hidden_act=act,
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        max_seq_len=hf.get("max_position_embeddings", 512),
        type_vocab_size=hf.get("type_vocab_size", 2),
        layer_norm_eps=float(hf.get("layer_norm_eps", 1e-12)),
        num_labels=len(hf.get("id2label") or {0: 0, 1: 1}),
    )
    fields.update(overrides)
    return BertConfig(**fields)


def import_bert(path: str, *, allow_headless: bool = False,
                **config_overrides: Any) -> tuple[BertConfig, dict]:
    """HF BERT checkpoint dir → (BertConfig, flax params) matching
    `Bert(cfg).init(...)`, with the serving task dispatched from the
    checkpoint's `architectures` (see _bert_task_from_arch): sequence /
    token classification heads, the tied MLM head, or the parameter-free
    embedding pooling for bare encoders.

    `allow_headless` applies to the sequence_classification task only: a
    classification import with no classifier.weight raises unless
    `allow_headless=True` (zero-init heads are only meaningful when the
    caller is about to fine-tune them, never for serving). To fine-tune a
    fresh head on a bare BertModel export — which now imports as
    task='embedding' — pass task='sequence_classification' (plus
    num_labels) together with allow_headless=True."""
    hf = read_hf_config(path)
    cfg = bert_config_from_hf(hf, **config_overrides)
    t = load_safetensors_dir(path)
    # Some exports omit the "bert." prefix on the encoder.
    pre = "bert." if any(k.startswith("bert.") for k in t) else ""
    h, nh = cfg.hidden_size, cfg.num_heads
    hd = h // nh
    pd = np.dtype(jnp.dtype(cfg.param_dtype).name)

    def lin(w):
        return np.ascontiguousarray(w.T)

    def ln(name):  # torch LayerNorm {weight,bias} -> flax {scale,bias}
        return {"scale": t[name + ".weight"], "bias": t[name + ".bias"]}

    def qkv(stem):  # [H, H] weight + [H] bias -> [H, nh, hd] + [nh, hd]
        return {"kernel": lin(t[stem + ".weight"]).reshape(h, nh, hd),
                "bias": t[stem + ".bias"].reshape(nh, hd)}

    params: dict[str, Any] = {
        "word_embeddings": t[pre + "embeddings.word_embeddings.weight"],
        "position_embeddings": t[pre + "embeddings.position_embeddings.weight"],
        "token_type_embeddings": t[pre + "embeddings.token_type_embeddings.weight"],
        "ln_embed": ln(pre + "embeddings.LayerNorm"),
    }
    for i in range(cfg.num_layers):
        lp = f"{pre}encoder.layer.{i}."
        od = t[lp + "attention.output.dense.weight"]  # [H, H]
        params[f"layer_{i}"] = {
            "q": qkv(lp + "attention.self.query"),
            "k": qkv(lp + "attention.self.key"),
            "v": qkv(lp + "attention.self.value"),
            "o": {"kernel": lin(od).reshape(nh, hd, h),
                  "bias": t[lp + "attention.output.dense.bias"]},
            "ln_attn": ln(lp + "attention.output.LayerNorm"),
            "ffn_in": {"kernel": lin(t[lp + "intermediate.dense.weight"]),
                       "bias": t[lp + "intermediate.dense.bias"]},
            "ffn_out": {"kernel": lin(t[lp + "output.dense.weight"]),
                        "bias": t[lp + "output.dense.bias"]},
            "ln_ffn": ln(lp + "output.LayerNorm"),
        }
    if cfg.task == "fill_mask":
        # BertOnlyMLMHead: cls.predictions.{transform.dense, transform.
        # LayerNorm, bias}; the decoder weight is TIED to word_embeddings
        # in the flax module (structural tie), so only the free bias and
        # transform are imported. An untied decoder (rare) would silently
        # deviate — refuse it.
        dec = "cls.predictions.decoder.weight"
        if dec in t and not np.array_equal(
                t[dec], t[pre + "embeddings.word_embeddings.weight"]):
            raise ValueError(
                "MaskedLM checkpoint has an UNTIED decoder weight; the "
                "flax MLM head ties the decoder to word_embeddings")
        params["mlm_transform"] = {
            "kernel": lin(t["cls.predictions.transform.dense.weight"]),
            "bias": t["cls.predictions.transform.dense.bias"]}
        params["mlm_ln"] = ln("cls.predictions.transform.LayerNorm")
        params["mlm_bias"] = t["cls.predictions.bias"]
    elif cfg.task == "token_classification":
        # Dense over every position; HF stores [num_labels, H].
        params["classifier"] = {"kernel": lin(t["classifier.weight"]),
                                "bias": t["classifier.bias"]}
    elif cfg.task == "embedding":
        pass  # pooling head has no parameters
    else:
        # Headless = no classifier. A missing pooler alone is NOT headless:
        # pooler-free classification exports exist and serve correctly with
        # use_pooler=False below (classifier on the raw [CLS] state).
        headless = "classifier.weight" not in t
        if headless and not allow_headless:
            raise KeyError(
                "checkpoint has no classification head (classifier.weight)"
                " — serving it would return constant zero logits; pass "
                "allow_headless=True only to fine-tune a fresh head")
        if pre + "pooler.dense.weight" in t:
            params["pooler"] = {
                "kernel": lin(t[pre + "pooler.dense.weight"]),
                "bias": t[pre + "pooler.dense.bias"]}
        else:
            # Pooler-free checkpoint: the classifier (existing or fresh)
            # consumes the RAW [CLS] hidden state — skip the pooler module
            # entirely (an identity kernel would still tanh and deviate
            # from the source model's logits).
            cfg = dataclasses.replace(cfg, use_pooler=False)
        if "classifier.weight" in t:
            params["classifier"] = {"kernel": lin(t["classifier.weight"]),
                                    "bias": t["classifier.bias"]}
        else:
            params["classifier"] = {
                "kernel": np.zeros((h, cfg.num_labels), pd),
                "bias": np.zeros((cfg.num_labels,), pd)}
    params = jax.tree.map(lambda x: jnp.asarray(np.asarray(x, pd)), params)
    return cfg, params


# ---------------------------------------------------------------------------
# GPT-2
# ---------------------------------------------------------------------------

def gpt2_config_from_hf(hf: dict, **overrides: Any):
    from kubeflow_tpu.models.gpt2 import GPT2Config

    act = hf.get("activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported activation_function {act!r}")
    # Attention-math variants this module does not implement must refuse,
    # not import with plain 1/sqrt(d) scaling (silently wrong logits).
    if not hf.get("scale_attn_weights", True):
        raise ValueError("scale_attn_weights=False is not implemented")
    for flag in ("scale_attn_by_inverse_layer_idx",
                 "reorder_and_upcast_attn"):
        if hf.get(flag):
            raise ValueError(f"{flag}=true is not implemented")
    fields = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["n_embd"],
        num_layers=hf["n_layer"],
        num_heads=hf["n_head"],
        intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
        max_seq_len=hf.get("n_positions", 1024),
        layer_norm_eps=float(hf.get("layer_norm_epsilon", 1e-5)),
    )
    fields.update(overrides)
    return GPT2Config(**fields)


def import_gpt2(path: str, **config_overrides: Any):
    """HF GPT2LMHeadModel checkpoint dir → (GPT2Config, flax params).

    HF GPT-2 uses Conv1D modules storing weights [in, out] — the flax
    kernel layout already — so unlike the Linear-based families nothing
    transposes; c_attn's fused [H, 3H] splits into q/k/v thirds."""
    hf = read_hf_config(path)
    cfg = gpt2_config_from_hf(hf, **config_overrides)
    t = load_safetensors_dir(path)
    pre = ("transformer."
           if any(k.startswith("transformer.") for k in t) else "")
    h, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    pd = np.dtype(jnp.dtype(cfg.param_dtype).name)

    def ln(name):
        return {"scale": t[name + ".weight"], "bias": t[name + ".bias"]}

    params: dict[str, Any] = {
        "wte": t[pre + "wte.weight"],
        "wpe": t[pre + "wpe.weight"],
        "ln_f": ln(pre + "ln_f"),
    }
    for i in range(cfg.num_layers):
        b = f"{pre}h.{i}."
        ca_w = t[b + "attn.c_attn.weight"]   # [H, 3H], Conv1D layout
        ca_b = t[b + "attn.c_attn.bias"]     # [3H]
        qw, kw, vw = np.split(ca_w, 3, axis=1)
        qb, kb, vb = np.split(ca_b, 3)
        params[f"block_{i}"] = {
            "q_proj": {"kernel": qw.reshape(h, nh, hd),
                       "bias": qb.reshape(nh, hd)},
            "k_proj": {"kernel": kw.reshape(h, nh, hd),
                       "bias": kb.reshape(nh, hd)},
            "v_proj": {"kernel": vw.reshape(h, nh, hd),
                       "bias": vb.reshape(nh, hd)},
            "o_proj": {"kernel": t[b + "attn.c_proj.weight"]
                       .reshape(nh, hd, h),
                       "bias": t[b + "attn.c_proj.bias"]},
            "ln_1": ln(b + "ln_1"),
            "ln_2": ln(b + "ln_2"),
            "fc": {"kernel": t[b + "mlp.c_fc.weight"],
                   "bias": t[b + "mlp.c_fc.bias"]},
            "proj": {"kernel": t[b + "mlp.c_proj.weight"],
                     "bias": t[b + "mlp.c_proj.bias"]},
        }
    params = jax.tree.map(lambda x: jnp.asarray(np.asarray(x, pd)), params)
    return cfg, params


# ---------------------------------------------------------------------------
# T5
# ---------------------------------------------------------------------------

def t5_config_from_hf(hf: dict, **overrides: Any):
    from kubeflow_tpu.models.t5 import T5Config

    proj = hf.get("feed_forward_proj", "relu")
    if proj not in ("relu", "gated-gelu"):
        raise ValueError(f"unsupported feed_forward_proj {proj!r}")
    fields = dict(
        vocab_size=hf["vocab_size"],
        d_model=hf["d_model"],
        d_kv=hf["d_kv"],
        d_ff=hf["d_ff"],
        num_layers=hf["num_layers"],
        num_decoder_layers=hf.get("num_decoder_layers", hf["num_layers"]),
        num_heads=hf["num_heads"],
        rel_buckets=hf.get("relative_attention_num_buckets", 32),
        rel_max_distance=hf.get("relative_attention_max_distance", 128),
        layer_norm_eps=float(hf.get("layer_norm_epsilon", 1e-6)),
        feed_forward_proj=proj,
        tie_embeddings=bool(hf.get("tie_word_embeddings", True)),
        decoder_start_id=int(hf.get("decoder_start_token_id") or 0),
        eos_id=int(hf.get("eos_token_id") or 1),
        # UMT5: every layer owns its relative-position bias table. The
        # detection MUST mirror build_from_hf's dispatch (model_type OR
        # architectures): an UMT5 arch with a stale model_type would
        # otherwise import with block-0 bias sharing — no missing-tensor
        # error to save us, just silently wrong generations.
        per_layer_rel_bias=(
            hf.get("model_type") == "umt5"
            or "UMT5" in (hf.get("architectures") or [""])[0]),
    )
    fields.update(overrides)
    return T5Config(**fields)


def import_t5(path: str, **config_overrides: Any):
    """HF T5ForConditionalGeneration checkpoint dir → (T5Config, flax
    params) matching `T5(cfg).init(...)` (tree equality asserted in
    tests/test_t5.py)."""
    hf = read_hf_config(path)
    cfg = t5_config_from_hf(hf, **config_overrides)
    t = load_safetensors_dir(path)
    h, nh, dk = cfg.d_model, cfg.num_heads, cfg.d_kv
    pd = np.dtype(jnp.dtype(cfg.param_dtype).name)

    def lin(w):  # torch [out, in] -> flax [in, out]
        return np.ascontiguousarray(w.T)

    def qkv(name):  # [nh*dk, d_model] -> [d_model, nh, dk]
        return {"kernel": lin(t[name + ".weight"]).reshape(h, nh, dk)}

    def out_proj(name):  # [d_model, nh*dk] -> [nh, dk, d_model]
        return {"kernel": lin(t[name + ".weight"]).reshape(nh, dk, h)}

    def attn(stem):
        return {"q": qkv(stem + ".q"), "k": qkv(stem + ".k"),
                "v": qkv(stem + ".v"), "o": out_proj(stem + ".o")}

    def ffn(stem):
        if cfg.gated:
            return {"wi_0": {"kernel": lin(t[stem + ".wi_0.weight"])},
                    "wi_1": {"kernel": lin(t[stem + ".wi_1.weight"])},
                    "wo": {"kernel": lin(t[stem + ".wo.weight"])}}
        return {"wi": {"kernel": lin(t[stem + ".wi.weight"])},
                "wo": {"kernel": lin(t[stem + ".wo.weight"])}}

    def ln(name):
        return {"scale": t[name + ".weight"]}

    def rel(stem):
        return {"rel_embedding": t[
            stem + ".SelfAttention.relative_attention_bias.weight"]}

    params: dict[str, Any] = {
        "shared_embedding": t["shared.weight"],
        "enc_final_ln": ln("encoder.final_layer_norm"),
        "dec_final_ln": ln("decoder.final_layer_norm"),
    }
    if not cfg.per_layer_rel_bias:
        params["enc_rel"] = rel("encoder.block.0.layer.0")
        params["dec_rel"] = rel("decoder.block.0.layer.0")
    for i in range(cfg.num_layers):
        b = f"encoder.block.{i}.layer"
        if cfg.per_layer_rel_bias:  # UMT5: each layer owns a table
            params[f"enc_{i}_rel"] = rel(f"{b}.0")
        params[f"enc_{i}_attn"] = attn(f"{b}.0.SelfAttention")
        params[f"enc_{i}_attn_ln"] = ln(f"{b}.0.layer_norm")
        params[f"enc_{i}_ffn"] = ffn(f"{b}.1.DenseReluDense")
        params[f"enc_{i}_ffn_ln"] = ln(f"{b}.1.layer_norm")
    for i in range(cfg.num_decoder_layers):
        b = f"decoder.block.{i}.layer"
        if cfg.per_layer_rel_bias:
            params[f"dec_{i}_rel"] = rel(f"{b}.0")
        params[f"dec_{i}_self"] = attn(f"{b}.0.SelfAttention")
        params[f"dec_{i}_self_ln"] = ln(f"{b}.0.layer_norm")
        params[f"dec_{i}_cross"] = attn(f"{b}.1.EncDecAttention")
        params[f"dec_{i}_cross_ln"] = ln(f"{b}.1.layer_norm")
        params[f"dec_{i}_ffn"] = ffn(f"{b}.2.DenseReluDense")
        params[f"dec_{i}_ffn_ln"] = ln(f"{b}.2.layer_norm")
    if not cfg.tie_embeddings:
        params["lm_head"] = lin(t["lm_head.weight"])
    params = jax.tree.map(lambda x: jnp.asarray(np.asarray(x, pd)), params)
    return cfg, params


# ---------------------------------------------------------------------------
# Model builders (used by the serving runtime)
# ---------------------------------------------------------------------------

def build_from_hf(path: str, **overrides: Any):
    """Architecture-dispatched import: returns (module, cfg, params)."""
    hf = read_hf_config(path)
    arch = (hf.get("architectures") or [hf.get("model_type", "")])[0]
    if "Bert" in arch or hf.get("model_type") == "bert":
        cfg, params = import_bert(path, **overrides)
        return Bert(cfg), cfg, params
    if arch == "GPT2LMHeadModel" or hf.get("model_type") == "gpt2":
        from kubeflow_tpu.models.gpt2 import GPT2

        cfg, params = import_gpt2(path, **overrides)
        return GPT2(cfg), cfg, params
    # Exact-match T5 dispatch. UMT5 (round 5) rides the same importer:
    # t5_config_from_hf flips per_layer_rel_bias so every layer owns its
    # relative-position table instead of sharing block 0's.
    if (arch in ("T5ForConditionalGeneration",
                 "MT5ForConditionalGeneration",
                 "UMT5ForConditionalGeneration")
            or hf.get("model_type") in ("t5", "mt5", "umt5")):
        from kubeflow_tpu.models.t5 import T5

        cfg, params = import_t5(path, **overrides)
        return T5(cfg), cfg, params
    if "Mixtral" in arch or hf.get("model_type") == "mixtral":
        from kubeflow_tpu.models.moe import MoELlama

        cfg, params = import_mixtral(path, **overrides)
        return MoELlama(cfg), cfg, params
    if "Gemma3" in arch or hf.get("model_type") in ("gemma3", "gemma3_text"):
        cfg, params = import_gemma3(path, **overrides)
        return Llama(cfg), cfg, params
    if arch == "Gemma2ForCausalLM" or hf.get("model_type") == "gemma2":
        cfg, params = import_gemma2(path, **overrides)
        return Llama(cfg), cfg, params
    if "Gemma" in arch and arch != "GemmaForCausalLM":
        # Any other non-v1 Gemma variant: refuse rather than guess.
        raise ValueError(f"unsupported architecture {arch!r}")
    if arch == "GemmaForCausalLM" or hf.get("model_type") == "gemma":
        cfg, params = import_gemma(path, **overrides)
        return Llama(cfg), cfg, params
    if "Qwen2Moe" in arch or hf.get("model_type") == "qwen2_moe":
        from kubeflow_tpu.models.moe import MoELlama

        cfg, params = import_qwen2_moe(path, **overrides)
        return MoELlama(cfg), cfg, params
    if "T5" in arch or hf.get("model_type", "").endswith("t5"):
        # Catches future T5 variants whether declared via architectures
        # OR only via model_type — falling through to import_llama would
        # crash with an opaque missing-tensor error.
        raise ValueError(
            f"unsupported T5-family architecture {arch!r} "
            "(T5/MT5/UMT5 are implemented)")
    cfg, params = import_llama(path, **overrides)
    return Llama(cfg), cfg, params
