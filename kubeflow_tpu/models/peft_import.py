"""PEFT LoRA adapter import: HF `adapter_model.safetensors` → this
framework's adapter leaves.

The reference ecosystem fine-tunes with HF PEFT (the training SDK's
LoraConfig produces a PEFT adapter dir: `adapter_config.json` +
`adapter_model.safetensors`) and serves the result; this module closes
the loop for checkpoints tuned ELSEWHERE: overlay the adapter onto an
imported base model (models/hf_import.py) as native `*_lora_*` leaves
(models/llama.py), then either run the adapted model directly or fold it
flat with train/lora.py `merge()` and serve a plain base tree.

Layouts: PEFT stores lora_A [r, in] and lora_B [out, r] (torch Linear
convention); ours are A [in, r] and B [r, *out] — transposes, plus the
head reshape for attention projections and the leading stacked-layer dim
for the scanned trunk. Scaling: PEFT applies alpha/r exactly like
models/llama.py `_lora_delta` (rsLoRA's alpha/sqrt(r) is refused loudly).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.hf_import import load_safetensors_dir

#: target_modules set -> our lora_targets mode.
_TARGET_MODES = {
    frozenset({"q_proj", "v_proj"}): "attn",
    frozenset({"q_proj", "v_proj", "gate_proj", "up_proj",
               "down_proj"}): "attn_mlp",
}


def read_adapter_config(path: str) -> dict:
    with open(os.path.join(path, "adapter_config.json")) as f:
        return json.load(f)


def load_peft_adapter(path: str, cfg):
    """(adapter dir, base LlamaConfig) -> (cfg with lora fields, flat
    {path tuple: jnp array} adapter leaves matching the scanned model).

    Unsupported adapter shapes fail loudly: silently dropping a target
    module would serve a model that quietly differs from what was tuned.
    """
    ac = read_adapter_config(path)
    if ac.get("peft_type", "LORA").upper() != "LORA":
        raise ValueError(
            f"unsupported peft_type {ac.get('peft_type')!r} (LoRA only)")
    if ac.get("use_rslora"):
        raise ValueError(
            "use_rslora=true scales by alpha/sqrt(r); this build "
            "implements classic alpha/r scaling only")
    if ac.get("use_dora"):
        raise ValueError("DoRA adapters are not supported")
    if (ac.get("bias") or "none") != "none":
        raise ValueError(
            f"adapter bias={ac.get('bias')!r}: bias deltas are not "
            "implemented (bias='none' only)")
    if ac.get("modules_to_save"):
        raise ValueError(
            f"modules_to_save={ac['modules_to_save']} holds fully-tuned "
            "modules this importer would silently drop — not supported")
    if ac.get("alpha_pattern") or ac.get("rank_pattern"):
        raise ValueError(
            "per-module alpha_pattern/rank_pattern are not supported "
            "(one global r/alpha only)")
    raw_targets = ac.get("target_modules") or ()
    if isinstance(raw_targets, str):
        # PEFT's string form is a regex FULLMATCHED against full dotted
        # module paths (peft.tuners.tuners_utils) — resolve it the same
        # way over this family's layout, plus the bare name (PEFT's
        # exact-name shortcut).
        import re

        def hits(m):
            group = ("self_attn" if m.endswith(("q_proj", "k_proj",
                                                "v_proj", "o_proj"))
                     else "mlp")
            full = f"model.layers.0.{group}.{m}"
            return (re.fullmatch(raw_targets, full)
                    or re.fullmatch(raw_targets, m))

        raw_targets = [m for m in ("q_proj", "k_proj", "v_proj", "o_proj",
                                   "gate_proj", "up_proj", "down_proj")
                       if hits(m)]
    targets = frozenset(raw_targets)
    mode = _TARGET_MODES.get(targets)
    if mode is None:
        raise ValueError(
            f"unsupported target_modules {sorted(targets)}; supported: "
            f"{[sorted(k) for k in _TARGET_MODES]}")
    r = int(ac["r"])
    alpha = float(ac.get("lora_alpha", r))
    from kubeflow_tpu.models.llama import LlamaConfig

    if not isinstance(cfg, LlamaConfig):
        raise ValueError(
            f"peft_adapter needs a Llama-family base model; "
            f"{type(cfg).__name__} has no adapter path")
    cfg = dataclasses.replace(cfg, lora_rank=r, lora_alpha=alpha,
                              lora_targets=mode)
    if not cfg.scan_layers:
        raise ValueError("adapter import expects the scanned trunk "
                         "(scan_layers=True)")

    t = load_safetensors_dir(path)

    def find(i: int, module: str, which: str) -> np.ndarray:
        suffix = f"layers.{i}.{_module_path(module)}.{which}.weight"
        hits = [k for k in t if k.endswith(suffix)]
        if len(hits) != 1:
            raise KeyError(
                f"expected exactly one tensor ending in {suffix!r}, "
                f"found {hits}")
        return t[hits[0]]

    L = cfg.num_layers
    out_shapes = {
        "q_proj": (cfg.num_heads, cfg.head_dim),
        "v_proj": (cfg.num_kv_heads, cfg.head_dim),
        "gate_proj": (cfg.intermediate_size,),
        "up_proj": (cfg.intermediate_size,),
        "down_proj": (cfg.hidden_size,),
    }
    modules = (("q_proj", "v_proj") if mode == "attn" else
               ("q_proj", "v_proj", "gate_proj", "up_proj", "down_proj"))
    leaves: dict[tuple, Any] = {}
    for m in modules:
        group = "attn" if m in ("q_proj", "v_proj") else "mlp"
        a = np.stack([find(i, m, "lora_A") for i in range(L)])  # [L, r, in]
        b = np.stack([find(i, m, "lora_B") for i in range(L)])  # [L, out, r]
        if a.shape[1] != r:
            raise ValueError(
                f"{m} lora_A rank dim {a.shape[1]} != config r {r}")
        a = np.ascontiguousarray(a.transpose(0, 2, 1))  # [L, in, r]
        b = np.ascontiguousarray(b.transpose(0, 2, 1))  # [L, r, out]
        b = b.reshape(L, r, *out_shapes[m])
        pd = np.dtype(jnp.dtype(cfg.param_dtype).name)
        leaves[("layers", group, f"{m}_lora_a")] = jnp.asarray(
            a.astype(pd))
        leaves[("layers", group, f"{m}_lora_b")] = jnp.asarray(
            b.astype(pd))
    return cfg, leaves


def _module_path(module: str) -> str:
    return (f"self_attn.{module}" if module.endswith(("q_proj", "v_proj"))
            else f"mlp.{module}")


def attach_peft_adapter(path: str, cfg, params):
    """Overlay a PEFT adapter onto imported base params: returns
    (adapted cfg, params carrying *_lora_* leaves) — apply with
    Llama(adapted_cfg), or fold flat with train/lora.py merge()."""
    from flax import traverse_util

    cfg, leaves = load_peft_adapter(path, cfg)
    flat = dict(traverse_util.flatten_dict(params))
    flat.update(leaves)
    return cfg, traverse_util.unflatten_dict(flat)
