"""Pipeline-parallel Llama: the scanned trunk partitioned over `pipe`.

SURVEY.md §2.6 PP row maps the reference's DeepSpeed/Megatron pipeline
engines (p2p microbatch send/recv inside user containers) to a compiled
stage-sharded schedule. parallel/pipeline.py provides the schedules (GPipe +
interleaved circular, AD straight through); this module binds them to the
REAL flagship model:

  * **Same parameter pytree as the scanned Llama** (models/llama.py with
    `scan_layers=True`): trunk leaves carry a leading `layers` dim L. PP is
    a *rules* change — logical axis `layers` maps to mesh axis `pipe`
    (sharding.py "pipeline" preset) — plus a reshape [L, ...] ->
    [stages, L/stages, ...] inside the step. Checkpoints, HF import, and
    the single-path model stay bit-identical; no second weight format.
  * **Embed / final-norm / unembed ride GSPMD outside the shard_map**: the
    pipeline region covers exactly the homogeneous trunk (constant
    activation shape), which is what the schedule requires; the vocab-sized
    ends keep their usual tensor/fsdp sharding rules and gradients
    all-reduce over `data` automatically.
  * **Per-layer forward is pure jnp** (no flax apply): inside the manual
    shard_map region, flax's logical-constraint machinery would try to
    issue auto-sharding constraints, which don't compose with manual axes.
    The math matches DecoderLayer exactly (RMSNorm fp32, RoPE fp32, GQA
    attention, SwiGLU in cfg.dtype).

Packed pre-training composes with PP: pass `positions` + `segment_ids`
and they ride the pipeline ring alongside the activations (a pytree
microbatch — parallel/pipeline.py), so each stage masks attention within
documents exactly like the scanned model. Block-sparse MaskSpecs
(cfg.mask_kind) flow into the stage attention the same way.

CP composes INSIDE the pipeline (`seq_axis`): traveling activations shard
their sequence dim over `seq` and stage attention runs the ring schedule
(position-masked einsum ring for 'naive', fused offset-case ring for
'flash') — ops/ring_attention.py manual bodies, callable because the
`seq` axis is part of the pipeline's own shard_map region. Packed
segment masks compose too (round 5): segs travel the pipeline AND rotate
the stage ring with K/V, on the einsum ring. MaskSpec families still
need the non-CP pipeline.

MoE composes too: a scanned MoELlama tree pipelines with expert weights
sharded over `expert` (_moe_ffn — EP's combine-psum inside the stage
region); MoE-PP and CP-inside-PP are mutually exclusive (expert capacity
is a global-sequence statistic).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.llama import LlamaConfig, apply_rope, rope_table
from kubeflow_tpu.ops.reference import naive_attention
from kubeflow_tpu.parallel.pipeline import (
    pipeline_apply, pipeline_apply_circular)


def _rms(x: jax.Array, scale: jax.Array, eps: float, dtype,
         plus_one: bool = False) -> jax.Array:
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    if plus_one:  # Gemma stores zero-centered scales, applied as (1 + w)
        scale = 1.0 + scale
    return (y * scale).astype(dtype)


def _resolve_attn(cfg: LlamaConfig) -> str:
    impl = cfg.attention_impl
    if impl == "auto":
        return ("flash" if jax.default_backend() in ("tpu", "axon")
                else "naive")
    if impl not in ("naive", "flash"):
        raise ValueError(
            f"pipeline parallelism supports attention_impl 'naive'/'flash' "
            f"(contiguous or packed causal sequences), not {impl!r}")
    return impl


def layer_fwd(cfg: LlamaConfig, lp: dict, x: jax.Array, cos: jax.Array,
              sin: jax.Array, positions: jax.Array,
              attn_impl: str = "naive",
              segment_ids: jax.Array | None = None,
              ring: tuple[str, int] | None = None,
              expert: tuple[str, int] | None = None,
              ) -> tuple[jax.Array, jax.Array]:
    """One decoder layer, pure jnp. lp: the layer's param subtree (kernels
    exactly as flax lays them out: q/k/v [H, heads, D], o [heads, D, H],
    gate/up [H, M], down [M, H]); x [mb, S, H] in cfg.dtype.
    `segment_ids` [mb, S] confines attention within packed documents;
    cfg.mask_spec selects the block-sparse mask family — both match the
    scanned Attention module's semantics (models/llama.py).

    `ring=(axis_name, n)`: context parallelism INSIDE the pipeline stage —
    x/positions arrive seq-sharded over the `axis_name` mesh axis (the
    enclosing shard_map region includes it) and attention runs the ring
    schedule over that axis (ops/ring_attention.py manual bodies).

    Returns (x, aux): aux is the layer's Switch load-balance statistic for
    routed-expert FFNs (`expert=(axis, n)` shards them), 0 for dense."""
    dt = cfg.dtype
    h = _rms(x, lp["input_norm"]["scale"], cfg.rms_eps, dt,
             cfg.norm_plus_one)
    q = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["q_proj"]["kernel"].astype(dt))
    k = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["k_proj"]["kernel"].astype(dt))
    v = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["v_proj"]["kernel"].astype(dt))
    if "bias" in lp["attn"]["q_proj"]:  # Qwen2-family QKV biases
        q = q + lp["attn"]["q_proj"]["bias"].astype(dt)
        k = k + lp["attn"]["k_proj"]["bias"].astype(dt)
        v = v + lp["attn"]["v_proj"]["bias"].astype(dt)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    mask = cfg.mask_spec
    if ring is not None:
        from kubeflow_tpu.ops.ring_attention import (
            ring_attention_flash_manual, ring_attention_manual)
        if mask is not None:
            raise ValueError(
                "ring attention inside the pipeline stage is causal-only "
                "(no MaskSpec families)")
        if attn_impl == "flash":
            # Contiguous layout: shard r owns positions [r*s_loc, ...), so
            # causality comes from ring offsets (fused Pallas inner).
            # Packed batches take the einsum ring (pipeline_forward
            # downgrades the impl) — the fused ring has no segment mask.
            if segment_ids is not None:
                raise ValueError(
                    "the fused ring has no segment mask; packed "
                    "CP-inside-PP uses the einsum ring (attn 'naive')")
            attn = ring_attention_flash_manual(
                q, k, v, ring[0], ring[1],
                block_q=cfg.flash_block_q, block_kv=cfg.flash_block_kv)
        else:
            # Position+segment-masked einsum ring: exact for packed
            # documents — segs rotate with K/V.
            attn = ring_attention_manual(q, k, v, positions, *ring,
                                         segment_ids=segment_ids)
    elif attn_impl == "flash":
        from kubeflow_tpu.ops.flash_attention import flash_attention
        attn = flash_attention(q, k, v, causal=True,
                               block_q=cfg.flash_block_q,
                               block_kv=cfg.flash_block_kv,
                               segment_ids=segment_ids, mask=mask)
    else:
        attn = naive_attention(q, k, v, causal=True, positions_q=positions,
                               positions_kv=positions,
                               segment_ids=segment_ids, mask=mask)
    attn = jnp.einsum("bsnd,ndh->bsh", attn,
                      lp["attn"]["o_proj"]["kernel"].astype(dt))
    x = x + attn
    h2 = _rms(x, lp["post_attn_norm"]["scale"], cfg.rms_eps, dt,
              cfg.norm_plus_one)
    if "router" in lp["mlp"]:
        y, aux = _moe_ffn(cfg, lp["mlp"], h2, expert)
        return x + y, aux
    gate = h2 @ lp["mlp"]["gate_proj"]["kernel"].astype(dt)
    up = h2 @ lp["mlp"]["up_proj"]["kernel"].astype(dt)
    if cfg.mlp_act == "silu":
        act = jax.nn.silu(gate)
    elif cfg.mlp_act == "gelu_tanh":  # Gemma's GeGLU gate
        act = jax.nn.gelu(gate, approximate=True)
    else:
        raise ValueError(f"mlp_act {cfg.mlp_act!r}: silu | gelu_tanh")
    y = (act * up) @ lp["mlp"]["down_proj"]["kernel"].astype(dt)
    return x + y, jnp.zeros((), jnp.float32)


def _moe_ffn(cfg, mp: dict, h2: jax.Array,
             expert: tuple[str, int] | None):
    """Routed-expert FFN for the pipeline stage (MoE-PP), pure jnp. mp:
    router [H, E] (replicated over `expert`), w_gate/w_up [E_loc, H, M],
    w_down [E_loc, M, H] — the LOCAL expert slice when the enclosing
    shard_map shards the expert dim. Routing math is the shared
    gshard_route (models/moe.py), so dispatch/combine/aux cannot drift
    from the scanned MoEBlock. With expert=(axis, n): every rank computes
    the full dispatch from its (replicated-over-expert) activations,
    slices its experts, and the combine psums partial outputs — the EP
    collective pattern inside the pipeline region."""
    from kubeflow_tpu.models.moe import expert_capacity, gshard_route

    dt = cfg.dtype
    s = h2.shape[1]
    C = expert_capacity(cfg, s)
    dispatch, combine, aux = gshard_route(
        h2, mp["router"], cfg.experts_per_token, C,
        renormalize=getattr(cfg, "norm_topk_prob", True))
    e_loc = mp["w_gate"].shape[0]
    if expert is not None and expert[1] > 1:
        start = jax.lax.axis_index(expert[0]) * e_loc
        dispatch = jax.lax.dynamic_slice_in_dim(dispatch, start, e_loc, 2)
        combine = jax.lax.dynamic_slice_in_dim(combine, start, e_loc, 2)
    xin = jnp.einsum("bsec,bsh->ebch", dispatch.astype(dt), h2.astype(dt))
    g = jnp.einsum("ebch,ehm->ebcm", xin, mp["w_gate"].astype(dt))
    u = jnp.einsum("ebch,ehm->ebcm", xin, mp["w_up"].astype(dt))
    hh = jax.nn.silu(g) * u
    out = jnp.einsum("ebcm,emh->ebch", hh, mp["w_down"].astype(dt))
    y = jnp.einsum("bsec,ebch->bsh", combine.astype(dt), out)
    if expert is not None and expert[1] > 1:
        y = jax.lax.psum(y, expert[0])
    if "w_shared_gate" in mp:
        # Qwen2-MoE shared expert (replicated over `expert`) — the ONE
        # definition in models/moe.py, same as MoEBlock.
        from kubeflow_tpu.models.moe import shared_expert_ffn

        y = y + shared_expert_ffn(h2, mp["w_shared_gate"],
                                  mp["w_shared_up"], mp["w_shared_down"],
                                  mp["shared_gate"], dt)
    return y.astype(dt), aux


def pipeline_forward(
    cfg: LlamaConfig,
    params: Any,
    tokens: jax.Array,
    *,
    mesh,
    num_microbatches: int,
    num_chunks: int = 1,
    data_axis: str | tuple[str, ...] | None = ("data", "fsdp"),
    return_hidden: bool = False,
    positions: jax.Array | None = None,
    segment_ids: jax.Array | None = None,
    seq_axis: str | None = None,
    expert_axis: str = "expert",
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Full causal-LM forward with the trunk pipelined over `pipe`.

    params: the SAME pytree the scanned Llama produces (trunk under
    params['layers'] with leading dim L). tokens [B, S]. Returns logits
    [B, S, V] (or post-norm hidden [B, S, H] with return_hidden for the
    chunked-CE path). Numerics match the non-pipelined model.

    MoE-PP: a scanned MoELlama param tree (models/moe.py — layer FFNs are
    routed experts) pipelines the same way; expert weights additionally
    shard over `expert_axis` when the mesh has it (>1), with the combine
    psum as the EP collective inside the pipeline region. Returns
    (out, aux) — the Switch load-balance aux averaged per (microbatch x
    data shard), the standard microbatched-routing statistic (it matches
    the scanned model's global-batch aux only at one microbatch/shard;
    logits match exactly regardless, routing is per-row).

    Packed pre-training: pass per-document restarting `positions` and
    `segment_ids` [B, S] (data/loader.py packing) — they microbatch and
    travel the pipeline ring with the activations, so every stage applies
    the same RoPE offsets and within-document attention mask the scanned
    model would.

    Context parallelism inside the pipeline (`seq_axis`): the traveling
    activations shard their SEQUENCE dim over `seq_axis` (in addition to
    microbatch rows over `data_axis`), and each stage's attention runs the
    ring schedule over that axis — PP x CP composition for long sequences
    (SURVEY §5.7 x §2.6). Contiguous layout; attn 'naive' uses the
    position-masked einsum ring (exact), 'flash' the fused offset-case
    ring. Packed batches compose: segment_ids shard with the sequence and
    rotate the stage ring alongside K/V (einsum ring — the impl
    auto-downgrades from 'flash'). MaskSpec families still refuse."""
    if cfg.num_layers % (mesh.shape["pipe"] * num_chunks):
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pipe "
            f"({mesh.shape['pipe']}) * chunks ({num_chunks})")
    if (getattr(cfg, "sliding_pattern", "all") != "all"
            or getattr(cfg, "qk_norm", False)
            or getattr(cfg, "rope_theta_local", 0)
            or getattr(cfg, "attn_softcap", 0)):
        # The stage body applies ONE attention recipe to every layer it
        # scans — per-layer kinds (Gemma-2/3 alternating windows, dual
        # rope bases) and the softcap/qk-norm score transforms would be
        # silently wrong, not slow. Train those families on the scanned
        # model.
        raise ValueError(
            "pipeline parallelism doesn't implement per-layer attention "
            "kinds or Gemma-2/3 score transforms (alternating windows / "
            "dual rope bases / qk_norm / softcap) — use the scanned "
            "model")
    attn_impl = _resolve_attn(cfg)
    ring = None
    if seq_axis is not None and mesh.shape[seq_axis] > 1:
        n_seq = mesh.shape[seq_axis]
        if segment_ids is not None:
            # Packed documents x CP-inside-PP: segment ids shard with the
            # sequence and rotate around the stage ring with K/V — exact
            # on the position+segment-masked einsum ring only (the fused
            # ring derives causality from layout, not positions).
            attn_impl = "naive"
        if cfg.mask_spec is not None:
            raise ValueError(
                f"CP-inside-PP is causal-only; mask_kind={cfg.mask_kind!r} "
                "needs the non-CP pipeline or the scanned model")
        if tokens.shape[1] % n_seq:
            raise ValueError(
                f"seq len {tokens.shape[1]} not divisible by seq axis "
                f"({n_seq})")
        if attn_impl == "flash" and positions is not None:
            raise ValueError(
                "CP-inside-PP flash ring derives causality from the "
                "contiguous layout; custom positions need 'naive'")
        ring = (seq_axis, n_seq)
    if (attn_impl == "flash" and positions is not None
            and segment_ids is None):
        # Mirror the scanned Attention's refusal: the flash kernel masks
        # causality by array index, so custom positions need the segment
        # mask to carry document structure.
        raise ValueError(
            "pipeline flash attention with custom positions needs "
            "segment_ids (packed sequences)")
    dt = cfg.dtype
    b, s = tokens.shape
    embed = params["embed"]
    x = embed.astype(dt)[tokens]
    if cfg.embed_scale:  # Gemma: sqrt(hidden) input scaling
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, dt)
    cos, sin = rope_table(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta, cfg)

    is_moe = "router" in params["layers"]["mlp"]
    expert = None
    if is_moe:
        if ring is not None:
            raise ValueError(
                "MoE-PP doesn't compose with CP-inside-PP (seq_axis) — "
                "expert capacity is a global-sequence statistic")
        n_exp = mesh.shape.get(expert_axis, 1)
        if n_exp > 1:
            if cfg.num_experts % n_exp:
                raise ValueError(
                    f"num_experts {cfg.num_experts} not divisible by "
                    f"mesh axis {expert_axis!r} ({n_exp})")
            expert = (expert_axis, n_exp)

    n_stages = mesh.shape["pipe"] * num_chunks
    per_stage = cfg.num_layers // n_stages
    stages = jax.tree.map(
        lambda a: a.reshape(n_stages, per_stage, *a.shape[1:]),
        params["layers"])
    # MoE expert weights shard their expert dim over `expert_axis`; the
    # router (and everything else) replicates over it.
    param_specs = None
    if expert is not None:
        param_specs = jax.tree.map(lambda _: None, stages)
        # Leaves are [n_stages, per_stage, E, ...]: entry 1 (per_stage)
        # replicates, entry 2 (experts) shards over the expert axis.
        param_specs["mlp"] = {
            k: ((None, expert_axis) if k in ("w_gate", "w_up", "w_down")
                else None)
            for k in stages["mlp"]}

    # The traveling microbatch: activations plus any packed metadata the
    # stages need (pipeline_apply treats the pytree opaquely).
    travel = {"h": x}
    if positions is not None or ring is not None:
        pos_in = (positions if positions is not None
                  else jnp.arange(s, dtype=jnp.int32)[None])
        travel["pos"] = jnp.broadcast_to(pos_in, (b, s))
    if segment_ids is not None:
        travel["seg"] = jnp.broadcast_to(segment_ids, (b, s))
    if is_moe:
        # Per-row aux accumulator: every row of a microbatch carries the
        # stage-summed Switch aux (identical values within a microbatch
        # x data shard) — a [mb] leaf rides the ring like everything else.
        travel["aux"] = jnp.zeros((b,), jnp.float32)
    # CP-inside-PP: sequence dims of the traveling leaves shard over the
    # seq axis; positions ALWAYS travel so each shard carries its global
    # offsets (RoPE + ring causal masking).
    travel_specs = None
    if ring is not None:
        travel_specs = {k: ((seq_axis, None) if k == "h" else (seq_axis,))
                        for k in travel}

    def stage_fn(sp, tr):
        h = tr["h"]
        pos = tr.get("pos")
        if pos is None:
            pos = jnp.broadcast_to(jnp.arange(s), (h.shape[0], s))
        seg = tr.get("seg")

        def body(carry, lp):
            hh, aux = carry
            hh, a = layer_fwd(cfg, lp, hh, cos, sin, pos, attn_impl,
                              segment_ids=seg, ring=ring, expert=expert)
            return (hh, aux + a), None

        (h, aux), _ = jax.lax.scan(
            body, (h, jnp.zeros((), jnp.float32)), sp)
        out = {**tr, "h": h}
        if "aux" in tr:
            out["aux"] = tr["aux"] + aux
        return out

    axes = ((data_axis,) if isinstance(data_axis, str)
            else tuple(data_axis or ()))
    dax = tuple(a for a in axes if mesh.shape[a] > 1) or None
    if dax is not None and len(dax) == 1:
        dax = dax[0]
    if num_chunks > 1:
        out = pipeline_apply_circular(
            stage_fn, stages, travel, mesh=mesh,
            num_microbatches=num_microbatches, num_chunks=num_chunks,
            data_axis=dax, travel_specs=travel_specs,
            param_specs=param_specs)
    else:
        out = pipeline_apply(
            stage_fn, stages, travel, mesh=mesh,
            num_microbatches=num_microbatches, data_axis=dax,
            travel_specs=travel_specs, param_specs=param_specs)
    x = out["h"]

    x = _rms(x, params["final_norm"]["scale"], cfg.rms_eps, dt,
             cfg.norm_plus_one)
    if return_hidden:
        result = x
    elif cfg.tie_embeddings:
        result = jnp.einsum("bsh,vh->bsv", x, embed.astype(dt))
    else:
        result = x @ params["lm_head"]["kernel"].astype(dt)
    if is_moe:
        # Rows within a (microbatch x data shard) carry identical values;
        # the global mean IS the mean over those sub-batches.
        return result, jnp.mean(out["aux"])
    return result
