"""Pipeline-parallel Llama: the scanned trunk partitioned over `pipe`.

SURVEY.md §2.6 PP row maps the reference's DeepSpeed/Megatron pipeline
engines (p2p microbatch send/recv inside user containers) to a compiled
stage-sharded schedule. parallel/pipeline.py provides the schedules (GPipe +
interleaved circular, AD straight through); this module binds them to the
REAL flagship model:

  * **Same parameter pytree as the scanned Llama** (models/llama.py with
    `scan_layers=True`): trunk leaves carry a leading `layers` dim L. PP is
    a *rules* change — logical axis `layers` maps to mesh axis `pipe`
    (sharding.py "pipeline" preset) — plus a reshape [L, ...] ->
    [stages, L/stages, ...] inside the step. Checkpoints, HF import, and
    the single-path model stay bit-identical; no second weight format.
  * **Embed / final-norm / unembed ride GSPMD outside the shard_map**: the
    pipeline region covers exactly the homogeneous trunk (constant
    activation shape), which is what the schedule requires; the vocab-sized
    ends keep their usual tensor/fsdp sharding rules and gradients
    all-reduce over `data` automatically.
  * **Per-layer forward is pure jnp** (no flax apply): inside the manual
    shard_map region, flax's logical-constraint machinery would try to
    issue auto-sharding constraints, which don't compose with manual axes.
    The math matches DecoderLayer exactly (RMSNorm fp32, RoPE fp32, GQA
    attention, SwiGLU in cfg.dtype).

Packed pre-training composes with PP: pass `positions` + `segment_ids`
and they ride the pipeline ring alongside the activations (a pytree
microbatch — parallel/pipeline.py), so each stage masks attention within
documents exactly like the scanned model. Block-sparse MaskSpecs
(cfg.mask_kind) flow into the stage attention the same way.

Scope (documented): dense Llama trunk, attention naive or flash. MoE-PP
and CP-inside-PP are future axes composition work (ops/ROADMAP.md).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.llama import LlamaConfig, apply_rope, rope_table
from kubeflow_tpu.ops.reference import naive_attention
from kubeflow_tpu.parallel.pipeline import (
    pipeline_apply, pipeline_apply_circular)


def _rms(x: jax.Array, scale: jax.Array, eps: float, dtype) -> jax.Array:
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (y * scale).astype(dtype)


def _resolve_attn(cfg: LlamaConfig) -> str:
    impl = cfg.attention_impl
    if impl == "auto":
        return ("flash" if jax.default_backend() in ("tpu", "axon")
                else "naive")
    if impl not in ("naive", "flash"):
        raise ValueError(
            f"pipeline parallelism supports attention_impl 'naive'/'flash' "
            f"(contiguous or packed causal sequences), not {impl!r}")
    return impl


def layer_fwd(cfg: LlamaConfig, lp: dict, x: jax.Array, cos: jax.Array,
              sin: jax.Array, positions: jax.Array,
              attn_impl: str = "naive",
              segment_ids: jax.Array | None = None) -> jax.Array:
    """One decoder layer, pure jnp. lp: the layer's param subtree (kernels
    exactly as flax lays them out: q/k/v [H, heads, D], o [heads, D, H],
    gate/up [H, M], down [M, H]); x [mb, S, H] in cfg.dtype.
    `segment_ids` [mb, S] confines attention within packed documents;
    cfg.mask_spec selects the block-sparse mask family — both match the
    scanned Attention module's semantics (models/llama.py)."""
    dt = cfg.dtype
    h = _rms(x, lp["input_norm"]["scale"], cfg.rms_eps, dt)
    q = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["q_proj"]["kernel"].astype(dt))
    k = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["k_proj"]["kernel"].astype(dt))
    v = jnp.einsum("bsh,hnd->bsnd", h, lp["attn"]["v_proj"]["kernel"].astype(dt))
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    mask = cfg.mask_spec
    if attn_impl == "flash":
        from kubeflow_tpu.ops.flash_attention import flash_attention
        attn = flash_attention(q, k, v, causal=True,
                               block_q=cfg.flash_block_q,
                               block_kv=cfg.flash_block_kv,
                               segment_ids=segment_ids, mask=mask)
    else:
        attn = naive_attention(q, k, v, causal=True, positions_q=positions,
                               positions_kv=positions,
                               segment_ids=segment_ids, mask=mask)
    attn = jnp.einsum("bsnd,ndh->bsh", attn,
                      lp["attn"]["o_proj"]["kernel"].astype(dt))
    x = x + attn
    h2 = _rms(x, lp["post_attn_norm"]["scale"], cfg.rms_eps, dt)
    gate = h2 @ lp["mlp"]["gate_proj"]["kernel"].astype(dt)
    up = h2 @ lp["mlp"]["up_proj"]["kernel"].astype(dt)
    return x + (jax.nn.silu(gate) * up) @ lp["mlp"]["down_proj"]["kernel"].astype(dt)


def pipeline_forward(
    cfg: LlamaConfig,
    params: Any,
    tokens: jax.Array,
    *,
    mesh,
    num_microbatches: int,
    num_chunks: int = 1,
    data_axis: str | tuple[str, ...] | None = ("data", "fsdp"),
    return_hidden: bool = False,
    positions: jax.Array | None = None,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Full causal-LM forward with the trunk pipelined over `pipe`.

    params: the SAME pytree the scanned Llama produces (trunk under
    params['layers'] with leading dim L). tokens [B, S]. Returns logits
    [B, S, V] (or post-norm hidden [B, S, H] with return_hidden for the
    chunked-CE path). Numerics match the non-pipelined model.

    Packed pre-training: pass per-document restarting `positions` and
    `segment_ids` [B, S] (data/loader.py packing) — they microbatch and
    travel the pipeline ring with the activations, so every stage applies
    the same RoPE offsets and within-document attention mask the scanned
    model would."""
    if cfg.num_layers % (mesh.shape["pipe"] * num_chunks):
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pipe "
            f"({mesh.shape['pipe']}) * chunks ({num_chunks})")
    attn_impl = _resolve_attn(cfg)
    if (attn_impl == "flash" and positions is not None
            and segment_ids is None):
        # Mirror the scanned Attention's refusal: the flash kernel masks
        # causality by array index, so custom positions need the segment
        # mask to carry document structure.
        raise ValueError(
            "pipeline flash attention with custom positions needs "
            "segment_ids (packed sequences)")
    dt = cfg.dtype
    b, s = tokens.shape
    embed = params["embed"]
    x = embed.astype(dt)[tokens]
    cos, sin = rope_table(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta, cfg)

    n_stages = mesh.shape["pipe"] * num_chunks
    per_stage = cfg.num_layers // n_stages
    stages = jax.tree.map(
        lambda a: a.reshape(n_stages, per_stage, *a.shape[1:]),
        params["layers"])

    # The traveling microbatch: activations plus any packed metadata the
    # stages need (pipeline_apply treats the pytree opaquely).
    travel = {"h": x}
    if positions is not None:
        travel["pos"] = jnp.broadcast_to(positions, (b, s))
    if segment_ids is not None:
        travel["seg"] = jnp.broadcast_to(segment_ids, (b, s))

    def stage_fn(sp, tr):
        h = tr["h"]
        pos = tr.get("pos")
        if pos is None:
            pos = jnp.broadcast_to(jnp.arange(s), (h.shape[0], s))
        seg = tr.get("seg")

        def body(carry, lp):
            return layer_fwd(cfg, lp, carry, cos, sin, pos, attn_impl,
                             segment_ids=seg), None

        h, _ = jax.lax.scan(body, h, sp)
        return {**tr, "h": h}

    axes = ((data_axis,) if isinstance(data_axis, str)
            else tuple(data_axis or ()))
    dax = tuple(a for a in axes if mesh.shape[a] > 1) or None
    if dax is not None and len(dax) == 1:
        dax = dax[0]
    if num_chunks > 1:
        out = pipeline_apply_circular(
            stage_fn, stages, travel, mesh=mesh,
            num_microbatches=num_microbatches, num_chunks=num_chunks,
            data_axis=dax)
    else:
        out = pipeline_apply(
            stage_fn, stages, travel, mesh=mesh,
            num_microbatches=num_microbatches, data_axis=dax)
    x = out["h"]

    x = _rms(x, params["final_norm"]["scale"], cfg.rms_eps, dt)
    if return_hidden:
        return x
    if cfg.tie_embeddings:
        return jnp.einsum("bsh,vh->bsv", x, embed.astype(dt))
    return x @ params["lm_head"]["kernel"].astype(dt)
