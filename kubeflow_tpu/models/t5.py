"""T5 encoder-decoder — the text2text model family.

The reference serves T5-class checkpoints through huggingfaceserver's
text2text_generation task (SURVEY.md §2.2 ⟨kserve:
python/huggingfaceserver⟩). This is a native flax implementation with the
T5 specifics that silently break naive ports: RMS layer norm in fp32 with
no mean subtraction, NO sqrt(d) attention scaling, bucketed relative
position bias owned by the first block of each stack (bidirectional for
the encoder, causal-asymmetric for the decoder, none for cross
attention), pre-LN residual blocks, and — when embeddings are tied — the
d_model**-0.5 logits rescale.

Generation is one XLA program end to end (`greedy_generate`): encoder,
per-layer cross K/V precompute, then a `lax.scan` over decoder steps with
a self-attention KV cache — no per-token host round trip, which on the
axon tunnel (~66 ms/fetch, PROFILE.md §1) is the difference between
serving and not.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6          # encoder
    num_decoder_layers: int = 6
    num_heads: int = 8
    rel_buckets: int = 32
    rel_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    feed_forward_proj: str = "relu"   # "relu" (v1.0) | "gated-gelu" (v1.1)
    # UMT5: EVERY layer owns its relative-position bias table (classic
    # T5/MT5 share block 0's across the stack).
    per_layer_rel_bias: bool = False
    tie_embeddings: bool = True
    decoder_start_id: int = 0
    eos_id: int = 1
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def gated(self) -> bool:
        return self.feed_forward_proj.startswith("gated")

    @property
    def num_params(self) -> int:
        e = self.vocab_size * self.d_model
        att = 4 * self.d_model * self.num_heads * self.d_kv
        ff = (3 if self.gated else 2) * self.d_model * self.d_ff
        enc = self.num_layers * (att + ff)
        dec = self.num_decoder_layers * (2 * att + ff)
        return e * (1 if self.tie_embeddings else 2) + enc + dec


def t5_small() -> T5Config:
    return T5Config()


def t5_tiny() -> T5Config:
    return T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                    num_layers=2, num_decoder_layers=2, num_heads=4,
                    rel_buckets=8, rel_max_distance=16)


class T5LayerNorm(nn.Module):
    """RMS norm, fp32 accumulation, no bias, no mean subtraction."""

    eps: float
    param_dtype: Any

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.with_logical_partitioning(
            nn.initializers.ones_init(), ("embed",)),
            (x.shape[-1],), self.param_dtype)
        dt = x.dtype
        xf = x.astype(jnp.float32)
        xf = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True)
                                + self.eps)
        return (xf * scale).astype(dt)


def relative_position_bucket(rel_pos, *, bidirectional: bool,
                             num_buckets: int, max_distance: int):
    """The T5 log-bucketed relative position → bucket index map
    (vectorized; matches the reference bucketing exactly, asserted by the
    torch-parity tests)."""
    ret = jnp.zeros_like(rel_pos)
    n = num_buckets
    if bidirectional:
        n = n // 2
        ret = ret + jnp.where(rel_pos > 0, n, 0)
        rel_pos = jnp.abs(rel_pos)
    else:
        rel_pos = -jnp.minimum(rel_pos, 0)
    max_exact = n // 2
    is_small = rel_pos < max_exact
    large = max_exact + (
        jnp.log(jnp.maximum(rel_pos, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact) * (n - max_exact)
    ).astype(rel_pos.dtype)
    large = jnp.minimum(large, n - 1)
    return ret + jnp.where(is_small, rel_pos, large)


class RelPosBias(nn.Module):
    """[heads, q_len, kv_len] additive bias from bucketed offsets."""

    cfg: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self, q_pos, kv_pos):
        cfg = self.cfg
        table = self.param("rel_embedding", nn.with_logical_partitioning(
            nn.initializers.normal(1.0), (None, "heads")),
            (cfg.rel_buckets, cfg.num_heads), cfg.param_dtype)
        rel = kv_pos[None, :] - q_pos[:, None]  # [Q, KV]
        bucket = relative_position_bucket(
            rel, bidirectional=self.bidirectional,
            num_buckets=cfg.rel_buckets, max_distance=cfg.rel_max_distance)
        return table[bucket].transpose(2, 0, 1).astype(cfg.dtype)


class T5Attention(nn.Module):
    """q @ k with NO sqrt(d) scaling; optional additive position bias.

    Projections live in setup so the cached decode path can call them
    individually (q/k/v on different tensors) outside a compact trace.
    """

    cfg: T5Config

    def setup(self):
        cfg = self.cfg
        proj = partial(
            nn.DenseGeneral, features=(cfg.num_heads, cfg.d_kv),
            use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(),
                ("qkv_embed", "heads", "kv")))
        self.q, self.k, self.v = proj(name="q"), proj(name="k"), proj(name="v")
        self.o = nn.DenseGeneral(
            features=cfg.d_model, axis=(-2, -1), use_bias=False,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("heads", "kv", "embed")),
            name="o")

    def __call__(self, x, kv, mask, bias=None):
        return self.finish(self.q(x), self.k(kv), self.v(kv), mask, bias)

    def finish(self, q, k, v, mask, bias=None):
        """Score/softmax/project half — shared by the cached decode path,
        which computes k/v against the cache instead."""
        cfg = self.cfg
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        if bias is not None:
            scores = scores + bias.astype(jnp.float32)
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return self.o(out)


class T5FFN(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype)
        up = dict(kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("embed", "mlp")))
        if cfg.gated:
            h = (nn.gelu(dense(cfg.d_ff, **up, name="wi_0")(x),
                         approximate=True)
                 * dense(cfg.d_ff, **up, name="wi_1")(x))
        else:
            h = nn.relu(dense(cfg.d_ff, **up, name="wi")(x))
        return dense(cfg.d_model, kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("mlp", "embed")),
            name="wo")(h)


class T5(nn.Module):
    """Teacher-forced forward: `__call__(input_ids, decoder_input_ids)` →
    logits [B, T, V]. Generation goes through `greedy_generate` (module
    methods `encode` / `cross_kv` / `decode_step` compose the one-program
    decode loop)."""

    cfg: T5Config

    def setup(self):
        cfg = self.cfg
        self.shared = self.param(
            "shared_embedding", nn.with_logical_partitioning(
                nn.initializers.normal(1.0), ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        ln = partial(T5LayerNorm, eps=cfg.layer_norm_eps,
                     param_dtype=cfg.param_dtype)
        if cfg.per_layer_rel_bias:  # UMT5: one table per layer
            self.enc_rels = [RelPosBias(cfg, bidirectional=True,
                                        name=f"enc_{i}_rel")
                             for i in range(cfg.num_layers)]
            self.dec_rels = [RelPosBias(cfg, bidirectional=False,
                                        name=f"dec_{i}_rel")
                             for i in range(cfg.num_decoder_layers)]
        else:
            self.enc_rel = RelPosBias(cfg, bidirectional=True,
                                      name="enc_rel")
            self.dec_rel = RelPosBias(cfg, bidirectional=False,
                                      name="dec_rel")
        self.enc_attn = [T5Attention(cfg, name=f"enc_{i}_attn")
                         for i in range(cfg.num_layers)]
        self.enc_attn_ln = [ln(name=f"enc_{i}_attn_ln")
                            for i in range(cfg.num_layers)]
        self.enc_ffn = [T5FFN(cfg, name=f"enc_{i}_ffn")
                        for i in range(cfg.num_layers)]
        self.enc_ffn_ln = [ln(name=f"enc_{i}_ffn_ln")
                           for i in range(cfg.num_layers)]
        self.enc_final_ln = ln(name="enc_final_ln")
        d = cfg.num_decoder_layers
        self.dec_self = [T5Attention(cfg, name=f"dec_{i}_self")
                         for i in range(d)]
        self.dec_self_ln = [ln(name=f"dec_{i}_self_ln") for i in range(d)]
        self.dec_cross = [T5Attention(cfg, name=f"dec_{i}_cross")
                          for i in range(d)]
        self.dec_cross_ln = [ln(name=f"dec_{i}_cross_ln") for i in range(d)]
        self.dec_ffn = [T5FFN(cfg, name=f"dec_{i}_ffn") for i in range(d)]
        self.dec_ffn_ln = [ln(name=f"dec_{i}_ffn_ln") for i in range(d)]
        self.dec_final_ln = ln(name="dec_final_ln")
        if not cfg.tie_embeddings:
            self.lm_head = self.param(
                "lm_head", nn.with_logical_partitioning(
                    nn.initializers.normal(1.0), ("embed", "vocab")),
                (cfg.d_model, cfg.vocab_size), cfg.param_dtype)

    # -- encoder ------------------------------------------------------------

    def encode(self, input_ids, enc_mask=None):
        cfg = self.cfg
        b, s = input_ids.shape
        if enc_mask is None:
            enc_mask = jnp.ones((b, s), jnp.bool_)
        x = self.shared[input_ids].astype(cfg.dtype)
        pos = jnp.arange(s)
        bias = (None if cfg.per_layer_rel_bias
                else self.enc_rel(pos, pos)[None])   # [1, H, S, S]
        mask = enc_mask[:, None, None, :]            # [B, 1, 1, S]
        for i in range(cfg.num_layers):
            b_i = (self.enc_rels[i](pos, pos)[None]
                   if cfg.per_layer_rel_bias else bias)
            h = self.enc_attn_ln[i](x)
            x = x + self.enc_attn[i](h, h, mask, b_i)
            x = x + self.enc_ffn[i](self.enc_ffn_ln[i](x))
        return self.enc_final_ln(x)

    # -- decoder ------------------------------------------------------------

    def _logits(self, x):
        cfg = self.cfg
        if cfg.tie_embeddings:
            # The tied head includes the T5 d_model**-0.5 rescale.
            x = x * (cfg.d_model ** -0.5)
            return jnp.einsum("btd,vd->btv", x,
                              self.shared.astype(cfg.dtype)
                              ).astype(jnp.float32)
        return jnp.einsum("btd,dv->btv", x,
                          self.lm_head.astype(cfg.dtype)
                          ).astype(jnp.float32)

    def decode(self, decoder_input_ids, enc_out, enc_mask):
        """Teacher-forced decoder pass → logits [B, T, V]."""
        cfg = self.cfg
        b, t = decoder_input_ids.shape
        x = self.shared[decoder_input_ids].astype(cfg.dtype)
        pos = jnp.arange(t)
        bias = (None if cfg.per_layer_rel_bias
                else self.dec_rel(pos, pos)[None])
        causal = (pos[:, None] >= pos[None, :])[None, None]
        cross_mask = enc_mask[:, None, None, :]
        for i in range(cfg.num_decoder_layers):
            b_i = (self.dec_rels[i](pos, pos)[None]
                   if cfg.per_layer_rel_bias else bias)
            h = self.dec_self_ln[i](x)
            x = x + self.dec_self[i](h, h, causal, b_i)
            x = x + self.dec_cross[i](self.dec_cross_ln[i](x), enc_out,
                                      cross_mask)
            x = x + self.dec_ffn[i](self.dec_ffn_ln[i](x))
        return self._logits(self.dec_final_ln(x))

    def __call__(self, input_ids, decoder_input_ids, enc_mask=None):
        b, s = input_ids.shape
        if enc_mask is None:
            enc_mask = jnp.ones((b, s), jnp.bool_)
        return self.decode(decoder_input_ids,
                           self.encode(input_ids, enc_mask), enc_mask)

    # -- one-program greedy decode parts ------------------------------------

    def cross_kv(self, enc_out):
        """Per-layer cross-attention K/V, computed once per request."""
        return [(self.dec_cross[i].k(enc_out), self.dec_cross[i].v(enc_out))
                for i in range(self.cfg.num_decoder_layers)]

    def decode_step(self, tok, cache_k, cache_v, pos, enc_mask, cross):
        """One decoder step at position `pos` (scalar): tok [B, 1] →
        (logits [B, V], updated caches). cache_k/v: [L, B, T_max, H, Dk]."""
        cfg = self.cfg
        x = self.shared[tok].astype(cfg.dtype)     # [B, 1, D]
        t_max = cache_k.shape[2]
        kv_pos = jnp.arange(t_max)
        bias = (None if cfg.per_layer_rel_bias
                else self.dec_rel(pos[None], kv_pos)[None])  # [1,H,1,T]
        self_mask = (kv_pos <= pos)[None, None, None, :]
        cross_mask = enc_mask[:, None, None, :]
        for i in range(cfg.num_decoder_layers):
            b_i = (self.dec_rels[i](pos[None], kv_pos)[None]
                   if cfg.per_layer_rel_bias else bias)
            attn = self.dec_self[i]
            h = self.dec_self_ln[i](x)
            q, k1, v1 = attn.q(h), attn.k(h), attn.v(h)
            cache_k = jax.lax.dynamic_update_slice(
                cache_k, k1[None].astype(cache_k.dtype), (i, 0, pos, 0, 0))
            cache_v = jax.lax.dynamic_update_slice(
                cache_v, v1[None].astype(cache_v.dtype), (i, 0, pos, 0, 0))
            x = x + attn.finish(q, cache_k[i].astype(cfg.dtype),
                                cache_v[i].astype(cfg.dtype),
                                self_mask, b_i)
            cattn = self.dec_cross[i]
            cq = cattn.q(self.dec_cross_ln[i](x))
            ckk, cvv = cross[i]
            x = x + cattn.finish(cq, ckk, cvv, cross_mask)
            x = x + self.dec_ffn[i](self.dec_ffn_ln[i](x))
        logits = self._logits(self.dec_final_ln(x))[:, 0]
        return logits, cache_k, cache_v


def greedy_generate(model: T5, params, input_ids, enc_mask=None, *,
                    max_tokens: int):
    """Whole greedy decode as ONE jittable program: encoder + cross-KV
    precompute + a lax.scan over `max_tokens` decoder steps with a
    self-attention KV cache. Emission stops advancing at EOS (tokens after
    are padded with eos_id); returns (tokens [B, max_tokens],
    n_valid [B])."""
    cfg = model.cfg
    b, s = input_ids.shape
    if enc_mask is None:
        enc_mask = jnp.ones((b, s), jnp.bool_)

    enc_out = model.apply({"params": params}, input_ids, enc_mask,
                          method=T5.encode)
    cross = model.apply({"params": params}, enc_out, method=T5.cross_kv)
    L, H, Dk = cfg.num_decoder_layers, cfg.num_heads, cfg.d_kv
    cache_k = jnp.zeros((L, b, max_tokens, H, Dk), cfg.dtype)
    cache_v = jnp.zeros_like(cache_k)

    def step(carry, pos):
        tok, ck, cv, done = carry
        logits, ck, cv = model.apply(
            {"params": params}, tok, ck, cv, pos, enc_mask, cross,
            method=T5.decode_step)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        nxt = jnp.where(done, jnp.int32(cfg.eos_id), nxt)
        done = jnp.logical_or(done, nxt == cfg.eos_id)
        return (nxt[:, None], ck, cv, done), nxt

    start = jnp.full((b, 1), cfg.decoder_start_id, jnp.int32)
    (_, _, _, done), toks = jax.lax.scan(
        step, (start, cache_k, cache_v, jnp.zeros((b,), jnp.bool_)),
        jnp.arange(max_tokens))
    toks = toks.T  # [B, max_tokens]
    n_valid = jnp.where(
        (toks == cfg.eos_id).any(1),
        jnp.argmax(toks == cfg.eos_id, 1), max_tokens)
    return toks, n_valid
