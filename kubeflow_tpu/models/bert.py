"""BERT-class bidirectional encoder — the serving model.

Eval config 3 is "KServe InferenceService: BERT-base predictor on TPU v5e"
(BASELINE.json). The reference serves BERT through KServe's huggingfaceserver
/ Triton runtimes (SURVEY.md §2.2); here it is a native flax model that the
serve/ runtime AOT-compiles per shape bucket.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    # "gelu" is EXACT erf GELU — the canonical BERT activation and what HF
    # checkpoints mean by it. (Changed round 2 from flax's tanh-approx
    # default; no exported checkpoints predate the change.) Also accepts
    # "gelu_new"/"gelu_pytorch_tanh" (tanh approximation) and "relu".
    hidden_act: str = "gelu"
    num_labels: int = 2  # classification head
    # Pooler-free classification exports exist (the classifier was trained
    # on the RAW [CLS] hidden state): use_pooler=False skips the
    # dense+tanh entirely — an identity-kernel pooler would still apply
    # tanh and silently deviate from the source model's logits.
    use_pooler: bool = True
    # Serving task — the reference's huggingfaceserver task surface
    # (SURVEY.md §2.2 ⟨kserve: python/huggingfaceserver⟩ supports
    # sequence_classification / token_classification / fill_mask /
    # embedding for encoder checkpoints). Selects the head:
    #   sequence_classification → pooled logits [B, num_labels]
    #   token_classification    → per-token logits [B, S, num_labels]
    #   fill_mask               → MLM logits [B, S, vocab] (tied decoder)
    #   embedding               → masked-mean L2-normalized [B, H]
    task: str = "sequence_classification"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def _activation(cfg: BertConfig, h):
    """The checkpoint's hidden activation — shared by the encoder FFN and
    the MLM transform so the two can never drift."""
    if cfg.hidden_act == "gelu":  # exact erf GELU (BERT canonical)
        return nn.gelu(h, approximate=False)
    if cfg.hidden_act in ("gelu_new", "gelu_pytorch_tanh"):
        return nn.gelu(h, approximate=True)
    if cfg.hidden_act == "relu":
        return nn.relu(h)
    raise ValueError(f"unsupported hidden_act {cfg.hidden_act!r}")


def bert_base(num_labels: int = 2) -> BertConfig:
    return BertConfig(num_labels=num_labels)


def bert_tiny() -> BertConfig:
    return BertConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, max_seq_len=64)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = partial(nn.DenseGeneral, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        q = dense(features=(cfg.num_heads, head_dim),
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("qkv_embed", "heads", "kv")),
                  name="q")(x)
        k = dense(features=(cfg.num_heads, head_dim),
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("qkv_embed", "heads", "kv")),
                  name="k")(x)
        v = dense(features=(cfg.num_heads, head_dim),
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("qkv_embed", "heads", "kv")),
                  name="v")(x)
        scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
        scores = scores / jnp.sqrt(head_dim)
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bhst,bthd->bshd", probs, v)
        attn = dense(features=cfg.hidden_size, axis=(-2, -1),
                     kernel_init=nn.with_logical_partitioning(
                         nn.initializers.lecun_normal(), ("heads", "kv", "embed")),
                     name="o")(attn)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_attn")(x + attn)
        h = dense(features=cfg.intermediate_size,
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("embed", "mlp")),
                  name="ffn_in")(x)
        h = _activation(cfg, h)
        h = dense(features=cfg.hidden_size,
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("mlp", "embed")),
                  name="ffn_out")(h)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            name="ln_ffn")(x + h)


class Bert(nn.Module):
    """Returns (sequence_output [B,S,H], head_output) — the head depends on
    cfg.task (see BertConfig.task); the default sequence_classification
    head yields pooled logits [B, num_labels]."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.cfg
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((b, s), jnp.bool_)
        else:
            attention_mask = attention_mask.astype(jnp.bool_)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((b, s), jnp.int32)
        emb = self.param("word_embeddings", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        pos = self.param("position_embeddings", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (None, "embed")),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype)
        typ = self.param("token_type_embeddings", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (None, "embed")),
            (cfg.type_vocab_size, cfg.hidden_size), cfg.param_dtype)
        x = emb[input_ids] + pos[jnp.arange(s)][None] + typ[token_type_ids]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_embed")(x.astype(cfg.dtype))
        for i in range(cfg.num_layers):
            x = EncoderLayer(cfg, name=f"layer_{i}")(x, attention_mask)

        if cfg.task == "token_classification":
            # Per-token head: same classifier params as HF's
            # BertForTokenClassification (Dense over every position).
            logits = nn.Dense(
                cfg.num_labels, dtype=jnp.float32,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "vocab")),
                name="classifier")(x)
            return x, logits

        if cfg.task == "fill_mask":
            # BertOnlyMLMHead: transform (dense+act+LN), then a decoder
            # TIED to word_embeddings plus a free output bias — the tie is
            # structural (same param), so a quantized or updated embedding
            # stays consistent with the decoder.
            h = nn.Dense(
                cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "embed2")),
                name="mlm_transform")(x)
            h = _activation(cfg, h)
            h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             name="mlm_ln")(h)
            bias = self.param("mlm_bias", nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("vocab",)),
                (cfg.vocab_size,), cfg.param_dtype)
            logits = (jnp.einsum("bsh,vh->bsv", h,
                                 emb.astype(cfg.dtype)).astype(jnp.float32)
                      + bias)
            return x, logits

        if cfg.task == "embedding":
            # Sentence-embedding head: attention-masked mean pooling over
            # the sequence output, L2-normalized (the sentence-transformers
            # convention the reference's embedding task follows). Computed
            # in fp32 — the norm of a bf16 sum drifts visibly at S=512.
            m = attention_mask[..., None].astype(jnp.float32)
            xf = x.astype(jnp.float32)
            pooled = (xf * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1e-9)
            normed = pooled / jnp.maximum(
                jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)
            return x, normed

        if cfg.task != "sequence_classification":
            raise ValueError(f"unknown task {cfg.task!r}")
        if cfg.use_pooler:
            pooled = nn.tanh(nn.Dense(
                cfg.hidden_size, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "embed2")),
                name="pooler")(x[:, 0]))
        else:
            pooled = x[:, 0]
        logits = nn.Dense(
            cfg.num_labels, dtype=jnp.float32, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")),
            name="classifier")(pooled)
        return x, logits
