"""Mixture-of-experts decoder — expert parallelism (EP) as a first-class
strategy (SURVEY.md §2.6: the reference launches DeepSpeed-MoE inside user
containers; here EP is native).

TPU-first design: GShard/Switch-style *capacity-based dense dispatch* —
routing becomes two einsums against one-hot dispatch/combine tensors, which
XLA maps onto the MXU and, when the `expert` mesh axis is sharded, lowers
the dispatch contraction into the expert all-to-all automatically. No
ragged/dynamic shapes anywhere (XLA requirement), tokens over capacity are
dropped (Switch semantics), and a Switch-style load-balancing auxiliary
loss (sown into the `aux_loss` collection, picked up by the train-step
factory) keeps routing uniform so drops stay rare.

Architecture mirrors Mixtral: the Llama trunk with every layer's FFN
replaced by top-k routed SwiGLU experts.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.llama import Llama, LlamaConfig


@dataclasses.dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    num_experts: int = 8
    experts_per_token: int = 2     # top-k routing (Mixtral: 2)
    capacity_factor: float = 1.25  # buffer slack over perfect balance
    router_aux_coef: float = 0.01  # Switch load-balance loss weight
    # Mixtral renormalizes the top-k gate values to sum 1; Qwen2-MoE's
    # default (norm_topk_prob=false) keeps the raw softmax mass.
    norm_topk_prob: bool = True
    # Qwen2-MoE shared expert: an always-on SwiGLU FFN of this width
    # whose output is scaled by a learned sigmoid gate (0 = none).
    shared_expert_size: int = 0

    def _shared_params(self) -> int:
        if not self.shared_expert_size:
            return 0
        return 3 * self.hidden_size * self.shared_expert_size \
            + self.hidden_size  # + the [H, 1] sigmoid gate

    @property
    def num_params(self) -> int:
        h, m, v = self.hidden_size, self.intermediate_size, self.vocab_size
        qkv = (h * self.num_heads * self.head_dim
               + 2 * h * self.num_kv_heads * self.head_dim)
        attn = qkv + self.num_heads * self.head_dim * h
        if self.attention_bias:
            attn += (self.num_heads + 2 * self.num_kv_heads) * self.head_dim
        experts = self.num_experts * 3 * h * m
        router = h * self.num_experts
        per_layer = attn + experts + router + 2 * h + self._shared_params()
        emb = v * h * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + emb + h

    @property
    def active_params(self) -> int:
        """Params touched per token (for MFU accounting of sparse models)."""
        h, m, v = self.hidden_size, self.intermediate_size, self.vocab_size
        qkv = (h * self.num_heads * self.head_dim
               + 2 * h * self.num_kv_heads * self.head_dim)
        attn = qkv + self.num_heads * self.head_dim * h
        if self.attention_bias:
            attn += (self.num_heads + 2 * self.num_kv_heads) * self.head_dim
        experts = self.experts_per_token * 3 * h * m
        per_layer = (attn + experts + h * self.num_experts + 2 * h
                     + self._shared_params())
        emb = v * h * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + emb + h


def mixtral_8x7b() -> MoEConfig:
    return MoEConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
        max_seq_len=8192, rope_theta=1e6, num_experts=8,
        experts_per_token=2)


def moe_tiny(vocab: int = 512) -> MoEConfig:
    """Test-size config — same routing topology, toy dims."""
    return MoEConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        max_seq_len=128, remat=False, num_experts=4, experts_per_token=2,
        flash_block_q=64, flash_block_kv=64)


def expert_capacity(cfg: MoEConfig, seq_len: int) -> int:
    """Per-(batch-row) expert buffer: perfect balance needs K*S/E slots;
    capacity_factor adds slack before tokens drop."""
    return max(1, int(math.ceil(
        seq_len * cfg.experts_per_token / cfg.num_experts
        * cfg.capacity_factor)))


def gshard_route(x: jax.Array, w_router: jax.Array, K: int, C: int,
                 renormalize: bool = True):
    """GShard/Switch capacity routing, pure jnp — shared by the flax
    MoEBlock and the pipeline stage body (models/llama_pp.py MoE-PP), so
    the two paths cannot drift.

    x [B, S, H] (any dtype; router runs fp32), w_router [H, E] fp32.
    Returns (dispatch [B,S,E,C], combine [B,S,E,C], aux scalar) where aux
    is the UNWEIGHTED Switch load-balance term E * Σ_e frac_e · mean_prob_e
    (caller applies router_aux_coef). `renormalize` scales the top-k gate
    values to sum 1 (Mixtral); Qwen2-MoE's norm_topk_prob=false keeps the
    raw softmax mass."""
    E = w_router.shape[-1]
    logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32),
                        w_router.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)            # [B,S,E]
    gate_vals, expert_idx = jax.lax.top_k(probs, K)    # [B,S,K]
    if renormalize:
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    B, S = x.shape[0], x.shape[1]
    # Capacity assignment, slot-major (GShard): slot-0 choices claim
    # buffer positions first, then slot-1, each in sequence order.
    dispatch = jnp.zeros((B, S, E, C), jnp.float32)
    combine = jnp.zeros((B, S, E, C), jnp.float32)
    count = jnp.zeros((B, 1, E), jnp.float32)  # claimed so far
    for k in range(K):
        mask_e = jax.nn.one_hot(expert_idx[:, :, k], E)       # [B,S,E]
        pos = jnp.cumsum(mask_e, axis=1) - mask_e + count     # [B,S,E]
        count = count + jnp.sum(mask_e, axis=1, keepdims=True)
        keep = mask_e * (pos < C)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), C) * keep[..., None]
        dispatch = dispatch + slot                            # [B,S,E,C]
        combine = combine + gate_vals[:, :, k, None, None] * slot

    # Switch aux loss: E * Σ_e (token fraction to e) · (mean prob of e).
    frac = jnp.mean(jax.nn.one_hot(expert_idx[:, :, 0], E), axis=(0, 1))
    mean_prob = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def shared_expert_ffn(x, w_gate, w_up, w_down, gate_w, dtype):
    """Qwen2-MoE's always-on shared expert, pure jnp — one definition
    shared by the flax MoEBlock and the pipeline stage body
    (models/llama_pp.py _moe_ffn) so the two paths cannot drift (same
    contract as gshard_route): dense SwiGLU scaled by a learned
    per-token sigmoid gate (fp32 sigmoid). x [.., H]; w_gate/w_up
    [H, Ms]; w_down [Ms, H]; gate_w [H, 1]."""
    xd = x.astype(dtype)
    sh = (jax.nn.silu(xd @ w_gate.astype(dtype))
          * (xd @ w_up.astype(dtype))) @ w_down.astype(dtype)
    gate = jax.nn.sigmoid((xd @ gate_w.astype(dtype)).astype(jnp.float32))
    return sh * gate.astype(dtype)


class MoEBlock(nn.Module):
    """Top-k routed SwiGLU experts with capacity-based dispatch."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x: jax.Array, adapter=None,
                 adapter_ids=None) -> jax.Array:  # [B, S, H]
        cfg = self.cfg
        if adapter is not None:
            raise ValueError(
                "multi-LoRA adapters don't apply to routed-expert FFNs "
                "(use attention-only adapters with MoE models)")
        B, S, H = x.shape
        E, K = cfg.num_experts, cfg.experts_per_token
        C = expert_capacity(cfg, S)

        # Router in fp32 (small matmul; numerics matter more than MXU).
        w_router = self.param(
            "router", nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", None)),
            (H, E), jnp.float32)
        dispatch, combine, aux = gshard_route(
            x, w_router, K, C, renormalize=cfg.norm_topk_prob)
        self.sow("aux_loss", "router", cfg.router_aux_coef * aux)

        # Dispatch → per-expert batches [E,B,C,H]; with `expert` sharded
        # this contraction IS the all-to-all (GSPMD inserts it).
        xin = jnp.einsum("bsec,bsh->ebch", dispatch.astype(cfg.dtype),
                         x.astype(cfg.dtype))
        xin = nn.with_logical_constraint(
            xin, ("expert", "batch", None, None))

        dense_init = nn.initializers.lecun_normal()
        w_gate = self.param(
            "w_gate", nn.with_logical_partitioning(
                dense_init, ("expert", "embed", "expert_mlp")),
            (E, H, cfg.intermediate_size), cfg.param_dtype)
        w_up = self.param(
            "w_up", nn.with_logical_partitioning(
                dense_init, ("expert", "embed", "expert_mlp")),
            (E, H, cfg.intermediate_size), cfg.param_dtype)
        w_down = self.param(
            "w_down", nn.with_logical_partitioning(
                dense_init, ("expert", "expert_mlp", "embed")),
            (E, cfg.intermediate_size, H), cfg.param_dtype)

        g = jnp.einsum("ebch,ehm->ebcm", xin, w_gate.astype(cfg.dtype))
        u = jnp.einsum("ebch,ehm->ebcm", xin, w_up.astype(cfg.dtype))
        h = nn.silu(g) * u
        h = nn.with_logical_constraint(
            h, ("expert", "batch", None, "expert_mlp"))
        out = jnp.einsum("ebcm,emh->ebch", h, w_down.astype(cfg.dtype))

        # Combine back to token order (the return all-to-all).
        y = jnp.einsum("bsec,ebch->bsh", combine.astype(cfg.dtype), out)

        if cfg.shared_expert_size:
            # Qwen2-MoE shared expert: an always-on dense SwiGLU whose
            # output is scaled by a learned per-token sigmoid gate —
            # replicated over `expert` (every rank computes it; it's the
            # dense fraction of the FLOPs), sharded like a dense MLP.
            ms = cfg.shared_expert_size
            ws_gate = self.param(
                "w_shared_gate", nn.with_logical_partitioning(
                    dense_init, ("embed", "mlp")), (H, ms), cfg.param_dtype)
            ws_up = self.param(
                "w_shared_up", nn.with_logical_partitioning(
                    dense_init, ("embed", "mlp")), (H, ms), cfg.param_dtype)
            ws_down = self.param(
                "w_shared_down", nn.with_logical_partitioning(
                    dense_init, ("mlp", "embed")), (ms, H), cfg.param_dtype)
            w_sgate = self.param(
                "shared_gate", nn.with_logical_partitioning(
                    dense_init, ("embed", None)), (H, 1), cfg.param_dtype)
            y = y + shared_expert_ffn(x, ws_gate, ws_up, ws_down, w_sgate,
                                      cfg.dtype)
        return y.astype(cfg.dtype)


def MoELlama(cfg: MoEConfig, **kwargs: Any) -> Llama:
    """Mixtral-family causal LM: Llama trunk + routed-expert FFNs."""
    return Llama(cfg, mlp_cls=MoEBlock, **kwargs)
