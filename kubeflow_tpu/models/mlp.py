"""MNIST-class MLP — the smoke-test model.

Fills the role of the reference's MNIST examples (training-operator
examples/, used by its e2e suite; SURVEY.md §2.1) and eval config 1
(TFJob MNIST single-worker CPU).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: tuple[int, ...] = (256, 128)
    num_classes: int = 10
    dtype: Any = jnp.float32


class MLP(nn.Module):
    cfg: MLPConfig = MLPConfig()

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = x.reshape(x.shape[0], -1).astype(cfg.dtype)
        for i, h in enumerate(cfg.hidden):
            x = nn.Dense(
                h, dtype=cfg.dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "mlp")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros, ("mlp",)),
                name=f"dense_{i}")(x)
            x = nn.relu(x)
        return nn.Dense(
            cfg.num_classes, dtype=cfg.dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("vocab",)),
            name="head")(x)
