"""Llama-class decoder-only transformer, TPU-first.

This is the flagship training model (north star: Llama-3-8B fine-tune via
JAXJob; BASELINE.md). The reference platform never owned a model — PyTorchJob
launched user containers holding HF/Megatron code (SURVEY.md §2.6). Here the
model is part of the framework, designed for XLA/TPU:

  * params annotated with logical axes (parallel/sharding.py rules engine)
    so DP/FSDP/TP/SP compose via GSPMD instead of NCCL process groups;
  * layers rolled into one `nn.scan` — O(1) HLO size in depth, fast compiles;
  * bfloat16 activations/matmuls (MXU-native), fp32 RMSNorm/softmax/rope;
  * selectable attention impl: naive einsum, Pallas flash kernel, or ring
    attention over the `seq` mesh axis for long context (SURVEY.md §5.7);
  * `jax.checkpoint` (remat) policy per block to trade FLOPs for HBM.

GQA, RoPE, SwiGLU, RMSNorm match the Llama-3 architecture family.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.parallel.sharding import logical_to_spec


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    # Llama-3.1-style rope scaling (the "llama3" rope_type): a one-time
    # remap of the inverse frequencies. factor == 1.0 disables it. Scalars
    # (not a dict) so the config stays hashable for jit-static use.
    rope_scaling_factor: float = 1.0
    rope_scaling_low_freq_factor: float = 1.0
    rope_scaling_high_freq_factor: float = 4.0
    rope_scaling_original_max_len: int = 8192
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # QKV projection biases (Qwen2-family checkpoints; o_proj stays
    # bias-free, matching HF).
    attention_bias: bool = False
    # Gemma-family conventions (models/hf_import.py import_gemma): RMSNorm
    # applies (1 + w); token embeddings scale by sqrt(hidden) at input;
    # the MLP gate activation is tanh-approximate GeLU instead of SiLU.
    norm_plus_one: bool = False
    embed_scale: bool = False
    mlp_act: str = "silu"  # silu | gelu_tanh
    # Gemma-2 conventions (import_gemma2): sandwich norms add a norm on
    # the attention/MLP OUTPUTS before the residual add (HF
    # post_attention_layernorm / post_feedforward_layernorm; our
    # post_attn_norm then plays HF's pre_feedforward_layernorm role);
    # attention scores and final logits pass through tanh soft-caps; the
    # score scale is query_pre_attn_scalar^-0.5 instead of head_dim^-0.5.
    sandwich_norms: bool = False
    attn_softcap: float = 0.0    # 0 = off
    final_softcap: float = 0.0   # 0 = off
    query_pre_attn_scalar: float = 0.0  # 0 = use head_dim
    # Which layers the sliding_window mask applies to: "all" (Mistral),
    # "even" (Gemma-2: layers 0,2,4,... sliding), or "5to1" (Gemma-3:
    # every 6th layer full, the rest sliding — HF layer_types). Non-"all"
    # patterns thread a per-layer traced flag through the scanned trunk,
    # so they run on the einsum attention path only.
    sliding_pattern: str = "all"
    # Gemma-3 conventions (import_gemma3): RMSNorm ((1+w), fp32) on the
    # projected q/k heads before RoPE; TWO rope bases — sliding layers
    # use rope_theta_local (0 = single-table models), full layers use
    # rope_theta with an optional LINEAR position scaling.
    qk_norm: bool = False
    rope_theta_local: float = 0.0
    rope_global_scaling_factor: float = 1.0
    # LoRA fine-tuning (the reference SDK's PEFT LoraConfig): rank 0 = off.
    # Adapters add (x @ A) @ B * alpha/rank to the target projections —
    # q/v (PEFT's Llama default) for "attn", plus gate/up/down for
    # "attn_mlp". B starts at zero, so step 0 equals the base model; the
    # train step freezes everything but *_lora_* leaves (train/lora.py).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: str = "attn"  # attn | attn_mlp
    # auto | naive | flash | ring | ring_flash | zigzag | zigzag_flash
    # (*_flash = fused Pallas inner block per ring step)
    attention_impl: str = "auto"
    remat: bool = True
    # Which residuals the remat'd backward may keep: "nothing" (recompute
    # the whole block — minimum memory, ~2 extra fwd FLOP-shares), "dots"
    # (save matmul outputs — recompute only elementwise, costs activation
    # memory), "dots_no_batch" (save only weight-stationary dots),
    # "save_attn" (keep attention outputs so bwd skips re-running the
    # attention kernel — wins only on HBM-rich parts; PROFILE.md §4).
    remat_policy: str = "nothing"
    scan_layers: bool = True
    # flash-kernel block sizes (tuned for v5e/v5p VMEM; ops/flash_attention.py)
    flash_block_q: int = 512
    flash_block_kv: int = 512
    # Block-sparse attention mask family (ops/flash_attention.MaskSpec):
    # causal | full | prefix_lm | sliding_window. Scalars (not a MaskSpec)
    # so the config stays hashable/serializable; see mask_spec below.
    mask_kind: str = "causal"
    mask_window: int = 0
    mask_prefix: int = 0
    # Weight-only int8 serving (serve/quant.py): dense/embed sites
    # consume Int8Leaf params natively — raw-int8 matmul operands with
    # the per-channel scale applied OUTPUT-side, so no full-size
    # dequantized weight is ever materialized (the SERVEBENCH 0.747x
    # fix). Only QuantizedModule sets this; the default False path
    # constructs exactly the historical modules.
    quantized_dense: bool = False

    @property
    def mask_spec(self):
        """MaskSpec for non-default masks, None for plain causal (the
        fast path keeps its historical call signatures)."""
        if self.mask_kind == "causal":
            return None
        from kubeflow_tpu.ops.flash_attention import MaskSpec
        return MaskSpec(self.mask_kind, window=self.mask_window,
                        prefix=self.mask_prefix)

    @property
    def num_params(self) -> int:
        """Parameter count (for MFU accounting; BASELINE.md formula)."""
        h, m, v = self.hidden_size, self.intermediate_size, self.vocab_size
        qkv = h * self.num_heads * self.head_dim + 2 * h * self.num_kv_heads * self.head_dim
        attn = qkv + self.num_heads * self.head_dim * h
        if self.attention_bias:
            attn += (self.num_heads + 2 * self.num_kv_heads) * self.head_dim
        mlp = 3 * h * m
        norms = 2 * h
        per_layer = attn + mlp + norms
        emb = v * h * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + emb + h


def _dense_cls(cfg: LlamaConfig):
    """The projection layer class: `nn.DenseGeneral` normally, its
    Int8Leaf-aware twin under quantized serving (cfg.quantized_dense —
    see serve/quant.py Int8DenseGeneral: raw-int8 matmul operand,
    output-side scale). Resolved per call so the default path has zero
    import-time coupling to the serve package."""
    if not cfg.quantized_dense:
        return nn.DenseGeneral
    from kubeflow_tpu.serve.quant import Int8DenseGeneral
    return Int8DenseGeneral


def llama3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama_tiny(vocab: int = 512) -> LlamaConfig:
    """Test-size config — same topology, toy dims."""
    return LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16, max_seq_len=128,
        remat=False, flash_block_q=64, flash_block_kv=64)


def llama_1b() -> LlamaConfig:
    """Bench-size config that fits a single emulated v5e chip."""
    return LlamaConfig(
        vocab_size=32768, hidden_size=2048, intermediate_size=5632,
        num_layers=16, num_heads=16, num_kv_heads=8, head_dim=128,
        max_seq_len=2048)


class RMSNorm(nn.Module):
    eps: float
    dtype: Any
    # Gemma convention: the learned scale is zero-centered and applied as
    # (1 + w) — checkpoints store w, init stays ones-equivalent via zeros.
    plus_one: bool = False

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale", nn.with_logical_partitioning(
                (nn.initializers.zeros_init() if self.plus_one
                 else nn.initializers.ones), ("norm",)),
            (x.shape[-1],), jnp.float32)
        if self.plus_one:
            scale = 1.0 + scale
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(self.dtype)


def rope_table(head_dim: int, max_len: int, theta: float,
               cfg: "LlamaConfig | None" = None) -> tuple[jax.Array, jax.Array]:
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if cfg is not None and getattr(cfg, "rope_global_scaling_factor",
                                   1.0) != 1.0:
        # HF "linear" rope scaling: positions divided by the factor —
        # identically, frequencies divided. Read from cfg so EVERY
        # cfg-passing call site (scanned trunk, pipeline stage) scales
        # identically; Gemma-3's LOCAL table passes cfg=None and stays
        # unscaled (HF scales the global rope only).
        inv = inv / cfg.rope_global_scaling_factor
    if cfg is not None and cfg.rope_scaling_factor != 1.0:
        # Llama-3.1 "llama3" rope scaling: leave high-frequency components
        # alone, divide low-frequency ones by `factor`, and interpolate
        # smoothly in between (matches HF modeling_rope_utils).
        factor = cfg.rope_scaling_factor
        low = cfg.rope_scaling_low_freq_factor
        high = cfg.rope_scaling_high_freq_factor
        old_len = cfg.rope_scaling_original_max_len
        wavelen = 2 * jnp.pi / inv
        low_wl, high_wl = old_len / low, old_len / high
        smooth = (old_len / wavelen - low) / (high - low)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = (1 - smooth) * inv / factor + smooth * inv
        inv = jnp.where(wavelen > low_wl, inv / factor,
                        jnp.where(wavelen < high_wl, inv, scaled))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array) -> jax.Array:
    """x: [B, S, H, D]; positions: [B, S] absolute positions (for decode)."""
    cos = cos[positions][:, :, None, :]  # [B,S,1,D/2]
    sin = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


# Re-exported for compatibility; canonical home is ops/reference.py (ops/
# must not depend on models/).
from kubeflow_tpu.ops.reference import naive_attention  # noqa: E402,F401


def init_cache(cfg: LlamaConfig, batch: int, max_len: int | None = None,
               dtype: Any = None, kv_quant: str = "none") -> dict:
    """Decode KV cache: {"k","v"} of [L, B, T, KH, D] (layer-stacked so the
    scanned trunk consumes it as a per-layer scan input). Functional — the
    cache is passed into and returned from `Llama.__call__`, never stored as
    a flax variable, so serving can AOT-compile prefill/decode as pure fns
    (the TPU answer to vLLM's mutable paged cache; SURVEY.md §2.2
    huggingfaceserver row).

    Sliding-window checkpoints (Mistral-class) whose window is shorter
    than the requested length get a ROLLING cache instead: T = window
    rows, writes wrap modularly, and a "pos" plane [L, B, T] records each
    row's absolute position (sentinel -(window+1) = never written) so
    attention can mask reads exactly — the vLLM/HF rolling-buffer
    capability, XLA-shaped (static shapes, pure fns).

    `kv_quant` != "none" (ISSUE 19) stores K/V as int8/fp8 with per-row
    f32 scale planes "ks"/"vs" of [L, B, T, KH] — the paged pool's
    quantized layout (serve/quant.py KV helpers). Rolling caches never
    quantize (the engine refuses the combination upstream: quantization
    requires the paged pool, rolling requires the flat layout)."""
    t = max_len or cfg.max_seq_len
    dt = dtype or cfg.dtype
    window = int(getattr(cfg, "mask_window", 0) or 0)
    cache = {}
    if (getattr(cfg, "mask_kind", "causal") == "sliding_window"
            and 0 < window < t
            and getattr(cfg, "sliding_pattern", "all") == "all"):
        # Alternating patterns (Gemma-2/3) have FULL-attention layers
        # that need the whole history — nothing rolls; they serve past
        # the window on the plain full-length layout with per-layer
        # banded decode reads (Attention's decode branch).
        t = window
        cache["pos"] = jnp.full((cfg.num_layers, batch, t),
                                -(window + 1), jnp.int32)
    shape = (cfg.num_layers, batch, t, cfg.num_kv_heads, cfg.head_dim)
    if kv_quant != "none":
        from kubeflow_tpu.serve.quant import kv_qdtype

        if "pos" in cache:
            raise ValueError("kv_quant does not compose with a rolling "
                             "sliding-window cache")
        qdt = kv_qdtype(kv_quant)
        cache.update({"k": jnp.zeros(shape, qdt),
                      "v": jnp.zeros(shape, qdt),
                      "ks": jnp.zeros(shape[:-1], jnp.float32),
                      "vs": jnp.zeros(shape[:-1], jnp.float32)})
        return cache
    cache.update({"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)})
    return cache


def _update_cache(cache_k, cache_v, k, v, index):
    """Write new k/v [B,S,KH,D] into per-layer cache [B,T,KH,D] at per-row
    sequence offsets index [B] (rows advance independently under continuous
    batching)."""
    def row(ck, cv, kk, vv, i):
        return (jax.lax.dynamic_update_slice(ck, kk, (i, 0, 0)),
                jax.lax.dynamic_update_slice(cv, vv, (i, 0, 0)))
    return jax.vmap(row)(cache_k, cache_v, k.astype(cache_k.dtype),
                         v.astype(cache_v.dtype), index)


def _update_rows(cache_leaf, new_rows, index):
    """`_update_cache` generalized over trailing rank: writes `new_rows`
    [B, S, ...] into a per-layer plane [B, T, ...] at per-row offsets —
    the quantized cache's f32 scale planes [B, T, KH] ride next to the
    value planes [B, T, KH, D] through the same per-row write."""
    def row(c, n, i):
        return jax.lax.dynamic_update_slice(c, n, (i,) + (0,) * (c.ndim - 1))
    return jax.vmap(row)(cache_leaf, new_rows.astype(cache_leaf.dtype),
                         index)


def _update_cache_rolling(cache, k, v, positions, index, window):
    """Modular writes into a per-layer rolling cache {"k","v","pos"}:
    chunk token j lands in row (index + j) % window with its absolute
    position recorded. Rows whose `positions` entry is negative (the
    engine marks prompt-bucket padding with a sentinel) keep their OLD
    contents — a padded write must never evict a real in-window row.
    Callers guarantee S <= window (the engine clamps prefill buckets), so
    the target rows are distinct and gather-then-set is well-defined."""
    s = k.shape[1]

    def row(ck, cv, cp, kk, vv, pos, i):
        rows = (i + jnp.arange(s)) % window
        valid = pos >= 0
        kk = jnp.where(valid[:, None, None], kk.astype(ck.dtype), ck[rows])
        vv = jnp.where(valid[:, None, None], vv.astype(cv.dtype), cv[rows])
        pp = jnp.where(valid, pos, cp[rows])
        return ck.at[rows].set(kk), cv.at[rows].set(vv), cp.at[rows].set(pp)

    ck, cv, cp = jax.vmap(row)(cache["k"], cache["v"], cache["pos"],
                               k, v, positions, index)
    return {"k": ck, "v": cv, "pos": cp}


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions, ring_axis: str | None = None,
                 standard_positions: bool = True, cache: dict | None = None,
                 cache_index: jax.Array | None = None,
                 segment_ids: jax.Array | None = None,
                 attend_full_cache: bool = False,
                 adapter: dict | None = None,
                 adapter_ids: jax.Array | None = None,
                 sliding: jax.Array | None = None,
                 rope_local: tuple | None = None):
        cfg = self.cfg
        dense = partial(
            _dense_cls(cfg), use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype)
        qkv_bias = dict()
        if cfg.attention_bias:
            # Qwen2-style QKV biases; [heads, head_dim] shards like the
            # kernel's output dims.
            qkv_bias = dict(
                use_bias=True,
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("heads", "kv")))
        q = dense(features=(cfg.num_heads, cfg.head_dim),
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("qkv_embed", "heads", "kv")),
                  name="q_proj", **qkv_bias)(x)
        k = dense(features=(cfg.num_kv_heads, cfg.head_dim),
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("qkv_embed", "heads", "kv")),
                  name="k_proj", **qkv_bias)(x)
        v = dense(features=(cfg.num_kv_heads, cfg.head_dim),
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("qkv_embed", "heads", "kv")),
                  name="v_proj", **qkv_bias)(x)
        if cfg.lora_rank > 0:
            # PEFT's Llama default targets: q_proj + v_proj.
            h_in = (cfg.hidden_size,)
            q = q + _lora_delta(self, cfg, "q_proj", x, h_in,
                                (cfg.num_heads, cfg.head_dim),
                                ("heads", "kv"))
            v = v + _lora_delta(self, cfg, "v_proj", x, h_in,
                                (cfg.num_kv_heads, cfg.head_dim),
                                ("heads", "kv"))
        if adapter is not None:
            # Multi-LoRA serving: per-row adapter selection.
            q = q + _multi_lora_delta(x, adapter_ids, adapter["q_proj"],
                                      (cfg.num_heads, cfg.head_dim))
            v = v + _multi_lora_delta(x, adapter_ids, adapter["v_proj"],
                                      (cfg.num_kv_heads, cfg.head_dim))
        if cfg.qk_norm:
            # Gemma-3: per-head RMSNorm on q/k BEFORE the score scale and
            # RoPE (the norm would erase a pre-applied scalar).
            q = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.norm_plus_one,
                        name="q_norm")(q)
            k = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.norm_plus_one,
                        name="k_norm")(k)
        if cfg.query_pre_attn_scalar:
            # Gemma-2 scales scores by query_pre_attn_scalar^-0.5; every
            # attention impl here divides by sqrt(head_dim), so fold the
            # ratio into q (AFTER adapter deltas — HF scales the full
            # projected query at score time).
            q = q * jnp.asarray(
                (cfg.head_dim ** 0.5) / (cfg.query_pre_attn_scalar ** 0.5),
                q.dtype)
        if rope_local is not None and sliding is not None:
            # Gemma-3 dual rope bases: this layer's table picked by the
            # traced sliding flag (local base on sliding layers, global —
            # possibly linear-scaled — on full layers).
            cos = jnp.where(sliding, rope_local[0], cos)
            sin = jnp.where(sliding, rope_local[1], sin)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        q = nn.with_logical_constraint(q, ("batch", "act_seq", "act_heads", "act_kv"))
        k = nn.with_logical_constraint(k, ("batch", "act_seq", None, "act_kv"))
        v = nn.with_logical_constraint(v, ("batch", "act_seq", None, "act_kv"))

        def o_proj(out):
            return dense(features=cfg.hidden_size, axis=(-2, -1),
                         kernel_init=nn.with_logical_partitioning(
                             nn.initializers.lecun_normal(),
                             ("heads", "kv", "embed")),
                         name="o_proj")(out)

        mask_spec = cfg.mask_spec
        if cache is not None and "pos" in cache:
            # Rolling sliding-window decode (vLLM/HF rolling-buffer
            # parity for Mistral-class serving past the window). Attend
            # BEFORE writing: a chunk's own modular writes may evict rows
            # its earliest queries are still entitled to see. Stale rows
            # (a spec-decode rewind leaves rows holding positions >= the
            # current write index) are masked to the sentinel first; the
            # fresh chunk's own K/V ride alongside the cache in the read.
            window = int(cfg.mask_window)
            sentinel = jnp.int32(-(window + 1))
            cpos = jnp.where(cache["pos"] >= cache_index[:, None],
                             sentinel, cache["pos"])
            keys = jnp.concatenate([cache["k"].astype(k.dtype), k], axis=1)
            vals = jnp.concatenate([cache["v"].astype(v.dtype), v], axis=1)
            pos_kv = jnp.concatenate([cpos, positions], axis=1)
            out = naive_attention(q, keys, vals, causal=True,
                                  positions_q=positions, positions_kv=pos_kv,
                                  mask=mask_spec, softcap=cfg.attn_softcap)
            new_cache = _update_cache_rolling(cache, k, v, positions,
                                              cache_index, window)
            return o_proj(out), new_cache
        if (mask_spec is not None and cache is not None
                and not (mask_spec.kind == "sliding_window"
                         and sliding is not None)):
            raise ValueError(
                "attention mask specs don't compose with KV-cache decode "
                "(v1): serve masked models with full-forward predict "
                "(sliding_window checkpoints roll automatically when the "
                "cache is built with max_len > window)")

        new_cache = None
        k_scale = v_scale = None
        if cache is not None:
            if "ks" in cache:
                # Quantized pool view (ISSUE 19): quantize ONLY the
                # newly written rows, write values + scales through the
                # generic per-row updater, and hand attention the RAW
                # quantized cache plus the scale planes — dequant is
                # output-side inside naive_attention (scores × k_scale,
                # probs × v_scale), so no full-width fp cache exists in
                # the scan carry and committed rows' bytes never change.
                from kubeflow_tpu.serve.quant import kv_quantize_rows

                qmode = ("int8" if cache["k"].dtype == jnp.int8
                         else "fp8")
                # tpk-sync: begin kv-quant-scatter decode
                kq, ks = kv_quantize_rows(k, qmode)
                vq, vs = kv_quantize_rows(v, qmode)
                # tpk-sync: end kv-quant-scatter
                new_cache = {
                    "k": _update_rows(cache["k"], kq, cache_index),
                    "v": _update_rows(cache["v"], vq, cache_index),
                    "ks": _update_rows(cache["ks"], ks, cache_index),
                    "vs": _update_rows(cache["vs"], vs, cache_index)}
                ck = new_cache["k"].astype(k.dtype)  # bare convert
                cv = new_cache["v"].astype(v.dtype)
                k_scale, v_scale = new_cache["ks"], new_cache["vs"]
            else:
                ck, cv = _update_cache(cache["k"], cache["v"], k, v,
                                       cache_index)
                new_cache = {"k": ck, "v": cv}
            if x.shape[1] == 1 or attend_full_cache:
                # Single-token decode — or a continuation chunk
                # (attend_full_cache: S new tokens at a nonzero offset,
                # the chunked-prefill path): attend over the whole cache;
                # causality and the not-yet-written tail (incl. stale
                # entries from a previous slot occupant) are both masked
                # by absolute positions (positions_kv > positions_q).
                # Alternating-window models (Gemma-2/3 past the window)
                # keep the FULL-length cache — the full-attention layers
                # need all history, so there is nothing to roll — and
                # the sliding layers band their reads per the traced
                # flag, exactly as in the full forward.
                t = ck.shape[1]
                out = naive_attention(
                    q, ck, cv, causal=True, positions_q=positions,
                    positions_kv=jnp.broadcast_to(jnp.arange(t), (ck.shape[0], t)),
                    softcap=cfg.attn_softcap,
                    mask=(mask_spec if sliding is not None else None),
                    windowed=sliding, k_scale=k_scale, v_scale=v_scale)
                return o_proj(out), new_cache
            # Prefill (cache_index must be 0): nothing precedes the new
            # tokens, so attention over just k/v is exact — the fast flash
            # path below serves it; the cache write above is the only extra.

        if cfg.attn_softcap or (sliding is not None
                                and mask_spec is not None):
            # Gemma-2's tanh score cap / per-layer traced window flag are
            # not implemented in the fused kernels — the einsum path is
            # the only correct impl; silently running flash would serve
            # wrong logits. NB `sliding` alone doesn't force this path:
            # after the serving engine's within-window causal rebuild the
            # flags stay alive for Gemma-3's dual rope selection, and
            # with the mask gone flash prefill is exact again.
            if cfg.attention_impl not in ("auto", "naive"):
                raise ValueError(
                    f"attn_softcap / alternating sliding layers need "
                    f"attention_impl 'naive', not "
                    f"{cfg.attention_impl!r}")
            out = naive_attention(q, k, v, causal=True,
                                  positions_q=positions,
                                  positions_kv=positions,
                                  segment_ids=segment_ids, mask=mask_spec,
                                  softcap=cfg.attn_softcap,
                                  windowed=sliding)
            return o_proj(out), new_cache

        impl = cfg.attention_impl
        if impl == "auto":
            if ring_axis is not None:
                impl = "ring"
            elif ((standard_positions or segment_ids is not None)
                  and jax.default_backend() in ("tpu", "axon")):
                impl = "flash"
            else:
                impl = "naive"
        if impl == "flash" and not standard_positions and segment_ids is None:
            # The flash kernel masks causality by array index; custom
            # positions (packed/offset sequences) need the segment mask
            # (pass segment_ids) or a position-aware impl.
            raise ValueError(
                "attention_impl='flash' with custom positions needs "
                "segment_ids (packed sequences); use 'naive' or 'ring' "
                "otherwise")
        if segment_ids is not None and impl not in ("flash", "naive"):
            raise ValueError(
                f"segment_ids (packed sequences) need attention_impl "
                f"'flash' or 'naive', not {impl!r}")
        if mask_spec is not None and impl not in ("flash", "naive"):
            raise ValueError(
                f"mask_kind={cfg.mask_kind!r} needs attention_impl 'flash' "
                f"or 'naive' (ring/zigzag schedules are causal-only), "
                f"not {impl!r}")
        if impl in ("ring", "ring_flash"):
            from kubeflow_tpu.ops.ring_attention import ring_attention
            if impl == "ring_flash":
                if not standard_positions:
                    raise ValueError(
                        "attention_impl='ring_flash' derives causality from "
                        "the contiguous layout; custom positions need 'ring'")
                out = ring_attention(q, k, v, axis_name=ring_axis or "seq",
                                     inner="flash",
                                     block_q=cfg.flash_block_q,
                                     block_kv=cfg.flash_block_kv)
            else:
                out = ring_attention(q, k, v, axis_name=ring_axis or "seq",
                                     positions=positions)
        elif impl in ("zigzag", "zigzag_flash"):
            # Balanced causal ring schedule: the CALLER must feed tokens in
            # zigzag order (ops.ring_attention.zigzag_indices) and pass the
            # matching absolute `positions` for RoPE — the trainer does both
            # when spec.ring_attention == "zigzag" (train/trainer.py).
            if standard_positions:
                # Default arange positions mean the data was NOT permuted:
                # the kernel would mask by zigzag positions on straight
                # data — silently corrupt attention. Refuse loudly.
                raise ValueError(
                    "attention_impl='zigzag' needs zigzag-permuted tokens "
                    "and their explicit absolute positions (the trainer's "
                    "ring_attention='zigzag' mode supplies both)")
            from kubeflow_tpu.ops.ring_attention import zigzag_ring_attention
            out = zigzag_ring_attention(
                q, k, v, axis_name=ring_axis or "seq", pre_permuted=True,
                inner="flash" if impl == "zigzag_flash" else "einsum",
                block_q=cfg.flash_block_q, block_kv=cfg.flash_block_kv)
        elif impl == "flash":
            from kubeflow_tpu.ops.flash_attention import flash_attention
            out = flash_attention(q, k, v, causal=True,
                                  block_q=cfg.flash_block_q,
                                  block_kv=cfg.flash_block_kv,
                                  segment_ids=segment_ids, mask=mask_spec)
        else:
            out = naive_attention(q, k, v, causal=True, positions_q=positions,
                                  positions_kv=positions,
                                  segment_ids=segment_ids, mask=mask_spec)
        return o_proj(out), new_cache


def _multi_lora_delta(x: jax.Array, ids: jax.Array, ab: dict,
                      out_shape: tuple) -> jax.Array:
    """Per-ROW adapter delta for multi-LoRA serving: each batch row
    selects its own adapter from stacked weights. ab = {"a": [N, in, r],
    "b": [N, r, *out]} where entry 0 is all-zeros ("no adapter") and B is
    PRE-SCALED by alpha/r at load time (serve/multilora.py), so the
    delta is just (x @ a[id]) @ b[id]. x [B, S, in]."""
    a = ab["a"][ids].astype(x.dtype)              # [B, in, r]
    b = ab["b"][ids].astype(x.dtype)              # [B, r, *out]
    low = jnp.einsum("bsh,bhr->bsr", x, a)
    bflat = b.reshape(b.shape[0], b.shape[1], -1)
    d = jnp.einsum("bsr,brf->bsf", low, bflat)
    return d.reshape(d.shape[0], d.shape[1], *out_shape)


def _lora_delta(mod: nn.Module, cfg: LlamaConfig, name: str, x: jax.Array,
                in_shape: tuple, out_shape: tuple,
                out_axes: tuple) -> jax.Array:
    """(x @ A) @ B * alpha/rank for one target projection. A
    [*in_shape, r] (small init), B [r, *out_shape] (ZERO init — the
    adapted model equals the base at step 0, the standard LoRA start).
    The rank dim is tiny and never sharded; B's output dims follow the
    base kernel's logical axes so TP shards the delta like the weight."""
    r = cfg.lora_rank
    a = mod.param(
        f"{name}_lora_a",
        nn.with_logical_partitioning(
            nn.initializers.normal(0.02),
            tuple([None] * len(in_shape)) + (None,)),
        tuple(in_shape) + (r,), cfg.param_dtype)
    b = mod.param(
        f"{name}_lora_b",
        nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (None,) + tuple(out_axes)),
        (r,) + tuple(out_shape), cfg.param_dtype)
    dt = cfg.dtype
    n_in = len(in_shape)
    low = jax.lax.dot_general(
        x.astype(dt), a.astype(dt),
        (((tuple(range(x.ndim - n_in, x.ndim))), tuple(range(n_in))),
         ((), ())))
    delta = jax.lax.dot_general(
        low, b.astype(dt), (((low.ndim - 1,), (0,)), ((), ())))
    return delta * (cfg.lora_alpha / r)


class MLPBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, adapter: dict | None = None,
                 adapter_ids: jax.Array | None = None):
        cfg = self.cfg
        dense = partial(_dense_cls(cfg), use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype)
        lora_mlp = cfg.lora_rank > 0 and cfg.lora_targets == "attn_mlp"
        multi_mlp = adapter is not None and "gate_proj" in adapter
        gate = dense(features=cfg.intermediate_size,
                     kernel_init=nn.with_logical_partitioning(
                         nn.initializers.lecun_normal(), ("embed", "mlp")),
                     name="gate_proj")(x)
        up = dense(features=cfg.intermediate_size,
                   kernel_init=nn.with_logical_partitioning(
                       nn.initializers.lecun_normal(), ("embed", "mlp")),
                   name="up_proj")(x)
        if lora_mlp:
            h = cfg.hidden_size
            gate = gate + _lora_delta(self, cfg, "gate_proj", x, (h,),
                                      (cfg.intermediate_size,), ("mlp",))
            up = up + _lora_delta(self, cfg, "up_proj", x, (h,),
                                  (cfg.intermediate_size,), ("mlp",))
        if multi_mlp:
            gate = gate + _multi_lora_delta(
                x, adapter_ids, adapter["gate_proj"],
                (cfg.intermediate_size,))
            up = up + _multi_lora_delta(
                x, adapter_ids, adapter["up_proj"],
                (cfg.intermediate_size,))
        if cfg.mlp_act == "silu":
            act = nn.silu(gate)
        elif cfg.mlp_act == "gelu_tanh":  # Gemma's GeGLU gate
            act = nn.gelu(gate, approximate=True)
        else:
            raise ValueError(f"mlp_act {cfg.mlp_act!r}: silu | gelu_tanh")
        h = act * up
        h = nn.with_logical_constraint(h, ("batch", "act_seq", "mlp"))
        down = dense(features=cfg.hidden_size,
                     kernel_init=nn.with_logical_partitioning(
                         nn.initializers.lecun_normal(), ("mlp", "embed")),
                     name="down_proj")(h)
        if lora_mlp:
            down = down + _lora_delta(
                self, cfg, "down_proj", h, (cfg.intermediate_size,),
                (cfg.hidden_size,), ("embed",))
        if multi_mlp:
            down = down + _multi_lora_delta(
                h, adapter_ids, adapter["down_proj"], (cfg.hidden_size,))
        return down


class DecoderLayer(nn.Module):
    cfg: LlamaConfig
    mlp_cls: Any = None  # defaults to MLPBlock; models/moe.py swaps in MoE

    @nn.compact
    def __call__(self, x, cos, sin, positions, ring_axis=None,
                 standard_positions=True, cache=None, cache_index=None,
                 segment_ids=None, attend_full_cache=False,
                 adapter=None, adapter_ids=None, sliding=None,
                 rope_local=None):
        cfg = self.cfg
        attn_ad = None
        mlp_ad = None
        if adapter is not None:
            attn_ad = {k: adapter[k] for k in ("q_proj", "v_proj")
                       if k in adapter} or None
            mlp_ad = {k: adapter[k]
                      for k in ("gate_proj", "up_proj", "down_proj")
                      if k in adapter} or None
        h = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.norm_plus_one,
                    name="input_norm")(x)
        attn_out, new_cache = Attention(cfg, name="attn")(
            h, cos, sin, positions, ring_axis, standard_positions, cache,
            cache_index, segment_ids, attend_full_cache,
            adapter=attn_ad, adapter_ids=adapter_ids, sliding=sliding,
            rope_local=rope_local)
        if cfg.sandwich_norms:
            # Gemma-2: norm the attention OUTPUT before the residual add
            # (HF post_attention_layernorm).
            attn_out = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.norm_plus_one,
                               name="attn_out_norm")(attn_out)
        # Remat landmark: policy "save_attn" keeps this tensor so the
        # backward skips re-running the attention kernel (small residual:
        # [B,S,H·D] bf16 per layer vs the full block internals).
        from jax.ad_checkpoint import checkpoint_name
        attn_out = checkpoint_name(attn_out, "attn_out")
        x = x + attn_out
        # In sandwich mode this plays HF's pre_feedforward_layernorm role
        # (same position: normed input to the MLP).
        h = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.norm_plus_one,
                    name="post_attn_norm")(x)
        mlp_out = (self.mlp_cls or MLPBlock)(cfg, name="mlp")(
            h, adapter=mlp_ad, adapter_ids=adapter_ids)
        if cfg.sandwich_norms:
            mlp_out = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.norm_plus_one,
                              name="mlp_out_norm")(mlp_out)
        x = x + mlp_out
        x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))
        return x, new_cache


class Llama(nn.Module):
    """Causal LM. __call__ returns logits [B, S, V]."""

    cfg: LlamaConfig
    mlp_cls: Any = None  # per-layer FFN class (None = dense MLPBlock)

    @nn.compact
    def __call__(self, tokens: jax.Array, positions: jax.Array | None = None,
                 ring_axis: str | None = None, cache: dict | None = None,
                 cache_index: jax.Array | None = None,
                 return_hidden: bool = False,
                 segment_ids: jax.Array | None = None,
                 attend_full_cache: bool = False,
                 adapter: dict | None = None,
                 adapter_ids: jax.Array | None = None):
        """Returns logits [B,S,V]; with `cache` (see init_cache) returns
        (logits, updated_cache) — prefill when S>1 at cache_index 0,
        single-token decode when S==1 (positions default to cache_index),
        and CONTINUATION when S>1 with `attend_full_cache=True`: the new
        tokens write at cache_index>0 and attend over the whole cache
        (chunked prefill of long prompts; pass absolute `positions`).
        `return_hidden` skips the unembedding and returns the post-norm
        hidden states [B,S,H] (chunked-CE training path). `segment_ids`
        [B,S] enables packed-sequence training: attention is confined
        within equal-id spans (pass the matching per-segment restarting
        `positions` for RoPE).

        Multi-LoRA serving (`adapter` + `adapter_ids`): `adapter` maps
        target module names to stacked adapter pairs {"a": [L, N, in, r],
        "b": [L, N, r, *out]} (entry 0 zeros = base, B pre-scaled by
        alpha/r — serve/multilora.py), and `adapter_ids` [B] selects one
        per batch row; the stacks ride the layer scan like the cache."""
        cfg = self.cfg
        if adapter is not None and adapter_ids is None:
            adapter_ids = jnp.zeros((tokens.shape[0],), jnp.int32)
        if cache is not None:
            if cache_index is None:
                cache_index = jnp.zeros((tokens.shape[0],), jnp.int32)
            if positions is None and tokens.shape[1] == 1:
                positions = cache_index[:, None]
        standard_positions = positions is None
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape)
        embed = self.param(
            "embed", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        if cfg.quantized_dense:
            # Int8-aware gather: rows dequantize AFTER the lookup
            # ([B,S,D] work, not [V,D] per call — see serve/quant.py).
            from kubeflow_tpu.serve.quant import quant_embed_lookup
            x = quant_embed_lookup(embed, tokens, cfg.dtype)
        else:
            x = embed.astype(cfg.dtype)[tokens]
        if cfg.embed_scale:
            # Gemma scales token embeddings by sqrt(hidden) at input; the
            # multiplier is cast to the activation dtype first (HF rounds
            # the normalizer to the model dtype before multiplying).
            x = x * jnp.asarray(cfg.hidden_size ** 0.5, cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))
        cos, sin = rope_table(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta,
                              cfg)
        # Per-layer kind flags (HF layer_types): needed by the alternating
        # MASK (while the config still carries it — the serving engine's
        # within-window rebuild drops the mask) AND by Gemma-3's dual
        # rope bases (which survive the rebuild, so the flags must not
        # depend on the mask being present).
        sliding = None
        if cfg.sliding_pattern != "all" and (
                cfg.mask_kind == "sliding_window" or cfg.rope_theta_local):
            idx = jnp.arange(cfg.num_layers)
            if cfg.sliding_pattern == "even":
                sliding = idx % 2 == 0       # Gemma-2
            elif cfg.sliding_pattern == "5to1":
                sliding = (idx + 1) % 6 != 0  # Gemma-3: every 6th full
            else:
                raise ValueError(
                    f"sliding_pattern {cfg.sliding_pattern!r}: "
                    "all | even | 5to1")
        rope_local = None
        if cfg.rope_theta_local:
            rope_local = rope_table(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta_local)

        layer_cls = DecoderLayer
        if cfg.remat:
            policies = {
                "nothing": jax.checkpoint_policies.nothing_saveable,
                "dots": jax.checkpoint_policies.checkpoint_dots,
                "dots_no_batch":
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                "save_attn": jax.checkpoint_policies.save_only_these_names(
                    "attn_out"),
            }
            try:
                policy = policies[cfg.remat_policy]
            except KeyError:
                raise ValueError(
                    f"remat_policy {cfg.remat_policy!r}: "
                    f"{sorted(policies)}") from None
            # Static argnums are SELF-BASED in nn.remat (the scope rides at
            # index 0, user args start at 1): ring_axis(5) and
            # standard_positions(6) and attend_full_cache(10) are python
            # values steering control flow and must not be traced;
            # cache/cache_index/segment_ids are arrays and must stay
            # dynamic (serving prefill passes a real cache through the
            # remat'd layers).
            layer_cls = nn.remat(layer_cls, policy=policy,
                                 static_argnums=(5, 6, 10))
        new_cache = None
        if cfg.scan_layers:
            # `cache` (leading layer dim) rides as the scan's per-layer input
            # and the updated cache comes back as its per-layer output.
            x, new_cache = nn.scan(
                lambda mdl, carry, layer_cache, ad, sl: mdl(
                    carry, cos, sin, positions, ring_axis,
                    standard_positions, layer_cache, cache_index,
                    segment_ids, attend_full_cache, ad, adapter_ids, sl,
                    rope_local),
                variable_axes={"params": 0, "aux_loss": 0},
                split_rngs={"params": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(layer_cls(cfg, self.mlp_cls, name="layers"), x, cache,
              adapter, sliding)
        else:
            layer_caches = []
            for i in range(cfg.num_layers):
                layer_cache = None if cache is None else jax.tree.map(
                    lambda c: c[i], cache)
                layer_ad = None if adapter is None else jax.tree.map(
                    lambda a: a[i], adapter)
                x, lc = layer_cls(cfg, self.mlp_cls, name=f"layer_{i}")(
                    x, cos, sin, positions, ring_axis, standard_positions,
                    layer_cache, cache_index, segment_ids,
                    attend_full_cache, layer_ad, adapter_ids,
                    None if sliding is None else sliding[i], rope_local)
                layer_caches.append(lc)
            if cache is not None:
                new_cache = jax.tree.map(
                    lambda *ls: jnp.stack(ls), *layer_caches)

        x = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.norm_plus_one,
                    name="final_norm")(x)
        if return_hidden:
            # Chunked-CE training path (train/step.py): the caller computes
            # logits blockwise against the unembedding so the [B·S, V] fp32
            # logits buffer is never materialized (ops/ROADMAP.md item 1).
            return (x, new_cache) if cache is not None else x
        if cfg.tie_embeddings:
            if cfg.quantized_dense:
                from kubeflow_tpu.serve.quant import quant_unembed
                logits = quant_unembed(x, embed, cfg.dtype)
            else:
                logits = jnp.einsum("bsh,vh->bsv", x,
                                    embed.astype(cfg.dtype))
        else:
            logits = _dense_cls(cfg)(
                features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "vocab")),
                name="lm_head")(x)
        if cfg.final_softcap:
            # Gemma-2 final-logit soft-cap. NB the chunked-CE training
            # path exits above via return_hidden — train/step.py applies
            # the same cap inside each logits chunk.
            logits = jnp.tanh(logits / cfg.final_softcap) * cfg.final_softcap
        if cache is not None:
            return logits, new_cache
        return logits
