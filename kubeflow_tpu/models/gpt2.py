"""GPT-2 decoder family — learned positions, biased MHA, pre-LN.

Another huggingfaceserver-servable causal-LM family (SURVEY.md §2.2
⟨kserve: python/huggingfaceserver⟩). The module implements the SAME
functional cache contract as Llama (models/llama.py: `tokens, cache,
cache_index, positions, attend_full_cache, return_hidden` → (logits,
cache) with the layer-stacked [L, B, T, H, D] cache from `init_cache`),
so the entire serving stack — GenerationEngine slots/buckets/prefix
cache, OpenAI surface, streaming — serves GPT-2 checkpoints unchanged.

Differences from Llama handled here: learned absolute position
embeddings (no RoPE), LayerNorm with bias (not RMS), fused-projection
attention WITH bias and 1/sqrt(d) scaling, tanh-approx GELU MLP with
bias, tied lm head. Attention runs through ops.reference.naive_attention
in all paths: GPT-2 is a serving family (max_seq_len 1024), not the
training flagship, and the position-aware naive path is exact for
prefill, decode, and chunked extension alike.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.reference import naive_attention


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq_len: int = 1024
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    # Engine-compat attributes (models/llama.py init_cache is duck-typed
    # on these): GPT-2 is MHA, so kv heads == heads.
    @property
    def num_kv_heads(self) -> int:
        return self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_params(self) -> int:
        h, L = self.hidden_size, self.num_layers
        return (self.vocab_size * h + self.max_seq_len * h
                + L * (4 * h * h + 2 * h * self.intermediate_size))


def gpt2_small() -> GPT2Config:
    return GPT2Config()


def gpt2_tiny() -> GPT2Config:
    return GPT2Config(vocab_size=96, hidden_size=32, num_layers=2,
                      num_heads=4, intermediate_size=64, max_seq_len=64)


def init_cache(cfg: GPT2Config, batch: int, max_len: int | None = None,
               dtype: Any = None) -> dict:
    from kubeflow_tpu.models import llama

    return llama.init_cache(cfg, batch, max_len, dtype)


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, positions, cache_k=None, cache_v=None,
                 cache_index=None, attend_full_cache=False):
        cfg = self.cfg
        nh, hd = cfg.num_heads, cfg.head_dim
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_eps,
                     dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype)

        h = ln(name="ln_1")(x)
        proj = dict(features=(nh, hd), kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("qkv_embed", "heads", "kv")))
        q = dense(**proj, name="q_proj")(h)
        k = dense(**proj, name="k_proj")(h)
        v = dense(**proj, name="v_proj")(h)

        new_k, new_v = None, None
        if cache_k is not None:
            from kubeflow_tpu.models.llama import _update_cache

            new_k, new_v = _update_cache(cache_k, cache_v, k, v,
                                         cache_index)
            if x.shape[1] == 1 or attend_full_cache:
                t = new_k.shape[1]
                kv_pos = jnp.broadcast_to(jnp.arange(t), (new_k.shape[0], t))
                attn = naive_attention(
                    q, new_k.astype(cfg.dtype), new_v.astype(cfg.dtype),
                    causal=True, positions_q=positions,
                    positions_kv=kv_pos)
            else:
                attn = naive_attention(q, k, v, causal=True,
                                       positions_q=positions,
                                       positions_kv=positions)
        else:
            attn = naive_attention(q, k, v, causal=True,
                                   positions_q=positions,
                                   positions_kv=positions)
        attn = dense(features=cfg.hidden_size, axis=(-2, -1),
                     kernel_init=nn.with_logical_partitioning(
                         nn.initializers.lecun_normal(),
                         ("heads", "kv", "embed")),
                     name="o_proj")(attn)
        x = x + attn
        h = ln(name="ln_2")(x)
        h = dense(features=cfg.intermediate_size,
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("embed", "mlp")),
                  name="fc")(h)
        h = nn.gelu(h, approximate=True)  # GPT-2 canonical gelu_new
        h = dense(features=cfg.hidden_size,
                  kernel_init=nn.with_logical_partitioning(
                      nn.initializers.lecun_normal(), ("mlp", "embed")),
                  name="proj")(h)
        return x + h, new_k, new_v


class GPT2(nn.Module):
    """Functional-cache causal LM (the Llama serving contract)."""

    cfg: GPT2Config

    @nn.compact
    def __call__(self, tokens, positions=None, cache=None,
                 cache_index=None, attend_full_cache=False,
                 return_hidden=False):
        cfg = self.cfg
        b, s = tokens.shape
        if cache is not None:
            if cache_index is None:
                cache_index = jnp.zeros((b,), jnp.int32)
            if positions is None and s == 1:
                # Single-token decode: the absolute position IS the cache
                # write offset (same derivation as llama.py __call__) —
                # arange would decode every step at position 0.
                positions = cache_index[:, None]
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        wte = self.param("wte", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        wpe = self.param("wpe", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (None, "embed")),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype)
        x = (wte[tokens] + wpe[positions]).astype(cfg.dtype)

        new_cache = None
        if cache is not None:
            ks, vs = [], []
            for i in range(cfg.num_layers):
                x, nk, nv = Block(cfg, name=f"block_{i}")(
                    x, positions, cache["k"][i], cache["v"][i],
                    cache_index, attend_full_cache)
                ks.append(nk)
                vs.append(nv)
            new_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs)}
        else:
            for i in range(cfg.num_layers):
                x, _, _ = Block(cfg, name=f"block_{i}")(x, positions)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if return_hidden:
            return x, new_cache
        logits = jnp.einsum("bsh,vh->bsv", x,
                            wte.astype(cfg.dtype)).astype(jnp.float32)
        if cache is not None:
            return logits, new_cache
        return logits
