"""FSDP master-state runtime: ZeRO-style param/optimizer-state sharding.

The sharding-rule engine (parallel/sharding.py) decides how params are laid
out *for compute*; under the hybrid rules a Llama kernel's embed dim already
shards over `fsdp`. What the rules do NOT guarantee is that the **training
state** — fp32 master params and both Adam moments, 10+ bytes/param of pure
storage — divides by the fsdp axis on *every* leaf: norm scales, bias-like
vectors and any dim the rules replicate ride along replicated, and the
compute copies stay in master precision. This module is the missing half
(ROADMAP item 1 / PROFILE §4 "next unlock is optimizer-state sharding"):

  * `master_spec` adds the `fsdp` mesh axis to the largest divisible
    unsharded dim of every state leaf, so fp32 params + Adam moments are
    born sharded 1/fsdp (on top of whatever tensor/expert sharding the
    rules already give them) — the ZeRO-3 storage layout, expressed as
    NamedShardings instead of a parameter-flattening runtime.
  * `FSDP.gather_params` runs INSIDE the jitted step: cast the master
    shard to the compute dtype (bf16 halves every all-gather byte), then
    `with_sharding_constraint` to the rules-derived compute layout. XLA
    emits the all-gathers and overlaps them with compute, and the
    backward of the same pair is a reduce(-scatter) of grads straight
    into the fp32 master layout — gather-for-compute and
    grad-reduce-for-update are one differentiable function, not runtime
    hooks.
  * Checkpoints stay **topology-portable** for free: orbax saves logical
    arrays, and restore targets whatever shardings the *current* mesh
    derives — save on N-way fsdp, restore on M-way (tests pin kill-9
    resume across topologies bit-identically).

`compute_dtype=None` is the exact escape hatch: the gather is a pure
layout constraint, numerics identical to the unsharded trainer (the
CPU-mesh equivalence tests pin fsdp=4 against replicated fsdp=1).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: The mesh axis master state shards over (parallel/mesh.py vocabulary).
AXIS = "fsdp"

#: Spec-knob spelling -> dtype. `param_dtype` on the JAXJob runtime picks
#: the COMPUTE dtype of the gathered copies; the master stays fp32.
COMPUTE_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
}


def parse_compute_dtype(name: str | None) -> Any:
    """spec.param_dtype -> jnp dtype (None = keep master dtype, exact)."""
    if name is None:
        return None
    try:
        return COMPUTE_DTYPES[name]
    except KeyError:
        raise ValueError(
            f"param_dtype {name!r}: one of {sorted(COMPUTE_DTYPES)}"
        ) from None


def master_spec(spec: P, shape: tuple[int, ...], axis_size: int,
                axis: str = AXIS) -> P:
    """Add `axis` to the largest divisible unsharded dim of `spec`.

    Identity when the rules already put `axis` somewhere on the leaf (the
    hybrid rules shard embed dims over fsdp — double-sharding would be a
    shape error) or when no dim divides (small odd leaves stay replicated;
    they are noise in the byte budget)."""
    entries: list[Any] = list(spec) + [None] * (len(shape) - len(spec))
    for e in entries:
        if e == axis or (isinstance(e, tuple) and axis in e):
            return spec
    best = -1
    for i, (e, n) in enumerate(zip(entries, shape)):
        if e is None and n >= axis_size and n % axis_size == 0:
            if best < 0 or n > shape[best]:
                best = i
    if best < 0:
        return spec
    entries[best] = axis
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def resize_candidates(max_fsdp: int, min_fsdp: int = 1) -> list[int]:
    """The fsdp sizes an elastic resize may target: divisors of
    `max_fsdp` in [min_fsdp, max_fsdp], descending.

    Divisors are the set that preserves the master-state sharding plan:
    `master_spec` shards a leaf dim only when it divides by the axis
    size, and every dim divisible by max_fsdp is divisible by each of
    its divisors — so the SAME leaves stay sharded (just into fewer,
    larger shards) and no leaf flips between sharded and replicated
    across a resize. The C++ controller's candidate picker
    (cpp/jaxjob.cc NextFsdpDown) walks this exact set; this mirror
    exists so Python tests and the train chaos harness can assert the
    controller never picks outside it."""
    if max_fsdp < 1:
        return []
    return [d for d in range(max_fsdp, max(min_fsdp, 1) - 1, -1)
            if max_fsdp % d == 0]


def tree_bytes_per_device(tree: Any) -> int:
    """Per-device bytes of a tree of sharded arrays (or ShapeDtypeStructs
    with shardings — the AOT scale-proof path uses the same accounting).
    Pure metadata: no device sync, safe to call from the trainer."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(tuple(shape))
        total += math.prod(shape) * jnp.dtype(leaf.dtype).itemsize
    return total


@dataclasses.dataclass
class FSDP:
    """The sharded-training-runtime plan, threaded through
    `abstract_train_state` / `init_train_state` / `make_train_step`.

    `prepare()` is called by abstract_train_state once the rules-derived
    compute shardings exist; init and the step factory then share one
    consistent (master layout, compute layout) pair."""

    mesh: Mesh
    compute_dtype: Any = None  # None = master dtype (exact escape hatch)
    axis: str = AXIS
    # Filled by prepare() (train/step.abstract_train_state):
    compute_param_shardings: Any = None
    master_param_shardings: Any = None

    @property
    def axis_size(self) -> int:
        return self.mesh.shape[self.axis]

    def master_state_shardings(self, abstract_state: Any,
                               shardings: Any) -> Any:
        """Rewrite the rules-derived TrainState shardings so every array
        leaf (params AND opt-state moments; scalars like step/count stay
        replicated) carries the fsdp axis."""
        def one(a, s):
            shape = tuple(getattr(a, "shape", ()))
            if not shape:
                return s
            return NamedSharding(
                self.mesh, master_spec(s.spec, shape, self.axis_size,
                                       self.axis))
        return jax.tree.map(one, abstract_state, shardings)

    def prepare(self, abstract_params: Any, param_shardings: Any) -> None:
        """Record the (compute, master) param layout pair. Runs inside
        abstract_train_state so init and the train step can't diverge."""
        self.compute_param_shardings = param_shardings
        self.master_param_shardings = self.master_state_shardings(
            abstract_params, param_shardings)

    def _require_prepared(self) -> None:
        if self.compute_param_shardings is None:
            raise ValueError(
                "FSDP plan not prepared — initialize the train state "
                "first (init_train_state/abstract_train_state with "
                "fsdp=plan) so the step shares init's layout")

    def gather_params(self, master: Any) -> Any:
        """Inside jit: master fp32 shards -> compute-dtype copies in the
        rules-derived compute layout. The cast runs BEFORE the layout
        constraint so the all-gather moves compute-dtype (half the bytes
        at bf16); XLA overlaps the gathers with compute and derives the
        backward reduce into the master layout from the same pair."""
        self._require_prepared()
        dt = self.compute_dtype

        def one(p, s):
            q = (p.astype(dt)
                 if dt is not None and jnp.issubdtype(p.dtype, jnp.floating)
                 else p)
            return jax.lax.with_sharding_constraint(q, s)
        return jax.tree.map(one, master, self.compute_param_shardings)

    def constrain_master_grads(self, grads: Any) -> Any:
        """Pin grads (already master-dtype via the gather's backward) to
        the master layout, so the accumulation carry and the optimizer
        update run sharded — never materializing a replicated fp32
        grad tree."""
        self._require_prepared()
        return jax.tree.map(jax.lax.with_sharding_constraint, grads,
                            self.master_param_shardings)
