"""Pipeline parallelism: GPipe microbatch schedule over the `pipe` mesh axis.

SURVEY.md §2.6 PP row: the reference launches DeepSpeed/Megatron pipeline
engines (p2p send/recv of microbatches over NCCL) inside user containers.
The TPU-native equivalent is a *compiled* schedule: stage-sharded weights
(leading `stage` axis over the `pipe` mesh axis), a `lax.scan` over
microbatch ticks, and `lax.ppermute` rotating activations stage→stage+1
over the ICI ring. XLA overlaps the permute with the next tick's compute;
reverse-mode AD differentiates straight through (ppermute transposes to the
reverse rotation), so the same schedule serves fwd+bwd — no hand-written
backward pipeline.

The bubble is the standard GPipe (P-1)/(M+P-1) fraction: every stage
computes on every tick, with garbage in the fill/drain ticks masked out of
the result. `pipeline_apply_circular` cuts it by the chunk count C
(Megatron's interleaved virtual stages): each device holds C model chunks
and microbatches ride the ring C times, so ticks are 1/C the work and the
fill/drain fraction drops to (P-1)/(C·M+P-1).

On memory: `jax.checkpoint` on the tick body makes the backward recompute
each tick's stage internals from its boundary carry, so the forward stores
one boundary activation per tick — O(ticks·microbatch) ≈ O(batch) — instead
of every stage's *internals* for every microbatch (depth × batch). That
removes the depth factor GPipe-without-remat pays; it is NOT 1F1B's
stronger O(P·microbatch) in-flight bound, which needs backward ticks
interleaved before the forward drains. Hand-interleaving fwd/bwd under XLA
would mean a custom VJP schedule for a constant-factor activation saving
the boundary-only footprint already makes small; deliberately not
implemented (documented trade-off). 1F1B's *bubble* benefit, by contrast,
IS implemented — that is exactly what the interleaved circular schedule
buys, without fighting AD.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jax import shard_map


def _check_data_axis(mesh: Mesh, data_axis, mb: int) -> None:
    """Fail early with a readable error instead of an opaque shard_map
    partition error when microbatch rows don't divide over the data axis."""
    if data_axis is None:
        return
    axes = (data_axis,) if isinstance(data_axis, str) else tuple(data_axis)
    dp = 1
    for a in axes:
        dp *= mesh.shape[a]
    if mb % dp:
        raise ValueError(
            f"microbatch size {mb} not divisible by data axis "
            f"{data_axis} (size {dp})")


def stack_stage_params(per_stage_params: list[Any]) -> Any:
    """Stacks per-stage pytrees into one pytree with a leading stage axis
    (shard it over `pipe` via the `stage` logical axis / PartitionSpec)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _travel_specs(x: Any, data_axis, travel_specs: Any | None) -> Any:
    """Per-leaf shard_map specs for the traveling microbatch pytree.

    Leaves are [M, mb, ...] after microbatching: dim 0 (microbatch index)
    replicates, dim 1 (rows) shards over the data axis, and `travel_specs`
    — a pytree matching x whose entries are tuples of mesh-axis names (or
    None) for the dims AFTER rows — shards trailing dims (CP-inside-PP
    shards the sequence dim over `seq` this way). None = all-replicated
    trailing dims (the default GPipe travel layout)."""
    o = P(None, data_axis) if data_axis is not None else P()
    if travel_specs is None:
        return jax.tree.map(lambda _: o, x)
    _, treedef = jax.tree.flatten(x)
    flat_extra = treedef.flatten_up_to(travel_specs)
    base = (None, data_axis if data_axis is not None else None)
    flat = [o if extra is None else P(*base, *extra)
            for extra in flat_extra]
    return jax.tree.unflatten(treedef, flat)


def _param_specs(stage_params: Any, lead: tuple, param_specs: Any | None
                 ) -> Any:
    """Per-leaf shard_map specs for the stage parameters. `lead` is the
    spec prefix for the leading stage dim(s) — (axis,) for pipeline_apply,
    (None, axis) for the chunk-major circular layout. `param_specs` — a
    pytree matching stage_params whose entries are tuples of mesh-axis
    names (or None) for the dims AFTER the leading stage dim — shards
    non-stage param dims (MoE-PP shards the expert dim over `expert`)."""
    if param_specs is None:
        return jax.tree.map(lambda _: P(*lead), stage_params)
    _, treedef = jax.tree.flatten(stage_params)
    flat_extra = treedef.flatten_up_to(param_specs)
    flat = [P(*lead) if extra is None else P(*lead, *extra)
            for extra in flat_extra]
    return jax.tree.unflatten(treedef, flat)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pipe",
    data_axis: str | tuple[str, ...] | None = None,
    travel_specs: Any | None = None,
    param_specs: Any | None = None,
) -> jax.Array:
    """Applies `stage_fn` P times in sequence, pipelined over microbatches.

    stage_params: pytree whose leaves have leading dim P (one slice per
      stage), sharded over mesh axis `axis`.
    x: [B, ...] global batch, B divisible by num_microbatches; activations
      must keep a constant shape across stages (transformer trunk shape).
    data_axis: optional mesh axis (or axes) carrying data parallelism —
      microbatch ROWS shard over it (PP x DP composition: each data rank
      pipelines its slice of every microbatch; stage weights replicate over
      data, grads all-reduce over it outside via GSPMD).
    Returns stage_{P-1}(...stage_0(x)) with identical numerics to the
    sequential loop — the schedule only changes *when* each stage runs.
    """
    num_stages = mesh.shape[axis]
    batch = jax.tree.leaves(x)[0].shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by microbatches {num_microbatches}")
    if num_microbatches < num_stages:
        raise ValueError(
            f"need microbatches ({num_microbatches}) >= stages "
            f"({num_stages}) to fill the pipeline")
    mb = batch // num_microbatches
    _check_data_axis(mesh, data_axis, mb)
    # x may be a pytree: the activation plus whatever per-microbatch
    # metadata must travel the ring with it (packed-sequence positions /
    # segment ids — [mb, S] int32, negligible next to [mb, S, H] acts).
    # The schedule is structure-agnostic: every leaf microbatches,
    # rotates, and emits identically.
    xm = jax.tree.map(
        lambda a: a.reshape(num_microbatches, mb, *a.shape[1:]), x)

    pspec = _param_specs(stage_params, (axis,), param_specs)
    # Inputs/outputs: replicated over the pipe axis; microbatch rows
    # sharded over the data axis; trailing dims per travel_specs
    # (CP-inside-PP shards the sequence dim).
    other = _travel_specs(x, data_axis, travel_specs)

    @partial(shard_map, mesh=mesh, in_specs=(pspec, other),
             out_specs=other, check_vma=False)
    def run(params, xm):
        stage = jax.lax.axis_index(axis)
        # Each shard holds its stage's slice with a leading dim of 1.
        params = jax.tree.map(lambda p: p[0], params)
        ticks = num_microbatches + num_stages - 1
        # Activation (+ metadata) arriving at this stage.
        buf = jax.tree.map(lambda a: jnp.zeros_like(a[0]), xm)

        def tick(buf, t):
            in_idx = jnp.clip(t, 0, num_microbatches - 1)
            h_in = jax.tree.map(
                lambda a, b: jnp.where(stage == 0, a[in_idx], b), xm, buf)
            h_out = stage_fn(params, h_in)
            # Rotate stage -> stage+1 (last -> 0 carries drain garbage,
            # overwritten before stage 0 reads it... stage 0 always reads
            # xm, so the wraparound value is simply unused).
            buf = jax.tree.map(
                lambda a: jax.lax.ppermute(
                    a, axis,
                    [(i, (i + 1) % num_stages) for i in range(num_stages)]),
                h_out)
            # h_out rides out as scan ys: emitted once per tick instead of
            # scattering into a carried [M, ...] buffer, so the remat'd
            # backward only stores per-tick boundary activations.
            return buf, h_out

        buf, emitted = jax.lax.scan(
            jax.checkpoint(tick), buf, jnp.arange(ticks))
        # The last stage's emissions for ticks P-1.. are microbatches 0..M.
        # Only the last stage holds real outputs; give every shard the
        # same result (out_specs replicate over `axis`).
        def finalize(e):
            out = e[num_stages - 1:]
            out = jnp.where(stage == num_stages - 1, out,
                            jnp.zeros_like(out))
            return jax.lax.psum(out, axis)

        return jax.tree.map(finalize, emitted)

    out = run(stage_params, xm)
    return jax.tree.map(
        lambda a: a.reshape(batch, *a.shape[2:]), out)


def pipeline_apply_circular(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    num_chunks: int,
    axis: str = "pipe",
    data_axis: str | tuple[str, ...] | None = None,
    travel_specs: Any | None = None,
    param_specs: Any | None = None,
) -> jax.Array:
    """Interleaved (circular) pipeline schedule — Megatron's interleaved-1F1B
    bubble reduction, compiled for TPU.

    Each device holds `num_chunks` (C) model chunks instead of one stage:
    global stage order is chunk-major, stage g = c·P + s runs chunk c on
    device s, and every microbatch rides the ICI ring C times. Ticks are
    1/C the work of a GPipe tick, so the fill/drain bubble shrinks from
    (P-1)/(M+P-1) to (P-1)/(C·M+P-1) — the same reason Megatron interleaves
    virtual stages, expressed as a `lax.scan` whose wraparound ppermute edge
    (last→0) IS the chunk-to-chunk hop. AD differentiates straight through,
    and the per-tick `jax.checkpoint` keeps activations boundary-only —
    though with C·M+P-1 ticks the emitted boundary stack is ~C× the GPipe
    schedule's (C× the batch in boundary activations): the bubble saving
    costs a bounded, known memory term, still far below un-remat'd stage
    internals.

    stage_params: leaves with leading dim C·P in application (chunk-major)
      order; x as in pipeline_apply. Requires M % P == 0 (microbatches are
      injected in groups of P so fresh input and wrapped activations never
      contend for a device slot).
    """
    num_stages = mesh.shape[axis]
    p, c, m = num_stages, num_chunks, num_microbatches
    batch = jax.tree.leaves(x)[0].shape[0]
    total = jax.tree.leaves(stage_params)[0].shape[0]
    if total != p * c:
        raise ValueError(
            f"stage_params leading dim {total} != pipe axis ({p}) * "
            f"num_chunks ({c})")
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by microbatches {m}")
    if m % p:
        raise ValueError(
            f"microbatches ({m}) must be a multiple of stages ({p}) for "
            "the interleaved schedule's group injection")
    mb = batch // m
    _check_data_axis(mesh, data_axis, mb)
    # Pytree x: see pipeline_apply — metadata rides the ring with the
    # activation.
    xm = jax.tree.map(lambda a: a.reshape(m, mb, *a.shape[1:]), x)
    groups = m // p
    period = c * p  # ticks to push one group through all chunks
    ticks = groups * period + p - 1

    # Reshape chunk-major [C*P, ...] -> [C, P, ...]; shard dim 1 over pipe.
    cparams = jax.tree.map(
        lambda a: a.reshape(c, p, *a.shape[1:]), stage_params)
    pspec = _param_specs(cparams, (None, axis), param_specs)
    other = _travel_specs(x, data_axis, travel_specs)

    # Tick t on device s computes the chunk of the activation that left
    # device 0 at tick t-s: chunk(t, s) = ((t - s) mod C·P) // P. Fresh
    # microbatches enter device 0 only on loop-0 slots; the emitted output
    # of device P-1 on a loop-(C-1) slot is a finished microbatch. All
    # indices are static per tick, so the gather below is a static take.
    out_ticks = [
        p - 1 + g * period + (c - 1) * p + slot
        for g in range(groups) for slot in range(p)
    ]  # emission tick of microbatch g*P + slot

    @partial(shard_map, mesh=mesh, in_specs=(pspec, other),
             out_specs=other, check_vma=False)
    def run(params, xm):
        stage = jax.lax.axis_index(axis)
        params = jax.tree.map(lambda a: a[:, 0], params)  # [C, ...] local

        def tick(buf, t):
            u = jnp.mod(t - stage, period)
            chunk = jnp.clip(u // p, 0, c - 1)
            # Device 0, loop-0 slot: inject microbatch g*P + slot.
            fresh_idx = jnp.clip((t // period) * p + jnp.mod(t, period),
                                 0, m - 1)
            is_fresh = (stage == 0) & (jnp.mod(t, period) < p) & (t < m * c)
            h_in = jax.tree.map(
                lambda a, b: jnp.where(is_fresh, a[fresh_idx], b), xm, buf)
            cp = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, chunk, keepdims=False), params)
            h_out = stage_fn(cp, h_in)
            buf = jax.tree.map(
                lambda a: jax.lax.ppermute(
                    a, axis,
                    [(i, (i + 1) % num_stages) for i in range(num_stages)]),
                h_out)
            return buf, h_out

        _, emitted = jax.lax.scan(
            jax.checkpoint(tick),
            jax.tree.map(lambda a: jnp.zeros_like(a[0]), xm),
            jnp.arange(ticks))

        def finalize(e):
            out = jnp.take(e, jnp.asarray(out_ticks), axis=0)
            out = jnp.where(stage == num_stages - 1, out,
                            jnp.zeros_like(out))
            return jax.lax.psum(out, axis)

        return jax.tree.map(finalize, emitted)

    out = run(cparams, xm)
    return jax.tree.map(
        lambda a: a.reshape(batch, *a.shape[2:]), out)


def sequential_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                     stage_params: Any, x: jax.Array) -> jax.Array:
    """Reference semantics of pipeline_apply (no pipelining) — for tests
    and single-device fallback."""
    num_stages = jax.tree.leaves(stage_params)[0].shape[0]
    for i in range(num_stages):
        params_i = jax.tree.map(lambda p: p[i], stage_params)
        x = stage_fn(params_i, x)
    return x
