"""Sharding-rule engine: logical axis names → mesh axes → PartitionSpec.

The reference platform has no parallelism math of its own (Kubeflow only
injects rendezvous env vars; SURVEY.md §2.6) — strategy lived inside user
containers (DDP/FSDP/Megatron/DeepSpeed configs). Here strategy is a
first-class, declarative table: models annotate parameters/activations with
*logical* axis names, and a rule table maps those to mesh axes per strategy.
Changing DP→FSDP→TP→hybrid is a rules swap, not a model rewrite — the GSPMD
analog of DeepSpeed's zero-stage / Megatron's tp-degree knobs.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# A rule maps a logical axis name to one mesh axis, a tuple of mesh axes, or
# None (replicate). First matching rule wins (flax logical-rules semantics).
Rules = Sequence[tuple[str, str | tuple[str, ...] | None]]

# Default hybrid rules, MaxText-style: batch over (data, fsdp); parameter
# embed dim over fsdp (ZeRO-3 gather at use); heads/mlp over tensor;
# activation sequence over seq (context parallelism); experts over expert.
DEFAULT_RULES: Rules = (
    ("batch", ("data", "fsdp")),
    ("act_seq", "seq"),
    ("act_embed", None),
    ("act_heads", "tensor"),
    ("act_kv", None),
    ("embed", "fsdp"),
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv", None),
    ("qkv_embed", "fsdp"),
    ("vocab", "tensor"),
    ("expert", "expert"),
    ("expert_mlp", "tensor"),
    ("layers", None),
    ("stage", "pipe"),
    ("norm", None),
)


def rules_for(strategy: str) -> Rules:
    """Preset rule tables per named strategy (SURVEY.md §2.6 inventory)."""
    presets: dict[str, Rules] = {
        # Pure DP: everything replicated except the batch.
        "dp": (("batch", ("data", "fsdp")),),
        # FSDP/ZeRO-3: params sharded on their embed-ish dim over fsdp.
        "fsdp": (
            ("batch", ("data", "fsdp")),
            ("embed", "fsdp"),
            ("qkv_embed", "fsdp"),
            ("vocab", "fsdp"),
            ("mlp", None),
            ("expert_mlp", None),
        ),
        # Megatron TP only.
        "tensor": (
            ("batch", "data"),
            ("mlp", "tensor"),
            ("heads", "tensor"),
            ("vocab", "tensor"),
            ("act_heads", "tensor"),
        ),
        # Sequence/context parallel attention (ring attention over `seq`).
        "context": (
            ("batch", ("data", "fsdp")),
            ("act_seq", "seq"),
            ("embed", "fsdp"),
        ),
        "hybrid": DEFAULT_RULES,
        # Pipeline parallelism: identical to hybrid except the scanned
        # trunk's `layers` dim shards over `pipe` — params are born
        # stage-partitioned and the PP step reshapes [L,...] ->
        # [stages, L/stages, ...] (models/llama_pp.py). A rules swap, not
        # a weight-format change.
        "pipeline": tuple(
            (name, "pipe") if name == "layers" else (name, to)
            for name, to in DEFAULT_RULES),
    }
    try:
        return presets[strategy]
    except KeyError:
        raise ValueError(
            f"unknown strategy {strategy!r}; have {sorted(presets)}"
        ) from None


def logical_to_spec(
    logical_axes: Sequence[str | None], rules: Rules = DEFAULT_RULES
) -> P:
    """Map a tuple of logical axis names (one per tensor dim) to a PartitionSpec."""
    table = dict(rules)  # first occurrence wins is preserved by dict for dup-free rules
    out: list[Any] = []
    for name in logical_axes:
        if name is None:
            out.append(None)
        else:
            out.append(table.get(name))
    # Trailing Nones are implicit in PartitionSpec.
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def tree_logical_to_sharding(
    logical_tree: Any, mesh: Mesh, rules: Rules = DEFAULT_RULES
) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings on `mesh`."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(e, (str, type(None))) for e in x),
    )


def constrain(x: jax.Array, logical_axes: Sequence[str | None], rules: Rules,
              mesh: Mesh | None = None) -> jax.Array:
    """Sharding constraint by logical axes (inside jit)."""
    spec = logical_to_spec(logical_axes, rules)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec) if mesh is not None else spec
    )


def param_spec_tree(params: Any) -> Any:
    """Extract logical PartitionSpecs from a flax param tree annotated with
    `nn.with_logical_partitioning` metadata; map through rules with
    `nn.logical_to_mesh_sharding`."""
    import flax.linen as nn

    return nn.get_partition_spec(params)
