"""Device-mesh construction for TPU slices.

TPU-first replacement for the reference's pod-topology + NCCL world layout
(Kubeflow training-operator injects MASTER_ADDR/RANK per pod and delegates the
actual communicator to NCCL inside user containers; see SURVEY.md §2.6/§2.7).
Here the mesh IS the communicator: we build a `jax.sharding.Mesh` with named
axes and let XLA compile collectives onto ICI/DCN from sharding annotations.

Axis vocabulary (all strategies from SURVEY.md §2.6 compose on one mesh):
  data    pure data parallelism (replicated params, all-reduce grads)
  fsdp    sharded data parallelism (ZeRO-3 style param/grad/opt sharding)
  pipe    pipeline stages (microbatched, collective_permute between stages)
  tensor  megatron-style intra-layer model parallelism
  seq     sequence/context parallelism (ring attention / all-to-all)
  expert  MoE expert parallelism (all-to-all token routing)

Multi-slice: `dcn_data`/`dcn_pipe` factors place the slowest-varying mesh dim
across slices so only DP/PP gradients ride DCN while tensor/seq/expert
collectives stay on intra-slice ICI.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: slowest-communicating axes first so that, on real
# hardware, DCN-crossing axes map to the outermost device dimension and
# tensor/seq (most chatty) map to contiguous ICI neighbours.
MESH_AXES = ("data", "fsdp", "pipe", "tensor", "seq", "expert")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Parallelism degrees. Product must divide the device count (a value of
    -1 for exactly one axis means "absorb all remaining devices")."""

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    # Number of slices the job spans; >1 splits the leading (data or pipe)
    # axis across DCN. Informational on emulated backends.
    num_slices: int = 1

    def axis_sizes(self, num_devices: int) -> tuple[int, ...]:
        sizes = [self.data, self.fsdp, self.pipe, self.tensor, self.seq, self.expert]
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {sizes}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if num_devices % fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = num_devices // fixed
        if math.prod(sizes) != num_devices:
            raise ValueError(
                f"mesh {dict(zip(MESH_AXES, sizes))} needs {math.prod(sizes)} devices, "
                f"have {num_devices}"
            )
        return tuple(sizes)


def build_mesh(
    config: MeshConfig | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the global mesh. On real multi-host TPU, `jax.devices()` is already
    ordered so contiguous devices share ICI; `mesh_utils` would refine this for
    specific topologies — we keep row-major order, which is correct for the
    virtual CPU meshes used in tests and for single-slice v5e/v5p defaults."""
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.axis_sizes(len(devices))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    dev = device or jax.devices()[0]
    return Mesh(np.asarray([dev]).reshape((1,) * len(MESH_AXES)), MESH_AXES)


def mesh_shape(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def dp_like_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes over which the batch is sharded (data + fsdp)."""
    return tuple(a for a in ("data", "fsdp") if mesh.shape[a] > 1) or ("data",)


def current_mesh() -> Mesh | None:
    """The mesh installed by `with mesh:` (thread-local). Lets ops like
    ring_attention find the mesh from inside a model without plumbing."""
    from jax._src import mesh as mesh_lib  # stable across jax 0.4–0.9

    phys = mesh_lib.thread_resources.env.physical_mesh
    return None if phys.empty else phys
