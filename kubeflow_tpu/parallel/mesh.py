"""Device-mesh construction for TPU slices.

TPU-first replacement for the reference's pod-topology + NCCL world layout
(Kubeflow training-operator injects MASTER_ADDR/RANK per pod and delegates the
actual communicator to NCCL inside user containers; see SURVEY.md §2.6/§2.7).
Here the mesh IS the communicator: we build a `jax.sharding.Mesh` with named
axes and let XLA compile collectives onto ICI/DCN from sharding annotations.

Axis vocabulary (all strategies from SURVEY.md §2.6 compose on one mesh):
  data    pure data parallelism (replicated params, all-reduce grads)
  fsdp    sharded data parallelism (ZeRO-3 style param/grad/opt sharding)
  pipe    pipeline stages (microbatched, collective_permute between stages)
  tensor  megatron-style intra-layer model parallelism
  seq     sequence/context parallelism (ring attention / all-to-all)
  expert  MoE expert parallelism (all-to-all token routing)

Multi-slice: `dcn_data`/`dcn_pipe` factors place the slowest-varying mesh dim
across slices so only DP/PP gradients ride DCN while tensor/seq/expert
collectives stay on intra-slice ICI.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: slowest-communicating axes first so that, on real
# hardware, DCN-crossing axes map to the outermost device dimension and
# tensor/seq (most chatty) map to contiguous ICI neighbours.
MESH_AXES = ("data", "fsdp", "pipe", "tensor", "seq", "expert")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Parallelism degrees. Product must divide the device count (a value of
    -1 for exactly one axis means "absorb all remaining devices")."""

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    # Number of slices the job spans; >1 builds a two-level mesh where the
    # slice index becomes the slowest-varying factor of the `data` (or, if
    # data doesn't divide, `pipe`) axis — so only DP gradient all-reduces /
    # PP boundary permutes cross DCN while fsdp/tensor/seq/expert
    # collectives stay on intra-slice ICI (SURVEY.md §5.8(c), eval config 5).
    num_slices: int = 1

    def dcn_axis(self, num_devices: int) -> str | None:
        """Which mesh axis carries the cross-slice (DCN) factor.

        Preference: data (gradient all-reduce tolerates DCN latency), then
        pipe (one boundary permute per microbatch), then seq — the ring-
        attention-across-pods long-context configuration, where each ring
        step's K/V permute is sized to overlap with the step's attention
        compute (SURVEY.md §5.7); chatty axes (fsdp/tensor/expert) never
        cross DCN."""
        if self.num_slices <= 1:
            return None
        sizes = dict(zip(MESH_AXES, self.axis_sizes(num_devices)))
        for axis in ("data", "pipe", "seq"):
            if sizes[axis] % self.num_slices == 0:
                return axis
        raise ValueError(
            f"num_slices={self.num_slices} must divide the data, pipe, or "
            f"seq axis; got mesh {sizes}")

    def axis_sizes(self, num_devices: int) -> tuple[int, ...]:
        sizes = [self.data, self.fsdp, self.pipe, self.tensor, self.seq, self.expert]
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {sizes}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if num_devices % fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = num_devices // fixed
        if math.prod(sizes) != num_devices:
            raise ValueError(
                f"mesh {dict(zip(MESH_AXES, sizes))} needs {math.prod(sizes)} devices, "
                f"have {num_devices}"
            )
        return tuple(sizes)


def _slice_groups(
    devices: Sequence[jax.Device], num_slices: int
) -> list[list[jax.Device]]:
    """Partition devices into per-slice groups, slice-major.

    Preference order mirrors how slices actually manifest: real multi-slice
    TPU devices carry `slice_index`; the emulated multi-slice e2e runs one
    process per slice (group by `process_index`); single-process virtual
    meshes fall back to contiguous blocks (the driver's dryrun)."""
    n = len(devices)
    if n % num_slices:
        raise ValueError(f"{n} devices not divisible by {num_slices} slices")
    per = n // num_slices
    for attr in ("slice_index", "process_index"):
        keys = {getattr(d, attr, None) for d in devices}
        if None not in keys and len(keys) == num_slices:
            groups = [
                [d for d in devices if getattr(d, attr) == k]
                for k in sorted(keys)
            ]
            if all(len(g) == per for g in groups):
                return groups
    return [list(devices[i * per:(i + 1) * per]) for i in range(num_slices)]


def build_mesh(
    config: MeshConfig | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the global mesh. On real multi-host TPU, `jax.devices()` is already
    ordered so contiguous devices share ICI; `mesh_utils` would refine this for
    specific topologies — we keep row-major order, which is correct for the
    virtual CPU meshes used in tests and for single-slice v5e/v5p defaults.

    With `num_slices > 1` the device array is assembled slice-major: the
    slice index is the outermost factor of the DCN-crossing axis (data,
    else pipe), so every other axis's collectives stay within one slice.
    This is the two-level ICI/DCN layout the reference world gets from
    NCCL rail-aware topology files — here it is just array layout, and XLA
    emits hierarchical collectives from it."""
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.axis_sizes(len(devices))
    if config.num_slices > 1:
        s = config.num_slices
        axis = config.dcn_axis(len(devices))
        idx = MESH_AXES.index(axis)
        groups = _slice_groups(devices, s)
        inner = list(sizes)
        inner[idx] //= s
        arr = np.asarray(groups).reshape([s] + inner)
        # Move the slice factor so it leads the DCN axis, then merge.
        perm = list(range(1, idx + 1)) + [0] + list(range(idx + 1, len(inner) + 1))
        dev_array = arr.transpose(perm).reshape(sizes)
    else:
        dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    dev = device or jax.devices()[0]
    return Mesh(np.asarray([dev]).reshape((1,) * len(MESH_AXES)), MESH_AXES)


def mesh_shape(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def dp_like_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes over which the batch is sharded (data + fsdp)."""
    return tuple(a for a in ("data", "fsdp") if mesh.shape[a] > 1) or ("data",)


def current_mesh() -> Mesh | None:
    """The mesh installed by `with mesh:` (thread-local). Lets ops like
    ring_attention find the mesh from inside a model without plumbing."""
    from jax._src import mesh as mesh_lib  # stable across jax 0.4–0.9

    phys = mesh_lib.thread_resources.env.physical_mesh
    return None if phys.empty else phys
