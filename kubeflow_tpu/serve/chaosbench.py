"""Fabric chaos harness (ISSUE 14) → CHAOSBENCH.json.

ISSUEs 9/11/13 proved the fabric's pieces in isolation; this harness
proves them TOGETHER under injected faults: the REAL router + REAL
tiny-engine replicas (each its own subprocess, so SIGKILL / SIGSTOP are
the real thing) under open-loop Poisson load, while a seeded fault
schedule kills, stalls, and drains replicas mid-run.

Arms and their pinned claims (tests/test_chaosbench.py):

  * **disagg_decode_kill** — 1 prefill + 2 decode replicas; a decode
    replica is SIGKILLed MID-STREAM and later replaced. Claim: every
    stream completes with ZERO caller-visible errors (the router
    resumes the held shipment on the survivor — `tpk_router_resume_
    total{reason}`), token counts are exact (no duplicate, no loss),
    and the fleet ran EXACTLY ONE prefill per request (zero re-prefill
    across the failover); a decode replica is also DRAINED mid-run
    (in-flight completes). Goodput recovers to >= 90% of pre-fault.
  * **unified_kill** — 2 unified replicas, one SIGKILLed and replaced.
    Unified streams have no held shipment: mid-stream deaths are
    HONEST caller-visible failures — but every one carries the
    terminal error envelope (no silent truncation), and goodput
    recovers to >= 90% of pre-fault within the bounded window.
  * **gray_stall** — 3 unified replicas; one suffers a CYCLIC
    SIGSTOP/SIGCONT stall (slow-but-alive: probes still answer — the
    binary `down` detector never fires). Run twice: gray-failure
    ejection ON vs OFF at identical seed/schedule. Claim: the ejection
    arm ejects the stalled replica to `slow` (and REJOINS it after the
    stall lifts) and its p99 stays strictly below the no-ejection
    control's.
  * **ctrl_leader_kill** — a 3-node replicated control plane (real
    binaries) behind the serving fleet; the LEADER is SIGKILLed while
    the router serves loadgen traffic. Claim: serving does not blip
    (the data-plane hot path has no control-plane dependency — zero
    non-200s), and the autoscaler's next reconcile (spec.replicas
    patch) succeeds against the promoted follower. Records
    skipped-with-reason when the binary is not built (the
    test_ctrlbench convention).

Harness discipline (PROFILE §11/§13): open-loop arrivals FIRE AT
SCHEDULE; replicas are REAL engines behind real ModelServers and the
real router (absolute latencies are 1-CPU tiny-model numbers — the
artifact is the claims and the arm DELTAS); every claim is computed
from PER-REQUEST provenance rows (replica, resume count, fault-window
overlap), not aggregates; the fault schedule is seeded and recorded.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np

#: Serving model name every worker registers under.
MODEL = "m"

#: Engine shape shared by every REAL worker (the disaggbench family:
#: tiny 2-layer llama, paged KV, pipelined decode).
GEN_KW = dict(slots=4, max_len=120, chunk=8, prefill_buckets=(16, 32),
              kv_block_size=8, kv_blocks=0, pipeline_depth=2)


# -- subprocess replica workers ---------------------------------------------


def _worker_main(args) -> int:
    """`python -m kubeflow_tpu.serve.chaosbench --worker`: one replica
    subprocess — builds the tiny REAL engine (or the fake timed model
    with --fake), serves it on a ModelServer, prints the ready line,
    and parks until killed. Being a real process is the point: SIGKILL
    and SIGSTOP from the parent are the actual faults."""
    import dataclasses

    from kubeflow_tpu.serve.server import ModelServer

    if args.fake:
        from kubeflow_tpu.serve.loadgen import FakeGenerativeModel

        model = FakeGenerativeModel(MODEL, slots=4)
    else:
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models.llama import Llama, llama_tiny
        from kubeflow_tpu.serve.generation import GenerativeJAXModel

        cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32,
                                  num_layers=2)
        net = Llama(cfg)
        params = jax.jit(lambda r: net.init(
            r, jnp.zeros((1, 8), jnp.int32))["params"])(
                jax.random.key(0))
        model = GenerativeJAXModel(
            MODEL, net, params, cfg,
            generation=dict(GEN_KW, role=args.role, seed=args.seed))
    server = ModelServer(max_inflight=128, executor_workers=128)
    server.repo.register(model, load=not args.fake)
    port = server.start_background()
    print(json.dumps({"event": "chaos_replica_ready", "port": port,
                      "role": args.role, "pid": os.getpid()}),
          flush=True)
    while True:  # parked: the parent kills/stalls/terminates us
        time.sleep(3600)


class ReplicaProc:
    """One replica subprocess + its fault controls."""

    def __init__(self, role: str = "any", *, fake: bool = False,
                 seed: int = 0, startup_timeout_s: float = 300.0):
        self.role = role
        cmd = [sys.executable, "-m", "kubeflow_tpu.serve.chaosbench",
               "--worker", "--role", role, "--seed", str(seed)]
        if fake:
            cmd.append("--fake")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # Best-effort shared compile cache across worker subprocesses
        # (ignored by jax versions/backends that don't support it).
        env.setdefault("JAX_COMPILATION_CACHE_DIR",
                       "/tmp/tpk-chaos-jax-cache")
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True)
        self.port: int | None = None
        # The ready line is read on a side thread: readline() blocks
        # indefinitely, so waiting on it directly would let a wedged
        # worker (hung engine build, no output, no exit) hold the
        # whole harness hostage past startup_timeout_s.
        ready = threading.Event()

        def read_ready():
            while True:
                line = self.proc.stdout.readline()
                if not line:
                    return
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("event") == "chaos_replica_ready":
                    self.port = int(ev["port"])
                    ready.set()
                    return

        reader = threading.Thread(target=read_ready, daemon=True,
                                  name="tpk-chaos-worker-ready")
        reader.start()
        if not ready.wait(startup_timeout_s) or self.port is None:
            self.proc.kill()
            raise RuntimeError(
                f"chaos replica worker (role={role}) never became "
                "ready")
        self.url = f"http://127.0.0.1:{self.port}"

    def kill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)

    def stall(self) -> None:
        self.proc.send_signal(signal.SIGSTOP)

    def unstall(self) -> None:
        self.proc.send_signal(signal.SIGCONT)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    def scrape(self, timeout_s: float = 5.0) -> str:
        with urllib.request.urlopen(f"{self.url}/metrics",
                                    timeout=timeout_s) as r:
            return r.read().decode()


def _metric_value(text: str, name: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name) and " " in line:
            base = line.partition(" ")[0].partition("{")[0]
            if base == name:
                try:
                    total += float(line.rpartition(" ")[2])
                except ValueError:
                    pass
    return total


# -- streaming open-loop driver ---------------------------------------------


def _stream_one(base: str, payload: dict, t_origin: float,
                timeout_s: float = 60.0) -> dict:
    """One streaming :generate through the router, reading frames
    INCREMENTALLY. Records per-request truth: token count, error
    frames, the router's provenance (replica header + the done frame's
    `_router` resume/replica trail), TTFT, and the request's wall
    window (for fault-overlap arithmetic)."""
    import urllib.parse

    parts = urllib.parse.urlsplit(base)
    rec = {"t_start_s": time.monotonic() - t_origin, "status": -1,
           "tokens": 0, "ttft_ms": None, "error_frame": False,
           "resumes": 0, "replicas": [], "done": False}
    t0 = time.monotonic()
    conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                      timeout=timeout_s)
    try:
        conn.request(
            "POST", f"/v1/models/{MODEL}:generate",
            body=json.dumps(dict(payload, stream=True)),
            headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        rec["status"] = resp.status
        rec["replica_hdr"] = resp.getheader("X-Tpk-Replica")
        buf = b""
        while True:
            try:
                chunk = resp.read1(65536)
            except (http.client.HTTPException, OSError):
                break  # truncation: any terminal envelope already read
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("tokens") and rec["ttft_ms"] is None:
                    rec["ttft_ms"] = (time.monotonic() - t0) * 1e3
                rec["tokens"] += len(ev.get("tokens") or ())
                if "error" in ev:
                    rec["error_frame"] = True
                if ev.get("done"):
                    rec["done"] = True
                    prov = ev.get("_router") or {}
                    rec["resumes"] = int(prov.get("resumes", 0))
                    rec["replicas"] = list(prov.get("replicas", ()))
            if rec["done"]:
                break
    except Exception as e:
        rec["transport_error"] = f"{type(e).__name__}: {e}"
    finally:
        conn.close()
    rec["t_end_s"] = time.monotonic() - t_origin
    rec["total_ms"] = (time.monotonic() - t0) * 1e3
    return rec


def _open_loop_stream(base: str, prompts, *, rate_rps: float,
                      duration_s: float, max_tokens: int,
                      seed: int) -> list[dict]:
    """Seeded Poisson arrivals, fired AT SCHEDULE (open loop), all
    streaming. One provenance record per request."""
    rng = np.random.default_rng(seed)
    arrivals = []
    t = 0.0
    while t < duration_s:
        t += float(rng.exponential(1.0 / rate_rps))
        if t < duration_s:
            arrivals.append(t)
    records: list[dict] = []
    lock = threading.Lock()
    threads = []
    start = time.monotonic()

    def fire(i: int, sched: float):
        payload = {"input_ids": prompts[i % len(prompts)],
                   "max_tokens": max_tokens}
        rec = _stream_one(base, payload, start)
        rec["sched_s"] = sched
        with lock:
            records.append(rec)

    for i, sched in enumerate(arrivals):
        delay = start + sched - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=fire, args=(i, sched), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=120.0)
    return records


def _overlaps(rec: dict, t0: float, t1: float) -> bool:
    return rec["t_start_s"] < t1 and rec.get("t_end_s", rec["t_start_s"]) > t0


def _goodput(records: list[dict], t0: float, t1: float,
             ok=lambda r: r.get("done")) -> float:
    """Completions/second landing inside [t0, t1)."""
    n = sum(1 for r in records
            if ok(r) and t0 <= r.get("t_end_s", -1.0) < t1)
    return n / max(t1 - t0, 1e-9)


def _pct(vals, p):
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    return round(vals[min(int(len(vals) * p), len(vals) - 1)], 2)


# -- fault schedule ---------------------------------------------------------


def make_schedule(seed: int, duration_s: float) -> dict:
    """The seeded fault schedule, derived from `seed` inside bounded
    windows and RECORDED in the artifact — reruns at the same seed
    replay the same chaos."""
    rng = np.random.default_rng(seed + 7919)
    kill_t = float(rng.uniform(0.30, 0.38) * duration_s)
    relaunch_t = kill_t + 0.16 * duration_s
    drain_t = float(rng.uniform(0.70, 0.78) * duration_s)
    stall_t0 = float(rng.uniform(0.25, 0.30) * duration_s)
    stall_t1 = stall_t0 + 0.35 * duration_s
    return {
        "kill_t_s": round(kill_t, 2),
        "relaunch_t_s": round(relaunch_t, 2),
        "drain_t_s": round(drain_t, 2),
        "stall_window_s": [round(stall_t0, 2), round(stall_t1, 2)],
        "stall_duty": {"stop_s": 0.45, "run_s": 0.15},
        "prefault_window_s": [round(0.08 * duration_s, 2),
                              round(kill_t, 2)],
        "recovery_window_s": [round(relaunch_t + 0.08 * duration_s, 2),
                              round(duration_s, 2)],
    }


class _FaultInjector(threading.Thread):
    """Runs (t_rel_s, fn) actions against the traffic clock."""

    def __init__(self, t_origin: float, actions):
        super().__init__(daemon=True, name="tpk-chaos-faults")
        self.t_origin = t_origin
        self.actions = sorted(actions)
        self.fired: list[float] = []

    def run(self):
        for t_rel, fn in self.actions:
            delay = self.t_origin + t_rel - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                fn()
            except Exception:
                pass  # the bench records outcomes, not injector luck
            self.fired.append(t_rel)


def _kill_when_busy(fleet, name: str, proc: ReplicaProc,
                    t_origin: float, not_before: float,
                    give_up: float) -> float:
    """SIGKILL `proc` at the first instant >= `not_before` (the seeded
    schedule time) at which the router holds an IN-FLIGHT stream on the
    replica — the warm tiny engine finishes a 64-token stream in tens
    of milliseconds, so a purely time-scheduled kill usually lands
    between streams and the mid-stream claim would be vacuous. The
    actual fire time is returned and recorded in the artifact."""
    while time.monotonic() - t_origin < not_before:
        time.sleep(0.005)
    while time.monotonic() - t_origin < give_up:
        rec = fleet.get(name)
        if rec is not None and rec["outstanding"] > 0:
            # Outstanding covers the whole forward, connect included:
            # ride past the TTFT so the kill lands inside the RELAY
            # window (a connect-phase kill would only exercise the
            # plain handoff retry, not the mid-stream resume), then
            # confirm the stream is still open.
            time.sleep(0.03)
            rec = fleet.get(name)
            if rec is not None and rec["outstanding"] > 0:
                break
        time.sleep(0.002)
    proc.kill()
    return time.monotonic() - t_origin


def _stall_cycler(proc: ReplicaProc, until_rel: float, t_origin: float,
                  stop_s: float, run_s: float):
    """Cyclic SIGSTOP/SIGCONT — a slow-but-ALIVE gray replica: probes
    answer in the CONT windows, so the binary down-detector never
    fires, yet every request it owns crawls."""
    def run():
        try:
            while time.monotonic() - t_origin < until_rel:
                proc.stall()
                time.sleep(stop_s)
                proc.unstall()
                time.sleep(run_s)
        finally:
            proc.unstall()
    th = threading.Thread(target=run, daemon=True,
                          name="tpk-chaos-stall")
    th.start()
    return th


# -- arms -------------------------------------------------------------------


def _prompts(seed: int, n: int, length: int, vocab: int = 30000):
    rng = np.random.default_rng(seed)
    return [[int(x) for x in rng.integers(2, vocab, length)]
            for _ in range(n)]


def _mk_router(gray: bool = True):
    from kubeflow_tpu.serve.fleet import Fleet
    from kubeflow_tpu.serve.router import RouterServer

    fleet = Fleet(poll_interval_s=0.2, gray_ejection=gray)
    router = RouterServer(fleet, forward_timeout_s=30.0)
    base = f"http://127.0.0.1:{router.start_background()}"
    return router, base


def arm_disagg_decode_kill(duration: float, rate: float,
                           seed: int) -> dict:
    """SIGKILL a decode replica mid-stream; drain another later."""
    from kubeflow_tpu.utils.resilience import metrics as res_metrics

    sched = make_schedule(seed, duration)
    pre = ReplicaProc("prefill", seed=seed)
    decs = {"d0": ReplicaProc("decode", seed=seed + 1),
            "d1": ReplicaProc("decode", seed=seed + 2)}
    router, base = _mk_router()
    replacement: dict = {}
    resumes0 = (res_metrics.get("tpk_router_resume_total",
                                reason="death") or 0) + \
               (res_metrics.get("tpk_router_resume_total",
                                reason="stall") or 0)
    try:
        router.fleet.add("pre0", pre.url, role="prefill")
        for name, proc in decs.items():
            router.fleet.add(name, proc.url, role="decode")
        time.sleep(0.5)  # first scrape
        fired: dict = {}

        def do_kill():
            fired["kill_t_s"] = round(_kill_when_busy(
                router.fleet, "d0", decs["d0"], t_origin,
                sched["kill_t_s"], sched["relaunch_t_s"] - 0.5), 3)

        def do_relaunch():
            replacement["proc"] = ReplicaProc("decode", seed=seed + 3)
            router.fleet.add("d2", replacement["proc"].url,
                             role="decode")

        def do_drain():
            router.fleet.drain("d1")

        t_origin = time.monotonic()
        inj = _FaultInjector(t_origin, [
            (sched["kill_t_s"], do_kill),
            (sched["relaunch_t_s"], do_relaunch),
            (sched["drain_t_s"], do_drain),
        ])
        inj.start()
        # Streams must be LONG relative to the kill: ~64 tiny-model
        # tokens keeps several streams in flight on the doomed replica
        # at the kill instant, so the resume path is genuinely mid-
        # stream, not connect-phase.
        prompts = _prompts(seed, 24, 12)
        records = _open_loop_stream(base, prompts, rate_rps=rate,
                                    duration_s=duration,
                                    max_tokens=96, seed=seed)
        inj.join(timeout=10)
        completed = [r for r in records if r.get("done")]
        kill_fired = fired.get("kill_t_s", sched["kill_t_s"])
        fault_hits = [r for r in records
                      if _overlaps(r, kill_fired - 0.05,
                                   kill_fired + 0.05)]
        pre_w, rec_w = sched["prefault_window_s"], \
            sched["recovery_window_s"]
        g_pre = _goodput(records, *pre_w)
        g_rec = _goodput(records, *rec_w)
        resumes = sum(r.get("resumes", 0) for r in records)
        resumes_metric = ((res_metrics.get("tpk_router_resume_total",
                                           reason="death") or 0)
                          + (res_metrics.get("tpk_router_resume_total",
                                             reason="stall") or 0)
                          - resumes0)
        prefill_chunks = _metric_value(
            pre.scrape(), "tpk_engine_prefill_chunks_total")
        # Flight-recorder provenance (ISSUE 20): fetched over the admin
        # endpoint (not in-process) so the artifact pins what an
        # operator would actually see, and fetched BEFORE teardown —
        # the ring dies with the router.
        with urllib.request.urlopen(f"{base}/admin/flightrecorder",
                                    timeout=5.0) as r:
            fr = json.loads(r.read())
        fr_resumed_ok = [rec for rec in fr["records"]
                         if rec.get("resumes", 0) > 0
                         and rec.get("outcome") == "ok"]
        flightrecorder = {
            "records": len(fr["records"]),
            "snapshots": len(fr["snapshots"]),
            "snapshot_reasons": sorted({s.get("reason", "")
                                        for s in fr["snapshots"]}),
            "resumed_ok": len(fr_resumed_ok),
            "resumed_ok_multi_replica": sum(
                1 for rec in fr_resumed_ok
                if len(rec.get("replicas", [])) >= 2),
        }
        return {
            "schedule": sched,
            "kill_fired_t_s": fired.get("kill_t_s"),
            "requests": len(records),
            "completed": len(completed),
            "caller_visible_errors": sum(
                1 for r in records
                if r.get("error_frame") or not r.get("done")),
            "token_integrity_violations": sum(
                1 for r in completed if r["tokens"] != 96),
            "streams_overlapping_kill": len(fault_hits),
            "resumes": resumes,
            "router_resume_metric": resumes_metric,
            "resumed_requests": sum(1 for r in records
                                    if r.get("resumes", 0) > 0),
            "fleet_prefill_chunks": prefill_chunks,
            "goodput_prefault_rps": round(g_pre, 2),
            "goodput_recovery_rps": round(g_rec, 2),
            "goodput_recovery_ratio": round(g_rec / max(g_pre, 1e-9), 3),
            "ttft_p50_ms": _pct([r["ttft_ms"] for r in completed], .5),
            "ttft_p99_ms": _pct([r["ttft_ms"] for r in completed], .99),
            "flightrecorder": flightrecorder,
            "router": {k: v for k, v in
                       router.router.stats_snapshot().items()
                       if k in ("handoffs", "handoff_retries", "resumes",
                                "resume_failures", "retries", "errors",
                                "no_replica")},
        }
    finally:
        router.stop()
        pre.stop()
        for p in decs.values():
            p.stop()
        if "proc" in replacement:
            replacement["proc"].stop()


def arm_unified_kill(duration: float, rate: float, seed: int) -> dict:
    """SIGKILL a unified replica mid-stream: honest caller-visible
    failures (every one enveloped), bounded recovery."""
    sched = make_schedule(seed, duration)
    reps = {"u0": ReplicaProc("any", seed=seed),
            "u1": ReplicaProc("any", seed=seed + 1)}
    router, base = _mk_router()
    replacement: dict = {}
    try:
        for name, proc in reps.items():
            router.fleet.add(name, proc.url)
        time.sleep(0.5)
        fired: dict = {}

        def do_kill():
            fired["kill_t_s"] = round(_kill_when_busy(
                router.fleet, "u0", reps["u0"], t_origin,
                sched["kill_t_s"], sched["relaunch_t_s"] - 0.5), 3)

        def do_relaunch():
            replacement["proc"] = ReplicaProc("any", seed=seed + 2)
            router.fleet.add("u2", replacement["proc"].url)

        t_origin = time.monotonic()
        inj = _FaultInjector(t_origin, [
            (sched["kill_t_s"], do_kill),
            (sched["relaunch_t_s"], do_relaunch),
        ])
        inj.start()
        prompts = _prompts(seed + 5, 24, 12)
        records = _open_loop_stream(base, prompts, rate_rps=rate,
                                    duration_s=duration,
                                    max_tokens=96, seed=seed)
        inj.join(timeout=10)
        completed = [r for r in records if r.get("done")]
        failed = [r for r in records if not r.get("done")]
        # Honest accounting: failures that had their 200 status out
        # must carry the terminal envelope (error_frame); ones that
        # never connected surface as transport/5xx errors.
        truncated = [r for r in failed if r.get("status") == 200]
        pre_w, rec_w = sched["prefault_window_s"], \
            sched["recovery_window_s"]
        g_pre = _goodput(records, *pre_w)
        g_rec = _goodput(records, *rec_w)
        return {
            "schedule": sched,
            "kill_fired_t_s": fired.get("kill_t_s"),
            "requests": len(records),
            "completed": len(completed),
            "failed": len(failed),
            "failed_overlapping_kill": sum(
                1 for r in failed
                if _overlaps(r, 0.0, sched["relaunch_t_s"])),
            "truncated_with_envelope": sum(
                1 for r in truncated if r.get("error_frame")),
            "truncated_silently": sum(
                1 for r in truncated if not r.get("error_frame")),
            "goodput_prefault_rps": round(g_pre, 2),
            "goodput_recovery_rps": round(g_rec, 2),
            "goodput_recovery_ratio": round(g_rec / max(g_pre, 1e-9), 3),
        }
    finally:
        router.stop()
        for p in reps.values():
            p.stop()
        if "proc" in replacement:
            replacement["proc"].stop()


def arm_gray_stall(duration: float, rate: float, seed: int) -> dict:
    """Cyclic SIGSTOP/CONT on one of three replicas; ejection ON vs OFF
    at the identical seed/schedule."""
    from kubeflow_tpu.serve.loadgen import open_loop
    from kubeflow_tpu.utils.resilience import metrics as res_metrics

    sched = make_schedule(seed, duration)

    def run(gray: bool) -> dict:
        reps = [ReplicaProc("any", seed=seed + i) for i in range(3)]
        router, base = _mk_router(gray=gray)
        ej0 = sum(res_metrics.get("tpk_fleet_ejections_total",
                                  replica=f"g{i}") or 0
                  for i in range(3))
        rj0 = sum(res_metrics.get("tpk_fleet_rejoins_total",
                                  replica=f"g{i}") or 0
                  for i in range(3))
        try:
            for i, proc in enumerate(reps):
                router.fleet.add(f"g{i}", proc.url)
            time.sleep(0.6)
            t_origin = time.monotonic()
            t0, t1 = sched["stall_window_s"]
            duty = sched["stall_duty"]
            inj = _FaultInjector(t_origin, [
                (t0, lambda: _stall_cycler(
                    reps[0], t1, t_origin, duty["stop_s"],
                    duty["run_s"])),
            ])
            inj.start()
            prompts = _prompts(seed + 9, 24, 12)
            records = open_loop(base, MODEL, prompts, rate_rps=rate,
                                duration_s=duration, max_tokens=8,
                                deadline_ms=None, seed=seed)
            inj.join(timeout=10)
            # Post-stall: give the half-open probes room to rejoin.
            state = router.fleet.get("g0")["state"]
            rejoin_deadline = time.monotonic() + 12.0
            while gray and state == "slow" \
                    and time.monotonic() < rejoin_deadline:
                time.sleep(0.3)
                state = router.fleet.get("g0")["state"]
            lat = [r["latency_ms"] for r in records
                   if r["status"] == 200]
            stall_hits = [r for r in records if _overlaps(r, t0, t1)]
            # The honest tail comparison is the SECOND HALF of the
            # stall window: ejection trips within the first couple of
            # strikes, so requests arriving after the midpoint see the
            # post-ejection fleet — while the control keeps placing a
            # share of them onto the stalled replica. (Overall p99 at
            # these request counts is just the worst sample, and BOTH
            # arms own at least one pre-ejection crawl.)
            mid = (t0 + t1) / 2
            late = [r for r in records if mid <= r["t_start_s"] < t1]
            return {
                "requests": len(records),
                "ok": sum(1 for r in records if r["status"] == 200),
                "errors": sum(1 for r in records
                              if r["status"] not in (200, 503, 504)),
                "p50_ms": _pct(lat, 0.5),
                "p99_ms": _pct(lat, 0.99),
                "late_window_p99_ms": _pct(
                    [r["latency_ms"] for r in late
                     if r["status"] == 200], 0.99),
                "late_window_requests": len(late),
                "late_window_stalled_hits": sum(
                    1 for r in late if r.get("replica") == "g0"),
                "stall_overlapping_requests": len(stall_hits),
                "stalled_replica_requests_during_window": sum(
                    1 for r in stall_hits if r.get("replica") == "g0"),
                "ejections": sum(
                    res_metrics.get("tpk_fleet_ejections_total",
                                    replica=f"g{i}") or 0
                    for i in range(3)) - ej0,
                "rejoins": sum(
                    res_metrics.get("tpk_fleet_rejoins_total",
                                    replica=f"g{i}") or 0
                    for i in range(3)) - rj0,
                "final_stalled_state": state,
            }
        finally:
            router.stop()
            for p in reps:
                p.stop()

    on = run(gray=True)
    off = run(gray=False)
    return {
        "schedule": sched,
        "ejection_on": on,
        "ejection_off": off,
        "p99_ratio_on_vs_off": round(
            (on["p99_ms"] or 0) / max(off["p99_ms"] or 1e-9, 1e-9), 3),
        "late_window_p99_ratio": round(
            (on["late_window_p99_ms"] or 0)
            / max(off["late_window_p99_ms"] or 1e-9, 1e-9), 3),
    }


def arm_ctrl_leader_kill(duration: float, rate: float,
                         seed: int, workdir: str) -> dict:
    """SIGKILL the replicated control-plane LEADER while the router
    serves traffic; serving must not blip and the autoscaler's next
    reconcile must land on the promoted follower."""
    try:
        from kubeflow_tpu.controlplane.client import find_binary

        find_binary()
    except (ImportError, FileNotFoundError):
        return {"skipped": "binary_not_built"}
    from kubeflow_tpu.controlplane.replication import ReplicaSet
    from kubeflow_tpu.serve.fleet import ControlPlaneScaler
    from kubeflow_tpu.serve.loadgen import open_loop

    sched = make_schedule(seed, duration)
    rs = ReplicaSet(workdir, n=3, lease_ms=400)
    rs.start()
    reps = [ReplicaProc("any", seed=seed + i) for i in range(2)]
    router, base = _mk_router()
    killed: dict = {}
    try:
        lead = rs.wait_leader()
        client = rs.client(timeout=30.0, deadline_s=30.0)
        # replicas=0: the reconcile target EXISTS (created pre-kill, so
        # the promoted follower must have replicated it) without the
        # controller launching replica processes into the bench's CPU
        # budget (there is no real bundle behind it).
        client.create("InferenceService", "chaos-isvc",
                      {"model": {"name": MODEL,
                                 "model_dir": "/nonexistent-chaos"},
                       "replicas": 0, "cpu_devices": 1})
        for i, proc in enumerate(reps):
            router.fleet.add(f"c{i}", proc.url)
        time.sleep(0.5)

        def do_kill():
            killed["lead"] = lead
            rs.handles[lead].proc.send_signal(signal.SIGKILL)

        t_origin = time.monotonic()
        inj = _FaultInjector(t_origin, [(sched["kill_t_s"], do_kill)])
        inj.start()
        prompts = _prompts(seed + 13, 24, 12)
        records = open_loop(base, MODEL, prompts, rate_rps=rate,
                            duration_s=duration, max_tokens=8,
                            deadline_ms=None, seed=seed)
        inj.join(timeout=10)
        # The reconcile AFTER failover: the scaler's spec.replicas
        # patch rides the client's redirect-chasing to the promoted
        # follower.
        scaler = ControlPlaneScaler(client, "chaos-isvc")
        scaler.scale_up()
        after = client.get("InferenceService", "chaos-isvc")
        new_lead = rs.wait_leader(exclude=lead)
        client.delete("InferenceService", "chaos-isvc")
        client.close()
        return {
            "schedule": sched,
            "requests": len(records),
            "ok": sum(1 for r in records if r["status"] == 200),
            "non_200_during_failover": sum(
                1 for r in records if r["status"] != 200),
            "killed_leader": lead,
            "promoted_leader": new_lead,
            "reconcile_replicas_after": int(
                after["spec"]["replicas"]),
        }
    finally:
        router.stop()
        for p in reps:
            p.stop()
        rs.stop()


# -- entrypoint -------------------------------------------------------------


def run_chaosbench(quick: bool = False, seed: int = 0) -> dict:
    import shutil
    import tempfile

    duration = 12.0 if quick else 26.0
    rate = 3.0 if quick else 4.0
    result: dict = {
        "metric": "chaosbench",
        "mode": "real-tiny-engines-subprocess",
        "note": ("replicas are REAL GenerationEngines (tiny model, "
                 "CPU) in their OWN subprocesses behind real "
                 "ModelServers and the real router, so SIGKILL/SIGSTOP "
                 "are the real faults; absolute latencies are 1-CPU "
                 "tiny-model numbers — the artifact is the claims "
                 "(zero-error resume, bounded recovery, ejection vs "
                 "control) computed from per-request provenance rows"),
        "params": {"duration_s": duration, "rate_rps": rate,
                   "seed": seed, "quick": bool(quick),
                   "gen_kw": dict(GEN_KW)},
        "arms": {},
    }
    result["arms"]["disagg_decode_kill"] = arm_disagg_decode_kill(
        duration, rate, seed)
    result["arms"]["unified_kill"] = arm_unified_kill(
        duration, rate, seed)
    result["arms"]["gray_stall"] = arm_gray_stall(
        duration, max(rate * 0.75, 2.0), seed)
    base = tempfile.mkdtemp(prefix="tpk-chaos-ctrl-")
    try:
        result["arms"]["ctrl_leader_kill"] = arm_ctrl_leader_kill(
            duration, rate, seed, base)
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return result


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="tpk-chaosbench")
    p.add_argument("--worker", action="store_true")
    p.add_argument("--role", default="any",
                   choices=("any", "prefill", "decode", "unified"))
    p.add_argument("--fake", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quick", action="store_true")
    args = p.parse_args(argv)
    if args.worker:
        if args.role == "any":
            args.role = "unified"
        return _worker_main(args)
    out = run_chaosbench(quick=args.quick)
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
