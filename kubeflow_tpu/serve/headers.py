"""Wire-protocol header names shared by the replica server and the
front-door router (ISSUE 9).

This module must stay DEPENDENCY-FREE: server.py imports the engine
stack (jax) at module level, so any constant the router needs must live
where importing it costs nothing — the router process (and its first
proxied request) must never pay the engine's import stall or RSS.
"""

#: End-to-end request budget in milliseconds. The router re-issues it
#: to the replica as the REMAINING budget at forward time — deadline
#: propagation, not per-hop resets.
DEADLINE_HEADER = "X-Request-Timeout-Ms"

#: Trace identity: honored when the caller sets it, assigned otherwise,
#: echoed back, and forwarded replica-ward — one id across the fabric.
REQUEST_ID_HEADER = "X-Request-Id"

#: Marker the replica sets on a drain shed (server.py admit()) so the
#: router can tell "draining — retry elsewhere" from "overloaded —
#: forward the backpressure".
DRAINING_HEADER = "X-Tpk-Draining"

#: Router-set response provenance (ISSUE 14): the replica that served
#: the request (for streams, the FIRST replica — later mid-stream
#: resumes ride the ndjson done frame's `_router` field, since response
#: headers are already on the wire by then) and how many placement
#: attempts the request took. Load harnesses read these so chaos-claim
#: arithmetic runs on per-request truth, not aggregates.
REPLICA_HEADER = "X-Tpk-Replica"
ATTEMPTS_HEADER = "X-Tpk-Attempts"
