"""Shared tokenizer plumbing for the generative serving models.

One copy of the bundled-tokenizer probe and the ids/text resolution used
by both the decoder-only engine wrapper (GenerativeJAXModel) and the
encoder-decoder wrapper (Text2TextJAXModel) — these were diverging
copies (round-4 review finding).
"""

from __future__ import annotations

import os

#: Files whose presence marks an HF checkpoint dir as carrying its own
#: tokenizer (fast JSON, sentencepiece Llama-style, sentencepiece T5).
TOKENIZER_FILES = ("tokenizer.json", "tokenizer.model", "spiece.model")


def load_bundled_tokenizer(ckpt: str, name: str):
    """AutoTokenizer from the checkpoint dir, or None (missing files or a
    failed load — logged, never fatal: the model still serves raw ids)."""
    if not any(os.path.exists(os.path.join(ckpt, f))
               for f in TOKENIZER_FILES):
        return None
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(ckpt)
    except Exception as e:
        print(f"tokenizer load skipped for {name}: {e}", flush=True)
        return None


def resolve_ids(tokenizer, payload: dict) -> list[int]:
    """'input_ids' | 'text' → non-empty token id list, or ValueError."""
    ids = payload.get("input_ids")
    text = payload.get("text")
    if ids is None and text is not None:
        if tokenizer == "bytes":
            ids = list(text.encode("utf-8"))
        elif hasattr(tokenizer, "encode"):  # HF-style tokenizer
            ids = list(tokenizer.encode(text))
        else:
            raise ValueError(
                "this model takes token ids ('input_ids'); no tokenizer "
                "is bundled")
    if ids is None:
        raise ValueError("request needs 'input_ids' (or 'text')")
    if not len(ids):
        raise ValueError("'input_ids'/'text' must be non-empty")
    return [int(i) for i in ids]


def decode_ids(tokenizer, ids: list[int]) -> str:
    if tokenizer == "bytes":
        return bytes(t for t in ids if 0 <= t < 256).decode(
            "utf-8", errors="replace")
    return tokenizer.decode(ids, skip_special_tokens=True)
