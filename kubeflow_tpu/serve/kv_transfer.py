"""KV blocks as a wire format + the host-RAM block tier (ISSUE 13).

The paged KV cache (serve/paging.py, ISSUE 6) made a request's decode
state a *transferable unit*: fixed-size refcounted blocks plus a block
table. This module is everything that moves those blocks OFF the device
pool and back:

  * **Wire format.** `pack_shipment`/`unpack_shipment` frame a JSON
    metadata header plus raw array payloads (per-layer K/V block
    gathers) into one byte string — versioned, magic-prefixed, with no
    pickle anywhere. The SAME bytes serve two transports:

      - **prefill→decode handoff** (DistServe-style disaggregation): a
        prefill replica chunk-prefills a prompt into pool blocks, ships
        `committed blocks + tokens + sampled first token/logprob + RNG
        key state` through the router to a decode replica, which admits
        the request straight into decode — zero prefill chunks ever run
        on a decode replica.
      - **host-RAM spill tier**: cold prefix-cache blocks evicted under
        pool pressure serialize through the same path into `HostKVTier`
        and restore on the next hit, lifting the effective pool beyond
        HBM.

  * **HostKVTier.** A bounded LRU of packed block payloads keyed the
    way the engine prefix cache is keyed — `(adapter, prefix_len,
    hash(tokens))` with the token tuple stored for hash-collision
    verification and a per-adapter length index for longest-prefix
    probes. Capacity is counted in BLOCKS (the pool's own currency).

Format versions (the `fmt` meta field — the frame itself never
changes, only what rides in it):

  * **fmt 1** — full-precision K/V blocks.
  * **fmt 2** — fmt 1 plus a versioned draft-KV section (speculative
    prefill handoff); refused by draft-less decode replicas.
  * **fmt 3** — QUANTIZED blocks (ISSUE 19): `k`/`v` arrays carry the
    raw int8/fp8 payloads, `ks`/`vs` carry the per-row-per-head f32
    scale planes, and `meta["kv_quant"]` names the mode. ≈2× smaller
    on the wire than fmt 1 for the same blocks. A decode replica whose
    `kv_quant` does not match refuses at submit_remote — never a
    silent dequant-upcast (mixed-precision fleets must not split a
    stream's numerics by which replica prefilled it). fmt 1 into a
    quantized replica is accepted: it quantizes at import with the
    same encode local admission uses. fmt 3 never combines with the
    draft section (`kv_quant × draft` is refused at engine init).

Determinism note: the shipment carries the prefill engine's RNG key
state (post-admission-splits, `jax.random.key_data`). A decode engine
that adopts it continues the exact key-split stream the unified engine
would have used, which is what makes a disaggregated stream
token+logprob-identical to the unified engine on the same seed
(test-pinned in tests/test_kv_transfer.py, per-stream — concurrent
shipments multiplex one engine key, exactly as concurrent local
admissions always have).
"""

from __future__ import annotations

import json
import struct
import threading
from collections import OrderedDict

import numpy as np

#: Wire magic + format version. Bump the digit on any layout change;
#: unpack refuses unknown versions loudly (a silently misparsed KV
#: payload would decode garbage tokens, not crash).
MAGIC = b"TPKV1\n"

_LEN = struct.Struct(">Q")


class ShipmentError(ValueError):
    """Malformed / incompatible shipment bytes (bad magic, truncated
    frame, unknown version, dtype/shape mismatch with this engine)."""


def _dtype_of(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 and friends live in ml_dtypes (a jax dependency).
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def pack_shipment(meta: dict, arrays: dict) -> bytes:
    """Frame `meta` (JSON-safe dict) + named host arrays into one byte
    string: MAGIC, u64 header length, JSON header, raw buffers in
    header order. Arrays round-trip byte-identically (C-order)."""
    names = sorted(arrays)
    specs = []
    bufs = []
    for name in names:
        arr = np.ascontiguousarray(arrays[name])
        specs.append({"name": name, "dtype": str(arr.dtype),
                      "shape": list(arr.shape)})
        bufs.append(arr.tobytes())
    header = json.dumps({"meta": meta, "arrays": specs},
                        sort_keys=True).encode()
    return b"".join([MAGIC, _LEN.pack(len(header)), header] + bufs)


def _parse_header(data) -> tuple[dict, memoryview, int]:
    """Shared frame parse: validate magic + length, decode the JSON
    header → (header, data_view, payload_offset). THE single home of
    the header layout — unpack_shipment, peek_meta, and rewrite_meta
    all go through it, so a format change cannot silently diverge the
    three parsers. Every malformation raises ShipmentError."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise ShipmentError(f"shipment must be bytes, got {type(data)}")
    data = memoryview(data)
    if bytes(data[:len(MAGIC)]) != MAGIC:
        raise ShipmentError(
            f"bad shipment magic {bytes(data[:len(MAGIC)])!r} "
            f"(want {MAGIC!r})")
    off = len(MAGIC)
    if len(data) < off + _LEN.size:
        raise ShipmentError("truncated shipment header length")
    (hlen,) = _LEN.unpack(bytes(data[off:off + _LEN.size]))
    off += _LEN.size
    if len(data) < off + hlen:
        raise ShipmentError("truncated shipment header")
    try:
        header = json.loads(bytes(data[off:off + hlen]))
    except ValueError as e:
        raise ShipmentError(f"bad shipment header: {e}") from e
    if not isinstance(header, dict):
        raise ShipmentError(
            f"bad shipment header: expected object, got "
            f"{type(header).__name__}")
    return header, data, off + hlen


def unpack_shipment(data: bytes) -> tuple[dict, dict]:
    """Inverse of `pack_shipment` → (meta, {name: np.ndarray}). Every
    malformation raises ShipmentError — truncated or alien bytes must
    never come back as a half-parsed cache."""
    header, data, off = _parse_header(data)
    try:
        meta = header["meta"]
        specs = header["arrays"]
    except KeyError as e:
        raise ShipmentError(f"bad shipment header: {e}") from e
    arrays = {}
    for spec in specs:
        try:
            dt = _dtype_of(spec["dtype"])
            shape = tuple(int(s) for s in spec["shape"])
        except (AttributeError, KeyError, TypeError, ValueError) as e:
            raise ShipmentError(f"bad array spec {spec!r}: {e}") from e
        n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if len(data) < off + n:
            raise ShipmentError(
                f"truncated shipment payload for {spec.get('name')!r}")
        arrays[spec["name"]] = np.frombuffer(
            data[off:off + n], dtype=dt).reshape(shape)
        off += n
    if off != len(data):
        raise ShipmentError(
            f"{len(data) - off} trailing bytes after shipment payload")
    return meta, arrays


def rewrite_meta(data, **updates) -> bytes:
    """Return a copy of a shipment with `updates` merged into its meta
    header — the array payload bytes are spliced through UNTOUCHED (no
    unpack, no array copies), so annotating a multi-MB shipment costs
    one header re-encode. The router uses this to stamp the RESUME
    CURSOR (`resume_skip`) onto a held shipment before re-submitting it
    to a surviving decode replica: the decode engine replays the same
    deterministic token stream and the cursor tells it how many leading
    tokens the caller has already been served (ISSUE 14)."""
    header, data, off = _parse_header(data)
    try:
        header["meta"].update(updates)
    except (KeyError, AttributeError) as e:
        raise ShipmentError(f"bad shipment header: {e}") from e
    new_header = json.dumps(header, sort_keys=True).encode()
    return b"".join([MAGIC, _LEN.pack(len(new_header)), new_header,
                     bytes(data[off:])])


def peek_meta(data) -> dict:
    """Parse ONLY the metadata header of a shipment (no array copies) —
    the server's :decode handler reads the stream flag and sizing here
    before handing the full payload to the engine."""
    header, _data, _off = _parse_header(data)
    try:
        return header["meta"]
    except KeyError as e:
        raise ShipmentError(f"bad shipment header: {e}") from e


class HostKVTier:
    """Host-RAM LRU tier for spilled KV block payloads.

    Keys follow the engine prefix cache's family — `(aid, n,
    hash(tokens))`, token tuple stored for verification, per-adapter
    length index for longest-prefix probes — so a spilled prefix is
    findable by exactly the probe that would have hit it in HBM.
    `take()` REMOVES the entry (restore-on-hit moves blocks back to the
    pool; the tier never holds a second copy of resident state).

    All state is mutated under one lock: the engine worker spills and
    restores, while metrics readers snapshot counters from other
    threads."""

    def __init__(self, capacity_blocks: int):
        if capacity_blocks < 1:
            raise ValueError(
                f"capacity_blocks must be >= 1, got {capacity_blocks}")
        self.capacity_blocks = int(capacity_blocks)
        # key -> (token_tuple, n_blocks, payload_bytes)
        self._lru: OrderedDict = OrderedDict()  # guarded-by: _lock
        self._lens: dict[int, dict[int, int]] = {}  # guarded-by: _lock
        self._blocks = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        self.stats = {  # guarded-by: _lock
            "spilled_blocks": 0, "restored_blocks": 0,
            "evicted_blocks": 0, "rejected_blocks": 0,
        }

    @property
    def resident_blocks(self) -> int:
        with self._lock:
            return self._blocks

    def stats_snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats, resident_blocks=self._blocks,
                        entries=len(self._lru))

    @staticmethod
    def _drop(lru: OrderedDict, lens: dict, stats: dict, key: tuple,
              counter: str) -> int:
        """Remove one entry from the passed-in table state (callers hold
        `_lock` and pass the guarded containers explicitly — the helper
        itself touches no `self` field, so the lock discipline stays
        lexically checkable). Returns the freed block count."""
        _, n, _ = lru.pop(key)
        stats[counter] += n
        aid, ln, _ = key
        per = lens.get(aid, {})
        if per.get(ln, 0) <= 1:
            per.pop(ln, None)
            if not per:
                lens.pop(aid, None)
        else:
            per[ln] -= 1
        return n

    def put(self, aid: int, kt: tuple, n_blocks: int,
            payload: bytes) -> bool:
        """Spill one prefix's packed blocks. Evicts LRU entries to make
        room; an entry larger than the whole tier is refused (False) —
        spilling it would just wipe the tier for nothing."""
        n_blocks = int(n_blocks)
        if n_blocks > self.capacity_blocks:
            with self._lock:
                self.stats["rejected_blocks"] += n_blocks
            return False
        key = (aid, len(kt), hash(kt))
        with self._lock:
            existing = self._lru.get(key)
            if existing is not None:
                if existing[0] == kt:
                    self._lru.move_to_end(key)
                    return True  # already resident: pure LRU touch
                self._blocks -= self._drop(  # hash collision
                    self._lru, self._lens, self.stats, key,
                    "evicted_blocks")
            while self._blocks + n_blocks > self.capacity_blocks:
                oldest = next(iter(self._lru))
                self._blocks -= self._drop(
                    self._lru, self._lens, self.stats, oldest,
                    "evicted_blocks")
            per = self._lens.setdefault(aid, {})
            per[len(kt)] = per.get(len(kt), 0) + 1
            self._lru[key] = (kt, n_blocks, payload)
            self._blocks += n_blocks
            self.stats["spilled_blocks"] += n_blocks
        return True

    def take(self, aid: int, kt: tuple) -> tuple[int, bytes] | None:
        """Remove and return (n_blocks, payload) for an exact prefix, or
        None. Restore-on-hit: the caller re-materializes the blocks in
        the pool, so the tier copy is retired here."""
        key = (aid, len(kt), hash(kt))
        with self._lock:
            entry = self._lru.get(key)
            if entry is None or entry[0] != kt:
                return None
            _, n, payload = entry
            self._blocks -= self._drop(self._lru, self._lens,
                                       self.stats, key,
                                       "restored_blocks")
        return n, payload

    def probe_longest(self, aid: int, ids) -> int | None:
        """Longest spilled prefix STRICTLY shorter than `ids` (the same
        contract as the engine's `_prefix_probe_paged`), or None. Read
        only — the caller follows up with `take()` once it has blocks
        to restore into."""
        with self._lock:
            lens = self._lens.get(aid)
            if not lens:
                return None
            for n in sorted(lens, reverse=True):
                if n >= len(ids):
                    continue
                kt = tuple(ids[:n])
                entry = self._lru.get((aid, n, hash(kt)))
                if entry is not None and entry[0] == kt:
                    return n
        return None
