"""OpenAI-compatible serving surface (completions + chat), under /openai.

The reference's huggingfaceserver exposes the OpenAI REST API in front of
vLLM ⟨kserve: python/huggingfaceserver — openai endpoints⟩; this is the
TPU-native equivalent in front of the generation engine:

  POST /openai/v1/completions        {"model", "prompt", ...}
  POST /openai/v1/chat/completions   {"model", "messages": [...], ...}
  GET  /openai/v1/models

Both POST surfaces support "stream": true as server-sent events
(`data: {...}\n\n`, terminated by `data: [DONE]\n\n`) riding the engine's
chunk-granular streaming, and `stop` sequences (text-level truncation —
the engine decodes on; vLLM stops the sampler, we stop the surface).
Chat prompts use the bundled HF tokenizer's chat template when it has
one, else a plain role-prefixed transcript. Errors use the OpenAI error
envelope. The namespace is prefixed (/openai) exactly like the
reference, so the v1 predict protocol keeps /v1/models.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any

import tornado.web

from kubeflow_tpu.serve.generation import KVCapacityExceeded
from kubeflow_tpu.serve.server import _Base, admission_gated, pump_stream


class _OpenAIBase(_Base):
    """Shares the server's handler base (repo access, JSON body parsing,
    request logging); only the error ENVELOPE differs."""

    def write_error(self, status_code: int, **kwargs) -> None:
        reason = self._reason
        if "exc_info" in kwargs:
            exc = kwargs["exc_info"][1]
            if not isinstance(exc, tornado.web.HTTPError):
                reason = f"{type(exc).__name__}: {exc}"
        self.set_header("Content-Type", "application/json")
        self.finish(json.dumps({"error": {
            "message": reason, "type": ("invalid_request_error"
                                        if status_code < 500
                                        else "internal_error"),
            "code": status_code}}))

    def shed_body(self) -> dict:
        # Admission sheds must wear the OpenAI envelope too: SDK clients
        # parse resp["error"]["message"]/["type"], not a bare string.
        return {"error": {
            "message": "server overloaded: admission queue full",
            "type": "overloaded_error", "code": 503}}

    def capacity_body(self, msg: str) -> dict:
        return {"error": {"message": msg, "type": "overloaded_error",
                          "code": 503}}

    def _generative(self, name: str):
        """Resolve an OpenAI model id to (model, adapter | None). The
        vLLM multi-LoRA convention: a loaded LoRA adapter's name IS a
        servable model id — "<base>:<adapter>" or the bare adapter name
        (when unambiguous) route to the base engine with that adapter
        selected per request.

        Precedence: a bare name resolves to a registered MODEL first; an
        adapter that shares a model's name stays reachable through the
        explicit "<base>:<adapter>" form (the model has no equivalent
        explicit form, so the model must win the bare lookup)."""
        def lookup(n):
            try:
                return self.repo.get(n)
            except tornado.web.HTTPError:
                return None  # repo.get 404s on unknown names

        model = lookup(name or "")
        adapter = None
        if model is None and name:
            base_name, _, ad = name.partition(":")
            if ad:
                cand = lookup(base_name)
                if cand is not None and ad in self._adapters_of(cand):
                    model, adapter = cand, ad
            else:
                hits = self._adapter_owners(name)
                if len(hits) == 1:
                    model, adapter = hits[0], name
                elif len(hits) > 1:
                    raise tornado.web.HTTPError(
                        400, reason=(
                            f"adapter name {name!r} is ambiguous (loaded "
                            "on multiple models); use "
                            "'<base>:<adapter>'"))
        if model is None:
            raise tornado.web.HTTPError(
                404, reason=f"model {name!r} not found")
        if getattr(model, "generate", None) is None:
            raise tornado.web.HTTPError(
                400, reason=f"model {name!r} is not generative")
        return model, adapter

    @staticmethod
    def _adapters_of(model) -> list:
        eng = getattr(model, "engine", None)
        if eng is None or not hasattr(eng, "adapter_names"):
            return []
        return eng.adapter_names()

    def _adapter_owners(self, adapter_name: str) -> list:
        """Loaded models that carry an adapter of this name."""
        out = []
        for n in self.repo.names():
            try:
                m = self.repo.get(n)
            except tornado.web.HTTPError:
                continue
            if adapter_name in self._adapters_of(m):
                out.append(m)
        return out


def _payload_from(body: dict) -> dict:
    if body.get("n", 1) != 1:
        raise tornado.web.HTTPError(400, reason="n > 1 is not supported")
    payload: dict[str, Any] = {
        "max_tokens": int(body.get("max_tokens", 16)),
        "temperature": float(body.get("temperature", 1.0)),
        "top_p": float(body.get("top_p", 1.0)),
    }
    if body.get("top_k") is not None:  # common extension
        payload["top_k"] = int(body["top_k"])
    return payload


def _stop_list(body: dict) -> list[str]:
    stop = body.get("stop")
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    if (not isinstance(stop, list) or len(stop) > 4
            or not all(isinstance(s, str) and s for s in stop)):
        raise tornado.web.HTTPError(
            400, reason="stop must be a non-empty string or up to 4 of "
                        "them")
    return stop


def _truncate_at_stop(text: str, stops: list[str]) -> tuple[str, bool]:
    """(text up to the earliest stop sequence — excluded, per OpenAI —
    and whether one matched)."""
    cut = -1
    for s in stops:
        i = text.find(s)
        if i >= 0 and (cut < 0 or i < cut):
            cut = i
    return (text[:cut], True) if cut >= 0 else (text, False)


def _chat_ids_or_text(model, messages: list) -> dict:
    """messages → generate payload. HF tokenizers with a chat template
    render it; otherwise a plain role-prefixed transcript with a trailing
    assistant cue."""
    if (not isinstance(messages, list) or not messages
            or not all(isinstance(m, dict) for m in messages)):
        raise tornado.web.HTTPError(
            400, reason="messages must be a non-empty array of objects")
    tok = getattr(model, "tokenizer", None)
    if hasattr(tok, "apply_chat_template") and getattr(
            tok, "chat_template", None):
        ids = tok.apply_chat_template(messages, tokenize=True,
                                      add_generation_prompt=True)
        return {"input_ids": list(ids)}
    text = "\n".join(
        f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages)
    return {"text": text + "\nassistant:"}


def _finish_reason(out: dict, max_tokens: int, stopped: bool) -> str:
    if stopped:
        return "stop"
    return "length" if out.get("num_output_tokens", 0) >= max_tokens \
        else "stop"


def _token_strings(model, ids: list) -> list[str]:
    """Byte-faithful per-token strings: multi-byte UTF-8 split across
    byte tokens must stay identifiable (OpenAI renders such tokens as
    bytes:0x..), and special tokens must not vanish — so NOT the
    skip-special full decode."""
    tok = getattr(model, "tokenizer", None)
    if tok == "bytes":
        return [chr(t) if 32 <= t < 127 else
                (f"bytes:{t:#04x}" if 0 <= t < 256 else str(t))
                for t in ids]
    if hasattr(tok, "convert_ids_to_tokens"):
        return [str(s) for s in tok.convert_ids_to_tokens(ids)]
    if hasattr(tok, "decode"):
        return [tok.decode([t]) for t in ids]
    return [str(t) for t in ids]


def _usage(out: dict) -> dict:
    p = out.get("num_input_tokens", 0)
    c = out.get("num_output_tokens", 0)
    return {"prompt_tokens": p, "completion_tokens": c,
            "total_tokens": p + c}


class _GenerativeHandler(_OpenAIBase):
    object_name = ""  # "text_completion" | "chat.completion"

    def make_payload(self, model, body: dict) -> dict:
        raise NotImplementedError

    def choice(self, out_text: str, finish, lp=None) -> dict:
        raise NotImplementedError

    def logprobs_obj(self, model, out) -> dict:
        raise NotImplementedError

    def wants_logprobs(self, body: dict) -> bool:
        raise NotImplementedError

    def delta_choice(self, delta: str, first: bool, finish) -> dict:
        raise NotImplementedError

    # Same admission gate as the native data plane: the OpenAI facade
    # must not become an unbounded side door around --max-inflight.
    @admission_gated
    async def post(self):
        body = self.body_json()
        if not isinstance(body, dict):
            raise tornado.web.HTTPError(400, reason="body must be an object")
        name = body.get("model", "")
        model, adapter = self._generative(name)
        stops = _stop_list(body)
        if stops and getattr(model, "tokenizer", None) is None:
            raise tornado.web.HTTPError(
                400, reason="stop sequences need a tokenizer-bundled model")
        try:
            payload = {**self.make_payload(model, body),
                       **_payload_from(body)}
            if adapter is not None:
                payload["adapter"] = adapter
        except tornado.web.HTTPError:
            raise
        except (TypeError, ValueError) as e:
            # Malformed fields (max_tokens: "abc", temperature: null, a
            # non-dict chat message, ...) are the CLIENT's fault — the
            # OpenAI envelope contract wants 400 invalid_request_error,
            # not a 500.
            raise tornado.web.HTTPError(
                400, reason=f"invalid request field: {e}") from None
        deadline = self.request_deadline()
        if deadline is not None:
            # In-process deadline propagation, exactly as the native
            # :generate path: the engine frees the decode slot on expiry.
            payload["_deadline"] = deadline
        # Trace propagation, exactly as the native path (the payload is
        # rebuilt from whitelisted fields, so a wire "_trace" can't ride
        # in): the facade's engine spans carry X-Request-Id too.
        payload["_trace"] = self.trace_id
        rid = f"{'chatcmpl' if 'chat' in self.object_name else 'cmpl'}-" \
              f"{uuid.uuid4().hex[:24]}"
        t0 = time.monotonic()
        if body.get("stream"):
            if self.wants_logprobs(body):
                raise tornado.web.HTTPError(
                    400, reason="logprobs with stream is not supported")
            await self._stream(name, model, payload, rid, stops, t0)
            return
        try:
            out = await self.await_bounded(
                self.submit_blocking(model.generate, payload), deadline)
        except KVCapacityExceeded as e:
            # Same shed semantics as the native :generate path, wearing
            # the OpenAI envelope via the capacity_body override.
            self.write_capacity_shed(str(e))
            return
        except (ValueError, RuntimeError) as e:
            raise tornado.web.HTTPError(400, reason=str(e)) from None
        text, stopped = _truncate_at_stop(out.get("text", ""), stops)
        finish = _finish_reason(out, payload["max_tokens"], stopped)
        # Chosen-token logprobs on request (top-N alternatives are not
        # computed; with stop truncation the list covers all SAMPLED
        # tokens, which may extend past the text cut).
        lp = (self.logprobs_obj(model, out)
              if self.wants_logprobs(body) else None)
        self.server.observe(name, out.get("num_output_tokens", 0),
                            time.monotonic() - t0)
        self.write_json({
            "id": rid, "object": self.object_name,
            "created": int(time.time()), "model": name,
            "choices": [self.choice(text, finish, lp)],
            "usage": _usage(out),
        })

    async def _stream(self, name, model, payload, rid, stops, t0):
        it = model.generate_stream(payload)
        base = {"id": rid, "object": self.object_name + ".chunk",
                "created": int(time.time()), "model": name}
        # With stop sequences, text is emitted through a pending buffer
        # that always withholds the last max(len(stop))-1 chars — a stop
        # spanning chunk boundaries can then still be excluded (already-
        # sent text can't be retracted), and the per-chunk search scans
        # only the bounded buffer, not the whole cumulative output.
        hold = max((len(s) for s in stops), default=1) - 1
        pending = ""
        tokens_out = 0
        stopped = False

        def sse(obj) -> None:
            self.write("data: " + json.dumps(obj) + "\n\n")

        def emit_text(delta: str, final: bool) -> str:
            nonlocal pending, stopped
            if not stops:
                return delta
            pending += delta
            whole, hit = _truncate_at_stop(pending, stops)
            if hit:
                pending, stopped = "", True
                return whole
            if final:
                out, pending = pending, ""
                return out
            keep = min(hold, len(pending))
            out = pending[:len(pending) - keep] if keep else pending
            pending = pending[len(pending) - keep:] if keep else ""
            return out

        def render(ev, first):
            nonlocal tokens_out, stopped
            if first:
                self.set_header("Content-Type", "text/event-stream")
                self.set_header("Cache-Control", "no-cache")
            done = bool(ev.get("done"))
            delta = emit_text(ev.get("text_delta", ""), done)
            tokens_out += len(ev.get("tokens", ()))
            if delta:
                sse({**base, "choices": [
                    self.delta_choice(delta, first, None)]})
            elif first and not done:
                sse({**base, "choices": [
                    self.delta_choice("", True, None)]})
            if done or stopped:
                finish = _finish_reason(ev if done else {},
                                        payload["max_tokens"], stopped)
                sse({**base, "usage": _usage(ev) if done else None,
                     "choices": [self.delta_choice("", False, finish)]})
                self.write("data: [DONE]\n\n")
                return True
            return False

        def render_error(msg):
            return "data: " + json.dumps({"error": {
                "message": msg, "type": "internal_error"}}) + "\n\n"

        await pump_stream(self, it, render, render_error)
        if stopped:
            it.close()  # stop consuming; engine finishes in background
        self.server.observe(name, tokens_out, time.monotonic() - t0)


class CompletionsHandler(_GenerativeHandler):
    object_name = "text_completion"

    def make_payload(self, model, body: dict) -> dict:
        prompt = body.get("prompt")
        if isinstance(prompt, list) and prompt and all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in prompt):
            return {"input_ids": prompt}
        if isinstance(prompt, list) and len(prompt) == 1 and isinstance(
                prompt[0], str):
            prompt = prompt[0]
        if isinstance(prompt, str):
            return {"text": prompt}
        raise tornado.web.HTTPError(
            400, reason="prompt must be a string or a token-id array")

    def choice(self, out_text, finish, lp=None):
        return {"index": 0, "text": out_text, "logprobs": lp,
                "finish_reason": finish}

    def logprobs_obj(self, model, out):
        return {"tokens": _token_strings(model, out.get("output_ids", [])),
                "token_logprobs": out.get("output_logprobs", []),
                "top_logprobs": None, "text_offset": None}

    def wants_logprobs(self, body):
        # Legacy completions semantics: logprobs is an int, and 0 is a
        # VALID request (chosen-token logprobs, zero alternatives).
        return body.get("logprobs") is not None

    def delta_choice(self, delta, first, finish):
        return {"index": 0, "text": delta, "logprobs": None,
                "finish_reason": finish}


class ChatCompletionsHandler(_GenerativeHandler):
    object_name = "chat.completion"

    def make_payload(self, model, body: dict) -> dict:
        return _chat_ids_or_text(model, body.get("messages"))

    def choice(self, out_text, finish, lp=None):
        c = {"index": 0, "finish_reason": finish,
             "message": {"role": "assistant", "content": out_text}}
        if lp is not None:
            c["logprobs"] = lp
        return c

    def logprobs_obj(self, model, out):
        toks = _token_strings(model, out.get("output_ids", []))
        # bytes/top_logprobs are part of the chat schema — strict SDK
        # consumers construct models from these keys.
        return {"content": [
            {"token": s, "logprob": l, "bytes": None, "top_logprobs": []}
            for s, l in zip(toks, out.get("output_logprobs", []))]}

    def wants_logprobs(self, body):
        return bool(body.get("logprobs"))

    def delta_choice(self, delta, first, finish):
        d: dict = {"content": delta} if delta else {}
        if first:
            d["role"] = "assistant"
        return {"index": 0, "delta": d, "finish_reason": finish}


class ModelsHandler(_OpenAIBase):
    def get(self):
        data = []
        for n in self.repo.names():
            data.append({"id": n, "object": "model", "owned_by": "tpukit"})
            # LoRA adapters list as servable models (vLLM convention).
            for ad in self._adapters_of(self.repo.get(n)):
                data.append({"id": f"{n}:{ad}", "object": "model",
                             "owned_by": "tpukit", "parent": n})
        self.write_json({"object": "list", "data": data})


def routes(server) -> list:
    kw = {"server": server}
    return [
        (r"/openai/v1/completions", CompletionsHandler, kw),
        (r"/openai/v1/chat/completions", ChatCompletionsHandler, kw),
        (r"/openai/v1/models", ModelsHandler, kw),
    ]
